"""Leader/worker rendezvous barrier over the KV store.

Ref: lib/runtime/src/utils/leader_worker_barrier.rs:1-616 — an etcd-based
barrier the reference uses for KVBM leader/worker startup and multi-node
engine coordination. Key layout (under ``barrier/{id}/``):

- ``data``                leader's payload (JSON), create-only
- ``worker/{worker_id}``  each worker's payload, create-only
- ``complete``            leader's completion signal
- ``abort``               leader's abort signal (timeout / failure)

Flow: the leader publishes ``data`` then waits until ``num_workers`` keys
exist under ``worker/``; it then signals ``complete`` and returns the worker
payloads. Each worker waits for ``data``, registers itself, then waits for
``complete`` (returning the leader payload) or ``abort`` (raising). All keys
bind to the caller's lease when given, so a dead participant's keys vanish
with its lease instead of wedging the next rendezvous.

TPU-build use: multi-host engine bring-up (mesh coordination over DCN),
KVBM leader/worker startup, planner fleet rollouts.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Dict, Optional

from dynamo_tpu.runtime.transports.kvstore import EventType, KeyExists, KvStore

BARRIER_ROOT = "barrier"


class BarrierAborted(Exception):
    """Leader signalled abort (or timed out waiting for workers)."""


class BarrierTimeout(Exception):
    pass


def _key(barrier_id: str, *suffix: str) -> str:
    return "/".join((BARRIER_ROOT, barrier_id) + suffix)


async def _wait_for_key(store: KvStore, key: str) -> bytes:
    """Return the key's value as soon as it exists (snapshot or watch)."""
    watch = await store.watch_prefix(key)
    try:
        async for ev in watch:
            if ev.type == EventType.PUT and ev.key == key and ev.value is not None:
                return ev.value
    finally:
        await watch.cancel()
    raise BarrierAborted(f"watch closed waiting for {key}")


class LeaderBarrier:
    """Leader side: publish data, wait for N workers, signal completion.

    Ref: leader_worker_barrier.rs:125 (``LeaderBarrier::sync``)."""

    def __init__(self, barrier_id: str, num_workers: int, timeout_s: Optional[float] = None):
        self.barrier_id = barrier_id
        self.num_workers = num_workers
        self.timeout_s = timeout_s

    async def sync(
        self, store: KvStore, data: Any, lease_id: Optional[int] = None
    ) -> Dict[str, Any]:
        """Returns {worker_id: worker_data} once all workers checked in."""
        payload = json.dumps(data).encode()
        await store.put(_key(self.barrier_id, "data"), payload, lease_id=lease_id, create_only=True)
        try:
            workers = await asyncio.wait_for(self._wait_for_workers(store), self.timeout_s)
        except asyncio.TimeoutError:
            await store.put(_key(self.barrier_id, "abort"), b"{}", lease_id=lease_id)
            raise BarrierTimeout(
                f"barrier {self.barrier_id}: timed out waiting for {self.num_workers} workers"
            )
        await store.put(_key(self.barrier_id, "complete"), b"{}", lease_id=lease_id)
        return workers

    async def _wait_for_workers(self, store: KvStore) -> Dict[str, Any]:
        prefix = _key(self.barrier_id, "worker") + "/"
        found: Dict[str, Any] = {}
        snapshot, watch = await store.get_and_watch_prefix(prefix)
        try:
            for e in snapshot:
                found[e.key[len(prefix):]] = json.loads(e.value)
            if len(found) >= self.num_workers:
                return found
            async for ev in watch:
                if ev.type == EventType.PUT and ev.value is not None:
                    found[ev.key[len(prefix):]] = json.loads(ev.value)
                    if len(found) >= self.num_workers:
                        return found
        finally:
            await watch.cancel()
        raise BarrierAborted(f"watch closed waiting for workers of {self.barrier_id}")


class WorkerBarrier:
    """Worker side: wait for leader data, register, wait for completion.

    Ref: leader_worker_barrier.rs:218 (``WorkerBarrier::sync``)."""

    def __init__(self, barrier_id: str, worker_id: str, timeout_s: Optional[float] = None):
        self.barrier_id = barrier_id
        self.worker_id = worker_id
        self.timeout_s = timeout_s

    async def sync(self, store: KvStore, data: Any, lease_id: Optional[int] = None) -> Any:
        """Returns the leader's data after the leader signals completion."""
        try:
            return await asyncio.wait_for(self._sync(store, data, lease_id), self.timeout_s)
        except asyncio.TimeoutError:
            raise BarrierTimeout(f"barrier {self.barrier_id}: worker {self.worker_id} timed out")

    async def _sync(self, store: KvStore, data: Any, lease_id: Optional[int]) -> Any:
        leader_raw = await _wait_for_key(store, _key(self.barrier_id, "data"))
        try:
            await store.put(
                _key(self.barrier_id, "worker", self.worker_id),
                json.dumps(data).encode(),
                lease_id=lease_id,
                create_only=True,
            )
        except KeyExists:
            raise KeyExists(
                f"barrier {self.barrier_id}: duplicate worker id {self.worker_id!r}"
            )
        # Wait for whichever signal lands first.
        complete = asyncio.create_task(_wait_for_key(store, _key(self.barrier_id, "complete")))
        abort = asyncio.create_task(_wait_for_key(store, _key(self.barrier_id, "abort")))
        done, pending = await asyncio.wait({complete, abort}, return_when=asyncio.FIRST_COMPLETED)
        for t in pending:
            t.cancel()
        await asyncio.gather(*pending, return_exceptions=True)
        if abort in done and not abort.cancelled() and abort.exception() is None:
            raise BarrierAborted(f"barrier {self.barrier_id}: leader aborted")
        return json.loads(leader_raw)
