"""TCP response plane: call-home response streaming.

Ref: lib/runtime/src/pipeline/network/tcp/{server.rs:1-613, client.rs:1-291},
codec/two_part.rs:1-764 (TwoPartCodec), network.rs:64 (ResponseStreamPrologue).

Flow (mirrors the reference's two-part wire, SURVEY.md §3A):
1. The caller (frontend/router) holds a lazily-started :class:`TcpStreamServer`
   and registers a pending stream id before pushing a request over pub/sub.
   The request carries ``ConnectionInfo{address, stream_id}``.
2. The worker handling the request connects back ("call home"), sends a
   prologue frame identifying the stream, then streams response frames, then a
   ``complete`` sentinel.
3. The caller's registered queue receives decoded frames as they arrive.

Wire format — TwoPartCodec: ``[u32 header_len][u32 body_len][header][body]``
(big-endian lengths). The header is a msgpack map (control metadata); the body
is the payload (msgpack-serialized response or raw bytes for KV blocks).
"""

from __future__ import annotations

import asyncio
import struct
import uuid
from dataclasses import dataclass, field
from typing import AsyncIterator, Dict, Optional, Tuple

import msgpack

_LEN = struct.Struct(">II")
MAX_FRAME = 256 * 1024 * 1024  # KV blocks can be large


class CodecError(Exception):
    pass


def encode_frame(header: dict, body: bytes = b"") -> bytes:
    h = msgpack.packb(header, use_bin_type=True)
    return _LEN.pack(len(h), len(body)) + h + body


async def read_frame(reader: asyncio.StreamReader) -> Tuple[dict, bytes]:
    raw = await reader.readexactly(_LEN.size)
    hlen, blen = _LEN.unpack(raw)
    if hlen > MAX_FRAME or blen > MAX_FRAME:
        raise CodecError(f"frame too large: header={hlen} body={blen}")
    h = await reader.readexactly(hlen) if hlen else b""
    b = await reader.readexactly(blen) if blen else b""
    header = msgpack.unpackb(h, raw=False) if h else {}
    return header, b


@dataclass
class ConnectionInfo:
    """Where the worker should call home (rides inside the pushed request)."""

    address: str  # "host:port"
    stream_id: str

    def to_dict(self) -> dict:
        return {"address": self.address, "stream_id": self.stream_id}

    @classmethod
    def from_dict(cls, d: dict) -> "ConnectionInfo":
        return cls(address=d["address"], stream_id=d["stream_id"])


@dataclass
class Frame:
    """A decoded response frame."""

    kind: str  # "prologue" | "data" | "complete" | "error"
    header: dict
    body: bytes = b""


class PendingStream:
    """Caller-side handle: an async iterator over incoming frames."""

    def __init__(self, stream_id: str):
        self.stream_id = stream_id
        self.queue: "asyncio.Queue[Optional[Frame]]" = asyncio.Queue()
        self.connected = asyncio.Event()

    async def frames(self) -> AsyncIterator[Frame]:
        while True:
            frame = await self.queue.get()
            if frame is None:
                return
            yield frame
            if frame.kind in ("complete", "error"):
                return


class TcpStreamServer:
    """Lazily-started response-plane listener (ref: tcp/server.rs).

    One per process; all in-flight requests multiplex onto it via stream ids.
    """

    def __init__(self, host: str = "0.0.0.0", port: int = 0, advertise_host: Optional[str] = None):
        self._host = host
        self._port = port
        self._advertise_host = advertise_host or "127.0.0.1"
        self._server: Optional[asyncio.AbstractServer] = None
        self._pending: Dict[str, PendingStream] = {}
        self._lock = asyncio.Lock()

    async def start(self) -> None:
        async with self._lock:
            if self._server is not None:
                return
            self._server = await asyncio.start_server(self._handle_conn, self._host, self._port)
            self._port = self._server.sockets[0].getsockname()[1]

    @property
    def address(self) -> str:
        assert self._server is not None, "server not started"
        return f"{self._advertise_host}:{self._port}"

    def register(self) -> Tuple[ConnectionInfo, PendingStream]:
        stream_id = uuid.uuid4().hex
        pending = PendingStream(stream_id)
        self._pending[stream_id] = pending
        return ConnectionInfo(address=self.address, stream_id=stream_id), pending

    def unregister(self, stream_id: str) -> None:
        pending = self._pending.pop(stream_id, None)
        if pending is not None:
            pending.queue.put_nowait(None)

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        pending: Optional[PendingStream] = None
        try:
            # First frame must be the prologue (ref: network.rs:64).
            header, body = await read_frame(reader)
            if header.get("kind") != "prologue":
                writer.close()
                return
            stream_id = header.get("stream_id", "")
            pending = self._pending.get(stream_id)
            if pending is None:
                # Stale stream (caller gone / timed out) — tell worker to stop.
                writer.write(encode_frame({"kind": "nack"}))
                await writer.drain()
                writer.close()
                return
            writer.write(encode_frame({"kind": "ack"}))
            await writer.drain()
            pending.connected.set()
            pending.queue.put_nowait(Frame(kind="prologue", header=header, body=body))
            while True:
                header, body = await read_frame(reader)
                kind = header.get("kind", "data")
                pending.queue.put_nowait(Frame(kind=kind, header=header, body=body))
                if kind in ("complete", "error"):
                    break
        except (asyncio.IncompleteReadError, ConnectionError):
            if pending is not None:
                # Abrupt disconnect mid-stream: surface as an error frame so the
                # Migration operator can react (ref: migration.rs stream drop).
                pending.queue.put_nowait(
                    Frame(kind="error", header={"kind": "error", "message": "connection reset", "disconnect": True})
                )
        finally:
            if pending is not None:
                self._pending.pop(pending.stream_id, None)
                pending.queue.put_nowait(None)
            try:
                writer.close()
            except Exception:
                pass

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for sid in list(self._pending):
            self.unregister(sid)


class TcpCallHome:
    """Worker-side client: connect to the caller and stream responses
    (ref: tcp/client.rs)."""

    def __init__(self, info: ConnectionInfo):
        self.info = info
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None

    async def connect(self, prologue_extra: Optional[dict] = None) -> bool:
        host, port = self.info.address.rsplit(":", 1)
        self._reader, self._writer = await asyncio.open_connection(host, int(port))
        header = {"kind": "prologue", "stream_id": self.info.stream_id}
        if prologue_extra:
            header.update(prologue_extra)
        self._writer.write(encode_frame(header))
        await self._writer.drain()
        ack, _ = await read_frame(self._reader)
        return ack.get("kind") == "ack"

    async def send(self, payload: dict, body: bytes = b"") -> None:
        assert self._writer is not None
        header = {"kind": "data", **payload}
        self._writer.write(encode_frame(header, body))
        await self._writer.drain()

    async def complete(self) -> None:
        assert self._writer is not None
        self._writer.write(encode_frame({"kind": "complete"}))
        await self._writer.drain()

    async def error(self, message: str, *, disconnect: bool = False) -> None:
        """``disconnect=True`` marks the error as a stream-level disconnect
        (worker draining, engine death): the caller raises StreamDisconnect
        and its Migration operator may replay, instead of a terminal
        RuntimeError."""
        assert self._writer is not None
        header = {"kind": "error", "message": message}
        if disconnect:
            header["disconnect"] = True
        self._writer.write(encode_frame(header))
        await self._writer.drain()

    async def close(self) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
                await self._writer.wait_closed()
            except Exception:
                pass
            self._writer = None
