"""Pub/sub messaging abstraction — the NATS role in the reference.

Ref: lib/runtime/src/transports/nats.rs:1-1299. The reference uses NATS for:
(a) request push to worker endpoints (core-NATS subjects, one consumer per
endpoint instance), (b) durable event streams (JetStream — KV events,
``kv_events``), (c) queue groups (prefill queue), (d) object store (router
radix snapshots).

This module maps those onto:
- :class:`PubSub.subscribe` — subject subscription (supports queue groups for
  load-balanced consumption).
- :class:`PubSub.request` — request/reply with inbox subjects.
- :class:`Stream` — a durable, replayable, sequence-numbered event log kept by
  the broker (the JetStream role) with consumer offsets.
- :class:`ObjectStore` — named blobs (the NATS object-store role).

Implementations: in-memory (this file) and the TCP control-plane client
(``dynamo_tpu.runtime.transports.tcp_control``).
"""

from __future__ import annotations

import asyncio
import time
import uuid
from dataclasses import dataclass, field
from typing import AsyncIterator, Callable, Dict, List, Optional, Tuple

from dynamo_tpu.runtime import faults


@dataclass
class Message:
    subject: str
    data: bytes
    headers: Dict[str, str] = field(default_factory=dict)
    reply_to: Optional[str] = None
    seq: int = 0


class Subscription:
    def __init__(self, queue: "asyncio.Queue[Optional[Message]]", cancel_cb):
        self._queue = queue
        self._cancel_cb = cancel_cb
        self._done = False

    def __aiter__(self) -> AsyncIterator[Message]:
        return self._gen()

    async def _gen(self) -> AsyncIterator[Message]:
        while True:
            msg = await self._queue.get()
            if msg is None:
                return
            yield msg

    async def next(self, timeout: Optional[float] = None) -> Optional[Message]:
        try:
            if timeout is None:
                msg = await self._queue.get()
            else:
                msg = await asyncio.wait_for(self._queue.get(), timeout)
        except asyncio.TimeoutError:
            return None
        return msg

    async def unsubscribe(self) -> None:
        if not self._done:
            self._done = True
            await self._cancel_cb(self)
            self._queue.put_nowait(None)


def subject_matches(pattern: str, subject: str) -> bool:
    """NATS-style matching: tokens split on '.', '*' matches one token,
    '>' matches one or more trailing tokens (as in real NATS: 'a.>' does
    not match 'a')."""
    pt, st = pattern.split("."), subject.split(".")
    for i, tok in enumerate(pt):
        if tok == ">":
            return len(st) > i
        if i >= len(st):
            return False
        if tok != "*" and tok != st[i]:
            return False
    return len(pt) == len(st)


class PubSub:
    """Abstract pub/sub interface."""

    async def publish(
        self,
        subject: str,
        data: bytes,
        headers: Optional[Dict[str, str]] = None,
        reply_to: Optional[str] = None,
    ) -> None:
        raise NotImplementedError

    async def subscribe(self, subject: str, queue_group: Optional[str] = None) -> Subscription:
        raise NotImplementedError

    async def request(
        self,
        subject: str,
        data: bytes,
        headers: Optional[Dict[str, str]] = None,
        timeout: float = 30.0,
    ) -> Message:
        """Request/reply over an ephemeral inbox subject."""
        inbox = f"_inbox.{uuid.uuid4().hex}"
        sub = await self.subscribe(inbox)
        try:
            await self.publish(subject, data, headers, reply_to=inbox)
            msg = await sub.next(timeout=timeout)
            if msg is None:
                raise asyncio.TimeoutError(f"request to {subject} timed out")
            return msg
        finally:
            await sub.unsubscribe()

    # --- durable streams (JetStream role) ---
    async def stream(self, name: str) -> "Stream":
        raise NotImplementedError

    # --- object store ---
    async def object_store(self, bucket: str) -> "ObjectStore":
        raise NotImplementedError

    async def close(self) -> None:
        pass


class Stream:
    """Durable sequence-numbered event log with replay (JetStream role).

    Ref: nats.rs JetStream usage — the KV-event stream the router consumes
    (kv_router/subscriber.rs:71) with snapshot+purge compaction.
    """

    def __init__(self, name: str):
        self.name = name
        self._events: List[Message] = []
        self._first_seq = 1  # seq of _events[0]
        self._next_seq = 1
        self._waiters: List[asyncio.Event] = []
        self._lock = asyncio.Lock()

    async def publish(self, subject: str, data: bytes, headers: Optional[Dict[str, str]] = None) -> int:
        async with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            self._events.append(Message(subject=subject, data=data, headers=headers or {}, seq=seq))
            for w in self._waiters:
                w.set()
            self._waiters.clear()
            return seq

    @property
    def last_seq(self) -> int:
        return self._next_seq - 1

    @property
    def first_seq(self) -> int:
        return self._first_seq

    async def purge(self, up_to_seq: Optional[int] = None) -> None:
        """Drop events with seq <= up_to_seq (all if None) — used after the
        router uploads a radix snapshot (ref: subscriber.rs purge-on-snapshot)."""
        async with self._lock:
            if up_to_seq is None:
                up_to_seq = self._next_seq - 1
            up_to_seq = min(up_to_seq, self._next_seq - 1)
            drop = up_to_seq - self._first_seq + 1
            if drop > 0:
                del self._events[:drop]
                self._first_seq = up_to_seq + 1

    async def fetch(self, from_seq: int, max_events: int = 1024) -> List[Message]:
        async with self._lock:
            if from_seq < self._first_seq:
                from_seq = self._first_seq
            idx = from_seq - self._first_seq
            return list(self._events[idx : idx + max_events])

    async def consume(self, from_seq: int = 1) -> AsyncIterator[Message]:
        """Yield events from ``from_seq`` onward, then follow the tail."""
        seq = max(from_seq, self._first_seq)
        while True:
            batch = await self.fetch(seq)
            if batch:
                for msg in batch:
                    yield msg
                seq = batch[-1].seq + 1
                continue
            ev = asyncio.Event()
            async with self._lock:
                if self._next_seq - 1 >= seq:
                    continue
                self._waiters.append(ev)
            await ev.wait()


class ObjectStore:
    """Named blob store (NATS object store role; router snapshots live here —
    ref: kv_router.rs RADIX_STATE_BUCKET :69)."""

    def __init__(self, bucket: str):
        self.bucket = bucket
        self._objects: Dict[str, bytes] = {}

    async def put(self, name: str, data: bytes) -> None:
        self._objects[name] = data

    async def get(self, name: str) -> Optional[bytes]:
        return self._objects.get(name)

    async def delete(self, name: str) -> bool:
        return self._objects.pop(name, None) is not None

    async def list(self) -> List[str]:
        return sorted(self._objects)


class MemPubSub(PubSub):
    """In-process broker. Queue groups pick one subscriber round-robin per
    group, mirroring NATS queue semantics (used by the prefill queue)."""

    def __init__(self):
        # (pattern, queue_group, queue)
        self._subs: List[Tuple[str, Optional[str], asyncio.Queue]] = []
        self._rr: Dict[Tuple[str, str], int] = {}
        self._streams: Dict[str, Stream] = {}
        self._buckets: Dict[str, ObjectStore] = {}
        self._lock = asyncio.Lock()

    async def publish(self, subject, data, headers=None, reply_to=None) -> None:
        if faults.armed():
            # Chaos plane: the control-plane hop. ``partition`` drops the
            # message on the floor (the subscriber simply never hears it);
            # ``delay`` holds delivery for delay_s. Scenario ``match``
            # supports subject_prefix so e.g. only the request-push plane
            # ("rq.") partitions while stats/control stay alive.
            try:
                await faults.afire("bus.publish", subject=subject)
            except faults.InjectedFault as f:
                if f.kind == "partition":
                    return
                raise
        msg = Message(subject=subject, data=data, headers=headers or {}, reply_to=reply_to)
        async with self._lock:
            # Group queue-group subscribers; deliver to every plain subscriber.
            groups: Dict[str, List[asyncio.Queue]] = {}
            for pattern, qg, queue in self._subs:
                if not subject_matches(pattern, subject):
                    continue
                if qg is None:
                    queue.put_nowait(msg)
                else:
                    groups.setdefault(f"{pattern}|{qg}", []).append(queue)
            for key, queues in groups.items():
                idx = self._rr.get((key, subject), 0) % len(queues)
                self._rr[(key, subject)] = idx + 1
                queues[idx].put_nowait(msg)

    async def subscribe(self, subject, queue_group=None) -> Subscription:
        queue: asyncio.Queue = asyncio.Queue()
        entry = (subject, queue_group, queue)
        async with self._lock:
            self._subs.append(entry)

        async def cancel(_sub, entry=entry):
            async with self._lock:
                if entry in self._subs:
                    self._subs.remove(entry)

        return Subscription(queue, cancel)

    async def stream(self, name) -> Stream:
        async with self._lock:
            if name not in self._streams:
                self._streams[name] = Stream(name)
            return self._streams[name]

    async def object_store(self, bucket) -> ObjectStore:
        async with self._lock:
            if bucket not in self._buckets:
                self._buckets[bucket] = ObjectStore(bucket)
            return self._buckets[bucket]

    async def close(self) -> None:
        async with self._lock:
            for _, _, q in self._subs:
                q.put_nowait(None)
            self._subs.clear()
