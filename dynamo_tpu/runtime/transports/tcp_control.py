"""Built-in TCP control plane: KV store + pub/sub served by one broker.

The reference externalizes its control plane to etcd (discovery/leases) and
NATS (messaging/streams/object store) — SURVEY.md §1 L1. dynamo-tpu ships a
built-in broker instead (``python -m dynamo_tpu.control_plane``) so a TPU pod
deployment has no external infra dependency; the abstract interfaces
(:class:`KvStore` / :class:`PubSub`) keep it swappable.

Protocol: length-prefixed msgpack frames over one TCP connection per client.
Client→server requests carry ``id`` for reply correlation; server→client
pushes (watch events, subscription messages) carry the watch/sub id they
belong to. Leases are server-side with TTL reaping, so client death (socket
close) revokes its leases — the same failure semantics as etcd lease expiry.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Dict, List, Optional, Tuple

import msgpack

from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.transports.kvstore import (
    EventType,
    KeyExists,
    KvEntry,
    KvStore,
    Lease,
    LeaseExpired,
    MemKvStore,
    Watch,
    WatchEvent,
)
from dynamo_tpu.runtime.transports.pubsub import (
    MemPubSub,
    Message,
    PubSub,
    Subscription,
)

logger = get_logger(__name__)

_LEN = struct.Struct(">I")
MAX_MSG = 512 * 1024 * 1024


def _pack(obj: dict) -> bytes:
    data = msgpack.packb(obj, use_bin_type=True)
    return _LEN.pack(len(data)) + data


async def _read_msg(reader: asyncio.StreamReader) -> dict:
    raw = await reader.readexactly(_LEN.size)
    (n,) = _LEN.unpack(raw)
    if n > MAX_MSG:
        raise ValueError(f"message too large: {n}")
    return msgpack.unpackb(await reader.readexactly(n), raw=False)


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class ControlPlaneServer:
    """The broker: wraps MemKvStore + MemPubSub behind the TCP protocol."""

    def __init__(self, host: str = "0.0.0.0", port: int = 6650):
        self.host = host
        self.port = port
        self.store = MemKvStore()
        self.bus = MemPubSub()
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle_client, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("control plane listening on %s:%d", self.host, self.port)

    async def serve_forever(self) -> None:
        assert self._server is not None
        await self._server.serve_forever()

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        await self.bus.close()
        await self.store.close()

    async def _handle_client(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        session = _ClientSession(self, reader, writer)
        await session.run()


class _ClientSession:
    def __init__(self, server: ControlPlaneServer, reader, writer):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.watches: Dict[int, Watch] = {}
        self.subs: Dict[int, Subscription] = {}
        self.leases: Dict[int, Lease] = {}
        self.tasks: List[asyncio.Task] = []
        self._wlock = asyncio.Lock()

    async def send(self, obj: dict) -> None:
        async with self._wlock:
            self.writer.write(_pack(obj))
            await self.writer.drain()

    async def run(self) -> None:
        try:
            while True:
                msg = await _read_msg(self.reader)
                try:
                    await self._dispatch(msg)
                except (KeyExists, LeaseExpired) as e:
                    await self.send({"id": msg.get("id"), "error": type(e).__name__, "message": str(e)})
                except Exception as e:
                    logger.exception("control plane op failed: %s", msg.get("op"))
                    await self.send({"id": msg.get("id"), "error": "Internal", "message": str(e)})
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            await self._cleanup()

    async def _cleanup(self) -> None:
        for t in self.tasks:
            t.cancel()
        for w in self.watches.values():
            await w.cancel()
        for s in self.subs.values():
            await s.unsubscribe()
        # Client gone ⇒ its leases die (same as etcd session loss).
        for lease in self.leases.values():
            await lease.revoke()
        try:
            self.writer.close()
        except Exception:
            pass

    async def _dispatch(self, msg: dict) -> None:
        op = msg["op"]
        mid = msg.get("id")
        store, bus = self.server.store, self.server.bus

        if op == "put":
            rev = await store.put(
                msg["key"], msg["value"], lease_id=msg.get("lease_id"), create_only=msg.get("create_only", False)
            )
            await self.send({"id": mid, "revision": rev})
        elif op == "get":
            e = await store.get(msg["key"])
            await self.send({"id": mid, "entry": _entry_wire(e)})
        elif op == "get_prefix":
            es = await store.get_prefix(msg["prefix"])
            await self.send({"id": mid, "entries": [_entry_wire(e) for e in es]})
        elif op == "delete":
            ok = await store.delete(msg["key"])
            await self.send({"id": mid, "deleted": ok})
        elif op == "delete_prefix":
            n = await store.delete_prefix(msg["prefix"])
            await self.send({"id": mid, "count": n})
        elif op == "watch":
            snapshot, watch = await store.get_and_watch_prefix(msg["prefix"])
            wid = msg["watch_id"]
            self.watches[wid] = watch
            await self.send({"id": mid, "entries": [_entry_wire(e) for e in snapshot]})
            self.tasks.append(asyncio.get_running_loop().create_task(self._pump_watch(wid, watch)))
        elif op == "watch_cancel":
            watch = self.watches.pop(msg["watch_id"], None)
            if watch:
                await watch.cancel()
            await self.send({"id": mid, "ok": True})
        elif op == "lease_grant":
            lease = await store.grant_lease(msg["ttl_s"])
            self.leases[lease.id] = lease
            await self.send({"id": mid, "lease_id": lease.id, "ttl_s": lease.ttl_s})
        elif op == "keep_alive":
            await store.keep_alive(msg["lease_id"])
            await self.send({"id": mid, "ok": True})
        elif op == "lease_revoke":
            lease = self.leases.pop(msg["lease_id"], None)
            if lease is not None:
                await lease.revoke()
            else:
                await store.revoke_lease(msg["lease_id"])
            await self.send({"id": mid, "ok": True})
        elif op == "publish":
            await bus.publish(msg["subject"], msg["data"], msg.get("headers") or {}, msg.get("reply_to"))
            if mid is not None:
                await self.send({"id": mid, "ok": True})
        elif op == "subscribe":
            sub = await bus.subscribe(msg["subject"], msg.get("queue_group"))
            sid = msg["sub_id"]
            self.subs[sid] = sub
            await self.send({"id": mid, "ok": True})
            self.tasks.append(asyncio.get_running_loop().create_task(self._pump_sub(sid, sub)))
        elif op == "unsubscribe":
            sub = self.subs.pop(msg["sub_id"], None)
            if sub:
                await sub.unsubscribe()
            await self.send({"id": mid, "ok": True})
        elif op == "s_publish":
            stream = await bus.stream(msg["stream"])
            seq = await stream.publish(msg["subject"], msg["data"], msg.get("headers") or {})
            await self.send({"id": mid, "seq": seq})
        elif op == "s_fetch":
            stream = await bus.stream(msg["stream"])
            batch = await stream.fetch(msg["from_seq"], msg.get("max_events", 1024))
            if not batch and msg.get("wait"):
                # Long-poll: wait for one event or timeout, then refetch.
                try:
                    await asyncio.wait_for(self._wait_stream(stream, msg["from_seq"]), msg.get("timeout", 5.0))
                except asyncio.TimeoutError:
                    pass
                batch = await stream.fetch(msg["from_seq"], msg.get("max_events", 1024))
            await self.send(
                {
                    "id": mid,
                    "events": [
                        {"subject": m.subject, "data": m.data, "headers": m.headers, "seq": m.seq} for m in batch
                    ],
                    "first_seq": stream.first_seq,
                    "last_seq": stream.last_seq,
                }
            )
        elif op == "s_purge":
            stream = await bus.stream(msg["stream"])
            await stream.purge(msg.get("up_to_seq"))
            await self.send({"id": mid, "ok": True})
        elif op == "o_put":
            obj = await bus.object_store(msg["bucket"])
            await obj.put(msg["name"], msg["data"])
            await self.send({"id": mid, "ok": True})
        elif op == "o_get":
            obj = await bus.object_store(msg["bucket"])
            await self.send({"id": mid, "data": await obj.get(msg["name"])})
        elif op == "o_delete":
            obj = await bus.object_store(msg["bucket"])
            await self.send({"id": mid, "deleted": await obj.delete(msg["name"])})
        elif op == "o_list":
            obj = await bus.object_store(msg["bucket"])
            await self.send({"id": mid, "names": await obj.list()})
        elif op == "ping":
            await self.send({"id": mid, "ok": True})
        else:
            await self.send({"id": mid, "error": "UnknownOp", "message": op})

    async def _wait_stream(self, stream, from_seq: int) -> None:
        while stream.last_seq < from_seq:
            ev = asyncio.Event()
            async with stream._lock:
                if stream.last_seq >= from_seq:
                    return
                stream._waiters.append(ev)
            await ev.wait()

    async def _pump_watch(self, wid: int, watch: Watch) -> None:
        try:
            async for ev in watch:
                await self.send(
                    {
                        "push": "watch_event",
                        "watch_id": wid,
                        "type": ev.type.value,
                        "key": ev.key,
                        "value": ev.value,
                        "revision": ev.revision,
                    }
                )
        except (ConnectionError, asyncio.CancelledError):
            pass

    async def _pump_sub(self, sid: int, sub: Subscription) -> None:
        try:
            async for m in sub:
                await self.send(
                    {
                        "push": "msg",
                        "sub_id": sid,
                        "subject": m.subject,
                        "data": m.data,
                        "headers": m.headers,
                        "reply_to": m.reply_to,
                    }
                )
        except (ConnectionError, asyncio.CancelledError):
            pass


def _entry_wire(e: Optional[KvEntry]) -> Optional[dict]:
    if e is None:
        return None
    return {"key": e.key, "value": e.value, "lease_id": e.lease_id, "revision": e.revision}


def _entry_from_wire(d: Optional[dict]) -> Optional[KvEntry]:
    if d is None:
        return None
    return KvEntry(key=d["key"], value=d["value"], lease_id=d.get("lease_id"), revision=d.get("revision", 0))


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class ControlPlaneConnection:
    """One multiplexed connection shared by TcpKvStore + TcpPubSub."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self._next_id = 1
        self._pending: Dict[int, asyncio.Future] = {}
        self._watch_queues: Dict[int, asyncio.Queue] = {}
        self._sub_queues: Dict[int, asyncio.Queue] = {}
        self._wlock = asyncio.Lock()
        self._reader_task = asyncio.get_running_loop().create_task(self._read_loop())
        self._closed = False

    async def _read_loop(self) -> None:
        try:
            while True:
                msg = await _read_msg(self.reader)
                push = msg.get("push")
                if push == "watch_event":
                    q = self._watch_queues.get(msg["watch_id"])
                    if q is not None:
                        q.put_nowait(
                            WatchEvent(
                                EventType(msg["type"]), msg["key"], msg.get("value"), msg.get("revision", 0)
                            )
                        )
                elif push == "msg":
                    q = self._sub_queues.get(msg["sub_id"])
                    if q is not None:
                        q.put_nowait(
                            Message(
                                subject=msg["subject"],
                                data=msg["data"],
                                headers=msg.get("headers") or {},
                                reply_to=msg.get("reply_to"),
                            )
                        )
                else:
                    fut = self._pending.pop(msg.get("id"), None)
                    if fut is not None and not fut.done():
                        if "error" in msg:
                            err = msg["error"]
                            exc = {"KeyExists": KeyExists, "LeaseExpired": LeaseExpired}.get(err, RuntimeError)
                            fut.set_exception(exc(msg.get("message", err)))
                        else:
                            fut.set_result(msg)
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            self._closed = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("control plane connection lost"))
            self._pending.clear()
            for q in self._watch_queues.values():
                q.put_nowait(None)
            for q in self._sub_queues.values():
                q.put_nowait(None)

    async def call(self, op: str, **kwargs) -> dict:
        if self._closed:
            raise ConnectionError("control plane connection lost")
        mid = self._next_id
        self._next_id += 1
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[mid] = fut
        async with self._wlock:
            self.writer.write(_pack({"op": op, "id": mid, **kwargs}))
            await self.writer.drain()
        return await fut

    async def send_nowait(self, op: str, **kwargs) -> None:
        async with self._wlock:
            self.writer.write(_pack({"op": op, **kwargs}))
            await self.writer.drain()

    def new_watch_queue(self) -> Tuple[int, asyncio.Queue]:
        wid = self._next_id
        self._next_id += 1
        q: asyncio.Queue = asyncio.Queue()
        self._watch_queues[wid] = q
        return wid, q

    def new_sub_queue(self) -> Tuple[int, asyncio.Queue]:
        sid = self._next_id
        self._next_id += 1
        q: asyncio.Queue = asyncio.Queue()
        self._sub_queues[sid] = q
        return sid, q

    async def close(self) -> None:
        self._reader_task.cancel()
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass


async def connect_control_plane(address: str, timeout: float = 10.0) -> ControlPlaneConnection:
    host, port = address.rsplit(":", 1)
    reader, writer = await asyncio.wait_for(asyncio.open_connection(host, int(port)), timeout)
    conn = ControlPlaneConnection(reader, writer)
    await conn.call("ping")
    return conn


class TcpKvStore(KvStore):
    def __init__(self, conn: ControlPlaneConnection):
        self.conn = conn

    async def put(self, key, value, lease_id=None, create_only=False) -> int:
        r = await self.conn.call("put", key=key, value=value, lease_id=lease_id, create_only=create_only)
        return r["revision"]

    async def get(self, key):
        r = await self.conn.call("get", key=key)
        return _entry_from_wire(r.get("entry"))

    async def get_prefix(self, prefix):
        r = await self.conn.call("get_prefix", prefix=prefix)
        return [_entry_from_wire(e) for e in r["entries"]]

    async def delete(self, key) -> bool:
        return (await self.conn.call("delete", key=key))["deleted"]

    async def delete_prefix(self, prefix) -> int:
        return (await self.conn.call("delete_prefix", prefix=prefix))["count"]

    async def watch_prefix(self, prefix) -> Watch:
        snapshot, watch = await self.get_and_watch_prefix(prefix)
        # Re-inject the snapshot as PUT events to preserve watch_prefix semantics.
        for e in snapshot:
            watch._queue.put_nowait(WatchEvent(EventType.PUT, e.key, e.value, e.revision))
        return watch

    async def get_and_watch_prefix(self, prefix):
        wid, queue = self.conn.new_watch_queue()
        r = await self.conn.call("watch", prefix=prefix, watch_id=wid)
        snapshot = [_entry_from_wire(e) for e in r["entries"]]

        async def cancel(_watch):
            self.conn._watch_queues.pop(wid, None)
            try:
                await self.conn.call("watch_cancel", watch_id=wid)
            except ConnectionError:
                pass

        # Queue was created before the watch call; snapshot events from
        # watch_prefix are injected by the caller above.
        live_watch = Watch(queue, cancel)
        return snapshot, live_watch

    async def grant_lease(self, ttl_s) -> Lease:
        r = await self.conn.call("lease_grant", ttl_s=ttl_s)
        return Lease(self, r["lease_id"], r["ttl_s"])

    async def keep_alive(self, lease_id) -> None:
        await self.conn.call("keep_alive", lease_id=lease_id)

    async def revoke_lease(self, lease_id) -> None:
        await self.conn.call("lease_revoke", lease_id=lease_id)

    async def close(self) -> None:
        pass  # connection shared with pubsub; closed by the runtime


class _TcpStream:
    """Client-side durable stream view (server holds the log)."""

    def __init__(self, conn: ControlPlaneConnection, name: str):
        self.conn = conn
        self.name = name

    async def publish(self, subject, data, headers=None) -> int:
        r = await self.conn.call("s_publish", stream=self.name, subject=subject, data=data, headers=headers or {})
        return r["seq"]

    async def fetch(self, from_seq, max_events=1024) -> List[Message]:
        r = await self.conn.call("s_fetch", stream=self.name, from_seq=from_seq, max_events=max_events)
        return [Message(subject=e["subject"], data=e["data"], headers=e["headers"], seq=e["seq"]) for e in r["events"]]

    async def purge(self, up_to_seq=None) -> None:
        await self.conn.call("s_purge", stream=self.name, up_to_seq=up_to_seq)

    async def consume(self, from_seq: int = 1):
        seq = from_seq
        while True:
            r = await self.conn.call("s_fetch", stream=self.name, from_seq=seq, wait=True, timeout=5.0)
            for e in r["events"]:
                yield Message(subject=e["subject"], data=e["data"], headers=e["headers"], seq=e["seq"])
                seq = e["seq"] + 1
            seq = max(seq, r.get("first_seq", seq))


class _TcpObjectStore:
    def __init__(self, conn: ControlPlaneConnection, bucket: str):
        self.conn = conn
        self.bucket = bucket

    async def put(self, name, data) -> None:
        await self.conn.call("o_put", bucket=self.bucket, name=name, data=data)

    async def get(self, name):
        return (await self.conn.call("o_get", bucket=self.bucket, name=name)).get("data")

    async def delete(self, name) -> bool:
        return (await self.conn.call("o_delete", bucket=self.bucket, name=name))["deleted"]

    async def list(self):
        return (await self.conn.call("o_list", bucket=self.bucket))["names"]


class TcpPubSub(PubSub):
    def __init__(self, conn: ControlPlaneConnection):
        self.conn = conn

    async def publish(self, subject, data, headers=None, reply_to=None) -> None:
        await self.conn.send_nowait("publish", subject=subject, data=data, headers=headers or {}, reply_to=reply_to)

    async def subscribe(self, subject, queue_group=None) -> Subscription:
        sid, queue = self.conn.new_sub_queue()
        await self.conn.call("subscribe", subject=subject, sub_id=sid, queue_group=queue_group)

        async def cancel(_sub):
            self.conn._sub_queues.pop(sid, None)
            try:
                await self.conn.call("unsubscribe", sub_id=sid)
            except ConnectionError:
                pass

        return Subscription(queue, cancel)

    async def stream(self, name) -> _TcpStream:
        return _TcpStream(self.conn, name)

    async def object_store(self, bucket) -> _TcpObjectStore:
        return _TcpObjectStore(self.conn, bucket)

    async def close(self) -> None:
        await self.conn.close()
