"""Key-value store abstraction with leases and prefix watches — the control
plane's discovery/registration substrate (the etcd role).

Ref: lib/runtime/src/transports/etcd.rs:1-770 (Client, kv_get_prefix,
kv_get_and_watch_prefix), etcd/lease.rs:1-116 (primary lease keepalive),
storage/key_value_store/{etcd,nats,mem}.rs (pluggable backends — mem.rs is the
test backend this module's MemKvStore mirrors).

Semantics preserved from the reference:
- Keys may be attached to a *lease*; when the lease expires or is revoked all
  its keys are deleted and watchers observe DELETE events. Instance discovery
  (``instances/{ns}/{comp}/{ep}:{lease_id}``) rides on this: a dead worker's
  lease lapses and every router's watch prunes it (SURVEY.md §3B).
- ``watch_prefix`` yields the current snapshot (PUT events) then live deltas.
- ``put`` supports create-only mode for barriers/locks.
"""

from __future__ import annotations

import asyncio
import enum
import fnmatch
import time
import uuid
from dataclasses import dataclass, field
from typing import AsyncIterator, Dict, List, Optional, Tuple


class EventType(enum.Enum):
    PUT = "put"
    DELETE = "delete"


@dataclass
class WatchEvent:
    type: EventType
    key: str
    value: Optional[bytes]
    revision: int = 0


@dataclass
class KvEntry:
    key: str
    value: bytes
    lease_id: Optional[int] = None
    revision: int = 0


class LeaseExpired(Exception):
    pass


class KeyExists(Exception):
    """Raised by create-only put when the key is already present."""


class Lease:
    """A client-held lease. ``keep_alive`` is managed by the store; callers
    use the lease id to bind keys and ``revoke()`` on shutdown.

    Ref: lib/runtime/src/transports/etcd/lease.rs.
    """

    def __init__(self, store: "KvStore", lease_id: int, ttl_s: float):
        self.store = store
        self.id = lease_id
        self.ttl_s = ttl_s
        self._revoked = asyncio.Event()

    @property
    def revoked(self) -> bool:
        return self._revoked.is_set()

    async def revoke(self) -> None:
        if not self._revoked.is_set():
            self._revoked.set()
            await self.store.revoke_lease(self.id)

    async def wait_revoked(self) -> None:
        await self._revoked.wait()


class Watch:
    """Handle returned by ``watch_prefix``: an async iterator of WatchEvents
    plus a cancel method."""

    def __init__(self, queue: "asyncio.Queue[Optional[WatchEvent]]", cancel_cb) -> None:
        self._queue = queue
        self._cancel_cb = cancel_cb
        self._cancelled = False

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self._gen()

    async def _gen(self) -> AsyncIterator[WatchEvent]:
        while True:
            ev = await self._queue.get()
            if ev is None:
                return
            yield ev

    async def cancel(self) -> None:
        if not self._cancelled:
            self._cancelled = True
            await self._cancel_cb(self)
            await self._queue.put(None)


class KvStore:
    """Abstract KV store interface. Async, linearizable per key."""

    async def put(
        self,
        key: str,
        value: bytes,
        lease_id: Optional[int] = None,
        create_only: bool = False,
    ) -> int:
        raise NotImplementedError

    async def get(self, key: str) -> Optional[KvEntry]:
        raise NotImplementedError

    async def get_prefix(self, prefix: str) -> List[KvEntry]:
        raise NotImplementedError

    async def delete(self, key: str) -> bool:
        raise NotImplementedError

    async def delete_prefix(self, prefix: str) -> int:
        raise NotImplementedError

    async def watch_prefix(self, prefix: str) -> Watch:
        """Snapshot (as PUT events) + live updates."""
        raise NotImplementedError

    async def get_and_watch_prefix(self, prefix: str) -> Tuple[List[KvEntry], Watch]:
        """Atomic snapshot + deltas-only watch (ref: etcd.rs
        kv_get_and_watch_prefix) — no gap, no duplicates."""
        raise NotImplementedError

    async def grant_lease(self, ttl_s: float) -> Lease:
        raise NotImplementedError

    async def keep_alive(self, lease_id: int) -> None:
        raise NotImplementedError

    async def revoke_lease(self, lease_id: int) -> None:
        raise NotImplementedError

    async def close(self) -> None:
        pass


@dataclass
class _MemLease:
    id: int
    ttl_s: float
    deadline: float
    keys: set = field(default_factory=set)


class MemKvStore(KvStore):
    """In-process store (ref: storage/key_value_store/mem.rs:1-201).

    Leases expire via a reaper task; `keep_alive` pushes the deadline out.
    Suitable for single-process deployments and unit tests; the TCP
    control-plane server wraps one of these.
    """

    def __init__(self, *, reaper_interval_s: float = 0.5):
        self._data: Dict[str, KvEntry] = {}
        self._leases: Dict[int, _MemLease] = {}
        self._watches: List[Tuple[str, asyncio.Queue]] = []
        self._revision = 0
        self._lock = asyncio.Lock()
        self._reaper_interval_s = reaper_interval_s
        self._reaper_task: Optional[asyncio.Task] = None
        self._closed = False

    def _ensure_reaper(self) -> None:
        if self._reaper_task is None or self._reaper_task.done():
            self._reaper_task = asyncio.get_running_loop().create_task(self._reaper())

    async def _reaper(self) -> None:
        try:
            while not self._closed:
                await asyncio.sleep(self._reaper_interval_s)
                now = time.monotonic()
                expired = [l.id for l in self._leases.values() if l.deadline < now]
                for lid in expired:
                    await self.revoke_lease(lid)
        except asyncio.CancelledError:
            pass

    def _notify(self, ev: WatchEvent) -> None:
        for prefix, queue in self._watches:
            if ev.key.startswith(prefix):
                queue.put_nowait(ev)

    async def put(self, key, value, lease_id=None, create_only=False) -> int:
        async with self._lock:
            if create_only and key in self._data:
                raise KeyExists(key)
            prev = self._data.get(key)
            if prev is not None and prev.lease_id is not None and prev.lease_id != lease_id:
                # Re-binding a key to a different lease (e.g. a second worker
                # re-registering the shared model entry): the OLD lease must
                # stop owning it, or that worker's drain/crash would delete a
                # key the survivor still backs.
                old = self._leases.get(prev.lease_id)
                if old is not None:
                    old.keys.discard(key)
            if lease_id is not None:
                lease = self._leases.get(lease_id)
                if lease is None:
                    raise LeaseExpired(f"lease {lease_id:x} not found")
                lease.keys.add(key)
            self._revision += 1
            entry = KvEntry(key=key, value=value, lease_id=lease_id, revision=self._revision)
            self._data[key] = entry
            self._notify(WatchEvent(EventType.PUT, key, value, self._revision))
            return self._revision

    async def get(self, key) -> Optional[KvEntry]:
        return self._data.get(key)

    async def get_prefix(self, prefix) -> List[KvEntry]:
        return [e for k, e in sorted(self._data.items()) if k.startswith(prefix)]

    async def delete(self, key) -> bool:
        async with self._lock:
            entry = self._data.pop(key, None)
            if entry is None:
                return False
            if entry.lease_id is not None:
                lease = self._leases.get(entry.lease_id)
                if lease:
                    lease.keys.discard(key)
            self._revision += 1
            self._notify(WatchEvent(EventType.DELETE, key, None, self._revision))
            return True

    async def delete_prefix(self, prefix) -> int:
        keys = [k for k in list(self._data) if k.startswith(prefix)]
        n = 0
        for k in keys:
            n += bool(await self.delete(k))
        return n

    async def watch_prefix(self, prefix) -> Watch:
        queue: asyncio.Queue = asyncio.Queue()
        async with self._lock:
            # Snapshot first, then register for deltas: no gap, no duplicates.
            for e in sorted(self._data.items()):
                if e[0].startswith(prefix):
                    queue.put_nowait(WatchEvent(EventType.PUT, e[1].key, e[1].value, e[1].revision))
            return self._register_watch(prefix, queue)

    async def get_and_watch_prefix(self, prefix) -> Tuple[List[KvEntry], Watch]:
        queue: asyncio.Queue = asyncio.Queue()
        async with self._lock:
            snapshot = [e for k, e in sorted(self._data.items()) if k.startswith(prefix)]
            return snapshot, self._register_watch(prefix, queue)

    def _register_watch(self, prefix: str, queue: "asyncio.Queue") -> Watch:
        pair = (prefix, queue)
        self._watches.append(pair)

        async def cancel(_watch, pair=pair):
            async with self._lock:
                if pair in self._watches:
                    self._watches.remove(pair)

        return Watch(queue, cancel)

    async def grant_lease(self, ttl_s) -> Lease:
        self._ensure_reaper()
        lease_id = uuid.uuid4().int & 0x7FFF_FFFF_FFFF_FFFF
        self._leases[lease_id] = _MemLease(id=lease_id, ttl_s=ttl_s, deadline=time.monotonic() + ttl_s)
        return Lease(self, lease_id, ttl_s)

    async def keep_alive(self, lease_id) -> None:
        lease = self._leases.get(lease_id)
        if lease is None:
            raise LeaseExpired(f"lease {lease_id:x} not found")
        lease.deadline = time.monotonic() + lease.ttl_s

    async def revoke_lease(self, lease_id) -> None:
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        for key in list(lease.keys):
            await self.delete(key)

    async def close(self) -> None:
        self._closed = True
        if self._reaper_task is not None:
            self._reaper_task.cancel()
            self._reaper_task = None
        for _, q in self._watches:
            q.put_nowait(None)
        self._watches.clear()


def match_glob(key: str, pattern: str) -> bool:
    """Subject glob matching helper (``*`` within a token, ``>``-style tails
    are expressed as prefix watches instead)."""
    return fnmatch.fnmatchcase(key, pattern)
