"""Transports: the control plane (key-value store with leases/watches — the
etcd role; pub/sub messaging — the NATS role) and the data plane (TCP response
streaming). Ref: lib/runtime/src/transports/{etcd,nats,zmq,tcp}.rs.

All control-plane users program against the abstract :class:`KvStore` /
:class:`PubSub` interfaces; deployments choose:

- in-memory (single process, unit tests — ref: storage/key_value_store/mem.rs)
- the built-in TCP control-plane server (multi-process / multi-host)
"""

from dynamo_tpu.runtime.transports.kvstore import KvStore, MemKvStore, Lease, WatchEvent, EventType
from dynamo_tpu.runtime.transports.pubsub import PubSub, MemPubSub, Message

__all__ = [
    "KvStore",
    "MemKvStore",
    "Lease",
    "WatchEvent",
    "EventType",
    "PubSub",
    "MemPubSub",
    "Message",
]
