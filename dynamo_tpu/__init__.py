"""dynamo-tpu: a TPU-native distributed LLM inference serving framework.

Provides the capabilities of NVIDIA Dynamo (reference: /root/reference — an
orchestrator of GPU engines: OpenAI frontend, KV-aware routing, tiered KV block
management, disaggregated prefill/decode, SLA planner) re-designed TPU-first:

- ``dynamo_tpu.runtime``   — distributed runtime: component model, discovery,
  leases, request push routing, TCP response plane (ref: lib/runtime/).
- ``dynamo_tpu.llm``       — LLM serving library: OpenAI protocols + HTTP
  frontend, preprocessor, KV router, KV block manager, disaggregation,
  migration (ref: lib/llm/).
- ``dynamo_tpu.engine``    — the native JAX/XLA/Pallas engine (the part the
  reference outsources to vLLM/SGLang/TRT-LLM): paged attention, continuous
  batching, TP/EP/SP over jax.sharding meshes.
- ``dynamo_tpu.planner``   — SLA/load autoscaling planner (ref: components/planner).
"""

__version__ = "0.1.0"
