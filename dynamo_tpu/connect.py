"""Pythonic device-to-device transfer API — the ``dynamo.nixl_connect`` role.

Ref: lib/bindings/python src/dynamo/nixl_connect/__init__.py — ``Connector``
(:501) with create_readable/create_writable, ``ReadOperation`` (:1273) /
``WriteOperation``, ``Readable/WritableOperation``, ``Descriptor`` (:723,
tensor-aware), ``RdmaMetadata`` (:1417). The reference rides NIXL
(RDMA/NVLink); on TPU hosts the data plane is the runtime's TCP call-home
stream server (the same wire as response streams and disagg KV pulls), with
ICI/DCN device transfer as the intra-slice fast path above it.

Rendezvous model (mirrors nixl_connect):
- One side creates an operation over local buffers and serializes its
  :class:`RdmaMetadata`, which travels to the peer out-of-band (HTTP body,
  pubsub message, store key — anything).
- ``create_readable`` → peer calls ``begin_read(metadata, local_descs)``
  to pull the buffers. ``create_writable`` → peer calls
  ``begin_write(local_descs, metadata)`` to push into them.
- Both sides ``await op.wait_for_completion()``.

``Descriptor`` wraps a numpy array (zero-copy) or a jax array (host
round-trip on export; ``to_jax()`` re-lands on device after receive).
"""

from __future__ import annotations

import asyncio
import json
import uuid
from typing import List, Optional, Sequence, Union

import numpy as np

from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.transports.tcp import ConnectionInfo, TcpCallHome

logger = get_logger(__name__)

_SUBJECT_PREFIX = "connect.read."


class TransferError(Exception):
    pass


class Descriptor:
    """A transferable buffer (ref: nixl_connect Descriptor :723).

    Accepts a numpy array (used in place, received data lands in it
    zero-copy) or a jax array (copied to host on export; use ``to_jax()``
    to put received bytes back on device)."""

    def __init__(self, array):
        import jax

        if isinstance(array, jax.Array):
            self.device = "tpu" if "tpu" in str(jax.devices()[0]).lower() else str(
                list(array.devices())[0].platform
            )
            self._np = np.asarray(array)  # host copy (device→host DMA)
        elif isinstance(array, np.ndarray):
            self.device = "cpu"
            self._np = array
        else:
            raise TypeError(f"Descriptor wants numpy or jax array, got {type(array)}")

    @property
    def array(self) -> np.ndarray:
        return self._np

    @property
    def shape(self):
        return tuple(self._np.shape)

    @property
    def dtype(self) -> str:
        return str(self._np.dtype)

    @property
    def nbytes(self) -> int:
        return self._np.nbytes

    def meta(self) -> dict:
        return {"shape": list(self.shape), "dtype": self.dtype}

    def to_jax(self, sharding=None):
        import jax

        return jax.device_put(self._np, sharding) if sharding is not None else jax.device_put(self._np)

    def _fill(self, raw: bytes, header: dict) -> None:
        shape, dtype = tuple(header["shape"]), np.dtype(header["dtype"])
        if shape != self.shape or np.dtype(dtype) != self._np.dtype:
            raise TransferError(
                f"descriptor mismatch: got {shape}/{dtype}, want {self.shape}/{self._np.dtype}"
            )
        incoming = np.frombuffer(raw, dtype=dtype).reshape(shape)
        np.copyto(self._np, incoming)


class RdmaMetadata:
    """Serializable rendezvous token (ref: nixl_connect RdmaMetadata :1417)."""

    def __init__(self, kind: str, nonce: str, descriptors: List[dict],
                 subject: Optional[str] = None, conn: Optional[dict] = None):
        self.kind = kind  # "readable" | "writable"
        self.nonce = nonce
        self.descriptors = descriptors
        self.subject = subject
        self.conn = conn

    def to_json(self) -> str:
        return json.dumps({
            "kind": self.kind, "nonce": self.nonce, "descriptors": self.descriptors,
            "subject": self.subject, "conn": self.conn,
        })

    @classmethod
    def from_json(cls, raw: Union[str, bytes]) -> "RdmaMetadata":
        d = json.loads(raw)
        return cls(d["kind"], d["nonce"], d["descriptors"], d.get("subject"), d.get("conn"))


class _Completable:
    def __init__(self):
        self._done = asyncio.Event()
        self._error: Optional[str] = None

    def _complete(self, error: Optional[str] = None) -> None:
        self._error = error
        self._done.set()

    async def wait_for_completion(self, timeout: Optional[float] = None) -> None:
        if timeout is None:
            await self._done.wait()
        else:
            await asyncio.wait_for(self._done.wait(), timeout)
        if self._error:
            raise TransferError(self._error)


class ReadableOperation(_Completable):
    """Local buffers a remote may pull (ref: nixl_connect ReadableOperation).
    Completes after ``remaining_reads`` pulls have been served."""

    def __init__(self, connector: "Connector", descriptors: Sequence[Descriptor], remaining_reads: int):
        super().__init__()
        self.connector = connector
        self.descriptors = list(descriptors)
        self.nonce = uuid.uuid4().hex
        self.subject = _SUBJECT_PREFIX + self.nonce
        self.remaining_reads = remaining_reads
        self._sub = None
        self._task: Optional[asyncio.Task] = None

    async def _start(self) -> None:
        self._sub = await self.connector.drt.bus.subscribe(self.subject)
        self._task = asyncio.get_running_loop().create_task(self._serve())

    async def _serve(self) -> None:
        served = 0
        try:
            async for msg in self._sub:
                try:
                    req = json.loads(msg.data)
                    call_home = TcpCallHome(ConnectionInfo.from_dict(req["conn"]))
                    if not await call_home.connect():
                        continue
                    try:
                        try:
                            for i, d in enumerate(self.descriptors):
                                await call_home.send(
                                    {"seq": i, "total": len(self.descriptors), **d.meta()},
                                    d.array.tobytes(),
                                )
                            await call_home.complete()
                        except Exception as e:
                            # Tell the reader why before closing — otherwise it
                            # hangs until its own wait_for_completion timeout.
                            try:
                                await call_home.error(f"serve failed: {e}")
                            except (ConnectionError, OSError):
                                pass
                            raise
                    finally:
                        await call_home.close()
                    served += 1
                    if served >= self.remaining_reads:
                        self._complete()
                        return
                except (ConnectionError, OSError, ValueError, KeyError) as e:
                    logger.warning("readable %s: serve failed: %s", self.nonce, e)
        except asyncio.CancelledError:
            pass
        finally:
            # Drop the broker subscription whether we completed, were
            # cancelled, or the subscription closed — a long-lived worker
            # creates one op per transfer and must not leak subscribers.
            if self._sub is not None:
                await self._sub.unsubscribe()
                self._sub = None

    def metadata(self) -> RdmaMetadata:
        return RdmaMetadata(
            "readable", self.nonce, [d.meta() for d in self.descriptors], subject=self.subject
        )

    async def cancel(self) -> None:
        if self._task is not None:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
        if self._sub is not None:
            await self._sub.unsubscribe()
            self._sub = None
        if not self._done.is_set():
            self._complete("cancelled")


class WritableOperation(_Completable):
    """Local buffers a remote will push into (ref: WritableOperation)."""

    def __init__(self, connector: "Connector", descriptors: Sequence[Descriptor]):
        super().__init__()
        self.connector = connector
        self.descriptors = list(descriptors)
        self.nonce = uuid.uuid4().hex
        self.conn_info, self._pending = connector.drt.tcp_server_handle().register()
        self._task: Optional[asyncio.Task] = None

    async def _start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._receive())

    async def _receive(self) -> None:
        filled = set()
        try:
            async for frame in self._pending.frames():
                if frame.kind == "data":
                    seq = int(frame.header["seq"])
                    if not 0 <= seq < len(self.descriptors):
                        self._complete(f"bad descriptor index {seq}")
                        return
                    self.descriptors[seq]._fill(frame.body, frame.header)
                    filled.add(seq)
                elif frame.kind == "error":
                    self._complete(frame.header.get("message", "write failed"))
                    return
            # Stream ended cleanly: only complete if every descriptor landed —
            # a short write (peer stopped early, count mismatch) must surface,
            # not yield silently stale buffers.
            if len(filled) < len(self.descriptors):
                self._complete(f"short write: {len(filled)}/{len(self.descriptors)} descriptors filled")
            else:
                self._complete()
        except (TransferError, ValueError, KeyError, TypeError) as e:
            # Malformed frame or unwritable destination: the op must still
            # complete (with the error) or waiters hang forever.
            self._complete(str(e))
        finally:
            self.connector.drt.tcp_server_handle().unregister(self.conn_info.stream_id)

    def metadata(self) -> RdmaMetadata:
        return RdmaMetadata(
            "writable", self.nonce, [d.meta() for d in self.descriptors],
            conn=self.conn_info.to_dict(),
        )


class ReadOperation(_Completable):
    """Pull a remote readable's buffers into local descriptors."""

    def __init__(self, connector: "Connector", metadata: RdmaMetadata, descriptors: Sequence[Descriptor]):
        super().__init__()
        if metadata.kind != "readable":
            raise ValueError("begin_read needs metadata from a ReadableOperation")
        self.connector = connector
        self.metadata_ = metadata
        self.descriptors = list(descriptors)
        self._task: Optional[asyncio.Task] = None

    async def _start(self) -> None:
        conn_info, pending = self.connector.drt.tcp_server_handle().register()
        await self.connector.drt.bus.publish(
            self.metadata_.subject, json.dumps({"conn": conn_info.to_dict()}).encode()
        )

        async def receive():
            filled = set()
            try:
                async for frame in pending.frames():
                    if frame.kind == "data":
                        seq = int(frame.header["seq"])
                        if not 0 <= seq < len(self.descriptors):
                            self._complete(f"bad descriptor index {seq}")
                            return
                        self.descriptors[seq]._fill(frame.body, frame.header)
                        filled.add(seq)
                    elif frame.kind == "error":
                        self._complete(frame.header.get("message", "read failed"))
                        return
                # A serve that stopped early must fail the read, not succeed
                # with stale/zero local buffers.
                if len(filled) < len(self.descriptors):
                    self._complete(f"short read: {len(filled)}/{len(self.descriptors)} descriptors filled")
                else:
                    self._complete()
            except (TransferError, ValueError, KeyError, TypeError) as e:
                self._complete(str(e))
            finally:
                self.connector.drt.tcp_server_handle().unregister(conn_info.stream_id)

        self._task = asyncio.get_running_loop().create_task(receive())


class WriteOperation(_Completable):
    """Push local descriptors into a remote writable."""

    def __init__(self, connector: "Connector", descriptors: Sequence[Descriptor], metadata: RdmaMetadata):
        super().__init__()
        if metadata.kind != "writable":
            raise ValueError("begin_write needs metadata from a WritableOperation")
        self.connector = connector
        self.metadata_ = metadata
        self.descriptors = list(descriptors)
        self._task: Optional[asyncio.Task] = None

    async def _start(self) -> None:
        async def push():
            call_home = TcpCallHome(ConnectionInfo.from_dict(self.metadata_.conn))
            try:
                if not await call_home.connect():
                    self._complete("remote writable rejected connection")
                    return
                for i, d in enumerate(self.descriptors):
                    await call_home.send(
                        {"seq": i, "total": len(self.descriptors), **d.meta()}, d.array.tobytes()
                    )
                await call_home.complete()
                self._complete()
            except (ConnectionError, OSError) as e:
                self._complete(f"write failed: {e}")
            finally:
                await call_home.close()

        self._task = asyncio.get_running_loop().create_task(push())


class Connector:
    """Factory bound to a DistributedRuntime (ref: nixl_connect Connector)."""

    def __init__(self, drt):
        self.drt = drt

    async def create_readable(
        self, *descriptors: Descriptor, remaining_reads: int = 1
    ) -> ReadableOperation:
        op = ReadableOperation(self, descriptors, remaining_reads)
        await op._start()
        return op

    async def create_writable(self, *descriptors: Descriptor) -> WritableOperation:
        op = WritableOperation(self, descriptors)
        await op._start()
        return op

    async def begin_read(
        self, metadata: Union[RdmaMetadata, str, bytes], *descriptors: Descriptor
    ) -> ReadOperation:
        if not isinstance(metadata, RdmaMetadata):
            metadata = RdmaMetadata.from_json(metadata)
        op = ReadOperation(self, metadata, descriptors)
        await op._start()
        return op

    async def begin_write(
        self, metadata: Union[RdmaMetadata, str, bytes], *descriptors: Descriptor
    ) -> WriteOperation:
        if not isinstance(metadata, RdmaMetadata):
            metadata = RdmaMetadata.from_json(metadata)
        op = WriteOperation(self, descriptors, metadata)
        await op._start()
        return op
