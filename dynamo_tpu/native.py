"""Loader for the optional C++ extension (``native/dynamo_tpu_native.cc``).

The native module provides the framework's hot paths — chained xxh3 block
hashing and the router radix tree (ref: lib/tokens/src/lib.rs and
lib/llm/src/kv_router/indexer.rs are native Rust in the reference for the
same reason). Pure-Python fallbacks exist everywhere; this module tries to
import the built extension and, failing that, builds it once in-tree.

Build hygiene: a file lock serializes concurrent builders (frontend + N
workers all importing at startup), the result — success or failure — is
stamped with the source mtime so a doomed build is attempted once per
source change rather than once per process, and compiler output goes to
``native/build/build.log``. Set ``DYN_NATIVE=0`` to force pure Python.
"""

from __future__ import annotations

import contextlib
import glob
import importlib
import logging
import os
import subprocess
import sys

logger = logging.getLogger(__name__)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO, "native")
_BUILD_DIR = os.path.join(_NATIVE_DIR, "build")
_SRC = os.path.join(_NATIVE_DIR, "dynamo_tpu_native.cc")
_STAMP = os.path.join(_BUILD_DIR, ".build_stamp")  # "<src_mtime> <ok|fail>"
_LOG = os.path.join(_BUILD_DIR, "build.log")

_module = None
_tried = False


def _try_import():
    if _BUILD_DIR not in sys.path and os.path.isdir(_BUILD_DIR):
        sys.path.insert(0, _BUILD_DIR)
    try:
        return importlib.import_module("dynamo_tpu_native")
    except ImportError:
        return None


def _src_mtime() -> float:
    try:
        return os.path.getmtime(_SRC)
    except OSError:
        return 0.0


def _stamp_state() -> str | None:
    """'ok'/'fail' if a build for the current source was already attempted."""
    try:
        with open(_STAMP) as f:
            mtime_s, state = f.read().split()
        if float(mtime_s) == _src_mtime():
            return state
    except (OSError, ValueError):
        pass
    return None


def _have_built_so() -> bool:
    return bool(glob.glob(os.path.join(_BUILD_DIR, "dynamo_tpu_native*.so")))


@contextlib.contextmanager
def _build_lock():
    os.makedirs(_BUILD_DIR, exist_ok=True)
    path = os.path.join(_BUILD_DIR, ".lock")
    fd = os.open(path, os.O_CREAT | os.O_RDWR)
    try:
        import fcntl

        fcntl.flock(fd, fcntl.LOCK_EX)
        yield
    finally:
        os.close(fd)


def _build() -> None:
    """Build under the lock; stamp the outcome so failures don't repeat."""
    with _build_lock():
        # Another process may have finished while we waited on the lock.
        if _stamp_state() is not None:
            return
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(_NATIVE_DIR, "setup.py")],
                cwd=_NATIVE_DIR,
                capture_output=True,
                text=True,
                timeout=180,
            )
            ok = proc.returncode == 0
            with open(_LOG, "w") as f:
                f.write(proc.stdout + "\n" + proc.stderr)
        except Exception as e:  # compiler missing, timeout, …
            ok = False
            with contextlib.suppress(OSError):
                with open(_LOG, "w") as f:
                    f.write(f"build invocation failed: {e}\n")
        tmp = _STAMP + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{_src_mtime()} {'ok' if ok else 'fail'}")
        os.replace(tmp, _STAMP)  # atomic: readers never see a partial stamp
        if not ok:
            logger.warning(
                "native extension build failed (pure-Python fallback active); see %s", _LOG
            )


def get_native():
    """The extension module, or None (pure-Python mode)."""
    global _module, _tried
    if _tried:
        return _module
    _tried = True
    if os.environ.get("DYN_NATIVE", "1") == "0":
        return None
    if not os.path.exists(_SRC):  # installed without sources: import-or-nothing
        _module = _try_import()
        return _module
    state = _stamp_state()
    if state is None or (state == "ok" and not _have_built_so()):
        _build()
        state = _stamp_state()
    if state == "ok":
        _module = _try_import()
        if _module is None:
            logger.warning("native extension built but import failed; pure-Python fallback")
    return _module


def available() -> bool:
    return get_native() is not None
