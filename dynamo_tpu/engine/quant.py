"""Weight-only int8 quantization: serve models ~2× bigger per HBM byte.

The role fp8/int8 weight formats play in the reference's engines
(--quantization levers; GGUF q8_0 is the storage-side equivalent —
llm/gguf.py loads it): layer matmul weights are stored as int8 codes with
a per-output-channel symmetric scale and dequantized to the compute dtype
one LAYER at a time inside the scan, so the resident footprint is the
int8 codes plus one layer's transient bf16 weights. Embedding and
lm_head stay in the compute dtype — re-dequantizing a vocab-sized matrix
every decode step would add ~1 GB of HBM traffic per token at 8B scale.

Measured consequence on a 16 GiB v5e: Llama-3-8B bf16 weights alone are
15.0 GiB and the decode workspace OOMs; with int8 layer weights the
model serves with room for KV.
"""

from __future__ import annotations

from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp

# Dense layer matmul weights eligible for int8 storage. MoE expert stacks
# keep their compute dtype (ragged/capacity dispatch paths index them in
# ways that would re-dequantize per expert; revisit if MoE capacity needs
# the headroom).
QUANT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


class QuantW(NamedTuple):
    """int8 weight + per-output-channel scale. A pytree — rides jit args,
    scan xs slices, and donation like a plain array."""

    q: jax.Array  # int8 [..., in, out]
    scale: jax.Array  # f32 [..., 1, out]


def quantize_weight(w: jax.Array) -> QuantW:
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return QuantW(q, scale)


def quantize_weight_np(w) -> QuantW:
    """Host-side (numpy) quantization for the checkpoint-load path: the
    bf16 stack never touches the device, so models whose full-precision
    weights exceed HBM (8B on v5e) load straight into int8 residency."""
    import numpy as np

    w32 = np.asarray(w, dtype=np.float32)
    amax = np.max(np.abs(w32), axis=-2, keepdims=True)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(w32 / scale), -127, 127).astype(np.int8)
    return QuantW(jnp.asarray(q), jnp.asarray(scale))


def wt(x, dtype=jnp.bfloat16):
    """Dequantize a QuantW to the compute dtype; plain arrays pass through.

    The product runs in f32 (codes are exact in f32, scale is stored f32)
    and only the RESULT casts down: multiplying in bf16 first rounds the
    scale to 8 mantissa bits and compounds a second rounding on the
    product — ~0.4% worst-case extra error per weight, on top of the
    half-code-step quantization floor. XLA still fuses the dequant into
    the consuming matmul's reads either way."""
    if isinstance(x, QuantW):
        return (x.q.astype(jnp.float32) * x.scale).astype(dtype)
    return x


def dequant_layer(lp: Dict, dtype) -> Dict:
    """Per-layer dequant at the top of a layer body: one transient bf16
    copy of this layer's matmul weights (tens of MB), never the stack."""
    if not any(isinstance(v, QuantW) for v in lp.values()):
        return lp
    return {k: wt(v, dtype) for k, v in lp.items()}


def quantize_params(params: Dict) -> Dict:
    """Quantize the dense layer matmul weights of a loaded param tree —
    IN PLACE, one tensor at a time, releasing each bf16 stack before the
    next quantizes. A functional version would hold the full bf16 tree
    and the int8 copies simultaneously: at 8B that is ~23 GiB of HBM and
    OOMs the 16 GiB chip the feature exists to fit (measured). MoE trees
    pass through untouched for non-QUANT_KEYS entries either way."""
    import numpy as np

    layers = params["layers"]
    for k in QUANT_KEYS:
        if k in layers and not isinstance(layers[k], QuantW):
            w = layers.pop(k)
            if w.ndim >= 3:
                # Stacked [L, in, out]: quantize per layer slice — the
                # float32 intermediates of a whole 8B-scale MLP stack are
                # ~2× its bf16 bytes and OOM next to the resident weights.
                qs, ss = [], []
                for l in range(w.shape[0]):
                    qw_l = quantize_weight(w[l])
                    # Real sync before the next slice (block_until_ready
                    # can return early on tunneled backends).
                    np.asarray(qw_l.scale.ravel()[0:1])
                    qs.append(qw_l.q)
                    ss.append(qw_l.scale)
                qw = QuantW(jnp.stack(qs), jnp.stack(ss))
                np.asarray(qw.scale.ravel()[0:1])
                del qs, ss
            else:
                qw = quantize_weight(w)
                np.asarray(qw.scale.ravel()[0:1])
            del w
            layers[k] = qw
    return params


def params_quantized(params: Dict) -> bool:
    return any(isinstance(v, QuantW) for v in params.get("layers", {}).values())
