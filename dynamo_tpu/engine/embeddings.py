"""Embedding engine: serves /v1/embeddings requests on the same weights.

Ref: the reference exposes /v1/embeddings (http/service/openai.rs:369) and
routes it to engines registered with ModelType::Embedding. Here the engine
runs ``llama.embed`` on bucketed lengths (one XLA executable per bucket,
same compile-caching strategy as the scheduler's prefill buckets).
"""

from __future__ import annotations

import asyncio
from typing import Any, AsyncIterator, List

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.scheduler import next_bucket
from dynamo_tpu.runtime.engine import Context


class EmbeddingEngine:
    """AsyncEngine over ``llama.embed``. Request wire:
    ``{"token_ids": [...]}` or ``{"batch_token_ids": [[...], ...]}``;
    one response frame ``{"embeddings": [[...]], "finish_reason": "stop"}``.
    """

    def __init__(self, config: ModelConfig, params, buckets: List[int] | None = None):
        self.config = config
        self.params = params
        self.buckets = buckets or [32, 128, 512, min(2048, config.max_seq_len)]
        self._jit = jax.jit(
            lambda p, t, n: llama.embed(p, self.config, t, n)
        )

    def _embed_one(self, ids: List[int]) -> List[float]:
        ids = ids[: min(self.config.max_seq_len, self.buckets[-1])]
        bucket = next_bucket(len(ids), self.buckets)
        padded = jnp.zeros((bucket,), dtype=jnp.int32).at[: len(ids)].set(jnp.asarray(ids, dtype=jnp.int32))
        out = self._jit(self.params, padded, jnp.int32(len(ids)))
        return [float(x) for x in out]

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        batches = request.get("batch_token_ids")
        if batches is None:
            batches = [request.get("token_ids") or []]
        embeddings = []
        for ids in batches:
            embeddings.append(await asyncio.to_thread(self._embed_one, list(ids)))
        yield {
            "embeddings": embeddings,
            "prompt_tokens": sum(len(b) for b in batches),
            "finish_reason": "stop",
            "token_ids": [],
            "index": 0,
        }
