"""Continuous batching scheduler: the engine's step loop.

The reference outsources this to vLLM/SGLang/TRT-LLM schedulers; the mocker
(lib/llm/src/mocker/scheduler.rs:240) emulates exactly this machinery —
prefill admission, decode batching, KV block accounting, eviction. Here it is
implemented for real against XLA's static-shape world:

- **Bucketed compilation**: prefill lengths and decode batch sizes round up
  to power-of-two buckets; XLA compiles one executable per bucket and reuses
  it (SURVEY.md §7 hard part (b)).
- **Chunked prefill**: prompts longer than the largest bucket run as chunks,
  interleaving with decode so long prompts don't starve running sequences.
- **Prefix caching**: prompt block hashes are matched against the allocator's
  registry; matched blocks skip prefill entirely (the engine-side half of the
  KV-aware routing story, §3D).
- **Mixed prefill+decode steps**: with sequences decoding AND prefill work
  waiting, each iteration dispatches ONE ragged batch — the full decode
  batch plus up to ``mixed_prefill_budget`` chunk tokens (llama.mixed_step;
  DynaServe arXiv:2504.09285 / TPU ragged paged attention arXiv:2604.15464
  show the same unification). A long prefill no longer stalls the decode
  wave, and admission no longer waits for an empty one.
- **Priority**: decode-first each iteration (keeps ITL low), one prefill
  chunk per iteration (bounds TTFT).

The step loop runs in a worker thread (`asyncio.to_thread`) so device-blocked
steps never stall the process's asyncio IO (the serving plane).
"""

from __future__ import annotations

import asyncio
import enum
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.engine.flight_recorder import FlightRecorder, StepCostModel, StepTimer
from dynamo_tpu.engine.kv_cache import BlockAllocator, KvCacheArrays, KvEvent, OutOfBlocksError
from dynamo_tpu.runtime.ledger import RequestBill, TenantLedger
from dynamo_tpu.runtime.telemetry import SloConfig, SloJudge, Telemetry
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.sampling import SamplingParams, guided_sample_batch, sample_batch
from dynamo_tpu.llm.tokens import extend_block_hashes
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.tracing import get_tracer

logger = get_logger(__name__)


def next_bucket(n: int, buckets: List[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def width_rungs(max_w: int, start: int = 4) -> List[int]:
    """Block-table width rungs up to and including the bucket of ``max_w``:
    pow2 and 1.5·pow2 (4, 6, 8, 12, 16, 24, ...)."""
    rungs: List[int] = []
    w = start
    while True:
        rungs.append(w)
        if w >= max_w:
            return rungs
        nxt = w + w // 2 if w & (w - 1) == 0 else (w // 3) * 4
        w = nxt


def width_bucket(n: int, cap: int) -> int:
    """Smallest pow2-or-1.5·pow2 rung ≥ n, clamped to ``cap``. bench.py uses
    the same rule so driver decode numbers reflect production table widths."""
    return min(width_rungs(max(n, 1))[-1], cap)


@dataclass
class StopConditions:
    max_tokens: int = 256
    min_tokens: int = 0
    stop_token_ids: List[int] = field(default_factory=list)
    ignore_eos: bool = False
    # Remaining deadline budget in ms at arrival (wire: the frontend's
    # --request-timeout-ms / client ``timeout``, minus time already spent).
    # Past-deadline rows are evicted with finish_reason "timeout" and their
    # KV freed — a hung or saturated engine cannot hold a request forever.
    deadline_ms: Optional[float] = None

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "StopConditions":
        d = d or {}
        dl = d.get("deadline_ms")
        return cls(
            max_tokens=d.get("max_tokens") or 256,
            min_tokens=d.get("min_tokens") or 0,
            stop_token_ids=list(d.get("stop_token_ids") or []),
            ignore_eos=bool(d.get("ignore_eos", False)),
            deadline_ms=float(dl) if dl else None,
        )


class SeqState(enum.Enum):
    WAITING = "waiting"
    PREFILL = "prefill"  # mid chunked-prefill
    RUNNING = "running"
    FINISHED = "finished"


@dataclass
class StepOutput:
    token_id: int
    finished: bool = False
    finish_reason: Optional[str] = None
    logprob: Optional[float] = None
    # Set on the first token only: seconds the request waited between
    # arrival and engine admission (the saturation signal the SLA planner
    # inverts; ref: http_queue_guard, http/service/metrics.rs).
    queue_s: Optional[float] = None
    # Set on the first token only: prompt tokens whose KV came from the
    # prefix cache instead of prefill compute — the engine's ground truth
    # behind OpenAI ``usage.prompt_tokens_details.cached_tokens`` and the
    # KV router's reuse accounting.
    cached_tokens: Optional[int] = None
    # OpenAI ``top_logprobs``: [(token_id, logprob), ...] for the k most
    # likely tokens at this position (k = sampling.top_logprobs), computed
    # in the same fused sampling dispatch as ``logprob``.
    top_logprobs: Optional[list] = None


@dataclass
class Sequence:
    request_id: str
    prompt: List[int]
    sampling: SamplingParams
    stop: StopConditions
    eos_token_ids: List[int] = field(default_factory=list)
    # runtime state
    state: SeqState = SeqState.WAITING
    output_ids: List[int] = field(default_factory=list)
    block_ids: List[int] = field(default_factory=list)
    num_computed: int = 0  # prompt tokens whose KV is in cache
    block_hashes: List[int] = field(default_factory=list)
    num_cached_blocks: int = 0  # prefix blocks reused from cache
    cached_tokens: int = 0  # prompt tokens skipped by the prefix cache
    out_queue: "asyncio.Queue[Optional[StepOutput]]" = field(default_factory=asyncio.Queue)
    arrival_ts: float = field(default_factory=time.monotonic)
    admitted_ts: Optional[float] = None  # first engine work (queue-time end)
    first_token_ts: Optional[float] = None
    aborted: bool = False
    abort_reason: str = "cancelled"
    # Absolute eviction deadline (arrival + stop.deadline_ms); None = no
    # deadline. Swept every step in _reap_aborted.
    deadline_ts: Optional[float] = None
    # Disaggregation: prefill-role sequences keep their blocks at finish for
    # export to the decode worker (ref: vllm do_remote_decode flow, §3C).
    keep_blocks_on_finish: bool = False
    # Decode-role sequences start from remotely prefilled KV.
    prefilled: Optional[dict] = None
    # Multimodal: feature rows injected at positions [0, F) during prefill
    # (the prompt's first F ids are placeholders). Disables prefix caching
    # for the sequence (placeholder ids don't identify image content).
    mm_features: Optional[np.ndarray] = None
    # Preemption resume: tokens whose KV must be recomputed (all generated
    # tokens fold in; the final token re-enters via decode, so no sampling
    # happens at the end of a resume prefill).
    resume_tokens: Optional[List[int]] = None
    preemptions: int = 0
    # Speculative decoding: positions coherently materialized in the DRAFT
    # cache (the draft mirrors the target's block tables; see _decode_spec).
    d_n: int = 0
    # Chosen-token logprob computed by the single-row sampler, consumed by
    # the next _append_token (sampling.logprobs requests).
    _pending_logprob: Optional[float] = None
    # Top-k alternatives for the same token (sampling.top_logprobs > 0),
    # consumed alongside _pending_logprob.
    _pending_top_logprobs: Optional[list] = None
    # Request tracing: (trace_id, parent_span_id) when this request's trace
    # is sampled; None keeps the scheduler's trace path one branch.
    trace: Optional[tuple] = None
    # Guided decoding: per-sequence token-FSM cursor (llm/guided
    # GuidedState). The scheduler advances it host-side from each sampled
    # token and masks logits device-side via the shared mask pool.
    guided: Optional[object] = None
    # Capacity-ledger attribution (runtime/ledger.py): the tenant this
    # request bills to, plus the running bill accumulators. Device-seconds
    # accrue per step in _bill_step; KV block-seconds accrue lazily from
    # ``kv_ts`` (the clock starts when blocks are first held — COW-shared
    # prefix blocks are in block_ids, so holders are charged too).
    tenant: str = "anon"
    bill_prefill_s: float = 0.0
    bill_decode_s: float = 0.0
    bill_flops: float = 0.0
    bill_kv_block_s: float = 0.0
    kv_ts: Optional[float] = None
    billed: bool = False

    @property
    def all_ids(self) -> List[int]:
        return self.prompt + self.output_ids

    @property
    def total_len(self) -> int:
        return len(self.prompt) + len(self.output_ids)


@dataclass
class SchedulerConfig:
    num_blocks: int = 512
    # Decode slots. Default 16→32 (r6): the bench http sweep's first-token
    # breakdown at concurrency 64 put 292 ms of the 393 ms TTFT p50 in the
    # ADMISSION QUEUE with 16 slots (prefill wait was 20 ms) — the knee was
    # queueing, not compute; 32 slots measured +53% req/s and halved p50,
    # with OutOfBlocks backpressure still guarding memory. Size num_blocks
    # to the expected context × slots as before.
    max_running: int = 32
    prefill_buckets: List[int] = field(default_factory=lambda: [32, 64, 128, 256, 512, 1024, 2048])
    decode_buckets: List[int] = field(default_factory=lambda: [1, 2, 4, 8, 16, 32])
    max_prefill_chunk: int = 2048
    enable_prefix_caching: bool = True
    # Disagg prefill role: how long finished-prefill KV blocks may await the
    # decode worker's pull before being reclaimed (orphan guard — e.g. the
    # decode worker timed out or died between prefill and pull).
    export_ttl_s: float = 120.0
    # Multi-step decode: run N autoregressive steps + sampling on device per
    # dispatch (vLLM --num-scheduler-steps role). Amortizes host dispatch —
    # the dominant cost on high-latency links. Tradeoffs: tokens stream out
    # in bursts of N, stop conditions trim after the window (up to N-1
    # wasted steps per finished sequence), and admission waits for the
    # window (only used when no request is waiting). Default 32: measured
    # on v5e at 1B (gather + hoisted window), window 16→32 takes b8 from
    # 7.6→4.9 ms/step and b32 from 11.6→8.0 — the hoisted prefix gather,
    # dispatch, and frame all amortize across the window; 64 gains nothing
    # further and doubles the burst.
    num_scheduler_steps: int = 32
    # While requests wait for admission, cap decode windows at this rung
    # (None = keep full windows). Full windows maximize throughput on
    # dispatch-latency-heavy links — each window pays one ~100 ms host
    # round-trip on tunneled devices, so shrinking FURTHER under load
    # serialized tokens on the wire (measured: served rate fell 25% at
    # cap 1). Default 8: a newly arrived request must never wait a full
    # 32-step window for admission (TTFT regression flagged in ADVICE.md)
    # — mixed batching largely subsumes this (prefill rides the decode
    # step), but the cap still bounds the window on the fallback paths
    # (spec decode, non-llama, mixed disabled). None restores full windows.
    window_waiting_cap: Optional[int] = 8
    # Mixed prefill+decode steps: when sequences are decoding AND prefill
    # work is waiting, each engine step carries the full decode batch plus
    # up to ``mixed_prefill_budget`` prefill tokens from the head of the
    # queue in ONE dispatch (llama.mixed_step) — a long prefill no longer
    # stalls the decode wave, and admission no longer waits for an empty
    # one. The budget bounds the chunk riding each step (the per-step
    # decode stall is one chunk's compute, not a whole prompt's); an
    # itl_budget_ms cap composes on top via _chunk_budget.
    enable_mixed_batching: bool = True
    mixed_prefill_budget: int = 512
    # ITL protection: while sequences are decoding, cap each prefill chunk so
    # its estimated device time stays under this budget (the prefill token
    # rate is learned online from measured chunks). None ⇒ chunks use
    # max_prefill_chunk regardless of running decodes. This bounds the
    # decode stall a long prompt can inject — the role chunked-prefill
    # interleaving plays in the reference's engines (mocker/scheduler.rs:240).
    itl_budget_ms: Optional[float] = None
    # On OutOfBlocks mid-decode, preempt the newest running sequence (free
    # its blocks, re-prefill it later) instead of finishing the starved
    # sequence with "length" (ref: vLLM recompute preemption).
    enable_preemption: bool = True
    # Zero-bubble decode: overlap the host's per-step bookkeeping with the
    # NEXT step's device compute. The fused decode+sample executable
    # (llama.decode_sample) returns the sampled tokens as a DEVICE array
    # that feeds straight back as the next dispatch's input, so step N+1
    # launches before step N's tokens ever reach the host; the readback +
    # stop/detok bookkeeping then run one step behind, overlapped with
    # device compute. Batch-composition changes (admission, finish,
    # preemption, block-table growth) and per-row extras (guided /
    # processors / seeded sampling / logprobs / penalties — all need host
    # work between steps) flush the pipeline back to the sync path, same
    # fallback shape as the spec/multi-step exclusions. Streaming runs one
    # step behind on this path (README "Decode pipeline").
    enable_overlap_decode: bool = True
    # Guided decoding: initial device mask-pool capacity in FSM-state rows.
    # The masked-sampling executable's shape is (decode_bucket, pool_rows);
    # warmup() precompiles it at this capacity, so as long as the total
    # states of live grammars fit, guided rows add no post-warmup compiles.
    # Overflow doubles the pool (pow2 buckets, one recompile, logged).
    guided_pool_rows: int = 1024
    # SLA telemetry: per-request latency targets (None = phase unjudged).
    # Every finished request's TTFT/TPOT is judged against these, feeding
    # the slo_*_total counters and the goodput account the planner reads.
    slo_ttft_ms: Optional[float] = None
    slo_tpot_ms: Optional[float] = None
    # Rolling window for the quantile-gauge snapshots (digest totals stay
    # cumulative for the aggregator's Prometheus histogram re-export).
    telemetry_window_s: float = 60.0
    # Stall watchdog: the step loop not completing a step for this long
    # while work is queued marks the engine stalled (unhealthy /health,
    # engine_stalled counter). Sized well past any legitimate cold compile.
    stall_after_s: float = 120.0
    # Tenant capacity ledger: SpaceSaving sketch size — per-tenant digests
    # and SLO counters exist only for the top-K set, so this bounds the
    # ledger's memory regardless of tenant cardinality.
    ledger_top_k: int = 16


@dataclass
class ForwardPassMetrics:
    """Worker load snapshot published to the router
    (ref: _core.pyi:354-427 ForwardPassMetrics{WorkerStats, KvStats})."""

    num_running: int = 0
    num_waiting: int = 0
    kv_usage: float = 0.0
    kv_total_blocks: int = 0
    kv_active_blocks: int = 0
    prefill_tokens_in_flight: int = 0
    request_total: int = 0
    # Speculative decoding acceptance accounting (SpecDecodeStats.to_dict(),
    # None when no draft model is attached) — ref: _core.pyi:354-427.
    spec_decode: Optional[dict] = None
    # Wide-EP capacity-dispatch pressure: (token, expert) assignments dropped
    # by capacity limits / total routed assignments (capacity MoE only).
    moe_dropped_total: int = 0
    moe_assignments_total: int = 0
    # Mixed-step composition: how many engine steps fused a prefill chunk
    # into the decode dispatch, and the token split they carried. The ratio
    # prefill_tokens/steps is the average chunk riding each decode step —
    # the saturation signal for mixed_prefill_budget tuning.
    mixed_steps_total: int = 0
    mixed_prefill_tokens_total: int = 0
    mixed_decode_tokens_total: int = 0
    # Zero-bubble decode pipeline: steps that ran overlapped (dispatch N+1
    # before step N's readback) and pipeline flushes back to the sync path
    # (admission/finish/growth/extras). flushes/steps is the fraction of
    # pipeline restarts — high ratios mean the traffic mix defeats overlap.
    overlap_steps_total: int = 0
    overlap_flushes_total: int = 0
    # Automatic prefix caching: prompt tokens served from resident KV
    # instead of prefill compute, and the block-granular hit/miss/evict/
    # onboard account behind them. hit/(hit+miss) is the block hit rate;
    # onboard counts DRAM/disk-tier blocks copied back into HBM on a hit.
    cached_tokens_total: int = 0
    prefix_hit_blocks_total: int = 0
    prefix_miss_blocks_total: int = 0
    prefix_evicted_blocks_total: int = 0
    prefix_onboard_total: int = 0
    # Elastic capacity dial (set_capacity_dial): the live prefill:decode
    # split. fraction 0.5 = the configured budget/slots; the budget/slots
    # gauges carry the APPLIED values so the router's cost model and the
    # planner's ratio actuator see the dial, not just its setting.
    elastic_prefill_fraction: float = 0.5
    elastic_prefill_budget: int = 0
    elastic_decode_slots: int = 0
    elastic_dial_changes_total: int = 0

    def to_wire(self) -> dict:
        return self.__dict__.copy()


class Scheduler:
    """Owns the device cache + compiled steps + the running/waiting sets.

    Synchronous core (stepped from a thread by TpuEngine); asyncio-facing
    methods only touch queues/events.
    """

    def __init__(
        self,
        model_config: ModelConfig,
        params,
        scheduler_config: Optional[SchedulerConfig] = None,
        *,
        dtype=jnp.bfloat16,
        on_kv_event: Optional[Callable[[KvEvent], None]] = None,
        eos_token_ids: Optional[List[int]] = None,
        rng_seed: int = 0,
        mesh=None,
        parallel=None,
    ):
        from dynamo_tpu.engine.config import resolve_moe_dispatch

        ep = parallel.ep if parallel is not None else (mesh.shape.get("ep", 1) if mesh else 1)
        model_config = resolve_moe_dispatch(model_config, ep)
        self.mc = model_config
        self.sc = scheduler_config or SchedulerConfig()
        self.mesh = mesh
        self.parallel = parallel
        self.allocator = BlockAllocator(self.sc.num_blocks, on_event=on_kv_event)
        # Reserve block 0 as the scratch sink for padded scatter positions.
        self.allocator._free.remove(0)
        if mesh is not None:
            # Sharded serving: place params + cache with the real partition
            # specs; GSPMD propagates shardings through the jitted steps and
            # inserts the tp all-reduces / dp batch splits over ICI.
            from jax.sharding import NamedSharding

            from dynamo_tpu.engine.sharding import kv_cache_spec, shard_params

            tp = parallel.tp if parallel is not None else mesh.shape.get("tp", 1)
            params = shard_params(params, mesh, model_config.tie_word_embeddings, model_config.num_experts)
            cache_sharding = NamedSharding(mesh, kv_cache_spec(model_config.num_kv_heads, tp))
            self.cache = KvCacheArrays.create(model_config, self.sc.num_blocks, dtype=dtype, sharding=cache_sharding)
        else:
            self.cache = KvCacheArrays.create(model_config, self.sc.num_blocks, dtype=dtype)
        self.params = params
        self.max_blocks_per_seq = (model_config.max_seq_len + model_config.block_size - 1) // model_config.block_size

        # Optional tiered block manager (KVBM) — set via attach_kvbm().
        self.kvbm = None
        # Finished prefill-role sequences awaiting KV export (disagg).
        self._pending_exports: Dict[str, Sequence] = {}
        self._export_deadline: Dict[str, float] = {}
        self.waiting: List[Sequence] = []
        self.running: List[Sequence] = []
        self.by_id: Dict[str, Sequence] = {}
        self.request_total = 0
        self.preempt_total = 0
        # Deadline eviction: requests whose deadline_ms budget lapsed before
        # they finished (finish_reason "timeout", KV freed at eviction).
        self.timeouts_total = 0
        self._has_deadlines = False  # skip the per-step sweep until one arrives
        # Online prefill-rate estimate (tokens/s) for ITL-budgeted chunking.
        self._prefill_tok_s: Optional[float] = None
        self._eos = eos_token_ids or []
        self._rng = jax.random.PRNGKey(rng_seed)
        self._step_counter = 0
        # SLA telemetry: mergeable latency digests (ttft/tpot/itl/queue_wait
        # + per-phase step durations via the flight recorder) and the SLO
        # judge behind the goodput account. All host-side — no dispatches.
        self.telemetry = Telemetry(window_s=self.sc.telemetry_window_s)
        self.slo = SloJudge(SloConfig(ttft_ms=self.sc.slo_ttft_ms, tpot_ms=self.sc.slo_tpot_ms))
        # Tenant capacity ledger: per-request bills (queue/device/KV-hold
        # time, FLOPs, tokens) roll into bounded top-K heavy-hitter
        # sketches + per-tenant SLO telemetry (runtime/ledger.py).
        self.ledger = TenantLedger(
            top_k=self.sc.ledger_top_k,
            slo=SloConfig(ttft_ms=self.sc.slo_ttft_ms, tpot_ms=self.sc.slo_tpot_ms),
            window_s=self.sc.telemetry_window_s,
        )
        # Flight recorder: per-phase step histograms + XLA compile tracker
        # (every dispatch registers its shape key; keys first seen after
        # warmup are counted/logged). Tracer: per-request lifecycle events
        # for sequences whose trace is sampled.
        self.flight = FlightRecorder(telemetry=self.telemetry)
        self.tracer = get_tracer()
        # Per-step FLOPs+bytes roofline model from the REAL params/cache
        # byte widths (int8 weights/KV are modeled as stored): BENCH
        # roofline numbers become the live mfu_*/hbm_frac_* gauges.
        p_leaves = jax.tree_util.tree_leaves(params)
        param_count = sum(int(x.size) for x in p_leaves)
        param_bytes = sum(int(x.size) * x.dtype.itemsize for x in p_leaves)
        kv_leaves = jax.tree_util.tree_leaves((self.cache.k, self.cache.v))
        kv_bytes = sum(int(x.size) * x.dtype.itemsize for x in kv_leaves)
        kv_per_token = kv_bytes / max(self.sc.num_blocks * model_config.block_size, 1)
        # KV-read traffic factor per attention path: the XLA gather's
        # read + packed-copy write + attend re-read moves 3× the true
        # prefix bytes; the paged Pallas paths (r5 kernel, megakernel)
        # stream each page once. Without this the hbm_frac_decode gauge
        # can't reflect the megakernel's actual roofline position.
        self._attn_impl = "gather"
        if model_config.architecture == "llama":
            llama.warn_attention_impl_degrade(model_config, self.cache.k)
            self._attn_impl = llama.resolve_attention_impl(model_config, self.cache.k)
        kv_read_factor = 1.0 if self._attn_impl in ("paged", "megakernel") else 3.0
        self.flight.set_cost_model(
            StepCostModel(param_count, param_bytes, kv_per_token,
                          kv_read_factor=kv_read_factor)
        )
        self._param_bytes = param_bytes
        self._kv_cache_bytes = kv_bytes

        # Trim buckets to the model's max length.
        self.sc.prefill_buckets = [b for b in self.sc.prefill_buckets if b <= model_config.max_seq_len] or [
            model_config.max_seq_len
        ]

        from dynamo_tpu.engine.models import get_module

        model = get_module(model_config)
        # Prefill impl: flash = Pallas kernel chunk attention (auto ⇒ TPU
        # only; the interpreted kernel is far too slow for CPU serving).
        self._use_flash_prefill = model_config.architecture == "llama" and (
            model_config.prefill_impl == "flash"
            or (model_config.prefill_impl == "auto" and jax.default_backend() == "tpu")
        )
        # Capacity-dispatch MoE exports drop counters (wide-EP observability;
        # ref: SURVEY.md §2e / trtllm_utils.py:37-39 wide-EP surface).
        self._moe_stats = (
            model_config.architecture == "llama"
            and model_config.num_experts > 0
            and model_config.moe_dispatch == "capacity"
        )
        self._moe_dropped_total = 0  # guarded-by: _aux_lock
        self._moe_assignments_total = 0  # guarded-by: _aux_lock
        # Elastic capacity dial (set_capacity_dial): bases capture the
        # CONFIGURED split — the dial scales mixed_prefill_budget and the
        # admission slot cap around them, fraction 0.5 = identity. Written
        # from the event loop (control op / planner actuator) while the
        # step thread reads the live sc knobs and the stats scrape reads
        # the gauges, so the grouped update rides _aux_lock.
        self._base_mixed_prefill_budget = self.sc.mixed_prefill_budget or self.sc.max_prefill_chunk
        self._base_max_running = self.sc.max_running
        self._elastic_fraction = 0.5  # guarded-by: _aux_lock
        self.elastic_dial_changes_total = 0  # guarded-by: _aux_lock
        self._pending_aux: list = []
        # _drain_aux runs on the step thread (overflow drain in
        # _consume_aux) AND the event loop (metrics()/moe_* properties via
        # the stats scrape): the swap-and-accumulate must not interleave.
        self._aux_lock = threading.Lock()
        # llama-only kwargs (MLA's forward has its own signature).
        stats_kw = {"moe_stats": True} if self._moe_stats else {}
        if self._use_flash_prefill:
            self._prefill_jit = jax.jit(
                lambda p, k, v, t, vl, cl, bt, hp: model.prefill(
                    p, self.mc, k, v, t, vl, cl, bt, use_flash=True, has_prefix=hp,
                    **stats_kw,
                ),
                donate_argnums=(1, 2),
                static_argnums=(7,),
            )
        else:
            # ``hp`` rides as a TRACED (unused) arg here: the XLA path's
            # masks cover prefix and fresh prefills alike, and a static arg
            # would compile two byte-identical executables per bucket.
            self._prefill_jit = jax.jit(
                lambda p, k, v, t, vl, cl, bt, hp: model.prefill(
                    p, self.mc, k, v, t, vl, cl, bt, **stats_kw
                ),
                donate_argnums=(1, 2),
            )
        # tokens/positions/active ride ONE packed [3, bucket] i32 upload and
        # split in-jit — three small per-step H2D transfers collapsed into
        # one (each costs ~0.1 ms of dispatch on tunneled devices).
        self._decode_jit = jax.jit(
            lambda p, k, v, tpa, bt: model.decode(
                p, self.mc, k, v, tpa[0], tpa[1], bt, tpa[2].astype(bool), **stats_kw
            ),
            donate_argnums=(1, 2),
        )
        self._sample_jit = jax.jit(sample_batch)
        # Logprobs folded into the sampling dispatch (one executable, one
        # readback) — the separate compute_logprobs op cost an extra device
        # round-trip per step for any batch with a logprobs row.
        from dynamo_tpu.engine.sampling import (
            guided_sample_batch_logprobs,
            guided_sample_batch_top_logprobs,
            sample_batch_logprobs,
            sample_batch_top_logprobs,
        )

        self._sample_lp_jit = jax.jit(sample_batch_logprobs)
        self._guided_sample_lp_jit = jax.jit(guided_sample_batch_logprobs)
        # Top-k variants (OpenAI top_logprobs): chosen logprob + the static
        # candidate cap's (ids, logprobs) in the same dispatch.
        self._sample_tlp_jit = jax.jit(sample_batch_top_logprobs)
        self._guided_sample_tlp_jit = jax.jit(guided_sample_batch_top_logprobs)
        # Zero-bubble overlapped decode (llama.decode_sample): fused
        # decode+sample+state-advance, device-side token feedback. _pipe
        # holds the in-flight step (see _overlap_step); _tables_cache keeps
        # the last decode block-table upload so tables cross the wire only
        # when a table actually changes.
        self._supports_overlap = hasattr(model, "decode_sample")
        if self._supports_overlap:
            self._decode_sample_jit = jax.jit(
                lambda p, k, v, tpa, bt, te, tk, tp, key: model.decode_sample(
                    p, self.mc, k, v, tpa, bt, te, tk, tp, key, **stats_kw
                ),
                donate_argnums=(1, 2),
            )
        self._pipe: Optional[dict] = None
        self._tables_cache: Optional[tuple] = None
        self._last_decode_dispatch_t: Optional[float] = None
        self.overlap_steps_total = 0
        self.overlap_flushes_total = 0
        # Deferred-retirement KV rollback: zero the slot the speculative
        # in-flight step wrote for a row that turned out finished (one
        # donated in-place scatter — a bare .at[].set would copy the cache).
        from dynamo_tpu.engine.kv_cache import QuantKv

        def _zero_slot(c, b, o):
            if isinstance(c, QuantKv):
                return QuantKv(c.q.at[:, b, o].set(0), c.scale.at[:, b, o].set(0))
            return c.at[:, b, o].set(jnp.zeros((), c.dtype))

        self._kv_zero_jit = jax.jit(
            lambda k, v, b, o: (_zero_slot(k, b, o), _zero_slot(v, b, o)),
            donate_argnums=(0, 1),
        )
        # Prefix-cache copy-on-write: duplicate one block's contents into a
        # private block (full-cover hits recompute only the LAST prompt
        # token, whose KV write would otherwise land in a block other
        # sequences still reference). Donated in-place scatter, one
        # executable for every (src, dst) pair; warmed against scratch.

        def _copy_block_arr(c, src, dst):
            if isinstance(c, QuantKv):
                return QuantKv(c.q.at[:, dst].set(c.q[:, src]), c.scale.at[:, dst].set(c.scale[:, src]))
            return c.at[:, dst].set(c[:, src])

        self._kv_copy_jit = jax.jit(
            lambda k, v, s, d: (_copy_block_arr(k, s, d), _copy_block_arr(v, s, d)),
            donate_argnums=(0, 1),
        )
        # Prefix-cache accounting: reuse is only "automatic" if it is
        # visible — cached_tokens flows request-level (StepOutput → usage)
        # and these totals flow through stats → aggregator → Grafana.
        self.cached_tokens_total = 0
        self.cow_blocks_total = 0
        self.prefix_onboard_total = 0
        # First-token latency decomposition (bench http-sweep breakdown):
        # queue (arrival→admission) and prefill (admission→first token)
        # sums over finished first tokens.
        self.queue_wait_s_total = 0.0
        self.prefill_wait_s_total = 0.0
        self.first_tokens_total = 0
        # Guided decoding (attach_guided): grammar compiler + device mask
        # pool. One fused mask+sample executable serves every guided batch.
        self.guided = None
        self._guided_sample_jit = jax.jit(guided_sample_batch)
        self.dtype = dtype
        self._mm_jit = None  # lazy: multimodal prefill variant
        # Speculative decoding (attach_draft): draft model + stats.
        self.draft_params = None
        self.draft_cfg = None
        self.draft_cache = None
        self.spec_gamma = 0
        self.spec_stats = None
        self._use_fused_spec = False
        self._spec_rounds = 0
        self._supports_multi_step = hasattr(model, "decode_multi")
        # Batched admission (chunk_decode waves) — llama-family only.
        self._supports_chunk_admit = hasattr(model, "chunk_decode")
        self._admit_jits: Dict = {}
        # Mixed prefill+decode steps (llama.mixed_step) — llama-family only.
        self._supports_mixed = hasattr(model, "mixed_step")
        self._mixed_jits: Dict = {}
        self.mixed_steps_total = 0
        self.mixed_prefill_tokens_total = 0
        self.mixed_decode_tokens_total = 0
        if self._supports_multi_step:
            # One executable per window rung: short requests must not pay a
            # full num_scheduler_steps window (a 16-token request under a
            # 32-step window wastes half the dispatch). _decode_multi picks
            # the smallest rung covering the batch's remaining budget.
            def mk_multi(steps: int):
                return jax.jit(
                    lambda p, k, v, t, pos, bt, act, te, tk, tp, key: model.decode_multi(
                        p, self.mc, k, v, t, pos, bt, act, te, tk, tp, key,
                        steps, **stats_kw,
                    ),
                    donate_argnums=(1, 2),
                )

            self._window_rungs = sorted(
                {w for w in (8, 16, self.sc.num_scheduler_steps) if w <= self.sc.num_scheduler_steps}
            )
            self._decode_multi_jits = {w: mk_multi(w) for w in self._window_rungs}
        # Fused megakernel decode window (llama.decode_multi_fused): a whole
        # greedy N-step window in ONE pallas launch — embedding, layers,
        # paged attention, lm_head, argmax, and KV writes inside one grid
        # with on-chip token feedback. Dense bf16/f32 llama only (no MoE /
        # int8 weights / quantized KV), and only where the working set fits
        # VMEM (fused_window_fits); everything else keeps decode_multi,
        # whose per-step attention still runs the ragged megakernel.
        self._use_fused_window = False
        if (
            self._supports_multi_step
            and self.sc.num_scheduler_steps > 1
            and hasattr(model, "decode_multi_fused")
            and self._attn_impl == "megakernel"
            and model_config.num_experts == 0
            and model_config.weight_dtype != "int8"
            and model_config.kv_cache_dtype != "int8"
        ):
            from dynamo_tpu.engine.attention.megakernel import fused_window_fits

            self._use_fused_window = fused_window_fits(
                self._param_bytes, self._kv_cache_bytes
            )
        if self._use_fused_window:
            # Three executables per rung, keyed (steps, sampled, guided):
            # greedy (byte-compatible with the PR-7 window), sampled (host-
            # precomputed [steps, bucket] uniforms + packed params drive the
            # in-kernel top-k/top-p epilogue), and guided (FSM mask + next-
            # state pools ride along; guided always uses the sampled
            # epilogue — greedy rows reduce to argmax through their one-hot
            # distributions, so one executable covers mixed batches).
            def mk_fused(steps: int, sampled: bool, guided: bool):
                if not sampled and not guided:
                    return jax.jit(
                        lambda p, k, v, t, pos, bt, act: model.decode_multi_fused(
                            p, self.mc, k, v, t, pos, bt, act, steps
                        ),
                        donate_argnums=(1, 2),
                    )
                if not guided:
                    return jax.jit(
                        lambda p, k, v, t, pos, bt, act, te, tk, tp, u: (
                            model.decode_multi_fused(
                                p, self.mc, k, v, t, pos, bt, act, steps,
                                temps=te, top_ks=tk, top_ps=tp, uniforms=u,
                                sampled=True,
                            )
                        ),
                        donate_argnums=(1, 2),
                    )
                return jax.jit(
                    lambda p, k, v, t, pos, bt, act, te, tk, tp, u, rows, mp, xp: (
                        model.decode_multi_fused(
                            p, self.mc, k, v, t, pos, bt, act, steps,
                            temps=te, top_ks=tk, top_ps=tp, uniforms=u,
                            guided_rows=rows, mask_pool=mp, next_pool=xp,
                            sampled=True, guided=True,
                        )
                    ),
                    donate_argnums=(1, 2),
                )

            self._decode_fused_jits = {
                (w, s, g): mk_fused(w, s, g)
                for w in self._window_rungs
                for (s, g) in ((False, False), (True, False), (True, True))
            }

    def attach_draft(self, draft_config: ModelConfig, draft_params, *, gamma: int = 4) -> None:
        """Enable batched speculative decoding: the draft model proposes γ
        tokens per round and the target verifies them in one chunk pass
        (llama.chunk_decode). The draft's paged cache mirrors the target's
        block tables, so allocation/preemption/prefix logic is shared.
        Ref: the reference surfaces engine speculation via SpecDecodeStats
        (_core.pyi:354-427); here the machinery is native."""
        from dynamo_tpu.engine.spec_decode import SpecDecodeStats

        if draft_config.block_size != self.mc.block_size:
            raise ValueError("draft and target must share block_size")
        if draft_config.vocab_size != self.mc.vocab_size:
            raise ValueError("draft and target must share the vocabulary")
        if draft_config.architecture != "llama" or self.mc.architecture != "llama":
            raise ValueError("spec decode needs llama-family draft AND target for now")
        if self.mesh is not None:
            # Sharded serving: the draft rides the target's mesh — same
            # partition specs, so GSPMD propagates the tp all-reduces / dp
            # splits through the draft's jitted steps too.
            from jax.sharding import NamedSharding

            from dynamo_tpu.engine.sharding import kv_cache_spec, shard_params

            tp = self.parallel.tp if self.parallel is not None else self.mesh.shape.get("tp", 1)
            if tp > 1 and draft_config.num_kv_heads % tp:
                raise ValueError(
                    f"draft kv_heads {draft_config.num_kv_heads} not divisible by tp={tp}"
                )
            draft_params = shard_params(
                draft_params, self.mesh, draft_config.tie_word_embeddings, draft_config.num_experts
            )
            d_sharding = NamedSharding(self.mesh, kv_cache_spec(draft_config.num_kv_heads, tp))
            self.draft_cache = KvCacheArrays.create(
                draft_config, self.sc.num_blocks, dtype=self.dtype, sharding=d_sharding
            )
        else:
            self.draft_cache = KvCacheArrays.create(draft_config, self.sc.num_blocks, dtype=self.dtype)
        self.draft_cfg = draft_config
        self.draft_params = draft_params
        self.spec_gamma = gamma
        self.spec_stats = SpecDecodeStats()
        dc = draft_config
        self._d_prefill_jit = jax.jit(
            lambda p, k, v, t, vl, cl, bt: llama.prefill(p, dc, k, v, t, vl, cl, bt),
            donate_argnums=(1, 2),
        )

        def d_chunk_sample(p, k, v, t, pos, val, bt, te, tk, tp, key):
            # Draft catch-up chunk + FIRST proposal sampled from the row's
            # last valid position with its own sampling params (greedy rows
            # reduce to argmax). Returns the dist too — spec_verify needs it.
            lg, k, v = llama.chunk_decode(p, dc, k, v, t, pos, val, bt, all_logits=True)
            last = jnp.take_along_axis(
                lg, jnp.maximum(val - 1, 0)[:, None, None], axis=1
            )[:, 0]  # [B, V]
            tok = sample_batch(last, te, tk, tp, key)
            return tok.astype(jnp.int32), last, k, v

        self._d_chunk_sample_jit = jax.jit(d_chunk_sample, donate_argnums=(1, 2))
        t_stats_kw = {"moe_stats": True} if self._moe_stats else {}
        self._t_chunk_jit = jax.jit(
            lambda p, k, v, t, pos, val, bt: llama.chunk_decode(
                p, self.mc, k, v, t, pos, val, bt, all_logits=True, **t_stats_kw
            ),
            donate_argnums=(1, 2),
        )
        from dynamo_tpu.engine.spec_decode import spec_verify

        self._spec_verify_jit = jax.jit(spec_verify)
        if gamma > 1:
            # On-device window for proposals 2..γ: one dispatch + one sync
            # instead of γ-1 round-trips; samples with the rows' REAL
            # params and returns per-step logits for rejection sampling.
            self._d_multi_jit = jax.jit(
                lambda p, k, v, t, pos, bt, act, te, tk, tp, key: llama.decode_multi(
                    p, dc, k, v, t, pos, bt, act, te, tk, tp, key, gamma - 1,
                    return_logits=True,
                ),
                donate_argnums=(1, 2),
            )
        # Fused speculative window: R whole draft+verify rounds in ONE
        # pallas launch (megakernel.fused_spec_window) — both models'
        # weights and caches VMEM-resident, accepted bursts advancing the
        # on-chip cursors. Gated like the fused decode window, but over the
        # COMBINED working set; degraded gracefully to the per-round spec
        # path above when it doesn't fit.
        self._use_fused_spec = False
        if (
            self._use_fused_window
            and hasattr(llama, "decode_spec_fused")
            and draft_config.num_experts == 0
            and draft_config.weight_dtype != "int8"
            and draft_config.kv_cache_dtype != "int8"
        ):
            from dynamo_tpu.engine.attention.megakernel import fused_window_fits

            d_leaves = jax.tree_util.tree_leaves(draft_params)
            d_param_bytes = sum(int(x.size) * x.dtype.itemsize for x in d_leaves)
            d_kv_leaves = jax.tree_util.tree_leaves(
                (self.draft_cache.k, self.draft_cache.v)
            )
            d_kv_bytes = sum(int(x.size) * x.dtype.itemsize for x in d_kv_leaves)
            self._use_fused_spec = fused_window_fits(
                self._param_bytes + d_param_bytes,
                self._kv_cache_bytes + d_kv_bytes,
            )
        if self._use_fused_spec:
            # Window length in ROUNDS: each round nets 1..γ+1 tokens, so
            # num_scheduler_steps/(γ+1) rounds keeps the fused-spec window's
            # worst-case token span equal to the plain fused window's.
            self._spec_rounds = max(1, self.sc.num_scheduler_steps // (gamma + 1))
            rounds = self._spec_rounds
            self._spec_fused_jit = jax.jit(
                lambda p, dp, kt, vt, kd, vd, t, xp, pos, bt, act, te, tk, tp, u: (
                    llama.decode_spec_fused(
                        p, self.mc, dp, dc, kt, vt, kd, vd, t, xp, pos,
                        bt, bt, act, te, tk, tp, u,
                        rounds=rounds, gamma=gamma,
                    )
                ),
                donate_argnums=(2, 3, 4, 5),
            )

    def attach_guided(self, tokenizer) -> None:
        """Enable grammar-constrained decoding: grammars lift to token FSMs
        against this tokenizer's vocabulary (llm/guided). Attach BEFORE
        warmup() so the masked-sampling executables precompile at the
        initial pool bucket."""
        from dynamo_tpu.llm.guided.processor import GuidedDecoder

        self.guided = GuidedDecoder(
            tokenizer,
            eos_ids=self._eos,
            vocab_size=self.mc.vocab_size,
            pool_rows=self.sc.guided_pool_rows,
        )

    def _fused_guided_ok(self) -> bool:
        """Guided rows may ride the fused window only while BOTH device
        pools (packed allow bitmasks + the [P, V] i32 next-row table the
        on-chip FSM advance reads) still fit the VMEM window budget
        alongside the weights — pool growth re-checks every window, so a
        grammar working set outgrowing VMEM degrades row-wise to the host
        FSM path instead of mis-launching."""
        if not self._use_fused_window or self.guided is None:
            return False
        from dynamo_tpu.engine.attention.megakernel import fused_window_fits

        pool = self.guided.pool
        pool_bytes = pool.capacity * pool.words * 4 + pool.next_pool_bytes()
        return fused_window_fits(
            self._param_bytes, self._kv_cache_bytes + pool_bytes
        )

    # --- public API (called from event loop) --------------------------------
    def add_request(
        self,
        request_id: str,
        token_ids: List[int],
        sampling: SamplingParams,
        stop: StopConditions,
        *,
        keep_blocks_on_finish: bool = False,
        prefilled: Optional[dict] = None,
        mm_features: Optional[np.ndarray] = None,
        trace: Optional[tuple] = None,
        guided: Optional[dict] = None,
        tenant: str = "anon",
    ) -> Sequence:
        if not token_ids:
            raise ValueError("empty prompt")
        if guided is not None and self.guided is None:
            raise ValueError(
                "guided decoding requested but no tokenizer is attached "
                "(Scheduler.attach_guided / EngineArgs.tokenizer)"
            )
        if len(token_ids) >= self.mc.max_seq_len:
            raise ValueError(f"prompt length {len(token_ids)} >= max_seq_len {self.mc.max_seq_len}")
        if mm_features is not None:
            if self.mc.architecture != "llama":
                raise ValueError("multimodal features require the llama prefill path")
            if mm_features.shape[0] > len(token_ids):
                raise ValueError("more multimodal feature rows than prompt tokens")
        seq = Sequence(
            request_id=request_id,
            prompt=list(token_ids),
            sampling=sampling,
            stop=stop,
            eos_token_ids=self._eos,
            keep_blocks_on_finish=keep_blocks_on_finish,
            prefilled=prefilled,
            mm_features=mm_features,
            trace=trace,
            tenant=tenant or "anon",
        )
        if guided is not None:
            seq.guided = self.guided.open(guided)  # ValueError on a bad spec
        if stop.deadline_ms is not None:
            seq.deadline_ts = seq.arrival_ts + stop.deadline_ms / 1000.0
            self._has_deadlines = True
        self.waiting.append(seq)
        self.by_id[request_id] = seq
        self.request_total += 1
        self._trace_event(seq, "queued", prompt_tokens=len(token_ids))
        if seq.guided is not None:
            self._trace_event(
                seq, "guided_mask",
                states=seq.guided.fsm.num_states,
                compile_s=round(seq.guided.fsm.compile_s, 6),
                cached=seq.guided.from_cache,
            )
        return seq

    def abort(self, request_id: str) -> None:
        seq = self.by_id.get(request_id)
        if seq is not None and seq.state != SeqState.FINISHED:
            seq.aborted = True

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    @property
    def moe_dropped_total(self) -> int:
        """Capacity-MoE drop counter, drained-on-read: jitted steps stage
        their aux scalars in ``_pending_aux`` (forcing them per step would
        add a host sync — see _consume_aux), so a direct read must drain
        first or it sees counters up to 256 steps stale."""
        self._drain_aux()
        return self._moe_dropped_total

    @property
    def moe_assignments_total(self) -> int:
        self._drain_aux()
        return self._moe_assignments_total

    def metrics(self) -> ForwardPassMetrics:
        a = self.allocator
        self._drain_aux()
        return ForwardPassMetrics(
            num_running=len(self.running),
            num_waiting=len(self.waiting),
            kv_usage=a.usage(),
            kv_total_blocks=a.num_blocks,
            kv_active_blocks=a.num_active,
            prefill_tokens_in_flight=sum(len(s.prompt) - s.num_computed for s in self.waiting),
            request_total=self.request_total,
            spec_decode=self.spec_stats.to_dict() if self.spec_stats else None,
            moe_dropped_total=self._moe_dropped_total,
            moe_assignments_total=self._moe_assignments_total,
            mixed_steps_total=self.mixed_steps_total,
            mixed_prefill_tokens_total=self.mixed_prefill_tokens_total,
            mixed_decode_tokens_total=self.mixed_decode_tokens_total,
            overlap_steps_total=self.overlap_steps_total,
            overlap_flushes_total=self.overlap_flushes_total,
            cached_tokens_total=self.cached_tokens_total,
            prefix_hit_blocks_total=a.hit_blocks_total,
            prefix_miss_blocks_total=a.miss_blocks_total,
            prefix_evicted_blocks_total=a.evicted_blocks_total,
            prefix_onboard_total=self.prefix_onboard_total,
            elastic_prefill_fraction=self._elastic_fraction,
            elastic_prefill_budget=self.sc.mixed_prefill_budget or 0,
            elastic_decode_slots=self.sc.max_running,
            elastic_dial_changes_total=self.elastic_dial_changes_total,
        )

    def kv_gauges(self) -> dict:
        """Block-pool utilization for the stats scrape: free/cached depth,
        internal fragmentation (allocated-but-unwritten slots across live
        sequences — the padding cost of block-granular allocation), and the
        prefix-cache hit rate."""
        a = self.allocator
        bs = self.mc.block_size
        allocated = 0
        used = 0
        for s in list(self.running) + list(self.waiting):
            nb = len(s.block_ids)
            if not nb:
                continue
            allocated += nb * bs
            used += min(s.total_len, nb * bs)
        hits, misses = a.hit_blocks_total, a.miss_blocks_total
        return {
            "kv_free_blocks": len(a._free),
            "kv_cached_blocks": a.num_cached,
            "kv_fragmentation": round(1.0 - used / allocated, 6) if allocated else 0.0,
            "prefix_hit_rate": round(hits / (hits + misses), 6) if (hits + misses) else 0.0,
        }

    def debug_state(self) -> dict:
        """Live introspection snapshot for /debug/state: every sequence with
        its age/progress, the block pool, digest percentiles, and the recent
        step timeline. Read from the event loop while the step thread
        mutates — last-write-wins races are fine for a debug dump."""
        now = time.monotonic()

        def seq_info(s: Sequence) -> dict:
            return {
                "request_id": s.request_id,
                "state": s.state.value,
                "age_s": round(now - s.arrival_ts, 3),
                "prompt_tokens": len(s.prompt),
                "output_tokens": len(s.output_ids),
                "computed": s.num_computed,
                "cached_tokens": s.cached_tokens,
                "blocks": len(s.block_ids),
                "preemptions": s.preemptions,
            }

        a = self.allocator
        f = self.flight
        return {
            "running": [seq_info(s) for s in list(self.running)],
            "waiting": [seq_info(s) for s in list(self.waiting)],
            "block_pool": {
                "total": a.num_blocks,
                "free": len(a._free),
                "cached": a.num_cached,
                "active": a.num_active,
                "usage": round(a.usage(), 6),
                **{k: v for k, v in self.kv_gauges().items() if k == "kv_fragmentation"},
            },
            "digests": self.telemetry.summary(),
            "slo": self.slo.to_stats(),
            "flight": {
                "last_step_phase": f.last_step_phase,
                "last_step_s": round(f.last_step_s, 6),
                "last_step_age_s": (
                    round(now - f.last_step_ts, 3) if f.last_step_ts is not None else None
                ),
                "compiles_total": f.compiles_total,
                "compiles_after_warmup_total": f.compiles_after_warmup_total,
                "post_warmup_keys": [str(k) for k in f.post_warmup_keys[-8:]],
                "recent_steps": [
                    {"age_s": round(now - ts, 3), "phase": ph, "dur_s": d, "tokens": t}
                    for ts, ph, d, t in list(f.recent_steps)
                ],
                "utilization": {
                    ph: {"mfu": round(m, 6), "hbm_frac": round(h, 6)}
                    for ph, (m, h) in f.utilization().items()
                },
            },
        }

    def config_snapshot(self) -> dict:
        """Deployment configuration for incident bundles: the scheduler
        knobs and the model/attention identity that reproduce the serving
        behavior under diagnosis (a bundle without its config is a mystery
        six months later)."""
        return {
            "scheduler": {
                k: v for k, v in vars(self.sc).items() if not k.startswith("_")
            },
            "model": {
                "name": self.mc.name,
                "architecture": self.mc.architecture,
                "max_seq_len": self.mc.max_seq_len,
                "block_size": self.mc.block_size,
                "kv_cache_dtype": getattr(self.mc, "kv_cache_dtype", None),
                "weight_dtype": getattr(self.mc, "weight_dtype", None),
                "attention_impl": self._attn_impl,
            },
            "parallel": str(self.parallel) if self.parallel is not None else None,
        }

    # --- elastic capacity dial ----------------------------------------------
    def set_capacity_dial(self, prefill_fraction: float) -> dict:
        """Live prefill:decode capacity split — the worker half of elastic
        prefill/decode (ROADMAP item 2; DynaServe arXiv:2504.09285 argues
        the same continuous-ratio pool). ``prefill_fraction`` ∈ [0, 1]:

        - 0.5 — the configured identity (mixed_prefill_budget / max_running
          exactly as constructed);
        - → 1.0 — prefill-heavy: the mixed-step chunk budget scales up to
          2× (clamped to max_prefill_chunk) while decode admission slots
          shrink toward 1;
        - → 0.0 — decode-heavy: admission slots stay at the configured cap
          while the chunk budget shrinks toward one block.

        Slots never exceed the configured max_running (the allocator and
        decode buckets are sized for it), and already-admitted rows past a
        shrunken cap drain naturally (_decode_step slices by decode bucket,
        not max_running). Thread-safe: called from the event loop (control
        op / planner actuator) while the step thread reads the knobs — the
        grouped update rides _aux_lock so a stats scrape never observes a
        half-applied dial. Returns the applied values."""
        f = min(1.0, max(0.0, float(prefill_fraction)))
        raw = int(round(2.0 * f * self._base_mixed_prefill_budget))
        budget = max(self.mc.block_size, min(raw, self.sc.max_prefill_chunk))
        slots = int(round(2.0 * (1.0 - f) * self._base_max_running))
        slots = max(1, min(self._base_max_running, slots))
        with self._aux_lock:
            self._elastic_fraction = f
            self.sc.mixed_prefill_budget = budget
            self.sc.max_running = slots
            self.elastic_dial_changes_total += 1
        logger.info(
            "capacity dial: prefill_fraction=%.3f → mixed_prefill_budget=%d decode_slots=%d",
            f, budget, slots,
        )
        return {
            "prefill_fraction": f,
            "mixed_prefill_budget": budget,
            "decode_slots": slots,
        }

    def _mixed_warm_buckets(self) -> List[int]:
        """Prefill-chunk buckets a mixed step can ride across the capacity
        dial's whole range: raw budgets span [block_size, min(2·base,
        max_prefill_chunk)] and chunks bucket UP (next_bucket), so warmup
        must cover every bucket between those bounds — a ratio shift must
        never compile mid-traffic (WARM001 / flight-recorder gate)."""
        eligible = [b for b in self.sc.prefill_buckets if b <= self.sc.max_prefill_chunk]
        if not eligible:
            eligible = [self.sc.prefill_buckets[0]]
        lo = next_bucket(max(self.mc.block_size, 1), eligible)
        hi = next_bucket(
            min(2 * self._base_mixed_prefill_budget, self.sc.max_prefill_chunk), eligible
        )
        return [b for b in eligible if lo <= b <= hi] or [eligible[0]]

    # --- step loop core (runs in worker thread) -----------------------------
    def step(self) -> List[tuple]:
        """One scheduler iteration. Returns [(seq, StepOutput), ...].

        With sequences decoding AND prefill work at the head of the queue,
        the iteration is a MIXED step: one dispatch carries the decode
        batch plus up to mixed_prefill_budget prefill tokens, so neither
        phase stalls the other. Otherwise the phase-separated order runs:
        decode first (ITL), then admit one prefill (TTFT).

        With an overlapped decode pipeline in flight (``_pipe``), the
        iteration instead dispatches step N+1 from the previous step's
        on-device sampled tokens and retires step N while the device runs —
        unless a composition change (waiting work, aborts, block growth,
        finish) forces a flush back to this sync path."""
        outputs: List[tuple] = []
        # Deadline sweep runs before the overlap fast path too: an expired
        # row marks itself aborted, which forces the pipeline flush below
        # (otherwise a pure-decode window could outlive the deadline).
        self._sweep_deadlines()
        if self._pipe is not None:
            if self._overlap_should_continue():
                self._overlap_step(outputs)
                return outputs
            self._overlap_flush(outputs)
        self._reap_aborted(outputs)
        cand = self._mixed_candidate()
        if cand is not None and not self._wave_preferred() and self._mixed_step(cand, outputs):
            return outputs
        if self.running:
            outputs.extend(self._decode_step())
        self._admit(outputs)
        return outputs

    def _mixed_candidate(self) -> Optional[Sequence]:
        """Head-of-queue sequence eligible to ride a mixed step, or None.
        Only the head is considered (FIFO — jumping an ineligible head
        would starve it); ineligible heads (remote-prefilled injection,
        multimodal, non-llama, draft-attached engines) fall back to the
        phase-separated path, as does a full decode set when the head has
        not been admitted yet."""
        if not (
            self.sc.enable_mixed_batching
            and self._supports_mixed
            and self.draft_params is None
            and self.running
            and self.waiting
        ):
            return None
        head = self.waiting[0]
        if head.aborted or head.prefilled is not None or head.mm_features is not None:
            return None
        if head.state == SeqState.WAITING and len(self.running) >= self.sc.max_running:
            return None
        return head

    def _wave_preferred(self) -> bool:
        """Prefer batched wave admission over a mixed step when ≥2 short
        wave-eligible prompts wait AND the head's chunk fits the mixed
        budget anyway — the wave admits them all in one dispatch with a
        stall no worse than the chunk a mixed step would carry. Long-prompt
        heads always take the mixed path: a wave would dispatch the whole
        prompt in one stall, which is exactly the regression mixed steps
        exist to kill."""
        if not self._supports_chunk_admit or self.draft_params is not None:
            return False
        if self.sc.itl_budget_ms and self.running:
            return False  # _admit_wave refuses under an ITL budget too
        cap = min(self._wave_s_cap(), self.sc.mixed_prefill_budget or self._wave_s_cap())
        room = self.sc.max_running - len(self.running)
        if room < 2:
            return False
        head = self.waiting[0]
        if not (self._wave_eligible(head) and len(head.prompt) <= cap):
            return False
        n = sum(
            1 for seq in self.waiting[: self.sc.decode_buckets[-1]]
            if self._wave_eligible(seq) and len(seq.prompt) <= cap
        )
        return n >= 2

    def _get_mixed_jit(self, key):
        """Mixed-step executable for (s_bucket, p_width, d_bucket, d_width)
        — shared by _mixed_step and warmup so both compile the same thing.
        ``hp`` follows the prefill convention: static on the flash path
        (the kernel skips the prefix piece), traced no-op on XLA."""
        if key not in self._mixed_jits:
            from dynamo_tpu.engine.models import get_module

            model = get_module(self.mc)
            stats_kw = {"moe_stats": True} if self._moe_stats else {}
            if self._use_flash_prefill:
                self._mixed_jits[key] = jax.jit(
                    lambda p, k, v, pt, pv, cl, ptab, dt, dpos, dtab, dact, hp: model.mixed_step(
                        p, self.mc, k, v, pt, pv, cl, ptab, dt, dpos, dtab, dact,
                        use_flash=True, has_prefix=hp, **stats_kw,
                    ),
                    donate_argnums=(1, 2),
                    static_argnums=(11,),
                )
            else:
                self._mixed_jits[key] = jax.jit(
                    lambda p, k, v, pt, pv, cl, ptab, dt, dpos, dtab, dact, hp: model.mixed_step(
                        p, self.mc, k, v, pt, pv, cl, ptab, dt, dpos, dtab, dact,
                        **stats_kw,
                    ),
                    donate_argnums=(1, 2),
                )
        return self._mixed_jits[key]

    def _mixed_step(self, seq: Sequence, outputs: List[tuple]) -> bool:
        """One mixed iteration: the full decode batch plus ``seq``'s next
        prefill chunk in ONE dispatch. Returns False (caller falls back to
        the phase-separated path) when the chunk's blocks can't be
        allocated. Preemption resumes ride too — their chunk recomputes KV
        and samples nothing at the end."""
        resuming = seq.resume_tokens is not None
        pf_tokens = seq.resume_tokens if resuming else seq.prompt
        if seq.state == SeqState.WAITING:
            total_tokens = (seq.total_len if resuming else len(seq.prompt)) + 1
            try:
                self._first_touch(seq, pf_tokens, total_tokens)
            except OutOfBlocksError:
                return False
        if seq.num_computed >= len(pf_tokens):
            # Prefix-cache hit covered the whole chunkable range already —
            # nothing to compute this step; let _prefill_one finish it.
            return False

        remaining = len(pf_tokens) - seq.num_computed
        budget = self._chunk_budget()
        if self.sc.mixed_prefill_budget:
            budget = min(budget, self.sc.mixed_prefill_budget)
        chunk = min(remaining, budget)
        s_bucket = next_bucket(chunk, self.sc.prefill_buckets)
        chunk = min(chunk, s_bucket)
        chunk_tokens = pf_tokens[seq.num_computed : seq.num_computed + chunk]
        p_tok = np.zeros((s_bucket,), dtype=np.int32)
        p_tok[: len(chunk_tokens)] = chunk_tokens
        p_table = self._prefill_table(seq)
        has_prefix = seq.num_computed > 0

        # Decode batch formation — identical to _decode_step (see there for
        # why max_running is NOT a term: dial shrinks must not strand rows).
        n = min(len(self.running), self.sc.decode_buckets[-1])
        batch = self.running[:n]
        d_bucket = next_bucket(n, self.sc.decode_buckets)
        width = self._width_bucket(max(len(s.block_ids) for s in batch))
        tokens = np.zeros((d_bucket,), dtype=np.int32)
        positions = np.zeros((d_bucket,), dtype=np.int32)
        active = np.zeros((d_bucket,), dtype=bool)
        for i, s in enumerate(batch):
            tokens[i] = s.all_ids[-1]
            positions[i] = s.total_len - 1
            active[i] = True
        tables = self._decode_tables(batch, d_bucket, width)

        mixed_key = (s_bucket, int(p_table.shape[0]), d_bucket, width)
        self.flight.record_exec(
            "mixed", mixed_key + ((has_prefix,) if self._use_flash_prefill else ())
        )
        self._break_decode_gap()
        with StepTimer() as timer:
            res = self._get_mixed_jit(mixed_key)(
                self.params, self.cache.k, self.cache.v,
                jnp.asarray(p_tok), jnp.int32(len(chunk_tokens)), jnp.int32(seq.num_computed),
                p_table, jnp.asarray(tokens), jnp.asarray(positions), tables,
                jnp.asarray(active), has_prefix,
            )
            logits, self.cache.k, self.cache.v = self._consume_aux(res)
            self.mixed_steps_total += 1
            self.mixed_prefill_tokens_total += len(chunk_tokens)
            self.mixed_decode_tokens_total += n

            # Decode rows first (output-order parity with the phase-separated
            # decode-then-admit iteration), then the chunk's progress.
            self._finish_decode_rows(batch, d_bucket, logits[1:], outputs)
        # Mixed-step roofline split: the chunk's FLOPs/bytes land in the
        # PREFILL bucket and the decode rows' in DECODE, so mfu_prefill /
        # hbm_frac_decode stay truthful when one fused launch serves both
        # phases (the step histogram itself stays under "mixed").
        self.flight.record_mixed_step(
            timer.dur, len(chunk_tokens), n,
            kv_read_prefill=seq.num_computed,
            kv_read_decode=sum(s.total_len for s in batch),
        )
        self._bill_step(
            timer.dur,
            [(seq, "prefill", len(chunk_tokens), seq.num_computed)]
            + [(s, "decode", 1, s.total_len) for s in batch],
        )
        self.telemetry.observe("itl", timer.dur)
        self._trace_event(
            seq, "mixed_ride", chunk_tokens=len(chunk_tokens), decode_rows=n,
            dur_s=round(timer.dur, 6),
        )

        seq.num_computed += len(chunk_tokens)
        self._register_full_blocks(seq)  # chunk's completed blocks go live
        if seq.num_computed < len(pf_tokens):
            return True  # more chunks ride later steps
        self.waiting.remove(seq)
        seq.state = SeqState.RUNNING
        self.running.append(seq)
        self._register_full_blocks(seq)
        if resuming:
            # KV restored through the last generated token; the final token
            # re-enters via decode — nothing to sample or emit.
            seq.resume_tokens = None
        else:
            token = self._sample_one(seq, logits[0])
            seq.first_token_ts = time.monotonic()
            self._append_token(seq, token, outputs)
        return True

    def _reap_aborted(self, outputs: List[tuple]) -> None:
        for seq in list(self.running):
            if seq.aborted:
                self._finish(seq, seq.abort_reason, outputs)
        for seq in list(self.waiting):
            if seq.aborted:
                self.waiting.remove(seq)
                seq.state = SeqState.FINISHED
                # Never-admitted requests still bill their queue time (and
                # any mid-prefill KV hold) — a timeout storm in the queue is
                # exactly what tenant attribution must see.
                self._emit_bill(seq, seq.abort_reason)
                # Mid-prefill cancellations already hold blocks — release them.
                self.allocator.release(seq.block_ids)
                seq.block_ids = []
                self.by_id.pop(seq.request_id, None)
                outputs.append((seq, StepOutput(token_id=-1, finished=True, finish_reason=seq.abort_reason)))

    def _sweep_deadlines(self) -> None:
        """Mark past-deadline rows aborted with reason "timeout"; the
        regular reap then frees their KV and emits the final frame. Runs at
        the head of every step (host-side, O(live rows)) but only once any
        deadline-carrying request has been admitted."""
        if not self._has_deadlines:
            return
        now = time.monotonic()
        for seq in self.running + self.waiting:
            if (
                seq.deadline_ts is not None
                and not seq.aborted
                and now >= seq.deadline_ts
            ):
                seq.aborted = True
                seq.abort_reason = "timeout"
                self.timeouts_total += 1
                self._trace_event(
                    seq, "deadline_evict",
                    overrun_ms=round((now - seq.deadline_ts) * 1000.0, 3),
                    output_tokens=len(seq.output_ids),
                )

    def _admit(self, outputs: List[tuple]) -> None:
        """Admit waiting sequences: a batched WAVE when several short
        prompts wait (one dispatch + one readback for all of them — on
        dispatch-latency-heavy links per-request prefills serialized
        admission at one ~100 ms round-trip each), else one chunked
        prefill."""
        if not self.waiting or len(self.running) >= self.sc.max_running:
            return
        # FIFO fairness: waves only form when the HEAD of the queue joins
        # them — otherwise an ineligible head (long prompt, seeded/logprobs
        # request) would starve behind an endless stream of wave-admitted
        # shorts. The head must ALSO fit the wave's chunk cap: a long-prompt
        # head is exactly the starvation case.
        head = self.waiting[0]
        if (
            self._wave_eligible(head)
            and len(head.prompt) <= self._wave_s_cap()
            and self._admit_wave(outputs)
        ):
            return
        seq = self.waiting[0]
        try:
            done = self._prefill_one(seq, outputs)
        except OutOfBlocksError:
            # Not enough KV blocks — leave in queue; decode progress will
            # free/evict blocks. (The reference's engines preempt here; we
            # backpressure instead.)
            return
        if done:
            self.waiting.pop(0)

    def _wave_s_cap(self) -> int:
        """Longest prompt a wave admission will take in one chunk."""
        return min(self.sc.max_prefill_chunk, self.sc.prefill_buckets[-1])

    def _get_admit_jit(self, key):
        """Wave-admission executable for (b_bucket, s_bucket, width) —
        shared by _admit_wave and warmup so both compile the same thing."""
        if key not in self._admit_jits:
            from dynamo_tpu.engine.models import get_module

            model = get_module(self.mc)
            self._admit_jits[key] = jax.jit(
                lambda p, k, v, t, p0, vl, bt: model.chunk_decode(
                    p, self.mc, k, v, t, p0, vl, bt, last_logits=True,
                    **({"moe_stats": True} if self._moe_stats else {}),
                ),
                donate_argnums=(1, 2),
            )
        return self._admit_jits[key]

    def _wave_eligible(self, seq: Sequence) -> bool:
        s = seq.sampling
        return (
            seq.state == SeqState.WAITING
            and seq.prefilled is None
            and seq.resume_tokens is None
            and seq.mm_features is None
            and seq.guided is None  # wave samples on device, unmasked
            and not s.logprobs
            and not s.top_logprobs
            and not s.logits_processors
            and not (s.seed is not None and s.temperature > 0)
        )

    def _admit_wave(self, outputs: List[tuple]) -> bool:
        """Prefill a wave of short waiting prompts in ONE ``chunk_decode``
        dispatch: KV for every row's whole prompt is written batched, the
        last-valid logits feed the on-device sampler, and the host reads
        back one [B] token array. Returns True when a wave was admitted.

        Falls through to the single-sequence path for prompts longer than
        one chunk, non-llama architectures, draft-attached engines (the
        draft catch-up is per-sequence), and requests needing per-token
        logprobs/processors/seeded sampling."""
        if not self._supports_chunk_admit or self.draft_params is not None:
            return False
        if self.sc.itl_budget_ms and self.running:
            # A wave dispatches B×S prompt tokens in one device call —
            # incompatible with an ITL budget while decodes run; the
            # single-prefill path enforces the budgeted chunk size.
            return False
        s_cap = self._wave_s_cap()
        room = self.sc.max_running - len(self.running)
        wave: List[Sequence] = []
        for seq in self.waiting:
            if len(wave) >= min(room, self.sc.decode_buckets[-1]):
                break
            if not self._wave_eligible(seq):
                continue
            if len(seq.prompt) > s_cap:
                continue
            wave.append(seq)
        if len(wave) < 2:
            return False

        # First touch per seq: prefix match + all-or-nothing allocation
        # (shared with _prefill_one; a seq that can't allocate ends the wave).
        admitted: List[Sequence] = []
        for seq in wave:
            try:
                self._first_touch(seq, seq.prompt, len(seq.prompt) + 1)
            except OutOfBlocksError:
                break
            admitted.append(seq)
        if len(admitted) < 2:
            # 0 or 1 allocated: hand everything back to the single-seq path
            # untouched (it re-runs first-touch matching, so blocks/refs
            # acquired here must be returned first).
            for seq in admitted:
                self.allocator.release(seq.block_ids)
                self.cached_tokens_total -= seq.cached_tokens
                seq.block_ids = []
                seq.num_cached_blocks = 0
                seq.num_computed = 0
                seq.cached_tokens = 0
                seq.kv_ts = None  # clock started at first touch; nothing held now
                seq.state = SeqState.WAITING
            return False

        s_max = max(len(seq.prompt) - seq.num_computed for seq in admitted)
        s_bucket = next_bucket(s_max, self.sc.prefill_buckets)
        b_bucket = next_bucket(len(admitted), self.sc.decode_buckets)
        width = self._width_bucket(max(len(seq.block_ids) for seq in admitted))

        from dynamo_tpu.engine.sampling import pack_param_rows

        tokens = np.zeros((b_bucket, s_bucket), dtype=np.int32)
        pos0 = np.zeros((b_bucket,), dtype=np.int32)
        valid = np.zeros((b_bucket,), dtype=np.int32)
        tables = np.zeros((b_bucket, width), dtype=np.int32)
        temps, top_ks, top_ps = pack_param_rows([s.sampling for s in admitted], b_bucket)
        for i, seq in enumerate(admitted):
            chunk = seq.prompt[seq.num_computed:]
            tokens[i, : len(chunk)] = chunk
            pos0[i] = seq.num_computed
            valid[i] = len(chunk)
            tables[i, : len(seq.block_ids)] = seq.block_ids

        self.flight.record_exec("admit", (b_bucket, s_bucket, width))
        self._break_decode_gap()
        with StepTimer() as timer:
            res = self._get_admit_jit((b_bucket, s_bucket, width))(
                self.params, self.cache.k, self.cache.v,
                jnp.asarray(tokens), jnp.asarray(pos0), jnp.asarray(valid), jnp.asarray(tables),
            )
            lg, self.cache.k, self.cache.v = self._consume_aux(res)
            self._step_counter += 1
            skey = jax.random.fold_in(self._rng, self._step_counter)
            sampled = np.asarray(
                self._sample_jit(
                    lg, jnp.asarray(temps), jnp.asarray(top_ks), jnp.asarray(top_ps), skey, None
                )
            )  # the wave's ONE host sync

            for i, seq in enumerate(admitted):
                self.waiting.remove(seq)
                seq.num_computed = len(seq.prompt)
                seq.first_token_ts = time.monotonic()
                seq.state = SeqState.RUNNING
                self.running.append(seq)
                self._register_full_blocks(seq)
                self._append_token(seq, int(sampled[i]), outputs)
        self.flight.record_step(
            "wave", timer.dur, int(valid.sum()) + len(admitted),
            kv_read_tokens=int(pos0.sum()),
        )
        self._bill_step(
            timer.dur,
            [(seq, "prefill", int(valid[i]) + 1, int(pos0[i])) for i, seq in enumerate(admitted)],
        )
        return True

    def _first_touch(self, seq: Sequence, pf_tokens: List[int], total_tokens: int) -> None:
        """First admission: prefix-cache match + full block allocation,
        all-or-nothing — a partial failure re-runs next step, so any
        acquired refs/blocks are returned before OutOfBlocksError
        propagates. Shared by single prefills and wave admission."""
        bs = self.mc.block_size
        try:
            if self.sc.enable_prefix_caching and seq.mm_features is None:
                seq.block_hashes = extend_block_hashes([], pf_tokens, bs)
                matched = self._match_prefix_tiers(seq)
                # At least one token must prefill so logits exist. A FULL
                # cover keeps every matched block and recomputes only the
                # last token — but its KV write lands inside the final
                # matched block, which other sequences may still reference:
                # copy-on-write it into a private block. A sole-held block
                # (refcount 1 = just us) is written in place instead — the
                # recomputed row is bit-identical, so no copy is needed.
                if matched and len(matched) * bs >= len(pf_tokens):
                    last = matched[-1]
                    if self.allocator.ref_count(last) > 1:
                        try:
                            (cow,) = self.allocator.allocate(1)
                        except OutOfBlocksError:
                            # No room for the private copy: degrade to
                            # recomputing the whole last block (still an
                            # n-1 block hit).
                            self.allocator.release([last])
                            matched = matched[:-1]
                        else:
                            self._copy_block(last, cow)
                            self.allocator.release([last])
                            matched[-1] = cow
                            self.cow_blocks_total += 1
                seq.block_ids = list(matched)
                seq.num_cached_blocks = len(matched)
                seq.num_computed = min(len(matched) * bs, len(pf_tokens) - 1)
                seq.cached_tokens = seq.num_computed
                self.cached_tokens_total += seq.cached_tokens
            needed = (total_tokens + bs - 1) // bs - len(seq.block_ids)
            if needed > 0:
                seq.block_ids.extend(self.allocator.allocate(needed))
        except OutOfBlocksError:
            self.allocator.release(seq.block_ids)
            self.cached_tokens_total -= seq.cached_tokens
            seq.block_ids = []
            seq.num_cached_blocks = 0
            seq.num_computed = 0
            seq.cached_tokens = 0
            seq.kv_ts = None
            raise
        # Block-seconds clock starts at first hold — prefix-cache matched
        # (COW-shared) blocks included, since the tenant pins their refcount.
        self._accrue_kv(seq)
        seq.state = SeqState.PREFILL
        if seq.admitted_ts is None:
            seq.admitted_ts = time.monotonic()
            self._trace_event(
                seq, "admitted",
                queue_s=round(seq.admitted_ts - seq.arrival_ts, 6),
                cached_blocks=seq.num_cached_blocks,
            )

    def _prefill_one(self, seq: Sequence, outputs: List[tuple]) -> bool:
        """Run one prefill chunk for ``seq``. Returns True when the prompt is
        fully computed (sequence moved to running). Preempted sequences
        resume here: ``resume_tokens`` (prompt + generated so far, minus the
        last token) recompute their KV, then decode continues — no sampling
        at the end of a resume."""
        bs = self.mc.block_size
        # Inject only on first admission: a preempted decode-role sequence
        # (resume_tokens set) must recompute, not re-inject — re-injection
        # would duplicate first_token and leave generated-token KV absent.
        if seq.state == SeqState.WAITING and seq.prefilled is not None and seq.resume_tokens is None:
            return self._inject_prefilled(seq, outputs)
        resuming = seq.resume_tokens is not None
        pf_tokens = seq.resume_tokens if resuming else seq.prompt
        if seq.state == SeqState.WAITING:
            total_tokens = (seq.total_len if resuming else len(seq.prompt)) + 1
            self._first_touch(seq, pf_tokens, total_tokens)

        remaining = len(pf_tokens) - seq.num_computed
        chunk = min(remaining, self._chunk_budget())
        bucket = next_bucket(chunk, self.sc.prefill_buckets)
        chunk = min(chunk, bucket)

        tokens = pf_tokens[seq.num_computed : seq.num_computed + chunk]
        padded = np.zeros((bucket,), dtype=np.int32)
        padded[: len(tokens)] = tokens
        table = self._prefill_table(seq)

        self._break_decode_gap()
        t0 = time.monotonic() if self.sc.itl_budget_ms else None
        with StepTimer() as timer:
            if seq.mm_features is not None:
                feats = seq.mm_features
                fb = 16
                while fb < feats.shape[0]:
                    fb *= 2
                padded_f = np.zeros((fb, feats.shape[1]), dtype=np.float32)
                padded_f[: feats.shape[0]] = feats
                self.flight.record_exec(
                    "prefill_mm", (bucket, int(table.shape[0]), fb, seq.num_computed > 0)
                )
                res = self._prefill_mm_jit()(
                    self.params, self.cache.k, self.cache.v,
                    jnp.asarray(padded), jnp.int32(len(tokens)), jnp.int32(seq.num_computed),
                    table, seq.num_computed > 0,
                    jnp.asarray(padded_f), jnp.int32(feats.shape[0]),
                )
            else:
                # Shape key mirrors warmup(): on the XLA path has_prefix is a
                # traced no-op arg (one executable serves both values).
                hp_key = (seq.num_computed > 0) if self._use_flash_prefill else False
                self.flight.record_exec("prefill", (bucket, int(table.shape[0]), hp_key))
                res = self._prefill_jit(
                    self.params,
                    self.cache.k,
                    self.cache.v,
                    jnp.asarray(padded),
                    jnp.int32(len(tokens)),
                    jnp.int32(seq.num_computed),
                    table,
                    seq.num_computed > 0,
                )
            logits, self.cache.k, self.cache.v = self._consume_aux(res)
        self.flight.record_step(
            "prefill", timer.dur, len(tokens), kv_read_tokens=seq.num_computed
        )
        self._bill_step(timer.dur, [(seq, "prefill", len(tokens), seq.num_computed)])
        self._trace_event(
            seq, "prefill_chunk", tokens=len(tokens), bucket=bucket,
            computed=seq.num_computed + len(tokens), dur_s=round(timer.dur, 6),
            resume=resuming,
        )
        if t0 is not None:
            # Sync to learn the chunk rate (feeds _chunk_budget's EMA).
            logits.block_until_ready()
            dt = max(time.monotonic() - t0, 1e-6)
            rate = len(tokens) / dt
            self._prefill_tok_s = rate if self._prefill_tok_s is None else (
                0.7 * self._prefill_tok_s + 0.3 * rate
            )
        seq.num_computed += len(tokens)
        self._register_full_blocks(seq)  # chunk's completed blocks go live
        self._draft_catchup_prefill(seq, pf_tokens)

        if seq.num_computed < len(pf_tokens):
            return False  # more chunks to go

        if resuming:
            # KV restored through the last generated token; the final token
            # re-enters via the decode step — nothing to sample or emit.
            seq.resume_tokens = None
            seq.state = SeqState.RUNNING
            self.running.append(seq)
            self._register_full_blocks(seq)
            self._trace_event(seq, "resume", total_len=seq.total_len)
            return True

        # Prompt fully computed: sample the first token.
        token = self._sample_one(seq, logits)
        seq.first_token_ts = time.monotonic()
        seq.state = SeqState.RUNNING
        self.running.append(seq)
        self._register_full_blocks(seq)
        self._append_token(seq, token, outputs)
        return True

    def _chunk_budget(self) -> int:
        """Max prefill-chunk tokens for this iteration. With an ITL budget
        and live decodes, cap the chunk so its estimated device time stays
        within budget (never below the smallest bucket — progress must be
        made)."""
        cap = self.sc.max_prefill_chunk
        if not self.sc.itl_budget_ms or not self.running or self._prefill_tok_s is None:
            return cap
        budget_tokens = int(self.sc.itl_budget_ms / 1000.0 * self._prefill_tok_s)
        return max(min(cap, budget_tokens), self.sc.prefill_buckets[0])

    def _width_bucket(self, max_used: int) -> int:
        """Block-table width buckets at pow2 AND 1.5·pow2 rungs
        (4, 6, 8, 12, 16, 24, ...). Pure pow2 pays up to 2× gather padding
        right past a boundary — at 256-token pages a 1025-token context
        would gather 2048 tokens; the 1.5 rungs cap the waste at 33% for
        2·log2(max_blocks) executable variants, still few enough for
        warmup() to precompile. (History: multiples of 16 produced
        max_seq/256 variants that compiled mid-traffic — the then-dominant
        serving-plane cost.)"""
        return width_bucket(max_used, self.max_blocks_per_seq)

    def _calibrate_cost_model(self, bucket: int, width: int) -> None:
        """Replace the cost model's hand-rolled 2·params FLOPs/token with
        XLA's own count of the decode executable
        (``jax.stages.Compiled.cost_analysis``) where the backend provides
        one. Lowering happens BEFORE the warmup dispatch of the same shape —
        ``lower()`` only records donation, it does not invalidate the live
        cache buffers — and the compile lands in the same compilation cache
        the warmup call hits. Failures degrade to the analytical model."""
        cm = self.flight.cost_model
        if cm is None:
            return
        try:
            tpa = jnp.zeros((3, bucket), jnp.int32)
            tables = jnp.zeros((bucket, width), jnp.int32)
            compiled = self._decode_jit.lower(
                self.params, self.cache.k, self.cache.v, tpa, tables
            ).compile()
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            flops = float(cost.get("flops", 0.0) or 0.0)
            if flops > 0 and cm.calibrate(flops / max(bucket, 1)):
                logger.info(
                    "cost model calibrated from XLA cost_analysis: "
                    "%.4g flops/token (analytical %.4g)",
                    cm.flops_per_token, 2.0 * cm.param_count,
                )
        except Exception as e:  # noqa: BLE001 — calibration is best-effort
            logger.debug("cost_analysis calibration unavailable: %s", e)

    def warmup(self, ctx_tokens: int = 2048) -> int:
        """Precompile the serving-hot executables so traffic never waits on
        XLA (the reference's engines warm up at startup for the same reason;
        vLLM role: --enforce-eager off + warmup passes). Covers: decode
        (every batch bucket × table widths up to ``ctx_tokens``), the
        multi-step window variant when enabled, fresh-prefill chunks per
        bucket, and the sampler per bucket. Dispatches run with all rows
        inactive, so writes land in the reserved scratch block 0 and cache
        contents are untouched. Returns the number of executables warmed."""
        bs = self.mc.block_size
        max_w = self._width_bucket((ctx_tokens + bs - 1) // bs)
        widths = sorted(set(min(r, self.max_blocks_per_seq) for r in width_rungs(max_w)))
        count = 0
        key = jax.random.PRNGKey(0)
        # Ask XLA for the decode executable's own FLOPs count before the
        # first dispatch of the same shape compiles it for real.
        self._calibrate_cost_model(self.sc.decode_buckets[0], widths[0])
        for bucket in self.sc.decode_buckets:
            for width in widths:
                toks = jnp.zeros((bucket,), jnp.int32)
                pos = jnp.zeros((bucket,), jnp.int32)
                tpa = jnp.zeros((3, bucket), jnp.int32)
                tables = jnp.zeros((bucket, width), jnp.int32)
                active = jnp.zeros((bucket,), bool)
                temps = jnp.zeros((bucket,), jnp.float32)
                tks = jnp.zeros((bucket,), jnp.int32)
                tps = jnp.ones((bucket,), jnp.float32)
                self.flight.record_exec("decode", (bucket, width))
                logits, self.cache.k, self.cache.v = self._consume_aux(
                    self._decode_jit(
                        self.params, self.cache.k, self.cache.v, tpa, tables
                    )
                )
                count += 1
                if self.sc.enable_overlap_decode and self._supports_overlap:
                    # Fused overlap step: same (bucket, width) key space as
                    # plain decode, so the pipeline never compiles mid-
                    # traffic (flight-recorder 0-post-warmup gate).
                    self.flight.record_exec("decode_sample", (bucket, width))
                    res = self._decode_sample_jit(
                        self.params, self.cache.k, self.cache.v, tpa, tables,
                        temps, tks, tps, key,
                    )
                    _, _, self.cache.k, self.cache.v = self._consume_aux(res)
                    count += 1
                if self.sc.num_scheduler_steps > 1 and self._supports_multi_step:
                    for w, mjit in self._decode_multi_jits.items():
                        self.flight.record_exec("decode_multi", (w, bucket, width))
                        _, self.cache.k, self.cache.v = self._consume_aux(
                            mjit(
                                self.params, self.cache.k, self.cache.v, toks, pos, tables,
                                active, temps, tks, tps, key,
                            )
                        )
                        count += 1
                if self._use_fused_window:
                    # Fused megakernel windows: greedy, sampled, and (when a
                    # grammar pool is attached and fits) guided variants
                    # over the same (steps, bucket, width) key space as
                    # decode_multi. The first trace also records the
                    # launches-per-window gauge (must be 1).
                    from dynamo_tpu.engine.attention import megakernel as _mk

                    guided_warm = self._fused_guided_ok()
                    for w in self._window_rungs:
                        unif = jnp.zeros((w, bucket), jnp.float32)
                        variants = [
                            ("decode_fused", (w, bucket, width),
                             (w, False, False),
                             (toks, pos, tables, active)),
                            ("decode_fused_sampled", (w, bucket, width),
                             (w, True, False),
                             (toks, pos, tables, active, temps, tks, tps, unif)),
                        ]
                        if guided_warm:
                            P = int(self.guided.pool.capacity)
                            variants.append((
                                "decode_fused_guided", (w, bucket, width, P),
                                (w, True, True),
                                (toks, pos, tables, active, temps, tks, tps,
                                 unif, jnp.zeros((bucket,), jnp.int32),
                                 self.guided.pool.device(),
                                 self.guided.pool.next_device()),
                            ))
                        for kind, key_t, jit_key, args in variants:
                            new_exec = self.flight.record_exec(kind, key_t)
                            launches0 = _mk.trace_launch_count()
                            _, self.cache.k, self.cache.v = self._decode_fused_jits[jit_key](
                                self.params, self.cache.k, self.cache.v, *args
                            )
                            if new_exec:
                                # Gauge holds the WORST variant: greedy,
                                # sampled-epilogue, and guided windows must
                                # all trace exactly one pallas launch.
                                self.flight.record_window_launches(max(
                                    _mk.trace_launch_count() - launches0,
                                    self.flight.fused_window_pallas_launches or 0,
                                ))
                            count += 1
                if self.draft_params is not None and self._use_fused_spec:
                    # Fused spec windows share decode's (bucket, width) key
                    # space — warm every combination so a spec batch joining
                    # warmed traffic compiles nothing.
                    gamma = self.spec_gamma
                    R = self._spec_rounds
                    self.flight.record_exec("spec_fused", (R, gamma, bucket, width))
                    unif_s = jnp.zeros((R, bucket, 2 * gamma + 1), jnp.float32)
                    (_, _, self.cache.k, self.cache.v,
                     self.draft_cache.k, self.draft_cache.v) = self._spec_fused_jit(
                        self.params, self.draft_params,
                        self.cache.k, self.cache.v,
                        self.draft_cache.k, self.draft_cache.v,
                        toks, toks, pos, tables, active,
                        temps, tks, tps, unif_s,
                    )
                    count += 1
            self._sample_jit(
                jnp.zeros((bucket, self.mc.vocab_size), jnp.float32),
                jnp.zeros((bucket,), jnp.float32), jnp.zeros((bucket,), jnp.int32),
                jnp.ones((bucket,), jnp.float32), key, None,
            )
            # Fused logprobs variant too: a logprobs row joining a warmed
            # batch must not compile the sampler mid-traffic.
            self._sample_lp_jit(
                jnp.zeros((bucket, self.mc.vocab_size), jnp.float32),
                jnp.zeros((bucket,), jnp.float32), jnp.zeros((bucket,), jnp.int32),
                jnp.ones((bucket,), jnp.float32), key, None,
            )
            # ... and the top-k variant (OpenAI top_logprobs; static
            # candidate cap, so one warm covers every requested k).
            self._sample_tlp_jit(
                jnp.zeros((bucket, self.mc.vocab_size), jnp.float32),
                jnp.zeros((bucket,), jnp.float32), jnp.zeros((bucket,), jnp.int32),
                jnp.ones((bucket,), jnp.float32), key, None,
            )
            count += 3
        # Deferred-retirement KV rollback (overlap pipeline): one executable,
        # warmed against the scratch slot so a finish-mid-pipeline never
        # compiles under traffic.
        if self.sc.enable_overlap_decode and self._supports_overlap:
            self.flight.record_exec("kv_rollback", ())
            self.cache.k, self.cache.v = self._kv_zero_jit(
                self.cache.k, self.cache.v, jnp.int32(0), jnp.int32(0)
            )
            count += 1
        # Prefix-cache copy-on-write block copy: one executable, warmed
        # against the scratch block so a full-cover hit under traffic never
        # compiles (0-post-warmup invariant with prefix caching enabled).
        if self.sc.enable_prefix_caching:
            self.flight.record_exec("kv_block_copy", ())
            self.cache.k, self.cache.v = self._kv_copy_jit(
                self.cache.k, self.cache.v, jnp.int32(0), jnp.int32(0)
            )
            count += 1
        # Guided masked-sampling executables: one per decode bucket (plus
        # the bucket-1 prefill-tail sampler) at the current pool capacity —
        # guided rows joining a warmed batch then compile nothing.
        if self.guided is not None:
            pool = self.guided.pool.device()
            P = int(pool.shape[0])
            for bucket in sorted(set(self.sc.decode_buckets) | {1}):
                self.flight.record_exec("guided_sample", (bucket, P))
                self._guided_sample_jit(
                    jnp.zeros((bucket, self.mc.vocab_size), jnp.float32), pool,
                    jnp.zeros((2, bucket), jnp.int32),
                    jnp.zeros((bucket,), jnp.float32),
                    jnp.ones((bucket,), jnp.float32), key, None,
                )
                self._guided_sample_lp_jit(
                    jnp.zeros((bucket, self.mc.vocab_size), jnp.float32), pool,
                    jnp.zeros((2, bucket), jnp.int32),
                    jnp.zeros((bucket,), jnp.float32),
                    jnp.ones((bucket,), jnp.float32), key, None,
                )
                self._guided_sample_tlp_jit(
                    jnp.zeros((bucket, self.mc.vocab_size), jnp.float32), pool,
                    jnp.zeros((2, bucket), jnp.int32),
                    jnp.zeros((bucket,), jnp.float32),
                    jnp.ones((bucket,), jnp.float32), key, None,
                )
                count += 3
        prev_bucket = 0
        for bucket in self.sc.prefill_buckets:
            if bucket > self.sc.max_prefill_chunk:
                continue
            # Smallest table width serving can pair with this chunk bucket:
            # the shortest prompt that maps here (prev_bucket+1 tokens),
            # bucketed by _prefill_table's rung rule (16 floor).
            min_w = max(16, width_bucket((prev_bucket + 1 + bs - 1) // bs, self.max_blocks_per_seq))
            # Wave-admission width floor for this chunk bucket: _admit_wave
            # buckets by the wave's longest block table (rung floor 4, NOT
            # _prefill_table's 16) — the shortest fresh prompt chunking
            # here plus its next-token slot.
            wave_lo = width_bucket((prev_bucket + 2 + bs - 1) // bs, self.max_blocks_per_seq)
            prev_bucket = bucket
            # Serving's _prefill_table buckets by the sequence's TOTAL block
            # count, not the chunk: a long prompt prefilled in small chunks
            # uses a wide table from chunk 0, and prefix-hit continuations
            # inherit the full-prompt width. Warm every rung width from the
            # bucket's minimum up to the ctx budget so neither compiles
            # mid-traffic.
            p_widths = sorted(set(
                min(r, self.max_blocks_per_seq)
                for r in width_rungs(max(max_w, min_w))
                if r >= min_w
            ))
            for width in p_widths:
                # Both has_prefix variants: fresh prefills AND chunked/
                # prefix-hit continuations. (On the XLA path hp is a traced
                # no-op arg, so the second call is a cache hit.)
                for hp in (False, True):
                    self.flight.record_exec(
                        "prefill", (bucket, width, hp if self._use_flash_prefill else False)
                    )
                    _, self.cache.k, self.cache.v = self._consume_aux(
                        self._prefill_jit(
                            self.params, self.cache.k, self.cache.v,
                            jnp.zeros((bucket,), jnp.int32), jnp.int32(1), jnp.int32(0),
                            jnp.zeros((width,), jnp.int32), hp,
                        )
                    )
                    count += 1
                if self.draft_params is not None:
                    _, self.draft_cache.k, self.draft_cache.v = self._d_prefill_jit(
                        self.draft_params, self.draft_cache.k, self.draft_cache.v,
                        jnp.zeros((bucket,), jnp.int32), jnp.int32(1), jnp.int32(0),
                        jnp.zeros((width,), jnp.int32),
                    )
                    count += 1
            self._sample_jit(
                jnp.zeros((1, self.mc.vocab_size), jnp.float32),
                jnp.zeros((1,), jnp.float32), jnp.zeros((1,), jnp.int32),
                jnp.ones((1,), jnp.float32), key, None,
            )
            count += 1
            # Wave-admission executables for this chunk bucket: every batch
            # rung a wave can form (≥2 admitted) × the table-width rungs
            # wave traffic actually produces — from the shortest fresh
            # prompt chunking here up to the longest wave-eligible prompt
            # (prefix-hit waves pair SMALL chunk buckets with the FULL
            # prompt's table width), clamped to the ctx budget. The round-5
            # advisor flagged these non-default (b, s, w) keys compiling
            # mid-traffic: only (top_bucket, s, 16-floor width) was warmed,
            # while real waves bucket width from their block tables (rung
            # floor 4).
            if self._supports_chunk_admit and self.draft_params is None:
                wave_hi = min(
                    max(max_w, wave_lo),
                    width_bucket((self._wave_s_cap() + 1 + bs - 1) // bs, self.max_blocks_per_seq),
                )
                wave_ws = sorted(
                    w for w in set(
                        min(r, self.max_blocks_per_seq) for r in width_rungs(wave_hi)
                    )
                    if wave_lo <= w <= wave_hi
                )
                for b_b in (b for b in self.sc.decode_buckets if b >= 2):
                    for w in wave_ws:
                        self.flight.record_exec("admit", (b_b, bucket, w))
                        _, self.cache.k, self.cache.v = self._consume_aux(
                            self._get_admit_jit((b_b, bucket, w))(
                                self.params, self.cache.k, self.cache.v,
                                jnp.zeros((b_b, bucket), jnp.int32), jnp.zeros((b_b,), jnp.int32),
                                jnp.zeros((b_b,), jnp.int32), jnp.zeros((b_b, w), jnp.int32),
                            )
                        )
                        count += 1
        # Mixed prefill+decode executables: every budget-sized chunk bucket
        # the capacity dial can produce (_mixed_warm_buckets — a ratio
        # shift between dial settings must not compile mid-traffic) at
        # every decode bucket × width, with the minimum prefill-table
        # width. Bucket rungs keep the key space bounded; rarer (s, Wp)
        # keys compile lazily.
        if (
            self._supports_mixed
            and self.sc.enable_mixed_batching
            and self.draft_params is None
        ):
            p_w = max(16, width_bucket(1, self.max_blocks_per_seq))
            for s_b in self._mixed_warm_buckets():
                for bucket in self.sc.decode_buckets:
                    for width in widths:
                        self.flight.record_exec(
                            "mixed",
                            (s_b, p_w, bucket, width)
                            + ((False,) if self._use_flash_prefill else ()),
                        )
                        res = self._get_mixed_jit((s_b, p_w, bucket, width))(
                            self.params, self.cache.k, self.cache.v,
                            jnp.zeros((s_b,), jnp.int32), jnp.int32(1), jnp.int32(0),
                            jnp.zeros((p_w,), jnp.int32), jnp.zeros((bucket,), jnp.int32),
                            jnp.zeros((bucket,), jnp.int32),
                            jnp.zeros((bucket, width), jnp.int32),
                            jnp.zeros((bucket,), bool), False,
                        )
                        _, self.cache.k, self.cache.v = self._consume_aux(res)
                        count += 1
        # Speculative-round executables (draft chunk+sample, γ-1 proposal
        # window, target chunk scoring, rejection verify): _decode_spec keys
        # them by (γ, decode bucket, table width), so with a draft attached
        # the first spec round after warmup would otherwise compile four
        # executables mid-traffic. All rows inactive/zero-valid, tables
        # zero → writes land in the reserved scratch block 0, same as the
        # decode warmup above.
        if self.draft_params is not None:
            gamma = self.spec_gamma
            S = gamma + 1
            for bucket in self.sc.decode_buckets:
                for width in widths:
                    self.flight.record_exec("spec", (gamma, bucket, width))
                    tables = jnp.zeros((bucket, width), jnp.int32)
                    temps = jnp.zeros((bucket,), jnp.float32)
                    tks = jnp.zeros((bucket,), jnp.int32)
                    tps = jnp.ones((bucket,), jnp.float32)
                    toks = jnp.zeros((bucket, S), jnp.int32)
                    pos0 = jnp.zeros((bucket,), jnp.int32)
                    valid = jnp.zeros((bucket,), jnp.int32)
                    tok1, lg1, self.draft_cache.k, self.draft_cache.v = (
                        self._d_chunk_sample_jit(
                            self.draft_params, self.draft_cache.k, self.draft_cache.v,
                            toks, pos0, valid, tables, temps, tks, tps, key,
                        )
                    )
                    count += 1
                    if gamma > 1:
                        _, lg_steps, self.draft_cache.k, self.draft_cache.v = (
                            self._d_multi_jit(
                                self.draft_params, self.draft_cache.k, self.draft_cache.v,
                                tok1, pos0, tables, jnp.zeros((bucket,), bool),
                                temps, tks, tps, key,
                            )
                        )
                        draft_logits = jnp.concatenate(
                            [lg1[:, None], jnp.transpose(lg_steps, (1, 0, 2))], axis=1
                        )
                        count += 1
                    else:
                        draft_logits = lg1[:, None]
                    t_logits, self.cache.k, self.cache.v = self._consume_aux(
                        self._t_chunk_jit(
                            self.params, self.cache.k, self.cache.v,
                            toks, pos0, valid, tables,
                        )
                    )
                    self._spec_verify_jit(
                        draft_logits, t_logits,
                        jnp.zeros((bucket, gamma), jnp.int32),
                        temps, tks, tps, key,
                    )
                    count += 2
        return count

    def _draft_catchup(self, seq: Sequence, tokens: List[int], upto: int) -> None:
        """Materialize draft KV for positions seq.d_n..upto-1 (prefill-style
        chunks over ``tokens``). Used to mirror prompt prefill, to absorb
        remotely-prefilled prompts, and to re-sync rows whose draft lag
        outgrew the spec chunk width (e.g. after stretches of non-spec
        decode in mixed batches)."""
        if self.draft_params is None or seq.mm_features is not None:
            return  # no vision path in the draft — mm rows decode unspeculated
        while seq.d_n < upto:
            start = seq.d_n
            chunk = min(upto - start, self.sc.max_prefill_chunk)
            bucket = next_bucket(chunk, self.sc.prefill_buckets)
            chunk = min(chunk, bucket)
            toks = tokens[start : start + chunk]
            padded = np.zeros((bucket,), dtype=np.int32)
            padded[: len(toks)] = toks
            _, self.draft_cache.k, self.draft_cache.v = self._d_prefill_jit(
                self.draft_params, self.draft_cache.k, self.draft_cache.v,
                jnp.asarray(padded), jnp.int32(len(toks)), jnp.int32(start),
                self._prefill_table(seq),
            )
            seq.d_n += len(toks)

    def _draft_catchup_prefill(self, seq: Sequence, pf_tokens: List[int]) -> None:
        """Mirror prefill into the draft cache (spec decode). The draft
        always computes the FULL prompt — target-side prefix-cache hits
        don't populate draft KV — so it runs from seq.d_n regardless of
        where the target's chunks started."""
        self._draft_catchup(seq, pf_tokens, seq.num_computed)

    # --- zero-bubble overlapped decode --------------------------------------
    def _decode_tables(self, batch: List[Sequence], bucket: int, width: int) -> jnp.ndarray:
        """Decode block tables as a device array, re-uploaded ONLY when a
        table actually changed. Block tables are append-only between
        composition changes, so steady-state decode re-transferred an
        identical [bucket, width] i32 array every step; one cached entry
        (keyed on composition + exact block ids) eliminates that."""
        key = (bucket, width, tuple(s.request_id for s in batch))
        blocks = tuple(tuple(s.block_ids) for s in batch)
        if self._tables_cache is not None:
            ckey, cblocks, dev = self._tables_cache
            if ckey == key and cblocks == blocks:
                return dev
        tables = np.zeros((bucket, width), dtype=np.int32)
        for i, s in enumerate(batch):
            tables[i, : len(s.block_ids)] = s.block_ids
        dev = jnp.asarray(tables)
        self._tables_cache = (key, blocks, dev)
        return dev

    def _record_host_gap(self) -> None:
        """Host-gap accounting, called right BEFORE a decode-family dispatch:
        the interval since the previous decode dispatch RETURNED is the
        bubble the device spent waiting on Python."""
        if self._last_decode_dispatch_t is not None:
            self.flight.record_host_gap(time.perf_counter() - self._last_decode_dispatch_t)

    def _note_decode_dispatch(self) -> None:
        """Called right after a decode-family dispatch call returns (device
        launched, host free again)."""
        self._last_decode_dispatch_t = time.perf_counter()

    def _break_decode_gap(self) -> None:
        """A non-decode dispatch intervened — the next interval is not a
        decode host gap."""
        self._last_decode_dispatch_t = None

    def _overlap_row_ok(self, seq: Sequence) -> bool:
        """Rows needing host work between steps can't ride the pipeline:
        guided (the FSM must advance before the next mask), processors and
        penalties (host/history logits edits), seeded sampling (per-row
        keys), logprobs (separate readback shape), disagg prefill-role
        exports. Same fallback shape as the spec/multi-step exclusions."""
        s = seq.sampling
        return not (
            seq.aborted
            or seq.guided is not None
            or s.logprobs
            or s.top_logprobs
            or s.logits_processors
            or s.has_penalties
            or (s.seed is not None and s.temperature > 0)
            or seq.keep_blocks_on_finish
        )

    def _overlap_start_ok(self, batch: List[Sequence]) -> bool:
        return (
            self.sc.enable_overlap_decode
            and self._supports_overlap
            and self.draft_params is None
            and not self.waiting
            and all(self._overlap_row_ok(s) for s in batch)
        )

    def _overlap_can_dispatch(self, batch: List[Sequence], positions: List[int]) -> bool:
        """The next fused dispatch writes KV at each row's input position:
        every slot must already exist (block-table growth flushes to the
        sync path, which allocates/preempts there) and stay inside
        max_seq_len."""
        bs = self.mc.block_size
        for seq, p in zip(batch, positions):
            if p + 1 > len(seq.block_ids) * bs or p >= self.mc.max_seq_len:
                return False
        return True

    def _overlap_should_continue(self) -> bool:
        pipe = self._pipe
        return (
            not self.waiting
            and not any(s.aborted for s in pipe["batch"])
            and self._overlap_can_dispatch(pipe["batch"], pipe["positions"])
        )

    def _dispatch_overlap(self, pipe: dict, tpa_dev) -> None:
        """Issue one fused decode+sample dispatch (async — returns as soon as
        the device has the work) and stage its outputs in the pipe."""
        self._step_counter += 1
        key = jax.random.fold_in(self._rng, self._step_counter)
        self.flight.record_exec("decode_sample", (pipe["bucket"], pipe["width"]))
        self._record_host_gap()
        res = self._decode_sample_jit(
            self.params, self.cache.k, self.cache.v, tpa_dev, pipe["tables"],
            pipe["temps"], pipe["tks"], pipe["tps"], key,
        )
        sampled, next_tpa, self.cache.k, self.cache.v = self._consume_aux(res)
        self._note_decode_dispatch()
        pipe["sampled"] = sampled
        pipe["next_tpa"] = next_tpa
        self.overlap_steps_total += 1

    def _overlap_start(self, batch: List[Sequence], bucket: int, width: int) -> bool:
        """Dispatch pipeline step 0. No tokens are retired this iteration —
        streaming runs one step behind on the overlap path (documented in
        README "Decode pipeline")."""
        positions = [s.total_len - 1 for s in batch]
        if not self._overlap_can_dispatch(batch, positions):
            return False
        from dynamo_tpu.engine.sampling import pack_param_rows

        temps, top_ks, top_ps = pack_param_rows([s.sampling for s in batch], bucket)
        tpa = np.zeros((3, bucket), dtype=np.int32)
        for i, seq in enumerate(batch):
            tpa[0, i] = seq.all_ids[-1]
            tpa[1, i] = positions[i]
            tpa[2, i] = 1
        pipe = {
            "batch": batch, "bucket": bucket, "width": width,
            "tables": self._decode_tables(batch, bucket, width),
            "temps": jnp.asarray(temps), "tks": jnp.asarray(top_ks),
            "tps": jnp.asarray(top_ps),
        }
        self._dispatch_overlap(pipe, jnp.asarray(tpa))
        pipe["positions"] = [p + 1 for p in positions]
        self._pipe = pipe
        return True

    def _overlap_step(self, outputs: List[tuple]) -> None:
        """Steady state: dispatch step N+1 from the previous step's ON-DEVICE
        sampled tokens, THEN read back and retire step N — the readback and
        all host bookkeeping overlap step N+1's device compute (JAX async
        dispatch). Exactly ONE blocking sync per steady-state step. A row
        that turns out finished at step N makes step N+1's token for it
        speculative garbage — the flush discards it and rolls back its KV
        write slot."""
        pipe = self._pipe
        prev_sampled = pipe["sampled"]
        # Capture rollback targets BEFORE retirement mutates block tables:
        # the N+1 dispatch writes each row's last-appended token's KV at
        # the row's pre-retire total_len.
        rollback = self._rollback_targets(pipe["batch"])
        with StepTimer() as timer:
            self._dispatch_overlap(pipe, pipe["next_tpa"])
            pipe["positions"] = [p + 1 for p in pipe["positions"]]
            # Retire step N while N+1 runs on device.
            sampled_h = np.asarray(prev_sampled)  # the step's one blocking sync
            finished = False
            for i, seq in enumerate(pipe["batch"]):
                self._append_token(seq, int(sampled_h[i]), outputs)
                if seq.state != SeqState.RUNNING:
                    finished = True
        self.flight.record_step(
            "decode", timer.dur, len(pipe["batch"]),
            kv_read_tokens=sum(s.total_len for s in pipe["batch"]),
        )
        self._bill_step(timer.dur, [(s, "decode", 1, s.total_len) for s in pipe["batch"]])
        self.telemetry.observe("itl", timer.dur)
        if finished:
            self._overlap_flush(outputs, rollback=rollback)

    def _rollback_targets(self, batch: List[Sequence]) -> List[Optional[tuple]]:
        """(block, offset) each row's in-flight dispatch writes to — the slot
        to zero if the row turns out finished while that dispatch runs."""
        bs = self.mc.block_size
        out: List[Optional[tuple]] = []
        for seq in batch:
            p = seq.total_len
            out.append((seq.block_ids[p // bs], p % bs) if p < len(seq.block_ids) * bs else None)
        return out

    def _overlap_flush(self, outputs: List[tuple], rollback: Optional[List] = None) -> None:
        """Absorb the in-flight step and return to the sync path. Rows still
        running keep their token (the in-flight step computed exactly what
        the sync path would have — no wasted work); rows that finished at
        the previous retire discard their speculative token and get the KV
        slot the in-flight step wrote zeroed (same shape as the preemption-
        resume recompute: the device state must not outrun the host's
        account of the sequence). ``rollback`` is only passed by
        _overlap_step's finish path — on a plain composition flush every
        row is still running and nothing rolls back."""
        pipe, self._pipe = self._pipe, None
        self.overlap_flushes_total += 1
        sampled_h = np.asarray(pipe["sampled"])
        for i, seq in enumerate(pipe["batch"]):
            if seq.state != SeqState.RUNNING:
                # Rollback applies ONLY to rows that finished at the previous
                # retire (a row preempted by a batchmate's capacity growth
                # below lands here WAITING — its blocks are already released
                # and possibly re-owned, nothing to zero).
                if (
                    rollback is not None and rollback[i] is not None
                    and seq.state == SeqState.FINISHED and not seq.aborted
                ):
                    blk, off = rollback[i]
                    self.flight.record_exec("kv_rollback", ())
                    self.cache.k, self.cache.v = self._kv_zero_jit(
                        self.cache.k, self.cache.v, jnp.int32(blk), jnp.int32(off)
                    )
                continue
            if seq.aborted:
                continue  # _reap_aborted finishes it without the extra token
            self._ensure_block_capacity(seq)
            if seq.state != SeqState.RUNNING:
                continue
            self._append_token(seq, int(sampled_h[i]), outputs)

    def _decode_step(self) -> List[tuple]:
        outputs: List[tuple] = []
        # Batch size caps at the largest decode bucket — NOT max_running:
        # admission keeps len(running) ≤ max_running in steady state, but a
        # capacity-dial shrink can leave more rows running than the new
        # cap, and slicing to max_running would decode the same head rows
        # every step while the tail starved forever. Over-cap rows drain.
        n = min(len(self.running), self.sc.decode_buckets[-1])
        batch = self.running[:n]
        bucket = next_bucket(n, self.sc.decode_buckets)

        if self.draft_params is not None and not any(
            seq.sampling.logits_processors
            or seq.sampling.logprobs
            or seq.sampling.top_logprobs
            or seq.sampling.has_penalties
            or seq.mm_features is not None
            # Guided rows can't ride speculation (proposal sampling
            # ignores the FSM mask): the batch gracefully falls back to
            # the non-spec single-step path below.
            or seq.guided is not None
            # Seeded sampling needs per-row keys the spec round doesn't
            # thread; greedy seeded rows are fine (seed is a no-op).
            or (seq.sampling.seed is not None and seq.sampling.temperature > 0)
            for seq in batch
        ):
            # Fused spec window first (draft bursts + target verifies in ONE
            # launch); falls through to the per-round spec path, then to
            # plain decode, when blocks/limits don't allow it.
            if self._use_fused_spec and self._decode_spec_fused(batch, bucket, outputs):
                return outputs
            if self._decode_spec(batch, bucket, outputs):
                return outputs

        if self.sc.num_scheduler_steps > 1 and self._supports_multi_step:
            # Fused-eligibility is "no per-row HOST extras", not "all
            # greedy": sampled rows ride via host-precomputed uniforms,
            # guided rows via the device mask + next-state pools. Only
            # penalties (history mutates inside the window), logits
            # processors, and logprobs/top_logprobs rows — which need the
            # host between tokens — are window-ineligible; without the
            # fused window, guided and seeded-sampled rows are too (the
            # decode_multi executable threads neither FSM masks nor
            # per-row keys).
            fused_w = self._use_fused_window
            guided_ok = fused_w and self._fused_guided_ok()

            def _window_ok(seq) -> bool:
                if (
                    seq.sampling.logits_processors
                    or seq.sampling.logprobs
                    or seq.sampling.top_logprobs
                    or seq.sampling.has_penalties
                ):
                    return False
                if seq.guided is not None:
                    return guided_ok
                if seq.sampling.seed is not None and seq.sampling.temperature > 0:
                    return fused_w
                return True

            win = [seq for seq in batch if _window_ok(seq)]
            if len(win) == len(batch):
                if self._decode_multi(batch, bucket, outputs):
                    return outputs
            elif win and fused_w:
                # Row-wise fallback: the window-eligible rows still ride the
                # fused window; ONLY the extras rows flush to the single-
                # step host path below (previously one logprobs row dragged
                # the whole batch off the fused path).
                w_bucket = next_bucket(len(win), self.sc.decode_buckets)
                if self._decode_multi(win, w_bucket, outputs):
                    batch = [seq for seq in batch if not _window_ok(seq)]
                    bucket = next_bucket(len(batch), self.sc.decode_buckets)

        # Bucket the block-table width by the longest sequence in the batch:
        # the attention gather is O(table_width), so short contexts must not
        # pay for max_seq_len. Power-of-two widths (see _width_bucket) bound
        # the executable count at log2(max_blocks) so warmup() precompiles
        # them all.
        width = self._width_bucket(max(len(seq.block_ids) for seq in batch))

        # Zero-bubble pipeline entry: no-extras batches with no waiting work
        # hand off to the overlapped fused-step loop (tokens stream one step
        # behind; this iteration emits nothing).
        if self._overlap_start_ok(batch) and self._overlap_start(batch, bucket, width):
            return outputs

        tpa = np.zeros((3, bucket), dtype=np.int32)
        for i, seq in enumerate(batch):
            tpa[0, i] = seq.all_ids[-1]
            tpa[1, i] = seq.total_len - 1  # write slot of the current token
            tpa[2, i] = 1
        tables = self._decode_tables(batch, bucket, width)

        self.flight.record_exec("decode", (bucket, width))
        with StepTimer() as timer:
            self._record_host_gap()
            res = self._decode_jit(
                self.params, self.cache.k, self.cache.v, jnp.asarray(tpa), tables
            )
            self._note_decode_dispatch()
            logits, self.cache.k, self.cache.v = self._consume_aux(res)
            self._finish_decode_rows(batch, bucket, logits, outputs)
        self.flight.record_step(
            "decode", timer.dur, len(outputs),
            kv_read_tokens=sum(s.total_len for s in batch),
        )
        self._bill_step(timer.dur, [(s, "decode", 1, s.total_len) for s in batch])
        self.telemetry.observe("itl", timer.dur)
        return outputs

    def _finish_decode_rows(
        self, batch: List[Sequence], bucket: int, logits: jax.Array, outputs: List[tuple]
    ) -> None:
        """Post-dispatch half of a single decode step: penalties, logits
        processors, sampling (with per-request seeds), logprobs, and token
        append/stop handling. Shared by _decode_step and _mixed_step — the
        decode rows of a mixed dispatch carry the same per-row [B, V]
        logits a plain decode step produces."""
        from dynamo_tpu.engine.sampling import pack_param_rows

        # Frequency/presence penalties: one batched device op for the whole
        # step (per-row output-token counts via scatter-add — sampling.py).
        # Penalty-free batches skip it entirely.
        if any(seq.sampling.has_penalties for seq in batch):
            logits = self._apply_penalties(batch, bucket, logits)
        # Per-request logits processors (dynamo_tpu.logits_processing): the
        # host path — ONLY the rows that carry processors cross to host
        # (device gather → [n_proc, V] transfer → device scatter), so one
        # logit_bias row no longer drags the whole batch's [B, V] logits
        # over the wire, and processor-free batches stay on the fast path.
        if any(seq.sampling.logits_processors for seq in batch):
            from dynamo_tpu.logits_processing import apply_chain

            proc_rows = [i for i, seq in enumerate(batch) if seq.sampling.logits_processors]
            sel = jnp.asarray(np.asarray(proc_rows, dtype=np.int32))
            sub = np.array(logits[sel])  # [n_proc, V] writable host copy
            for j, i in enumerate(proc_rows):
                sub[j] = np.asarray(
                    apply_chain(batch[i].sampling.logits_processors, batch[i].output_ids, jnp.asarray(sub[j]))
                )
            logits = logits.at[sel].set(jnp.asarray(sub))
        self._step_counter += 1
        key = jax.random.fold_in(self._rng, self._step_counter)
        row_keys = None
        if any(seq.sampling.seed is not None for seq in batch):
            from dynamo_tpu.engine.sampling import make_row_keys

            seeds = np.zeros((bucket,), dtype=np.int32)
            poss_out = np.zeros((bucket,), dtype=np.int32)
            has_seed = np.zeros((bucket,), dtype=bool)
            for i, seq in enumerate(batch):
                if seq.sampling.seed is not None:
                    seeds[i] = seq.sampling.seed
                    poss_out[i] = len(seq.output_ids)
                    has_seed[i] = True
            row_keys = make_row_keys(
                key, jnp.asarray(seeds), jnp.asarray(poss_out), jnp.asarray(has_seed)
            )
        temps, top_ks, top_ps = pack_param_rows([s.sampling for s in batch], bucket)
        # Logprobs fold into the SAME sampling dispatch when any row wants
        # them (sampling.sample_batch_logprobs): one executable, one
        # readback — previously a separate compute_logprobs device op plus
        # its own sync per step. A top_logprobs row widens the dispatch to
        # the top-k variant (static candidate cap — one executable for any
        # requested k); the chosen-token logprob rides along either way.
        want_tlp = any(seq.sampling.top_logprobs for seq in batch)
        want_lp = want_tlp or any(seq.sampling.logprobs for seq in batch)
        logprobs_np = None
        top_ids_np = top_lps_np = None
        if any(seq.guided is not None for seq in batch):
            # Guided rows: gather each row's FSM-state mask from the shared
            # device pool inside the fused mask+sample dispatch. Unguided
            # rows point at the reserved allow-all row 0, so the mixed batch
            # shares one executable.
            pool = self.guided.pool.device()
            k_rows = np.zeros((2, bucket), dtype=np.int32)
            k_rows[0] = top_ks
            for i, seq in enumerate(batch):
                if seq.guided is not None:
                    k_rows[1, i] = seq.guided.row_id
            self.flight.record_exec("guided_sample", (bucket, int(pool.shape[0])))
            if want_tlp:
                sampled, logprobs_np, top_ids_np, top_lps_np = jax.device_get(
                    self._guided_sample_tlp_jit(
                        logits, pool, jnp.asarray(k_rows),
                        jnp.asarray(temps), jnp.asarray(top_ps), key, row_keys,
                    )
                )
            elif want_lp:
                sampled, logprobs_np = jax.device_get(
                    self._guided_sample_lp_jit(
                        logits, pool, jnp.asarray(k_rows),
                        jnp.asarray(temps), jnp.asarray(top_ps), key, row_keys,
                    )
                )
            else:
                sampled = np.asarray(
                    self._guided_sample_jit(
                        logits, pool, jnp.asarray(k_rows),
                        jnp.asarray(temps), jnp.asarray(top_ps), key, row_keys,
                    )
                )
        elif want_tlp:
            sampled, logprobs_np, top_ids_np, top_lps_np = jax.device_get(
                self._sample_tlp_jit(
                    logits, jnp.asarray(temps), jnp.asarray(top_ks), jnp.asarray(top_ps), key, row_keys
                )
            )
        elif want_lp:
            sampled, logprobs_np = jax.device_get(
                self._sample_lp_jit(
                    logits, jnp.asarray(temps), jnp.asarray(top_ks), jnp.asarray(top_ps), key, row_keys
                )
            )
        else:
            sampled = np.asarray(
                self._sample_jit(
                    logits, jnp.asarray(temps), jnp.asarray(top_ks), jnp.asarray(top_ps), key, row_keys
                )
            )

        for i, seq in enumerate(batch):
            if seq.state != SeqState.RUNNING:
                continue  # preempted while growing an earlier row this step
            self._ensure_block_capacity(seq)
            if seq.state != SeqState.RUNNING:
                continue  # itself preempted (no candidate to evict)
            lp = (
                float(logprobs_np[i])
                if logprobs_np is not None
                and (seq.sampling.logprobs or seq.sampling.top_logprobs)
                else None
            )
            tlp = None
            if top_ids_np is not None and seq.sampling.top_logprobs:
                k = min(seq.sampling.top_logprobs, top_ids_np.shape[1])
                tlp = [
                    (int(top_ids_np[i, j]), float(top_lps_np[i, j])) for j in range(k)
                ]
            self._append_token(seq, int(sampled[i]), outputs, logprob=lp, top_logprobs=tlp)

    def _decode_multi(self, batch: List[Sequence], bucket: int, outputs: List[tuple]) -> bool:
        """Multi-step decode window: N steps in one dispatch, one host sync.
        Returns False (caller falls back to single-step) when KV blocks for
        the whole window can't be reserved."""
        # Smallest window rung covering the batch's remaining token budget —
        # a request needing 5 more tokens dispatches an 8-step window, not
        # the full num_scheduler_steps. Windows keep running at full size
        # while requests wait (disabling them under load serialized every
        # token on the wire — measured 4% of the raw decode rate on a
        # dispatch-latency-heavy link); deployments that want bounded
        # admission delay opt in via window_waiting_cap, which caps the
        # window at the first rung ≥ the configured value.
        rem = max(
            max(1, seq.stop.max_tokens - len(seq.output_ids)) for seq in batch
        )
        steps = next((w for w in self._window_rungs if w >= rem), self._window_rungs[-1])
        if self.sc.window_waiting_cap:
            cap_rung = next(
                (w for w in self._window_rungs if w >= self.sc.window_waiting_cap),
                self._window_rungs[-1],
            )
            if self.waiting:
                steps = min(steps, cap_rung)
            # ``rem`` is the MAX remaining across the batch, so one long
            # request would drag short-remaining batchmates through an
            # oversized window — every step past a batchmate's stop is
            # computed then trimmed. When any batchmate is within a rung of
            # finishing, clamp to the same cap rung: the short row wastes at
            # most cap_rung-1 trimmed steps instead of the full window.
            rem_min = min(
                max(1, seq.stop.max_tokens - len(seq.output_ids)) for seq in batch
            )
            if rem_min <= cap_rung:
                steps = min(steps, cap_rung)
        bs = self.mc.block_size
        # Reserve blocks for the whole window up front (+1 for the next
        # iteration's write slot, matching _ensure_block_capacity).
        for seq in batch:
            if seq.total_len + steps > self.mc.max_seq_len:
                # Window would run past max_seq_len (and past the per-seq
                # block-table capacity): let single-step finish it off.
                return False
            need = (seq.total_len + steps + bs - 1) // bs - len(seq.block_ids)
            if need > 0:
                try:
                    seq.block_ids.extend(self.allocator.allocate(need))
                except OutOfBlocksError:
                    return False

        width = self._width_bucket(max(len(seq.block_ids) for seq in batch))

        from dynamo_tpu.engine.sampling import pack_param_rows

        tokens = np.zeros((bucket,), dtype=np.int32)
        positions = np.zeros((bucket,), dtype=np.int32)
        active = np.zeros((bucket,), dtype=bool)
        temps, top_ks, top_ps = pack_param_rows([s.sampling for s in batch], bucket)
        for i, seq in enumerate(batch):
            tokens[i] = seq.all_ids[-1]
            positions[i] = seq.total_len - 1
            active[i] = True
        tables = self._decode_tables(batch, bucket, width)

        # Fused megakernel window: any batch with no per-row HOST extras
        # dispatches the whole N-step window as ONE pallas launch (grid =
        # steps × layers, token feedback through on-chip scratch) — the
        # per-launch dispatch tax is paid once per WINDOW and the weights/
        # prefix are read once, not ``steps`` times. Sampled rows ride via
        # host-precomputed per-step uniforms (no per-step host sync) with
        # the in-kernel top-k/top-p epilogue; guided rows ride the device
        # mask pool with the FSM advanced on-chip through the next-state
        # pool. Only penalties/logprobs/processors rows (and a grammar
        # working set outgrowing VMEM) keep the multi-launch decode_multi.
        any_guided = any(s.guided is not None for s in batch)
        fused_ok = (
            self._use_fused_window
            and not any(
                s.sampling.logits_processors
                or s.sampling.logprobs
                or s.sampling.top_logprobs
                or s.sampling.has_penalties
                for s in batch
            )
            and (not any_guided or self._fused_guided_ok())
        )
        if fused_ok:
            from dynamo_tpu.engine.attention import megakernel as _mk

            use_sampled = any_guided or any(
                s.sampling.temperature > 0 for s in batch
            )
            if any_guided:
                kind, key_t = "decode_fused_guided", (
                    steps, bucket, width, int(self.guided.pool.capacity)
                )
            elif use_sampled:
                kind, key_t = "decode_fused_sampled", (steps, bucket, width)
            else:
                kind, key_t = "decode_fused", (steps, bucket, width)
            new_exec = self.flight.record_exec(kind, key_t)
            launches0 = _mk.trace_launch_count() if new_exec else 0
            n0 = len(outputs)
            with StepTimer() as timer:
                self._record_host_gap()
                args = [
                    self.params, self.cache.k, self.cache.v,
                    jnp.asarray(tokens), jnp.asarray(positions), tables,
                    jnp.asarray(active),
                ]
                if use_sampled:
                    # One [steps, bucket] uniforms upload per window —
                    # threefry keys honor per-row seeds (make_row_keys), so
                    # seeded sampled rows stay reproducible on this path.
                    from dynamo_tpu.engine.sampling import make_window_uniforms

                    self._step_counter += 1
                    base_key = jax.random.fold_in(self._rng, self._step_counter)
                    seeds = np.zeros((bucket,), dtype=np.int32)
                    poss_out = np.zeros((bucket,), dtype=np.int32)
                    has_seed = np.zeros((bucket,), dtype=bool)
                    for i, seq in enumerate(batch):
                        if seq.sampling.seed is not None:
                            seeds[i] = seq.sampling.seed
                            poss_out[i] = len(seq.output_ids)
                            has_seed[i] = True
                    uniforms = make_window_uniforms(
                        base_key, jnp.asarray(seeds), jnp.asarray(poss_out),
                        jnp.asarray(has_seed), steps,
                    )
                    args += [
                        jnp.asarray(temps), jnp.asarray(top_ks),
                        jnp.asarray(top_ps), uniforms,
                    ]
                if any_guided:
                    rows0 = np.zeros((bucket,), dtype=np.int32)
                    for i, seq in enumerate(batch):
                        if seq.guided is not None:
                            rows0[i] = seq.guided.row_id
                    args += [
                        jnp.asarray(rows0), self.guided.pool.device(),
                        self.guided.pool.next_device(),
                    ]
                fjit = self._decode_fused_jits[(steps, use_sampled, any_guided)]
                toks_out, self.cache.k, self.cache.v = fjit(*args)
                self._note_decode_dispatch()
                sampled = np.asarray(toks_out)  # the one host sync per window

                for i, seq in enumerate(batch):
                    for s in range(steps):
                        if seq.state != SeqState.RUNNING:
                            break
                        self._append_token(seq, int(sampled[s, i]), outputs)
            if new_exec:
                # Launch sites traced into this window executable — the
                # amortization invariant (== 1) CI asserts.
                self.flight.record_window_launches(_mk.trace_launch_count() - launches0)
            self.flight.fused_windows_total += 1
            if use_sampled:
                self.flight.fused_sampled_windows_total += 1
            self.flight.record_step(
                "decode", timer.dur, len(outputs) - n0,
                # VMEM-resident window: weights and prefix stream from HBM
                # once per window, not once per step.
                kv_read_tokens=sum(s.total_len for s in batch),
                param_passes=1.0,
            )
            self._bill_step(timer.dur, [(s, "decode", steps, s.total_len) for s in batch])
            self.telemetry.observe("itl", timer.dur / max(steps, 1))
            return True

        self._step_counter += 1
        key = jax.random.fold_in(self._rng, self._step_counter)
        self.flight.record_exec("decode_multi", (steps, bucket, width))
        n0 = len(outputs)
        with StepTimer() as timer:
            self._record_host_gap()
            res = self._decode_multi_jits[steps](
                self.params, self.cache.k, self.cache.v,
                jnp.asarray(tokens), jnp.asarray(positions), tables,
                jnp.asarray(active), jnp.asarray(temps), jnp.asarray(top_ks),
                jnp.asarray(top_ps), key,
            )
            self._note_decode_dispatch()
            toks_out, self.cache.k, self.cache.v = self._consume_aux(res)
            sampled = np.asarray(toks_out)  # [steps, bucket] — the one host sync

            for i, seq in enumerate(batch):
                for s in range(steps):
                    if seq.state != SeqState.RUNNING:
                        break  # stopped mid-window; later tokens are trimmed
                    self._append_token(seq, int(sampled[s, i]), outputs)
        self.flight.record_step(
            "decode", timer.dur, len(outputs) - n0,
            kv_read_tokens=steps * sum(s.total_len for s in batch),
            # The fori_loop window re-streams the parameter set every step.
            param_passes=float(steps),
        )
        self._bill_step(timer.dur, [(s, "decode", steps, steps * s.total_len) for s in batch])
        self.telemetry.observe("itl", timer.dur / max(steps, 1))
        return True

    def _decode_spec_fused(self, batch: List[Sequence], bucket: int, outputs: List[tuple]) -> bool:
        """R whole speculative rounds in ONE pallas launch: per round the
        draft proposes γ sampled tokens, the target verifies the γ+1 chunk,
        and rejection sampling accepts a prefix + correction/bonus — with
        the accepted burst advancing on-chip cursors, so the host syncs
        once per WINDOW (two small int arrays) instead of 3×γ times. The
        output distribution equals sampling the target directly (same math
        as spec_decode.spec_verify, driven by host-precomputed uniforms);
        greedy rows reduce to exact argmax agreement. Returns False to fall
        back to the per-round spec path when blocks/limits don't allow the
        full window."""
        from dynamo_tpu.engine.sampling import pack_param_rows

        gamma = self.spec_gamma
        R = self._spec_rounds
        span = R * (gamma + 1)  # worst-case tokens appended per window
        bs = self.mc.block_size
        for seq in batch:
            if seq.total_len + span + 1 > self.mc.max_seq_len:
                return False
            need = (seq.total_len + span + 1 + bs - 1) // bs - len(seq.block_ids)
            if need > 0:
                try:
                    seq.block_ids.extend(self.allocator.allocate(need))
                except OutOfBlocksError:
                    return False
            if seq.total_len - seq.d_n > 2:
                # The in-kernel catch-up re-feeds exactly ONE token (the one
                # at pos-1), so the draft cache must already cover pos-2 —
                # absorb any longer lag with prefill-style chunks first.
                self._draft_catchup(seq, seq.all_ids, seq.total_len - 1)

        B = bucket
        width = self._width_bucket(max(len(seq.block_ids) for seq in batch))
        from dynamo_tpu.engine.attention import megakernel as _mk

        new_exec = self.flight.record_exec("spec_fused", (R, gamma, B, width))
        launches0 = _mk.trace_launch_count() if new_exec else 0
        self._break_decode_gap()
        n0 = len(outputs)
        t_round = time.perf_counter()
        tables = np.zeros((B, width), dtype=np.int32)
        tok0 = np.zeros((B,), dtype=np.int32)
        xprev0 = np.zeros((B,), dtype=np.int32)
        pos0 = np.zeros((B,), dtype=np.int32)
        act = np.zeros((B,), dtype=bool)
        temps, top_ks, top_ps = pack_param_rows([s.sampling for s in batch], B)
        for i, seq in enumerate(batch):
            tables[i, : len(seq.block_ids)] = seq.block_ids
            tok0[i] = seq.all_ids[-1]
            xprev0[i] = seq.all_ids[-2]  # total_len ≥ 2 by the time we decode
            pos0[i] = seq.total_len - 1
            act[i] = True
        # All of the window's draws — γ proposal draws, γ accept draws, and
        # the correction/bonus pick per (round, row) — upload as ONE
        # [R, B, 2γ+1] operand; nothing syncs until the window returns.
        self._step_counter += 1
        ukey = jax.random.fold_in(self._rng, self._step_counter)
        uniforms = jax.random.uniform(ukey, (R, B, 2 * gamma + 1))

        toks_out, accepted, self.cache.k, self.cache.v, self.draft_cache.k, self.draft_cache.v = (
            self._spec_fused_jit(
                self.params, self.draft_params,
                self.cache.k, self.cache.v,
                self.draft_cache.k, self.draft_cache.v,
                jnp.asarray(tok0), jnp.asarray(xprev0), jnp.asarray(pos0),
                jnp.asarray(tables), jnp.asarray(act),
                jnp.asarray(temps), jnp.asarray(top_ks), jnp.asarray(top_ps),
                uniforms,
            )
        )
        toks_h = np.asarray(toks_out)  # [R, B, γ+1] — the one sync
        acc_h = np.asarray(accepted)  # [R, B]
        if new_exec:
            self.flight.record_window_launches(_mk.trace_launch_count() - launches0)

        st = self.spec_stats
        for r in range(R):
            st.num_rounds += 1
            for i, seq in enumerate(batch):
                if seq.state != SeqState.RUNNING:
                    continue  # stopped in an earlier round; trailing rounds trim
                k = int(acc_h[r, i])
                st.record_round(k, gamma)
                old_total = seq.total_len
                for t in list(toks_h[r, i, :k]) + [int(toks_h[r, i, gamma])]:
                    if seq.state != SeqState.RUNNING:
                        break  # stop hit mid-burst; stale KV is position-masked
                    self._append_token(seq, int(t), outputs)
                # Draft rows are confirmed through position old_total-1+
                # min(k, γ-1)+... — the catch-up row plus the first
                # min(k, γ-1) proposal feeds (same ledger as _decode_spec).
                seq.d_n = old_total + min(k, gamma - 1)
        dur_round = time.perf_counter() - t_round
        self.flight.spec_fused_windows_total += 1
        self.flight.spec_fused_accepted_tokens_total += max(len(outputs) - n0, 0)
        self.flight.record_step(
            "spec", dur_round, len(outputs) - n0,
            kv_read_tokens=2 * R * sum(s.total_len for s in batch),
            # Both models' weights are VMEM-resident for the whole window.
            param_passes=1.0,
        )
        self._bill_step(
            dur_round, [(s, "decode", span, 2 * R * s.total_len) for s in batch]
        )
        self.telemetry.observe(
            "itl", dur_round / max(len(outputs) - n0, 1)
        )
        return True

    def _decode_spec(self, batch: List[Sequence], bucket: int, outputs: List[tuple]) -> bool:
        """One speculative round for the whole batch: the draft catches up on
        any unconsumed confirmed tokens and proposes γ SAMPLED tokens (one
        chunk pass + a γ-1 window), the target scores [last ; proposals] in
        ONE chunk pass, and rejection sampling (spec_decode.spec_verify)
        accepts a prefix + a correction/bonus token per row — the output
        distribution equals sampling the target directly; greedy rows reduce
        to exact argmax agreement. Returns False to fall back to normal
        decode when blocks/limits don't allow a full window."""
        gamma = self.spec_gamma
        S = gamma + 1
        bs = self.mc.block_size
        for seq in batch:
            if seq.total_len + S + 1 > self.mc.max_seq_len:
                return False
            need = (seq.total_len + S + 1 + bs - 1) // bs - len(seq.block_ids)
            if need > 0:
                try:
                    seq.block_ids.extend(self.allocator.allocate(need))
                except OutOfBlocksError:
                    return False
            if seq.total_len - seq.d_n > S:
                # Oversized lag (stretches of non-spec decode in mixed
                # batches, fallback rounds): absorb it with prefill-style
                # chunks so the row rejoins speculation instead of latching
                # the whole batch off spec forever.
                self._draft_catchup(seq, seq.all_ids, seq.total_len - 1)

        from dynamo_tpu.engine.sampling import pack_param_rows

        B = bucket
        width = self._width_bucket(max(len(seq.block_ids) for seq in batch))
        self.flight.record_exec("spec", (gamma, B, width))
        self._break_decode_gap()
        n0 = len(outputs)
        t_round = time.perf_counter()
        tables = np.zeros((B, width), dtype=np.int32)
        d_toks = np.zeros((B, S), dtype=np.int32)
        d_pos0 = np.zeros((B,), dtype=np.int32)
        d_valid = np.zeros((B,), dtype=np.int32)
        temps, top_ks, top_ps = pack_param_rows([s.sampling for s in batch], B)
        for i, seq in enumerate(batch):
            lag = seq.total_len - seq.d_n  # ≥ 1: the last token is never materialized
            d_toks[i, :lag] = seq.all_ids[seq.d_n :]
            d_pos0[i] = seq.d_n
            d_valid[i] = lag
            tables[i, : len(seq.block_ids)] = seq.block_ids
        tables_j = jnp.asarray(tables)
        temps_j, tks_j, tps_j = jnp.asarray(temps), jnp.asarray(top_ks), jnp.asarray(top_ps)

        # Draft: catch-up chunk + SAMPLED first proposal (+ its dist), then
        # γ-1 sampled window steps with per-step logits.
        self._step_counter += 1
        key = jax.random.fold_in(self._rng, self._step_counter)
        tok1, lg1, self.draft_cache.k, self.draft_cache.v = self._d_chunk_sample_jit(
            self.draft_params, self.draft_cache.k, self.draft_cache.v,
            jnp.asarray(d_toks), jnp.asarray(d_pos0), jnp.asarray(d_valid), tables_j,
            temps_j, tks_j, tps_j, key,
        )
        tok1_h = np.asarray(tok1)
        proposals = np.zeros((B, gamma), dtype=np.int32)
        poss = np.zeros((B,), dtype=np.int32)
        act = np.zeros((B,), dtype=bool)
        for i, seq in enumerate(batch):
            proposals[i, 0] = tok1_h[i]
            poss[i] = seq.total_len
            act[i] = True
        if gamma > 1:
            self._step_counter += 1
            key2 = jax.random.fold_in(self._rng, self._step_counter)
            toks_out, lg_steps, self.draft_cache.k, self.draft_cache.v = self._d_multi_jit(
                self.draft_params, self.draft_cache.k, self.draft_cache.v,
                tok1, jnp.asarray(poss), tables_j, jnp.asarray(act),
                temps_j, tks_j, tps_j, key2,
            )
            proposals[:, 1:] = np.asarray(toks_out).T
            draft_logits = jnp.concatenate(
                [lg1[:, None], jnp.transpose(lg_steps, (1, 0, 2))], axis=1
            )  # [B, γ, V]
        else:
            draft_logits = lg1[:, None]

        # Target: score [last_confirmed ; proposals] in one chunk pass.
        t_toks = np.zeros((B, S), dtype=np.int32)
        t_pos0 = np.zeros((B,), dtype=np.int32)
        t_valid = np.zeros((B,), dtype=np.int32)
        for i, seq in enumerate(batch):
            t_toks[i, 0] = seq.all_ids[-1]
            t_toks[i, 1:] = proposals[i]
            t_pos0[i] = seq.total_len - 1
            t_valid[i] = S
        t_logits, self.cache.k, self.cache.v = self._consume_aux(
            self._t_chunk_jit(
                self.params, self.cache.k, self.cache.v,
                jnp.asarray(t_toks), jnp.asarray(t_pos0), jnp.asarray(t_valid), tables_j,
            )
        )

        # Rejection-sampling verification (greedy rows: exact argmax check).
        self._step_counter += 1
        vkey = jax.random.fold_in(self._rng, self._step_counter)
        accepted, next_tok = self._spec_verify_jit(
            draft_logits, t_logits, jnp.asarray(proposals), temps_j, tks_j, tps_j, vkey
        )
        accepted_h = np.asarray(accepted)
        next_h = np.asarray(next_tok)

        st = self.spec_stats
        st.num_rounds += 1
        for i, seq in enumerate(batch):
            if seq.state != SeqState.RUNNING:
                continue
            k = int(accepted_h[i])
            st.record_round(k, gamma)
            old_total = seq.total_len
            for t in list(proposals[i, :k]) + [int(next_h[i])]:
                if seq.state != SeqState.RUNNING:
                    break  # stop hit mid-chunk; stale KV rows are position-masked
                self._append_token(seq, int(t), outputs)
            # Draft-coherent prefix: catch-up reached old_total-1; proposal
            # inputs covered positions old_total..old_total+γ-2, of which the
            # first min(k, γ-1) carry accepted (confirmed) tokens.
            seq.d_n = old_total + min(k, gamma - 1)
        dur_round = time.perf_counter() - t_round
        self.flight.record_step(
            "spec", dur_round, len(outputs) - n0,
            kv_read_tokens=2 * sum(s.total_len for s in batch),
        )
        self._bill_step(dur_round, [(s, "decode", S, 2 * s.total_len) for s in batch])
        return True

    # --- disaggregation support ---------------------------------------------
    def _inject_prefilled(self, seq: Sequence, outputs: List[tuple]) -> bool:
        """Decode-role admission: KV arrived from a prefill worker — scatter
        it into fresh blocks and enter decode directly (no prefill compute).
        ``prefilled["blocks"]`` carries host numpy block pairs (wire path);
        ``prefilled["device_blocks"]`` carries stacked device arrays (the
        device-native path: in-process handoff or transfer-server pull)."""
        from dynamo_tpu.llm.block_manager.transfer import scatter_blocks, scatter_blocks_device

        bs = self.mc.block_size
        data = seq.prefilled
        # Token-boundary splits (elastic disagg): ``prefill_len`` marks how
        # many prompt tokens the transferred KV covers. Absent or >= the
        # prompt, this is the classic full-prefill handoff.
        n_pref = min(int(data.get("prefill_len") or len(seq.prompt)), len(seq.prompt))
        full = n_pref >= len(seq.prompt)
        n_blocks = (len(seq.prompt) + 1 + bs - 1) // bs
        seq.block_ids = self.allocator.allocate(n_blocks)  # raises → retried next step
        self._accrue_kv(seq)  # decode leg's block-seconds clock starts at injection
        if "device_blocks" in data:
            k_stack, v_stack = data["device_blocks"]
            scatter_blocks_device(self.cache, seq.block_ids[: k_stack.shape[1]], k_stack, v_stack)
        else:
            for bid, (k_np, v_np) in zip(seq.block_ids, data["blocks"]):
                scatter_blocks(self.cache, bid, k_np, v_np)
        seq.num_computed = n_pref
        if seq.admitted_ts is None:
            seq.admitted_ts = time.monotonic()
        # Spec decode: the draft cache has nothing for remotely-prefilled KV —
        # compute the draft's own prompt KV before the row joins spec rounds.
        self._draft_catchup_prefill(seq, seq.prompt)
        if self.sc.enable_prefix_caching:
            seq.block_hashes = extend_block_hashes([], seq.prompt, bs)
            self._register_full_blocks(seq)
        if not full:
            # Partial injection: the split request continues as a normal
            # chunked prefill from position n_pref — the REAL first token
            # is sampled at prompt completion (the prefill leg's capped
            # first_token is a placeholder and is discarded), so the
            # output stream is bit-identical to single-worker serving.
            seq.state = SeqState.PREFILL
            seq.prefilled = None
            self._trace_event(
                seq, "disagg_inject", blocks=len(seq.block_ids),
                device_native="device_blocks" in data,
                partial=True, prefill_len=n_pref,
            )
            return False
        seq.state = SeqState.RUNNING
        seq.first_token_ts = time.monotonic()
        self.running.append(seq)
        self._trace_event(
            seq, "disagg_inject", blocks=len(seq.block_ids),
            device_native="device_blocks" in data,
        )
        self._append_token(seq, int(data["first_token"]), outputs)
        seq.prefilled = None  # consumed — a later preemption resumes via recompute
        return True

    def take_export(self, request_id: str):
        """Prefill-role export: hand over the finished sequence's blocks
        (k/v numpy per block) and release them. Returns (blocks, hashes,
        prompt_len) or None."""
        from dynamo_tpu.llm.block_manager.transfer import gather_blocks

        seq = self._pending_exports.pop(request_id, None)
        self._export_deadline.pop(request_id, None)
        if seq is None:
            return None
        data = [gather_blocks(self.cache, bid) for bid in seq.block_ids]
        self.allocator.release(seq.block_ids)
        seq.block_ids = []
        return data, seq.block_hashes, len(seq.prompt)

    def take_export_device(self, request_id: str):
        """Device-native export: stack the sequence's blocks into fresh
        device arrays (one fused gather, no host round-trip) and release
        them. Returns ((k_stack [L,n,BS,KVH,HD], v_stack|None), hashes,
        prompt_len) or None. The stack is independent of the cache, so it
        can await a remote pull while the blocks are reused."""
        from dynamo_tpu.llm.block_manager.transfer import gather_blocks_device

        seq = self._pending_exports.pop(request_id, None)
        self._export_deadline.pop(request_id, None)
        if seq is None:
            return None
        k_stack, v_stack = gather_blocks_device(self.cache, seq.block_ids)
        self.allocator.release(seq.block_ids)
        seq.block_ids = []
        return (k_stack, v_stack), seq.block_hashes, len(seq.prompt)

    def expire_exports(self, now: Optional[float] = None) -> int:
        """Reclaim exports nobody pulled within export_ttl_s. Returns count."""
        now = time.monotonic() if now is None else now
        expired = [rid for rid, dl in self._export_deadline.items() if dl < now]
        for rid in expired:
            seq = self._pending_exports.pop(rid, None)
            self._export_deadline.pop(rid, None)
            if seq is not None:
                self.allocator.release(seq.block_ids)
                seq.block_ids = []
        return len(expired)

    # --- helpers ------------------------------------------------------------
    def _trace_event(self, seq: Sequence, name: str, **attrs) -> None:
        """Lifecycle event on the request's trace (no-op when unsampled —
        ``seq.trace`` is only set for sampled requests, so the hot path
        pays one None check)."""
        if seq.trace is None:
            return
        self.tracer.event(
            name, seq.trace[0], parent_id=seq.trace[1], service="scheduler",
            request_id=seq.request_id, **attrs,
        )

    def attach_kvbm(self, kvbm) -> None:
        """Enable tiered offload/onboard (KVBM G2/G3) for this scheduler."""
        self.kvbm = kvbm

    def _copy_block(self, src: int, dst: int) -> None:
        """Device-side block duplication (the COW copy). One warmed
        executable; src/dst ride as traced scalars."""
        self.flight.record_exec("kv_block_copy", ())
        self.cache.k, self.cache.v = self._kv_copy_jit(
            self.cache.k, self.cache.v, jnp.int32(src), jnp.int32(dst)
        )

    def _match_prefix_tiers(self, seq: Sequence) -> List[int]:
        """G1 match, extended through G2/G3 onboarding when KVBM is attached.
        Onboarded blocks count as hits (reuse, not recompute) — the
        allocator's G1 walk saw them as misses, so the counters are
        re-attributed here; ``prefix_onboard_total`` tracks the subset that
        crossed a tier boundary back into HBM."""
        if self.kvbm is None:
            return self.allocator.match_prefix(seq.block_hashes)
        match = self.kvbm.match_prefix(seq.block_hashes)
        blocks = self.kvbm.onboard(match, seq.block_hashes)
        onboarded = len(blocks) - len(match.g1_blocks)
        if onboarded > 0:
            self.prefix_onboard_total += onboarded
            self.allocator.hit_blocks_total += onboarded
            self.allocator.miss_blocks_total -= onboarded
        return blocks

    def _block_table(self, seq: Sequence) -> jnp.ndarray:
        table = np.zeros((self.max_blocks_per_seq,), dtype=np.int32)
        table[: len(seq.block_ids)] = seq.block_ids
        return jnp.asarray(table)

    def _consume_aux(self, res):
        """Strip the moe-stats aux (when enabled) from a jitted step's result
        tuple. The aux scalars stay on device — forcing them here would add a
        host sync per step on a path that otherwise syncs once; metrics()
        drains them in a batch."""
        if not self._moe_stats:
            return res
        *main, aux = res
        self._pending_aux.append((aux["moe_dropped"], aux["moe_assignments"]))
        if len(self._pending_aux) >= 256:
            self._drain_aux()
        return tuple(main)

    def _drain_aux(self) -> None:
        if not self._pending_aux:
            return
        with self._aux_lock:
            pend, self._pending_aux = self._pending_aux, []
            vals = jax.device_get(pend)  # one transfer for the whole batch
            self._moe_dropped_total += int(sum(int(d) for d, _ in vals))
            self._moe_assignments_total += int(sum(int(a) for _, a in vals))

    def _prefill_mm_jit(self):
        """Lazy jit of the multimodal prefill variant (feature injection)."""
        if self._mm_jit is None:
            from dynamo_tpu.engine.models import get_module

            model = get_module(self.mc)
            uf = self._use_flash_prefill

            self._mm_jit = jax.jit(
                lambda p, k, v, t, vl, cl, bt, hp, mf, ml: model.prefill(
                    p, self.mc, k, v, t, vl, cl, bt,
                    use_flash=uf, has_prefix=hp, mm_feats=mf, mm_len=ml,
                    moe_stats=self._moe_stats,
                ),
                donate_argnums=(1, 2),
                static_argnums=(7,),
            )
        return self._mm_jit

    def _prefill_table(self, seq: Sequence) -> jnp.ndarray:
        """Prefill block table bucketed to a power-of-two width covering the
        sequence's blocks — NOT padded to max_blocks_per_seq. The prefill
        prefix gather/mask is O(width·block_size), so a 2K prompt must not
        pay for a 128K max_seq_len (measured: the dominant prefill cost at
        1B on v5e before this). Rung widths (see width_rungs) bound the
        executable count at 2·log2(max_blocks) variants per prefill
        bucket."""
        w = max(16, width_bucket(len(seq.block_ids), self.max_blocks_per_seq))
        table = np.zeros((w,), dtype=np.int32)
        table[: len(seq.block_ids)] = seq.block_ids
        return jnp.asarray(table)

    def _ensure_block_capacity(self, seq: Sequence) -> None:
        """Grow the block table if the *next* token would overflow it.
        On OutOfBlocks, preempt the newest other running sequence (recompute
        preemption) and retry; only when no victim exists does the sequence
        finish with "length"."""
        bs = self.mc.block_size
        while seq.total_len + 1 > len(seq.block_ids) * bs:
            try:
                seq.block_ids.extend(self.allocator.allocate(1))
                return
            except OutOfBlocksError:
                if self.sc.enable_preemption and self._preempt_for(seq):
                    continue  # victim freed blocks — retry
                # Out of memory, nobody to evict: finish with "length".
                seq.aborted = True
                seq.abort_reason = "length"
                logger.warning("seq %s out of KV blocks at len %d", seq.request_id, seq.total_len)
                return

    def _preempt_for(self, needy: Sequence) -> bool:
        """Evict the newest other running sequence: release its blocks and
        send it back to the waiting queue for recompute (ref: vLLM recompute
        preemption). Returns True if a victim was preempted."""
        candidates = [s for s in self.running if s is not needy and s.state == SeqState.RUNNING]
        if not candidates:
            return False
        victim = max(candidates, key=lambda s: s.arrival_ts)
        self.running.remove(victim)
        # Close the victim's KV accrual at the true release point: it holds
        # no blocks while waiting for recompute, so its clock stops here.
        self._accrue_kv(victim)
        victim.kv_ts = None
        self.allocator.release(victim.block_ids)
        victim.block_ids = []
        victim.block_hashes = []
        victim.num_cached_blocks = 0
        victim.num_computed = 0
        victim.d_n = 0  # draft cache rows are gone with the blocks
        # Recompute everything up to (not including) the last token; the
        # last token re-enters through the decode step on resume.
        victim.resume_tokens = list(victim.all_ids[:-1])
        victim.state = SeqState.WAITING
        victim.preemptions += 1
        self.preempt_total += 1
        self.waiting.insert(0, victim)
        self._trace_event(
            victim, "preempted", total_len=victim.total_len, for_request=needy.request_id
        )
        logger.info("preempted %s (len %d) to free blocks", victim.request_id, victim.total_len)
        return True

    def _apply_penalties(self, batch: List[Sequence], bucket: int, logits: jax.Array) -> jax.Array:
        """Apply frequency/presence penalties for the rows that request them
        (sampling.apply_penalties). History width buckets to powers of two so
        the executable count stays bounded as outputs grow."""
        from dynamo_tpu.engine.sampling import apply_penalties

        H = 16
        longest = max(
            (len(s.output_ids) for s in batch if s.sampling.has_penalties), default=0
        )
        while H < longest:
            H *= 2
        hist = np.zeros((bucket, H), dtype=np.int32)
        hist_len = np.zeros((bucket,), dtype=np.int32)
        freq = np.zeros((bucket,), dtype=np.float32)
        pres = np.zeros((bucket,), dtype=np.float32)
        for i, seq in enumerate(batch):
            if not seq.sampling.has_penalties or not seq.output_ids:
                continue
            n = len(seq.output_ids)
            hist[i, :n] = seq.output_ids
            hist_len[i] = n
            freq[i] = seq.sampling.frequency_penalty
            pres[i] = seq.sampling.presence_penalty
        return apply_penalties(
            logits, jnp.asarray(hist), jnp.asarray(hist_len), jnp.asarray(freq), jnp.asarray(pres)
        )

    def _row_key(self, seq: Sequence) -> jax.Array:
        """Per-row PRNG key. Seeded requests fold the per-request position
        (same seed + prompt ⇒ same samples, whatever the batch around them);
        unseeded rows fold the global step counter."""
        if seq.sampling.seed is not None:
            return jax.random.fold_in(jax.random.PRNGKey(seq.sampling.seed), len(seq.output_ids))
        return jax.random.fold_in(self._rng, self._step_counter)

    def _sample_one(self, seq: Sequence, logits: jax.Array) -> int:
        self._step_counter += 1
        s = seq.sampling
        if s.logits_processors:
            from dynamo_tpu.logits_processing import apply_chain

            logits = apply_chain(s.logits_processors, seq.output_ids, logits)
        if seq.guided is not None:
            # First token after prefill: same fused mask+sample executable
            # as the batched path at bucket 1.
            pool = self.guided.pool.device()
            self.flight.record_exec("guided_sample", (1, int(pool.shape[0])))
            tok = self._guided_sample_jit(
                logits[None, :], pool,
                jnp.asarray([[s.top_k], [seq.guided.row_id]], dtype=jnp.int32),
                jnp.asarray([s.temperature], dtype=jnp.float32),
                jnp.asarray([s.top_p], dtype=jnp.float32),
                self._row_key(seq),
            )
        else:
            tok = self._sample_jit(
                logits[None, :],
                jnp.asarray([s.temperature], dtype=jnp.float32),
                jnp.asarray([s.top_k], dtype=jnp.int32),
                jnp.asarray([s.top_p], dtype=jnp.float32),
                self._row_key(seq),
            )
        token = int(np.asarray(tok)[0])
        if s.top_logprobs:
            # First token's alternatives: same op group as the batched
            # top-k path (guided rows already applied their mask above via
            # the fused sampler; these logprobs are of the raw logits the
            # single-row sampler saw).
            from dynamo_tpu.engine.sampling import compute_topk_logprobs

            chosen, ids, lps = jax.device_get(
                compute_topk_logprobs(logits[None, :], jnp.asarray([token]))
            )
            seq._pending_logprob = float(chosen[0])
            k = min(s.top_logprobs, ids.shape[1])
            seq._pending_top_logprobs = [
                (int(ids[0, j]), float(lps[0, j])) for j in range(k)
            ]
        elif s.logprobs:
            from dynamo_tpu.engine.sampling import compute_logprobs

            seq._pending_logprob = float(
                np.asarray(compute_logprobs(logits[None, :], jnp.asarray([token])))[0]
            )
        return token

    def _append_token(
        self,
        seq: Sequence,
        token: int,
        outputs: List[tuple],
        logprob: Optional[float] = None,
        top_logprobs: Optional[list] = None,
    ) -> None:
        if logprob is None:
            logprob = getattr(seq, "_pending_logprob", None)
            seq._pending_logprob = None
        if top_logprobs is None:
            top_logprobs = getattr(seq, "_pending_top_logprobs", None)
            seq._pending_top_logprobs = None
        seq.output_ids.append(token)
        if seq.guided is not None:
            # Host-side FSM advance: one next-state table lookup on the
            # token the step already read back — no extra device sync.
            seq.guided.advance(token)
        # First token carries the request's queue time (arrival → admission)
        # and its prefix-cache reuse (skipped prompt tokens).
        queue_s = None
        cached = None
        if len(seq.output_ids) == 1:
            if seq.admitted_ts is not None:
                queue_s = max(0.0, seq.admitted_ts - seq.arrival_ts)
                self.queue_wait_s_total += queue_s
                self.telemetry.observe("queue_wait", queue_s)
                if seq.first_token_ts is not None:
                    self.prefill_wait_s_total += max(0.0, seq.first_token_ts - seq.admitted_ts)
            self.first_tokens_total += 1
            cached = seq.cached_tokens
            ttft_s = max(0.0, (seq.first_token_ts or time.monotonic()) - seq.arrival_ts)
            self.telemetry.observe("ttft", ttft_s)
            self._trace_event(
                seq, "first_token",
                ttft_s=round(time.monotonic() - seq.arrival_ts, 6),
                cached_tokens=seq.cached_tokens,
            )
        reason = self._check_stop(seq, token)
        if reason is not None:
            # Token that triggered 'stop' is still emitted (backend strips).
            outputs.append(
                (seq, StepOutput(token_id=token, finished=True, finish_reason=reason,
                                 logprob=logprob, queue_s=queue_s, cached_tokens=cached,
                                 top_logprobs=top_logprobs))
            )
            self._finish(seq, reason, outputs, emit=False)
        else:
            outputs.append(
                (seq, StepOutput(token_id=token, logprob=logprob, queue_s=queue_s,
                                 cached_tokens=cached, top_logprobs=top_logprobs))
            )

    def _check_stop(self, seq: Sequence, token: int) -> Optional[str]:
        if seq.guided is not None and seq.guided.exhausted:
            # The FSM accepts and only EOS remains (or the cursor is done):
            # force-finish instead of burning a step to sample the EOS.
            return "stop"
        n_out = len(seq.output_ids)
        if n_out >= seq.stop.min_tokens:
            if not seq.stop.ignore_eos and token in seq.eos_token_ids:
                return "stop"
            if token in seq.stop.stop_token_ids:
                return "stop"
        if n_out >= seq.stop.max_tokens:
            return "length"
        if seq.total_len >= self.mc.max_seq_len:
            return "length"
        return None

    def _register_full_blocks(self, seq: Sequence) -> None:
        """Publish completed prompt blocks for prefix reuse. Called after
        EVERY prefill chunk, not just at prompt completion: a burst of
        same-prefix requests then shares KV mid-prefill — the second
        request's first touch matches the chunks the first has already
        computed instead of recomputing the whole prompt in parallel."""
        if not self.sc.enable_prefix_caching or not seq.block_hashes:
            return
        bs = self.mc.block_size
        n_full = min(seq.num_computed, len(seq.prompt)) // bs
        n_full = min(n_full, len(seq.block_hashes), len(seq.block_ids))
        if n_full > seq.num_cached_blocks:
            self.allocator.register_hashes(seq.block_ids[:n_full], seq.block_hashes[:n_full])

    # --- tenant capacity billing (runtime/ledger.py) ------------------------

    def _measured_mult(self) -> float:
        """Wall→device-seconds multiplier from the continuous profiler:
        ``measured_modeled_mfu_ratio`` is modeled/measured (= step_s /
        device_s), so device-seconds per wall second is its inverse.
        Clamped to a sane band so one noisy window can't distort bills;
        1.0 until a measured window lands."""
        snap = self.flight.measured_snapshot()
        if not snap:
            return 1.0
        r = float(snap.get("measured_modeled_mfu_ratio") or 0.0)
        if r <= 0.0:
            return 1.0
        return min(4.0, max(0.25, 1.0 / r))

    def _bill_step(self, dur_s: float, rows: List[tuple]) -> None:
        """Charge one step's wall time to its rows' bills. ``rows`` is
        [(seq, phase, tokens, kv_read_tokens)]; each row's share is its
        MARGINAL roofline weight from the step cost model (its flops +
        its KV traffic; the parameter read is batch-shared, so it's
        excluded from attribution), normalized so shares sum to dur_s
        exactly — per-step conservation — then scaled to device-seconds
        by the measured/modeled ratio when the continuous profiler has a
        live window. Also the per-step KV block-second accrual point."""
        if dur_s <= 0.0 or not rows:
            return
        cm = self.flight.cost_model
        weights: List[float] = []
        flops_rows: List[float] = []
        for _seq, _phase, tokens, kv_read in rows:
            if cm is not None:
                fl = cm.flops_per_token * tokens
                by = (kv_read * cm.kv_read_factor + tokens) * cm.kv_bytes_per_token
                w = max(fl / cm.peak_flops, by / cm.peak_bw)
            else:
                fl = 0.0
                w = float(max(tokens, 1))
            weights.append(max(w, 1e-12))
            flops_rows.append(fl)
        scale = dur_s * self._measured_mult() / sum(weights)
        now = time.monotonic()
        for (seq, phase, _tokens, _kv), w, fl in zip(rows, weights, flops_rows):
            if phase == "prefill":
                seq.bill_prefill_s += w * scale
            else:
                seq.bill_decode_s += w * scale
            seq.bill_flops += fl
            self._accrue_kv(seq, now)

    def _accrue_kv(self, seq: Sequence, now: Optional[float] = None) -> None:
        """Lazy KV block-second accrual: charge the blocks held since the
        last accrual point (step billing, preemption, finish). COW-shared
        prefix blocks sit in ``block_ids`` like any other, so every holder
        pays for the blocks it pins. Block-count growth mid-interval is
        charged at the new count for ≤ one step — negligible and cheap."""
        if now is None:
            now = time.monotonic()
        if seq.kv_ts is not None:
            seq.bill_kv_block_s += len(seq.block_ids) * (now - seq.kv_ts)
        seq.kv_ts = now if seq.block_ids else None

    def _emit_bill(self, seq: Sequence, reason: str,
                   ttft_s: Optional[float] = None,
                   tpot_s: Optional[float] = None) -> None:
        """Emit the request's RequestBill into the tenant ledger — the ONE
        choke point (finish, timeout eviction, abort reap), guarded so a
        request can never bill twice on one worker. A migrated or disagg
        request's other leg bills on ITS worker's ledger, so legs sum
        across the fleet without double-billing. Must run while the
        sequence still holds its blocks (the final KV accrual)."""
        if seq.billed:
            return
        seq.billed = True
        self._accrue_kv(seq)
        queue_end = seq.admitted_ts if seq.admitted_ts is not None else time.monotonic()
        self.ledger.record(RequestBill(
            tenant=seq.tenant,
            request_id=seq.request_id,
            queue_s=max(0.0, queue_end - seq.arrival_ts),
            prefill_device_s=seq.bill_prefill_s,
            decode_device_s=seq.bill_decode_s,
            flops=seq.bill_flops,
            output_tokens=len(seq.output_ids),
            kv_block_s=seq.bill_kv_block_s,
            finish_reason=reason,
            ttft_s=ttft_s,
            tpot_s=tpot_s,
        ))

    def _finish(self, seq: Sequence, reason: str, outputs: List[tuple], emit: bool = True) -> None:
        if seq in self.running:
            self.running.remove(seq)
        seq.state = SeqState.FINISHED
        # Request-level telemetry + the SLO/goodput verdict. Cancelled and
        # errored requests are not judged (the client walked away; counting
        # them as violations would let an abort storm fake an SLO breach).
        ttft_s = tpot_s = None
        if seq.first_token_ts is not None and reason in ("stop", "length"):
            now = time.monotonic()
            ttft_s = max(0.0, seq.first_token_ts - seq.arrival_ts)
            n_out = len(seq.output_ids)
            if n_out > 1:
                tpot_s = max(0.0, now - seq.first_token_ts) / (n_out - 1)
                self.telemetry.observe("tpot", tpot_s)
            self.slo.judge(ttft_s, tpot_s, n_out)
        # Tenant ledger: the request's capacity bill, emitted while blocks
        # are still held so the KV accrual closes at the true release point.
        self._emit_bill(seq, reason, ttft_s=ttft_s, tpot_s=tpot_s)
        self._trace_event(
            seq, "finish", reason=reason, output_tokens=len(seq.output_ids),
            preemptions=seq.preemptions,
        )
        # Extend hashes over generated tokens so completed output blocks are
        # reusable too (multi-turn: next request's prompt includes them).
        # mm sequences never register: placeholder ids don't hash the image.
        if self.sc.enable_prefix_caching and reason != "cancelled" and seq.mm_features is None:
            bs = self.mc.block_size
            seq.block_hashes = extend_block_hashes(seq.block_hashes, seq.all_ids, bs)
            n_full = len(seq.all_ids) // bs
            self.allocator.register_hashes(seq.block_ids[:n_full], seq.block_hashes[:n_full])
        if seq.keep_blocks_on_finish and reason not in ("cancelled", "timeout"):
            # Disagg prefill role: hold blocks until the decode worker pulls
            # them (take_export); refs stay live so eviction can't touch them.
            self._pending_exports[seq.request_id] = seq
            self._export_deadline[seq.request_id] = time.monotonic() + self.sc.export_ttl_s
        else:
            self.allocator.release(seq.block_ids)
            seq.block_ids = []
        if emit:
            outputs.append((seq, StepOutput(token_id=-1, finished=True, finish_reason=reason)))
        self.by_id.pop(seq.request_id, None)
