"""Device meshes + partition specs for the engine.

The reference delegates TP/PP/EP to engine-internal NCCL (SURVEY.md §2e);
here parallelism is native: a ``jax.sharding.Mesh`` with named axes and
GSPMD-propagated shardings. XLA inserts the collectives (all-reduce after
row-parallel matmuls, etc.) over ICI — no hand-written comm code in the
model.

Axis convention:
- ``dp``   — data parallel (batch) across chips within one engine instance.
- ``pp``   — pipeline parallel: stacked layer axis split into stages
             (microbatched ppermute pipeline; pipeline_parallel.py).
- ``tp``   — tensor parallel: attention heads + MLP hidden dim.
- ``ep``   — expert parallel (MoE models).
- ``sp``   — sequence/context parallel (ring attention, long prefill).

Weight layout (megatron-style column→row pairs so each layer needs exactly
one all-reduce per block):
- wq/wk/wv, w_gate/w_up: shard output dim over tp (column-parallel).
- wo, w_down:            shard input dim over tp (row-parallel).
- KV cache:              shard kv_heads over tp.
- embed/lm_head:         shard vocab over tp.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class ParallelConfig:
    tp: int = 1
    dp: int = 1
    ep: int = 1
    sp: int = 1
    pp: int = 1

    @property
    def total(self) -> int:
        return self.tp * self.dp * self.ep * self.sp * self.pp


def build_mesh(parallel: ParallelConfig, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = parallel.total
    if len(devices) < n:
        raise ValueError(f"need {n} devices for {parallel}, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(
        parallel.dp, parallel.pp, parallel.sp, parallel.ep, parallel.tp
    )
    return Mesh(arr, axis_names=("dp", "pp", "sp", "ep", "tp"))


def param_specs(tie_word_embeddings: bool, num_experts: int = 0, pp: bool = False) -> dict:
    """PartitionSpec pytree matching llama.init_params structure.

    MoE: experts shard over ``ep`` and the FFN hidden dim over ``tp`` —
    the wide-EP layout (each chip holds E/ep experts, each split tp-ways).
    With ``pp=True`` the stacked layer axis additionally shards over ``pp``
    (each pipeline stage holds L/pp contiguous layers)."""
    lax_ = "pp" if pp else None  # leading (stacked-layer) axis
    specs = {
        "embed": P("tp", None),
        "final_norm": P(None),
        "layers": {
            "attn_norm": P(lax_, None),
            "mlp_norm": P(lax_, None),
            "wq": P(lax_, None, "tp"),
            "wk": P(lax_, None, "tp"),
            "wv": P(lax_, None, "tp"),
            "wo": P(lax_, "tp", None),
        },
    }
    if num_experts == 0:
        specs["layers"].update(
            w_gate=P(lax_, None, "tp"),
            w_up=P(lax_, None, "tp"),
            w_down=P(lax_, "tp", None),
        )
    else:
        specs["layers"].update(
            router=P(lax_, None, None),
            w_gate=P(lax_, "ep", None, "tp"),
            w_up=P(lax_, "ep", None, "tp"),
            w_down=P(lax_, "ep", "tp", None),
        )
    if not tie_word_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def kv_cache_spec(num_kv_heads: int = 0, tp_size: int = 1, pp: bool = False) -> P:
    """[L, N, BS, KVH, HD] — shard kv heads over tp when divisible; when
    tp > kv_heads (e.g. 70B kv_heads=8 on tp=16) the cache replicates and the
    duplicated-KV-head handling lives in the attention partitioning. With
    ``pp=True`` the layer axis shards over pp alongside the layer stack."""
    lax_ = "pp" if pp else None
    if tp_size > 1 and num_kv_heads % tp_size == 0:
        return P(lax_, None, None, "tp", None)
    return P(lax_, None, None, None, None)


def shard_params(params, mesh: Mesh, tie_word_embeddings: bool, num_experts: int = 0, pp: bool = False):
    specs = param_specs(tie_word_embeddings, num_experts, pp=pp)

    def _put(x, s):
        from dynamo_tpu.engine.quant import QuantW

        if isinstance(x, QuantW):
            # int8 codes take the weight's spec; the per-output-channel
            # scale [..., 1, out] keeps only the spec's LAST axis (its
            # other axes are size-1 or layer-stacked and must not shard a
            # unit dimension).
            s_scale = P(*([None] * (len(s) - 1) + [s[-1]])) if len(s) else s
            return QuantW(
                jax.device_put(x.q, NamedSharding(mesh, s)),
                jax.device_put(x.scale, NamedSharding(mesh, s_scale)),
            )
        return jax.device_put(x, NamedSharding(mesh, s))

    from dynamo_tpu.engine.quant import QuantW

    return jax.tree.map(
        _put, params, specs,
        is_leaf=lambda x: isinstance(x, (jax.Array, QuantW)),
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("dp"))
