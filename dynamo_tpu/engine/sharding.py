"""Device meshes + partition specs for the engine.

The reference delegates TP/PP/EP to engine-internal NCCL (SURVEY.md §2e);
here parallelism is native: a ``jax.sharding.Mesh`` with named axes and
GSPMD-propagated shardings. XLA inserts the collectives (all-reduce after
row-parallel matmuls, etc.) over ICI — no hand-written comm code in the
model.

Axis convention:
- ``dp``   — data parallel (batch) across chips within one engine instance.
- ``tp``   — tensor parallel: attention heads + MLP hidden dim.
- ``ep``   — expert parallel (MoE models).
- ``sp``   — sequence/context parallel (ring attention, long prefill).

Weight layout (megatron-style column→row pairs so each layer needs exactly
one all-reduce per block):
- wq/wk/wv, w_gate/w_up: shard output dim over tp (column-parallel).
- wo, w_down:            shard input dim over tp (row-parallel).
- KV cache:              shard kv_heads over tp.
- embed/lm_head:         shard vocab over tp.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class ParallelConfig:
    tp: int = 1
    dp: int = 1
    ep: int = 1
    sp: int = 1

    @property
    def total(self) -> int:
        return self.tp * self.dp * self.ep * self.sp


def build_mesh(parallel: ParallelConfig, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = parallel.total
    if len(devices) < n:
        raise ValueError(f"need {n} devices for {parallel}, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(parallel.dp, parallel.sp, parallel.ep, parallel.tp)
    return Mesh(arr, axis_names=("dp", "sp", "ep", "tp"))


def param_specs(tie_word_embeddings: bool, num_experts: int = 0) -> dict:
    """PartitionSpec pytree matching llama.init_params structure.

    MoE: experts shard over ``ep`` and the FFN hidden dim over ``tp`` —
    the wide-EP layout (each chip holds E/ep experts, each split tp-ways)."""
    specs = {
        "embed": P("tp", None),
        "final_norm": P(None),
        "layers": {
            "attn_norm": P(None, None),
            "mlp_norm": P(None, None),
            "wq": P(None, None, "tp"),
            "wk": P(None, None, "tp"),
            "wv": P(None, None, "tp"),
            "wo": P(None, "tp", None),
        },
    }
    if num_experts == 0:
        specs["layers"].update(
            w_gate=P(None, None, "tp"),
            w_up=P(None, None, "tp"),
            w_down=P(None, "tp", None),
        )
    else:
        specs["layers"].update(
            router=P(None, None, None),
            w_gate=P(None, "ep", None, "tp"),
            w_up=P(None, "ep", None, "tp"),
            w_down=P(None, "ep", "tp", None),
        )
    if not tie_word_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def kv_cache_spec(num_kv_heads: int = 0, tp_size: int = 1) -> P:
    """[L, N, BS, KVH, HD] — shard kv heads over tp when divisible; when
    tp > kv_heads (e.g. 70B kv_heads=8 on tp=16) the cache replicates and the
    duplicated-KV-head handling lives in the attention partitioning."""
    if tp_size > 1 and num_kv_heads % tp_size == 0:
        return P(None, None, None, "tp", None)
    return P(None, None, None, None, None)


def shard_params(params, mesh: Mesh, tie_word_embeddings: bool, num_experts: int = 0):
    specs = param_specs(tie_word_embeddings, num_experts)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params,
        specs,
        is_leaf=lambda x: isinstance(x, jax.Array),
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("dp"))
