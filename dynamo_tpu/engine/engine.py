"""TpuEngine: the AsyncEngine facade over the continuous-batching scheduler.

This is what a dynamo-tpu worker serves (the role vLLM's ``AsyncLLM`` plays
for the reference's vllm adapter, components/backends/vllm handlers.py).

Request wire shape (PreprocessedRequest, ref: protocols/common):
``{"token_ids": [...], "sampling_options": {...}, "stop_conditions": {...}}``
Response frames (LLMEngineOutput): ``{"token_ids": [t], "finish_reason": ...,
"index": 0}`` — detokenization happens upstream in the Backend operator,
never in the engine.

Single-task ownership: only the engine's step-loop task mutates the
scheduler; ``generate``/``abort`` stage work through event-loop-local lists,
and the blocking device step runs via ``asyncio.to_thread`` so serving IO
never stalls.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, List, Optional

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import ModelConfig, get_config
from dynamo_tpu.engine.kv_cache import KvEvent
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import (
    ForwardPassMetrics,
    Scheduler,
    SchedulerConfig,
    Sequence,
    StepOutput,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.tracing import get_tracer

logger = get_logger(__name__)


@dataclass
class EngineArgs:
    model: str = "tiny"
    model_config: Optional[ModelConfig] = None
    scheduler: SchedulerConfig = field(default_factory=SchedulerConfig)
    dtype: str = "bfloat16"
    seed: int = 0
    eos_token_ids: List[int] = field(default_factory=list)
    checkpoint_path: Optional[str] = None
    # KVBM tiers (0 / None = disabled): host-DRAM and disk offload pools.
    kvbm_host_blocks: int = 0
    kvbm_disk_dir: Optional[str] = None
    kvbm_disk_blocks: int = 0
    # Sharded serving: a ParallelConfig (engine/sharding.py) with total > 1
    # builds a device mesh and shards params + KV cache over it.
    parallel: Optional[Any] = None
    # Speculative decoding: a draft model preset/config proposing spec_gamma
    # tokens per round (greedy batches only; ref SpecDecodeStats surface).
    draft_model: Optional[str] = None
    draft_checkpoint_path: Optional[str] = None
    spec_gamma: int = 4
    # KV cache storage dtype override ("auto" | "int8") — config.py.
    kv_cache_dtype: str = "auto"
    # Weight storage dtype override ("auto" | "int8") — config.py weight_dtype.
    weight_dtype: str = "auto"
    # Precompile serving-hot executables for contexts up to this many tokens
    # before taking traffic (scheduler.warmup; 0 = skip). Without it, every
    # new (batch bucket × table width) shape compiles mid-request — measured
    # as the dominant serving-plane latency on fresh processes.
    warmup_ctx: int = 0
    # Guided decoding (structured outputs) needs the SERVED tokenizer to
    # lift grammars to token-level FSMs (llm/guided). Attached before
    # warmup so the masked-sampling executables precompile; without it,
    # guided requests are rejected engine-side.
    tokenizer: Optional[Any] = None
    # Incident autopsy plane (runtime/incidents.py): anomaly-triggered
    # black-box bundles land here (None falls back to DYN_INCIDENT_DIR;
    # unset = detect + count but never write). The detector itself is
    # always armed — it is host-side work on the stats-scrape cadence.
    incident_dir: Optional[str] = None
    incident_keep: int = 16
    # Attach a short jax.profiler device capture to each bundle (TPU
    # diagnosis: was the spike device time or host time?).
    profile_on_incident: bool = False
    # Continuous device-truth sampler (runtime/profiling.ContinuousProfiler):
    # short programmatic profiler windows at a bounded duty cycle, parsed
    # into measured MFU / per-kernel top-N siblings of the modeled gauges.
    # On by default — its defaults are a <1% duty cycle and the first
    # window only opens a full interval after startup.
    continuous_profiling: bool = True
    profile_window_s: float = 0.25
    profile_interval_s: float = 30.0
    # Artifact root for ALL capture paths (falls back to DYN_PROFILE_DIR).
    profile_dir: Optional[str] = None


class TpuEngine:
    def __init__(
        self,
        scheduler: Scheduler,
        *,
        kv_event_sink: Optional[Callable[[KvEvent], None]] = None,
    ):
        self.scheduler = scheduler
        self._staged_adds: List[tuple] = []
        self._staged_aborts: List[str] = []
        self._wake = asyncio.Event()
        self._loop_task: Optional[asyncio.Task] = None
        self._closed = False
        self._kv_event_sink = kv_event_sink
        # Stall watchdog: work queued but no step completing for
        # stall_after_s marks the engine stalled (counter + log + unhealthy
        # /health). Evaluated lazily on every stats scrape / health probe —
        # no background task, deterministic under a monkeypatched clock.
        from dynamo_tpu.runtime.telemetry import StallWatchdog

        self.watchdog = StallWatchdog(
            probe=lambda: (scheduler.has_work(), scheduler.flight.last_step_ts),
            stall_after_s=scheduler.sc.stall_after_s,
        )
        # Incident autopsy plane: the anomaly detector rides every stats
        # scrape (same lazy cadence as the watchdog) and, when a signal
        # fires, the recorder snapshots a self-contained black-box bundle.
        # build() replaces this default (capture-disabled) plane with one
        # pointed at EngineArgs.incident_dir.
        from dynamo_tpu.runtime.incidents import IncidentConfig, IncidentPlane

        self.incidents = IncidentPlane(
            IncidentConfig(),
            state_probe=self.debug_state,
            flight_probe=scheduler.flight.ring_snapshot,
            config_probe=scheduler.config_snapshot,
        )
        # Tenant ledger snapshot rides every incident bundle (autopsy --tenant
        # reads it); process-global like the router's decision ring — a
        # rebuilt engine replaces its predecessor's probe.
        from dynamo_tpu.runtime.incidents import register_evidence_probe

        register_evidence_probe("tenant_ledger", scheduler.ledger.snapshot)
        # Device-truth profiling plane: ONE DeviceProfiler per engine — the
        # serialization point every capture path (health server POST,
        # incident captures, continuous sampler) must share — and the
        # optional background sampler build() arms. Engines constructed
        # directly (tests) get the profiler but no sampler thread.
        from dynamo_tpu.runtime.profiling import DeviceProfiler

        self.profiler = DeviceProfiler()
        self.continuous_profiler = None

    # --- construction -------------------------------------------------------
    @classmethod
    def build(
        cls,
        args: EngineArgs,
        *,
        params=None,
        draft_params=None,
        kv_event_sink: Optional[Callable[[KvEvent], None]] = None,
    ) -> "TpuEngine":
        mc = args.model_config or get_config(args.model)
        if args.kv_cache_dtype != "auto":
            mc = mc.replace(kv_cache_dtype=args.kv_cache_dtype)
        if args.weight_dtype != "auto":
            mc = mc.replace(weight_dtype=args.weight_dtype)
        dtype = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
        if params is None:
            if args.checkpoint_path:
                from dynamo_tpu.engine.weights import load_checkpoint

                params = load_checkpoint(args.checkpoint_path, mc, dtype=dtype)
            else:
                from dynamo_tpu.engine.models import get_module

                logger.warning("no checkpoint: initializing random weights for %s", mc.name)
                params = get_module(mc).init_params(mc, jax.random.PRNGKey(args.seed), dtype=dtype)
        if mc.weight_dtype == "int8":
            from dynamo_tpu.engine.quant import params_quantized, quantize_params

            if not params_quantized(params):
                params = quantize_params(params)
                logger.info("int8 weight-only quantization applied (layer matmul weights)")
        mesh = None
        if args.parallel is not None and args.parallel.total > 1:
            from dynamo_tpu.engine.sharding import build_mesh

            mesh = build_mesh(args.parallel)
        engine = cls(
            Scheduler(
                mc,
                params,
                args.scheduler,
                dtype=dtype,
                eos_token_ids=args.eos_token_ids,
                on_kv_event=lambda ev: engine._on_kv_event(ev),
                rng_seed=args.seed,
                mesh=mesh,
                parallel=args.parallel,
            ),
            kv_event_sink=kv_event_sink,
        )
        if args.draft_model:
            from dynamo_tpu.engine.models import get_module

            dc = get_config(args.draft_model)
            if draft_params is None:
                if args.draft_checkpoint_path:
                    from dynamo_tpu.engine.weights import load_checkpoint

                    draft_params = load_checkpoint(args.draft_checkpoint_path, dc, dtype=dtype)
                else:
                    logger.warning("no draft checkpoint: random weights for %s", dc.name)
                    draft_params = get_module(dc).init_params(
                        dc, jax.random.PRNGKey(args.seed + 1), dtype=dtype
                    )
            engine.scheduler.attach_draft(dc, draft_params, gamma=args.spec_gamma)
        if args.tokenizer is not None:
            engine.scheduler.attach_guided(args.tokenizer)
        if args.warmup_ctx > 0:
            n = engine.scheduler.warmup(args.warmup_ctx)
            logger.info("warmed %d executables (ctx %d)", n, args.warmup_ctx)
        # From here on, compiles are mid-traffic: the flight recorder counts
        # them (and alerts when a warmup pass was supposed to cover them).
        engine.scheduler.flight.mark_warmup_done(warmed=args.warmup_ctx > 0)
        # Incident capture: point the plane at the bundle directory (CLI /
        # env); the detector is armed either way — counters flow to the
        # scrape even when no bundles are written.
        import os as _os

        from dynamo_tpu.runtime.incidents import INCIDENT_DIR_ENV, IncidentConfig, IncidentPlane

        incident_dir = args.incident_dir or _os.environ.get(INCIDENT_DIR_ENV) or None
        # One shared DeviceProfiler for every capture path — incident
        # captures, the health server's POST /debug/profile, and the
        # continuous sampler all serialize through its capture lock.
        if args.profile_dir:
            engine.profiler.out_dir = args.profile_dir
        elif args.profile_on_incident and incident_dir:
            engine.profiler.out_dir = _os.path.join(incident_dir, "profiles")
        engine.incidents = IncidentPlane(
            IncidentConfig(
                dir=incident_dir,
                keep=args.incident_keep,
                profile_on_incident=args.profile_on_incident,
            ),
            state_probe=engine.debug_state,
            flight_probe=engine.scheduler.flight.ring_snapshot,
            config_probe=engine.scheduler.config_snapshot,
            profiler=engine.profiler,
        )
        if args.continuous_profiling:
            from dynamo_tpu.runtime.profiling import (
                ContinuousProfileConfig,
                ContinuousProfiler,
            )

            flight = engine.scheduler.flight
            engine.continuous_profiler = ContinuousProfiler(
                engine.profiler,
                ContinuousProfileConfig(
                    window_s=args.profile_window_s,
                    interval_s=args.profile_interval_s,
                ),
                cost_probe=flight.roofline_totals,
                sink=flight.record_measured_window,
            )
            engine.continuous_profiler.start()
        if args.kvbm_host_blocks > 0:
            from dynamo_tpu.llm.block_manager import KvBlockManager

            engine.kvbm = KvBlockManager(
                engine.scheduler.cache,
                engine.scheduler.allocator,
                host_blocks=args.kvbm_host_blocks,
                disk_dir=args.kvbm_disk_dir,
                disk_blocks=args.kvbm_disk_blocks,
            )
            engine.scheduler.attach_kvbm(engine.kvbm)
        return engine

    def _on_kv_event(self, ev: KvEvent) -> None:
        if self._kv_event_sink is not None:
            self._kv_event_sink(ev)

    # --- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if self._loop_task is None:
            self._loop_task = asyncio.get_running_loop().create_task(self._loop(), name="engine-step-loop")

    async def stop(self) -> None:
        self._closed = True
        self._wake.set()
        if self.continuous_profiler is not None:
            await asyncio.to_thread(self.continuous_profiler.stop)
        if self._loop_task is not None:
            await self._loop_task
            self._loop_task = None
        kvbm = getattr(self.scheduler, "kvbm", None)
        if kvbm is not None:
            # Queued offload snapshots must reach the host/disk tiers —
            # a persistent G3 dir is supposed to survive restarts.
            await asyncio.to_thread(kvbm.flush_pending)

    async def _loop(self) -> None:
        try:
            while not self._closed:
                n = self.scheduler.expire_exports()
                if n:
                    logger.warning("reclaimed %d unpulled KV exports past TTL", n)
                if not (self._staged_adds or self._staged_aborts or self.scheduler.has_work()):
                    self._wake.clear()
                    # Wake periodically while exports await pulling so the
                    # TTL guard runs even when the engine is otherwise idle.
                    if self.scheduler._pending_exports:
                        try:
                            await asyncio.wait_for(self._wake.wait(), timeout=1.0)
                        except asyncio.TimeoutError:
                            pass
                    else:
                        await self._wake.wait()
                    continue
                for rid, tokens, sampling, stop, queue, extras in self._staged_adds:
                    try:
                        seq = self.scheduler.add_request(rid, tokens, sampling, stop, **extras)
                        seq.out_queue = queue
                    except ValueError as e:
                        queue.put_nowait(StepOutput(token_id=-1, finished=True, finish_reason=f"error:{e}"))
                self._staged_adds.clear()
                for rid in self._staged_aborts:
                    self.scheduler.abort(rid)
                self._staged_aborts.clear()

                outputs = await asyncio.to_thread(self.scheduler.step)
                for seq, out in outputs:
                    seq.out_queue.put_nowait(out)
        except Exception:
            logger.exception("engine step loop crashed")
            # Engine death: fail all in-flight requests so streams end and the
            # migration operator can replay them elsewhere (ref: engine
            # monitor EngineDeadError flow, vllm handlers.py:88-92).
            for seq in list(self.scheduler.by_id.values()):
                seq.out_queue.put_nowait(StepOutput(token_id=-1, finished=True, finish_reason="error:engine_dead"))
            raise

    # --- AsyncEngine --------------------------------------------------------
    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        self.start()
        rid = context.id
        sampling_d = request.get("sampling_options") or {}
        temp = sampling_d.get("temperature")
        seed = sampling_d.get("seed")
        tlp = int(sampling_d.get("top_logprobs") or 0)
        sampling = SamplingParams(
            temperature=1.0 if temp is None else float(temp),  # null ≡ unset ≡ default
            top_k=int(sampling_d.get("top_k") or 0),
            top_p=float(sampling_d.get("top_p") or 1.0),
            seed=int(seed) if seed is not None else None,
            logprobs=bool(sampling_d.get("logprobs")) or tlp > 0,
            top_logprobs=tlp,
            frequency_penalty=float(sampling_d.get("frequency_penalty") or 0.0),
            presence_penalty=float(sampling_d.get("presence_penalty") or 0.0),
        )
        logit_bias = sampling_d.get("logit_bias")
        if logit_bias:
            from dynamo_tpu.logits_processing import LogitBiasProcessor

            # Applied pre-sampling via the per-request processor chain (the
            # host path — logit_bias rows skip the batched fast paths).
            sampling.logits_processors = [LogitBiasProcessor(logit_bias)]
        stop = StopConditions.from_dict(request.get("stop_conditions"))
        disagg = request.get("disagg_params") or {}
        # keep_blocks: prefill role (decode worker will pull the KV);
        # _prefilled: decode role (KV already pulled, injected locally).
        extras = {
            "keep_blocks_on_finish": bool(disagg.get("do_remote_decode")),
            "prefilled": request.get("_prefilled"),
            # Capacity-ledger attribution (runtime/ledger.py): resolved by
            # the frontend, billed by the scheduler.
            "tenant": request.get("tenant") or "anon",
        }
        guided = request.get("guided_decoding")
        if guided is not None:
            # Grammar-constrained decoding (llm/guided): the scheduler
            # compiles/caches the token FSM and masks sampling device-side.
            extras["guided"] = guided
        mm = request.get("multimodal")
        if mm is not None:
            from dynamo_tpu.llm.multimodal import features_from_wire

            extras["mm_features"] = (
                mm if hasattr(mm, "shape") else features_from_wire(mm)
            )
        # Request tracing: hand the scheduler the (trace_id, parent_span)
        # pair only for traces that should record — head-sampled (the
        # deterministic decision matches the frontend's, so one request is
        # one trace) or, in tail mode, every trace: unsampled records stay
        # in the in-memory ring for SLO-violation promotion and incident
        # bundles instead of exporting.
        tracer = get_tracer()
        tp = context.traceparent
        if tracer.enabled and tp is not None and tracer.record_allowed(tp.trace_id):
            extras["trace"] = (tp.trace_id, tp.parent_id)
        queue: "asyncio.Queue[StepOutput]" = asyncio.Queue()
        self._staged_adds.append((rid, list(request["token_ids"]), sampling, stop, queue, extras))
        self._wake.set()

        finished = False
        stop_task = asyncio.create_task(context.stopped())
        try:
            while True:
                # Fast path: drain whatever the last scheduler dispatch
                # already queued — a multi-step window lands up to
                # num_scheduler_steps tokens at once, and pushing them as
                # ONE frame collapses the per-token asyncio/detok/SSE hops
                # that dominated the serving plane (measured: the plane,
                # not the device, capped HTTP throughput at ~6 req/s).
                outs = []
                try:
                    while True:
                        outs.append(queue.get_nowait())
                        if outs[-1].finished:
                            break
                except asyncio.QueueEmpty:
                    pass
                if not outs:
                    if context.is_stopped():
                        self.abort(rid)
                        out = await queue.get()
                        while not out.finished:
                            out = await queue.get()
                        finished = True
                        return
                    get_task = asyncio.create_task(queue.get())
                    done, _ = await asyncio.wait(
                        {get_task, stop_task}, return_when=asyncio.FIRST_COMPLETED
                    )
                    if stop_task in done and get_task not in done:
                        get_task.cancel()
                        self.abort(rid)
                        out = await queue.get()
                        while not out.finished:
                            out = await queue.get()
                        finished = True
                        return
                    outs.append(get_task.result())

                frame = {"token_ids": [], "finish_reason": None, "index": 0}
                logprobs = []
                top_logprobs = []
                for out in outs:
                    if out.finish_reason and out.finish_reason.startswith("error:"):
                        if frame["token_ids"]:
                            if logprobs:
                                frame["logprobs"] = logprobs
                            if top_logprobs:
                                frame["top_logprobs"] = top_logprobs
                            yield frame  # tokens decoded before the error
                        finished = True
                        raise RuntimeError(out.finish_reason[6:])
                    if out.token_id >= 0:
                        frame["token_ids"].append(out.token_id)
                    if out.logprob is not None:
                        logprobs.append(out.logprob)
                    if out.top_logprobs is not None:
                        # Per emitted token: [[alt_token_id, logprob], ...] —
                        # parallel to frame["logprobs"] (top_logprobs implies
                        # logprobs, so the lists stay index-aligned).
                        top_logprobs.append([[t, lp] for t, lp in out.top_logprobs])
                    if out.queue_s is not None and "queue_s" not in frame:
                        frame["queue_s"] = out.queue_s
                    if out.cached_tokens is not None and "cached_tokens" not in frame:
                        # Prefix-cache reuse (first frame): prompt tokens
                        # served from resident KV — flows to OpenAI
                        # usage.prompt_tokens_details and router accounting.
                        frame["cached_tokens"] = out.cached_tokens
                    if out.finished:
                        frame["finish_reason"] = out.finish_reason
                if logprobs:
                    frame["logprobs"] = logprobs
                if top_logprobs:
                    frame["top_logprobs"] = top_logprobs
                yield frame
                if frame["finish_reason"]:
                    finished = True
                    return
        finally:
            stop_task.cancel()
            # Abandoned stream (GeneratorExit / disconnect without kill):
            # stop decoding a request nobody is reading.
            if not finished:
                self.abort(rid)

    def abort(self, request_id: str) -> None:
        self._staged_aborts.append(request_id)
        self._wake.set()

    # --- disaggregation -----------------------------------------------------
    async def take_export(self, request_id: str):
        """Pull a finished prefill-role request's KV blocks (device→host) and
        release them. Returns (blocks, hashes, prompt_len) or None."""
        return await asyncio.to_thread(self.scheduler.take_export, request_id)

    async def take_export_device(self, request_id: str):
        """Device-native export: stacked device arrays, no host round-trip.
        Returns ((k_stack, v_stack), hashes, prompt_len) or None."""
        return await asyncio.to_thread(self.scheduler.take_export_device, request_id)

    # --- elastic capacity dial ---------------------------------------------
    def set_capacity_dial(self, prefill_fraction: float) -> dict:
        """Re-split this worker's budget between prefill and decode, live.

        Thread-safe (scheduler takes _aux_lock); reachable remotely via the
        ``set_dial`` control op on the worker's control subject.
        """
        return self.scheduler.set_capacity_dial(prefill_fraction)

    # --- introspection ------------------------------------------------------
    def metrics(self) -> ForwardPassMetrics:
        return self.scheduler.metrics()

    def stats_handler(self) -> dict:
        m = self.scheduler.metrics()
        stats = {
            "kv_usage": m.kv_usage,
            "kv_total_blocks": m.kv_total_blocks,
            "kv_active_blocks": m.kv_active_blocks,
            "num_running": m.num_running,
            "num_waiting": m.num_waiting,
            "preemptions_total": self.scheduler.preempt_total,
            # Failure-lifecycle counters: deadline evictions (finish_reason
            # "timeout" + KV freed) — the chaos suite's recovery signal.
            "request_timeouts_total": self.scheduler.timeouts_total,
            # Mixed-step composition (scrape-visible so the planner and
            # dashboards can see how much prefill rides the decode wave —
            # runtime/metrics.py documents the derived counters).
            "mixed_steps_total": m.mixed_steps_total,
            "mixed_prefill_tokens_total": m.mixed_prefill_tokens_total,
            "mixed_decode_tokens_total": m.mixed_decode_tokens_total,
            # Zero-bubble decode pipeline: overlapped steps vs flushes back
            # to the sync path (admission/finish/growth/extras). The gap
            # histogram itself rides flight.to_stats() below.
            "overlap_steps_total": m.overlap_steps_total,
            "overlap_flushes_total": m.overlap_flushes_total,
            # Automatic prefix caching: skipped prompt tokens + the block
            # hit/miss/evict/onboard account (Grafana "Prefix cache" rows).
            "cached_tokens_total": m.cached_tokens_total,
            "prefix_hit_blocks_total": m.prefix_hit_blocks_total,
            "prefix_miss_blocks_total": m.prefix_miss_blocks_total,
            "prefix_evicted_blocks_total": m.prefix_evicted_blocks_total,
            "prefix_onboard_total": m.prefix_onboard_total,
            # First-token latency decomposition: queue (arrival→admission)
            # and prefill (admission→first token) sums — with the flight
            # recorder's step histograms these give the bench http sweep
            # its queue/prefill/decode breakdown.
            "queue_wait_seconds_total": round(self.scheduler.queue_wait_s_total, 6),
            "prefill_wait_seconds_total": round(self.scheduler.prefill_wait_s_total, 6),
            "first_tokens_total": self.scheduler.first_tokens_total,
            # Elastic capacity dial: the live prefill:decode budget split
            # (set_capacity_dial) so the planner's ratio actuator and the
            # Grafana "Elastic" row can see each worker's current shape.
            "elastic_prefill_fraction": m.elastic_prefill_fraction,
            "elastic_prefill_budget": m.elastic_prefill_budget,
            "elastic_decode_slots": m.elastic_decode_slots,
            "elastic_dial_changes_total": m.elastic_dial_changes_total,
        }
        # Flight recorder: per-phase step/token counters + the XLA compile
        # tracker (compiles_after_warmup_total > 0 in steady state is the
        # alert that shapes are compiling mid-traffic — PR 1's silent killer)
        # + the measured device-truth siblings once profile windows landed.
        stats.update(self.scheduler.flight.to_stats())
        # Continuous device-truth sampler: window/skip/error counters and
        # the live duty-cycle gauge (pure dict assembly — no device work).
        if self.continuous_profiler is not None:
            stats.update(self.continuous_profiler.to_stats())
        # KV-pool utilization gauges (free/cached depth, fragmentation,
        # prefix hit rate) + the SLO/goodput account + stall-watchdog state.
        stats.update(self.scheduler.kv_gauges())
        stats.update(self.scheduler.slo.to_stats())
        stats.update(self.watchdog.to_stats())
        # Mergeable latency digests (ttft/tpot/itl/queue_wait + per-phase
        # step durations): the aggregator merges these across workers into
        # true fleet-wide quantiles — averaging per-worker p99s does not.
        stats["digests"] = self.scheduler.telemetry.to_wire()
        # Tenant capacity ledger: flat billed totals on the worker plane +
        # the nested sketch wire the aggregator merges into fleet-true
        # per-tenant top-K families (runtime/ledger.py).
        stats.update(self.scheduler.ledger.to_stats())
        stats["tenant_ledger"] = self.scheduler.ledger.to_wire()
        # Guided decoding: request + grammar-compile counters (scrape-
        # visible so dashboards can watch structured-output traffic).
        if self.scheduler.guided is not None:
            stats.update(self.scheduler.guided.stats())
        # Chaos plane: injected-fault counters when an injector is armed
        # (runtime/faults.py; {} otherwise — the keys only exist on
        # chaos-armed workers).
        from dynamo_tpu.runtime import faults as _faults

        stats.update(_faults.stats())
        # Incident autopsy plane: the detector checks THIS snapshot (the
        # scrape is the poll cadence, exactly like the watchdog above) and
        # may write a black-box bundle; its counters ride the same scrape.
        self.incidents.observe(stats)
        stats.update(self.incidents.to_stats())
        return stats

    def debug_state(self) -> dict:
        """Live engine introspection for the health server's /debug/state."""
        state = self.scheduler.debug_state()
        state["watchdog"] = self.watchdog.to_stats()
        state["watchdog"]["stall_after_s"] = self.watchdog.stall_after_s
        state["incidents"] = self.incidents.debug_info()
        return state

    def attach_guided_tokenizer(self, tokenizer) -> None:
        """Enable guided decoding post-build (pipeline assembly attaches the
        serving tokenizer here when EngineArgs.tokenizer wasn't set)."""
        self.scheduler.attach_guided(tokenizer)
