"""Paged KV cache: device arrays + host-side block allocator with prefix
caching and KV event emission.

The device cache is a global block pool: ``k``/``v`` arrays of shape
``[layers, num_blocks, block_size, kv_heads, head_dim]``. Sequences own
*block tables* (lists of block indices); attention gathers through them.
This is the TPU-native equivalent of vLLM's paged KV plus the engine-side
part of the reference's KVBM G1 tier (lib/llm/src/block_manager — device
pool, sequence-hash reuse in block/registry.rs:478, pool/managed.rs
active/inactive sets with eviction).

Prefix caching: completed full blocks are registered under their chained
block hash (``dynamo_tpu.llm.tokens``). New sequences match their prefix
hashes against the registry and skip prefill for matched blocks. Eviction is
LRU over unreferenced cached blocks. Every register/evict emits a KV event
for the KV-aware router (ref: kv_router/publisher.rs — the engine→router
event loop, SURVEY.md §3D).
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.llm.tokens import BlockHash


class QuantKv(NamedTuple):
    """int8-quantized KV tensor: values + per-(token, head) symmetric scale.

    A pytree, so it flows through jit args, scan xs, and donation exactly
    like a plain array — model code dispatches on the type at gather/scatter
    points (``dequantize_kv`` / ``quantize_kv_rows``)."""

    q: jax.Array  # int8, [L, N, BS, KVH, HD]
    scale: jax.Array  # f32, [L, N, BS, KVH, 1]

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype

    def reshape(self, *shape) -> "QuantKv":
        # Layer-flat views ([L*N, ...]) reshape both members coherently.
        return QuantKv(self.q.reshape(*shape), self.scale.reshape(*shape[:-1], 1))


def quantize_kv_rows(rows: jax.Array) -> QuantKv:
    """Symmetric int8 quantization over the trailing (head_dim) axis."""
    amax = jnp.max(jnp.abs(rows.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(rows.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return QuantKv(q, scale)


def dequantize_kv(x, dtype=jnp.bfloat16):
    """QuantKv → real-valued rows; plain arrays pass through."""
    if isinstance(x, QuantKv):
        return (x.q.astype(jnp.float32) * x.scale).astype(dtype)
    return x


def ragged_scatter_targets(
    block_table: jax.Array,  # [W] block ids for one sequence (0 = scratch)
    positions: jax.Array,  # [T] absolute write slot per token row
    live: jax.Array,  # [T] bool — dead rows (bucket padding) sink to block 0
    block_size: int,
):
    """Paged-KV scatter targets for a ragged run of token rows sharing one
    block table (a prefill chunk, or one sequence's slice of a mixed
    batch). Returns ``(tgt_blocks [T], tgt_offs [T])``; dead rows target
    the reserved scratch block 0 so no real block is corrupted. Shared by
    ``llama.prefill`` and ``llama.mixed_step`` so the per-row position →
    (block, offset) convention lives in one place."""
    slots = jnp.where(live, positions, 0)
    return jnp.where(live, block_table[slots // block_size], 0), slots % block_size


@dataclass
class KvCacheArrays:
    """Device-side block pool (one array pair covering all layers). With
    ``config.kv_cache_dtype == "int8"`` the members are :class:`QuantKv`
    pytrees instead of plain arrays."""

    k: Any  # jax.Array | QuantKv — [L, N, BS, KVH, HD]
    v: Any

    @classmethod
    def create(
        cls,
        config: ModelConfig,
        num_blocks: int,
        dtype=jnp.bfloat16,
        sharding: Optional[jax.sharding.Sharding] = None,
    ) -> "KvCacheArrays":
        if config.architecture == "mla":
            # MLA stores one shared latent row per token (kv_lora_rank +
            # rope dim) in ``k``; ``v`` is a placeholder (values decompress
            # from the latent — models/mla.py). int8 quantizes the latent
            # row with one per-token scale (the row is rms-normed latent ‖
            # rope'd keys — O(1) ranges, one scale holds within a code step).
            width = config.kv_lora_rank + config.qk_rope_head_dim
            shape = (config.num_layers, num_blocks, config.block_size, 1, width)
            if config.kv_cache_dtype == "int8":
                q = jnp.zeros(shape, dtype=jnp.int8)
                scale = jnp.zeros((*shape[:-1], 1), dtype=jnp.float32)
                if sharding is not None:
                    q = jax.device_put(q, sharding)
                    scale = jax.device_put(scale, sharding)
                return cls(k=QuantKv(q, scale), v=jnp.zeros((config.num_layers, 1, 1, 1, 1), dtype=dtype))
            k = jnp.zeros(shape, dtype=dtype)
            if sharding is not None:
                k = jax.device_put(k, sharding)
            return cls(k=k, v=jnp.zeros((config.num_layers, 1, 1, 1, 1), dtype=dtype))
        shape = (config.num_layers, num_blocks, config.block_size, config.num_kv_heads, config.head_dim)

        def mk():
            if config.kv_cache_dtype == "int8":
                q = jnp.zeros(shape, dtype=jnp.int8)
                scale = jnp.zeros((*shape[:-1], 1), dtype=jnp.float32)
                if sharding is not None:
                    q = jax.device_put(q, sharding)
                    scale = jax.device_put(scale, sharding)
                return QuantKv(q, scale)
            init = jnp.zeros(shape, dtype=dtype)
            return jax.device_put(init, sharding) if sharding is not None else init

        return cls(k=mk(), v=mk())


class OutOfBlocksError(Exception):
    pass


@dataclass
class KvEvent:
    """Engine→router cache event (ref: kv-cache-events consumed by
    KvIndexer.apply_event, indexer.rs)."""

    kind: str  # "stored" | "removed"
    block_hashes: List[int]
    parent_hash: Optional[int] = None
    ts: float = field(default_factory=time.time)

    def to_wire(self) -> dict:
        return {
            "kind": self.kind,
            "block_hashes": [h & 0xFFFFFFFFFFFFFFFF for h in self.block_hashes],
            "parent_hash": self.parent_hash,
            "ts": self.ts,
        }


class BlockAllocator:
    """Host-side bookkeeping for the device block pool.

    Block states (mirrors pool/managed.rs active/inactive):
    - free      — on the free list, contents dead.
    - active    — referenced by ≥1 live sequence (refcount > 0).
    - cached    — refcount 0 but registered under a block hash; evictable LRU.
    """

    def __init__(self, num_blocks: int, on_event: Optional[Callable[[KvEvent], None]] = None):
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._refcount: Dict[int, int] = {}
        # block_hash -> block_id for completed, reusable blocks.
        self._by_hash: Dict[BlockHash, int] = {}
        self._hash_of: Dict[int, BlockHash] = {}
        # LRU over cached (refcount-0, hashed) blocks.
        self._cached_lru: "OrderedDict[int, None]" = OrderedDict()
        self.on_event = on_event
        # KVBM offload hook: called (block_id, block_hash) when a cached
        # block is evicted for reuse — the copy-out point for the G1→G2
        # cascade (content is still intact at call time).
        self.on_evict: Optional[Callable[[int, int], None]] = None
        # Prefix-cache accounting (monotonic; surfaced through worker stats
        # → aggregator counters → the Grafana hit-rate panels).
        self.hit_blocks_total = 0
        self.miss_blocks_total = 0
        self.evicted_blocks_total = 0

    # --- queries ------------------------------------------------------------
    @property
    def num_free(self) -> int:
        return len(self._free) + len(self._cached_lru)

    @property
    def num_active(self) -> int:
        return sum(1 for c in self._refcount.values() if c > 0)

    @property
    def num_cached(self) -> int:
        return len(self._cached_lru)

    def usage(self) -> float:
        return 1.0 - len(self._free) / max(self.num_blocks, 1)

    # --- prefix matching ----------------------------------------------------
    def match_prefix(self, block_hashes: Sequence[BlockHash]) -> List[int]:
        """Longest prefix of ``block_hashes`` present in cache; acquires a
        reference on each matched block (caller owns them)."""
        matched: List[int] = []
        for h in block_hashes:
            bid = self._by_hash.get(h)
            if bid is None:
                break
            self._acquire(bid)
            matched.append(bid)
        self.hit_blocks_total += len(matched)
        self.miss_blocks_total += len(block_hashes) - len(matched)
        return matched

    def ref_count(self, bid: int) -> int:
        """Live references on a block (0 = cached/free). The scheduler's
        copy-on-write check: a matched block with other holders must not be
        written in place."""
        return self._refcount.get(bid, 0)

    # --- allocation ---------------------------------------------------------
    def allocate(self, n: int) -> List[int]:
        """Take n fresh blocks, evicting LRU cached blocks as needed."""
        out: List[int] = []
        removed_hashes: List[int] = []
        try:
            for _ in range(n):
                if self._free:
                    bid = self._free.pop()
                elif self._cached_lru:
                    bid, _ = self._cached_lru.popitem(last=False)  # LRU evict
                    h = self._hash_of.pop(bid)
                    del self._by_hash[h]
                    removed_hashes.append(h)
                    self.evicted_blocks_total += 1
                    if self.on_evict is not None:
                        self.on_evict(bid, h)  # offload cascade copy-out
                else:
                    raise OutOfBlocksError(f"need {n} blocks, {len(out)} available")
                self._refcount[bid] = 1
                out.append(bid)
        except OutOfBlocksError:
            for bid in out:
                self.release([bid])
            raise
        finally:
            if removed_hashes and self.on_event:
                self.on_event(KvEvent(kind="removed", block_hashes=removed_hashes))
        return out

    def _acquire(self, bid: int) -> None:
        c = self._refcount.get(bid, 0)
        if c == 0 and bid in self._cached_lru:
            del self._cached_lru[bid]
        self._refcount[bid] = c + 1

    def acquire(self, block_ids: Sequence[int]) -> None:
        for bid in block_ids:
            self._acquire(bid)

    def release(self, block_ids: Sequence[int]) -> None:
        """Drop a reference; refcount-0 blocks become cached (if hashed) or
        free (if not).

        Blocks enter the LRU in REVERSE list order. Block tables are
        chain-ordered (prefix head first), and a chained prefix is only
        matchable up to its first missing block — evicting a chain HEAD
        destroys the whole prefix while its tail blocks sit uselessly in
        cache. Reversing makes eviction consume chains tail-first: matches
        degrade to shorter prefixes instead of zero, and per-request suffix
        blocks (unique, never re-matched) go before shared prefix heads."""
        for bid in reversed(list(block_ids)):
            c = self._refcount.get(bid, 0) - 1
            if c > 0:
                self._refcount[bid] = c
                continue
            self._refcount.pop(bid, None)
            if bid in self._hash_of:
                self._cached_lru[bid] = None
                self._cached_lru.move_to_end(bid)
            else:
                self._free.append(bid)

    # --- hash registration --------------------------------------------------
    def register_hashes(self, block_ids: Sequence[int], block_hashes: Sequence[BlockHash]) -> None:
        """Publish completed blocks for reuse (ref: block/registry.rs).
        Emits a ``stored`` KV event."""
        stored: List[int] = []
        event_parent: Optional[int] = None
        parent: Optional[int] = None  # hash of the previous block in the chain
        for bid, h in zip(block_ids, block_hashes):
            if bid in self._hash_of:
                parent = self._hash_of[bid]
                continue
            existing = self._by_hash.get(h)
            if existing is not None and existing != bid:
                # Duplicate content: keep the existing registration.
                parent = h
                continue
            self._by_hash[h] = bid
            self._hash_of[bid] = h
            if not stored:
                event_parent = parent  # chain linkage for the router index
            stored.append(h)
            parent = h
        if stored and self.on_event:
            self.on_event(KvEvent(kind="stored", block_hashes=stored, parent_hash=event_parent))

    def touch(self, block_ids: Sequence[int]) -> None:
        for bid in block_ids:
            if bid in self._cached_lru:
                self._cached_lru.move_to_end(bid)

    def clear_cached(self) -> int:
        """Drop all refcount-0 cached blocks (ref: clear_kv_blocks endpoint,
        http/service/clear_kv_blocks.rs). Returns count cleared."""
        n = len(self._cached_lru)
        removed = []
        for bid in list(self._cached_lru):
            h = self._hash_of.pop(bid)
            del self._by_hash[h]
            removed.append(h)
            self._free.append(bid)
        self._cached_lru.clear()
        self.evicted_blocks_total += n
        if removed and self.on_event:
            self.on_event(KvEvent(kind="removed", block_hashes=removed))
        return n
