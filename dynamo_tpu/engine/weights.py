"""Checkpoint loading: HF safetensors → stacked-layer JAX params.

The reference resolves/downloads models via hf-hub (lib/llm/src/local_model.rs
hub.rs:299); in this zero-egress environment we load from a local directory
only. Conversion maps per-layer HF tensors onto the stacked ``[L, ...]``
layout ``dynamo_tpu.engine.models.llama`` scans over.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import ModelConfig


def config_from_hf(path: str) -> ModelConfig:
    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    head_dim = hf.get("head_dim") or hf["hidden_size"] // hf["num_attention_heads"]
    return ModelConfig(
        name=os.path.basename(path.rstrip("/")),
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=head_dim,
        intermediate_size=hf["intermediate_size"],
        rope_theta=hf.get("rope_theta", 500000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
        max_seq_len=hf.get("max_position_embeddings", 8192),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
    )


def load_checkpoint(path: str, config: ModelConfig, dtype=jnp.bfloat16) -> Dict:
    """Load HF Llama safetensors from a local directory into stacked params."""
    from safetensors import safe_open

    files = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors files in {path}")

    raw: Dict[str, np.ndarray] = {}
    for fname in files:
        with safe_open(os.path.join(path, fname), framework="np") as f:
            for key in f.keys():
                raw[key] = f.get_tensor(key)

    c = config
    L = c.num_layers

    def stack(fmt: str, transpose: bool = True) -> jnp.ndarray:
        # HF nn.Linear stores [out, in]; our layout is [in, out].
        layers = [raw[fmt.format(l)] for l in range(L)]
        arr = np.stack(layers)
        if transpose:
            arr = arr.transpose(0, 2, 1)
        return jnp.asarray(arr, dtype=dtype)

    params = {
        "embed": jnp.asarray(raw["model.embed_tokens.weight"], dtype=dtype),
        "final_norm": jnp.asarray(raw["model.norm.weight"], dtype=dtype),
        "layers": {
            "attn_norm": jnp.asarray(
                np.stack([raw[f"model.layers.{l}.input_layernorm.weight"] for l in range(L)]), dtype=dtype
            ),
            "mlp_norm": jnp.asarray(
                np.stack([raw[f"model.layers.{l}.post_attention_layernorm.weight"] for l in range(L)]), dtype=dtype
            ),
            "wq": stack("model.layers.{}.self_attn.q_proj.weight"),
            "wk": stack("model.layers.{}.self_attn.k_proj.weight"),
            "wv": stack("model.layers.{}.self_attn.v_proj.weight"),
            "wo": stack("model.layers.{}.self_attn.o_proj.weight"),
            "w_gate": stack("model.layers.{}.mlp.gate_proj.weight"),
            "w_up": stack("model.layers.{}.mlp.up_proj.weight"),
            "w_down": stack("model.layers.{}.mlp.down_proj.weight"),
        },
    }
    if not c.tie_word_embeddings and "lm_head.weight" in raw:
        params["lm_head"] = jnp.asarray(raw["lm_head.weight"].T, dtype=dtype)
    return params


def resolve_model(name_or_path: str) -> Optional[str]:
    """Return a local checkpoint dir if one exists (no network egress)."""
    candidates = [
        name_or_path,
        os.path.expanduser(f"~/.cache/huggingface/hub/models--{name_or_path.replace('/', '--')}"),
    ]
    for c in candidates:
        if os.path.isdir(c) and any(f.endswith(".safetensors") for f in os.listdir(c)):
            return c
    return None
