"""Checkpoint loading: HF safetensors → stacked-layer JAX params.

The reference resolves/downloads models via hf-hub (lib/llm/src/local_model.rs
hub.rs:299); in this zero-egress environment we load from a local directory
only. Conversion maps per-layer HF tensors onto the stacked ``[L, ...]``
layout ``dynamo_tpu.engine.models.llama`` scans over.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import ModelConfig


def config_from_hf(path: str) -> ModelConfig:
    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    head_dim = hf.get("head_dim") or hf["hidden_size"] // hf["num_attention_heads"]
    return ModelConfig(
        name=os.path.basename(path.rstrip("/")),
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=hf["num_attention_heads"],
        num_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=head_dim,
        intermediate_size=hf["intermediate_size"],
        rope_theta=hf.get("rope_theta", 500000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-5),
        max_seq_len=hf.get("max_position_embeddings", 8192),
        tie_word_embeddings=hf.get("tie_word_embeddings", False),
    )


def load_checkpoint(path: str, config: ModelConfig, dtype=jnp.bfloat16) -> Dict:
    """Load a llama-family checkpoint into stacked params: HF safetensors
    directory, a .gguf file, or a directory holding one."""
    if os.path.isfile(path) and path.endswith(".gguf"):
        return load_gguf_checkpoint(path, config, dtype=dtype)
    from safetensors import safe_open

    files = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    if not files:
        ggufs = sorted(f for f in os.listdir(path) if f.endswith(".gguf"))
        if ggufs:
            return load_gguf_checkpoint(os.path.join(path, ggufs[0]), config, dtype=dtype)
        raise FileNotFoundError(f"no .safetensors or .gguf files in {path}")

    raw: Dict[str, np.ndarray] = {}
    for fname in files:
        with safe_open(os.path.join(path, fname), framework="np") as f:
            for key in f.keys():
                raw[key] = f.get_tensor(key)

    c = config
    L = c.num_layers

    quant_int8 = c.weight_dtype == "int8"

    def stack(fmt: str, transpose: bool = True, quantizable: bool = False):
        # HF nn.Linear stores [out, in]; our layout is [in, out].
        layers = [raw[fmt.format(l)] for l in range(L)]
        arr = np.stack(layers)
        if transpose:
            arr = arr.transpose(0, 2, 1)
        if quantizable and quant_int8:
            # Quantize on HOST: the bf16 stack never lands on the device,
            # so checkpoints bigger than HBM in full precision (8B on a
            # 16 GiB v5e) load directly into int8 residency.
            from dynamo_tpu.engine.quant import quantize_weight_np

            return quantize_weight_np(arr)
        return jnp.asarray(arr, dtype=dtype)

    params = {
        "embed": jnp.asarray(raw["model.embed_tokens.weight"], dtype=dtype),
        "final_norm": jnp.asarray(raw["model.norm.weight"], dtype=dtype),
        "layers": {
            "attn_norm": jnp.asarray(
                np.stack([raw[f"model.layers.{l}.input_layernorm.weight"] for l in range(L)]), dtype=dtype
            ),
            "mlp_norm": jnp.asarray(
                np.stack([raw[f"model.layers.{l}.post_attention_layernorm.weight"] for l in range(L)]), dtype=dtype
            ),
            "wq": stack("model.layers.{}.self_attn.q_proj.weight", quantizable=True),
            "wk": stack("model.layers.{}.self_attn.k_proj.weight", quantizable=True),
            "wv": stack("model.layers.{}.self_attn.v_proj.weight", quantizable=True),
            "wo": stack("model.layers.{}.self_attn.o_proj.weight", quantizable=True),
            "w_gate": stack("model.layers.{}.mlp.gate_proj.weight", quantizable=True),
            "w_up": stack("model.layers.{}.mlp.up_proj.weight", quantizable=True),
            "w_down": stack("model.layers.{}.mlp.down_proj.weight", quantizable=True),
        },
    }
    if not c.tie_word_embeddings and "lm_head.weight" in raw:
        params["lm_head"] = jnp.asarray(raw["lm_head.weight"].T, dtype=dtype)
    return params


def config_from_gguf(path: str) -> ModelConfig:
    """Architecture record from GGUF metadata (ref: local_model.rs GGUF
    resolution + gguf/ parsing)."""
    from dynamo_tpu.llm.gguf import parse_gguf

    meta = parse_gguf(path)
    hidden = int(meta.arch_field("embedding_length") or 0)
    heads = int(meta.arch_field("attention.head_count") or 0)
    vocab = None
    for t in meta.tensors:
        if t.name == "token_embd.weight":
            vocab = int(t.shape[-1])  # ne = [hidden, vocab]
    if vocab is None:
        toks = meta.tokens
        vocab = len(toks) if toks else 0
    has_head = any(t.name == "output.weight" for t in meta.tensors)
    # head_dim: GGUF carries attention.key_length when it differs from
    # hidden/heads (e.g. some Gemma/Qwen exports); trust it over the ratio.
    key_len = meta.arch_field("attention.key_length")
    if key_len:
        head_dim = int(key_len)
    else:
        if heads and hidden % heads != 0:
            raise ValueError(
                f"GGUF {path}: embedding_length {hidden} not divisible by "
                f"head_count {heads} and no attention.key_length present"
            )
        head_dim = hidden // max(heads, 1)
    scaling_type = meta.arch_field("rope.scaling.type")
    scaling_factor = float(meta.arch_field("rope.scaling.factor") or 1.0)
    if scaling_type and str(scaling_type) != "none" and scaling_factor != 1.0:
        raise ValueError(
            f"GGUF {path}: rope.scaling.type={scaling_type!r} factor="
            f"{scaling_factor} is not applied by this engine — refusing to "
            "load with silently-wrong RoPE"
        )
    return ModelConfig(
        name=meta.model_name or os.path.basename(path),
        vocab_size=vocab,
        hidden_size=hidden,
        num_layers=int(meta.num_layers or 0),
        num_heads=heads,
        num_kv_heads=int(meta.arch_field("attention.head_count_kv") or heads),
        head_dim=head_dim,
        intermediate_size=int(meta.arch_field("feed_forward_length") or 0),
        rope_theta=float(meta.arch_field("rope.freq_base") or 500000.0),
        rms_norm_eps=float(meta.arch_field("attention.layer_norm_rms_epsilon") or 1e-5),
        max_seq_len=int(meta.context_length or 8192),
        tie_word_embeddings=not has_head,
    )


def load_gguf_checkpoint(path: str, config: ModelConfig, dtype=jnp.bfloat16) -> Dict:
    """Load a GGUF llama-family checkpoint (f32/f16/bf16/q8_0 tensors) into
    stacked params. GGUF matrices read back HF-style [out, in] (gguf.py
    read_tensor), so the same transpose applies as for safetensors."""
    from dynamo_tpu.llm.gguf import load_tensors

    raw = load_tensors(path)
    c = config
    L = c.num_layers

    def stack(fmt: str, transpose: bool = True) -> jnp.ndarray:
        layers = [raw[fmt.format(l)] for l in range(L)]
        arr = np.stack(layers)
        if transpose:
            arr = arr.transpose(0, 2, 1)
        return jnp.asarray(arr, dtype=dtype)

    params = {
        "embed": jnp.asarray(raw["token_embd.weight"], dtype=dtype),
        "final_norm": jnp.asarray(raw["output_norm.weight"], dtype=dtype),
        "layers": {
            "attn_norm": jnp.asarray(
                np.stack([raw[f"blk.{l}.attn_norm.weight"] for l in range(L)]), dtype=dtype
            ),
            "mlp_norm": jnp.asarray(
                np.stack([raw[f"blk.{l}.ffn_norm.weight"] for l in range(L)]), dtype=dtype
            ),
            "wq": stack("blk.{}.attn_q.weight"),
            "wk": stack("blk.{}.attn_k.weight"),
            "wv": stack("blk.{}.attn_v.weight"),
            "wo": stack("blk.{}.attn_output.weight"),
            "w_gate": stack("blk.{}.ffn_gate.weight"),
            "w_up": stack("blk.{}.ffn_up.weight"),
            "w_down": stack("blk.{}.ffn_down.weight"),
        },
    }
    if not c.tie_word_embeddings and "output.weight" in raw:
        params["lm_head"] = jnp.asarray(raw["output.weight"].T, dtype=dtype)
    return params


def _has_weights(d: str) -> bool:
    try:
        return any(f.endswith((".safetensors", ".gguf")) for f in os.listdir(d))
    except OSError:
        return False


def resolve_model(name_or_path: str) -> Optional[str]:
    """Resolve a name/path to a local checkpoint (no network egress):

    1. A directory with safetensors/GGUF files, or a GGUF file path.
    2. The HF cache layout (hub.rs:299 role):
       ``~/.cache/huggingface/hub/models--ORG--NAME/snapshots/<rev>/`` with
       the revision taken from ``refs/main`` when present.
    """
    if os.path.isfile(name_or_path) and name_or_path.endswith(".gguf"):
        return name_or_path
    if os.path.isdir(name_or_path) and _has_weights(name_or_path):
        return name_or_path
    root = os.environ.get("HF_HOME") or os.path.expanduser("~/.cache/huggingface")
    repo = os.path.join(root, "hub", f"models--{name_or_path.replace('/', '--')}")
    snaps = os.path.join(repo, "snapshots")
    if os.path.isdir(snaps):
        rev = None
        ref_main = os.path.join(repo, "refs", "main")
        if os.path.isfile(ref_main):
            with open(ref_main) as f:
                rev = f.read().strip()
        candidates = [rev] if rev else sorted(os.listdir(snaps))
        for r in candidates:
            d = os.path.join(snaps, r) if r else None
            if d and os.path.isdir(d) and _has_weights(d):
                return d
    return None
