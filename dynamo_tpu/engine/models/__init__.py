"""Model forward passes (functional JAX, stacked-layer scan)."""
