"""Model forward passes (functional JAX, stacked-layer scan).

Families dispatch on ``ModelConfig.architecture``: each module exposes
``init_params / prefill / decode`` with the same paged-cache signature so
the scheduler, prefix cache, KVBM and disaggregation drive any family
uniformly (the role vLLM's model registry plays for the reference's
engines)."""

from dynamo_tpu.engine.config import ModelConfig


def get_module(config: ModelConfig):
    if config.architecture == "llama":
        from dynamo_tpu.engine.models import llama

        return llama
    if config.architecture == "mla":
        from dynamo_tpu.engine.models import mla

        return mla
    raise ValueError(f"unknown architecture {config.architecture!r}")
