"""Llama-family transformer: functional forward passes over a paged KV cache.

TPU-first design choices (vs the reference's CUDA engines):
- **Stacked layers + ``lax.scan``**: one compiled layer body regardless of
  depth — fast compiles, XLA-friendly.
- **Static shapes**: prefill runs on bucketed sequence lengths, decode on
  bucketed batch sizes; the scheduler picks the bucket, XLA caches one
  executable per bucket.
- **Paged KV**: block-table scatter on write, block gather on read. The
  gather-based attention keeps everything in pure XLA (works on CPU test
  meshes); the Pallas paged-attention kernel in
  ``dynamo_tpu.engine.attention`` replaces the gather on real TPUs.
- **bf16 weights/activations, f32 softmax + norms** (MXU-friendly).

Block 0 of the pool is reserved as a scratch sink: padded token positions
scatter there so no real block is corrupted (the allocator never hands out
block 0).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.engine.kv_cache import QuantKv, quantize_kv_rows, ragged_scatter_targets
from dynamo_tpu.engine.quant import dequant_layer

Params = Dict[str, jax.Array]

# decode_multi hoisted-gather budget: the once-per-window packed prefix
# buffer ([L, B, ctx, KVH, HD] × k+v) must stay well under spare HBM. Past
# this, the window falls back to per-step gathers.
_HOIST_GATHER_MAX_BYTES = 4 << 30


def _hoist_gather_budget() -> int:
    """Resolve the hoist cap at trace time. Env override first; otherwise a
    third of currently-free device memory (the buffer shares HBM with its
    own transient gather output), bounded by the static cap — a
    memory-tight config (e.g. int8 KV chosen for capacity, where the
    hoisted bf16 buffer is 2× the prefix's cache bytes) must fall back to
    per-step gathers rather than OOM a deployment that decoded fine
    before hoisting existed."""
    env = os.environ.get("DYNAMO_TPU_HOIST_GATHER_MAX_BYTES")
    if env is not None:
        return int(env)
    try:
        stats = jax.devices()[0].memory_stats() or {}
        free = int(stats.get("bytes_limit", 0)) - int(stats.get("bytes_in_use", 0))
        if free > 0:
            return min(_HOIST_GATHER_MAX_BYTES, free // 3)
    except Exception:
        pass
    return _HOIST_GATHER_MAX_BYTES


def _gather_kv(flat, idx, dtype):
    """Gather KV rows through a block-table index; int8 caches dequantize on
    the way out (per-token-per-head symmetric scale).

    Dequant runs directly in the compute dtype — an f32 intermediate would
    double the materialized bytes (int8 codes are ≤7 bits of mantissa,
    safely inside bf16). Note: on current XLA:TPU the int8 gather itself
    does not run faster than bf16 (measured: parity at b8, slower at wide
    batch — the gather widens byte elements internally), so int8 KV is a
    CAPACITY feature (double the blocks per HBM byte — longer contexts,
    bigger batches before preemption), not a decode-latency one."""
    if isinstance(flat, QuantKv):
        return flat.q[idx].astype(dtype) * flat.scale[idx].astype(dtype)
    return flat[idx]


def _scatter_kv(cache, layer_idx, blocks, offs, rows):
    """Scatter fresh KV rows into the cache; int8 caches quantize on the way
    in (requantization is stable to within one code step)."""
    if isinstance(cache, QuantKv):
        qk = quantize_kv_rows(rows)
        return QuantKv(
            cache.q.at[layer_idx, blocks, offs].set(qk.q),
            cache.scale.at[layer_idx, blocks, offs].set(qk.scale),
        )
    return cache.at[layer_idx, blocks, offs].set(rows)


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------


def init_params(config: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    """Random-init weights (testing / benchmarking). HF checkpoint loading
    lives in ``dynamo_tpu.engine.weights``."""
    c = config
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    def dense(key, shape, scale=None):
        scale = scale if scale is not None else (shape[-2] ** -0.5 if len(shape) >= 2 else 0.02)
        return (jax.random.normal(key, shape, dtype=jnp.float32) * scale).astype(dtype)

    L = c.num_layers
    keys = jax.random.split(k_layers, 8)
    layers: Dict[str, jax.Array] = {
        "attn_norm": jnp.ones((L, c.hidden_size), dtype=dtype),
        "mlp_norm": jnp.ones((L, c.hidden_size), dtype=dtype),
        "wq": dense(keys[0], (L, c.hidden_size, c.q_size)),
        "wk": dense(keys[1], (L, c.hidden_size, c.kv_size)),
        "wv": dense(keys[2], (L, c.hidden_size, c.kv_size)),
        "wo": dense(keys[3], (L, c.q_size, c.hidden_size)),
    }
    if c.num_experts == 0:
        layers.update(
            w_gate=dense(keys[4], (L, c.hidden_size, c.intermediate_size)),
            w_up=dense(keys[5], (L, c.hidden_size, c.intermediate_size)),
            w_down=dense(keys[6], (L, c.intermediate_size, c.hidden_size)),
        )
    else:
        E = c.num_experts
        layers.update(
            router=dense(keys[7], (L, c.hidden_size, E)),
            w_gate=dense(keys[4], (L, E, c.hidden_size, c.intermediate_size)),
            w_up=dense(keys[5], (L, E, c.hidden_size, c.intermediate_size)),
            w_down=dense(keys[6], (L, E, c.intermediate_size, c.hidden_size)),
        )
    params: Params = {
        "embed": dense(k_embed, (c.vocab_size, c.hidden_size), scale=0.02),
        "final_norm": jnp.ones((c.hidden_size,), dtype=dtype),
        "layers": layers,
    }
    if not c.tie_word_embeddings:
        params["lm_head"] = dense(k_head, (c.hidden_size, c.vocab_size), scale=0.02)
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    norm = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (norm * weight.astype(jnp.float32)).astype(x.dtype)


def rope_frequencies(head_dim: int, theta: float) -> jax.Array:
    return 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))


def apply_rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """x: [..., T, heads, head_dim]; positions: [..., T]."""
    freqs = rope_frequencies(x.shape[-1], theta)  # [hd/2]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., T, hd/2]
    cos = jnp.cos(angles)[..., None, :]  # [..., T, 1, hd/2]
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _route(x: jax.Array, lp: Dict[str, jax.Array], K: int):
    """Top-k routing: (weights [T,K] f32 softmax over the chosen experts,
    expert ids [T,K] i32)."""
    router_logits = (x @ lp["router"]).astype(jnp.float32)  # [T, E]
    top_vals, top_idx = lax.top_k(router_logits, K)
    return jax.nn.softmax(top_vals, axis=-1), top_idx


def _moe_dense(x: jax.Array, lp: Dict[str, jax.Array], config: ModelConfig) -> jax.Array:
    """Every expert computes every token; router weights combine. Exact but
    compute inflates ×E/K — the tiny-model / debugging fallback."""
    T = x.shape[0]
    E, K = config.num_experts, config.num_experts_per_tok
    weights, top_idx = _route(x, lp, K)
    weights = weights.astype(x.dtype)
    combine = jnp.zeros((T, E), dtype=x.dtype).at[jnp.arange(T)[:, None], top_idx].set(weights)
    g = jnp.einsum("td,edf->tef", x, lp["w_gate"])
    u = jnp.einsum("td,edf->tef", x, lp["w_up"])
    h = jax.nn.silu(g) * u
    out = jnp.einsum("tef,efd->ted", h, lp["w_down"])
    return jnp.einsum("ted,te->td", out, combine)


def _moe_ragged(
    x: jax.Array, lp: Dict[str, jax.Array], config: ModelConfig, valid: Optional[jax.Array] = None
) -> jax.Array:
    """Sparse dispatch via grouped GEMM (``lax.ragged_dot``): sort the T·K
    (token, expert) assignments by expert, run one ragged matmul per
    projection over the expert-contiguous rows, and scatter-add the weighted
    outputs back. Exact (no token drops) and per-token expert FLOPs scale
    with K, not E — the MegaBlocks formulation in native XLA. Best on a
    single shard or tp-sharded weights (the group axis cannot be partitioned
    over ``ep``; use "capacity" dispatch there).

    ``valid`` masks padded rows (inactive decode lanes / prefill padding):
    they are folded into expert 0's group (finite compute, bounded by bucket
    padding) and combined with weight 0."""
    T = x.shape[0]
    E, K = config.num_experts, config.num_experts_per_tok
    weights, top_idx = _route(x, lp, K)
    flat_e = top_idx.reshape(-1)  # [T*K]
    wflat = weights.reshape(-1)
    if valid is not None:
        vflat = jnp.repeat(valid, K)
        flat_e = jnp.where(vflat, flat_e, 0)
        wflat = jnp.where(vflat, wflat, 0.0)
    order = jnp.argsort(flat_e)  # stable: expert-major, token order within
    tok = order // K  # source token per sorted row
    xs = x[tok]  # [T*K, D]
    group_sizes = jnp.bincount(flat_e, length=E)  # [E]
    g = lax.ragged_dot(xs, lp["w_gate"], group_sizes)
    u = lax.ragged_dot(xs, lp["w_up"], group_sizes)
    h = jax.nn.silu(g) * u
    y = lax.ragged_dot(h, lp["w_down"], group_sizes)  # [T*K, D]
    w_sorted = wflat[order].astype(x.dtype)
    return jnp.zeros_like(x).at[tok].add(y * w_sorted[:, None])


def _moe_capacity(
    x: jax.Array, lp: Dict[str, jax.Array], config: ModelConfig, valid: Optional[jax.Array] = None
) -> jax.Array:
    """GShard-style capacity-factor dispatch: each expert owns C static
    slots (C = T·K/E · capacity_factor); dispatch/combine are one-hot
    einsums over [E, C, T], so GSPMD partitions the expert axis over the
    ``ep`` mesh and the FFN hidden dim over ``tp`` with a single psum
    combine — the wide-EP serving path. Earlier tokens win slots; a token
    overflowing every chosen expert's capacity contributes only its residual
    (raise ``moe_capacity_factor`` if drop counters show pressure).

    ``valid`` masks padded rows so inactive decode lanes cannot steal
    capacity slots from live tokens (they are excluded from the slot count
    and dispatched nowhere).

    Cost note: the dispatch/combine einsums are O(E·C·T·D) = O(cf·K·T²·D) —
    quadratic in T. Relative to the expert GEMMs (O(cf·K·T·D·F)) that is
    ~T/(3F): negligible for decode batches, ~5% at T=2048/F=14336, growing
    linearly with prefill chunk length — bound the chunk size fed through
    this path (the scheduler's prefill buckets already do)."""
    import math

    T = x.shape[0]
    E, K = config.num_experts, config.num_experts_per_tok
    C = max(1, min(T, math.ceil(T * K * config.moe_capacity_factor / E)))
    weights, top_idx = _route(x, lp, K)
    flat_e = top_idx.reshape(-1)  # [T*K]
    tok = jnp.arange(T * K, dtype=jnp.int32) // K
    # Slot of each assignment within its expert's queue (t-major priority).
    onehot = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)  # [T*K, E]
    if valid is not None:
        # Invalid rows occupy no slots and are never dispatched.
        onehot = onehot * jnp.repeat(valid, K).astype(jnp.int32)[:, None]
    slot = jnp.take_along_axis(jnp.cumsum(onehot, axis=0) - onehot, flat_e[:, None], axis=1)[:, 0]
    keep = slot < C
    live = jnp.ones_like(keep) if valid is None else jnp.repeat(valid, K)
    keep = keep & live
    # Capacity-drop accounting: live assignments that lost the slot race
    # (their token contributes only its residual). Exported per step via
    # ForwardPassMetrics → Prometheus when moe_stats is requested (ref:
    # wide-EP observability, SURVEY.md §2e).
    dropped = jnp.sum(live & ~keep).astype(jnp.int32)
    slot_c = jnp.clip(slot, 0, C - 1)
    # (e, slot) pairs are unique among kept rows (cumsum), so .add == .set;
    # dropped rows add 0.
    disp = jnp.zeros((E, C, T), dtype=x.dtype).at[flat_e, slot_c, tok].add(keep.astype(x.dtype))
    comb = jnp.zeros((E, C, T), dtype=jnp.float32).at[flat_e, slot_c, tok].add(
        jnp.where(keep, weights.reshape(-1), 0.0)
    )
    xe = jnp.einsum("ect,td->ecd", disp, x)  # gather tokens into slots
    g = jnp.einsum("ecd,edf->ecf", xe, lp["w_gate"])
    u = jnp.einsum("ecd,edf->ecf", xe, lp["w_up"])
    h = jax.nn.silu(g) * u
    ye = jnp.einsum("ecf,efd->ecd", h, lp["w_down"])
    out = jnp.einsum("ecd,ect->td", ye.astype(jnp.float32), comb).astype(x.dtype)
    return out, dropped


def _mlp(
    x: jax.Array,
    lp: Dict[str, jax.Array],
    config: ModelConfig,
    valid: Optional[jax.Array] = None,
    stats: bool = False,
):
    """Feed-forward block: dense SwiGLU, or MoE when config.num_experts > 0.

    MoE dispatch is selected by ``config.moe_dispatch`` (see config.py):
    "ragged" (exact grouped GEMM, K-scaling FLOPs) by default, "capacity"
    (GShard einsum dispatch over the ``ep`` axis) for wide-EP meshes,
    "dense" as the exhaustive fallback. "auto" resolves via
    ``resolve_moe_dispatch`` wherever the mesh is known (Scheduler,
    pipelined decode); direct model calls default to "ragged". The reference
    only *configures* wide-EP in its engines (SURVEY.md §2e,
    trtllm_utils.py:37); here the dispatch kernel is native.

    ``valid`` marks live rows (decode ``active`` lanes / prefill valid
    tokens); sparse dispatch excludes dead rows so they cannot consume
    expert capacity meant for live tokens.

    With ``stats=True`` returns ``(out, dropped i32)`` — the number of live
    (token, expert) assignments dropped by capacity pressure this call
    (always 0 for exact dispatch modes)."""
    if config.num_experts == 0:
        out = (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
        return (out, jnp.int32(0)) if stats else out
    mode = config.moe_dispatch
    if mode == "auto":
        mode = "ragged"
    if mode == "dense":
        out = _moe_dense(x, lp, config)
        return (out, jnp.int32(0)) if stats else out
    if mode == "ragged":
        out = _moe_ragged(x, lp, config, valid)
        return (out, jnp.int32(0)) if stats else out
    out, dropped = _moe_capacity(x, lp, config, valid)
    return (out, dropped) if stats else out


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


_warned_paged_int8 = False


def resolve_attention_impl(c: ModelConfig, k_cache) -> str:
    """Resolve ``ModelConfig.attention_impl`` against the backend and the
    cache dtype → one of ``"gather" | "paged" | "megakernel"``.

    - ``"auto"`` flips to the ragged megakernel on TPU (where its
      one-launch-per-layer amortization wins — see the attention_impl
      docstring for the measured record) and stays on the XLA gather off-
      TPU (interpreted Pallas is test-only).
    - ``"paged"`` (the r5 per-piece kernel) has no int8 path; int8-KV
      deployments degrade to the gather with a logged warning instead of
      the former hard ValueError — the megakernel is the int8-capable
      fused path.
    """
    impl = c.attention_impl
    if impl == "auto":
        impl = "megakernel" if _on_tpu() else "gather"
    if impl == "paged" and isinstance(k_cache, QuantKv):
        # Pure resolution only: this runs inside traced bodies
        # (_use_paged_decode / _use_megakernel), where host-side logging is
        # a trace-time effect. warn_attention_impl_degrade() carries the
        # operator-facing warning from the scheduler's init path.
        impl = "gather"
    return impl


def warn_attention_impl_degrade(c: ModelConfig, k_cache) -> None:
    """Host-side companion to ``resolve_attention_impl``: log the paged+int8
    degrade once, from setup code (the scheduler's __init__), never from a
    jit-reachable body."""
    global _warned_paged_int8
    if (
        c.attention_impl == "paged"
        and isinstance(k_cache, QuantKv)
        and not _warned_paged_int8
    ):
        _warned_paged_int8 = True
        import logging

        logging.getLogger(__name__).warning(
            "attention_impl='paged' has no int8-KV path — degrading to "
            "the XLA gather for this deployment. Use "
            "attention_impl='megakernel' for the fused int8 "
            "dequant-in-VMEM path."
        )


def _use_paged_decode(c: ModelConfig, k_cache) -> bool:
    """The r5 per-piece Pallas paged kernel (attention/decode.py) — still
    explicit opt-in only; superseded by the ragged megakernel for the
    fused path. int8 caches degrade to gather (resolve_attention_impl)."""
    return resolve_attention_impl(c, k_cache) == "paged"


def _use_megakernel(c: ModelConfig, k_cache) -> bool:
    """Ragged paged-attention megakernel (attention/megakernel.py): ONE
    launch per layer serves every row of the step — prefill chunks,
    mixed-step ragged batches, and decode rows — with no gathered prefix
    copy and pl.when-skipped dead slots. Auto-selected on TPU."""
    return resolve_attention_impl(c, k_cache) == "megakernel"


def _mega_attend_rows(
    c: ModelConfig,
    q: jax.Array,  # [NQ, H, HD]
    k_extra: jax.Array,  # [CK, KVH, HD]
    v_extra: jax.Array,
    k_flat,  # [L*N, BS, KVH, HD] layer-flat pages (QuantKv ok)
    v_flat,
    tables: jax.Array,  # [R, W] layer-offset page tables
    meta: jax.Array,  # [5, NQ] megakernel.build_meta
) -> jax.Array:
    """One fused ragged-attention launch for a whole step's rows."""
    from dynamo_tpu.engine.attention.megakernel import ragged_paged_attention

    return ragged_paged_attention(
        q, k_extra, v_extra, k_flat, v_flat, tables, meta,
        num_kv_heads=c.num_kv_heads, block_size=c.block_size,
        interpret=not _on_tpu(),
    )


def _paged_prefix_partials(c: ModelConfig, q, k_flat, v_flat, tables_l, lengths):
    """Kernel-backed prefix piece in the ``_attend_piece`` partial layout."""
    from dynamo_tpu.engine.attention.decode import paged_decode_partials

    return paged_decode_partials(
        q, k_flat, v_flat, tables_l, lengths,
        num_kv_heads=c.num_kv_heads, block_size=c.block_size,
        interpret=not _on_tpu(),
    )


def _attend_piece(qg, kp, vp, maskp, scale):
    """Partial decode attention over one KV piece → (m, l, acc) online-
    softmax state. qg [B,KVH,G,hd]; kp/vp [B,S,KVH,hd]; maskp [B,S].
    Shared by both decode backends: the Pallas paged kernel produces the
    same partials for the cached prefix, so the pieces merge identically."""
    s = jnp.einsum("bkgd,bskd->bkgs", qg, kp).astype(jnp.float32) * scale
    s = jnp.where(maskp[:, None, None, :], s, -1e30)
    m = jnp.max(s, axis=-1)  # [B,KVH,G]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bkgs,bskd->bkgd", p.astype(vp.dtype), vp).astype(jnp.float32)
    return m, l, acc


def _merge_pieces(m1, l1, acc1, m2, l2, acc2) -> jax.Array:
    """Close the online softmax across two attention pieces → [B,KVH,G,hd]
    f32 (caller casts). All-masked pieces (m = -inf, l = 0) drop out."""
    m_t = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m_t)
    a2 = jnp.exp(m2 - m_t)
    l_t = l1 * a1 + l2 * a2
    acc = acc1 * a1[..., None] + acc2 * a2[..., None]
    return acc / jnp.maximum(l_t, 1e-30)[..., None]


def _attend(q: jax.Array, k: jax.Array, v: jax.Array, mask: jax.Array, config: ModelConfig) -> jax.Array:
    """q: [T, H, hd]; k/v: [S, KVH, hd]; mask: [T, S] bool → [T, H, hd].

    Grouped-query form: query heads are folded into (kv_head, group) so the
    KV tensors are used as-is — no ``jnp.repeat`` materialization (which
    would multiply HBM traffic by the group factor every layer)."""
    T = q.shape[0]
    kvh, hd = config.num_kv_heads, config.head_dim
    groups = config.num_heads // kvh
    qg = q.reshape(T, kvh, groups, hd)
    scale = config.head_dim ** -0.5
    scores = jnp.einsum("tkgd,skd->ktgs", qg, k).astype(jnp.float32) * scale
    scores = jnp.where(mask[None, :, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("ktgs,skd->tkgd", probs, v)
    return out.reshape(T, config.num_heads, hd)


# ---------------------------------------------------------------------------
# Prefill
# ---------------------------------------------------------------------------


def prefill(
    params: Params,
    config: ModelConfig,
    k_cache: jax.Array,  # [L, N, BS, KVH, HD]
    v_cache: jax.Array,
    tokens: jax.Array,  # [T] bucket-padded token ids
    valid_len: jax.Array,  # scalar: actual new tokens
    cache_len: jax.Array,  # scalar: tokens already in the block table (prefix reuse / chunked prefill)
    block_table: jax.Array,  # [W] block ids (0 = scratch); W bucketed by the caller
    all_logits: bool = False,  # static: return logits for every position [T, V]
    use_flash: bool = False,  # static: Pallas flash kernel for chunk attention
    has_prefix: bool = True,  # static: False ⇒ cache_len == 0, skip the prefix piece
    mm_feats: Optional[jax.Array] = None,  # [F, D] multimodal feature rows
    mm_len: Optional[jax.Array] = None,  # scalar i32: valid feature rows
    moe_stats: bool = False,  # static: also return {"moe_dropped", "moe_assignments"}
) -> Tuple[jax.Array, ...]:
    """One prefill (or prefill chunk). Returns (last_logits [V], k_cache,
    v_cache) — or ([T, V] logits with ``all_logits=True``, the target-model
    verification pass for speculative decoding; spec_decode.py).

    With ``use_flash`` the chunk's causal self-attention runs in the Pallas
    flash kernel (attention/prefill.py — scores never leave VMEM) and the
    cached-prefix piece (absent for fresh prefills: ``has_prefix=False``)
    is an online-softmax partial merged outside the kernel. The XLA path
    (use_flash=False) materializes the full [T, ctx+T] mask — CPU meshes /
    debugging."""
    c = config
    bs = c.block_size
    T = tokens.shape[0]
    ctx = block_table.shape[0] * bs

    h = params["embed"].at[tokens].get(mode="clip")  # [T, D]
    positions = cache_len + jnp.arange(T, dtype=jnp.int32)
    valid_q = jnp.arange(T, dtype=jnp.int32) < valid_len
    if mm_feats is not None:
        # Multimodal early fusion: positions [0, mm_len) are image-feature
        # rows (vision-prefix); override their token embeddings with the
        # encoder's projected features (ref role: trtllm encode_helper.py —
        # the encode worker hands features to prefill).
        inject = (positions < mm_len) & valid_q
        rows = mm_feats.at[jnp.clip(positions, 0, mm_feats.shape[0] - 1)].get(mode="clip")
        h = jnp.where(inject[:, None], rows.astype(h.dtype), h)

    # Scatter targets for the new tokens; padded positions sink to block 0.
    tgt_blocks, tgt_offs = ragged_scatter_targets(block_table, positions, valid_q, bs)

    # The cache is READ-ONLY inside the layer scan (slices ride the scan xs);
    # each layer's fresh chunk K/V is attended in-register and stacked into
    # the scan ys, then ONE fused scatter writes all layers afterwards. A
    # scatter inside the carry forced XLA into a full cache copy per layer
    # (~5 ms/step at 1B/b8 on v5e — measured); this formulation keeps the
    # cache bytes touched proportional to the tokens written.
    interp = jax.default_backend() != "tpu"
    kvh = c.num_kv_heads

    # Layer-flat cache view: gathering from [L*N, ...] with layer-offset
    # tables avoids the scan's per-layer dynamic-slice of the cache, which
    # XLA materializes as a full layer-cache copy per iteration (measured:
    # the dominant decode-attention cost at 1B/b32 on v5e). The reshape is
    # layout-free ([L, N] row-major ≡ [L*N]); block 0 of every layer stays a
    # scratch sink because offset tables map 0 → l*N, layer l's own block 0.
    L = c.num_layers
    N = k_cache.shape[1]
    k_flat = k_cache.reshape(L * N, bs, c.num_kv_heads, c.head_dim)
    v_flat = v_cache.reshape(L * N, bs, c.num_kv_heads, c.head_dim)

    use_mega = _use_megakernel(c, k_cache)
    if use_mega:
        # The prefill chunk is one ragged megakernel row: causal fresh
        # chunk + paged prefix in ONE launch per layer — no gathered
        # prefix copy, pad queries (and fresh prefills' empty prefix)
        # skipped dead in-kernel.
        from dynamo_tpu.engine.attention.megakernel import build_meta

        t_iq = jnp.arange(T, dtype=jnp.int32)
        mega_meta = build_meta(
            jnp.zeros((T,), jnp.int32),
            jnp.full((T,), cache_len, jnp.int32),
            jnp.zeros((T,), jnp.int32),
            t_iq + 1,
            (t_iq < valid_len).astype(jnp.int32),
        )

    def layer_fn(h, xs):
        lp, l = xs  # l: scalar layer index
        lp = dequant_layer(lp, h.dtype)  # int8 weight-only storage
        x = rms_norm(h, lp["attn_norm"], c.rms_norm_eps)
        q = (x @ lp["wq"]).reshape(T, c.num_heads, c.head_dim)
        k = (x @ lp["wk"]).reshape(T, c.num_kv_heads, c.head_dim)
        v = (x @ lp["wv"]).reshape(T, c.num_kv_heads, c.head_dim)
        q = apply_rope(q, positions, c.rope_theta)
        k = apply_rope(k, positions, c.rope_theta)

        if use_mega:
            attn = _mega_attend_rows(
                c, q, k, v, k_flat, v_flat,
                (block_table + l * N)[None, :], mega_meta,
            ).astype(h.dtype)
            h = h + attn.reshape(T, c.q_size) @ lp["wo"]
            x = rms_norm(h, lp["mlp_norm"], c.rms_norm_eps)
            if moe_stats:
                mlp_out, drops = _mlp(x, lp, c, valid=valid_q, stats=True)
                h = h + mlp_out
                return h, (k, v, drops)
            h = h + _mlp(x, lp, c, valid=valid_q)
            return h, (k, v)

        # Ragged chunk attention over [cached prefix ; chunk] — shared with
        # the mixed prefill+decode step (attention/ragged.py). The prefix
        # gather is bounded by the caller's width-bucketed table — the true
        # prefix extent, not max_seq_len; flash fresh chunks skip it.
        from dynamo_tpu.engine.attention.ragged import ragged_chunk_attention

        if use_flash and not has_prefix:
            k_ctx = v_ctx = None
        else:
            table_l = block_table + l * N
            k_ctx = _gather_kv(k_flat, table_l, h.dtype).reshape(ctx, kvh, c.head_dim)
            v_ctx = _gather_kv(v_flat, table_l, h.dtype).reshape(ctx, kvh, c.head_dim)
        attn = ragged_chunk_attention(
            q, k, v, k_ctx, v_ctx, valid_len, cache_len,
            num_kv_heads=kvh, use_flash=use_flash, has_prefix=has_prefix,
            interpret=interp,
        )
        h = h + attn.reshape(T, c.q_size) @ lp["wo"]

        x = rms_norm(h, lp["mlp_norm"], c.rms_norm_eps)
        if moe_stats:
            mlp_out, drops = _mlp(x, lp, c, valid=valid_q, stats=True)
            h = h + mlp_out
            return h, (k, v, drops)
        h = h + _mlp(x, lp, c, valid=valid_q)
        return h, (k, v)

    if moe_stats:
        h, (k_rows, v_rows, layer_drops) = lax.scan(
            layer_fn, h, (params["layers"], jnp.arange(L, dtype=jnp.int32))
        )
        aux = {
            "moe_dropped": jnp.sum(layer_drops),
            "moe_assignments": jnp.sum(valid_q).astype(jnp.int32)
            * jnp.int32(max(c.num_experts_per_tok, 1) * L),
        }
    else:
        h, (k_rows, v_rows) = lax.scan(
            layer_fn, h, (params["layers"], jnp.arange(L, dtype=jnp.int32))
        )

    # One all-layer scatter: [L, T] targets into the donated cache buffers.
    layer_idx = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[:, None], (L, T))
    k_new = _scatter_kv(k_cache, layer_idx, tgt_blocks[None, :], tgt_offs[None, :], k_rows)
    v_new = _scatter_kv(v_cache, layer_idx, tgt_blocks[None, :], tgt_offs[None, :], v_rows)

    head = params.get("lm_head")
    if all_logits:
        h_all = rms_norm(h, params["final_norm"], c.rms_norm_eps)
        logits = h_all @ (head if head is not None else params["embed"].T)
    else:
        last = jnp.maximum(valid_len - 1, 0)
        h_last = rms_norm(h[last], params["final_norm"], c.rms_norm_eps)
        logits = h_last @ (head if head is not None else params["embed"].T)
    if moe_stats:
        return logits.astype(jnp.float32), k_new, v_new, aux
    return logits.astype(jnp.float32), k_new, v_new


def decode_multi(
    params: Params,
    config: ModelConfig,
    k_cache: jax.Array,  # [L, N, BS, KVH, HD]
    v_cache: jax.Array,
    tokens: jax.Array,  # [B] current token per sequence
    positions: jax.Array,  # [B] write slot of the current token
    block_tables: jax.Array,  # [B, max_blocks] — must cover positions+num_steps
    active: jax.Array,  # [B] bool
    temps: jax.Array,  # [B] f32 (0 = greedy)
    top_ks: jax.Array,  # [B] i32 (0 = off)
    top_ps: jax.Array,  # [B] f32 (1 = off)
    rng_key: jax.Array,
    num_steps: int,
    moe_stats: bool = False,  # static: also return {"moe_dropped", "moe_assignments"}
    return_logits: bool = False,  # static: also return per-step logits [steps, B, V]
    uniforms: Optional[jax.Array] = None,  # [num_steps, B] — inverse-CDF draws
) -> Tuple[jax.Array, ...]:
    """``num_steps`` autoregressive decode steps + on-device sampling in ONE
    compiled dispatch. Returns (tokens_out [num_steps, B], k_cache, v_cache).

    The TPU-native answer to per-step dispatch overhead (the reference's
    engines expose the same lever as vLLM ``--num-scheduler-steps``): the
    sample→embed feedback loop stays on device, so the host syncs once per
    window instead of once per token. Stop conditions are checked on the
    host afterwards; tokens past a stop are trimmed by the scheduler.

    **Window-local KV**: the paged cache is READ-ONLY for the entire window.
    Each step's fresh K/V rows accumulate in a small carry
    (``[L, num_steps, B, KVH, HD]``) that attention folds in alongside the
    cached prefix, and ONE fused scatter writes the whole window afterwards.
    Scattering into the cache carry every step forced XLA into a full cache
    copy per iteration (scatter in-place elision does not fire for gather-
    indexed writes inside a while body — measured ~0.9 ms/step/tensor at 1B
    scale on v5e, dominating the step); the window carry is KV-row-sized, so
    the per-step write cost is proportional to tokens produced, not cache
    size.

    With ``uniforms`` ([num_steps, B] from sampling.make_window_uniforms)
    each step samples via the shared reference filter + inverse-CDF draw
    instead of the threaded PRNG key — the uniforms contract the fused
    megakernel window uses, so this path is the bit-identical host replay
    the sampled-fused parity tests compare against."""
    if moe_stats and return_logits:
        raise NotImplementedError(
            "decode_multi: moe_stats and return_logits cannot be combined yet "
            "(the return tuples would be ambiguous to existing unpackers)"
        )
    from dynamo_tpu.engine.sampling import sample_batch, sample_from_uniforms

    c = config
    B = tokens.shape[0]
    L, KVH, HD = c.num_layers, c.num_kv_heads, c.head_dim
    bs = c.block_size

    # Cached-prefix mask is fixed for the whole window (the cache is not
    # written during it); window rows carry the in-flight tokens.
    _, _, mask0 = decode_targets(positions, block_tables, active, bs)

    # Hoist the cached-prefix gather out of the window loop: the prefix is
    # read-only for the whole window, so gathering it per step pays the
    # materialize-write + re-read (2× the true KV bytes) num_steps times
    # over. One gather up front amortizes that to 1/num_steps; each step
    # then streams the packed buffer (measured b32/ctx1024/w16 on v5e:
    # 9.7 → ~6.9 ms/step). Capped so wide-batch × long-context shapes don't
    # pin multi-GB buffers — past the cap the per-step gather path runs.
    wdtype = params["embed"].dtype
    ctx_w = block_tables.shape[1] * bs
    N = k_cache.shape[1]
    k_ctx_all = v_ctx_all = None
    hoist_bytes = 2 * L * B * ctx_w * KVH * HD * jnp.dtype(wdtype).itemsize
    if (
        num_steps > 1
        and not _use_paged_decode(c, k_cache)
        and not _use_megakernel(c, k_cache)
        and hoist_bytes <= _hoist_gather_budget()
    ):
        k_flat = k_cache.reshape(L * N, bs, KVH, HD)
        v_flat = v_cache.reshape(L * N, bs, KVH, HD)
        tables_all = block_tables[None] + (jnp.arange(L, dtype=jnp.int32) * N)[:, None, None]
        k_ctx_all = _gather_kv(k_flat, tables_all, wdtype).reshape(L, B, ctx_w, KVH, HD)
        v_ctx_all = _gather_kv(v_flat, tables_all, wdtype).reshape(L, B, ctx_w, KVH, HD)

    def body(i, state):
        toks, k_win, v_win, out, lg_out, key, drops = state
        poss = positions + i
        h = params["embed"].at[toks].get(mode="clip")  # [B, D]
        h, k_rows, v_rows, step_drops = _decode_layer_scan_window(
            params["layers"], c, k_cache, v_cache, h, poss, block_tables,
            mask0, k_win, v_win, i, active, moe_stats=moe_stats,
            k_ctx_all=k_ctx_all, v_ctx_all=v_ctx_all,
        )
        k_win = k_win.at[:, i].set(k_rows)
        v_win = v_win.at[:, i].set(v_rows)
        h = rms_norm(h, params["final_norm"], c.rms_norm_eps)
        head = params.get("lm_head")
        logits = (h @ (head if head is not None else params["embed"].T)).astype(jnp.float32)
        key, sub = jax.random.split(key)
        if uniforms is not None:
            nxt = sample_from_uniforms(
                logits, temps, top_ks, top_ps, uniforms[i]
            ).astype(jnp.int32)
        else:
            nxt = sample_batch(logits, temps, top_ks, top_ps, sub).astype(jnp.int32)
        out = out.at[i].set(nxt)
        if return_logits:
            lg_out = lg_out.at[i].set(logits)
        return (nxt, k_win, v_win, out, lg_out, key, drops + step_drops)

    # Window rows are IN-FLIGHT real values (compute dtype) — int8 caches
    # only quantize at the final fused scatter. (cache.dtype would be int8
    # for QuantKv: scattering f32 rows into it is an unsafe cast — a JAX
    # FutureWarning today, an error in future releases — and would strip
    # the scales.)
    k_win0 = jnp.zeros((L, num_steps, B, KVH, HD), dtype=wdtype)
    v_win0 = jnp.zeros((L, num_steps, B, KVH, HD), dtype=wdtype)
    out0 = jnp.zeros((num_steps, B), dtype=jnp.int32)
    V = params["embed"].shape[0]
    lg0 = jnp.zeros((num_steps if return_logits else 1, B, V if return_logits else 1), jnp.float32)
    _, k_win, v_win, out, lg_steps, _, total_drops = lax.fori_loop(
        0, num_steps, body, (tokens, k_win0, v_win0, out0, lg0, rng_key, jnp.int32(0))
    )

    # One fused scatter for the whole window: row (l, j, b) → slot pos_b + j.
    steps_i = jnp.arange(num_steps, dtype=jnp.int32)
    slots = jnp.where(active[None, :], positions[None, :] + steps_i[:, None], 0)  # [w, B]
    tgt_blocks = jnp.where(
        active[None, :], block_tables[jnp.arange(B)[None, :], slots // bs], 0
    )  # [w, B] — inactive rows sink to scratch block 0
    tgt_offs = slots % bs
    layer_idx = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[:, None, None], (L, num_steps, B))
    k_new = _scatter_kv(k_cache, layer_idx, tgt_blocks[None], tgt_offs[None], k_win)
    v_new = _scatter_kv(v_cache, layer_idx, tgt_blocks[None], tgt_offs[None], v_win)
    if moe_stats:
        aux = {
            "moe_dropped": total_drops,
            "moe_assignments": jnp.sum(active).astype(jnp.int32)
            * jnp.int32(max(c.num_experts_per_tok, 1) * L * num_steps),
        }
        return out, k_new, v_new, aux
    if return_logits:
        return out, lg_steps, k_new, v_new
    return out, k_new, v_new


def decode_multi_fused(
    params: Params,
    config: ModelConfig,
    k_cache: jax.Array,  # [L, N, BS, KVH, HD]
    v_cache: jax.Array,
    tokens: jax.Array,  # [B] current token per sequence
    positions: jax.Array,  # [B] write slot of the current token
    block_tables: jax.Array,  # [B, W] — must cover positions+num_steps
    active: jax.Array,  # [B] bool
    num_steps: int,
    temps: Optional[jax.Array] = None,  # [B] f32 (with sampled=True)
    top_ks: Optional[jax.Array] = None,  # [B] i32
    top_ps: Optional[jax.Array] = None,  # [B] f32
    uniforms: Optional[jax.Array] = None,  # [num_steps, B] f32
    guided_rows: Optional[jax.Array] = None,  # [B] i32 (with guided=True)
    mask_pool: Optional[jax.Array] = None,  # [P, ceil(V/32)] uint32
    next_pool: Optional[jax.Array] = None,  # [P, V] i32
    sampled: bool = False,
    guided: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """``num_steps`` decode steps in ONE Pallas launch — the fused window
    megakernel (attention/megakernel.fused_decode_window). The grid
    spans (steps × layers); the sampled token feeds back through on-chip
    scratch between grid steps and KV rows are written in place, so the
    per-``pallas_call`` dispatch tax that killed the r4 kernel is paid
    once per WINDOW instead of ``num_steps × num_layers`` times, and the
    prefix pages are the only KV bytes read. Token-for-token and
    cache-content parity with greedy ``decode_multi`` (tested).

    ``sampled=True`` runs the in-kernel top-k/top-p epilogue against
    host-precomputed uniforms; ``guided=True`` masks each row by its FSM
    state's packed allow bitmask and advances the FSM on-chip via the
    next-state pool. Dense llama only (no MoE, no int8 weights) — callers
    gate via ``megakernel.fused_window_fits`` and fall back to
    ``decode_multi`` (whose attention still runs the per-step ragged
    megakernel)."""
    from dynamo_tpu.engine.attention.megakernel import fused_decode_window

    c = config
    lp = params["layers"]
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    return fused_decode_window(
        params["embed"], head, params["final_norm"],
        lp["attn_norm"], lp["mlp_norm"],
        lp["wq"], lp["wk"], lp["wv"], lp["wo"],
        lp["w_gate"], lp["w_up"], lp["w_down"],
        k_cache, v_cache, tokens, positions, block_tables, active,
        temps, top_ks, top_ps, uniforms, guided_rows, mask_pool, next_pool,
        num_steps=num_steps, num_heads=c.num_heads,
        num_kv_heads=c.num_kv_heads, head_dim=c.head_dim,
        block_size=c.block_size, rms_eps=c.rms_norm_eps,
        theta=c.rope_theta, interpret=not _on_tpu(),
        sampled=sampled, guided=guided,
    )


def decode_spec_fused(
    target_params: Params,
    target_config: ModelConfig,
    draft_params: Params,
    draft_config: ModelConfig,
    k_t: jax.Array,
    v_t: jax.Array,
    k_d: jax.Array,
    v_d: jax.Array,
    tokens: jax.Array,  # [B] i32 — last confirmed token
    xprev: jax.Array,  # [B] i32 — token at positions-1
    positions: jax.Array,  # [B] i32 — position of the last confirmed token
    tables_t: jax.Array,  # [B, W] i32
    tables_d: jax.Array,  # [B, W] i32
    active: jax.Array,  # [B] bool
    temps: jax.Array,  # [B] f32
    top_ks: jax.Array,  # [B] i32
    top_ps: jax.Array,  # [B] f32
    uniforms: jax.Array,  # [rounds, B, 2*gamma+1] f32
    rounds: int,
    gamma: int,
) -> Tuple[jax.Array, ...]:
    """``rounds`` speculative rounds (draft γ-burst + target verify +
    rejection sampling) in ONE Pallas launch — megakernel.fused_spec_window
    with both llama models' weights resolved to the kernel layout. Returns
    (tokens_out [rounds, B, γ+1], accepted [rounds, B], k_t, v_t, k_d,
    v_d)."""
    from dynamo_tpu.engine.attention.megakernel import fused_spec_window

    tc, dc = target_config, draft_config

    def _w(p):
        head = p.get("lm_head")
        if head is None:
            head = p["embed"].T
        lp = p["layers"]
        return (
            p["embed"], head, p["final_norm"], lp["attn_norm"], lp["mlp_norm"],
            lp["wq"], lp["wk"], lp["wv"], lp["wo"],
            lp["w_gate"], lp["w_up"], lp["w_down"],
        )

    return fused_spec_window(
        *_w(target_params), *_w(draft_params),
        k_t, v_t, k_d, v_d,
        tokens, xprev, positions, tables_t, tables_d, active,
        temps, top_ks, top_ps, uniforms,
        rounds=rounds, gamma=gamma, block_size=tc.block_size,
        t_num_heads=tc.num_heads, t_num_kv_heads=tc.num_kv_heads,
        t_head_dim=tc.head_dim, t_rms_eps=tc.rms_norm_eps,
        t_theta=tc.rope_theta,
        d_num_heads=dc.num_heads, d_num_kv_heads=dc.num_kv_heads,
        d_head_dim=dc.head_dim, d_rms_eps=dc.rms_norm_eps,
        d_theta=dc.rope_theta,
        interpret=not _on_tpu(),
    )


def _decode_layer_scan_window(
    layers: Dict[str, jax.Array],
    c: ModelConfig,
    k_cache: jax.Array,  # [L, N, BS, KVH, HD] — read-only throughout
    v_cache: jax.Array,
    h: jax.Array,  # [B, D]
    positions: jax.Array,  # [B] true position of the current token
    block_tables: jax.Array,  # [B, max_blocks]
    mask0: jax.Array,  # [B, ctx] cached-prefix mask (fixed at window start)
    k_win: jax.Array,  # [L, w, B, KVH, HD] window rows written so far
    v_win: jax.Array,
    step: jax.Array,  # scalar i — window rows j < i are live
    active: jax.Array,  # [B] bool
    moe_stats: bool = False,
    k_ctx_all: Optional[jax.Array] = None,  # [L, B, ctx, KVH, HD] pre-gathered
    v_ctx_all: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Decode layer scan attending [cached prefix ; window rows ; current].
    Same math as ``decode_layer_scan`` — the window rows are exactly the
    tokens a per-step cache write would have placed at positions
    pos0..pos0+i-1, read from the carry instead of the cache.

    When ``k_ctx_all``/``v_ctx_all`` are given, the cached prefix was
    gathered ONCE for the whole window (see decode_multi) and the scan
    reads per-layer slices instead of re-gathering — the gather's
    materialize-write plus re-read otherwise recurs every window step on a
    prefix that is read-only for the window's duration (measured at
    b32/ctx1024 on v5e: 4.6 ms of a 9.7 ms step in the prefix piece vs a
    1.6 ms true-bytes floor)."""
    B = h.shape[0]
    bs = c.block_size
    ctx = block_tables.shape[1] * bs
    w = k_win.shape[1]
    kvh, G, hd = c.num_kv_heads, c.num_heads // c.num_kv_heads, c.head_dim
    scale = hd**-0.5
    # Layer-flat cache views (see prefill): the scan gathers with
    # layer-offset tables instead of slicing the cache per layer.
    L = k_cache.shape[0]
    N = k_cache.shape[1]
    k_flat = k_cache.reshape(L * N, bs, kvh, hd)
    v_flat = v_cache.reshape(L * N, bs, kvh, hd)
    # Small-piece mask: window rows j < step, then the current token (always).
    small_mask = jnp.concatenate(
        [
            jnp.broadcast_to((jnp.arange(w, dtype=jnp.int32) < step)[None, :], (B, w)),
            jnp.ones((B, 1), dtype=bool),
        ],
        axis=1,
    )  # [B, w+1]

    hoisted = k_ctx_all is not None
    use_paged = not hoisted and _use_paged_decode(c, k_cache)
    use_mega = not hoisted and _use_megakernel(c, k_cache)
    # Prefix length is fixed for the whole window (mask0 semantics): the
    # window rows live in the carry, not the cache.
    win_prefix_lens = jnp.minimum(positions - step, ctx).astype(jnp.int32)
    if use_mega:
        # Megakernel row metadata: each decode query's fresh keys are its
        # row's slice of [current ; window rows] — a contiguous [start,
        # end) column window, end advancing with the in-window step (the
        # not-yet-written carry rows stay masked for free).
        from dynamo_tpu.engine.attention.megakernel import build_meta

        rows_i = jnp.arange(B, dtype=jnp.int32)
        mega_meta = build_meta(
            rows_i, win_prefix_lens, rows_i * (w + 1),
            rows_i * (w + 1) + 1 + step, jnp.ones((B,), jnp.int32),
        )

    def layer_fn(h, xs):
        if hoisted:
            lp, l, kwl, vwl, k_ctx, v_ctx = xs
        else:
            lp, l, kwl, vwl = xs  # kwl/vwl: [w, B, KVH, HD] this layer's window rows
        lp = dequant_layer(lp, h.dtype)  # int8 weight-only storage
        x = rms_norm(h, lp["attn_norm"], c.rms_norm_eps)
        q = (x @ lp["wq"]).reshape(B, 1, c.num_heads, c.head_dim)
        k = (x @ lp["wk"]).reshape(B, 1, c.num_kv_heads, c.head_dim)
        v = (x @ lp["wv"]).reshape(B, 1, c.num_kv_heads, c.head_dim)
        q = apply_rope(q, positions[:, None], c.rope_theta)[:, 0]
        k = apply_rope(k, positions[:, None], c.rope_theta)[:, 0]
        v = v[:, 0]
        qg = q.reshape(B, kvh, G, hd)

        if use_mega:
            # ONE launch: paged prefix + [current ; live window rows] —
            # the carry rows ride as the kernel's fresh-key piece.
            k_extra = jnp.concatenate(
                [k[:, None], jnp.swapaxes(kwl, 0, 1)], axis=1
            ).reshape(B * (w + 1), kvh, hd)
            v_extra = jnp.concatenate(
                [v[:, None], jnp.swapaxes(vwl, 0, 1)], axis=1
            ).reshape(B * (w + 1), kvh, hd)
            attn = _mega_attend_rows(
                c, q, k_extra, v_extra, k_flat, v_flat,
                block_tables + l * N, mega_meta,
            ).astype(h.dtype)
            h = h + attn.reshape(B, c.q_size) @ lp["wo"]
            x = rms_norm(h, lp["mlp_norm"], c.rms_norm_eps)
            if moe_stats:
                mlp_out, drops = _mlp(x, lp, c, valid=active, stats=True)
                return h + mlp_out, (k, v, drops)
            h = h + _mlp(x, lp, c, valid=active)
            return h, (k, v)
        if use_paged:
            m1, l1, acc1 = _paged_prefix_partials(
                c, q, k_flat, v_flat, block_tables + l * N, win_prefix_lens
            )
        else:
            if not hoisted:
                tables_l = block_tables + l * N
                # Piece 1: cached prefix via the width-bucketed gather (two-
                # piece online-softmax — no concat re-materialization).
                k_ctx = _gather_kv(k_flat, tables_l, h.dtype).reshape(B, ctx, kvh, hd)
                v_ctx = _gather_kv(v_flat, tables_l, h.dtype).reshape(B, ctx, kvh, hd)
            m1, l1, acc1 = _attend_piece(qg, k_ctx, v_ctx, mask0, scale)
        # Piece 2: in-register rows [window ; current] — never round-trip HBM.
        k_small = jnp.concatenate([jnp.swapaxes(kwl, 0, 1), k[:, None]], axis=1)  # [B, w+1, ...]
        v_small = jnp.concatenate([jnp.swapaxes(vwl, 0, 1), v[:, None]], axis=1)
        m2, l2, acc2 = _attend_piece(qg, k_small, v_small, small_mask, scale)
        attn = _merge_pieces(m1, l1, acc1, m2, l2, acc2).astype(h.dtype)

        h = h + attn.reshape(B, c.q_size) @ lp["wo"]
        x = rms_norm(h, lp["mlp_norm"], c.rms_norm_eps)
        if moe_stats:
            mlp_out, drops = _mlp(x, lp, c, valid=active, stats=True)
            return h + mlp_out, (k, v, drops)
        h = h + _mlp(x, lp, c, valid=active)
        return h, (k, v)

    xs = (layers, jnp.arange(L, dtype=jnp.int32), k_win, v_win)
    if hoisted:
        xs = xs + (k_ctx_all, v_ctx_all)
    if moe_stats:
        h, (k_rows, v_rows, layer_drops) = lax.scan(layer_fn, h, xs)
        return h, k_rows, v_rows, jnp.sum(layer_drops)
    h, (k_rows, v_rows) = lax.scan(layer_fn, h, xs)
    return h, k_rows, v_rows, jnp.int32(0)


def chunk_decode(
    params: Params,
    config: ModelConfig,
    k_cache: jax.Array,  # [L, N, BS, KVH, HD]
    v_cache: jax.Array,
    tokens: jax.Array,  # [B, S] per-row token chunks (padded)
    positions0: jax.Array,  # [B] position of tokens[:, 0]
    valid: jax.Array,  # [B] valid tokens per row (0 = inactive row)
    block_tables: jax.Array,  # [B, W]
    all_logits: bool = False,  # static: return logits [B, S, V] instead of argmax
    moe_stats: bool = False,  # static: also return {"moe_dropped", "moe_assignments"}
    last_logits: bool = False,  # static: return only each row's last-valid logits [B, V]
) -> Tuple[jax.Array, ...]:
    """Batched multi-token decode: each row consumes up to S tokens in ONE
    pass and yields the greedy next-token prediction after every consumed
    position → (argmax tokens [B, S] i32, k_cache, v_cache) — or the full
    per-position logits with ``all_logits=True``, or only the last valid
    position's logits per row with ``last_logits=True`` (the batched-
    admission prefill path: one dispatch prefills a WAVE of short prompts
    and feeds the sampler directly).

    This is the engine primitive behind batched speculative decoding
    (spec_decode.py; ref surfaces SpecDecodeStats, _core.pyi:354-427): the
    target model verifies γ+1-token chunks for the whole batch in one
    MXU-friendly pass, and the draft model uses the same op to catch up on
    accepted tokens. KV rows for all S slots are written (stale-ok: rows
    past a row's accepted prefix are position-masked until the real token
    at that position overwrites them — write-before-attend, monotone
    positions)."""
    c = config
    bs = c.block_size
    B, S = tokens.shape
    L, KVH, HD = c.num_layers, c.num_kv_heads, c.head_dim
    kvh, G, hd = KVH, c.num_heads // KVH, HD
    ctx = block_tables.shape[1] * bs
    scale = hd**-0.5
    active = valid > 0

    N = k_cache.shape[1]
    k_flat = k_cache.reshape(L * N, bs, kvh, hd)
    v_flat = v_cache.reshape(L * N, bs, kvh, hd)

    h = params["embed"].at[tokens].get(mode="clip")  # [B, S, D]
    positions = positions0[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]  # [B, S]

    # Prefix mask: cached keys strictly before the chunk. Chunk mask: causal
    # within the chunk, limited to each row's valid tokens.
    key_pos = jnp.arange(ctx, dtype=jnp.int32)
    prefix_mask = key_pos[None, :] < positions0[:, None]  # [B, ctx]
    s_i = jnp.arange(S, dtype=jnp.int32)
    chunk_mask = (s_i[None, None, :] <= s_i[None, :, None]) & (
        s_i[None, None, :] < valid[:, None, None]
    )  # [B, S_q, S_k]

    def piece(qg, kp, vp, maskp):
        """qg [B,S,KVH,G,hd]; kp/vp [B,S_k,KVH,hd]; maskp [B,(S_q,)S_k] →
        online-softmax partials (m, l, acc) with S_q query positions."""
        s = jnp.einsum("bqkgd,bskd->bkgqs", qg, kp).astype(jnp.float32) * scale
        if maskp.ndim == 2:
            m_b = maskp[:, None, None, None, :]
        else:
            m_b = maskp[:, None, None, :, :]
        s = jnp.where(m_b, s, -1e30)
        m = jnp.max(s, axis=-1)  # [B,KVH,G,S_q]
        p = jnp.exp(s - m[..., None])
        l = jnp.sum(p, axis=-1)
        acc = jnp.einsum("bkgqs,bskd->bkgqd", p.astype(vp.dtype), vp).astype(jnp.float32)
        return m, l, acc

    def layer_fn(h, xs):
        lp, l = xs
        lp = dequant_layer(lp, h.dtype)  # int8 weight-only storage
        x = rms_norm(h, lp["attn_norm"], c.rms_norm_eps)
        q = (x @ lp["wq"]).reshape(B, S, c.num_heads, hd)
        k = (x @ lp["wk"]).reshape(B, S, kvh, hd)
        v = (x @ lp["wv"]).reshape(B, S, kvh, hd)
        q = apply_rope(q, positions, c.rope_theta)
        k = apply_rope(k, positions, c.rope_theta)
        qg = q.reshape(B, S, kvh, G, hd)

        tables_l = block_tables + l * N
        k_ctx = _gather_kv(k_flat, tables_l, h.dtype).reshape(B, ctx, kvh, hd)
        v_ctx = _gather_kv(v_flat, tables_l, h.dtype).reshape(B, ctx, kvh, hd)
        m1, l1, acc1 = piece(qg, k_ctx, v_ctx, prefix_mask)
        m2, l2, acc2 = piece(qg, k, v, chunk_mask)
        m_t = jnp.maximum(m1, m2)
        a1 = jnp.exp(m1 - m_t)
        a2 = jnp.exp(m2 - m_t)
        l_t = l1 * a1 + l2 * a2
        acc = acc1 * a1[..., None] + acc2 * a2[..., None]
        attn = (acc / jnp.maximum(l_t, 1e-30)[..., None]).astype(h.dtype)  # [B,KVH,G,S,hd]
        attn = jnp.transpose(attn, (0, 3, 1, 2, 4)).reshape(B, S, c.q_size)

        h = h + attn @ lp["wo"]
        x = rms_norm(h, lp["mlp_norm"], c.rms_norm_eps)
        valid_flat = (s_i[None, :] < valid[:, None]).reshape(B * S)
        if moe_stats:
            mlp_out, drops = _mlp(x.reshape(B * S, -1), lp, c, valid=valid_flat, stats=True)
            h = h + mlp_out.reshape(B, S, -1)
            return h, (k, v, drops)
        mlp_out = _mlp(x.reshape(B * S, -1), lp, c, valid=valid_flat).reshape(B, S, -1)
        h = h + mlp_out
        return h, (k, v)

    if moe_stats:
        h, (k_rows, v_rows, layer_drops) = lax.scan(
            layer_fn, h, (params["layers"], jnp.arange(L, dtype=jnp.int32))
        )
        chunk_aux = {
            "moe_dropped": jnp.sum(layer_drops),
            "moe_assignments": jnp.sum(valid).astype(jnp.int32)
            * jnp.int32(max(c.num_experts_per_tok, 1) * L),
        }
    else:
        h, (k_rows, v_rows) = lax.scan(
            layer_fn, h, (params["layers"], jnp.arange(L, dtype=jnp.int32))
        )

    # Fused scatter of all chunk rows: slot (b, s) → positions0[b]+s when
    # s < valid[b], else the scratch sink (block 0 of each layer).
    live = s_i[None, :] < valid[:, None]  # [B, S]
    slots = jnp.where(live, positions, 0)
    tgt_blocks = jnp.where(
        live, jnp.take_along_axis(block_tables, slots // bs, axis=1), 0
    )  # [B, S]
    tgt_offs = slots % bs
    layer_idx = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[:, None, None], (L, B, S))
    # k_rows: [L, B, S, KVH, HD]
    k_new = _scatter_kv(k_cache, layer_idx, tgt_blocks[None], tgt_offs[None], k_rows)
    v_new = _scatter_kv(v_cache, layer_idx, tgt_blocks[None], tgt_offs[None], v_rows)

    h = rms_norm(h, params["final_norm"], c.rms_norm_eps)
    head = params.get("lm_head")
    if last_logits:
        # Batched-admission prefill: only each row's LAST valid position
        # feeds sampling, so the lm_head runs on [B, D] picked rows, not
        # [B, S, D] — and the returned logits are sampler-sized ([B, V],
        # not a [B, S, V] buffer that would be GBs at real vocab sizes).
        last = jnp.maximum(valid - 1, 0)  # [B]
        h_last = jnp.take_along_axis(h, last[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        lg = (h_last @ (head if head is not None else params["embed"].T)).astype(jnp.float32)
        if moe_stats:
            return lg, k_new, v_new, chunk_aux
        return lg, k_new, v_new
    logits = h @ (head if head is not None else params["embed"].T)  # [B, S, V]
    if all_logits:
        # Sampled speculative verification needs the full target
        # distributions per position (spec_decode.spec_verify).
        if moe_stats:
            return logits.astype(jnp.float32), k_new, v_new, chunk_aux
        return logits.astype(jnp.float32), k_new, v_new
    next_tokens = jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)
    if moe_stats:
        return next_tokens, k_new, v_new, chunk_aux
    return next_tokens, k_new, v_new


def mixed_step(
    params: Params,
    config: ModelConfig,
    k_cache: jax.Array,  # [L, N, BS, KVH, HD]
    v_cache: jax.Array,
    p_tokens: jax.Array,  # [S] prefill-chunk token ids (bucket-padded)
    p_valid: jax.Array,  # scalar i32: actual chunk tokens (the row's ``len``)
    p_cache_len: jax.Array,  # scalar i32: tokens already materialized (``start``)
    p_table: jax.Array,  # [Wp] the chunk sequence's block table (width-bucketed)
    d_tokens: jax.Array,  # [B] current token per decode row
    d_positions: jax.Array,  # [B] write slot of each decode token
    d_tables: jax.Array,  # [B, Wd] decode block tables
    d_active: jax.Array,  # [B] bool — padded decode lanes are False
    use_flash: bool = False,  # static: Pallas flash kernel for the chunk piece
    has_prefix: bool = True,  # static on flash: False ⇒ p_cache_len == 0
    moe_stats: bool = False,  # static: also return {"moe_dropped", "moe_assignments"}
) -> Tuple[jax.Array, ...]:
    """One MIXED engine step: a ragged prefill chunk + the full decode batch
    in ONE compiled dispatch. Returns ``(logits [1+B, V] f32, k_cache,
    v_cache)`` — row 0 is the chunk's last-valid position (the prompt's
    next-token logits once the chunk completes it), rows 1.. are the decode
    rows. Sampling happens only at each sequence's last row: decode entries
    are their own last row; the chunk contributes exactly one.

    This dissolves the prefill/decode phase boundary: the flat token axis
    is ``[chunk row (start=p_cache_len, len=p_valid) ; B length-1 decode
    rows]``. Projections, MLP, and the final fused KV scatter run over the
    whole ragged batch (decode matmuls alone leave the MXU idle — the chunk
    tokens ride the same dispatch instead of stalling behind it), while
    attention splits into the two shapes it actually has: the ragged chunk
    piece (attention/ragged.py — width-bucketed prefix gather + causal
    chunk, flash kernel opt-in) and the decode rows' two-piece online-
    softmax (cached prefix + current token in-register), identical math to
    ``prefill`` and ``decode`` respectively."""
    c = config
    bs = c.block_size
    S = p_tokens.shape[0]
    B = d_tokens.shape[0]
    L, KVH, HD = c.num_layers, c.num_kv_heads, c.head_dim
    kvh, G, hd = KVH, c.num_heads // KVH, HD
    scale = hd**-0.5
    interp = jax.default_backend() != "tpu"

    N = k_cache.shape[1]
    k_flat = k_cache.reshape(L * N, bs, kvh, hd)
    v_flat = v_cache.reshape(L * N, bs, kvh, hd)

    p_positions = p_cache_len + jnp.arange(S, dtype=jnp.int32)
    p_valid_q = jnp.arange(S, dtype=jnp.int32) < p_valid
    positions_all = jnp.concatenate([p_positions, d_positions])
    valid_all = jnp.concatenate([p_valid_q, d_active])
    h = params["embed"].at[jnp.concatenate([p_tokens, d_tokens])].get(mode="clip")  # [S+B, D]

    ctx_p = p_table.shape[0] * bs
    ctx_d = d_tables.shape[1] * bs
    d_tgt_blocks, d_tgt_offs, d_mask = decode_targets(d_positions, d_tables, d_active, bs)
    use_paged = _use_paged_decode(c, k_cache)
    use_mega = _use_megakernel(c, k_cache)
    d_prefix_lens = jnp.minimum(d_positions, ctx_d).astype(jnp.int32)
    if use_mega:
        # Megakernel packing: the WHOLE mixed step's attention — the chunk's
        # (start, len) queries AND the B length-1 decode rows — is one
        # ragged batch sharing one grid, one launch per layer. Tables pack
        # [chunk row ; decode rows]; padded table slots hold the scratch
        # page and are skipped (pl.when) along with dead chunk-bucket
        # queries and inactive decode lanes.
        from dynamo_tpu.engine.attention.megakernel import build_meta

        Wp, Wd = p_table.shape[0], d_tables.shape[1]
        Wmax = max(Wp, Wd)
        mega_tbl = jnp.zeros((1 + B, Wmax), jnp.int32)
        mega_tbl = mega_tbl.at[0, :Wp].set(p_table.astype(jnp.int32))
        mega_tbl = mega_tbl.at[1:, :Wd].set(d_tables.astype(jnp.int32))
        s_iq = jnp.arange(S, dtype=jnp.int32)
        d_iq = jnp.arange(B, dtype=jnp.int32)
        mega_meta = build_meta(
            jnp.concatenate([jnp.zeros((S,), jnp.int32), 1 + d_iq]),
            jnp.concatenate([jnp.full((S,), p_cache_len, jnp.int32), d_prefix_lens]),
            jnp.concatenate([jnp.zeros((S,), jnp.int32), S + d_iq]),
            jnp.concatenate([s_iq + 1, S + d_iq + 1]),
            jnp.concatenate(
                [(s_iq < p_valid).astype(jnp.int32), d_active.astype(jnp.int32)]
            ),
        )

    from dynamo_tpu.engine.attention.ragged import ragged_chunk_attention

    def layer_fn(h, xs):
        lp, l = xs
        lp = dequant_layer(lp, h.dtype)  # int8 weight-only storage
        x = rms_norm(h, lp["attn_norm"], c.rms_norm_eps)
        q = (x @ lp["wq"]).reshape(S + B, c.num_heads, hd)
        k = (x @ lp["wk"]).reshape(S + B, kvh, hd)
        v = (x @ lp["wv"]).reshape(S + B, kvh, hd)
        q = apply_rope(q, positions_all, c.rope_theta)
        k = apply_rope(k, positions_all, c.rope_theta)

        if use_mega:
            # ONE fused launch for chunk + decode rows: the fresh-key piece
            # is the packed [chunk K ; decode K] projection output itself.
            attn = _mega_attend_rows(
                c, q, k, v, k_flat, v_flat, mega_tbl + l * N, mega_meta
            ).astype(h.dtype).reshape(S + B, c.q_size)
            h = h + attn @ lp["wo"]
            x = rms_norm(h, lp["mlp_norm"], c.rms_norm_eps)
            if moe_stats:
                mlp_out, drops = _mlp(x, lp, c, valid=valid_all, stats=True)
                return h + mlp_out, (k, v, drops)
            h = h + _mlp(x, lp, c, valid=valid_all)
            return h, (k, v)

        # Chunk piece: [cached prefix ; chunk] — prefill's exact math.
        if use_flash and not has_prefix:
            kp_ctx = vp_ctx = None
        else:
            table_pl = p_table + l * N
            kp_ctx = _gather_kv(k_flat, table_pl, h.dtype).reshape(ctx_p, kvh, hd)
            vp_ctx = _gather_kv(v_flat, table_pl, h.dtype).reshape(ctx_p, kvh, hd)
        attn_p = ragged_chunk_attention(
            q[:S], k[:S], v[:S], kp_ctx, vp_ctx, p_valid, p_cache_len,
            num_kv_heads=kvh, use_flash=use_flash, has_prefix=has_prefix,
            interpret=interp,
        )

        # Decode rows: cached prefix + current token in-register — the
        # decode_layer_scan two-piece merge.
        qg_d = q[S:].reshape(B, kvh, G, hd)
        if use_paged:
            m1, l1, acc1 = _paged_prefix_partials(
                c, q[S:], k_flat, v_flat, d_tables + l * N, d_prefix_lens
            )
        else:
            tables_dl = d_tables + l * N
            kd_ctx = _gather_kv(k_flat, tables_dl, h.dtype).reshape(B, ctx_d, kvh, hd)
            vd_ctx = _gather_kv(v_flat, tables_dl, h.dtype).reshape(B, ctx_d, kvh, hd)
            m1, l1, acc1 = _attend_piece(qg_d, kd_ctx, vd_ctx, d_mask, scale)
        m2, l2, acc2 = _attend_piece(
            qg_d, k[S:, None], v[S:, None], jnp.ones((B, 1), dtype=bool), scale
        )
        attn_d = _merge_pieces(m1, l1, acc1, m2, l2, acc2).astype(h.dtype)

        attn = jnp.concatenate(
            [attn_p.reshape(S, c.q_size), attn_d.reshape(B, c.q_size)], axis=0
        )
        h = h + attn @ lp["wo"]
        x = rms_norm(h, lp["mlp_norm"], c.rms_norm_eps)
        if moe_stats:
            mlp_out, drops = _mlp(x, lp, c, valid=valid_all, stats=True)
            return h + mlp_out, (k, v, drops)
        h = h + _mlp(x, lp, c, valid=valid_all)
        return h, (k, v)

    if moe_stats:
        h, (k_rows, v_rows, layer_drops) = lax.scan(
            layer_fn, h, (params["layers"], jnp.arange(L, dtype=jnp.int32))
        )
        aux = {
            "moe_dropped": jnp.sum(layer_drops),
            "moe_assignments": jnp.sum(valid_all).astype(jnp.int32)
            * jnp.int32(max(c.num_experts_per_tok, 1) * L),
        }
    else:
        h, (k_rows, v_rows) = lax.scan(
            layer_fn, h, (params["layers"], jnp.arange(L, dtype=jnp.int32))
        )

    # ONE fused ragged scatter for chunk rows + decode rows together.
    p_tgt_blocks, p_tgt_offs = ragged_scatter_targets(p_table, p_positions, p_valid_q, bs)
    tgt_blocks = jnp.concatenate([p_tgt_blocks, d_tgt_blocks])
    tgt_offs = jnp.concatenate([p_tgt_offs, d_tgt_offs])
    layer_idx = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[:, None], (L, S + B))
    k_new = _scatter_kv(k_cache, layer_idx, tgt_blocks[None, :], tgt_offs[None, :], k_rows)
    v_new = _scatter_kv(v_cache, layer_idx, tgt_blocks[None, :], tgt_offs[None, :], v_rows)

    # lm_head only at each sequence's LAST row: the chunk's last valid
    # position + every decode row — [1+B, D] picked rows, never [S+B, V].
    last_p = jnp.maximum(p_valid - 1, 0)
    h_rows = jnp.concatenate([h[last_p][None], h[S:]], axis=0)
    h_rows = rms_norm(h_rows, params["final_norm"], c.rms_norm_eps)
    head = params.get("lm_head")
    logits = (h_rows @ (head if head is not None else params["embed"].T)).astype(jnp.float32)
    if moe_stats:
        return logits, k_new, v_new, aux
    return logits, k_new, v_new


def decode_sample(
    params: Params,
    config: ModelConfig,
    k_cache: jax.Array,  # [L, N, BS, KVH, HD]
    v_cache: jax.Array,
    tpa: jax.Array,  # [3, B] i32 — rows: (tokens, positions, active)
    block_tables: jax.Array,  # [B, max_blocks]
    temps: jax.Array,  # [B] f32 (0 = greedy)
    top_ks: jax.Array,  # [B] i32 (0 = off)
    top_ps: jax.Array,  # [B] f32 (1 = off)
    rng_key: jax.Array,
    moe_stats: bool = False,  # static: also return {"moe_dropped", "moe_assignments"}
) -> Tuple[jax.Array, ...]:
    """One FUSED decode+sample step for the zero-bubble overlap pipeline:
    the forward pass, on-device sampling, and the next step's input-state
    advance run as ONE executable. Returns ``(sampled [B] i32,
    next_tpa [3, B] i32, k_cache, v_cache)``.

    ``next_tpa`` is the on-device token feedback: row 0 is the sampled
    tokens (the next step's inputs), row 1 the advanced positions, row 2
    the unchanged active lanes — so the scheduler can dispatch step N+1 by
    handing step N's ``next_tpa`` straight back without a host round-trip
    on the critical path. The [3, B] packing also serves the sync path:
    tokens/positions/active ride ONE host→device transfer instead of three
    (each small upload costs ~0.1 ms of dispatch on tunneled devices)."""
    tokens = tpa[0]
    positions = tpa[1]
    active = tpa[2].astype(bool)
    res = decode(
        params, config, k_cache, v_cache, tokens, positions, block_tables, active,
        moe_stats=moe_stats,
    )
    if moe_stats:
        logits, k_new, v_new, aux = res
    else:
        logits, k_new, v_new = res
    from dynamo_tpu.engine.sampling import sample_batch

    sampled = sample_batch(logits, temps, top_ks, top_ps, rng_key)
    next_tpa = jnp.stack([sampled, positions + 1, tpa[2]])
    if moe_stats:
        return sampled, next_tpa, k_new, v_new, aux
    return sampled, next_tpa, k_new, v_new


def embed(
    params: Params,
    config: ModelConfig,
    tokens: jax.Array,  # [T] bucket-padded token ids
    valid_len: jax.Array,  # scalar
) -> jax.Array:
    """Sequence embedding: full causal forward (no KV cache), masked mean
    pool over the final hidden states → [hidden_size] f32, L2-normalized.
    (Serving path for /v1/embeddings — ref: http/service/openai.rs:369.)"""
    c = config
    T = tokens.shape[0]
    h = params["embed"].at[tokens].get(mode="clip")  # [T, D]
    positions = jnp.arange(T, dtype=jnp.int32)
    valid = positions < valid_len
    mask = (positions[None, :] <= positions[:, None]) & valid[None, :]

    def layer_fn(h, lp):
        lp = dequant_layer(lp, h.dtype)  # int8 weight-only storage
        x = rms_norm(h, lp["attn_norm"], c.rms_norm_eps)
        q = apply_rope((x @ lp["wq"]).reshape(T, c.num_heads, c.head_dim), positions, c.rope_theta)
        k = apply_rope((x @ lp["wk"]).reshape(T, c.num_kv_heads, c.head_dim), positions, c.rope_theta)
        v = (x @ lp["wv"]).reshape(T, c.num_kv_heads, c.head_dim)
        attn = _attend(q, k, v, mask, c)
        h = h + attn.reshape(T, c.q_size) @ lp["wo"]
        x = rms_norm(h, lp["mlp_norm"], c.rms_norm_eps)
        h = h + _mlp(x, lp, c, valid=valid)
        return h, None

    h, _ = lax.scan(layer_fn, h, params["layers"])
    h = rms_norm(h, params["final_norm"], c.rms_norm_eps).astype(jnp.float32)
    weights = valid.astype(jnp.float32)[:, None]
    pooled = jnp.sum(h * weights, axis=0) / jnp.maximum(jnp.sum(weights), 1.0)
    return pooled / jnp.maximum(jnp.linalg.norm(pooled), 1e-9)


# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------


def decode_targets(
    positions: jax.Array,  # [B]
    block_tables: jax.Array,  # [B, max_blocks]
    active: jax.Array,  # [B] bool
    block_size: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Paged-KV scatter targets + cached-prefix mask for one decode step.

    Inactive rows sink to scratch block 0 (never allocated). Returns
    (tgt_blocks [B], tgt_offs [B], mask [B, ctx]). The mask covers the
    CACHED prefix only (key_pos < positions) — the current token's K/V is
    folded into attention in-register, not read back from the cache. Shared
    by ``decode`` and the pipelined path so the addressing convention lives
    in one place."""
    slots = jnp.where(active, positions, 0)
    tgt_blocks = jnp.where(
        active, jnp.take_along_axis(block_tables, (slots // block_size)[:, None], axis=1)[:, 0], 0
    )
    tgt_offs = slots % block_size
    ctx = block_tables.shape[1] * block_size
    key_pos = jnp.arange(ctx, dtype=jnp.int32)
    mask = key_pos[None, :] < positions[:, None]  # [B, ctx] — cached prefix
    return tgt_blocks, tgt_offs, mask


def decode_layer_scan(
    layers: Dict[str, jax.Array],
    c: ModelConfig,
    k_cache: jax.Array,  # [L', N, BS, KVH, HD] — full stack or a pipeline stage's slice
    v_cache: jax.Array,
    h: jax.Array,  # [B, D] embedded inputs (or activations from the previous pp stage)
    positions: jax.Array,  # [B]
    block_tables: jax.Array,  # [B, max_blocks]
    mask: jax.Array,  # [B, ctx] bool — cached prefix only (decode_targets)
    active: Optional[jax.Array] = None,  # [B] bool — live lanes (MoE dispatch mask)
    moe_stats: bool = False,  # also return summed capacity drops
):
    """Scan the decode layer body over a stacked layer group. Factored out of
    ``decode`` so pipeline parallelism (pipeline_parallel.py) can run the
    same body on each stage's local L/pp slice of layers + KV cache.

    The cache is READ-ONLY here: per-layer slices ride the scan xs and each
    layer's new K/V row is attended in-register (appended to the gathered
    context / folded into the kernel's online softmax) and returned stacked
    ``[L', B, KVH, HD]`` for the caller's single fused scatter. Writing the
    cache inside the scan carry forced XLA into a full cache copy per layer
    (~5 ms/step at 1B/b8 on v5e — measured with tools/profile_cache.py);
    read-only xs slicing leaves the buffers untouched."""
    B = h.shape[0]
    bs = c.block_size
    ctx = block_tables.shape[1] * bs
    # Layer-flat cache views (see prefill): no per-layer slice copies in the
    # scan — gathers index [L'*N, ...] with layer-offset tables instead.
    Lp = k_cache.shape[0]
    N = k_cache.shape[1]
    k_flat = k_cache.reshape(Lp * N, bs, c.num_kv_heads, c.head_dim)
    v_flat = v_cache.reshape(Lp * N, bs, c.num_kv_heads, c.head_dim)

    kvh, G, hd = c.num_kv_heads, c.num_heads // c.num_kv_heads, c.head_dim
    scale = hd**-0.5
    use_paged = _use_paged_decode(c, k_cache)
    use_mega = _use_megakernel(c, k_cache)
    prefix_lens = jnp.minimum(positions, ctx).astype(jnp.int32)
    if use_mega:
        from dynamo_tpu.engine.attention.megakernel import build_meta

        rows_i = jnp.arange(B, dtype=jnp.int32)
        mega_meta = build_meta(
            rows_i, prefix_lens, rows_i, rows_i + 1, jnp.ones((B,), jnp.int32)
        )

    def layer_fn(h, xs):
        lp, l = xs  # l: scalar layer index within this stack
        lp = dequant_layer(lp, h.dtype)  # int8 weight-only storage
        x = rms_norm(h, lp["attn_norm"], c.rms_norm_eps)
        q = (x @ lp["wq"]).reshape(B, 1, c.num_heads, c.head_dim)
        k = (x @ lp["wk"]).reshape(B, 1, c.num_kv_heads, c.head_dim)
        v = (x @ lp["wv"]).reshape(B, 1, c.num_kv_heads, c.head_dim)
        q = apply_rope(q, positions[:, None], c.rope_theta)[:, 0]  # [B, H, hd]
        k = apply_rope(k, positions[:, None], c.rope_theta)[:, 0]  # [B, KVH, hd]
        v = v[:, 0]
        qg = q.reshape(B, kvh, G, hd)

        tables_l = block_tables + l * N
        if use_mega:
            # Ragged megakernel: prefix pages + the current token merge
            # inside ONE launch's online softmax — no gathered copy, no
            # external piece merge (attention/megakernel.py).
            attn = _mega_attend_rows(
                c, q, k, v, k_flat, v_flat, tables_l, mega_meta
            ).astype(h.dtype)
        else:
            # Two online-softmax pieces: cached prefix + current token
            # in-register. Prefix: Pallas paged flash kernel (pages stream
            # HBM→VMEM once) or the width-bucketed XLA gather fallback.
            if use_paged:
                m1, l1, acc1 = _paged_prefix_partials(c, q, k_flat, v_flat, tables_l, prefix_lens)
            else:
                k_ctx = _gather_kv(k_flat, tables_l, h.dtype).reshape(B, ctx, kvh, hd)
                v_ctx = _gather_kv(v_flat, tables_l, h.dtype).reshape(B, ctx, kvh, hd)
                m1, l1, acc1 = _attend_piece(qg, k_ctx, v_ctx, mask, scale)
            m2, l2, acc2 = _attend_piece(
                qg, k[:, None], v[:, None], jnp.ones((B, 1), dtype=bool), scale
            )
            attn = _merge_pieces(m1, l1, acc1, m2, l2, acc2).astype(h.dtype)
        h = h + attn.reshape(B, c.q_size) @ lp["wo"]

        x = rms_norm(h, lp["mlp_norm"], c.rms_norm_eps)
        if moe_stats:
            mlp_out, drops = _mlp(x, lp, c, valid=active, stats=True)
            return h + mlp_out, (k, v, drops)
        h = h + _mlp(x, lp, c, valid=active)
        return h, (k, v)

    if moe_stats:
        h, (k_rows, v_rows, layer_drops) = lax.scan(
            layer_fn, h, (layers, jnp.arange(Lp, dtype=jnp.int32))
        )
        return h, k_rows, v_rows, jnp.sum(layer_drops)
    h, (k_rows, v_rows) = lax.scan(
        layer_fn, h, (layers, jnp.arange(Lp, dtype=jnp.int32))
    )
    return h, k_rows, v_rows


def scatter_kv_rows(
    k_cache: jax.Array,  # [L', N, BS, KVH, HD]
    v_cache: jax.Array,
    k_rows: jax.Array,  # [L', B, KVH, HD] from decode_layer_scan
    v_rows: jax.Array,
    tgt_blocks: jax.Array,  # [B]
    tgt_offs: jax.Array,  # [B]
) -> Tuple[jax.Array, jax.Array]:
    """Single fused all-layer KV write (one scatter per cache tensor)."""
    L, B = k_rows.shape[0], k_rows.shape[1]
    layer_idx = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[:, None], (L, B))
    k_new = _scatter_kv(k_cache, layer_idx, tgt_blocks[None, :], tgt_offs[None, :], k_rows)
    v_new = _scatter_kv(v_cache, layer_idx, tgt_blocks[None, :], tgt_offs[None, :], v_rows)
    return k_new, v_new


def decode(
    params: Params,
    config: ModelConfig,
    k_cache: jax.Array,  # [L, N, BS, KVH, HD]
    v_cache: jax.Array,
    tokens: jax.Array,  # [B] current token per sequence
    positions: jax.Array,  # [B] position of each token (its write slot)
    block_tables: jax.Array,  # [B, max_blocks]
    active: jax.Array,  # [B] bool — padded batch slots are False
    moe_stats: bool = False,  # static: also return {"moe_dropped", "moe_assignments"}
) -> Tuple[jax.Array, ...]:
    """One decode step for a batch. Returns (logits [B, V], k_cache, v_cache)
    (+ capacity-MoE drop aux with ``moe_stats``)."""
    c = config
    bs = c.block_size

    h = params["embed"].at[tokens].get(mode="clip")  # [B, D]

    tgt_blocks, tgt_offs, mask = decode_targets(positions, block_tables, active, bs)

    # Decode attention: the ragged megakernel (one launch per layer, TPU
    # auto) or the width-bucketed XLA gather with a two-piece online-
    # softmax merge — see ModelConfig.attention_impl for the full record.
    if moe_stats:
        h, k_rows, v_rows, drops = decode_layer_scan(
            params["layers"], c, k_cache, v_cache, h, positions,
            block_tables, mask, active=active, moe_stats=True,
        )
    else:
        h, k_rows, v_rows = decode_layer_scan(
            params["layers"], c, k_cache, v_cache, h, positions,
            block_tables, mask, active=active,
        )
    k_new, v_new = scatter_kv_rows(k_cache, v_cache, k_rows, v_rows, tgt_blocks, tgt_offs)

    h = rms_norm(h, params["final_norm"], c.rms_norm_eps)
    head = params.get("lm_head")
    logits = h @ (head if head is not None else params["embed"].T)
    if moe_stats:
        aux = {
            "moe_dropped": drops,
            "moe_assignments": jnp.sum(active).astype(jnp.int32)
            * jnp.int32(max(c.num_experts_per_tok, 1) * c.num_layers),
        }
        return logits.astype(jnp.float32), k_new, v_new, aux
    return logits.astype(jnp.float32), k_new, v_new
