"""TPU-native ViT image encoder for the multimodal encode-worker role.

Ref role: the encode worker that turns image inputs into embedding tensors
handed to prefill (components/backends/trtllm/src/dynamo/trtllm/utils/
encode_helper.py + the vllm/sglang image paths). The reference delegates
the vision tower to its engines; here it is a native JAX module:

- Patchify as ONE reshape+matmul (``[B, P, p*p*3] @ W``) — MXU-friendly,
  no conv lowering needed.
- Bidirectional transformer over stacked layers via ``lax.scan`` (one
  compiled layer body), f32 norms / bf16 matmuls like the LM side.
- Final projection to the language model's hidden size, so the output
  rows drop directly into prefill's embedding stream
  (llama.prefill ``mm_feats``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax

Params = Dict[str, jax.Array]


@dataclass(frozen=True)
class VisionConfig:
    image_size: int = 224
    patch_size: int = 14
    hidden_size: int = 1024
    num_layers: int = 12
    num_heads: int = 16
    intermediate_size: int = 4096
    lm_hidden_size: int = 2048  # projection target (the LM's hidden size)
    layer_norm_eps: float = 1e-6

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


PRESETS = {
    # Small tower for tests (CPU-friendly).
    "tiny-vit": VisionConfig(
        image_size=32, patch_size=8, hidden_size=32, num_layers=2, num_heads=2,
        intermediate_size=64, lm_hidden_size=64,
    ),
    # CLIP-L/14-class tower projected to the 1B LM width.
    "vit-l-14": VisionConfig(lm_hidden_size=2048),
}


def init_params(config: VisionConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    c = config
    ks = jax.random.split(key, 10)

    def dense(k, shape, scale=None):
        scale = scale if scale is not None else shape[0] ** -0.5
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    L, D, F = c.num_layers, c.hidden_size, c.intermediate_size
    patch_dim = c.patch_size * c.patch_size * 3
    return {
        "patch_embed": dense(ks[0], (patch_dim, D), scale=0.02),
        "pos_embed": dense(ks[1], (c.num_patches, D), scale=0.02),
        "layers": {
            "ln1": jnp.ones((L, D), dtype),
            "ln1_b": jnp.zeros((L, D), dtype),
            "ln2": jnp.ones((L, D), dtype),
            "ln2_b": jnp.zeros((L, D), dtype),
            "wq": dense(ks[2], (L, D, D)),
            "wk": dense(ks[3], (L, D, D)),
            "wv": dense(ks[4], (L, D, D)),
            "wo": dense(ks[5], (L, D, D)),
            "w_up": dense(ks[6], (L, D, F)),
            "w_down": dense(ks[7], (L, F, D)),
        },
        "final_ln": jnp.ones((D,), dtype),
        "final_ln_b": jnp.zeros((D,), dtype),
        "proj": dense(ks[8], (D, c.lm_hidden_size)),
    }


def _layer_norm(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
    return ((xf - mu) * lax.rsqrt(var + eps) * w.astype(jnp.float32) + b.astype(jnp.float32)).astype(
        x.dtype
    )


def patchify(images: jax.Array, patch: int) -> jax.Array:
    """[B, H, W, 3] → [B, P, patch*patch*3] (row-major patch grid)."""
    B, H, W, C = images.shape
    gh, gw = H // patch, W // patch
    x = images.reshape(B, gh, patch, gw, patch, C)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(B, gh * gw, patch * patch * C)


def encode(params: Params, config: VisionConfig, images: jax.Array) -> jax.Array:
    """images [B, H, W, 3] (f32 in [0, 1]) → features [B, P, lm_hidden] f32."""
    c = config
    x = patchify(images, c.patch_size).astype(params["patch_embed"].dtype)
    h = x @ params["patch_embed"] + params["pos_embed"][None]  # [B, P, D]
    B, P, D = h.shape
    nh, hd = c.num_heads, c.head_dim
    scale = hd**-0.5

    def layer_fn(h, lp):
        x = _layer_norm(h, lp["ln1"], lp["ln1_b"], c.layer_norm_eps)
        q = (x @ lp["wq"]).reshape(B, P, nh, hd)
        k = (x @ lp["wk"]).reshape(B, P, nh, hd)
        v = (x @ lp["wv"]).reshape(B, P, nh, hd)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
        p = jax.nn.softmax(s, axis=-1).astype(h.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(B, P, D)
        h = h + attn @ lp["wo"]
        x = _layer_norm(h, lp["ln2"], lp["ln2_b"], c.layer_norm_eps)
        h = h + jax.nn.gelu(x @ lp["w_up"]) @ lp["w_down"]
        return h, None

    h, _ = lax.scan(layer_fn, h, params["layers"])
    h = _layer_norm(h, params["final_ln"], params["final_ln_b"], c.layer_norm_eps)
    return (h @ params["proj"]).astype(jnp.float32)
