"""Multi-head latent attention (MLA, DeepSeek-V2/V3 family) over a paged
*latent* KV cache.

The reference serves DeepSeek models through engine adapters (SGLang
DP-attention / TRT-LLM wide-EP recipes, SURVEY.md §2e); here MLA is native.
TPU-first design:

- **Latent cache**: each token stores one row ``[kv_lora_rank + rope_dim]``
  (e.g. 512+64) instead of per-head K/V — ~7× less HBM than GQA-8 at
  head_dim 128, which multiplies the decode batch the HBM can hold.
- **Absorbed projections**: queries are pre-multiplied by W_uk
  (``q_eff = q_nope · W_uk``) so attention contracts directly against the
  latent; values decompress *after* the probability-weighted latent sum
  (``out = (p · c_kv) · W_uv``) — both are MXU matmuls, nothing per-key.
- Same paged block-table layout as the llama family (block 0 = scratch
  sink), so the scheduler, prefix cache, KVBM and disaggregation move MLA
  blocks with zero special-casing.

Cache layout: k_cache [L, N, BS, 1, R] with R = kv_lora_rank +
qk_rope_head_dim (the "1" keeps the [L, N, BS, heads, dim] rank the rest of
the stack expects); v_cache is unused (shape [L, 1, 1, 1, 1]).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.engine.models.llama import _gather_kv, _scatter_kv, _mlp, apply_rope, rms_norm

Params = Dict[str, jax.Array]


def latent_width(config: ModelConfig) -> int:
    return config.kv_lora_rank + config.qk_rope_head_dim


def init_params(config: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    c = config
    L, H = c.num_layers, c.num_heads
    qk = c.qk_nope_head_dim + c.qk_rope_head_dim
    keys = jax.random.split(key, 12)

    def dense(k, shape, scale=None):
        scale = scale if scale is not None else shape[-2] ** -0.5 if len(shape) >= 2 else 0.02
        return (jax.random.normal(k, shape, dtype=jnp.float32) * scale).astype(dtype)

    layers: Dict[str, jax.Array] = {
        "attn_norm": jnp.ones((L, c.hidden_size), dtype=dtype),
        "mlp_norm": jnp.ones((L, c.hidden_size), dtype=dtype),
        "kv_norm": jnp.ones((L, c.kv_lora_rank), dtype=dtype),
        "wq": dense(keys[0], (L, c.hidden_size, H * qk)),
        "w_dkv": dense(keys[1], (L, c.hidden_size, c.kv_lora_rank)),
        "w_kr": dense(keys[2], (L, c.hidden_size, c.qk_rope_head_dim)),
        "w_uk": dense(keys[3], (L, H, c.qk_nope_head_dim, c.kv_lora_rank), scale=c.qk_nope_head_dim**-0.5),
        "w_uv": dense(keys[4], (L, H, c.kv_lora_rank, c.v_head_dim), scale=c.kv_lora_rank**-0.5),
        "wo": dense(keys[5], (L, H * c.v_head_dim, c.hidden_size)),
    }
    if c.num_experts == 0:
        layers.update(
            w_gate=dense(keys[6], (L, c.hidden_size, c.intermediate_size)),
            w_up=dense(keys[7], (L, c.hidden_size, c.intermediate_size)),
            w_down=dense(keys[8], (L, c.intermediate_size, c.hidden_size)),
        )
    else:
        E = c.num_experts
        layers.update(
            router=dense(keys[9], (L, c.hidden_size, E)),
            w_gate=dense(keys[6], (L, E, c.hidden_size, c.intermediate_size)),
            w_up=dense(keys[7], (L, E, c.hidden_size, c.intermediate_size)),
            w_down=dense(keys[8], (L, E, c.intermediate_size, c.hidden_size)),
        )
    params: Params = {
        "embed": dense(keys[10], (c.vocab_size, c.hidden_size), scale=0.02),
        "final_norm": jnp.ones((c.hidden_size,), dtype=dtype),
        "layers": layers,
    }
    if not c.tie_word_embeddings:
        params["lm_head"] = dense(keys[11], (c.hidden_size, c.vocab_size), scale=0.02)
    return params


def _project_q(x: jax.Array, lp, c: ModelConfig, positions: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """x [T, D] → (q_eff [T, H, r], q_rope [T, H, rope]) with q_eff absorbed
    through W_uk."""
    T = x.shape[0]
    qk = c.qk_nope_head_dim + c.qk_rope_head_dim
    q = (x @ lp["wq"]).reshape(T, c.num_heads, qk)
    q_nope = q[..., : c.qk_nope_head_dim]
    q_rope = apply_rope(q[..., c.qk_nope_head_dim :], positions, c.rope_theta)
    q_eff = jnp.einsum("thn,hnr->thr", q_nope, lp["w_uk"])  # absorb W_uk
    return q_eff, q_rope


def _latent_kv(x: jax.Array, lp, c: ModelConfig, positions: jax.Array) -> jax.Array:
    """x [T, D] → latent rows [T, R] = [norm(c_kv) ‖ rope(k_rope)]."""
    c_kv = rms_norm(x @ lp["w_dkv"], lp["kv_norm"], c.rms_norm_eps)
    k_rope = apply_rope((x @ lp["w_kr"])[:, None, :], positions, c.rope_theta)[:, 0]
    return jnp.concatenate([c_kv, k_rope], axis=-1)


def _attend_latent(
    q_eff: jax.Array,  # [T, H, r]
    q_rope: jax.Array,  # [T, H, rope]
    latent: jax.Array,  # [S, R]
    mask: jax.Array,  # [T, S]
    lp,
    c: ModelConfig,
) -> jax.Array:
    """→ [T, H * v_head_dim]."""
    r = c.kv_lora_rank
    c_kv, k_rope = latent[:, :r], latent[:, r:]
    scale = (c.qk_nope_head_dim + c.qk_rope_head_dim) ** -0.5
    scores = (
        jnp.einsum("thr,sr->ths", q_eff, c_kv) + jnp.einsum("the,se->ths", q_rope, k_rope)
    ).astype(jnp.float32) * scale
    scores = jnp.where(mask[:, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q_eff.dtype)
    attn_lat = jnp.einsum("ths,sr->thr", probs, c_kv)  # weighted latent sum
    out = jnp.einsum("thr,hrv->thv", attn_lat, lp["w_uv"])  # decompress once
    return out.reshape(q_eff.shape[0], c.num_heads * c.v_head_dim)


def prefill(
    params: Params,
    config: ModelConfig,
    k_cache: jax.Array,  # [L, N, BS, 1, R]
    v_cache: jax.Array,  # unused
    tokens: jax.Array,  # [T]
    valid_len: jax.Array,
    cache_len: jax.Array,
    block_table: jax.Array,  # [max_blocks]
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    c = config
    bs = c.block_size
    T = tokens.shape[0]
    ctx = block_table.shape[0] * bs

    h = params["embed"].at[tokens].get(mode="clip")
    positions = cache_len + jnp.arange(T, dtype=jnp.int32)
    valid_q = jnp.arange(T, dtype=jnp.int32) < valid_len
    slots = jnp.where(valid_q, positions, 0)
    tgt_blocks = jnp.where(valid_q, block_table[slots // bs], 0)
    tgt_offs = slots % bs

    # Cache read-only in the scan; the chunk's latent rows come out as ys and
    # ONE fused scatter writes all layers afterwards — a scatter inside the
    # carry forces a full cache copy per layer (measured; see
    # llama.decode_layer_scan). The gather reads a layer-flat [L*N] view with
    # layer-offset tables so the scan never slices the cache per layer
    # (the slice materializes a layer-cache copy per iteration).
    key_pos = jnp.arange(ctx, dtype=jnp.int32)
    prefix_mask = jnp.broadcast_to(key_pos[None, :] < cache_len, (T, ctx))
    chunk_q = jnp.arange(T, dtype=jnp.int32)
    chunk_mask = (chunk_q[None, :] <= chunk_q[:, None]) & valid_q[None, :]
    mask = jnp.concatenate([prefix_mask, chunk_mask], axis=1)  # [T, ctx+T]
    N = k_cache.shape[1]
    k_flat = k_cache.reshape(c.num_layers * N, bs, 1, latent_width(c))

    def layer_fn(h, xs):
        lp, l = xs
        x = rms_norm(h, lp["attn_norm"], c.rms_norm_eps)
        q_eff, q_rope = _project_q(x, lp, c, positions)
        latent_new = _latent_kv(x, lp, c, positions)  # [T, R]
        latent_ctx = _gather_kv(k_flat, block_table + l * N, h.dtype).reshape(ctx, latent_width(c))
        attn = _attend_latent(
            q_eff, q_rope, jnp.concatenate([latent_ctx, latent_new], axis=0), mask, lp, c
        )
        h = h + attn @ lp["wo"]
        x = rms_norm(h, lp["mlp_norm"], c.rms_norm_eps)
        h = h + _mlp(x, lp, c, valid=valid_q)
        return h, latent_new

    h, latent_rows = lax.scan(
        layer_fn, h, (params["layers"], jnp.arange(c.num_layers, dtype=jnp.int32))
    )
    L = c.num_layers
    layer_idx = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[:, None], (L, T))
    k_new = _scatter_kv(
        k_cache, layer_idx, tgt_blocks[None, :], tgt_offs[None, :], latent_rows[:, :, None, :]
    )
    last = jnp.maximum(valid_len - 1, 0)
    h_last = rms_norm(h[last], params["final_norm"], c.rms_norm_eps)
    head = params.get("lm_head")
    logits = h_last @ (head if head is not None else params["embed"].T)
    return logits.astype(jnp.float32), k_new, v_cache


def decode(
    params: Params,
    config: ModelConfig,
    k_cache: jax.Array,  # [L, N, BS, 1, R]
    v_cache: jax.Array,  # unused
    tokens: jax.Array,  # [B]
    positions: jax.Array,  # [B]
    block_tables: jax.Array,  # [B, W]
    active: jax.Array,  # [B]
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    c = config
    bs = c.block_size
    B = tokens.shape[0]
    ctx = block_tables.shape[1] * bs
    R = latent_width(c)

    h = params["embed"].at[tokens].get(mode="clip")
    slots = jnp.where(active, positions, 0)
    tgt_blocks = jnp.where(active, jnp.take_along_axis(block_tables, (slots // bs)[:, None], axis=1)[:, 0], 0)
    tgt_offs = slots % bs
    # Cached-prefix mask; the current row's latent is attended in-register
    # and written back with one fused scatter after the scan.
    key_pos = jnp.arange(ctx, dtype=jnp.int32)
    mask = key_pos[None, :] < positions[:, None]
    mask_full = jnp.concatenate([mask, jnp.ones((B, 1), dtype=bool)], axis=1)
    # Layer-flat view: no per-layer cache slice in the scan (see prefill).
    N = k_cache.shape[1]
    k_flat = k_cache.reshape(c.num_layers * N, bs, 1, R)

    def layer_fn(h, xs):
        lp, l = xs
        x = rms_norm(h, lp["attn_norm"], c.rms_norm_eps)
        # dim 0 is the batch here; rope broadcasts per-row positions the same
        # way it broadcasts per-token positions in prefill.
        q_eff, q_rope = _project_q(x, lp, c, positions)
        latent_row = _latent_kv(x, lp, c, positions)  # [B, R]
        latent_ctx = _gather_kv(k_flat, block_tables + l * N, h.dtype).reshape(B, ctx, R)
        latent_full = jnp.concatenate([latent_ctx, latent_row[:, None]], axis=1)
        attn = jax.vmap(
            lambda qe, qr, lat, mb: _attend_latent(qe[None], qr[None], lat, mb[None], lp, c)[0]
        )(q_eff, q_rope, latent_full, mask_full)  # [B, H*v]
        h = h + attn @ lp["wo"]
        x2 = rms_norm(h, lp["mlp_norm"], c.rms_norm_eps)
        h = h + _mlp(x2, lp, c, valid=active)
        return h, latent_row

    h, latent_rows = lax.scan(
        layer_fn, h, (params["layers"], jnp.arange(c.num_layers, dtype=jnp.int32))
    )
    L = c.num_layers
    layer_idx = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[:, None], (L, B))
    k_new = _scatter_kv(
        k_cache, layer_idx, tgt_blocks[None, :], tgt_offs[None, :], latent_rows[:, :, None, :]
    )
    h = rms_norm(h, params["final_norm"], c.rms_norm_eps)
    head = params.get("lm_head")
    logits = h @ (head if head is not None else params["embed"].T)
    return logits.astype(jnp.float32), k_new, v_cache


def decode_multi(
    params: Params,
    config: ModelConfig,
    k_cache: jax.Array,  # [L, N, BS, 1, R]
    v_cache: jax.Array,  # unused
    tokens: jax.Array,  # [B]
    positions: jax.Array,  # [B]
    block_tables: jax.Array,  # [B, W] — must cover positions+num_steps
    active: jax.Array,  # [B]
    temps: jax.Array,
    top_ks: jax.Array,
    top_ps: jax.Array,
    rng_key: jax.Array,
    num_steps: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Multi-step decode window (see llama.decode_multi): N steps + sampling
    per dispatch. Returns (tokens_out [num_steps, B], k_cache, v_cache).

    Window-local latent rows: the cache is READ-ONLY for the whole window —
    per-step latent rows accumulate in a small carry and ONE fused scatter
    writes them afterwards (a per-step scatter on the carry forces a full
    latent-cache copy per iteration; see llama.decode_multi)."""
    from dynamo_tpu.engine.sampling import sample_batch

    c = config
    bs = c.block_size
    B = tokens.shape[0]
    L = c.num_layers
    R = latent_width(c)
    N = k_cache.shape[1]
    ctx = block_tables.shape[1] * bs
    k_flat = k_cache.reshape(L * N, bs, 1, R)
    key_pos = jnp.arange(ctx, dtype=jnp.int32)
    mask0 = key_pos[None, :] < positions[:, None]  # fixed: cache not written in-window

    def body(i, state):
        toks, lat_win, out, key = state
        poss = positions + i
        h = params["embed"].at[toks].get(mode="clip")
        win_mask = jnp.broadcast_to(
            (jnp.arange(num_steps, dtype=jnp.int32) < i)[None, :], (B, num_steps)
        )
        mask_full = jnp.concatenate([mask0, win_mask, jnp.ones((B, 1), dtype=bool)], axis=1)

        def layer_fn(h, xs):
            lp, l, lwl = xs  # lwl: [w, B, R] this layer's window latent rows
            x = rms_norm(h, lp["attn_norm"], c.rms_norm_eps)
            q_eff, q_rope = _project_q(x, lp, c, poss)
            latent_row = _latent_kv(x, lp, c, poss)  # [B, R]
            latent_ctx = _gather_kv(k_flat, block_tables + l * N, h.dtype).reshape(B, ctx, R)
            latent_full = jnp.concatenate(
                [latent_ctx, jnp.swapaxes(lwl, 0, 1), latent_row[:, None]], axis=1
            )
            attn = jax.vmap(
                lambda qe, qr, lat, mb: _attend_latent(qe[None], qr[None], lat, mb[None], lp, c)[0]
            )(q_eff, q_rope, latent_full, mask_full)
            h = h + attn @ lp["wo"]
            x2 = rms_norm(h, lp["mlp_norm"], c.rms_norm_eps)
            h = h + _mlp(x2, lp, c, valid=active)
            return h, latent_row

        h, lat_rows = lax.scan(
            layer_fn, h, (params["layers"], jnp.arange(L, dtype=jnp.int32), lat_win)
        )
        lat_win = lat_win.at[:, i].set(lat_rows)
        h = rms_norm(h, params["final_norm"], c.rms_norm_eps)
        head = params.get("lm_head")
        logits = (h @ (head if head is not None else params["embed"].T)).astype(jnp.float32)
        key, sub = jax.random.split(key)
        nxt = sample_batch(logits, temps, top_ks, top_ps, sub).astype(jnp.int32)
        out = out.at[i].set(nxt)
        return (nxt, lat_win, out, key)

    # Window rows are in-flight REAL values; int8 caches quantize only at
    # the final fused scatter (k_cache.dtype would be int8 for QuantKv).
    lat_win0 = jnp.zeros((L, num_steps, B, R), dtype=params["embed"].dtype)
    out0 = jnp.zeros((num_steps, B), dtype=jnp.int32)
    _, lat_win, out, _ = lax.fori_loop(0, num_steps, body, (tokens, lat_win0, out0, rng_key))

    steps_i = jnp.arange(num_steps, dtype=jnp.int32)
    slots = jnp.where(active[None, :], positions[None, :] + steps_i[:, None], 0)  # [w, B]
    tgt_blocks = jnp.where(active[None, :], block_tables[jnp.arange(B)[None, :], slots // bs], 0)
    tgt_offs = slots % bs
    layer_idx = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32)[:, None, None], (L, num_steps, B))
    k_new = _scatter_kv(
        k_cache, layer_idx, tgt_blocks[None], tgt_offs[None], lat_win[:, :, :, None, :]
    )
    return out, k_new, v_cache
