"""Pallas TPU fused prefill (flash) attention.

The XLA prefill path materializes f32 scores ``[KVH, T, G, ctx+T]`` per
layer plus a gathered copy of the cached context — at 2K tokens that is
GBs of HBM traffic per layer and caps prefill at ~15% MFU (measured on
v5e, BENCH_r03). This kernel is the role FlashAttention plays inside the
reference's engines (SURVEY.md §1 L5; anchor
/root/reference/docs/benchmarks/pre_deployment_profiling.md:54): blocked
K/V with an online softmax, scores never leave VMEM.

Design notes (v5e, measured with tools in tools/):
- Head-major layout: the caller transposes the chunk K/V to
  ``[KVH, T, HD]`` / K to ``[KVH, HD, T]`` (K pre-transposed so both
  matmuls are MXU-natural — contracting q's lane dim against kᵀ's sublane
  dim; contracting lanes-vs-lanes forces an in-kernel transpose that
  halves throughput, measured).
- Grouped queries ride as rows: q is ``[KVH, T*G, HD]`` and a (kvh, qb)
  program computes ``[BQ*G, BK]`` score tiles — GQA never materializes
  repeated KV heads.
- Causal + validity masking happens on the f32 tile in VMEM; the k-block
  loop stops at the causal frontier of the q block, so the triangle's
  upper half is never computed.
- The kernel also returns the online-softmax state ``(m, l)`` per row so
  a cached-prefix piece (paged KV, gathered by XLA bounded to the true
  prefix width) merges outside the kernel. Fresh prefills (cache_len==0,
  the serving-hot path) statically skip that piece altogether.

Measured (llama-3.2-1b shapes, KVH=8 G=4 HD=64, T=2048, v5e): 40.8
TFLOP/s causal — ~21× the two-piece XLA path at equal shapes.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _chunk_kernel(
    len_ref,  # SMEM [1] i32 — valid_len (keys/queries beyond are padding)
    q_ref,  # VMEM [1, BQ*G, HD]
    kt_ref,  # VMEM [1, HD, T] — whole chunk K, pre-transposed
    v_ref,  # VMEM [1, T, HD]
    o_ref,  # VMEM [1, BQ*G, HD]
    m_ref,  # VMEM [1, BQ*G, 1] f32 — row max (online-softmax state)
    l_ref,  # VMEM [1, BQ*G, 1] f32 — row sum
    *,
    block_q: int,
    block_k: int,
    chunk_len: int,
    groups: int,
    scale: float,
):
    qb = pl.program_id(1)
    valid_len = len_ref[0]
    q = q_ref[0]  # [BQG, HD]
    rows = q.shape[0]
    hd = q.shape[1]
    m = jnp.full((rows, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((rows, 1), jnp.float32)
    acc = jnp.zeros((rows, hd), jnp.float32)
    # Query position of each row: rows are (t, g) pairs, g minor.
    tq = qb * block_q + lax.broadcasted_iota(jnp.int32, (rows, 1), 0) // groups

    # Only k blocks at or below the causal frontier of this q block.
    nk = (qb * block_q + block_q + block_k - 1) // block_k

    def body(j, carry):
        m, l, acc = carry
        if block_k == chunk_len:
            # Single k block (small buckets): no dynamic slice — lane-dim
            # offsets must be provably 128-aligned, which j*block_k is not
            # for block_k < 128 (Mosaic rejects the load).
            kt = kt_ref[0]  # [HD, T]
            v = v_ref[0]  # [T, HD]
        else:
            start = pl.multiple_of(j * block_k, block_k)
            kt = kt_ref[0, :, pl.ds(start, block_k)]  # [HD, BK]
            v = v_ref[0, pl.ds(start, block_k), :]  # [BK, HD]
        s = (
            lax.dot_general(q, kt, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
            * scale
        )  # [BQG, BK]
        kpos = j * block_k + lax.broadcasted_iota(jnp.int32, (rows, block_k), 1)
        s = jnp.where((kpos <= tq) & (kpos < valid_len), s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(0, nk, body, (m, l, acc))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    m_ref[0] = m
    l_ref[0] = l


def _pick_blocks(T: int, groups: int) -> Tuple[int, int]:
    """Block sizes: BQ*G ≈ 1024 rows (sweep-optimal on v5e), BK = 512.
    T is a power-of-two bucket, so divisibility holds by construction.
    BK must be ≥128 (lane-aligned dynamic slices) — below that the kernel
    takes the whole chunk as one k block."""
    target = max(1024 // max(groups, 1), 128)
    bq = 1 << (target.bit_length() - 1)  # pow2 ≤ target
    bq = max(1, min(bq, T))
    while T % bq:
        bq //= 2
    bk = min(512, T)
    while T % bk:
        bk //= 2
    if bk < 128:
        bk = T  # single block — no in-kernel dynamic slicing
    return bq, bk


@functools.partial(jax.jit, static_argnames=("num_kv_heads", "interpret"))
def flash_chunk_attention(
    q: jax.Array,  # [T, H, HD] post-rope
    k_new: jax.Array,  # [T, KVH, HD] post-rope
    v_new: jax.Array,  # [T, KVH, HD]
    valid_len: jax.Array,  # scalar i32
    *,
    num_kv_heads: int,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Causal chunk self-attention with online softmax.

    Returns ``(out [T, H, HD], m [T, KVH, G], l [T, KVH, G])`` — the
    normalized output plus softmax state for merging a cached-prefix
    piece via :func:`merge_attention_pieces`.
    """
    T, H, HD = q.shape
    KVH = num_kv_heads
    G = H // KVH
    BQ, BK = _pick_blocks(T, G)
    BQG = BQ * G
    nq = T // BQ

    # Head-major fold: rows of head kvh are its (t, g) query pairs.
    q_r = q.reshape(T, KVH, G, HD).transpose(1, 0, 2, 3).reshape(KVH, T * G, HD)
    kt = k_new.transpose(1, 2, 0)  # [KVH, HD, T]
    v_r = v_new.transpose(1, 0, 2)  # [KVH, T, HD]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(KVH, nq),
        in_specs=[
            pl.BlockSpec((1, BQG, HD), lambda h, i, *_: (h, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, HD, T), lambda h, i, *_: (h, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T, HD), lambda h, i, *_: (h, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, BQG, HD), lambda h, i, *_: (h, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BQG, 1), lambda h, i, *_: (h, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BQG, 1), lambda h, i, *_: (h, i, 0), memory_space=pltpu.VMEM),
        ),
    )
    out, m, l = pl.pallas_call(
        functools.partial(
            _chunk_kernel, block_q=BQ, block_k=BK, chunk_len=T, groups=G, scale=HD**-0.5
        ),
        out_shape=(
            jax.ShapeDtypeStruct((KVH, T * G, HD), q.dtype),
            jax.ShapeDtypeStruct((KVH, T * G, 1), jnp.float32),
            jax.ShapeDtypeStruct((KVH, T * G, 1), jnp.float32),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(jnp.asarray([valid_len], dtype=jnp.int32), q_r, kt, v_r)

    out = out.reshape(KVH, T, G, HD).transpose(1, 0, 2, 3).reshape(T, H, HD)
    m = m.reshape(KVH, T, G).transpose(1, 0, 2)  # [T, KVH, G]
    l = l.reshape(KVH, T, G).transpose(1, 0, 2)
    return out, m, l


def merge_attention_pieces(
    out2: jax.Array,  # [T, H, HD] — normalized kernel output
    m2: jax.Array,  # [T, KVH, G]
    l2: jax.Array,
    m1: jax.Array,  # [KVH, T, G] — XLA prefix piece (llama.prefill `piece` layout)
    l1: jax.Array,
    acc1: jax.Array,  # [KVH, T, G, HD] f32 — UNnormalized prefix accumulator
) -> jax.Array:
    """Close the online softmax across [cached prefix ; chunk] pieces."""
    T, H, HD = out2.shape
    KVH = m1.shape[0]
    G = H // KVH
    m2t = m2.transpose(1, 0, 2)  # [KVH, T, G]
    l2t = l2.transpose(1, 0, 2)
    acc2 = out2.reshape(T, KVH, G, HD).transpose(1, 0, 2, 3).astype(jnp.float32) * l2t[..., None]
    m_t = jnp.maximum(m1, m2t)
    a1 = jnp.exp(m1 - m_t)
    a2 = jnp.exp(m2t - m_t)
    l_t = l1 * a1 + l2t * a2
    acc = acc1 * a1[..., None] + acc2 * a2[..., None]
    out = acc / jnp.maximum(l_t, 1e-30)[..., None]  # [KVH, T, G, HD]
    return out.transpose(1, 0, 2, 3).reshape(T, H, HD).astype(out2.dtype)
