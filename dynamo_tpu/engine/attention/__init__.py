"""Attention implementations: the ragged paged-attention megakernel
(megakernel.py — ONE Pallas launch per layer for a whole mixed step's
ragged batch, plus the fused N-step decode window; TPU auto-selection),
the Pallas flash prefill kernel (prefill.py — 40.8 TF/s causal at 1B
shapes on v5e), the opt-in per-piece paged decode kernel (decode.py),
ring attention for sequence/context parallelism (ring.py), and the XLA
width-bucketed gather fallback (models/llama.py). Selection + the full
dispatch-overhead record: ModelConfig.attention_impl."""
