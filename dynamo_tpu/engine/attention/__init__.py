"""Attention implementations: the Pallas flash prefill kernel (prefill.py —
40.8 TF/s causal at 1B shapes on v5e), ring attention for sequence/context
parallelism (ring.py), and the XLA width-bucketed gather for paged decode
(models/llama.py). A Pallas paged-DMA decode kernel lived here until r4;
it was deleted after measuring 3-6× slower than the gather in every regime
— ModelConfig.attention_impl records the numbers."""
