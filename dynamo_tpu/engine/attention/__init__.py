"""Attention implementations: XLA paged gather (default), ring attention for
sequence/context parallelism, Pallas kernels for TPU hot paths."""
