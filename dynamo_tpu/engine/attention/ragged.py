"""Ragged prefill-chunk attention: the shared chunk-over-[prefix ; chunk]
piece behind both phase-separated prefill and MIXED prefill+decode steps.

A ragged batch row is a ``(start, len)`` run of tokens over the paged KV
cache: ``start`` (= ``cache_len``) tokens are already materialized behind a
block table, ``len`` (= ``valid_len``) fresh tokens attend causally within
the chunk and fully over the cached prefix. Decode entries are just
length-1 rows of the same shape — the mixed step (models/llama.py
``mixed_step``) carries them through the in-register two-piece path while
this module handles the chunk rows.

Two backends, numerically interchangeable:
- **XLA** (default off-TPU): one masked softmax over the concatenated
  ``[prefix ; chunk]`` keys — the width-bucketed gather bounds the prefix
  extent, the mask covers fresh and continuation chunks alike.
- **Pallas flash** (opt-in fast path, ``ModelConfig.prefill_impl``): the
  chunk's causal self-attention runs in the flash kernel (prefill.py —
  scores never leave VMEM) and the cached-prefix piece is an online-softmax
  partial merged outside the kernel; fresh chunks (``has_prefix=False``)
  statically skip the prefix piece altogether.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def ragged_chunk_attention(
    q: jax.Array,  # [T, H, HD] post-rope chunk queries
    k_new: jax.Array,  # [T, KVH, HD] post-rope chunk keys
    v_new: jax.Array,  # [T, KVH, HD]
    k_ctx: Optional[jax.Array],  # [ctx, KVH, HD] gathered cached prefix (None iff flash+fresh)
    v_ctx: Optional[jax.Array],
    valid_len: jax.Array,  # scalar i32 — the row's ``len``
    cache_len: jax.Array,  # scalar i32 — the row's ``start``
    *,
    num_kv_heads: int,
    use_flash: bool = False,
    has_prefix: bool = True,
    interpret: bool = False,
) -> jax.Array:
    """Attention for one ragged chunk row over ``[cached prefix ; chunk]``.

    Returns ``[T, H, HD]``. The caller gathers ``k_ctx``/``v_ctx`` through
    its width-bucketed block table (the gather stays O(true prefix), not
    O(max_seq_len)); on the flash path with ``has_prefix=False`` the prefix
    arguments may be ``None`` and no gather is needed at all.
    """
    T, H, HD = q.shape
    kvh = num_kv_heads
    G = H // kvh
    scale = HD**-0.5

    if use_flash:
        from dynamo_tpu.engine.attention.prefill import (
            flash_chunk_attention,
            merge_attention_pieces,
        )

        out2, m2, l2 = flash_chunk_attention(
            q, k_new, v_new, valid_len, num_kv_heads=kvh, interpret=interpret
        )
        if not has_prefix:
            return out2
        # Cached-prefix partial (online-softmax state), merged with the
        # kernel's chunk piece outside the kernel.
        ctx = k_ctx.shape[0]
        key_pos = jnp.arange(ctx, dtype=jnp.int32)
        qg = q.reshape(T, kvh, G, HD)
        s = jnp.einsum("tkgd,skd->ktgs", qg, k_ctx).astype(jnp.float32) * scale
        s = jnp.where((key_pos < cache_len)[None, None, None, :], s, -1e30)
        m1 = jnp.max(s, axis=-1)  # [KVH, T, G]
        p = jnp.exp(s - m1[..., None])
        l1 = jnp.sum(p, axis=-1)
        acc1 = jnp.einsum("ktgs,skd->ktgd", p.astype(v_ctx.dtype), v_ctx).astype(jnp.float32)
        return merge_attention_pieces(out2, m2, l2, m1, l1, acc1)

    # XLA path: full masked softmax over [prefix ; chunk]. ``has_prefix``
    # is a no-op here — the prefix mask (key_pos < cache_len) covers fresh
    # chunks (cache_len == 0 masks everything), so one executable serves
    # both and the callers keep it traced.
    ctx = k_ctx.shape[0]
    key_pos = jnp.arange(ctx, dtype=jnp.int32)
    chunk_q = jnp.arange(T, dtype=jnp.int32)
    valid_q = chunk_q < valid_len
    prefix_mask = jnp.broadcast_to(key_pos[None, :] < cache_len, (T, ctx))  # [T, ctx]
    chunk_mask = (chunk_q[None, :] <= chunk_q[:, None]) & valid_q[None, :]  # [T, T]
    mask = jnp.concatenate([prefix_mask, chunk_mask], axis=1)  # [T, ctx+T]

    qg = q.reshape(T, kvh, G, HD)
    k_all = jnp.concatenate([k_ctx, k_new], axis=0)
    v_all = jnp.concatenate([v_ctx, v_new], axis=0)
    scores = jnp.einsum("tkgd,skd->ktgs", qg, k_all).astype(jnp.float32) * scale
    scores = jnp.where(mask[None, :, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("ktgs,skd->tkgd", probs, v_all)
    return out.reshape(T, H, HD)
