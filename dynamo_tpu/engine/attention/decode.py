"""Pallas TPU paged flash-decode attention.

The XLA decode path gathers the cached prefix through the block table —
``kc[block_tables]`` — which materializes the gathered copy in HBM: every
byte of prefix KV moves three times (read at gather, write of the copy,
read by the attention dot). ``decode_multi`` hoists that gather to once
per window, but the packed buffer still costs a full extra read+write per
window and pins multi-GB buffers at wide batch. This kernel is the role
FlashAttention/paged-attention plays inside the reference's GPU engines
(SURVEY.md §1 L5; /root/reference/lib/llm/src/block_manager/ is the
block-table owner there): attention reads each prefix page from HBM into
VMEM exactly once, and nothing is ever written back.

Design notes (v5e, measured with the decode ablation harness — now folded
into bench.py's ``decode_attention`` section):
- **Pages ARE the pipeline blocks.** The grid is ``(B, W)`` — one program
  per (sequence, table slot) — and the page fetch is a plain BlockSpec
  whose index_map reads the block id from the scalar-prefetched table.
  Pallas's grid pipeline double-buffers the fetches; there are no manual
  DMAs. This only pays at large pages: at ``block_size=16`` the per-page
  issue/latency cost exceeds the 19 ns the 16 KB transfer needs, which is
  exactly why the r4 hand-rolled kernel lost 3× to the XLA gather and was
  deleted. At 256-token pages (256 KB per K page) the fetch is
  bandwidth-bound. Big pages are the TPU-native choice (same conclusion
  as vLLM's TPU backend); the scheduler's block accounting is already
  ``block_size``-agnostic.
- **Ragged for free.** Slots past a sequence's true length point at the
  reserved scratch block 0; consecutive identical block indices skip the
  refetch in the pipeline, so a short sequence in a wide-bucketed table
  costs one wasted page fetch, not W. Compute for dead slots is skipped
  with ``pl.when``.
- **Block-diagonal GQA fold.** Per page the kernel runs TWO dots, not
  2·KVH tiny ones: the caller scatters q into a block-diagonal
  ``Wq[B, KVH*G, KVH*HD]`` (zeros off-block) so
  ``scores = Wq[b] · k_pageᵀ`` yields exact per-head scores (off-block
  lanes hit zeros) in one MXU-shaped ``[KVH*G, 512]×[512, BS]`` matmul.
  The ×KVH FLOP overhead is immaterial — decode attention has ~100×
  MXU headroom; bytes are the budget. The lanes-vs-lanes contraction
  (cache pages are token-major ``[BS, KVH, HD]``) costs an in-kernel
  transpose that would matter in a compute-bound kernel and does not
  here.
- Returns UNnormalized online-softmax partials ``(m, l, acc)`` in the
  ``_attend_piece`` layout so the decode window's in-register piece
  merges outside the kernel via ``_merge_pieces``, identically to the
  XLA path.

On non-TPU backends the kernel runs in interpreter mode so unit tests
exercise the identical code path (``interpret=True``).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _paged_kernel(
    tables_ref,  # SMEM [B, W] i32 — block ids (already layer-offset)
    lens_ref,  # SMEM [B] i32 — prefix length per row (0 = inactive)
    wq_ref,  # VMEM [1, KVG, KVHD] — block-diagonal folded queries, this b
    k_ref,  # VMEM [1, BS, KVH*HD] — this (b, w)'s K page (merged-head lanes)
    v_ref,  # VMEM [1, BS, KVH*HD]
    m_ref,  # VMEM [1, KVG, 1] f32 out
    l_ref,  # VMEM [1, KVG, 1] f32 out
    acc_ref,  # VMEM [1, KVG, KVHD] f32 out
    *,
    block_size: int,
    scale: float,
):
    b, w = pl.program_id(0), pl.program_id(1)
    kv_len = lens_ref[b]
    bs = block_size

    @pl.when(w == 0)
    def _init():
        m_ref[0] = jnp.full(m_ref.shape[1:], NEG_INF, jnp.float32)
        l_ref[0] = jnp.zeros(l_ref.shape[1:], jnp.float32)
        acc_ref[0] = jnp.zeros(acc_ref.shape[1:], jnp.float32)

    # Tokens this page holds: [w*bs, w*bs + bs) — compute only if any are
    # inside the row's true prefix.
    @pl.when(w * bs < kv_len)
    def _compute():
        wq = wq_ref[0]  # [KVG, KVHD]
        rows, merged = wq.shape
        k = k_ref[0]  # [BS, KVH*HD] — merged lanes, reshaped by the caller
        v = v_ref[0]
        s = (
            lax.dot_general(
                wq, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * scale
        )  # [KVG, BS]
        kpos = w * bs + lax.broadcasted_iota(jnp.int32, (rows, bs), 1)
        s = jnp.where(kpos < kv_len, s, NEG_INF)
        m_prev = m_ref[0]  # [KVG, 1]
        l_prev = l_ref[0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        pv = lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [KVG, KVHD]
        m_ref[0] = m_new
        l_ref[0] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[0] = acc_ref[0] * alpha + pv


@functools.partial(
    jax.jit, static_argnames=("num_kv_heads", "block_size", "interpret")
)
def paged_decode_partials(
    q: jax.Array,  # [B, H, HD] post-rope current-token queries
    k_pages: jax.Array,  # [NP, BS, KVH, HD] layer-flat page pool
    v_pages: jax.Array,
    tables: jax.Array,  # [B, W] i32 — page ids, layer-offset, padded slots → 0
    lengths: jax.Array,  # [B] i32 — true prefix length (0 = inactive row)
    *,
    num_kv_heads: int,
    block_size: int,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Prefix-piece decode attention over the paged cache.

    Returns ``(m, l, acc)`` — UNnormalized online-softmax partials shaped
    ``[B, KVH, G]`` / ``[B, KVH, G]`` / ``[B, KVH, G, HD]`` f32, matching
    ``llama._attend_piece`` so the caller merges with the window piece via
    ``llama._merge_pieces``. Rows with ``lengths == 0`` come back as the
    empty piece (m = -inf, l = 0) and drop out of the merge.
    """
    B, H, HD = q.shape
    KVH = num_kv_heads
    G = H // KVH
    KVG, KVHD = KVH * G, KVH * HD
    W = tables.shape[1]

    # Block-diagonal fold: Wq[b, (kvh, g), (kvh', hd)] = q · 1[kvh == kvh'].
    q_r = q.reshape(B, KVH, G, HD)
    eye = jnp.eye(KVH, dtype=q.dtype)[:, None, :, None]  # [KVH, 1, KVH, 1]
    wq = (q_r[:, :, :, None, :] * eye[None]).reshape(B, KVG, KVHD)

    # Merge the (KVH, HD) trailing dims into lanes OUTSIDE the kernel —
    # contiguous, so XLA reshapes metadata only; Mosaic cannot shape-cast
    # [BS, KVH, HD] → [BS, KVH*HD] in-kernel.
    NP = k_pages.shape[0]
    BS = k_pages.shape[1]
    k2 = k_pages.reshape(NP, BS, KVHD)
    v2 = v_pages.reshape(NP, BS, KVHD)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, W),
        in_specs=[
            pl.BlockSpec((1, KVG, KVHD), lambda b, w, t, ln: (b, 0, 0)),
            pl.BlockSpec((1, BS, KVHD), lambda b, w, t, ln: (t[b, w], 0, 0)),
            pl.BlockSpec((1, BS, KVHD), lambda b, w, t, ln: (t[b, w], 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, KVG, 1), lambda b, w, t, ln: (b, 0, 0)),
            pl.BlockSpec((1, KVG, 1), lambda b, w, t, ln: (b, 0, 0)),
            pl.BlockSpec((1, KVG, KVHD), lambda b, w, t, ln: (b, 0, 0)),
        ),
    )
    m, l, acc = pl.pallas_call(
        functools.partial(
            _paged_kernel, block_size=block_size, scale=HD**-0.5
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, KVG, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, KVG, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, KVG, KVHD), jnp.float32),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32), wq, k2, v2)

    m = m.reshape(B, KVH, G)
    l = l.reshape(B, KVH, G)
    # acc rows live in their head's diagonal block: [B, (kvh, g), (kvh, hd)].
    acc = acc.reshape(B, KVH, G, KVH, HD)
    acc = acc[:, jnp.arange(KVH), :, jnp.arange(KVH), :]  # [KVH, B, G, HD]
    acc = acc.transpose(1, 0, 2, 3)  # [B, KVH, G, HD]
    return m, l, acc
