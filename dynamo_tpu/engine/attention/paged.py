"""Pallas TPU paged-attention decode kernel.

The XLA fallback in ``models/llama.py`` materializes the gathered context
``kc[block_tables]`` — ``[B, ctx, KVH, HD]`` of HBM traffic per layer even
for short sequences, because the gather length is the *bucketed* block-table
width. This kernel is the TPU-native replacement (the role
``block_copy.cu`` + FlashAttention play on the reference's GPU engines,
SURVEY.md §2b N3): it walks each sequence's real block list, DMAs KV blocks
HBM→VMEM with double buffering, and accumulates attention with an online
softmax — HBM traffic is proportional to the *actual* context length, and
no gathered copy of the cache is ever materialized.

Mosaic alignment drives the layout: DMA slices must be lane-aligned (minor
dim a multiple of 128), so KV pages move as ``[BS, KVH*HD]`` rows — the
contiguous row of our ``[N, BS, KVH, HD]`` cache, and a 128-multiple for
every real model (KVH*HD ≥ 512). Per-kv-head compute would need unaligned
``HD``-sized lane slices, so the kernel never splits heads; instead the
caller folds the grouped queries into a block-diagonal matrix
``W[KVH*HD, KVH*G]`` (zeros off-block) and the kernel is just two matmuls
per page:

    scores[KVH*G, BS]   = Wᵀ · k_pageᵀ     (exact GQA scores — off-block
                                            lanes contribute 0)
    out_m[KVH*G, KVH*HD] += softmax(scores) · v_page

All online-softmax state is rowwise (``[KVH*G, 1]``), so there are no
in-kernel transposes or reshapes. The block-diagonal of ``out_m`` (the true
attention output) is extracted outside the kernel in XLA. The ×KVH matmul
overhead is immaterial: decode attention is HBM-bandwidth-bound and the DMA
volume is unchanged.

On non-TPU backends the same kernel runs in interpreter mode so unit tests
exercise the identical code path (``interpret=True``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    tables_ref,  # SMEM [B, W] int32 — block ids per sequence
    lens_ref,  # SMEM [B] int32 — CACHED kv length (current token separate; 0 = inactive)
    extra_ref,  # SMEM [B] int32 — valid in-register rows (≥1: current + window)
    # inputs
    w_ref,  # VMEM [1, KVH*HD, KVH*G] — block-diagonal queries
    k_hbm,  # ANY  [N, BS, KVH*HD]
    v_hbm,  # ANY  [N, BS, KVH*HD]
    kcur_ref,  # VMEM [1, R, KVH*HD] — in-register rows: current token (+ window)
    vcur_ref,  # VMEM [1, R, KVH*HD]
    # outputs
    out_ref,  # VMEM [1, KVH*G, KVH*HD]
    # scratch
    k_buf,  # VMEM [2, STRIP*BS, KVH*HD]
    v_buf,  # VMEM [2, STRIP*BS, KVH*HD]
    sems,  # DMA sems [2, STRIP, 2]
    *,
    block_size: int,
    scale: float,
    strip: int,
    fold_cur: bool,
):
    """Pages are processed in strips of ``strip`` pages per loop iteration:
    one 16-token page is a ~16 KB DMA (latency-bound) and a [rows, 16]
    matmul (MXU-starved); a strip amortizes DMA issue latency over
    strip× the bytes and widens the matmuls to [rows, strip*BS]."""
    b = pl.program_id(0)
    kv_len = lens_ref[b]
    bs = block_size
    n_pages = pl.cdiv(kv_len, bs)
    n_strips = pl.cdiv(n_pages, strip)

    rows = w_ref.shape[2]  # KVH*G
    merged = w_ref.shape[1]  # KVH*HD

    def strip_dma(slot, strip_idx):
        """Issue up to ``strip`` page-pair DMAs into the slot's buffer."""
        dmas = []
        for j in range(strip):  # static unroll
            page_idx = strip_idx * strip + j
            # Clamp: tail strips re-read page 0 into lanes that the score
            # mask then discards — cheaper than a dynamic DMA count.
            safe_idx = jnp.where(page_idx < n_pages, page_idx, 0)
            block_id = tables_ref[b, safe_idx]
            dmas.append(pltpu.make_async_copy(
                k_hbm.at[block_id], k_buf.at[slot, pl.ds(j * bs, bs)], sems.at[slot, j, 0]
            ))
            dmas.append(pltpu.make_async_copy(
                v_hbm.at[block_id], v_buf.at[slot, pl.ds(j * bs, bs)], sems.at[slot, j, 1]
            ))
        return dmas

    @pl.when(kv_len > 0)
    def _():
        for dma in strip_dma(0, 0):
            dma.start()

    w = w_ref[0]  # [KVH*HD, KVH*G]
    span = strip * bs

    def body(i, carry):
        m, l, acc = carry
        slot = lax.rem(i, 2)

        @pl.when(i + 1 < n_strips)
        def _():
            for dma in strip_dma(lax.rem(i + 1, 2), i + 1):
                dma.start()

        for dma in strip_dma(slot, i):
            dma.wait()

        k = k_buf[slot]  # [STRIP*BS, KVH*HD]
        v = v_buf[slot]

        # scores[r, s] = Σ_c w[c, r] · k[s, c] — GQA scores for row r=(kvh,g):
        # w is zero outside kvh's lane block, so cross-head lanes vanish.
        scores = lax.dot_general(
            w, k,
            dimension_numbers=(((0,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [KVH*G, STRIP*BS]

        key_pos = i * span + lax.broadcasted_iota(jnp.int32, (rows, span), 1)
        scores = jnp.where(key_pos < kv_len, scores, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(scores, axis=1, keepdims=True))  # [rows, 1]
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new)  # [rows, STRIP*BS]
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)

        # out_m[r, c] += Σ_s p[r, s] · v[s, c]
        pv = lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [rows, merged]
        acc_new = acc * alpha + pv
        return m_new, l_new, acc_new

    m0 = jnp.full((rows, 1), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((rows, 1), dtype=jnp.float32)
    acc0 = jnp.zeros((rows, merged), dtype=jnp.float32)
    m, l, acc = lax.fori_loop(0, n_strips, body, (m0, l0, acc0))

    if fold_cur:
        # Fold the in-register rows (current token + any multi-step window
        # rows — their K/V never round-trips through HBM): [rows, R] scores
        # with columns ≥ extra_ref[b] masked, then close the online softmax.
        k_cur = kcur_ref[0]  # [R, merged]
        v_cur = vcur_ref[0]
        R = k_cur.shape[0]
        s_cur = lax.dot_general(
            w, k_cur,
            dimension_numbers=(((0,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [rows, R]
        col = lax.broadcasted_iota(jnp.int32, (rows, R), 1)
        s_cur = jnp.where(col < extra_ref[b], s_cur, NEG_INF)
        m_f = jnp.maximum(m, jnp.max(s_cur, axis=1, keepdims=True))
        alpha_f = jnp.exp(m - m_f)
        p_f = jnp.exp(s_cur - m_f)  # [rows, R]
        l = l * alpha_f + jnp.sum(p_f, axis=1, keepdims=True)
        acc = acc * alpha_f + lax.dot_general(
            p_f.astype(v_cur.dtype), v_cur,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    l_safe = jnp.where(l > 0.0, l, 1.0)
    out_ref[0] = (acc / l_safe).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_size", "interpret", "pages_per_strip"))
def paged_decode_attention(
    q: jax.Array,  # [B, H, HD]
    k_cache: jax.Array,  # [N, BS, KVH, HD]
    v_cache: jax.Array,
    block_tables: jax.Array,  # [B, W] int32
    kv_lens: jax.Array,  # [B] int32 — CACHED tokens per row (0 for inactive)
    *,
    k_cur: Optional[jax.Array] = None,  # [B, KVH, HD] or [B, R, KVH, HD] in-register K rows
    v_cur: Optional[jax.Array] = None,
    extra_valid: Optional[jax.Array] = None,  # [B] i32 — valid in-register rows (default: all R)
    block_size: int,
    interpret: bool = False,
    pages_per_strip: int = 16,
) -> jax.Array:
    """Single decode-step attention over the paged KV cache → [B, H, HD].

    ``k_cur``/``v_cur`` carry in-register K/V rows that never round-trip
    through HBM: the token being decoded, and (multi-step windows) the
    window's earlier tokens — row 0 must be the current token, rows 1..R-1
    the window rows, with ``extra_valid[b]`` giving the live prefix count.
    Callers can thus defer the cache write to one fused scatter per window
    (llama.decode_multi). When omitted, rows attend to the cached prefix
    only."""
    B, H, HD = q.shape
    N, BS, KVH, _ = k_cache.shape
    G = H // KVH
    merged = KVH * HD
    rows = KVH * G
    strip = max(1, min(pages_per_strip, block_tables.shape[1]))

    # Block-diagonal fold: W[b, kvh*HD+d, kvh*G+g] = q[b, kvh, g, d].
    q5 = q.reshape(B, KVH, G, HD)
    eye = jnp.eye(KVH, dtype=q.dtype)
    w = jnp.einsum("bkgd,kj->bkdjg", q5, eye).reshape(B, merged, rows)

    if k_cur is None:
        # No in-register token: fold a -inf-scoring dummy (zero K with the
        # score masked via zero V and the guard below keeps exactness).
        k_cur_m = jnp.zeros((B, 1, merged), dtype=k_cache.dtype)
        v_cur_m = jnp.zeros((B, 1, merged), dtype=v_cache.dtype)
        extra = jnp.zeros((B,), dtype=jnp.int32)
        fold_cur = False
    else:
        R = 1 if k_cur.ndim == 3 else k_cur.shape[1]
        k_cur_m = k_cur.reshape(B, R, merged)
        v_cur_m = v_cur.reshape(B, R, merged)
        extra = (
            jnp.full((B,), R, dtype=jnp.int32)
            if extra_valid is None
            else extra_valid.astype(jnp.int32)
        )
        fold_cur = True

    # Minor-dims merge is layout-free; pages DMA as contiguous [BS, KVH*HD].
    k_m = k_cache.reshape(N, BS, merged)
    v_m = v_cache.reshape(N, BS, merged)

    Rm = k_cur_m.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, merged, rows), lambda b, *_: (b, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, Rm, merged), lambda b, *_: (b, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Rm, merged), lambda b, *_: (b, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, rows, merged), lambda b, *_: (b, 0, 0), memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, strip * BS, merged), k_cache.dtype),
            pltpu.VMEM((2, strip * BS, merged), v_cache.dtype),
            pltpu.SemaphoreType.DMA((2, strip, 2)),
        ],
    )

    out_m = pl.pallas_call(
        functools.partial(
            _decode_kernel, block_size=block_size, scale=HD**-0.5, strip=strip, fold_cur=fold_cur
        ),
        out_shape=jax.ShapeDtypeStruct((B, rows, merged), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32), kv_lens.astype(jnp.int32), extra,
        w, k_m, v_m, k_cur_m, v_cur_m,
    )

    # Extract the block diagonal: out[b, kvh, g, :] = out_m[b, kvh*G+g, kvh*HD:+HD].
    out5 = out_m.reshape(B, KVH, G, KVH, HD)
    diag = jnp.diagonal(out5, axis1=1, axis2=3)  # [B, G, HD, KVH]
    return jnp.transpose(diag, (0, 3, 1, 2)).reshape(B, H, HD)
