"""Ring attention: causal attention over sequence-sharded Q/K/V.

The reference has **no** sequence/context parallelism — long context is
handled by KV offload + disaggregation + engine-side TP (SURVEY.md §2e).
Ring attention is dynamo-tpu's genuinely new engine capability: shard the
sequence over the ``sp`` mesh axis, rotate K/V shards around the ring with
``ppermute`` (ICI neighbor exchanges — the cheapest collective on a TPU
torus), and accumulate attention with an online-softmax (flash-style) state
so no device ever materializes the full sequence.

Math: per ring step the local state (m, l, o) merges a new score block via
the standard log-sum-exp update; after ``axis_size`` rotations every Q shard
has attended to every K/V shard. Causality is enforced per (q_shard,
kv_shard) pair on global positions: shards strictly in the future are
skipped-by-masking (fully masked rows contribute zero weight).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _block_attend(q, k, v, q_offset, kv_offset, scale, causal):
    """One Q-shard × KV-shard attention block in grouped-query form.

    q: [T, KVH, G, hd]; k/v: [S, KVH, hd] → (scores_max [T,KVH,G],
    exp_scores [KVH,T,G,S], value_part [T,KVH,G,hd] pieces via caller).
    Returns (m_block, p, pv): row max, exp'd scores, and p@v.
    """
    scores = jnp.einsum("tkgd,skd->ktgs", q, k).astype(jnp.float32) * scale  # [KVH,T,G,S]
    if causal:
        T, S = q.shape[0], k.shape[0]
        qpos = q_offset + jnp.arange(T)
        kpos = kv_offset + jnp.arange(S)
        mask = qpos[:, None] >= kpos[None, :]  # [T, S]
        scores = jnp.where(mask[None, :, None, :], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)  # [KVH, T, G]
    p = jnp.exp(scores - m[..., None])
    # Fully-masked rows: m = NEG_INF ⇒ force p to 0 (exp(0)=1 otherwise).
    p = jnp.where(m[..., None] <= NEG_INF / 2, 0.0, p)
    pv = jnp.einsum("ktgs,skd->ktgd", p.astype(v.dtype), v).astype(jnp.float32)  # [KVH,T,G,hd]
    return m, p, pv


def _ring_attention_sharded(q, k, v, *, axis_name: str, causal: bool, scale: float):
    """Runs inside shard_map: q/k/v are the local sequence shards.

    q: [T_local, H, hd]; k/v: [S_local, KVH, hd].
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    T, H, hd = q.shape
    S, KVH, _ = k.shape
    G = H // KVH
    qg = q.reshape(T, KVH, G, hd)
    q_offset = idx * T

    # Online-softmax accumulators (pcast-to-varying: the loop makes them device-varying,
    # so the carry must start that way for shard_map's type system).
    m_acc = lax.pcast(jnp.full((KVH, T, G), NEG_INF, dtype=jnp.float32), axis_name, to="varying")
    l_acc = lax.pcast(jnp.zeros((KVH, T, G), dtype=jnp.float32), axis_name, to="varying")
    o_acc = lax.pcast(jnp.zeros((KVH, T, G, hd), dtype=jnp.float32), axis_name, to="varying")

    def body(r, carry):
        m_acc, l_acc, o_acc, k_cur, v_cur = carry
        src = (idx - r) % n  # which shard these K/V came from
        kv_offset = src * S
        m_blk, p, pv = _block_attend(qg, k_cur, v_cur, q_offset, kv_offset, scale, causal)
        m_new = jnp.maximum(m_acc, m_blk)
        # Rescale old state and the new block into the shared max.
        alpha = jnp.exp(jnp.where(m_acc <= NEG_INF / 2, NEG_INF, m_acc - m_new))
        beta = jnp.exp(jnp.where(m_blk <= NEG_INF / 2, NEG_INF, m_blk - m_new))
        l_new = l_acc * alpha + jnp.sum(p, axis=-1) * beta
        o_new = o_acc * alpha[..., None] + pv * beta[..., None]
        # Rotate K/V one step around the ring (neighbor exchange on ICI).
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return m_new, l_new, o_new, k_nxt, v_nxt

    m_acc, l_acc, o_acc, _, _ = lax.fori_loop(0, n, body, (m_acc, l_acc, o_acc, k, v))
    out = o_acc / jnp.maximum(l_acc[..., None], 1e-30)
    # [KVH, T, G, hd] → [T, H, hd]
    return out.transpose(1, 0, 2, 3).reshape(T, H, hd).astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """Causal attention with the sequence sharded over ``axis_name``.

    q: [T, H, hd], k/v: [T, KVH, hd] — global shapes; T must divide by the
    axis size. Returns [T, H, hd] with the same sharding as q.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    spec = P(axis_name, None, None)
    fn = jax.shard_map(
        partial(_ring_attention_sharded, axis_name=axis_name, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
