"""Ragged paged-attention megakernel: ONE Pallas launch for the whole
mixed prefill+decode step's attention, plus a fused multi-step decode
window (one launch spanning N steps × L layers).

Why this exists (the dispatch-overhead record): the r4 per-piece Pallas
paged kernel lost every serving regime not on bytes but on per-
``pallas_call`` dispatch overhead — a no-op kernel inside a jitted loop
measures 1.3-5 ms/call on tunneled runtimes, and the old design issued
2+ launches per layer (chunk flash kernel + decode prefix kernel) plus
the XLA gather's triple traffic (gather read + packed-copy write +
attend re-read) on the fallback. The fix is to amortize launches, not to
re-tune the kernel (ROADMAP item 1; blueprint: "Ragged Paged Attention",
arxiv 2604.15464):

**Tier 1 — ``ragged_paged_attention``** (this module's workhorse): one
launch per layer serves EVERY row of a mixed step. A row is a
``(start, len)`` run of queries over ``[paged prefix ; fresh keys]``:
prefill chunks are wide rows, decode entries are length-1 rows, and both
share one grid — ``(query, page)`` — with

- *scalar-prefetched block tables* (the page fetch is a plain BlockSpec
  whose index_map reads the table; Pallas double-buffers the HBM→VMEM
  streams, nothing is ever written back — vs the gather's 3× traffic),
- the *block-diagonal GQA fold* proven in ``attention/decode.py`` (one
  MXU-shaped dot per page instead of G tiny ones; decode attention has
  ~100× MXU headroom, bytes are the budget),
- ``pl.when`` skipping for dead slots: padded queries and
  table slots past a row's true length cost no page fetch and no
  compute, so ragged batches cost bytes, not bucket width,
- an int8-KV dequant-in-VMEM path (per-(token, head) scales streamed
  alongside the int8 codes and expanded over lanes in-kernel), so
  capacity-mode deployments keep the fused path.

**Tier 2 — ``fused_decode_window``**: one ``pallas_call`` whose grid
spans ``(num_steps, num_layers)`` runs an ENTIRE greedy decode window —
embedding, per-layer matmuls + rope + paged attention + SwiGLU, lm_head,
argmax, and the KV writes — with the sampled token fed back through VMEM
scratch between grid steps (TPU grids execute sequentially, so the
carry is exact). Exactly ONE kernel launch per N-step window; the
``decode_multi`` dispatch-overhead term disappears entirely and the
prefix pages are the only KV bytes read. Gated to VMEM-resident scale
(``fused_window_fits``): weights + cache must fit on-chip, which covers
draft/small models today; larger models use Tier 1 per step. Compiled-
TPU status: experimental — the kernel is written jnp-first and verified
in interpreter mode (tier-1 CI); the VMEM gate keeps it off real chips
until the DMA-streamed variant lands.

``trace_launch_count()`` counts ``pallas_call`` invocations at TRACE
time: a fused window executable must contain exactly ONE launch site
(asserted in CI via the flight recorder's ``fused_window_pallas_launches``
gauge) so dispatch-amortization regressions — someone un-fusing the loop
back into per-step or per-piece kernels — fail loudly.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Trace-time pallas_call counter (see module docstring). Incremented once
# per launch SITE traced, so `delta == 1` across tracing a whole fused
# window proves the executable contains a single fused launch.
_TRACE_LAUNCHES = 0


def _count_launch() -> None:
    global _TRACE_LAUNCHES
    _TRACE_LAUNCHES += 1


def trace_launch_count() -> int:
    """Total pallas_call sites traced by this module since import."""
    return _TRACE_LAUNCHES


# ---------------------------------------------------------------------------
# Tier 1: ragged paged-attention megakernel (one launch per layer)
# ---------------------------------------------------------------------------


def build_meta(
    row_of: jax.Array,  # [NQ] i32 — block-table row of each query
    prefix_len: jax.Array,  # [NQ] i32 — cached-prefix length each query attends
    extra_start: jax.Array,  # [NQ] i32 — first fresh-key column (incl.)
    extra_end: jax.Array,  # [NQ] i32 — fresh-key causal frontier (excl.)
    active: jax.Array,  # [NQ] bool/i32 — dead queries skip pages AND compute
) -> jax.Array:
    """Pack per-query ragged metadata into the kernel's [5, NQ] i32 table."""
    return jnp.stack(
        [
            row_of.astype(jnp.int32),
            prefix_len.astype(jnp.int32),
            extra_start.astype(jnp.int32),
            extra_end.astype(jnp.int32),
            active.astype(jnp.int32),
        ]
    )


def _online_update(m_ref, l_ref, acc_ref, s, v):
    """Fold one score tile + value tile into the online-softmax scratch."""
    m_prev = m_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m_prev - m_new)
    pv = lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = m_new
    l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + pv


def _mega_kernel(
    tables_ref,  # SMEM [R, W] i32 — per-row page ids (layer-offset, dead → 0)
    meta_ref,  # SMEM [5, NQ] i32 — build_meta layout
    wq_ref,  # VMEM [1, KVG, KVHD] — this query's block-diagonal fold
    ke_ref,  # VMEM [CK, KVHD] — ALL fresh keys (lane-merged), loaded once
    ve_ref,  # VMEM [CK, KVHD]
    k_ref,  # VMEM [1, BS, KVHD] — this (query, slot)'s K page
    v_ref,
    *rest,  # (ks_ref, vs_ref)? o_ref, m_ref, l_ref, acc_ref
    block_size: int,
    num_slots: int,
    scale: float,
    quant: bool,
):
    if quant:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
        ks_ref = vs_ref = None
    nq, w = pl.program_id(0), pl.program_id(1)
    prefix_len = meta_ref[1, nq]
    e_start = meta_ref[2, nq]
    e_end = meta_ref[3, nq]
    live = meta_ref[4, nq] > 0
    bs = block_size
    wq = wq_ref[0]  # [KVG, KVHD]
    rows = wq.shape[0]

    @pl.when(w == 0)
    def _init():
        m_ref[:] = jnp.full(m_ref.shape, NEG_INF, jnp.float32)
        l_ref[:] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[:] = jnp.zeros(acc_ref.shape, jnp.float32)

    # Paged-prefix piece: slot w holds tokens [w*bs, w*bs+bs) of this
    # query's row. Dead queries and slots past the true prefix are skipped
    # entirely — no page fetch is wasted on bucket width (consecutive
    # identical table entries reuse the pipelined fetch, so a short row in
    # a wide bucket costs one scratch-page fetch, not W).
    @pl.when(live & (w < num_slots) & (w * bs < prefix_len))
    def _page():
        if quant:
            # int8 dequant in VMEM: per-(token, head) scales expand over
            # the HD lanes (lane j of the merged (kvh, hd) axis carries
            # head j // HD). The codes stream at 1 byte/value — the whole
            # point of int8 KV is capacity, and the fused path keeps it.
            hd = k_ref.shape[2] // ks_ref.shape[2]
            k = k_ref[0].astype(wq.dtype) * jnp.repeat(
                ks_ref[0], hd, axis=-1
            ).astype(wq.dtype)
            v = v_ref[0].astype(wq.dtype) * jnp.repeat(
                vs_ref[0], hd, axis=-1
            ).astype(wq.dtype)
        else:
            k = k_ref[0]  # [BS, KVHD]
            v = v_ref[0]
        s = (
            lax.dot_general(
                wq, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * scale
        )  # [KVG, BS]
        kpos = w * bs + lax.broadcasted_iota(jnp.int32, (rows, bs), 1)
        s = jnp.where(kpos < prefix_len, s, NEG_INF)
        _online_update(m_ref, l_ref, acc_ref, s, v)

    # Final slot: the in-flight (not-yet-cached) keys — a chunk query's
    # causal window over its own chunk, a decode query's current token, a
    # window query's carry rows — then close the softmax and normalize.
    @pl.when(w == num_slots)
    def _fresh_and_final():
        @pl.when(live & (e_end > e_start))
        def _fresh():
            ke = ke_ref[:]  # [CK, KVHD]
            ve = ve_ref[:]
            s = (
                lax.dot_general(
                    wq, ke, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [KVG, CK]
            cpos = lax.broadcasted_iota(jnp.int32, (rows, ke.shape[0]), 1)
            s = jnp.where((cpos >= e_start) & (cpos < e_end), s, NEG_INF)
            _online_update(m_ref, l_ref, acc_ref, s, ve)

        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("num_kv_heads", "block_size", "interpret")
)
def ragged_paged_attention(
    q: jax.Array,  # [NQ, H, HD] post-rope queries (chunk rows then decode rows)
    k_extra: jax.Array,  # [CK, KVH, HD] in-flight keys (chunk K, window rows, current tokens)
    v_extra: jax.Array,
    k_pages,  # [NP, BS, KVH, HD] layer-flat page pool, or QuantKv
    v_pages,
    tables: jax.Array,  # [R, W] i32 — per-sequence-row page ids (layer-offset)
    meta: jax.Array,  # [5, NQ] i32 — build_meta
    *,
    num_kv_heads: int,
    block_size: int,
    interpret: bool = False,
) -> jax.Array:
    """Attention for a whole ragged batch over [paged prefix ; fresh keys]
    in ONE kernel launch. Returns normalized ``[NQ, H, HD]`` — the prefix
    pages and the fresh piece merge inside the kernel's online softmax, so
    no external ``_merge_pieces`` is needed and no gathered prefix copy is
    ever materialized in HBM.

    Dead queries (``meta`` active = 0) return zeros and read nothing.
    """
    from dynamo_tpu.engine.kv_cache import QuantKv

    NQ, H, HD = q.shape
    KVH = num_kv_heads
    G = H // KVH
    KVG, KVHD = KVH * G, KVH * HD
    W = tables.shape[1]
    CK = k_extra.shape[0]
    quant = isinstance(k_pages, QuantKv)

    # Block-diagonal GQA fold (attention/decode.py): off-block lanes hit
    # zeros, so one [KVG, KVHD]×[KVHD, BS] dot yields exact per-head
    # scores. The ×KVH query-byte inflation is immaterial next to the KV
    # bytes the kernel exists to save.
    q_r = q.reshape(NQ, KVH, G, HD)
    eye = jnp.eye(KVH, dtype=q.dtype)[:, None, :, None]
    wq = (q_r[:, :, :, None, :] * eye[None]).reshape(NQ, KVG, KVHD)

    ke = k_extra.reshape(CK, KVHD)
    ve = v_extra.reshape(CK, KVHD)

    if quant:
        NP, BS = k_pages.q.shape[0], k_pages.q.shape[1]
        k2, v2 = k_pages.q.reshape(NP, BS, KVHD), v_pages.q.reshape(NP, BS, KVHD)
        ks = k_pages.scale.reshape(NP, BS, KVH).astype(jnp.float32)
        vs = v_pages.scale.reshape(NP, BS, KVH).astype(jnp.float32)
    else:
        NP, BS = k_pages.shape[0], k_pages.shape[1]
        k2, v2 = k_pages.reshape(NP, BS, KVHD), v_pages.reshape(NP, BS, KVHD)

    def page_idx(nq, w, t, mt):
        return (t[mt[0, nq], jnp.minimum(w, W - 1)], 0, 0)

    in_specs = [
        pl.BlockSpec((1, KVG, KVHD), lambda nq, w, t, mt: (nq, 0, 0)),
        pl.BlockSpec((CK, KVHD), lambda nq, w, t, mt: (0, 0)),
        pl.BlockSpec((CK, KVHD), lambda nq, w, t, mt: (0, 0)),
        pl.BlockSpec((1, BS, KVHD), page_idx),
        pl.BlockSpec((1, BS, KVHD), page_idx),
    ]
    args = [wq, ke, ve, k2, v2]
    if quant:
        in_specs += [
            pl.BlockSpec((1, BS, KVH), page_idx),
            pl.BlockSpec((1, BS, KVH), page_idx),
        ]
        args += [ks, vs]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(NQ, W + 1),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, KVG, KVHD), lambda nq, w, t, mt: (nq, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((KVG, 1), jnp.float32),
            pltpu.VMEM((KVG, 1), jnp.float32),
            pltpu.VMEM((KVG, KVHD), jnp.float32),
        ],
    )
    _count_launch()
    out = pl.pallas_call(
        functools.partial(
            _mega_kernel,
            block_size=block_size,
            num_slots=W,
            scale=HD**-0.5,
            quant=quant,
        ),
        out_shape=jax.ShapeDtypeStruct((NQ, KVG, KVHD), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(tables.astype(jnp.int32), meta.astype(jnp.int32), *args)

    # Each query's output lives in its head's diagonal block of the fold.
    out = out.reshape(NQ, KVH, G, KVH, HD)
    out = out[:, jnp.arange(KVH), :, jnp.arange(KVH), :]  # [KVH, NQ, G, HD]
    return out.transpose(1, 0, 2, 3).reshape(NQ, H, HD)


# ---------------------------------------------------------------------------
# Tier 2: fused multi-step decode window (one launch per window)
# ---------------------------------------------------------------------------


def fused_window_fits(
    param_bytes: int, cache_bytes: int, budget_bytes: Optional[int] = None
) -> bool:
    """VMEM-residency gate for the fused window: the kernel keeps weights,
    embedding/head, and the paged cache on-chip, so it only serves models
    whose working set fits (draft/small models; the tier-1 test scale).
    Larger deployments fall back to the per-step ragged megakernel, which
    streams pages per launch. Override via
    ``DYNAMO_TPU_FUSED_WINDOW_MAX_BYTES``."""
    import os

    if budget_bytes is None:
        budget_bytes = int(
            os.environ.get("DYNAMO_TPU_FUSED_WINDOW_MAX_BYTES", 12 << 20)
        )
    return param_bytes + cache_bytes <= budget_bytes


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """apply_rope's exact math (split halves, not interleaved) in-kernel.
    ``lax.iota`` instead of ``jnp.arange``: arange materializes a constant
    the kernel would capture (Pallas rejects captured consts)."""
    hd = x.shape[-1]
    freqs = 1.0 / (theta ** (lax.iota(jnp.float32, hd // 2) * 2.0 / hd))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., hd/2]
    cos = jnp.cos(angles)[..., None, :]
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _rms(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    n = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (n * w.astype(jnp.float32)).astype(x.dtype)


def _fused_window_kernel(
    # scalar prefetch
    tables_ref,  # SMEM [B, W] i32 — block ids (NOT layer-offset)
    pos0_ref,  # SMEM [B] i32 — write slot of the first window token
    act_ref,  # SMEM [B] i32
    tok0_ref,  # SMEM [B] i32 — step-0 input tokens
    # tensor inputs (whole arrays resident; the VMEM gate guards size)
    embed_ref,  # [V, D]
    head_ref,  # [D, V]
    fnorm_ref,  # [D]
    anorm_ref,  # [L, D]
    mnorm_ref,  # [L, D]
    wq_ref,  # [L, D, HQ]
    wk_ref,  # [L, D, HKV]
    wv_ref,  # [L, D, HKV]
    wo_ref,  # [L, HQ, D]
    wg_ref,  # [L, D, F]
    wu_ref,  # [L, D, F]
    wd_ref,  # [L, F, D]
    k_in_ref,  # [L, N, BS, KVH, HD] (aliased to k_out off-interpret)
    v_in_ref,
    # outputs
    tok_out_ref,  # [NSTEPS, B] i32
    k_out_ref,  # [L, N, BS, KVH, HD]
    v_out_ref,
    # scratch
    h_ref,  # VMEM [B, D] wdtype — the inter-layer residual carry
    tok_ref,  # SMEM [B] i32 — on-device token feedback between steps
    *,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
    block_size: int,
    rms_eps: float,
    theta: float,
):
    i, l = pl.program_id(0), pl.program_id(1)
    L = pl.num_programs(1)
    B = h_ref.shape[0]
    W = tables_ref.shape[1]
    H, KVH, HD, bs = num_heads, num_kv_heads, head_dim, block_size
    G = H // KVH
    scale = HD**-0.5

    # One defensive full-cache copy at window start: correct whether or not
    # the runtime honored the input/output alias (interpret mode does not).
    @pl.when((i == 0) & (l == 0))
    def _seed_cache():
        k_out_ref[:] = k_in_ref[:]
        v_out_ref[:] = v_in_ref[:]

    # Step entry: embed this step's input tokens — step 0 from the host,
    # later steps from the PREVIOUS grid step's argmax (VMEM/SMEM carry:
    # the on-device token feedback that makes one launch span the window).
    @pl.when(l == 0)
    def _embed():
        for b in range(B):
            tok = jnp.where(i == 0, tok0_ref[b], tok_ref[b])
            h_ref[b, :] = embed_ref[tok, :].astype(h_ref.dtype)

    h = h_ref[:]  # [B, D]
    x = _rms(h, anorm_ref[l], rms_eps)
    q = jnp.dot(x, wq_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
    k = jnp.dot(x, wk_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
    v = jnp.dot(x, wv_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
    q = q.reshape(B, H, HD)
    k = k.reshape(B, KVH, HD)
    v = v.reshape(B, KVH, HD)
    positions = jnp.stack([pos0_ref[b] for b in range(B)]) + i  # [B]
    q = _rope(q, positions, theta)
    k = _rope(k, positions, theta)

    # Write-before-attend: this step's K/V rows land in the cache first,
    # then attention masks kpos <= pos — identical math to the in-register
    # current-token piece, and it makes the cache the single source of
    # truth for the window carry (parity with decode_multi's final fused
    # scatter is asserted down to cache contents).
    for b in range(B):
        pos_b = positions[b]
        live = act_ref[b] > 0
        slot = jnp.where(live, pos_b, 0)
        blk = jnp.where(live, tables_ref[b, slot // bs], 0)
        off = slot % bs
        k_out_ref[l, blk, off] = k[b].astype(k_out_ref.dtype)
        v_out_ref[l, blk, off] = v[b].astype(v_out_ref.dtype)

    attn_rows = []
    for b in range(B):
        pages_k = [k_out_ref[l, tables_ref[b, w]] for w in range(W)]
        pages_v = [v_out_ref[l, tables_ref[b, w]] for w in range(W)]
        kb = jnp.concatenate(pages_k, axis=0).astype(x.dtype)  # [W*BS, KVH, HD]
        vb = jnp.concatenate(pages_v, axis=0).astype(x.dtype)
        qg = q[b].reshape(KVH, G, HD)
        s = jnp.einsum("kgd,skd->kgs", qg, kb).astype(jnp.float32) * scale
        kpos = lax.iota(jnp.int32, W * bs)
        s = jnp.where(kpos[None, None, :] <= positions[b], s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1).astype(x.dtype)
        attn_rows.append(jnp.einsum("kgs,skd->kgd", p, vb).reshape(H * HD))
    attn = jnp.stack(attn_rows)  # [B, HQ]

    h = h + jnp.dot(attn, wo_ref[l], preferred_element_type=jnp.float32).astype(h.dtype)
    x = _rms(h, mnorm_ref[l], rms_eps)
    g = jnp.dot(x, wg_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
    u = jnp.dot(x, wu_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
    mlp = jnp.dot(
        jax.nn.silu(g) * u, wd_ref[l], preferred_element_type=jnp.float32
    ).astype(h.dtype)
    h = h + mlp
    h_ref[:] = h

    # Last layer: head + greedy argmax, token fed back for step i+1.
    @pl.when(l == L - 1)
    def _sample():
        hf = _rms(h_ref[:], fnorm_ref[:], rms_eps)
        logits = jnp.dot(
            hf, head_ref[:], preferred_element_type=jnp.float32
        )  # [B, V] f32
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        tok_out_ref[i, :] = nxt
        for b in range(B):
            tok_ref[b] = nxt[b]


@functools.partial(
    jax.jit,
    static_argnames=("num_steps", "num_heads", "num_kv_heads", "head_dim",
                     "block_size", "rms_eps", "theta", "interpret"),
)
def fused_decode_window(
    embed: jax.Array,  # [V, D]
    head: jax.Array,  # [D, V] (caller resolves tied embeddings)
    final_norm: jax.Array,  # [D]
    attn_norm: jax.Array,  # [L, D]
    mlp_norm: jax.Array,
    wq: jax.Array,  # [L, D, HQ]
    wk: jax.Array,
    wv: jax.Array,
    wo: jax.Array,
    w_gate: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    k_cache: jax.Array,  # [L, N, BS, KVH, HD]
    v_cache: jax.Array,
    tokens: jax.Array,  # [B] i32
    positions: jax.Array,  # [B] i32
    tables: jax.Array,  # [B, W] i32
    active: jax.Array,  # [B] bool
    *,
    num_steps: int,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
    block_size: int,
    rms_eps: float,
    theta: float,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """N greedy decode steps in ONE kernel launch (grid = steps × layers).

    Returns ``(tokens_out [num_steps, B] i32, k_cache, v_cache)`` with the
    window's KV rows written in place — token-for-token AND cache-content
    parity with greedy ``decode_multi`` (tested). The host syncs once per
    window and the device dispatches once per window.
    """
    L, N, BS, KVH, HD = k_cache.shape
    B = tokens.shape[0]
    V, D = embed.shape

    vspec = pl.BlockSpec(memory_space=pltpu.ANY) if False else pl.BlockSpec(
        memory_space=pltpu.VMEM
    )
    n_tensor_in = 14
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(num_steps, L),
        in_specs=[vspec] * n_tensor_in,
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((B, D), embed.dtype),
            pltpu.SMEM((B,), jnp.int32),
        ],
    )
    kwargs = {}
    if not interpret:
        # Donate the cache buffers into their outputs: zero-copy in-place
        # window writes on device (the kernel still seeds via an explicit
        # copy, harmless on aliased buffers). Interpret mode does not
        # support aliasing; the seed copy keeps it correct there.
        kwargs["input_output_aliases"] = {n_tensor_in - 2 + 4: 1, n_tensor_in - 1 + 4: 2}
    _count_launch()
    toks, k_new, v_new = pl.pallas_call(
        functools.partial(
            _fused_window_kernel,
            num_heads=num_heads,
            num_kv_heads=num_kv_heads,
            head_dim=head_dim,
            block_size=block_size,
            rms_eps=rms_eps,
            theta=theta,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((num_steps, B), jnp.int32),
            jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
            jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
        **kwargs,
    )(
        tables.astype(jnp.int32),
        positions.astype(jnp.int32),
        active.astype(jnp.int32),
        tokens.astype(jnp.int32),
        embed, head, final_norm, attn_norm, mlp_norm,
        wq, wk, wv, wo, w_gate, w_up, w_down,
        k_cache, v_cache,
    )
    return toks, k_new, v_new
