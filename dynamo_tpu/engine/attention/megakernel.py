"""Ragged paged-attention megakernel: ONE Pallas launch for the whole
mixed prefill+decode step's attention, plus a fused multi-step decode
window (one launch spanning N steps × L layers).

Why this exists (the dispatch-overhead record): the r4 per-piece Pallas
paged kernel lost every serving regime not on bytes but on per-
``pallas_call`` dispatch overhead — a no-op kernel inside a jitted loop
measures 1.3-5 ms/call on tunneled runtimes, and the old design issued
2+ launches per layer (chunk flash kernel + decode prefix kernel) plus
the XLA gather's triple traffic (gather read + packed-copy write +
attend re-read) on the fallback. The fix is to amortize launches, not to
re-tune the kernel (ROADMAP item 1; blueprint: "Ragged Paged Attention",
arxiv 2604.15464):

**Tier 1 — ``ragged_paged_attention``** (this module's workhorse): one
launch per layer serves EVERY row of a mixed step. A row is a
``(start, len)`` run of queries over ``[paged prefix ; fresh keys]``:
prefill chunks are wide rows, decode entries are length-1 rows, and both
share one grid — ``(query, page)`` — with

- *scalar-prefetched block tables* (the page fetch is a plain BlockSpec
  whose index_map reads the table; Pallas double-buffers the HBM→VMEM
  streams, nothing is ever written back — vs the gather's 3× traffic),
- the *block-diagonal GQA fold* proven in ``attention/decode.py`` (one
  MXU-shaped dot per page instead of G tiny ones; decode attention has
  ~100× MXU headroom, bytes are the budget),
- ``pl.when`` skipping for dead slots: padded queries and
  table slots past a row's true length cost no page fetch and no
  compute, so ragged batches cost bytes, not bucket width,
- an int8-KV dequant-in-VMEM path (per-(token, head) scales streamed
  alongside the int8 codes and expanded over lanes in-kernel), so
  capacity-mode deployments keep the fused path.

**Tier 2 — ``fused_decode_window``**: one ``pallas_call`` whose grid
spans ``(num_steps, num_layers)`` runs an ENTIRE greedy decode window —
embedding, per-layer matmuls + rope + paged attention + SwiGLU, lm_head,
argmax, and the KV writes — with the sampled token fed back through VMEM
scratch between grid steps (TPU grids execute sequentially, so the
carry is exact). Exactly ONE kernel launch per N-step window; the
``decode_multi`` dispatch-overhead term disappears entirely and the
prefix pages are the only KV bytes read. Gated to VMEM-resident scale
(``fused_window_fits``): weights + cache must fit on-chip, which covers
draft/small models today; larger models use Tier 1 per step. Compiled-
TPU status: experimental — the kernel is written jnp-first and verified
in interpreter mode (tier-1 CI); the VMEM gate keeps it off real chips
until the DMA-streamed variant lands.

``trace_launch_count()`` counts ``pallas_call`` invocations at TRACE
time: a fused window executable must contain exactly ONE launch site
(asserted in CI via the flight recorder's ``fused_window_pallas_launches``
gauge) so dispatch-amortization regressions — someone un-fusing the loop
back into per-step or per-piece kernels — fail loudly.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# Trace-time pallas_call counter (see module docstring). Incremented once
# per launch SITE traced, so `delta == 1` across tracing a whole fused
# window proves the executable contains a single fused launch.
_TRACE_LAUNCHES = 0


def _count_launch() -> None:
    global _TRACE_LAUNCHES
    _TRACE_LAUNCHES += 1


def trace_launch_count() -> int:
    """Total pallas_call sites traced by this module since import."""
    return _TRACE_LAUNCHES


# ---------------------------------------------------------------------------
# Tier 1: ragged paged-attention megakernel (one launch per layer)
# ---------------------------------------------------------------------------


def build_meta(
    row_of: jax.Array,  # [NQ] i32 — block-table row of each query
    prefix_len: jax.Array,  # [NQ] i32 — cached-prefix length each query attends
    extra_start: jax.Array,  # [NQ] i32 — first fresh-key column (incl.)
    extra_end: jax.Array,  # [NQ] i32 — fresh-key causal frontier (excl.)
    active: jax.Array,  # [NQ] bool/i32 — dead queries skip pages AND compute
) -> jax.Array:
    """Pack per-query ragged metadata into the kernel's [5, NQ] i32 table."""
    return jnp.stack(
        [
            row_of.astype(jnp.int32),
            prefix_len.astype(jnp.int32),
            extra_start.astype(jnp.int32),
            extra_end.astype(jnp.int32),
            active.astype(jnp.int32),
        ]
    )


def _online_update(m_ref, l_ref, acc_ref, s, v):
    """Fold one score tile + value tile into the online-softmax scratch."""
    m_prev = m_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m_prev - m_new)
    pv = lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = m_new
    l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + pv


def _mega_kernel(
    tables_ref,  # SMEM [R, W] i32 — per-row page ids (layer-offset, dead → 0)
    meta_ref,  # SMEM [5, NQ] i32 — build_meta layout
    wq_ref,  # VMEM [1, KVG, KVHD] — this query's block-diagonal fold
    ke_ref,  # VMEM [CK, KVHD] — ALL fresh keys (lane-merged), loaded once
    ve_ref,  # VMEM [CK, KVHD]
    k_ref,  # VMEM [1, BS, KVHD] — this (query, slot)'s K page
    v_ref,
    *rest,  # (ks_ref, vs_ref)? o_ref, m_ref, l_ref, acc_ref
    block_size: int,
    num_slots: int,
    scale: float,
    quant: bool,
):
    if quant:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
        ks_ref = vs_ref = None
    nq, w = pl.program_id(0), pl.program_id(1)
    prefix_len = meta_ref[1, nq]
    e_start = meta_ref[2, nq]
    e_end = meta_ref[3, nq]
    live = meta_ref[4, nq] > 0
    bs = block_size
    wq = wq_ref[0]  # [KVG, KVHD]
    rows = wq.shape[0]

    @pl.when(w == 0)
    def _init():
        m_ref[:] = jnp.full(m_ref.shape, NEG_INF, jnp.float32)
        l_ref[:] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[:] = jnp.zeros(acc_ref.shape, jnp.float32)

    # Paged-prefix piece: slot w holds tokens [w*bs, w*bs+bs) of this
    # query's row. Dead queries and slots past the true prefix are skipped
    # entirely — no page fetch is wasted on bucket width (consecutive
    # identical table entries reuse the pipelined fetch, so a short row in
    # a wide bucket costs one scratch-page fetch, not W).
    @pl.when(live & (w < num_slots) & (w * bs < prefix_len))
    def _page():
        if quant:
            # int8 dequant in VMEM: per-(token, head) scales expand over
            # the HD lanes (lane j of the merged (kvh, hd) axis carries
            # head j // HD). The codes stream at 1 byte/value — the whole
            # point of int8 KV is capacity, and the fused path keeps it.
            hd = k_ref.shape[2] // ks_ref.shape[2]
            k = k_ref[0].astype(wq.dtype) * jnp.repeat(
                ks_ref[0], hd, axis=-1
            ).astype(wq.dtype)
            v = v_ref[0].astype(wq.dtype) * jnp.repeat(
                vs_ref[0], hd, axis=-1
            ).astype(wq.dtype)
        else:
            k = k_ref[0]  # [BS, KVHD]
            v = v_ref[0]
        s = (
            lax.dot_general(
                wq, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * scale
        )  # [KVG, BS]
        kpos = w * bs + lax.broadcasted_iota(jnp.int32, (rows, bs), 1)
        s = jnp.where(kpos < prefix_len, s, NEG_INF)
        _online_update(m_ref, l_ref, acc_ref, s, v)

    # Final slot: the in-flight (not-yet-cached) keys — a chunk query's
    # causal window over its own chunk, a decode query's current token, a
    # window query's carry rows — then close the softmax and normalize.
    @pl.when(w == num_slots)
    def _fresh_and_final():
        @pl.when(live & (e_end > e_start))
        def _fresh():
            ke = ke_ref[:]  # [CK, KVHD]
            ve = ve_ref[:]
            s = (
                lax.dot_general(
                    wq, ke, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [KVG, CK]
            cpos = lax.broadcasted_iota(jnp.int32, (rows, ke.shape[0]), 1)
            s = jnp.where((cpos >= e_start) & (cpos < e_end), s, NEG_INF)
            _online_update(m_ref, l_ref, acc_ref, s, ve)

        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("num_kv_heads", "block_size", "interpret")
)
def ragged_paged_attention(
    q: jax.Array,  # [NQ, H, HD] post-rope queries (chunk rows then decode rows)
    k_extra: jax.Array,  # [CK, KVH, HD] in-flight keys (chunk K, window rows, current tokens)
    v_extra: jax.Array,
    k_pages,  # [NP, BS, KVH, HD] layer-flat page pool, or QuantKv
    v_pages,
    tables: jax.Array,  # [R, W] i32 — per-sequence-row page ids (layer-offset)
    meta: jax.Array,  # [5, NQ] i32 — build_meta
    *,
    num_kv_heads: int,
    block_size: int,
    interpret: bool = False,
) -> jax.Array:
    """Attention for a whole ragged batch over [paged prefix ; fresh keys]
    in ONE kernel launch. Returns normalized ``[NQ, H, HD]`` — the prefix
    pages and the fresh piece merge inside the kernel's online softmax, so
    no external ``_merge_pieces`` is needed and no gathered prefix copy is
    ever materialized in HBM.

    Dead queries (``meta`` active = 0) return zeros and read nothing.
    """
    from dynamo_tpu.engine.kv_cache import QuantKv

    NQ, H, HD = q.shape
    KVH = num_kv_heads
    G = H // KVH
    KVG, KVHD = KVH * G, KVH * HD
    W = tables.shape[1]
    CK = k_extra.shape[0]
    quant = isinstance(k_pages, QuantKv)

    # Block-diagonal GQA fold (attention/decode.py): off-block lanes hit
    # zeros, so one [KVG, KVHD]×[KVHD, BS] dot yields exact per-head
    # scores. The ×KVH query-byte inflation is immaterial next to the KV
    # bytes the kernel exists to save.
    q_r = q.reshape(NQ, KVH, G, HD)
    eye = jnp.eye(KVH, dtype=q.dtype)[:, None, :, None]
    wq = (q_r[:, :, :, None, :] * eye[None]).reshape(NQ, KVG, KVHD)

    ke = k_extra.reshape(CK, KVHD)
    ve = v_extra.reshape(CK, KVHD)

    if quant:
        NP, BS = k_pages.q.shape[0], k_pages.q.shape[1]
        k2, v2 = k_pages.q.reshape(NP, BS, KVHD), v_pages.q.reshape(NP, BS, KVHD)
        ks = k_pages.scale.reshape(NP, BS, KVH).astype(jnp.float32)
        vs = v_pages.scale.reshape(NP, BS, KVH).astype(jnp.float32)
    else:
        NP, BS = k_pages.shape[0], k_pages.shape[1]
        k2, v2 = k_pages.reshape(NP, BS, KVHD), v_pages.reshape(NP, BS, KVHD)

    def page_idx(nq, w, t, mt):
        return (t[mt[0, nq], jnp.minimum(w, W - 1)], 0, 0)

    in_specs = [
        pl.BlockSpec((1, KVG, KVHD), lambda nq, w, t, mt: (nq, 0, 0)),
        pl.BlockSpec((CK, KVHD), lambda nq, w, t, mt: (0, 0)),
        pl.BlockSpec((CK, KVHD), lambda nq, w, t, mt: (0, 0)),
        pl.BlockSpec((1, BS, KVHD), page_idx),
        pl.BlockSpec((1, BS, KVHD), page_idx),
    ]
    args = [wq, ke, ve, k2, v2]
    if quant:
        in_specs += [
            pl.BlockSpec((1, BS, KVH), page_idx),
            pl.BlockSpec((1, BS, KVH), page_idx),
        ]
        args += [ks, vs]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(NQ, W + 1),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, KVG, KVHD), lambda nq, w, t, mt: (nq, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((KVG, 1), jnp.float32),
            pltpu.VMEM((KVG, 1), jnp.float32),
            pltpu.VMEM((KVG, KVHD), jnp.float32),
        ],
    )
    _count_launch()
    out = pl.pallas_call(
        functools.partial(
            _mega_kernel,
            block_size=block_size,
            num_slots=W,
            scale=HD**-0.5,
            quant=quant,
        ),
        out_shape=jax.ShapeDtypeStruct((NQ, KVG, KVHD), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(tables.astype(jnp.int32), meta.astype(jnp.int32), *args)

    # Each query's output lives in its head's diagonal block of the fold.
    out = out.reshape(NQ, KVH, G, KVH, HD)
    out = out[:, jnp.arange(KVH), :, jnp.arange(KVH), :]  # [KVH, NQ, G, HD]
    return out.transpose(1, 0, 2, 3).reshape(NQ, H, HD)


# ---------------------------------------------------------------------------
# Tier 2: fused multi-step decode window (one launch per window)
# ---------------------------------------------------------------------------


def fused_window_fits(
    param_bytes: int, cache_bytes: int, budget_bytes: Optional[int] = None
) -> bool:
    """VMEM-residency gate for the fused window: the kernel keeps weights,
    embedding/head, and the paged cache on-chip, so it only serves models
    whose working set fits (draft/small models; the tier-1 test scale).
    Larger deployments fall back to the per-step ragged megakernel, which
    streams pages per launch. Override via
    ``DYNAMO_TPU_FUSED_WINDOW_MAX_BYTES``."""
    import os

    if budget_bytes is None:
        budget_bytes = int(
            os.environ.get("DYNAMO_TPU_FUSED_WINDOW_MAX_BYTES", 12 << 20)
        )
    return param_bytes + cache_bytes <= budget_bytes


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """apply_rope's exact math (split halves, not interleaved) in-kernel.
    ``lax.iota`` instead of ``jnp.arange``: arange materializes a constant
    the kernel would capture (Pallas rejects captured consts)."""
    hd = x.shape[-1]
    freqs = 1.0 / (theta ** (lax.iota(jnp.float32, hd // 2) * 2.0 / hd))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., hd/2]
    cos = jnp.cos(angles)[..., None, :]
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _rms(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    n = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (n * w.astype(jnp.float32)).astype(x.dtype)


def _fused_window_kernel(
    # scalar prefetch
    tables_ref,  # SMEM [B, W] i32 — block ids (NOT layer-offset)
    pos0_ref,  # SMEM [B] i32 — write slot of the first window token
    act_ref,  # SMEM [B] i32
    tok0_ref,  # SMEM [B] i32 — step-0 input tokens
    rows0_ref,  # SMEM [B] i32 — guided mask-pool row at window start (0 = allow-all)
    # tensor inputs (whole arrays resident; the VMEM gate guards size):
    # 12 weights, then (sampled? temps/tks/tps/uniforms), then
    # (guided? mask_pool/next_pool), then k_in/v_in — parsed from *rest so
    # the cache operands stay LAST and the in/out alias indices stay a
    # fixed formula of n_tensor_in.
    embed_ref,  # [V, D]
    head_ref,  # [D, V]
    fnorm_ref,  # [D]
    anorm_ref,  # [L, D]
    mnorm_ref,  # [L, D]
    wq_ref,  # [L, D, HQ]
    wk_ref,  # [L, D, HKV]
    wv_ref,  # [L, D, HKV]
    wo_ref,  # [L, HQ, D]
    wg_ref,  # [L, D, F]
    wu_ref,  # [L, D, F]
    wd_ref,  # [L, F, D]
    *rest,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
    block_size: int,
    rms_eps: float,
    theta: float,
    sampled: bool,
    guided: bool,
):
    r = 0
    if sampled:
        temps_ref, tks_ref, tps_ref, unif_ref = rest[r : r + 4]
        r += 4
    if guided:
        mask_ref, next_ref = rest[r : r + 2]  # [P, ceil(V/32)] u32, [P, V] i32
        r += 2
    (
        k_in_ref,  # [L, N, BS, KVH, HD] (aliased to k_out off-interpret)
        v_in_ref,
        # outputs
        tok_out_ref,  # [NSTEPS, B] i32
        k_out_ref,  # [L, N, BS, KVH, HD]
        v_out_ref,
        # scratch
        h_ref,  # VMEM [B, D] wdtype — the inter-layer residual carry
        tok_ref,  # SMEM [B] i32 — on-device token feedback between steps
        row_ref,  # SMEM [B] i32 — guided FSM row carry (unused unless guided)
    ) = rest[r : r + 8]

    i, l = pl.program_id(0), pl.program_id(1)
    L = pl.num_programs(1)
    B = h_ref.shape[0]
    W = tables_ref.shape[1]
    H, KVH, HD, bs = num_heads, num_kv_heads, head_dim, block_size
    G = H // KVH
    scale = HD**-0.5

    # One defensive full-cache copy at window start: correct whether or not
    # the runtime honored the input/output alias (interpret mode does not).
    @pl.when((i == 0) & (l == 0))
    def _seed_cache():
        k_out_ref[:] = k_in_ref[:]
        v_out_ref[:] = v_in_ref[:]
        for b in range(B):
            row_ref[b] = rows0_ref[b]

    # Step entry: embed this step's input tokens — step 0 from the host,
    # later steps from the PREVIOUS grid step's argmax (VMEM/SMEM carry:
    # the on-device token feedback that makes one launch span the window).
    @pl.when(l == 0)
    def _embed():
        for b in range(B):
            tok = jnp.where(i == 0, tok0_ref[b], tok_ref[b])
            h_ref[b, :] = embed_ref[tok, :].astype(h_ref.dtype)

    h = h_ref[:]  # [B, D]
    x = _rms(h, anorm_ref[l], rms_eps)
    q = jnp.dot(x, wq_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
    k = jnp.dot(x, wk_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
    v = jnp.dot(x, wv_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
    q = q.reshape(B, H, HD)
    k = k.reshape(B, KVH, HD)
    v = v.reshape(B, KVH, HD)
    positions = jnp.stack([pos0_ref[b] for b in range(B)]) + i  # [B]
    q = _rope(q, positions, theta)
    k = _rope(k, positions, theta)

    # Write-before-attend: this step's K/V rows land in the cache first,
    # then attention masks kpos <= pos — identical math to the in-register
    # current-token piece, and it makes the cache the single source of
    # truth for the window carry (parity with decode_multi's final fused
    # scatter is asserted down to cache contents).
    for b in range(B):
        pos_b = positions[b]
        live = act_ref[b] > 0
        slot = jnp.where(live, pos_b, 0)
        blk = jnp.where(live, tables_ref[b, slot // bs], 0)
        off = slot % bs
        k_out_ref[l, blk, off] = k[b].astype(k_out_ref.dtype)
        v_out_ref[l, blk, off] = v[b].astype(v_out_ref.dtype)

    attn_rows = []
    for b in range(B):
        pages_k = [k_out_ref[l, tables_ref[b, w]] for w in range(W)]
        pages_v = [v_out_ref[l, tables_ref[b, w]] for w in range(W)]
        kb = jnp.concatenate(pages_k, axis=0).astype(x.dtype)  # [W*BS, KVH, HD]
        vb = jnp.concatenate(pages_v, axis=0).astype(x.dtype)
        qg = q[b].reshape(KVH, G, HD)
        s = jnp.einsum("kgd,skd->kgs", qg, kb).astype(jnp.float32) * scale
        kpos = lax.iota(jnp.int32, W * bs)
        s = jnp.where(kpos[None, None, :] <= positions[b], s, NEG_INF)
        p = jax.nn.softmax(s, axis=-1).astype(x.dtype)
        attn_rows.append(jnp.einsum("kgs,skd->kgd", p, vb).reshape(H * HD))
    attn = jnp.stack(attn_rows)  # [B, HQ]

    h = h + jnp.dot(attn, wo_ref[l], preferred_element_type=jnp.float32).astype(h.dtype)
    x = _rms(h, mnorm_ref[l], rms_eps)
    g = jnp.dot(x, wg_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
    u = jnp.dot(x, wu_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
    mlp = jnp.dot(
        jax.nn.silu(g) * u, wd_ref[l], preferred_element_type=jnp.float32
    ).astype(h.dtype)
    h = h + mlp
    h_ref[:] = h

    # Last layer: head + in-kernel epilogue — guided rows mask against
    # their FSM row's packed allow bitmask (apply_token_masks math),
    # sampled rows draw via the shared reference filter + inverse-CDF on
    # this step's host-precomputed uniform, greedy rows argmax — then the
    # token feeds back for step i+1 and guided rows advance their FSM row
    # through the device-resident next-state pool.
    @pl.when(l == L - 1)
    def _sample():
        from dynamo_tpu.engine.sampling import sample_from_uniforms

        hf = _rms(h_ref[:], fnorm_ref[:], rms_eps)
        logits = jnp.dot(
            hf, head_ref[:], preferred_element_type=jnp.float32
        )  # [B, V] f32
        V = logits.shape[-1]
        if guided:
            rows = jnp.stack([mask_ref[row_ref[b]] for b in range(B)])  # [B, W32]
            vidx = lax.iota(jnp.int32, V)
            words = rows[:, vidx >> 5]  # [B, V] uint32
            bit = jnp.right_shift(words, (vidx & 31).astype(jnp.uint32)) & jnp.uint32(1)
            logits = jnp.where(bit.astype(bool), logits, -jnp.inf)
        if sampled:
            nxt = sample_from_uniforms(
                logits, temps_ref[:], tks_ref[:], tps_ref[:], unif_ref[i, :]
            )
        else:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        tok_out_ref[i, :] = nxt
        for b in range(B):
            tok_ref[b] = nxt[b]
            if guided:
                row_ref[b] = next_ref[row_ref[b], nxt[b]]


@functools.partial(
    jax.jit,
    static_argnames=("num_steps", "num_heads", "num_kv_heads", "head_dim",
                     "block_size", "rms_eps", "theta", "interpret",
                     "sampled", "guided"),
)
def fused_decode_window(
    embed: jax.Array,  # [V, D]
    head: jax.Array,  # [D, V] (caller resolves tied embeddings)
    final_norm: jax.Array,  # [D]
    attn_norm: jax.Array,  # [L, D]
    mlp_norm: jax.Array,
    wq: jax.Array,  # [L, D, HQ]
    wk: jax.Array,
    wv: jax.Array,
    wo: jax.Array,
    w_gate: jax.Array,
    w_up: jax.Array,
    w_down: jax.Array,
    k_cache: jax.Array,  # [L, N, BS, KVH, HD]
    v_cache: jax.Array,
    tokens: jax.Array,  # [B] i32
    positions: jax.Array,  # [B] i32
    tables: jax.Array,  # [B, W] i32
    active: jax.Array,  # [B] bool
    temps: Optional[jax.Array] = None,  # [B] f32 (sampled=True)
    top_ks: Optional[jax.Array] = None,  # [B] i32
    top_ps: Optional[jax.Array] = None,  # [B] f32
    uniforms: Optional[jax.Array] = None,  # [num_steps, B] f32 (make_window_uniforms)
    guided_rows: Optional[jax.Array] = None,  # [B] i32 mask-pool rows (guided=True)
    mask_pool: Optional[jax.Array] = None,  # [P, ceil(V/32)] uint32
    next_pool: Optional[jax.Array] = None,  # [P, V] i32 FSM next-row pool
    *,
    num_steps: int,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
    block_size: int,
    rms_eps: float,
    theta: float,
    interpret: bool = False,
    sampled: bool = False,
    guided: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """N decode steps in ONE kernel launch (grid = steps × layers).

    Returns ``(tokens_out [num_steps, B] i32, k_cache, v_cache)`` with the
    window's KV rows written in place — token-for-token AND cache-content
    parity with greedy ``decode_multi`` (tested). The host syncs once per
    window and the device dispatches once per window.

    ``sampled=True`` adds the in-kernel top-k/top-p epilogue: per-row
    packed params plus a host-precomputed ``[num_steps, B]`` uniforms
    operand (sampling.make_window_uniforms — one upload per window, no
    per-step host sync or PRNG threading in-kernel). ``guided=True`` adds
    grammar masking: each row's FSM mask rides the device-resident packed
    allow-bitmask pool, and the FSM advances ON-CHIP between steps through
    the next-state row pool, so guided rows no longer flush the window.
    """
    L, N, BS, KVH, HD = k_cache.shape
    B = tokens.shape[0]
    V, D = embed.shape

    vspec = pl.BlockSpec(memory_space=pltpu.VMEM)
    extra = []
    if sampled:
        extra += [
            temps.astype(jnp.float32), top_ks.astype(jnp.int32),
            top_ps.astype(jnp.float32), uniforms.astype(jnp.float32),
        ]
    if guided:
        extra += [mask_pool, next_pool.astype(jnp.int32)]
    n_tensor_in = 12 + len(extra) + 2
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(num_steps, L),
        in_specs=[vspec] * n_tensor_in,
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((B, D), embed.dtype),
            pltpu.SMEM((B,), jnp.int32),
            pltpu.SMEM((B,), jnp.int32),
        ],
    )
    kwargs = {}
    if not interpret:
        # Donate the cache buffers into their outputs: zero-copy in-place
        # window writes on device (the kernel still seeds via an explicit
        # copy, harmless on aliased buffers). Interpret mode does not
        # support aliasing; the seed copy keeps it correct there.
        kwargs["input_output_aliases"] = {n_tensor_in - 2 + 5: 1, n_tensor_in - 1 + 5: 2}
    rows0 = guided_rows if guided_rows is not None else jnp.zeros((B,), jnp.int32)
    _count_launch()
    toks, k_new, v_new = pl.pallas_call(
        functools.partial(
            _fused_window_kernel,
            num_heads=num_heads,
            num_kv_heads=num_kv_heads,
            head_dim=head_dim,
            block_size=block_size,
            rms_eps=rms_eps,
            theta=theta,
            sampled=sampled,
            guided=guided,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((num_steps, B), jnp.int32),
            jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
            jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
        **kwargs,
    )(
        tables.astype(jnp.int32),
        positions.astype(jnp.int32),
        active.astype(jnp.int32),
        tokens.astype(jnp.int32),
        rows0.astype(jnp.int32),
        embed, head, final_norm, attn_norm, mlp_norm,
        wq, wk, wv, wo, w_gate, w_up, w_down,
        *extra,
        k_cache, v_cache,
    )
    return toks, k_new, v_new


# ---------------------------------------------------------------------------
# Tier 2b: fused speculative window (draft + target verify in ONE launch)
# ---------------------------------------------------------------------------


def _one_token_forward(
    toks,  # [B] i32 — one input token per row
    positions,  # [B] i32 — write slot / attention frontier per row
    act_ref,  # SMEM [B] i32
    tables_ref,  # SMEM [B, W] i32
    k_ref,  # [L, N, BS, KVH, HD] output-aliased cache ref
    v_ref,
    w,  # 12-tuple of weight refs (embed..w_down, fused-window layout)
    *,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
    block_size: int,
    rms_eps: float,
    theta: float,
):
    """One token per row through ALL layers of one VMEM-resident model:
    write-before-attend KV at ``positions``, then the same paged-page
    attention math as ``_fused_window_kernel`` (python layer loop instead
    of a grid axis). Returns logits [B, V] f32. Dead rows sink their KV
    write to scratch block 0 and their logits are ignored."""
    (embed_ref, head_ref, fnorm_ref, anorm_ref, mnorm_ref,
     wq_ref, wk_ref, wv_ref, wo_ref, wg_ref, wu_ref, wd_ref) = w
    B = toks.shape[0]
    L = anorm_ref.shape[0]
    W = tables_ref.shape[1]
    H, KVH, HD, bs = num_heads, num_kv_heads, head_dim, block_size
    G = H // KVH
    scale = HD**-0.5

    h = jnp.stack([embed_ref[toks[b], :] for b in range(B)])  # [B, D]
    for l in range(L):
        x = _rms(h, anorm_ref[l], rms_eps)
        q = jnp.dot(x, wq_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
        k = jnp.dot(x, wk_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
        v = jnp.dot(x, wv_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
        q = _rope(q.reshape(B, H, HD), positions, theta)
        k = _rope(k.reshape(B, KVH, HD), positions, theta)
        v = v.reshape(B, KVH, HD)
        for b in range(B):
            live = act_ref[b] > 0
            slot = jnp.where(live, jnp.maximum(positions[b], 0), 0)
            blk = jnp.where(live, tables_ref[b, slot // bs], 0)
            off = slot % bs
            k_ref[l, blk, off] = k[b].astype(k_ref.dtype)
            v_ref[l, blk, off] = v[b].astype(v_ref.dtype)
        attn_rows = []
        for b in range(B):
            kb = jnp.concatenate(
                [k_ref[l, tables_ref[b, wi]] for wi in range(W)], axis=0
            ).astype(x.dtype)  # [W*BS, KVH, HD]
            vb = jnp.concatenate(
                [v_ref[l, tables_ref[b, wi]] for wi in range(W)], axis=0
            ).astype(x.dtype)
            qg = q[b].reshape(KVH, G, HD)
            s = jnp.einsum("kgd,skd->kgs", qg, kb).astype(jnp.float32) * scale
            kpos = lax.iota(jnp.int32, W * bs)
            s = jnp.where(kpos[None, None, :] <= positions[b], s, NEG_INF)
            p = jax.nn.softmax(s, axis=-1).astype(x.dtype)
            attn_rows.append(jnp.einsum("kgs,skd->kgd", p, vb).reshape(H * HD))
        attn = jnp.stack(attn_rows)  # [B, HQ]
        h = h + jnp.dot(attn, wo_ref[l], preferred_element_type=jnp.float32).astype(h.dtype)
        x = _rms(h, mnorm_ref[l], rms_eps)
        g = jnp.dot(x, wg_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
        u = jnp.dot(x, wu_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
        h = h + jnp.dot(
            jax.nn.silu(g) * u, wd_ref[l], preferred_element_type=jnp.float32
        ).astype(h.dtype)
    hf = _rms(h, fnorm_ref[:], rms_eps)
    return jnp.dot(hf, head_ref[:], preferred_element_type=jnp.float32)  # [B, V] f32


def _chunk_forward(
    toks,  # [B, S] i32 — S consecutive tokens per row
    pos0,  # [B] i32 — position of column 0
    act_ref,
    tables_ref,
    k_ref,
    v_ref,
    w,
    *,
    num_heads: int,
    num_kv_heads: int,
    head_dim: int,
    block_size: int,
    rms_eps: float,
    theta: float,
):
    """S-token chunk through ALL layers of one resident model (the target
    verify pass): per layer, every chunk row's K/V lands in the cache
    FIRST, then each row attends causally (kpos ≤ pos0+s) — so in-chunk
    attention reads the cache it just wrote, same write-before-attend
    contract as the single-token forward. Returns logits [B, S, V] f32."""
    (embed_ref, head_ref, fnorm_ref, anorm_ref, mnorm_ref,
     wq_ref, wk_ref, wv_ref, wo_ref, wg_ref, wu_ref, wd_ref) = w
    B, S = toks.shape
    L = anorm_ref.shape[0]
    W = tables_ref.shape[1]
    H, KVH, HD, bs = num_heads, num_kv_heads, head_dim, block_size
    G = H // KVH
    scale = HD**-0.5

    h = jnp.stack(
        [jnp.stack([embed_ref[toks[b, s], :] for s in range(S)]) for b in range(B)]
    )  # [B, S, D]
    positions = pos0[:, None] + lax.iota(jnp.int32, S)[None, :]  # [B, S]
    for l in range(L):
        x = _rms(h, anorm_ref[l], rms_eps)
        q = jnp.dot(x, wq_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
        k = jnp.dot(x, wk_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
        v = jnp.dot(x, wv_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
        q = _rope(q.reshape(B, S, H, HD), positions, theta)
        k = _rope(k.reshape(B, S, KVH, HD), positions, theta)
        v = v.reshape(B, S, KVH, HD)
        for b in range(B):
            live = act_ref[b] > 0
            for s in range(S):
                slot = jnp.where(live, jnp.maximum(positions[b, s], 0), 0)
                blk = jnp.where(live, tables_ref[b, slot // bs], 0)
                off = slot % bs
                k_ref[l, blk, off] = k[b, s].astype(k_ref.dtype)
                v_ref[l, blk, off] = v[b, s].astype(v_ref.dtype)
        attn_rows = []
        for b in range(B):
            kb = jnp.concatenate(
                [k_ref[l, tables_ref[b, wi]] for wi in range(W)], axis=0
            ).astype(x.dtype)  # [T, KVH, HD]
            vb = jnp.concatenate(
                [v_ref[l, tables_ref[b, wi]] for wi in range(W)], axis=0
            ).astype(x.dtype)
            qg = q[b].reshape(S, KVH, G, HD)
            s_sc = jnp.einsum("skgd,tkd->skgt", qg, kb).astype(jnp.float32) * scale
            kpos = lax.iota(jnp.int32, W * bs)
            mask = kpos[None, None, None, :] <= positions[b][:, None, None, None]
            s_sc = jnp.where(mask, s_sc, NEG_INF)
            p = jax.nn.softmax(s_sc, axis=-1).astype(x.dtype)
            attn_rows.append(jnp.einsum("skgt,tkd->skgd", p, vb).reshape(S, H * HD))
        attn = jnp.stack(attn_rows)  # [B, S, HQ]
        h = h + jnp.dot(attn, wo_ref[l], preferred_element_type=jnp.float32).astype(h.dtype)
        x = _rms(h, mnorm_ref[l], rms_eps)
        g = jnp.dot(x, wg_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
        u = jnp.dot(x, wu_ref[l], preferred_element_type=jnp.float32).astype(x.dtype)
        h = h + jnp.dot(
            jax.nn.silu(g) * u, wd_ref[l], preferred_element_type=jnp.float32
        ).astype(h.dtype)
    hf = _rms(h, fnorm_ref[:], rms_eps)
    return jnp.dot(hf, head_ref[:], preferred_element_type=jnp.float32)  # [B, S, V]


def _fused_spec_kernel(
    # scalar prefetch (6)
    tables_t_ref,  # SMEM [B, W] i32 — target block ids
    tables_d_ref,  # SMEM [B, W] i32 — draft block ids
    pos0_ref,  # SMEM [B] i32 — position of the last confirmed token
    act_ref,  # SMEM [B] i32
    tok0_ref,  # SMEM [B] i32 — last confirmed token
    xprev0_ref,  # SMEM [B] i32 — token at pos0-1 (draft catch-up feed)
    *rest,
    gamma: int,
    t_num_heads: int,
    t_num_kv_heads: int,
    t_head_dim: int,
    d_num_heads: int,
    d_num_kv_heads: int,
    d_head_dim: int,
    block_size: int,
    t_rms_eps: float,
    d_rms_eps: float,
    t_theta: float,
    d_theta: float,
):
    """One speculative ROUND per grid step, entire window in one launch:
    draft catch-up + γ sampled proposals, target γ+1-token verify chunk,
    inline rejection sampling, and the accepted-burst cursor advance — all
    against the two resident caches. Rejected proposals are never
    rewound: the write cursor retreats to pos+k+1, and every stale row
    beyond it is overwritten by the NEXT round's sequential writes before
    anything attends to it (write-before-attend + monotone positions), so
    rejection costs zero cache traffic."""
    from dynamo_tpu.engine.sampling import filtered_probs_rows, pick_from_probs

    G = gamma
    w_t = rest[0:12]
    w_d = rest[12:24]
    temps_ref, tks_ref, tps_ref, unif_ref = rest[24:28]  # unif: [R, B, 2G+1]
    k_t_in, v_t_in, k_d_in, v_d_in = rest[28:32]
    (toks_out_ref, acc_out_ref, k_t_ref, v_t_ref, k_d_ref, v_d_ref,
     pos_ref, tok_ref, xprev_ref) = rest[32:41]

    r = pl.program_id(0)
    B = pos0_ref.shape[0]
    t_dims = dict(
        num_heads=t_num_heads, num_kv_heads=t_num_kv_heads, head_dim=t_head_dim,
        block_size=block_size, rms_eps=t_rms_eps, theta=t_theta,
    )
    d_dims = dict(
        num_heads=d_num_heads, num_kv_heads=d_num_kv_heads, head_dim=d_head_dim,
        block_size=block_size, rms_eps=d_rms_eps, theta=d_theta,
    )

    @pl.when(r == 0)
    def _seed():
        k_t_ref[:] = k_t_in[:]
        v_t_ref[:] = v_t_in[:]
        k_d_ref[:] = k_d_in[:]
        v_d_ref[:] = v_d_in[:]
        for b in range(B):
            pos_ref[b] = pos0_ref[b]
            tok_ref[b] = tok0_ref[b]
            xprev_ref[b] = xprev0_ref[b]

    pos = jnp.stack([pos_ref[b] for b in range(B)])  # [B]
    tok = jnp.stack([tok_ref[b] for b in range(B)])
    xprev = jnp.stack([xprev_ref[b] for b in range(B)])
    temps, tks, tps = temps_ref[:], tks_ref[:], tps_ref[:]

    # 1. Draft catch-up: re-feed the token at pos-1 unconditionally. For
    # rows whose draft cache already covers pos-1 this deterministically
    # recomputes the same row (idempotent); for rows one short (the all-γ-
    # accepted case) it materializes the missing row. Logits discarded.
    _one_token_forward(
        xprev, pos - 1, act_ref, tables_d_ref, k_d_ref, v_d_ref, w_d, **d_dims
    )

    # 2. Draft proposes γ tokens via the shared reference filter +
    # inverse-CDF on host-precomputed uniforms (slots 0..γ-1).
    props = []
    pds = []
    cur, cur_pos = tok, pos
    for g in range(G):
        logits = _one_token_forward(
            cur, cur_pos, act_ref, tables_d_ref, k_d_ref, v_d_ref, w_d, **d_dims
        )
        dist = filtered_probs_rows(logits, temps, tks, tps)
        x = pick_from_probs(dist, unif_ref[r, :, g])
        props.append(x)
        pds.append(dist)
        cur, cur_pos = x, cur_pos + 1

    # 3. Target verifies [tok, x1..xγ] in one in-kernel chunk pass.
    chunk = jnp.stack([tok] + props, axis=1)  # [B, G+1]
    logits_all = _chunk_forward(
        chunk, pos, act_ref, tables_t_ref, k_t_ref, v_t_ref, w_t, **t_dims
    )  # [B, G+1, V]
    pts = [
        filtered_probs_rows(logits_all[:, s, :], temps, tks, tps)
        for s in range(G + 1)
    ]

    # 4. Rejection sampling (spec_decode.spec_verify math, uniforms from
    # slots γ..2γ-1 for accepts and 2γ for the correction/bonus pick).
    # Greedy rows' one-hot dists reduce every formula to exact argmax
    # agreement + argmax bonus.
    prop_mat = jnp.stack(props, axis=1)  # [B, G]
    accept_cols = []
    for g in range(G):
        x = props[g]
        pt_x = jnp.take_along_axis(pts[g], x[:, None], axis=1)[:, 0]
        pd_x = jnp.take_along_axis(pds[g], x[:, None], axis=1)[:, 0]
        ratio = pt_x / jnp.maximum(pd_x, 1e-20)
        accept_cols.append(unif_ref[r, :, G + g] < jnp.minimum(ratio, 1.0))
    rejected = ~jnp.stack(accept_cols, axis=1)  # [B, G]
    first_rej = jnp.where(
        jnp.any(rejected, axis=1), jnp.argmax(rejected, axis=1), G
    ).astype(jnp.int32)
    idxc = jnp.clip(first_rej, 0, G - 1)
    pt_stack = jnp.stack(pts[:G], axis=1)  # [B, G, V]
    pd_stack = jnp.stack(pds, axis=1)
    pt_k = jnp.take_along_axis(pt_stack, idxc[:, None, None], axis=1)[:, 0]
    pd_k = jnp.take_along_axis(pd_stack, idxc[:, None, None], axis=1)[:, 0]
    resid = jnp.maximum(pt_k - pd_k, 0.0)
    rs = jnp.sum(resid, axis=-1, keepdims=True)
    resid = jnp.where(rs > 1e-20, resid / jnp.maximum(rs, 1e-20), pt_k)
    upick = unif_ref[r, :, 2 * G]
    corr = pick_from_probs(resid, upick)
    bonus = pick_from_probs(pts[G], upick)
    y = jnp.where(first_rej == G, bonus, corr).astype(jnp.int32)

    # 5. Emit this round's proposals + correction/bonus and the accept
    # count; the host replays the cursor to trim at k and handle stops.
    toks_out_ref[r, :, :] = jnp.concatenate([prop_mat, y[:, None]], axis=1)
    acc_out_ref[r, :] = first_rej

    # 6. Accepted-burst cursor advance: pos += k+1, the correction/bonus
    # becomes the next round's feed token, and x_k (or tok when k=0)
    # becomes the catch-up token at the new pos-1.
    xk = jnp.where(
        first_rej >= 1,
        jnp.take_along_axis(prop_mat, jnp.clip(first_rej - 1, 0, G - 1)[:, None], axis=1)[:, 0],
        tok,
    ).astype(jnp.int32)
    for b in range(B):
        pos_ref[b] = pos[b] + first_rej[b] + 1
        tok_ref[b] = y[b]
        xprev_ref[b] = xk[b]


@functools.partial(
    jax.jit,
    static_argnames=(
        "rounds", "gamma", "block_size",
        "t_num_heads", "t_num_kv_heads", "t_head_dim", "t_rms_eps", "t_theta",
        "d_num_heads", "d_num_kv_heads", "d_head_dim", "d_rms_eps", "d_theta",
        "interpret",
    ),
)
def fused_spec_window(
    # target weights (fused-window layout)
    t_embed, t_head, t_fnorm, t_anorm, t_mnorm,
    t_wq, t_wk, t_wv, t_wo, t_wg, t_wu, t_wd,
    # draft weights
    d_embed, d_head, d_fnorm, d_anorm, d_mnorm,
    d_wq, d_wk, d_wv, d_wo, d_wg, d_wu, d_wd,
    k_t: jax.Array,  # [Lt, N, BS, KVHt, HDt] target cache
    v_t: jax.Array,
    k_d: jax.Array,  # draft cache
    v_d: jax.Array,
    tokens: jax.Array,  # [B] i32 — last confirmed token per row
    xprev: jax.Array,  # [B] i32 — token at positions-1 (draft catch-up)
    positions: jax.Array,  # [B] i32 — position of the last confirmed token
    tables_t: jax.Array,  # [B, W] i32
    tables_d: jax.Array,  # [B, W] i32
    active: jax.Array,  # [B] bool
    temps: jax.Array,  # [B] f32
    top_ks: jax.Array,  # [B] i32
    top_ps: jax.Array,  # [B] f32
    uniforms: jax.Array,  # [rounds, B, 2*gamma+1] f32
    *,
    rounds: int,
    gamma: int,
    block_size: int,
    t_num_heads: int,
    t_num_kv_heads: int,
    t_head_dim: int,
    t_rms_eps: float,
    t_theta: float,
    d_num_heads: int,
    d_num_kv_heads: int,
    d_head_dim: int,
    d_rms_eps: float,
    d_theta: float,
    interpret: bool = False,
) -> Tuple[jax.Array, ...]:
    """``rounds`` speculative rounds — draft γ-proposal bursts AND the
    target verify chunks — in ONE Pallas launch (grid = rounds; both
    models' weights and both paged caches VMEM-resident; gated by
    ``fused_window_fits`` over the combined working set).

    Returns ``(tokens_out [rounds, B, γ+1] i32, accepted [rounds, B] i32,
    k_t, v_t, k_d, v_d)``: per round, row b proposed ``tokens_out[r, b,
    :γ]``, accepted the first ``accepted[r, b]`` of them, and appended
    ``tokens_out[r, b, γ]`` as correction/bonus. The host syncs once per
    window and replays cursors (stop conditions, draft-lag accounting)
    from the two small int outputs."""
    B = tokens.shape[0]
    n_tensor_in = 32
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(rounds,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * n_tensor_in,
        out_specs=tuple(pl.BlockSpec(memory_space=pltpu.VMEM) for _ in range(6)),
        scratch_shapes=[
            pltpu.SMEM((B,), jnp.int32),
            pltpu.SMEM((B,), jnp.int32),
            pltpu.SMEM((B,), jnp.int32),
        ],
    )
    kwargs = {}
    if not interpret:
        # Donate both caches into their outputs (same contract as the
        # plain fused window; the seed copy keeps interpret mode correct).
        kwargs["input_output_aliases"] = {
            n_tensor_in - 4 + 6: 2, n_tensor_in - 3 + 6: 3,
            n_tensor_in - 2 + 6: 4, n_tensor_in - 1 + 6: 5,
        }
    _count_launch()
    return pl.pallas_call(
        functools.partial(
            _fused_spec_kernel,
            gamma=gamma,
            t_num_heads=t_num_heads, t_num_kv_heads=t_num_kv_heads,
            t_head_dim=t_head_dim,
            d_num_heads=d_num_heads, d_num_kv_heads=d_num_kv_heads,
            d_head_dim=d_head_dim,
            block_size=block_size,
            t_rms_eps=t_rms_eps, d_rms_eps=d_rms_eps,
            t_theta=t_theta, d_theta=d_theta,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((rounds, B, gamma + 1), jnp.int32),
            jax.ShapeDtypeStruct((rounds, B), jnp.int32),
            jax.ShapeDtypeStruct(k_t.shape, k_t.dtype),
            jax.ShapeDtypeStruct(v_t.shape, v_t.dtype),
            jax.ShapeDtypeStruct(k_d.shape, k_d.dtype),
            jax.ShapeDtypeStruct(v_d.shape, v_d.dtype),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
        **kwargs,
    )(
        tables_t.astype(jnp.int32),
        tables_d.astype(jnp.int32),
        positions.astype(jnp.int32),
        active.astype(jnp.int32),
        tokens.astype(jnp.int32),
        xprev.astype(jnp.int32),
        t_embed, t_head, t_fnorm, t_anorm, t_mnorm,
        t_wq, t_wk, t_wv, t_wo, t_wg, t_wu, t_wd,
        d_embed, d_head, d_fnorm, d_anorm, d_mnorm,
        d_wq, d_wk, d_wv, d_wo, d_wg, d_wu, d_wd,
        temps.astype(jnp.float32), top_ks.astype(jnp.int32),
        top_ps.astype(jnp.float32), uniforms.astype(jnp.float32),
        k_t, v_t, k_d, v_d,
    )
