"""Engine flight recorder: step histograms + XLA compile tracking.

The scheduler's step loop is where serving latency is actually spent, but
until now its only outputs were aggregate counters. The flight recorder
keeps a host-side, allocation-free account of every dispatch:

- **Step-duration histograms labelled by phase** (prefill / decode / mixed /
  wave / spec) with per-phase token counts — the per-step token throughput
  and the "where did this request's time go" denominator.
- **An XLA compile tracker.** Executables are keyed by their static shape
  tuple (the same keys ``Scheduler.warmup`` precompiles). Every dispatch
  registers its key; a key first seen *after* warmup completed means XLA
  compiled mid-traffic — PR 1's silent killer (decode executables compiling
  under load, measured as the dominant serving-plane latency) — and is
  counted and logged with its shape key so it alerts instead of hiding in
  p99.

Everything is plain Python ints/floats mutated from the step thread and
read from the event loop via ``to_stats()`` — last-write-wins races on a
scrape are acceptable for monitoring data, so no locks on the hot path.
"""

from __future__ import annotations

import bisect
import time
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)

# Step durations span sub-ms CPU mock steps to multi-second cold compiles.
STEP_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0
)

PHASES = ("prefill", "decode", "mixed", "wave", "spec")

# Host-gap buckets: the decode pipeline's subject is the SUB-millisecond
# window between a dispatch returning and the next dispatch being issued —
# far finer-grained than step durations. Overlapped steady state should sit
# in the lowest buckets; sync-path steps pay the full
# readback+bookkeeping+upload gap (ms to tens of ms on tunneled devices).
GAP_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25
)


class _PhaseHist:
    __slots__ = ("counts", "total", "sum_s", "tokens", "buckets")

    def __init__(self, buckets: Tuple[float, ...] = STEP_BUCKETS) -> None:
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0
        self.sum_s = 0.0
        self.tokens = 0

    def observe(self, dur_s: float, tokens: int) -> None:
        self.counts[bisect.bisect_left(self.buckets, dur_s)] += 1
        self.total += 1
        self.sum_s += dur_s
        self.tokens += tokens

    def percentile(self, q: float) -> float:
        """Approximate q-quantile (0..1) from the bucket counts: linear
        interpolation within the covering bucket, upper bound for +Inf."""
        if not self.total:
            return 0.0
        rank = q * self.total
        seen = 0
        lo = 0.0
        for i, c in enumerate(self.counts):
            hi = self.buckets[i] if i < len(self.buckets) else self.buckets[-1]
            if seen + c >= rank:
                if c == 0:
                    return hi
                frac = (rank - seen) / c
                return lo + frac * (hi - lo)
            seen += c
            lo = hi
        return self.buckets[-1]


# Peak hardware numbers for the live MFU / HBM-roofline gauges, keyed by a
# lowercase substring of jax's device_kind. Sources: published TPU specs
# (bf16 FLOPs, HBM bandwidth). CPU gets a nominal floor so the gauges stay
# defined (their absolute value is meaningless off-accelerator; the bench
# anchors are the real numbers).
_PEAKS: Tuple[Tuple[str, float, float], ...] = (
    ("v5e", 197e12, 819e9),
    ("v5p", 459e12, 2765e9),
    ("v5", 197e12, 819e9),
    ("v4", 275e12, 1228e9),
    ("v6", 918e12, 1640e9),
)
_CPU_PEAKS = (1e12, 100e9)


def detect_peaks() -> Tuple[float, float]:
    """(peak FLOPs/s, peak HBM bytes/s) for the local accelerator."""
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 — no backend is a valid state
        return _CPU_PEAKS
    for sub, flops, bw in _PEAKS:
        if sub in kind:
            return flops, bw
    return _CPU_PEAKS


class StepCostModel:
    """Per-step FLOPs + bytes model so BENCH roofline numbers become a live
    metric. Analytical, host-side only:

    - FLOPs ≈ 2 · params · tokens (the matmul-dominated transformer count;
      attention FLOPs are second-order at serving context lengths).
    - Bytes: decode/mixed steps stream the whole parameter set once per
      parameter pass plus the active KV they read; prefill writes its
      chunk's KV and re-reads the prefix.

    ``param_count``/``param_bytes`` come from the actual params pytree and
    ``kv_bytes_per_token`` from the actual cache arrays, so quantized
    deployments (int8 weights/KV) are modeled at their real byte widths.

    ``kv_read_factor`` models the attention path's traffic amplification
    over the true prefix bytes: the XLA width-bucketed gather materializes
    a packed copy (gather read + copy write + attend re-read ⇒ 3.0), while
    the paged Pallas paths — the opt-in r5 kernel and the ragged
    megakernel — stream each page HBM→VMEM exactly once (1.0). With the
    factor wrong the live ``hbm_frac_decode`` gauge would report the
    megakernel at a third of its real roofline fraction (or the gather at
    3× — either way, not the number BENCH_r* anchors).
    """

    __slots__ = ("param_count", "param_bytes", "kv_bytes_per_token",
                 "kv_read_factor", "peak_flops", "peak_bw",
                 "flops_per_token", "calibrated", "calibration_source")

    # XLA's own count must land within this band of the 2·params hand count
    # to be trusted: a wildly different number means the probe measured the
    # wrong executable (or cost_analysis returned transcendental-op noise),
    # and silently adopting it would skew every mfu_* gauge and the
    # measured-vs-modeled tolerance gate downstream.
    CALIBRATION_BAND = (0.2, 5.0)

    def __init__(self, param_count: int, param_bytes: int, kv_bytes_per_token: float,
                 peak_flops: Optional[float] = None, peak_bw: Optional[float] = None,
                 kv_read_factor: float = 1.0):
        self.param_count = max(int(param_count), 1)
        self.param_bytes = max(int(param_bytes), 1)
        self.kv_bytes_per_token = max(float(kv_bytes_per_token), 0.0)
        self.kv_read_factor = max(float(kv_read_factor), 0.0)
        if peak_flops is None or peak_bw is None:
            peak_flops, peak_bw = detect_peaks()
        self.peak_flops = peak_flops
        self.peak_bw = peak_bw
        # Hand-rolled default; Scheduler warmup replaces it with XLA's own
        # cost_analysis() count of the decode executable when available.
        self.flops_per_token = 2.0 * self.param_count
        self.calibrated = False
        self.calibration_source = "analytical"

    def calibrate(self, flops_per_token: float, source: str = "xla_cost_analysis") -> bool:
        """Adopt a measured FLOPs-per-token count (normally from
        ``jax.stages.Compiled.cost_analysis()``). Rejected outside the
        sanity band around the analytical count — returns whether adopted."""
        hand = 2.0 * self.param_count
        lo, hi = self.CALIBRATION_BAND
        if not (flops_per_token > 0 and lo * hand <= flops_per_token <= hi * hand):
            logger.warning(
                "rejecting cost_analysis calibration %.3g flops/token "
                "(analytical %.3g, accepted band [%.1fx, %.1fx])",
                flops_per_token, hand, lo, hi,
            )
            return False
        self.flops_per_token = float(flops_per_token)
        self.calibrated = True
        self.calibration_source = source
        return True

    def step_cost(
        self, tokens: int, kv_read_tokens: int, param_passes: float = 1.0
    ) -> Tuple[float, float]:
        """(flops, bytes) for one dispatch computing ``tokens`` token rows
        while reading ``kv_read_tokens`` of resident KV.

        ``param_passes``: how many times the dispatch streams the parameter
        set from HBM — 1 for single steps, ``num_steps`` for a
        ``decode_multi`` window (the fori_loop re-reads weights every
        step), and 1 again for the fused megakernel window (weights are
        VMEM-resident for the whole window; that is the launch-amortization
        win the gauge must show)."""
        flops = self.flops_per_token * tokens
        bytes_moved = (
            self.param_bytes * max(param_passes, 1.0)
            + kv_read_tokens * self.kv_bytes_per_token * self.kv_read_factor
            + tokens * self.kv_bytes_per_token  # written KV rows
        )
        return flops, bytes_moved

    def roofline_time(self, flops: float, bytes_moved: float) -> float:
        """Lower-bound seconds for (flops, bytes) on this chip — the
        max(compute, bandwidth) roofline. Used to split a mixed step's
        wall time between its phases."""
        return max(flops / self.peak_flops, bytes_moved / self.peak_bw)


class _PhaseRoofline:
    """Rolling (flops, bytes, seconds) account per phase: the live-gauge
    window. A bounded deque of recent steps, so a quiet engine's MFU decays
    to reflect recent traffic rather than all-time averages."""

    __slots__ = ("recent", "flops_total", "bytes_total", "secs_total")

    def __init__(self, maxlen: int = 256):
        self.recent: deque = deque(maxlen=maxlen)  # (flops, bytes, dur_s)
        self.flops_total = 0.0
        self.bytes_total = 0.0
        self.secs_total = 0.0

    def record(self, flops: float, bytes_moved: float, dur_s: float) -> None:
        self.recent.append((flops, bytes_moved, dur_s))
        self.flops_total += flops
        self.bytes_total += bytes_moved
        self.secs_total += dur_s

    def live(self, peak_flops: float, peak_bw: float) -> Tuple[float, float]:
        """(MFU, HBM-roofline fraction) over the recent-step window."""
        if not self.recent:
            return 0.0, 0.0
        f = sum(x for x, _, _ in self.recent)
        b = sum(x for _, x, _ in self.recent)
        t = sum(x for _, _, x in self.recent)
        if t <= 0:
            return 0.0, 0.0
        return f / t / peak_flops, b / t / peak_bw


class FlightRecorder:
    """Owned by one Scheduler; mutated on the step thread only."""

    def __init__(self, telemetry=None) -> None:
        self._hists: Dict[str, _PhaseHist] = {p: _PhaseHist() for p in PHASES}
        # Optional runtime.telemetry.Telemetry: record_step feeds per-phase
        # ``{phase}_step`` digests so step-duration percentiles merge
        # fleet-wide (the bucket histograms above stay for bench readers).
        self.telemetry = telemetry
        # Per-step FLOPs+bytes roofline account (set_cost_model); None keeps
        # record_step cost-free for schedulers that never attach one.
        self.cost_model: Optional[StepCostModel] = None
        self._roofline: Dict[str, _PhaseRoofline] = {}
        # Stall watchdog reference point + /debug/state step timeline.
        self.last_step_ts: Optional[float] = None
        self.recent_steps: deque = deque(maxlen=64)  # (ts, phase, dur_s, tokens)
        # Decode host gap: time from a decode dispatch RETURNING (device
        # launched, host free) to the NEXT decode dispatch being issued —
        # the bubble the overlap pipeline exists to close. Only consecutive
        # decode-family dispatches are measured (phase changes reset it).
        self._gap = _PhaseHist(GAP_BUCKETS)
        # Fused decode-window launch accounting: the number of pallas_call
        # sites traced into ONE fused-window executable (must be exactly 1 —
        # the whole point of the megakernel window is one launch per window;
        # CI asserts it) and how many fused windows have been dispatched.
        self.fused_window_pallas_launches: Optional[int] = None
        self.fused_windows_total = 0
        # In-kernel sampling + fused speculation: windows whose epilogue
        # sampled on-chip (uniforms operand), and whole draft+verify spec
        # windows with their accepted-token yield — the bench/Grafana
        # accepted-tokens-per-window signal.
        self.fused_sampled_windows_total = 0
        self.spec_fused_windows_total = 0
        self.spec_fused_accepted_tokens_total = 0
        # Compile tracker state.
        self._exec_keys: Set[tuple] = set()
        self.compiles_total = 0
        self.compiles_after_warmup_total = 0
        self.post_warmup_keys: List[tuple] = []
        self._warmup_done = False
        self._warmed = False  # did a real warmup() pass run before traffic?
        # Last-step snapshot (gauge-style, for quick introspection).
        self.last_step_phase: Optional[str] = None
        self.last_step_s = 0.0
        # Measured device truth (ContinuousProfiler windows). Written from
        # the profiler thread — distinct fields with a single writer, read
        # by the scrape; last-write-wins is fine for monitoring data.
        self.measured_windows_total = 0
        self.measured_device_seconds_total = 0.0
        self.measured_wall_seconds_total = 0.0
        self._measured_last: Optional[dict] = None

    # --- measured device truth ----------------------------------------------
    def roofline_totals(self) -> Tuple[float, float, float, int]:
        """Cumulative (flops, bytes, modeled step seconds, fused windows)
        across every phase — the ContinuousProfiler's cost probe. Deltas of
        this across a profile window attribute measured device time to the
        modeled work done in the same span."""
        f = b = s = 0.0
        for r in self._roofline.values():
            f += r.flops_total
            b += r.bytes_total
            s += r.secs_total
        return f, b, s, self.fused_windows_total

    def record_measured_window(self, record: dict) -> None:
        """Fold one profile window's measured truth into the recorder.

        ``record`` is the ContinuousProfiler's per-window dict (or a bench
        fixture shaped the same): wall_s, device_time_s, flops, bytes,
        step_seconds, top_kernels, top_kernel_share,
        launches_per_fused_window. Derived gauges:

        - ``measured_mfu`` / ``measured_hbm_frac``: modeled work ÷ MEASURED
          device-busy time ÷ peak — the measured sibling of ``mfu_*``.
        - ``measured_modeled_mfu_ratio``: modeled step seconds ÷ measured
          device seconds over the same span. 1.0 means the cost model's
          wall clock and the device's own account agree; the bench asserts
          a tolerance band on the fixture path.
        """
        device_s = max(float(record.get("device_time_s", 0.0)), 0.0)
        flops = max(float(record.get("flops", 0.0)), 0.0)
        bytes_moved = max(float(record.get("bytes", 0.0)), 0.0)
        step_s = max(float(record.get("step_seconds", 0.0)), 0.0)
        self.measured_windows_total += 1
        self.measured_device_seconds_total += device_s
        self.measured_wall_seconds_total += float(record.get("wall_s", 0.0))
        mfu = hbm = 0.0
        if self.cost_model is not None and device_s > 0:
            mfu = flops / device_s / self.cost_model.peak_flops
            hbm = bytes_moved / device_s / self.cost_model.peak_bw
        ratio = (step_s / device_s) if device_s > 0 else 0.0
        self._measured_last = {
            "measured_mfu": round(mfu, 6),
            "measured_hbm_frac": round(hbm, 6),
            "measured_device_frac": (
                round(device_s / float(record["wall_s"]), 6)
                if record.get("wall_s") else 0.0
            ),
            "measured_modeled_mfu_ratio": round(ratio, 6),
            "measured_top_kernel_share": round(
                float(record.get("top_kernel_share", 0.0)), 6
            ),
            "measured_launches_per_fused_window": (
                round(float(record["launches_per_fused_window"]), 6)
                if record.get("launches_per_fused_window") is not None else 0.0
            ),
            "top_kernels": record.get("top_kernels", []),
        }

    def measured_snapshot(self) -> Optional[dict]:
        """Last measured window's derived gauges + kernel top-N (bench and
        incident-bundle view); None before the first window."""
        last = self._measured_last
        return dict(last) if last else None

    # --- step accounting ----------------------------------------------------
    def set_cost_model(self, model: StepCostModel) -> None:
        """Attach the per-step FLOPs+bytes model: record_step then keeps a
        live per-phase MFU / HBM-roofline account."""
        self.cost_model = model

    def record_step(
        self, phase: str, dur_s: float, tokens: int, kv_read_tokens: int = 0,
        param_passes: float = 1.0,
    ) -> None:
        h = self._hists.get(phase)
        if h is None:
            h = self._hists.setdefault(phase, _PhaseHist())
        h.observe(dur_s, tokens)
        self.last_step_phase = phase
        self.last_step_s = dur_s
        self.last_step_ts = time.monotonic()
        self.recent_steps.append((self.last_step_ts, phase, round(dur_s, 6), tokens))
        if self.telemetry is not None:
            self.telemetry.observe(f"{phase}_step", dur_s)
        if self.cost_model is not None:
            flops, bytes_moved = self.cost_model.step_cost(
                tokens, kv_read_tokens, param_passes
            )
            self._record_roofline(phase, flops, bytes_moved, dur_s)

    def _record_roofline(
        self, phase: str, flops: float, bytes_moved: float, dur_s: float
    ) -> None:
        r = self._roofline.get(phase)
        if r is None:
            r = self._roofline.setdefault(phase, _PhaseRoofline())
        r.record(flops, bytes_moved, dur_s)

    def record_mixed_step(
        self,
        dur_s: float,
        prefill_tokens: int,
        decode_tokens: int,
        kv_read_prefill: int = 0,
        kv_read_decode: int = 0,
    ) -> None:
        """One MIXED prefill+decode dispatch. The step histogram stays under
        the "mixed" phase (steps/time/tokens counters unchanged), but the
        FLOPs/bytes roofline account is SPLIT into the prefill and decode
        buckets: when the fused kernel serves both phases in one launch,
        charging everything to "mixed" would starve ``mfu_prefill`` and
        ``hbm_frac_decode`` of exactly the traffic mixed steps carry —
        under heavy mixed batching those gauges would decay to zero while
        the engine is at peak. Wall time is apportioned by each phase's
        roofline-time share (prefill chunks are FLOPs-bound, decode rows
        bytes-bound, so a 50/50 token split is NOT a 50/50 time split)."""
        h = self._hists["mixed"]
        h.observe(dur_s, prefill_tokens + decode_tokens)
        self.last_step_phase = "mixed"
        self.last_step_s = dur_s
        self.last_step_ts = time.monotonic()
        self.recent_steps.append(
            (self.last_step_ts, "mixed", round(dur_s, 6), prefill_tokens + decode_tokens)
        )
        if self.telemetry is not None:
            self.telemetry.observe("mixed_step", dur_s)
        if self.cost_model is None:
            return
        # The parameter stream is shared by both phases in one dispatch —
        # attribute it to the decode rows (a mixed step exists because the
        # decode batch was running anyway; the chunk rides for free).
        f_p, b_p = self.cost_model.step_cost(prefill_tokens, kv_read_prefill, 0.0)
        f_d, b_d = self.cost_model.step_cost(decode_tokens, kv_read_decode, 1.0)
        t_p = self.cost_model.roofline_time(f_p, b_p)
        t_d = self.cost_model.roofline_time(f_d, b_d)
        share_p = t_p / (t_p + t_d) if (t_p + t_d) > 0 else 0.5
        if prefill_tokens > 0:
            self._record_roofline("prefill", f_p, b_p, dur_s * share_p)
        if decode_tokens > 0:
            self._record_roofline("decode", f_d, b_d, dur_s * (1.0 - share_p))

    def record_window_launches(self, n: int) -> None:
        """Pallas launch sites traced into one fused decode-window
        executable (megakernel.trace_launch_count delta across its first
        trace). Exported as the ``fused_window_pallas_launches`` gauge; CI
        asserts == 1 so dispatch-amortization regressions — someone
        un-fusing the window back into per-step or per-piece kernels —
        fail loudly instead of silently re-losing to overhead."""
        self.fused_window_pallas_launches = int(n)

    def utilization(self) -> Dict[str, Tuple[float, float]]:
        """{phase: (mfu, hbm_roofline_fraction)} over the recent-step
        window; empty without a cost model."""
        if self.cost_model is None:
            return {}
        return {
            phase: r.live(self.cost_model.peak_flops, self.cost_model.peak_bw)
            for phase, r in self._roofline.items()
        }

    def record_host_gap(self, gap_s: float) -> None:
        """One dispatch-return → next-dispatch interval on the decode path."""
        self._gap.observe(gap_s, 0)

    def gap_percentile(self, q: float) -> float:
        """Approximate decode-host-gap quantile in SECONDS (bench reporting)."""
        return self._gap.percentile(q)

    # --- compile tracking ---------------------------------------------------
    def record_exec(self, kind: str, key: tuple) -> bool:
        """Register a dispatch's executable shape key. Returns True when the
        key is new (== XLA compiled for it). New keys after warmup are the
        alert condition."""
        k = (kind,) + tuple(key)
        if k in self._exec_keys:
            return False
        self._exec_keys.add(k)
        self.compiles_total += 1
        if self._warmup_done:
            self.compiles_after_warmup_total += 1
            self.post_warmup_keys.append(k)
            # A warmed engine compiling mid-traffic is a coverage bug worth
            # alerting on; an engine that skipped warmup compiles lazily by
            # design — record it, but don't cry wolf.
            log = logger.warning if self._warmed else logger.debug
            log("XLA compile after warmup: %s %s (post-warmup compiles: %d)",
                kind, key, self.compiles_after_warmup_total)
        return True

    def mark_warmup_done(self, warmed: bool) -> None:
        """Called once traffic may start. ``warmed`` = a warmup() pass
        actually precompiled the serving set (compiles after this point are
        unexpected); False = lazy compilation is expected but still
        counted."""
        self._warmup_done = True
        self._warmed = warmed

    def exec_key_summary(self) -> Dict[str, List[int]]:
        """{kind: sorted key arities} of every executable key registered so
        far — the dynamic twin of dtlint's ``static_warmup_report()``.
        bench.py diffs the two so the static warmup enumeration and the
        recorder's observed compile keys cannot drift apart."""
        out: Dict[str, Set[int]] = {}
        for k in self._exec_keys:
            out.setdefault(k[0], set()).add(len(k) - 1)
        return {kind: sorted(v) for kind, v in sorted(out.items())}

    # --- export -------------------------------------------------------------
    def to_stats(self) -> dict:
        """Flat dict merged into the worker stats scrape (monotonic keys end
        in ``_total`` so the aggregator exports them as Counters)."""
        out: dict = {
            "compiles_total": self.compiles_total,
            "compiles_after_warmup_total": self.compiles_after_warmup_total,
            # Host-gap histogram exported as sum+count counters: PromQL
            # rate(sum)/rate(count) is the live average gap; bench reads
            # the full bucket histogram host-side for p50/p99.
            "decode_host_gap_events_total": self._gap.total,
            "decode_host_gap_seconds_total": round(self._gap.sum_s, 6),
        }
        if self.fused_windows_total or self.fused_window_pallas_launches is not None:
            out["fused_windows_total"] = self.fused_windows_total
            out["fused_sampled_windows_total"] = self.fused_sampled_windows_total
            out["fused_window_pallas_launches"] = (
                self.fused_window_pallas_launches
                if self.fused_window_pallas_launches is not None else 0
            )
        if self.spec_fused_windows_total:
            out["spec_fused_windows_total"] = self.spec_fused_windows_total
            out["spec_fused_accepted_tokens_total"] = (
                self.spec_fused_accepted_tokens_total
            )
        for phase, h in self._hists.items():
            if not h.total and phase not in ("prefill", "decode", "mixed"):
                continue  # wave/spec only when the path is exercised
            out[f"step_{phase}_steps_total"] = h.total
            out[f"step_{phase}_time_seconds_total"] = round(h.sum_s, 6)
            out[f"step_{phase}_tokens_total"] = h.tokens
        if self.cost_model is not None:
            for phase, r in self._roofline.items():
                out[f"step_{phase}_flops_total"] = round(r.flops_total, 1)
                out[f"step_{phase}_bytes_total"] = round(r.bytes_total, 1)
                mfu, hbm = r.live(self.cost_model.peak_flops, self.cost_model.peak_bw)
                out[f"mfu_{phase}"] = round(mfu, 6)
                out[f"hbm_frac_{phase}"] = round(hbm, 6)
            out["cost_model_calibrated"] = 1.0 if self.cost_model.calibrated else 0.0
        if self.measured_windows_total:
            out["measured_windows_total"] = self.measured_windows_total
            out["measured_device_seconds_total"] = round(
                self.measured_device_seconds_total, 6
            )
            out["measured_wall_seconds_total"] = round(
                self.measured_wall_seconds_total, 6
            )
            last = self._measured_last or {}
            for key in (
                "measured_mfu", "measured_hbm_frac", "measured_device_frac",
                "measured_modeled_mfu_ratio", "measured_top_kernel_share",
                "measured_launches_per_fused_window",
            ):
                out[key] = last.get(key, 0.0)
        return out

    def histogram(self, phase: str) -> Tuple[Tuple[float, ...], List[int]]:
        """(bucket upper bounds, counts incl. +Inf) for one phase; the
        ``"host_gap"`` pseudo-phase returns the decode host-gap histogram."""
        h = self._gap if phase == "host_gap" else self._hists[phase]
        return h.buckets, list(h.counts)

    def ring_snapshot(self) -> dict:
        """Incident-bundle view of the flight recorder: the recent-step
        ring verbatim plus the host-gap and compile evidence — enough for
        ``tools/autopsy.py`` to reconstruct "what the engine was doing in
        the seconds before the trigger" without the live process."""
        now = time.monotonic()
        return {
            "recent_steps": [
                {"age_s": round(now - ts, 3), "phase": ph, "dur_s": d, "tokens": t}
                for ts, ph, d, t in list(self.recent_steps)
            ],
            "last_step_phase": self.last_step_phase,
            "last_step_age_s": (
                round(now - self.last_step_ts, 3) if self.last_step_ts is not None else None
            ),
            "host_gap": {
                "events": self._gap.total,
                "sum_s": round(self._gap.sum_s, 6),
                "p50_s": round(self._gap.percentile(0.5), 6),
                "p99_s": round(self._gap.percentile(0.99), 6),
            },
            "compiles_total": self.compiles_total,
            "compiles_after_warmup_total": self.compiles_after_warmup_total,
            "post_warmup_keys": [str(k) for k in self.post_warmup_keys[-16:]],
        }


class StepTimer:
    """Tiny context helper: ``with StepTimer() as t: ...; flight.record_step
    (phase, t.dur, n)`` without try/finally noise at each dispatch site."""

    __slots__ = ("t0", "dur")

    def __enter__(self) -> "StepTimer":
        self.t0 = time.perf_counter()
        self.dur = 0.0
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.dur = time.perf_counter() - self.t0
