"""Speculative decoding: draft-model proposal + one-pass target verification.

The reference surfaces engine-side speculation through SpecDecodeStats
(lib/bindings/python _core.pyi:354-427 ForwardPassMetrics); the engines do
the speculating. Here it is native: a small draft model proposes ``gamma``
tokens greedily, the target scores all of them in ONE batched forward
(``prefill(..., all_logits=True)`` — MXU-friendly: the verify pass turns γ
sequential decode steps into one γ-token matmul pass), and the longest
agreeing prefix is accepted plus one bonus/correction token from the target
distribution.

Greedy acceptance (temperature 0): accepted_i ⇔ draft_i == target_argmax_i.
Per round the target advances by k+1 tokens (k accepted + bonus) for one
target forward — the speedup when draft agreement is high.

Cache bookkeeping: proposals are written into both paged caches as they are
produced; rejected slots hold stale rows but are position-masked until the
real token at that position overwrites them (write-before-attend, monotone
positions), so no rollback pass is needed.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.scheduler import next_bucket


@dataclass
class SpecDecodeStats:
    """Ref: _core.pyi SpecDecodeStats — acceptance accounting."""

    num_spec_tokens: int = 0  # total proposed
    num_accepted_tokens: int = 0
    num_draft_tokens: int = 0
    num_rounds: int = 0  # batch rounds (one per spec dispatch)
    num_seq_rounds: int = 0  # per-row rounds (one per record_round call)
    # Per-position acceptance counts (how often position i of a proposal run
    # was accepted) — the reference exposes the same shape.
    accepted_per_position: List[int] = field(default_factory=list)

    @property
    def acceptance_rate(self) -> float:
        """Accepted/proposed ratio; 0.0 (never NaN) for γ=0 rounds or a
        zero-round history — the bench summary divides nothing by zero."""
        return self.num_accepted_tokens / self.num_draft_tokens if self.num_draft_tokens else 0.0

    @property
    def accepted_per_round(self) -> float:
        """Mean tokens CONFIRMED per row-round including the correction/
        bonus (the ≥2-accepted-tokens-per-step acceptance criterion reads
        this); 0.0 (never NaN) for γ=0 or an empty history."""
        if not self.num_seq_rounds:
            return 0.0
        return (self.num_accepted_tokens + self.num_seq_rounds) / self.num_seq_rounds

    def record_round(self, accepted: int, gamma: int) -> None:
        """Account one speculative round: γ proposed, ``accepted`` agreed."""
        self.num_draft_tokens += gamma
        self.num_spec_tokens += gamma
        self.num_accepted_tokens += accepted
        self.num_seq_rounds += 1
        while len(self.accepted_per_position) < gamma:
            self.accepted_per_position.append(0)
        for i in range(accepted):
            self.accepted_per_position[i] += 1

    def to_dict(self) -> dict:
        return {
            "num_spec_tokens": self.num_spec_tokens,
            "num_accepted_tokens": self.num_accepted_tokens,
            "num_draft_tokens": self.num_draft_tokens,
            "num_rounds": self.num_rounds,
            "acceptance_rate": round(self.acceptance_rate, 4),
            "accepted_per_round": round(self.accepted_per_round, 4),
            "accepted_per_position": self.accepted_per_position,
        }


class SpecDecoder:
    """Greedy speculative generation over two llama-family models sharing a
    tokenizer/vocab. Self-contained paged caches (not the serving scheduler's
    pool) — the serving integration point is one sequence at a time."""

    def __init__(
        self,
        target_config: ModelConfig,
        target_params,
        draft_config: ModelConfig,
        draft_params,
        *,
        gamma: int = 4,
        dtype=jnp.float32,
    ):
        if target_config.block_size != draft_config.block_size:
            raise ValueError("target and draft must share block_size")
        if target_config.vocab_size != draft_config.vocab_size:
            raise ValueError("target and draft must share the vocabulary")
        self.tc, self.dc = target_config, draft_config
        self.tp, self.dp = target_params, draft_params
        self.gamma = gamma
        self.dtype = dtype

        self._t_prefill = jax.jit(
            lambda p, k, v, t, vl, cl, bt: llama.prefill(p, self.tc, k, v, t, vl, cl, bt),
            donate_argnums=(1, 2),
        )
        self._t_verify = jax.jit(
            lambda p, k, v, t, vl, cl, bt: llama.prefill(p, self.tc, k, v, t, vl, cl, bt, all_logits=True),
            donate_argnums=(1, 2),
        )
        self._d_prefill = jax.jit(
            lambda p, k, v, t, vl, cl, bt: llama.prefill(p, self.dc, k, v, t, vl, cl, bt),
            donate_argnums=(1, 2),
        )
        self._d_decode = jax.jit(
            lambda p, k, v, t, pos, bt, act: llama.decode(p, self.dc, k, v, t, pos, bt, act),
            donate_argnums=(1, 2),
        )

    def generate(
        self,
        prompt: List[int],
        max_tokens: int,
        *,
        eos_token_ids: Optional[List[int]] = None,
        stats: Optional[SpecDecodeStats] = None,
    ) -> List[int]:
        """Greedy generation; returns generated token ids (≤ max_tokens)."""
        eos = set(eos_token_ids or [])
        total_len = len(prompt) + max_tokens + self.gamma + 2
        bs = self.tc.block_size
        n_blocks = (total_len + bs - 1) // bs
        table = jnp.arange(1, 1 + n_blocks, dtype=jnp.int32)
        t_cache = KvCacheArrays.create(self.tc, n_blocks + 1, dtype=self.dtype)
        d_cache = KvCacheArrays.create(self.dc, n_blocks + 1, dtype=self.dtype)

        buckets = [32, 64, 128, 256, 512, 1024, 2048]
        T = len(prompt)
        bucket = next_bucket(T, buckets)
        padded = jnp.zeros((bucket,), dtype=jnp.int32).at[:T].set(jnp.asarray(prompt, dtype=jnp.int32))

        t_logits, t_cache.k, t_cache.v = self._t_prefill(
            self.tp, t_cache.k, t_cache.v, padded, jnp.int32(T), jnp.int32(0), table
        )
        _, d_cache.k, d_cache.v = self._d_prefill(
            self.dp, d_cache.k, d_cache.v, padded, jnp.int32(T), jnp.int32(0), table
        )

        out: List[int] = [int(jnp.argmax(t_logits))]  # first target token
        n = T  # tokens materialized in the target cache
        d_n = T  # tokens materialized in the draft cache (may lag n)
        verify_bucket = 1 << (self.gamma + 1 - 1).bit_length()

        while len(out) < max_tokens and out[-1] not in eos:
            b = out[-1]  # last confirmed token, not yet in either cache
            # --- draft catches up on confirmed tokens it hasn't consumed,
            # then proposes gamma tokens (sequential small decodes).
            # Confirmed token at position T+i is out[i]; the catch-up feeds
            # positions d_n..n (the last one is b) so the draft cache is
            # coherent with the target's accepted prefix before proposing.
            proposals: List[int] = []
            logits = None
            for pos in range(d_n, n + 1):
                logits, d_cache.k, d_cache.v = self._d_decode(
                    self.dp, d_cache.k, d_cache.v,
                    jnp.asarray([out[pos - T]], dtype=jnp.int32),
                    jnp.asarray([pos], dtype=jnp.int32),
                    table[None, :],
                    jnp.ones((1,), dtype=bool),
                )
            tok = int(jnp.argmax(logits[0]))
            proposals.append(tok)
            pos = n + 1
            for _ in range(self.gamma - 1):
                logits, d_cache.k, d_cache.v = self._d_decode(
                    self.dp, d_cache.k, d_cache.v,
                    jnp.asarray([tok], dtype=jnp.int32),
                    jnp.asarray([pos], dtype=jnp.int32),
                    table[None, :],
                    jnp.ones((1,), dtype=bool),
                )
                tok = int(jnp.argmax(logits[0]))
                proposals.append(tok)
                pos += 1

            # --- target verifies [b, x1..xγ] in one pass -------------------
            chunk = [b] + proposals
            padded_c = jnp.zeros((verify_bucket,), dtype=jnp.int32).at[: len(chunk)].set(
                jnp.asarray(chunk, dtype=jnp.int32)
            )
            logits_all, t_cache.k, t_cache.v = self._t_verify(
                self.tp, t_cache.k, t_cache.v, padded_c, jnp.int32(len(chunk)), jnp.int32(n), table
            )
            preds = np.asarray(jnp.argmax(logits_all[: len(chunk)], axis=-1))
            # preds[i] = target's token after consuming chunk[:i+1].
            k = 0
            while k < self.gamma and proposals[k] == int(preds[k]):
                k += 1
            accepted = proposals[:k]
            bonus = int(preds[k])  # correction (k<γ) or extension (k==γ)

            if stats is not None:
                stats.num_rounds += 1
                stats.record_round(k, self.gamma)

            # Emit accepted + bonus, honoring eos/max_tokens.
            for t in accepted:
                out.append(t)
                if len(out) >= max_tokens or t in eos:
                    return out[:max_tokens]
            out.append(bonus)
            old_n = n
            n += 1 + k  # b plus accepted proposals are now target-cache-valid
            # Draft consumed b + proposals[:γ-1] this round; only the
            # confirmed prefix (b + accepted[:min(k,γ-1)]) is coherent —
            # stale rows beyond it get overwritten by the next catch-up
            # before they are attended to. Absolute, not incremental: the
            # catch-up loop re-materialized everything through old_n.
            d_n = old_n + 1 + min(k, self.gamma - 1)
        return out[:max_tokens]


# ---------------------------------------------------------------------------
# Sampled (rejection-sampling) verification — Leviathan et al. speculative
# sampling, generalized to mixed greedy/sampled batches. The output
# distribution provably equals sampling from the target alone.
# ---------------------------------------------------------------------------


def _filtered_probs(logits, temps, top_ks, top_ps):
    """Row-wise sampling distribution: temperature scale + top-k/top-p
    truncation + softmax. logits [B, S, V]; params [B] → probs [B, S, V].
    Greedy rows (temp 0) return a one-hot argmax distribution.

    This is sampling.filtered_probs_rows — THE reference distribution the
    host sampler, the fused window's in-kernel epilogue, and the fused spec
    kernel all share — broadcast over the chunk axis, so the draft's
    proposal distribution and this verifier's p_d agree bit-exactly (a
    divergence would bias the rejection-sampled output distribution).
    Cost note: this is the full-vocab-sort path (~ms at 128k vocab); a
    windowed variant like sample_batch's SAMPLE_WINDOW fast path is a
    known optimization once spec rounds show up in serving profiles."""
    from dynamo_tpu.engine.sampling import filtered_probs_rows

    B, S, V = logits.shape
    flat = filtered_probs_rows(
        logits.reshape(B * S, V), jnp.repeat(temps, S),
        jnp.repeat(top_ks, S), jnp.repeat(top_ps, S),
    )
    return flat.reshape(B, S, V)


def spec_verify(
    draft_logits: jax.Array,  # [B, G, V] — draft dist at each proposal position
    target_logits: jax.Array,  # [B, G+1, V] — target dist at those + bonus position
    proposals: jax.Array,  # [B, G] i32
    temps: jax.Array,  # [B] f32 (0 = greedy row)
    top_ks: jax.Array,  # [B] i32
    top_ps: jax.Array,  # [B] f32
    key: jax.Array,
):
    """Batched speculative verification → (accepted [B] i32, next_token [B]).

    Sampled rows: accept proposal i with prob min(1, p_t(x_i)/p_d(x_i));
    on first rejection sample the correction from norm(max(p_t − p_d, 0));
    if all γ accepted, sample the bonus from the target's γ+1-th dist.
    Greedy rows reduce to argmax agreement + argmax bonus (the one-hot
    distributions make the same formulas exact). Ref surface:
    SpecDecodeStats (_core.pyi:354-427); algorithm: speculative sampling.
    """
    B, G, V = draft_logits.shape
    pd = _filtered_probs(draft_logits, temps, top_ks, top_ps)  # [B, G, V]
    pt = _filtered_probs(target_logits[:, :G], temps, top_ks, top_ps)  # [B, G, V]
    pt_x = jnp.take_along_axis(pt, proposals[..., None], axis=-1)[..., 0]  # [B, G]
    pd_x = jnp.take_along_axis(pd, proposals[..., None], axis=-1)[..., 0]
    key_u, key_resid, key_bonus = jax.random.split(key, 3)
    u = jax.random.uniform(key_u, (B, G))
    ratio = pt_x / jnp.maximum(pd_x, 1e-20)
    accept = u < jnp.minimum(ratio, 1.0)  # [B, G]
    # First rejection index; G if none.
    rejected = ~accept
    first_rej = jnp.where(
        jnp.any(rejected, axis=1), jnp.argmax(rejected, axis=1), G
    ).astype(jnp.int32)  # [B]

    # Correction token at the first rejected position: norm(max(pt-pd, 0)).
    idx = jnp.clip(first_rej, 0, G - 1)
    pt_k = jnp.take_along_axis(pt, idx[:, None, None], axis=1)[:, 0]  # [B, V]
    pd_k = jnp.take_along_axis(pd, idx[:, None, None], axis=1)[:, 0]
    resid = jnp.maximum(pt_k - pd_k, 0.0)
    resid_sum = jnp.sum(resid, axis=-1, keepdims=True)
    # Degenerate residual (identical dists): fall back to pt_k.
    resid = jnp.where(resid_sum > 1e-20, resid / jnp.maximum(resid_sum, 1e-20), pt_k)
    corr = jax.random.categorical(key_resid, jnp.log(jnp.maximum(resid, 1e-30)), axis=-1)

    # Bonus token when everything accepted: target's G+1-th distribution.
    pt_bonus = _filtered_probs(target_logits[:, G:], temps, top_ks, top_ps)[:, 0]  # [B, V]
    bonus = jax.random.categorical(key_bonus, jnp.log(jnp.maximum(pt_bonus, 1e-30)), axis=-1)

    next_token = jnp.where(first_rej == G, bonus, corr).astype(jnp.int32)
    return first_rej, next_token
