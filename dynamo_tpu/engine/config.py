"""Model architecture configuration + presets.

The reference carries per-model config in the ModelDeploymentCard
(lib/llm/src/model_card.rs:91 — tokenizer, context length, kv block size);
engine-side architecture lives in the engines themselves. Here both meet:
:class:`ModelConfig` is the engine-side architecture record the MDC points at.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    intermediate_size: int
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    max_seq_len: int = 8192
    # Paged KV cache block size in tokens (ref default: 64 in MDC, vLLM
    # uses 16). Measured on v5e at 1B/b32/ctx1024 with the gather path and
    # equal gathered bytes: bs=16 7.9 ms/step, bs=64 8.3, bs=256 9.8 — XLA
    # gathers 16-token rows at full efficiency, so bigger pages only add
    # fragmentation. Revisit if the Pallas paged kernel (attention_impl=
    # "paged") becomes the default — it wants ≥128-token pages.
    block_size: int = 16
    tie_word_embeddings: bool = False
    dtype: str = "bfloat16"
    # MoE (0 experts = dense).
    num_experts: int = 0
    num_experts_per_tok: int = 0
    # MoE dispatch strategy (ref exposes wide-EP only as engine config,
    # components/backends/trtllm/utils/trtllm_utils.py:37-39; here it is a
    # native engine concern):
    # - "dense":    every expert computes every token (exact, tiny models).
    # - "ragged":   grouped GEMM via lax.ragged_dot — exact (no token drops),
    #               per-token FLOPs scale with top-k K, not E. Single-shard /
    #               tp-sharded meshes.
    # - "capacity": GShard-style capacity-factor dispatch/combine einsums —
    #               GSPMD partitions experts over the ``ep`` mesh axis; tokens
    #               beyond an expert's capacity fall back to their residual.
    # - "auto":     "ragged"; the engine resolves to "capacity" when ep > 1.
    moe_dispatch: str = "auto"
    # Per-expert slot budget for "capacity" dispatch, as a multiple of the
    # balanced load T*K/E. 2.0 absorbs typical routing imbalance.
    moe_capacity_factor: float = 2.0
    # Architecture family: "llama" (GQA) or "mla" (DeepSeek-style multi-head
    # latent attention — compressed KV latent cache).
    architecture: str = "llama"
    # MLA dims (ignored for llama): per-head nope/rope query dims, value dim,
    # and the shared latent rank. Cache row = kv_lora_rank + qk_rope_head_dim.
    kv_lora_rank: int = 0
    qk_nope_head_dim: int = 0
    qk_rope_head_dim: int = 0
    v_head_dim: int = 0
    # Attention implementation for the paged-prefix piece — in plain
    # decode steps, the decode rows AND chunk rows of MIXED prefill+decode
    # steps, and prefill chunks (llama.mixed_step / prefill /
    # decode_layer_scan):
    # - "gather": XLA width-bucketed gather, two-piece online-softmax
    #   merge, once-per-window hoist (decode_multi). The CPU/debug
    #   baseline, and the off-TPU resolution of "auto".
    # - "megakernel": the ragged paged-attention megakernel
    #   (attention/megakernel.py) — ONE pallas_call per layer serves the
    #   whole step's ragged batch ((start, len) chunk rows + length-1
    #   decode rows share one grid), scalar-prefetched block tables,
    #   block-diagonal GQA fold, pl.when-skipped dead slots, and an int8-KV
    #   dequant-in-VMEM path. Amortizes the dispatch overhead that killed
    #   the r4/r5 per-piece kernels: 1 launch/layer/step regardless of
    #   batch composition (vs 2+ for chunk+decode kernels), and
    #   decode_multi_fused collapses a whole greedy decode window into ONE
    #   launch (grid = steps × layers, on-chip token feedback) where the
    #   working set fits VMEM (megakernel.fused_window_fits).
    # - "paged": the r5 per-piece Pallas paged flash-decode kernel
    #   (attention/decode.py) — correct (interpret-mode parity tests) but
    #   NEVER auto-selected: on tunneled runtimes every pallas_call
    #   execution carries ms-scale dispatch overhead (a no-op kernel
    #   inside a jitted loop measures 1.3-5 ms/call; 16 per-layer
    #   calls/step is fatal), so it lost every serving regime to the
    #   gather end-to-end regardless of its memory-traffic win. No int8
    #   path — int8 caches degrade to gather with a logged warning
    #   (llama.resolve_attention_impl).
    # - "auto": "megakernel" on TPU, "gather" elsewhere (interpreted
    #   Pallas is test-only). Measured record: decode at b32 sat at ~54%
    #   of HBM roofline on the gather (BENCH_r05 — the gather's
    #   read + packed-copy write + attend re-read is 3× the true KV
    #   bytes); the megakernel streams each page HBM→VMEM exactly once
    #   per launch and pays dispatch once per layer, not per piece. Track
    #   via bench.py's `decode_attention` section (tok/s,
    #   pct_hbm_roofline, per-launch dispatch overhead, gather vs
    #   megakernel at b∈{8,32}).
    attention_impl: str = "auto"
    # Prefill chunk attention — for phase-separated prefills AND the
    # ragged chunk rows of mixed steps (attention/ragged.py): "auto" =
    # Pallas flash kernel on TPU (attention/prefill.py — 40.8 TFLOP/s
    # causal vs ~2 for the two-piece XLA path at 1B shapes on v5e), XLA
    # path elsewhere; "flash"/"xla" force one ("flash" off-TPU runs the
    # kernel interpreted — tests only).
    prefill_impl: str = "auto"
    # KV cache storage dtype: "auto" follows the compute dtype; "int8" stores
    # quantized KV (per-token-per-head symmetric scale) — halves KV memory,
    # i.e. double the block capacity per HBM byte (longer contexts, bigger
    # batches before preemption). Decode latency is NOT improved on current
    # XLA:TPU (the int8 gather widens bytes internally — measured).
    # Covers llama KV and MLA latent rows (per-token scale over the latent).
    # Ref role: the engines' --kv-cache-dtype fp8 levers.
    kv_cache_dtype: str = "auto"
    # Weight storage dtype: "int8" stores dense layer matmul weights as
    # int8 + per-output-channel scale, dequantized one layer at a time in
    # the scan (engine/quant.py) — ~2× model capacity per HBM byte.
    # Measured necessity: Llama-3-8B bf16 is 15.0 GiB of weights and OOMs
    # a 16 GiB v5e before the first decode step; int8 weights serve it.
    # Embed/lm_head stay in compute dtype (per-step re-dequant of a
    # vocab-size matrix would add ~1 GB/token of traffic at 8B).
    weight_dtype: str = "auto"

    def __post_init__(self):
        if self.attention_impl not in ("auto", "gather", "paged", "megakernel"):
            raise ValueError(
                "attention_impl must be auto|gather|paged|megakernel, "
                f"got {self.attention_impl!r}"
            )
        # attention_impl='paged' + int8 KV no longer raises: the paged
        # kernel has no int8 path, so the engine degrades that combination
        # to the gather with a logged warning (llama.resolve_attention_impl)
        # — the megakernel is the int8-capable fused path.
        if self.prefill_impl not in ("auto", "flash", "xla"):
            raise ValueError(f"prefill_impl must be auto|flash|xla, got {self.prefill_impl!r}")
        if self.moe_dispatch not in ("auto", "dense", "ragged", "capacity"):
            raise ValueError(
                f"moe_dispatch must be auto|dense|ragged|capacity, got {self.moe_dispatch!r}"
            )
        if self.kv_cache_dtype not in ("auto", "int8"):
            raise ValueError(f"kv_cache_dtype must be auto|int8, got {self.kv_cache_dtype!r}")
        if self.weight_dtype not in ("auto", "int8"):
            raise ValueError(f"weight_dtype must be auto|int8, got {self.weight_dtype!r}")
        if self.weight_dtype == "int8" and self.architecture != "llama":
            raise ValueError(
                "weight_dtype='int8' is llama-family only (MLA layer scans "
                "do not dequantize yet)"
            )
        if self.weight_dtype == "int8" and self.num_experts > 0:
            raise ValueError(
                "weight_dtype='int8' does not cover MoE expert stacks "
                "(ragged/capacity dispatch would re-dequantize per expert)"
            )

    @property
    def q_size(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_size(self) -> int:
        return self.num_kv_heads * self.head_dim

    def replace(self, **kwargs) -> "ModelConfig":
        return dataclasses.replace(self, **kwargs)


PRESETS = {
    # Tiny config for unit tests: fast on a single CPU core.
    "tiny": ModelConfig(
        name="tiny",
        vocab_size=256,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        intermediate_size=128,
        max_seq_len=256,
        block_size=16,
        rope_theta=10000.0,
    ),
    # Tiny MoE config for EP tests.
    "tiny-moe": ModelConfig(
        name="tiny-moe",
        vocab_size=256,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        head_dim=16,
        intermediate_size=64,
        max_seq_len=256,
        block_size=16,
        rope_theta=10000.0,
        num_experts=4,
        num_experts_per_tok=2,
    ),
    "mixtral-8x7b": ModelConfig(
        name="mixtral-8x7b",
        vocab_size=32000,
        hidden_size=4096,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        intermediate_size=14336,
        rope_theta=1000000.0,
        max_seq_len=32768,
        num_experts=8,
        num_experts_per_tok=2,
    ),
    # Wide-EP MoE decode target (ref recipe: recipes/gpt-oss-120b) —
    # architecture approximated from public specs.
    "gpt-oss-120b": ModelConfig(
        name="gpt-oss-120b",
        vocab_size=201088,
        hidden_size=2880,
        num_layers=36,
        num_heads=64,
        num_kv_heads=8,
        head_dim=64,
        intermediate_size=2880,
        max_seq_len=131072,
        num_experts=128,
        num_experts_per_tok=4,
    ),
    # Tiny MLA config (DeepSeek-style latent attention) for unit tests.
    "tiny-mla": ModelConfig(
        name="tiny-mla",
        vocab_size=256,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=1,
        head_dim=16,
        intermediate_size=128,
        max_seq_len=256,
        block_size=16,
        rope_theta=10000.0,
        architecture="mla",
        kv_lora_rank=32,
        qk_nope_head_dim=16,
        qk_rope_head_dim=8,
        v_head_dim=16,
    ),
    # DeepSeek-V2-Lite (public specs): MLA + 64-expert MoE.
    "deepseek-v2-lite": ModelConfig(
        name="deepseek-v2-lite",
        vocab_size=102400,
        hidden_size=2048,
        num_layers=27,
        num_heads=16,
        num_kv_heads=1,
        head_dim=128,
        intermediate_size=1408,
        max_seq_len=32768,
        rope_theta=10000.0,
        architecture="mla",
        kv_lora_rank=512,
        qk_nope_head_dim=128,
        qk_rope_head_dim=64,
        v_head_dim=128,
        num_experts=64,
        num_experts_per_tok=6,
    ),
    # DeepSeek-V3 / R1 (public specs): the wide-EP MLA decode target
    # (ref recipe: components/backends/sglang slurm_jobs DeepSeek-R1).
    "deepseek-v3": ModelConfig(
        name="deepseek-v3",
        vocab_size=129280,
        hidden_size=7168,
        num_layers=61,
        num_heads=128,
        num_kv_heads=1,
        head_dim=128,
        intermediate_size=2048,
        max_seq_len=131072,
        rope_theta=10000.0,
        architecture="mla",
        kv_lora_rank=512,
        qk_nope_head_dim=128,
        qk_rope_head_dim=64,
        v_head_dim=128,
        num_experts=256,
        num_experts_per_tok=8,
    ),
    # Llama-architecture aliases with their own dims.
    "qwen2.5-7b": ModelConfig(
        name="qwen2.5-7b",
        vocab_size=152064,
        hidden_size=3584,
        num_layers=28,
        num_heads=28,
        num_kv_heads=4,
        head_dim=128,
        intermediate_size=18944,
        rope_theta=1000000.0,
        max_seq_len=32768,
    ),
    "mistral-7b": ModelConfig(
        name="mistral-7b",
        vocab_size=32768,
        hidden_size=4096,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        intermediate_size=14336,
        rope_theta=1000000.0,
        max_seq_len=32768,
    ),
    "llama-3.2-1b": ModelConfig(
        name="llama-3.2-1b",
        vocab_size=128256,
        hidden_size=2048,
        num_layers=16,
        num_heads=32,
        num_kv_heads=8,
        head_dim=64,
        intermediate_size=8192,
        max_seq_len=131072,
        tie_word_embeddings=True,
    ),
    "llama-3.2-3b": ModelConfig(
        name="llama-3.2-3b",
        vocab_size=128256,
        hidden_size=3072,
        num_layers=28,
        num_heads=24,
        num_kv_heads=8,
        head_dim=128,
        intermediate_size=8192,
        max_seq_len=131072,
        tie_word_embeddings=True,
    ),
    "llama-3-8b": ModelConfig(
        name="llama-3-8b",
        vocab_size=128256,
        hidden_size=4096,
        num_layers=32,
        num_heads=32,
        num_kv_heads=8,
        head_dim=128,
        intermediate_size=14336,
        max_seq_len=8192,
    ),
    "llama-3-70b": ModelConfig(
        name="llama-3-70b",
        vocab_size=128256,
        hidden_size=8192,
        num_layers=80,
        num_heads=64,
        num_kv_heads=8,
        head_dim=128,
        intermediate_size=28672,
        max_seq_len=8192,
    ),
}


def get_config(name: str) -> ModelConfig:
    if name in PRESETS:
        return PRESETS[name]
    raise KeyError(f"unknown model preset: {name} (have {sorted(PRESETS)})")


def resolve_moe_dispatch(config: ModelConfig, ep: int) -> ModelConfig:
    """Resolve "auto" MoE dispatch against the actual expert-parallel degree.

    Called by every entry point that knows the mesh (Scheduler, pipelined
    decode, profilers). Wide-EP meshes need "capacity" (its einsum expert
    axis partitions over ``ep``); single-shard/tp meshes use the exact
    "ragged" grouped GEMM. Direct model calls that never see a mesh keep the
    "auto"→"ragged" default in ``_mlp``."""
    if config.num_experts and config.moe_dispatch == "auto":
        return config.replace(moe_dispatch="capacity" if ep > 1 else "ragged")
    return config
