"""Multi-host engine coordination: jax.distributed over ICI + DCN.

Ref: the reference coordinates multi-node engines via ``MultiNodeConfig``
(lib/llm/src/engines.rs:28 — node_rank/num_nodes/leader) and MPI/srun
launchers (components/backends/trtllm/multinode/srun_*.sh). The TPU-native
equivalent is JAX's multi-controller runtime: every host process calls
``jax.distributed.initialize(coordinator, num_processes, process_id)``;
afterwards ``jax.devices()`` spans all hosts and the exact same
Mesh/pjit/shard_map serving code runs SPMD across the pod — XLA routes
collectives over ICI within a slice and DCN across slices.

Topology-aware meshes: ``build_multihost_mesh`` places the DCN-crossing
axis (data parallel between slices) outermost via
``mesh_utils.create_hybrid_device_mesh`` so only dp-gradient-free
serving traffic (none) or batch splits ride DCN, while tp/ep/sp/pp stay
on ICI.

Rendezvous without static addresses: the leader (first process to win the
create-only store key) publishes its coordinator address; followers pick up
the address and claim dense process ids from an atomic counter — the
etcd-barrier pattern the reference uses for its KVBM leader
(lib/llm/src/block_manager/distributed/leader.rs:24).
"""

from __future__ import annotations

import os
import socket
from dataclasses import dataclass
from typing import Optional

from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)

COORD_PREFIX = "multihost"


@dataclass
class MultiHostConfig:
    """Ref: engines.rs:28 MultiNodeConfig{num_nodes, node_rank, leader}."""

    num_processes: int = 1
    process_id: int = 0
    coordinator: Optional[str] = None  # host:port of process 0

    @classmethod
    def from_env(cls) -> "MultiHostConfig":
        return cls(
            num_processes=int(os.environ.get("DYN_MULTIHOST_PROCESSES", "1")),
            process_id=int(os.environ.get("DYN_MULTIHOST_PROCESS_ID", "0")),
            coordinator=os.environ.get("DYN_MULTIHOST_COORDINATOR") or None,
        )

    @property
    def enabled(self) -> bool:
        return self.num_processes > 1

    @property
    def is_leader(self) -> bool:
        return self.process_id == 0


def init_multihost(cfg: MultiHostConfig) -> None:
    """Join the multi-controller runtime. Must run before any jax backend
    touch; afterwards jax.devices() is global, jax.local_devices() is ours."""
    if not cfg.enabled:
        return
    import jax

    if cfg.coordinator is None:
        raise ValueError("multi-host needs a coordinator address (leader's host:port)")
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )
    logger.info(
        "multihost up: process %d/%d, %d local / %d global devices",
        cfg.process_id, cfg.num_processes, jax.local_device_count(), jax.device_count(),
    )


def pick_coordinator_port(host: Optional[str] = None) -> str:
    """Reserve an ephemeral port on this host for the coordinator service."""
    host = host or socket.gethostname()
    with socket.socket() as s:
        s.bind(("", 0))
        return f"{host}:{s.getsockname()[1]}"


async def rendezvous(drt, group: str, num_processes: int, *, timeout_s: float = 60.0) -> MultiHostConfig:
    """Store-based dense process-id assignment + coordinator publication.

    Rank assignment happens FIRST (create-only puts on
    ``multihost/{group}/rank/{i}``); only the process that actually won rank
    0 then publishes its coordinator address, and every other rank polls the
    key *after* assignment. Publishing before/independently of rank
    assignment is racy: a process could win the coordinator key but lose
    rank 0, leaving the group pointed at an address where no coordinator
    service will ever listen.
    """
    import asyncio
    import time

    from dynamo_tpu.runtime.transports.kvstore import KeyExists

    process_id = None
    deadline = time.monotonic() + timeout_s
    marker = f"{socket.gethostname()}:{os.getpid()}"  # opaque claim payload
    while process_id is None:
        for i in range(num_processes):
            try:
                await drt.store.put(f"{COORD_PREFIX}/{group}/rank/{i}", marker.encode(), create_only=True)
                process_id = i
                break
            except KeyExists:
                continue
        if process_id is None:
            if time.monotonic() > deadline:
                raise TimeoutError(f"no free rank among {num_processes} for group {group}")
            await asyncio.sleep(0.1)

    coord_key = f"{COORD_PREFIX}/{group}/coordinator"
    if process_id == 0:
        coordinator = pick_coordinator_port()
        await drt.store.put(coord_key, coordinator.encode())
    else:
        coordinator = None
        while coordinator is None:
            entry = await drt.store.get(coord_key)
            if entry is not None:
                coordinator = entry.value.decode()
                break
            if time.monotonic() > deadline:
                raise TimeoutError(f"rank 0 never published a coordinator for group {group}")
            await asyncio.sleep(0.1)

    return MultiHostConfig(num_processes=num_processes, process_id=process_id, coordinator=coordinator)


def build_multihost_mesh(parallel, dcn_dp: int = 1):
    """Mesh over all hosts' devices: DCN-crossing dp axis outermost, ICI
    axes (pp/sp/ep/tp + intra-slice dp) inner.

    ``parallel`` is the per-slice ParallelConfig (engine/sharding.py);
    ``dcn_dp`` is the number of slices (data-parallel replicas across DCN).
    """
    import jax
    import numpy as np
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    total_ici = parallel.total
    n = total_ici * dcn_dp
    if jax.device_count() < n:
        raise ValueError(f"need {n} devices, have {jax.device_count()}")
    if dcn_dp == 1:
        from dynamo_tpu.engine.sharding import build_mesh

        return build_mesh(parallel)
    try:
        devices = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(parallel.dp, parallel.pp, parallel.sp, parallel.ep, parallel.tp),
            dcn_mesh_shape=(dcn_dp, 1, 1, 1, 1),
            devices=jax.devices()[:n],
        )
        arr = np.asarray(devices)
    except ValueError:
        # Non-TPU devices carry no slice_index topology: fall back to
        # process-ordered placement (jax.devices() is ordered by process, and
        # process boundaries ARE the DCN boundaries).
        arr = np.array(jax.devices()[:n])
    # Hybrid mesh folds dcn_dp into the first axis: [dcn_dp*dp, pp, sp, ep, tp].
    arr = arr.reshape(dcn_dp * parallel.dp, parallel.pp, parallel.sp, parallel.ep, parallel.tp)
    return Mesh(arr, axis_names=("dp", "pp", "sp", "ep", "tp"))
