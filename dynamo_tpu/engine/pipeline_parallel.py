"""Pipeline parallelism: microbatched ppermute pipeline over pp-sharded layers.

The reference only *configures* pipeline parallel in its engines (trtllm
``pipeline_parallel_size``, SURVEY.md §2e) — the actual pipelining lives in
TRT-LLM/vLLM CUDA runtimes. Here it is native and TPU-idiomatic:

- The model keeps its stacked-layer layout (``[L, ...]`` leaves, scanned by
  ``lax.scan``). The stack shards over the ``pp`` mesh axis — each stage
  holds ``L/pp`` contiguous layers and the matching slice of the paged KV
  cache (``kv_cache_spec(pp=True)``), so HBM per chip drops by pp×.
- A partial-manual ``jax.shard_map(axis_names={'pp'})`` makes only ``pp``
  manual; tensor-parallel sharding of the weights *inside* each stage stays
  GSPMD-automatic, so pp composes with tp/dp without hand-written psums.
- The decode batch splits into M microbatches that flow through stages in
  the classic GPipe schedule: at step t, stage s processes microbatch
  ``t - s``; activations hop stage→stage+1 via ``lax.ppermute`` over ICI.
  ``M + pp - 1`` steps drain the pipeline; with M ≥ pp every stage is busy
  in steady state. Out-of-range steps run with ``active=False`` so their KV
  writes sink to scratch block 0 (the allocator never hands out block 0).

The schedule is a ``lax.fori_loop`` — one compiled step body regardless of
microbatch count, XLA-friendly (no Python unrolling).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.engine.models.llama import (
    decode_layer_scan,
    decode_targets,
    rms_norm,
    scatter_kv_rows,
)


def pipelined_decode(
    params,
    config: ModelConfig,
    k_cache: jax.Array,  # [L, N, BS, KVH, HD], layer axis sharded over pp
    v_cache: jax.Array,
    tokens: jax.Array,  # [B]
    positions: jax.Array,  # [B]
    block_tables: jax.Array,  # [B, max_blocks]
    active: jax.Array,  # [B] bool
    mesh: Mesh,
    num_microbatches: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step for a batch, pipelined over the ``pp`` mesh axis.

    Same contract as ``llama.decode``: returns (logits [B, V] f32, k_cache,
    v_cache). Requires ``B % num_microbatches == 0`` (default M = pp)."""
    from dynamo_tpu.engine.config import resolve_moe_dispatch

    c = resolve_moe_dispatch(config, mesh.shape.get("ep", 1))
    pp = mesh.shape["pp"]
    B = tokens.shape[0]
    M = num_microbatches or pp
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    if c.num_layers % pp != 0:
        raise ValueError(f"num_layers {c.num_layers} not divisible by pp {pp}")
    mb = B // M
    bs = c.block_size
    max_blocks = block_tables.shape[1]

    poss_mb = positions.reshape(M, mb)
    tables_mb = block_tables.reshape(M, mb, max_blocks)
    act_mb = active.reshape(M, mb)

    embed = params["embed"]
    final_norm = params["final_norm"]
    tied = "lm_head" not in params
    head = embed if tied else params["lm_head"]
    layers = params["layers"]

    # Embed all microbatches once, outside the pipeline body: the embedding
    # table is tp-sharded over the vocab, so the gather (and its collective)
    # runs once under GSPMD instead of on every stage at every step.
    h0_mb = embed.at[tokens.reshape(M, mb)].get(mode="clip")  # [M, mb, D]

    def body(layers, kc, vc, h0, poss, tables, act):
        stage = lax.axis_index("pp")
        last = pp - 1

        def step(t, state):
            h_prev, kc, vc, out = state
            mb_idx = t - stage
            in_range = (mb_idx >= 0) & (mb_idx < M)
            i = jnp.clip(mb_idx, 0, M - 1)

            poss_i = jnp.take(poss, i, axis=0)
            tables_i = jnp.take(tables, i, axis=0)  # [mb, max_blocks]
            act_i = jnp.take(act, i, axis=0) & in_range

            # Stage 0 feeds its current microbatch's embeddings; later stages
            # consume the activation that arrived from the previous stage.
            h_in = jnp.where(stage == 0, jnp.take(h0, i, axis=0), h_prev)

            tgt_blocks, tgt_offs, mask = decode_targets(poss_i, tables_i, act_i, bs)

            h_out, k_rows, v_rows = decode_layer_scan(
                layers, c, kc, vc, h_in, poss_i, tables_i, mask, active=act_i,
            )
            kc, vc = scatter_kv_rows(kc, vc, k_rows, v_rows, tgt_blocks, tgt_offs)

            # Only the last stage's output is real; collect hidden states
            # ([mb, D], cheap) — the lm-head matmul runs once after the loop,
            # not V-wide on every stage every step.
            write = ((stage == last) & in_range).astype(h_out.dtype)
            out = out.at[i].set(out[i] * (1.0 - write) + h_out * write)

            h_next = lax.ppermute(h_out, "pp", [(s, (s + 1) % pp) for s in range(pp)])
            return (h_next, kc, vc, out)

        init = (
            jnp.zeros((mb, c.hidden_size), dtype=h0.dtype),
            kc, vc,
            jnp.zeros((M, mb, c.hidden_size), dtype=h0.dtype),
        )
        _, kc, vc, out = lax.fori_loop(0, M + pp - 1, step, init)
        # out is populated only on the last stage; exactly one stage
        # contributes, so the psum is an exact broadcast over pp. The f32
        # cast routes around an XLA-CPU crash on bf16 all-reduce
        # ("Invalid binary instruction opcode copy") and is harmless on TPU.
        out = lax.psum(jnp.where(stage == last, 1.0, 0.0) * out.astype(jnp.float32), "pp")
        return out.astype(h0.dtype), kc, vc

    sharded = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P("pp"), P("pp"), P("pp"), P(), P(), P(), P()),
        out_specs=(P(), P("pp"), P("pp")),
        axis_names={"pp"},
        check_vma=False,
    )
    out, k_new, v_new = sharded(
        layers, k_cache, v_cache, h0_mb,
        poss_mb, tables_mb, act_mb,
    )
    # Final norm + lm head outside the pipeline body: the head weight is
    # tp-sharded, so GSPMD partitions this one matmul over tp.
    hl = rms_norm(out.reshape(B, c.hidden_size), final_norm, c.rms_norm_eps)
    logits = (hl @ (head.T if tied else head)).astype(jnp.float32)
    return logits, k_new, v_new
