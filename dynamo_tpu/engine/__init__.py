"""The native TPU engine: JAX/XLA/Pallas model execution with paged KV cache,
continuous batching, and mesh parallelism.

This is the subsystem the reference *outsources* to vLLM/SGLang/TRT-LLM
(SURVEY.md §2d engine adapters); dynamo-tpu implements it natively so the
whole serving stack is TPU-first:

- ``config``     — model architecture configs + presets.
- ``models``     — functional forward passes (Llama family first).
- ``kv_cache``   — paged KV cache on device + block allocator.
- ``attention``  — paged/dense attention (XLA fallback; Pallas kernels).
- ``sampling``   — jit-compatible token sampling.
- ``scheduler``  — continuous batching over bucketed compiled steps.
- ``engine``     — the AsyncEngine facade workers serve.
- ``sharding``   — jax.sharding meshes + partition specs (TP/EP/...).
"""

from dynamo_tpu.engine.config import ModelConfig, PRESETS
