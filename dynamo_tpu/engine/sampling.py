"""Jit-compatible token sampling: greedy / temperature / top-k / top-p.

Sampling parameters arrive per-request (ref: protocols/common SamplingOptions,
SURVEY.md §2b protocols); the scheduler batches them into per-slot arrays so
one compiled sampler serves mixed-parameter batches.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import jax
import jax.numpy as jnp


@dataclass
class SamplingParams:
    """Host-side per-request sampling options."""

    temperature: float = 1.0
    top_k: int = 0  # 0 = disabled
    top_p: float = 1.0
    seed: Optional[int] = None
    # Per-request processors (dynamo_tpu.logits_processing) — host path.
    logits_processors: List = field(default_factory=list)

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0


def sample_batch(
    logits: jax.Array,  # [B, V] f32
    temperature: jax.Array,  # [B] f32 (0 = greedy)
    top_k: jax.Array,  # [B] i32 (0 = off)
    top_p: jax.Array,  # [B] f32 (1 = off)
    key: jax.Array,
) -> jax.Array:
    """Sample one token per row honouring per-row parameters. Greedy rows
    (temperature 0) take argmax."""
    B, V = logits.shape
    greedy_tok = jnp.argmax(logits, axis=-1)

    safe_temp = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_temp[:, None]

    # top-k: mask everything below the k-th largest (k=0 disables).
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    k_idx = jnp.clip(jnp.where(top_k > 0, top_k, V) - 1, 0, V - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=1)
    scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)

    # top-p (nucleus): keep the smallest set of tokens with cumulative
    # probability >= top_p. Always keep the argmax.
    probs_sorted = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs_sorted, axis=-1)
    cutoff_mask_sorted = (cum - probs_sorted) < top_p[:, None]  # keep while prior mass < p
    # Map the sorted-space threshold back: keep token if its prob >= min kept prob.
    min_kept = jnp.min(jnp.where(cutoff_mask_sorted, sorted_desc, jnp.inf), axis=-1)
    scaled = jnp.where(scaled >= min_kept[:, None], scaled, -jnp.inf)

    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy_tok).astype(jnp.int32)


def compute_logprobs(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Log-probability of chosen tokens. logits [B, V], tokens [B] → [B]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, tokens[:, None], axis=1)[:, 0]
