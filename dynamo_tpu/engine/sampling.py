"""Jit-compatible token sampling: greedy / temperature / top-k / top-p.

Sampling parameters arrive per-request (ref: protocols/common SamplingOptions,
SURVEY.md §2b protocols); the scheduler batches them into per-slot arrays so
one compiled sampler serves mixed-parameter batches.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import List, Optional

import jax
import jax.numpy as jnp


@dataclass
class SamplingParams:
    """Host-side per-request sampling options."""

    temperature: float = 1.0
    top_k: int = 0  # 0 = disabled
    top_p: float = 1.0
    # Per-request PRNG: same seed + same prompt ⇒ same sample sequence,
    # independent of batch composition (the key folds in the per-request
    # token position, not the global step counter).
    seed: Optional[int] = None
    # OpenAI penalties over generated tokens (vLLM semantics: counts cover
    # the OUTPUT so far, not the prompt). Applied to logits before
    # temperature/top-k/top-p — ref: protocols/common SamplingOptions +
    # protocols/openai/validate.rs.
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    # Return the chosen token's log-probability with each step.
    logprobs: bool = False
    # Number of top-alternative (token, logprob) pairs to return per step
    # (OpenAI ``top_logprobs``). Served from the same fused sampling
    # dispatch with a STATIC candidate cap (TOP_LOGPROBS_CAP) so every
    # request shares one executable; implies ``logprobs``.
    top_logprobs: int = 0
    # Per-request processors (dynamo_tpu.logits_processing) — host path.
    logits_processors: List = field(default_factory=list)

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0

    @property
    def has_penalties(self) -> bool:
        return self.frequency_penalty != 0.0 or self.presence_penalty != 0.0


def pack_param_rows(samplings: List["SamplingParams"], bucket: int):
    """Pack per-request sampling params into the sampler's per-slot numpy
    rows, padded to ``bucket``. Pad rows are greedy (temperature 0.0,
    top_p 1.0) so all-greedy batches hit the sampler's argmax fast path
    regardless of bucket padding. One packing rule for every batched
    sampler call site: single-step decode, multi-step windows, spec-decode
    rounds, wave admission, and mixed prefill+decode steps — a mixed step
    samples only at each sequence's last row, and these rows ARE those."""
    import numpy as np

    temps = np.zeros((bucket,), dtype=np.float32)
    top_ks = np.zeros((bucket,), dtype=np.int32)
    top_ps = np.ones((bucket,), dtype=np.float32)
    for i, s in enumerate(samplings):
        temps[i] = s.temperature
        top_ks[i] = s.top_k
        top_ps[i] = s.top_p
    return temps, top_ks, top_ps


# Top-k/top-p thresholds are resolved inside the best-SAMPLE_WINDOW logits
# (lax.top_k) instead of a full-vocab sort: two O(V log V) sorts per step
# cost ~7 ms on a 128k vocab (v5e, b8) — more than the whole 1B forward
# pass. The windowed result is checked for exactness per row: when any row
# requests top_k > SAMPLE_WINDOW, or its window holds less than ``top_p``
# probability mass, the batch falls back to the exact full-vocab sort for
# that step (runtime lax.cond — the fast path stays sort-free). Sampling
# semantics therefore always match the requested top-k/top-p exactly.
SAMPLE_WINDOW = 64


def _exact_thresholds(scaled, lse, top_k, top_p):
    """Full-vocab top-k/top-p truncation thresholds (one descending sort)."""
    V = scaled.shape[-1]
    srt = jnp.sort(scaled, axis=-1)[:, ::-1]  # [B, V] descending
    k_idx = jnp.clip(jnp.where(top_k > 0, top_k, V) - 1, 0, V - 1)
    kth = jnp.take_along_axis(srt, k_idx[:, None], axis=1)[:, 0]
    k_thresh = jnp.where(top_k > 0, kth, -jnp.inf)

    probs = jnp.exp(srt - lse)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs) < top_p[:, None]
    min_kept = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1)
    p_thresh = jnp.where(top_p < 1.0, min_kept, -jnp.inf)
    return jnp.maximum(k_thresh, p_thresh)


def filtered_probs_rows(
    logits: jax.Array,  # [B, V] f32
    temps: jax.Array,  # [B] f32 (0 = greedy)
    top_ks: jax.Array,  # [B] i32 (0 = off)
    top_ps: jax.Array,  # [B] f32 (1 = off)
) -> jax.Array:
    """THE reference sampling distribution: temperature scale + exact
    top-k/top-p truncation (``_exact_thresholds``) + softmax, per row.
    Greedy rows (temperature 0) return a one-hot argmax distribution.

    One implementation shared by the host sampler's exact path, the fused
    megakernel's in-kernel epilogue, and spec_decode's verifier — tie-
    breaking (the ``>= thresh`` keep rule after one descending sort) is
    bit-identical everywhere, so fused vs sync parity and draft-vs-verify
    distribution agreement hold exactly."""
    V = logits.shape[-1]
    safe_t = jnp.where(temps > 0, temps, 1.0)
    scaled = logits / safe_t[:, None]
    lse = jax.scipy.special.logsumexp(scaled, axis=-1, keepdims=True)
    thresh = _exact_thresholds(scaled, lse, top_ks, top_ps)
    masked = jnp.where(scaled >= thresh[:, None], scaled, -jnp.inf)
    probs = jax.nn.softmax(masked, axis=-1)
    greedy = jax.nn.one_hot(jnp.argmax(logits, axis=-1), V, dtype=probs.dtype)
    return jnp.where((temps > 0)[:, None], probs, greedy)


def pick_from_probs(probs: jax.Array, u: jax.Array) -> jax.Array:
    """Inverse-CDF draw: the first index whose cumulative probability
    exceeds ``u`` (per row; ``probs`` [B, V], ``u`` [B] in [0, 1)). The
    fp-degenerate tail (u beyond the row's total mass) falls back to the
    row's mode so the pick is always a kept token."""
    cum = jnp.cumsum(probs, axis=-1)
    hit = cum > u[:, None]
    picked = jnp.argmax(hit, axis=-1).astype(jnp.int32)
    fallback = jnp.argmax(probs, axis=-1).astype(jnp.int32)
    return jnp.where(hit[:, -1], picked, fallback)


def sample_from_uniforms(
    logits: jax.Array,  # [B, V] f32
    temps: jax.Array,  # [B] f32 (0 = greedy)
    top_ks: jax.Array,  # [B] i32 (0 = off)
    top_ps: jax.Array,  # [B] f32 (1 = off)
    u: jax.Array,  # [B] f32 — precomputed uniforms in [0, 1)
) -> jax.Array:
    """Sample one token per row from precomputed uniforms instead of a
    threaded PRNG key — the fused megakernel's sampling contract: the host
    derives per-(step, row) uniforms up front (``make_window_uniforms``)
    and the kernel consumes one per step via inverse-CDF, so the in-kernel
    epilogue and any host replay of the same uniforms pick bit-identical
    tokens. Greedy rows ride the one-hot distribution (cum jumps 0→1 at
    the argmax, any u < 1 picks it)."""
    return pick_from_probs(filtered_probs_rows(logits, temps, top_ks, top_ps), u)


@functools.partial(jax.jit, static_argnames=("num_steps",))
def make_window_uniforms(
    base_key: jax.Array,
    seeds: jax.Array,  # [B] i32 (0 where unseeded)
    positions: jax.Array,  # [B] i32 — per-request token position at window start
    has_seed: jax.Array,  # [B] bool
    num_steps: int,
) -> jax.Array:
    """Host-side uniforms for a fused sampled window → [num_steps, B].
    ``u[s, b]`` is the inverse-CDF draw row b consumes at window step s:
    seeded rows derive from PRNGKey(seed) folded with the row's absolute
    token position (``make_row_keys`` semantics — batch-composition
    independent, so a seeded request replays identically at any batch
    slot), unseeded rows fold the per-step subkey with their row index.
    ONE dispatch per window, not per step (no per-step host sync)."""

    def step_u(s):
        ks = make_row_keys(
            jax.random.fold_in(base_key, s), seeds, positions + s, has_seed
        )
        return jax.vmap(lambda k: jax.random.uniform(k, ()))(ks)

    return jnp.stack([step_u(s) for s in range(num_steps)])


def sample_batch(
    logits: jax.Array,  # [B, V] f32
    temperature: jax.Array,  # [B] f32 (0 = greedy)
    top_k: jax.Array,  # [B] i32 (0 = off)
    top_p: jax.Array,  # [B] f32 (1 = off)
    key: jax.Array,
    row_keys: Optional[jax.Array] = None,  # [B, 2] per-row PRNG keys (seeded requests)
) -> jax.Array:
    """Sample one token per row honouring per-row parameters. Greedy rows
    (temperature 0) take argmax; all-greedy batches skip sampling entirely
    (runtime branch — the common temperature=0 serving case). With
    ``row_keys`` each row draws from its own key (per-request seeds)."""
    B, V = logits.shape
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def sample_path(_):
        safe_temp = jnp.where(temperature > 0, temperature, 1.0)
        scaled = logits / safe_temp[:, None]
        cap = min(SAMPLE_WINDOW, V)
        top_vals = jax.lax.top_k(scaled, cap)[0]  # [B, cap] descending
        lse = jax.scipy.special.logsumexp(scaled, axis=-1, keepdims=True)
        probs_top = jnp.exp(top_vals - lse)  # true probabilities of window
        cum = jnp.cumsum(probs_top, axis=-1)

        def windowed(_):
            # top-k threshold: the k-th largest (k ≤ window by construction).
            k_idx = jnp.clip(jnp.where(top_k > 0, top_k, cap) - 1, 0, cap - 1)
            kth = jnp.take_along_axis(top_vals, k_idx[:, None], axis=1)[:, 0]
            k_thresh = jnp.where(top_k > 0, kth, -jnp.inf)
            # top-p threshold: smallest prob among the nucleus.
            keep = (cum - probs_top) < top_p[:, None]  # keep while prior mass < p
            min_kept = jnp.min(jnp.where(keep, top_vals, jnp.inf), axis=-1)
            p_thresh = jnp.where(top_p < 1.0, min_kept, -jnp.inf)
            return jnp.maximum(k_thresh, p_thresh)

        # Window is exact for a row iff requested k fits and (top_p off or
        # the window holds ≥ top_p of the probability mass).
        sampling_row = temperature > 0
        k_fits = (top_k <= 0) | (top_k <= cap)
        p_fits = (top_p >= 1.0) | (cum[:, -1] >= top_p)
        window_exact = jnp.all(~sampling_row | (k_fits & p_fits)) | (cap == V)

        thresh = jax.lax.cond(
            window_exact, windowed, lambda _: _exact_thresholds(scaled, lse, top_k, top_p), None
        )
        masked = jnp.where(scaled >= thresh[:, None], scaled, -jnp.inf)
        if row_keys is not None:
            sampled = jax.vmap(
                lambda k, row: jax.random.categorical(k, row)
            )(row_keys, masked).astype(jnp.int32)
        else:
            sampled = jax.random.categorical(key, masked, axis=-1).astype(jnp.int32)
        return jnp.where(temperature > 0, sampled, greedy_tok)

    return jax.lax.cond(jnp.any(temperature > 0), sample_path, lambda _: greedy_tok, None)


def apply_token_masks(
    logits: jax.Array,  # [B, V] f32
    pool: jax.Array,  # [P, ceil(V/32)] uint32 — shared guided mask pool
    row_ids: jax.Array,  # [B] i32 — pool row per batch row (0 = allow-all)
) -> jax.Array:
    """Grammar-constrained decoding's jit-side hook: gather each row's
    allowed-token bitmask from the device mask pool by FSM-state row id and
    add ``-inf`` to disallowed logits. Row 0 of the pool is the reserved
    allow-everything row, so unguided rows in a mixed batch pass through the
    same executable unchanged (llm/guided/processor.py owns the pool)."""
    B, V = logits.shape
    rows = pool[row_ids]  # [B, W]
    idx = jnp.arange(V, dtype=jnp.int32)
    words = rows[:, idx >> 5]  # [B, V] uint32
    bit = jnp.right_shift(words, (idx & 31).astype(jnp.uint32)) & jnp.uint32(1)
    return jnp.where(bit.astype(bool), logits, -jnp.inf)


def guided_sample_batch(
    logits: jax.Array,  # [B, V] f32
    pool: jax.Array,  # [P, W] uint32
    k_rows: jax.Array,  # [2, B] i32: row 0 = top_k, row 1 = mask-pool row ids
    temperature: jax.Array,  # [B] f32
    top_p: jax.Array,  # [B] f32
    key: jax.Array,
    row_keys: Optional[jax.Array] = None,
) -> jax.Array:
    """Mask-gather fused with the batched sampler: ONE dispatch per step for
    guided batches, identical semantics to ``sample_batch`` over the
    FSM-allowed token set. ``top_k`` and the pool row ids ride one packed
    i32 upload, so a guided step pays the same number of per-step
    host→device transfers as an unguided one (measured: each small upload
    costs ~0.1 ms of dispatch on CPU-class links — the whole guided margin)."""
    return sample_batch(
        apply_token_masks(logits, pool, k_rows[1]), temperature, k_rows[0], top_p, key, row_keys
    )


def sample_batch_logprobs(
    logits: jax.Array,  # [B, V] f32
    temperature: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
    key: jax.Array,
    row_keys: Optional[jax.Array] = None,
) -> tuple:
    """``sample_batch`` with the chosen-token log-probabilities folded into
    the SAME dispatch → (tokens [B] i32, logprobs [B] f32). When any row
    requests logprobs the scheduler used to issue a separate
    ``compute_logprobs`` device op (+ its own host sync) per step; fusing it
    here keeps logprobs batches at one dispatch and one readback, same as
    plain ones."""
    tok = sample_batch(logits, temperature, top_k, top_p, key, row_keys)
    return tok, compute_logprobs(logits, tok)


def guided_sample_batch_logprobs(
    logits: jax.Array,
    pool: jax.Array,
    k_rows: jax.Array,
    temperature: jax.Array,
    top_p: jax.Array,
    key: jax.Array,
    row_keys: Optional[jax.Array] = None,
) -> tuple:
    """``guided_sample_batch`` + fused logprobs (see sample_batch_logprobs).
    Logprobs are of the MASKED distribution — the model's renormalized
    probability over the FSM-allowed set, which is what the row actually
    sampled from."""
    masked = apply_token_masks(logits, pool, k_rows[1])
    tok = sample_batch(masked, temperature, k_rows[0], top_p, key, row_keys)
    return tok, compute_logprobs(masked, tok)


@jax.jit
def apply_penalties(
    logits: jax.Array,  # [B, V] f32
    hist: jax.Array,  # [B, H] i32 — generated-token history, padded
    hist_len: jax.Array,  # [B] i32 — valid history per row
    frequency_penalty: jax.Array,  # [B] f32
    presence_penalty: jax.Array,  # [B] f32
) -> jax.Array:
    """Batched OpenAI frequency/presence penalties in ONE dispatch:
    per-row output-token counts built by scatter-add from the padded
    history, then ``logits - freq·count - pres·(count > 0)``. Host cost is
    the [B, H] history upload (H = longest output, bucketed); the [B, V]
    count tensor exists only on device. vLLM semantics: counts cover
    generated tokens only, not the prompt."""
    B, V = logits.shape
    H = hist.shape[1]
    valid = jnp.arange(H, dtype=jnp.int32)[None, :] < hist_len[:, None]
    tok = jnp.where(valid, hist, 0)
    counts = jnp.zeros((B, V), jnp.float32).at[
        jnp.arange(B, dtype=jnp.int32)[:, None], tok
    ].add(valid.astype(jnp.float32))  # padded rows add 0 to token 0
    return logits - frequency_penalty[:, None] * counts - presence_penalty[:, None] * (
        counts > 0
    )


@jax.jit
def make_row_keys(
    base_key: jax.Array,
    seeds: jax.Array,  # [B] i32 (0 where unseeded)
    positions: jax.Array,  # [B] i32 per-request token position
    has_seed: jax.Array,  # [B] bool
) -> jax.Array:
    """Per-row sampling keys in ONE dispatch (a per-row Python loop of
    fold_in calls costs ~B tiny dispatches on the decode hot path): seeded
    rows fold their request position into PRNGKey(seed) — batch-composition
    independent — while unseeded rows fold their row index into the step's
    base key."""

    def mk(seed, pos, i, has):
        seeded = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
        unseeded = jax.random.fold_in(base_key, i)
        return jnp.where(has, seeded, unseeded)

    idx = jnp.arange(seeds.shape[0], dtype=jnp.int32)
    return jax.vmap(mk)(seeds, positions, idx, has_seed)


def compute_logprobs(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Log-probability of chosen tokens. logits [B, V], tokens [B] → [B]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, tokens[:, None], axis=1)[:, 0]


# Static per-executable candidate count for top-logprobs rows. Requests ask
# for k ∈ [1, TOP_LOGPROBS_CAP] (the OpenAI bound is 20) but the dispatch
# always computes the cap: a traced k would compile one executable per
# distinct requested k. Rows trim to their own k on the host.
TOP_LOGPROBS_CAP = 20


def compute_topk_logprobs(logits: jax.Array, tokens: jax.Array) -> tuple:
    """Chosen-token logprob plus the TOP_LOGPROBS_CAP most likely tokens'
    ids and logprobs in one op group — logits [B, V], tokens [B] →
    (chosen [B] f32, top_ids [B, CAP] i32, top_lps [B, CAP] f32)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    chosen = jnp.take_along_axis(logp, tokens[:, None], axis=1)[:, 0]
    cap = min(TOP_LOGPROBS_CAP, logits.shape[-1])
    top_lps, top_ids = jax.lax.top_k(logp, cap)
    return chosen, top_ids.astype(jnp.int32), top_lps


def sample_batch_top_logprobs(
    logits: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
    key: jax.Array,
    row_keys: Optional[jax.Array] = None,
) -> tuple:
    """``sample_batch_logprobs`` widened with the per-row top-k alternatives
    (OpenAI ``top_logprobs``) in the SAME dispatch → (tokens [B] i32,
    logprobs [B] f32, top_ids [B, CAP] i32, top_lps [B, CAP] f32). One
    executable regardless of each row's requested k (static cap)."""
    tok = sample_batch(logits, temperature, top_k, top_p, key, row_keys)
    chosen, top_ids, top_lps = compute_topk_logprobs(logits, tok)
    return tok, chosen, top_ids, top_lps


def guided_sample_batch_top_logprobs(
    logits: jax.Array,
    pool: jax.Array,
    k_rows: jax.Array,
    temperature: jax.Array,
    top_p: jax.Array,
    key: jax.Array,
    row_keys: Optional[jax.Array] = None,
) -> tuple:
    """``guided_sample_batch_logprobs`` + fused top-k alternatives. Like the
    lp variant, all logprobs (chosen and alternatives) are of the MASKED
    distribution — the renormalized probability over the FSM-allowed set."""
    masked = apply_token_masks(logits, pool, k_rows[1])
    tok = sample_batch(masked, temperature, k_rows[0], top_p, key, row_keys)
    chosen, top_ids, top_lps = compute_topk_logprobs(masked, tok)
    return tok, chosen, top_ids, top_lps
