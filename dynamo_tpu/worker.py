"""Worker CLI: serve a JAX engine (or mocker) as a discoverable endpoint.

Ref: components/backends/{vllm,mocker}/main.py — roles: ``aggregated``
(default), ``decode`` (forwards long prefills to the prefill pool),
``prefill`` (serves remote prefills + KV export). The reference wraps
external engines; here the engine is native (dynamo_tpu.engine) or the
mocker.

Run: ``python -m dynamo_tpu.worker --model tiny [--role decode|prefill]
[--mocker]``.
"""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.disagg import DisaggDecodeHandler, DisaggRouter, DisaggRouterConf, KvExportService
from dynamo_tpu.llm.entrypoint import register_llm
from dynamo_tpu.llm.kv_router import KvEventPublisher, WorkerMetricsPublisher
from dynamo_tpu.llm.mocker import MockEngineArgs, MockTpuEngine
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.logging import get_logger, init_logging

logger = get_logger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="dynamo-tpu worker")
    p.add_argument("--model", default="tiny", help="model preset name or local checkpoint dir")
    p.add_argument("--served-model-name", default=None)
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default=None, help="defaults to role name")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--role", choices=["aggregated", "decode", "prefill", "encode"], default="aggregated")
    p.add_argument("--vision-model", default="tiny-vit",
                   help="vision tower preset for --role encode (engine/models/vision.py)")
    p.add_argument("--vision-seed", type=int, default=0)
    p.add_argument("--mocker", action="store_true", help="serve the mocker engine instead of the JAX engine")
    p.add_argument("--num-blocks", type=int, default=512)
    p.add_argument("--max-running", type=int, default=16)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--kv-cache-dtype", choices=["auto", "int8"], default="auto",
                   help="int8 halves KV memory/bytes (llama gather path)")
    p.add_argument("--weight-dtype", choices=["auto", "int8"], default="auto",
                   help="int8 stores layer matmul weights quantized (2x model capacity per HBM byte)")
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--tokenizer", default=None)
    p.add_argument("--draft-model", default=None,
                   help="draft model preset for speculative decoding (greedy batches)")
    p.add_argument("--draft-checkpoint", default=None)
    p.add_argument("--spec-gamma", type=int, default=4,
                   help="speculative tokens proposed per round")
    p.add_argument("--kvbm-host-blocks", type=int, default=0)
    p.add_argument("--kvbm-disk-dir", default=None)
    p.add_argument("--kvbm-disk-blocks", type=int, default=0)
    p.add_argument("--kvbm-remote", action="store_true",
                   help="enable the G4 remote KV tier on the control-plane object store")
    p.add_argument("--max-local-prefill-length", type=int, default=0)
    p.add_argument("--speedup-ratio", type=float, default=1.0, help="mocker time compression")
    p.add_argument("--kv-transfer", choices=["device", "host"], default="device",
                   help="disagg KV plane: device-native (NIXL role) or host-numpy over TCP")
    # Intra-engine parallelism (sharded serving over a device mesh).
    p.add_argument("--tp", type=int, default=1, help="tensor parallel size")
    p.add_argument("--dp", type=int, default=1, help="data parallel size (within this process's mesh)")
    p.add_argument("--ep", type=int, default=1, help="expert parallel size")
    p.add_argument("--pp", type=int, default=1, help="pipeline parallel size")
    # Multi-host (ref: MultiNodeConfig engines.rs:28): either pass explicit
    # --num-processes/--process-id/--coordinator, or just --num-processes
    # and let store-based rendezvous elect ranks + coordinator.
    p.add_argument("--num-processes", type=int, default=1)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--coordinator", default=None, help="leader host:port for jax.distributed")
    p.add_argument("--multihost-group", default="default")
    # Request tracing (runtime/tracing.py): JSONL span export + sampling.
    # Defaults come from DYN_TRACE_FILE / DYN_TRACE_SAMPLE.
    p.add_argument("--trace-file", default=None, help="JSONL span export path (enables tracing)")
    p.add_argument("--trace-sample", type=float, default=None,
                   help="trace sampling ratio in [0,1]; decision is per-trace-id (default 1.0)")
    p.add_argument("--trace-ring", type=int, default=None,
                   help="in-memory trace black-box depth in records (default 256; 0 disables; "
                        "incident bundles capture this ring even with no trace file)")
    p.add_argument("--trace-tail", action="store_true",
                   help="tail-based keep: unsampled traces still record into the ring so "
                        "SLO-violating requests can be promoted to the export after the fact")
    # Incident autopsy plane (runtime/incidents.py): anomaly-triggered
    # black-box bundles + optional per-incident device profile.
    p.add_argument("--incident-dir", default=None,
                   help="write anomaly-triggered incident bundles here "
                        "(default DYN_INCIDENT_DIR; unset = detect + count only)")
    p.add_argument("--incident-keep", type=int, default=16,
                   help="LRU retention cap on incident bundle files")
    p.add_argument("--profile-on-incident", action="store_true",
                   help="attach a short jax.profiler device capture to each incident bundle")
    # Continuous device-truth sampler (runtime/profiling.py): short profiler
    # windows at a bounded duty cycle feed measured MFU / per-kernel top-N
    # siblings of the modeled roofline gauges.
    p.add_argument("--no-continuous-profiling", action="store_true",
                   help="disarm the background device-truth sampler")
    p.add_argument("--profile-window-s", type=float, default=0.25,
                   help="continuous sampler capture window (seconds)")
    p.add_argument("--profile-interval-s", type=float, default=30.0,
                   help="seconds between continuous capture windows (duty-cycle-clamped)")
    p.add_argument("--profile-dir", default=None,
                   help="artifact root for all device captures (default DYN_PROFILE_DIR)")
    p.add_argument("--warmup-ctx", type=int, default=0,
                   help="precompile serving executables for contexts up to this many tokens "
                        "(0 = lazy; the flight recorder then counts mid-traffic compiles)")
    # SLA telemetry (runtime/telemetry.py): per-request SLO judgments +
    # goodput accounting against these engine-side latency targets.
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   help="TTFT SLO target in ms (enables slo_*/goodput counters)")
    p.add_argument("--slo-tpot-ms", type=float, default=None,
                   help="per-output-token latency SLO target in ms")
    p.add_argument("--stall-after-s", type=float, default=120.0,
                   help="watchdog: step loop idle this long with work queued => engine_stalled")
    p.add_argument("--health-port", type=int, default=None,
                   help="serve /health + /metrics + /debug/state on this port (0 = ephemeral)")
    # Chaos plane (runtime/faults.py): deterministic fault injection for
    # drills and the chaos test suite. Off unless armed.
    p.add_argument("--fault-scenario", default=None,
                   help="arm the fault injector: inline JSON or @/path/to/scenario.json "
                        "(DYN_FAULTS env is the default)")
    return p


async def amain(args) -> None:
    drt = await DistributedRuntime.from_settings()
    drt.runtime.install_signal_handlers()

    if args.role == "encode":
        # Multimodal encode worker (ref: trtllm encode_helper.py): serves
        # image → embedding features for the LM pool's prefill injection.
        from dynamo_tpu.llm.multimodal import EncodeWorkerHandler, LocalVisionEncoder

        handler = EncodeWorkerHandler(LocalVisionEncoder(preset=args.vision_model, seed=args.vision_seed))
        ep = drt.namespace(args.namespace).component(args.component or "encode").endpoint(args.endpoint)
        handle = await ep.serve_endpoint(handler.generate, stats_handler=handler.stats_handler)
        logger.info("encode worker ready: vision=%s instance=%x", args.vision_model, handle.instance.instance_id)
        try:
            await drt.runtime.cancellation.cancelled()
        finally:
            await drt.shutdown()
        return

    if args.num_processes > 1:
        # Join the multi-controller runtime BEFORE any jax backend touch.
        from dynamo_tpu.engine.multihost import MultiHostConfig, init_multihost, rendezvous

        if args.process_id is not None and args.coordinator:
            mh = MultiHostConfig(args.num_processes, args.process_id, args.coordinator)
        else:
            mh = await rendezvous(drt, args.multihost_group, args.num_processes)
        init_multihost(mh)

    if args.mocker:
        engine = MockTpuEngine(
            MockEngineArgs(
                num_blocks=args.num_blocks, block_size=args.block_size,
                speedup_ratio=args.speedup_ratio,
                slo_ttft_ms=args.slo_ttft_ms, slo_tpot_ms=args.slo_tpot_ms,
            )
        )
    else:
        parallel = None
        if args.tp * args.dp * args.ep * args.pp > 1:
            from dynamo_tpu.engine.sharding import ParallelConfig

            parallel = ParallelConfig(tp=args.tp, dp=args.dp, ep=args.ep, pp=args.pp)
        from dynamo_tpu.llm.tokenizer import load_tokenizer

        engine = TpuEngine.build(
            EngineArgs(
                model=args.model,
                dtype=args.dtype,
                checkpoint_path=args.checkpoint,
                # Guided decoding compiles token FSMs against the SAME
                # tokenizer the frontend detokenizes with (the model card's).
                tokenizer=load_tokenizer(args.tokenizer),
                kvbm_host_blocks=args.kvbm_host_blocks,
                kvbm_disk_dir=args.kvbm_disk_dir,
                kvbm_disk_blocks=args.kvbm_disk_blocks,
                scheduler=SchedulerConfig(
                    num_blocks=args.num_blocks, max_running=args.max_running,
                    slo_ttft_ms=args.slo_ttft_ms, slo_tpot_ms=args.slo_tpot_ms,
                    stall_after_s=args.stall_after_s,
                ),
                parallel=parallel,
                draft_model=args.draft_model,
                draft_checkpoint_path=args.draft_checkpoint,
                spec_gamma=args.spec_gamma,
                kv_cache_dtype=args.kv_cache_dtype,
                weight_dtype=args.weight_dtype,
                warmup_ctx=args.warmup_ctx,
                incident_dir=args.incident_dir,
                incident_keep=args.incident_keep,
                profile_on_incident=args.profile_on_incident,
                continuous_profiling=not args.no_continuous_profiling,
                profile_window_s=args.profile_window_s,
                profile_interval_s=args.profile_interval_s,
                profile_dir=args.profile_dir,
            )
        )
        if args.kvbm_remote and getattr(engine, "kvbm", None) is not None:
            from dynamo_tpu.llm.block_manager.storage import RemotePool

            engine.kvbm.attach_remote(RemotePool(drt, asyncio.get_running_loop()))

    component = args.component or ("backend" if args.role == "aggregated" else args.role)
    ep = drt.namespace(args.namespace).component(component).endpoint(args.endpoint)

    handler = engine
    disagg_router = None
    prefill_client = None
    if args.role == "decode":
        prefill_ep = drt.namespace(args.namespace).component("prefill").endpoint(args.endpoint)
        prefill_client = await prefill_ep.client()
        disagg_router = DisaggRouter(
            drt, args.served_model_name or args.model,
            conf=DisaggRouterConf(max_local_prefill_length=args.max_local_prefill_length),
        )
        await disagg_router.start()
        handler = DisaggDecodeHandler(
            drt, engine, prefill_client, disagg_router, kv_transfer=args.kv_transfer
        )

    card = ModelDeploymentCard(
        name=args.served_model_name or args.model,
        model_type="chat",
        tokenizer_path=args.tokenizer,
        kv_cache_block_size=args.block_size,
    )
    stats = handler.stats_handler if hasattr(handler, "stats_handler") else None
    if args.role == "prefill":
        # Prefill workers serve the internal pool, not public model discovery.
        handle = await ep.serve_endpoint(engine.generate, stats_handler=stats)
    else:
        handle, _ = await register_llm(drt, ep, handler, card, stats_handler=stats)

    worker_id = handle.instance.instance_id
    kv_pub = KvEventPublisher(drt, args.namespace, component, worker_id)
    kv_pub.start()
    loop = asyncio.get_running_loop()
    if args.mocker:
        engine.set_kv_event_sink(kv_pub.publish)
    else:
        # Engine KV events fire on the scheduler thread — hop to the loop.
        engine._kv_event_sink = lambda ev: kv_pub.publish_threadsafe(loop, ev)
    m_pub = WorkerMetricsPublisher(drt, args.namespace, component, worker_id, engine.metrics)
    m_pub.start()
    publishers = [kv_pub, m_pub]

    kvx = None
    if args.role == "prefill":
        kvx = KvExportService(drt, engine, handle.instance)
        await kvx.start()

    # Health + live-introspection server: /health readiness includes engine
    # liveness (stall watchdog, compiles-after-warmup, last-step age) and
    # /debug/state dumps the scheduler's live view (sequences, block pool,
    # digests, step timeline).
    status_server = None
    if args.health_port is not None:
        from dynamo_tpu.runtime.config import SystemConfig
        from dynamo_tpu.runtime.health import SystemHealth, SystemStatusServer

        health = SystemHealth()
        health.set_system_ready()
        if hasattr(engine, "watchdog"):
            health.attach_engine(
                lambda: {
                    **engine.watchdog.to_stats(),
                    "compiles_after_warmup_total":
                        engine.scheduler.flight.compiles_after_warmup_total,
                }
            )
        # On-demand device profiling (POST /debug/profile?seconds=N): every
        # capture path must share ONE DeviceProfiler (its capture lock is
        # the serialization point vs incident and continuous captures), so
        # prefer the engine's, then the incident plane's, then a fresh one.
        incidents = getattr(engine, "incidents", None)
        profiler = getattr(engine, "profiler", None)
        if profiler is None and incidents is not None:
            profiler = incidents.profiler
        if profiler is None:
            from dynamo_tpu.runtime.profiling import DeviceProfiler

            profiler = DeviceProfiler()
            if incidents is not None:
                incidents.profiler = profiler
        async def drain_and_exit() -> None:
            # The drain lifecycle (POST /drain; SIGTERM takes the same path
            # through drt.shutdown → ServeHandle.stop): deregister from
            # discovery, stop admitting, finish or migrate in-flight work
            # within shutdown_timeout_s, flush traces, then exit.
            logger.warning("drain requested for instance %x", worker_id)
            health.system_status = "notready"
            await handle.stop(drain=True)
            from dynamo_tpu.runtime.tracing import get_tracer

            get_tracer().flush()
            drt.runtime.trigger_shutdown()

        status_server = SystemStatusServer(
            health,
            config=SystemConfig(enabled=True, port=args.health_port, host="0.0.0.0"),
            state_probe=getattr(engine, "debug_state", None),
            profiler=profiler,
            drain_cb=drain_and_exit,
        )
        await status_server.start()

    logger.info("worker ready: role=%s model=%s instance=%x", args.role, card.name, worker_id)
    try:
        await drt.runtime.cancellation.cancelled()
    finally:
        if status_server is not None:
            await status_server.stop()
        for pub in publishers:
            await pub.stop()
        if kvx is not None:
            await kvx.stop()
        if disagg_router is not None:
            await disagg_router.stop()
        if hasattr(engine, "stop"):
            await engine.stop()
        await drt.shutdown()


def main() -> None:
    init_logging()
    args = build_parser().parse_args()
    from dynamo_tpu.runtime.tracing import configure_tracing

    configure_tracing(path=args.trace_file, sample=args.trace_sample,
                      service=f"worker-{args.role}",
                      ring_size=args.trace_ring, tail=args.trace_tail or None)
    from dynamo_tpu.runtime import faults

    if args.fault_scenario:
        faults.arm_from_spec(args.fault_scenario)
    else:
        faults.maybe_arm_from_env()
    try:
        asyncio.run(amain(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
