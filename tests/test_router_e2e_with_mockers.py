"""Router e2e against mocker fleets (ref:
tests/router/test_router_e2e_with_mockers.py:50-80 — N mockers + real router,
verify KV-routing behavior end-to-end over the real wire path)."""

import asyncio
import json

import pytest

from dynamo_tpu.engine.kv_cache import KvEvent
from dynamo_tpu.llm.kv_router import (
    KvEventPublisher,
    KvPushRouter,
    KvRouterConfig,
    WorkerMetricsPublisher,
)
from dynamo_tpu.llm.mocker import MockEngineArgs, MockTpuEngine
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context


async def spawn_mocker(drt, ep, *, speedup=50.0):
    """Serve one mocker on the endpoint with KV event + metrics publishing."""
    engine = MockTpuEngine(MockEngineArgs(speedup_ratio=speedup, num_blocks=128))
    handle = await ep.serve_endpoint(engine.generate, stats_handler=engine.stats_handler)
    worker_id = handle.instance.instance_id
    publisher = KvEventPublisher(drt, ep.namespace, ep.component, worker_id)
    publisher.start()
    loop = asyncio.get_running_loop()
    engine.set_kv_event_sink(lambda ev: publisher.publish(ev))
    metrics_pub = WorkerMetricsPublisher(drt, ep.namespace, ep.component, worker_id, engine.metrics, interval_s=0.1)
    metrics_pub.start()
    # Force wire path: requests go through pub/sub + TCP like real deployments.
    drt.local_engines.pop(worker_id)
    return engine, handle, publisher, metrics_pub


def req(tokens, max_tokens=4):
    return {
        "token_ids": tokens,
        "sampling_options": {"temperature": 0.0},
        "stop_conditions": {"max_tokens": max_tokens},
    }


async def test_kv_routing_prefers_warm_worker():
    """Same-prefix requests should converge onto the worker that cached the
    prefix; the router must learn this from KV events alone."""
    drt = await DistributedRuntime.detached()
    cleanup = []
    try:
        ep = drt.namespace("kvtest").component("mocker").endpoint("generate")
        for _ in range(2):
            cleanup.append(await spawn_mocker(drt, ep))

        client = await ep.client()
        await client.wait_for_instances(2, timeout=5)
        router = await KvPushRouter.create(client, KvRouterConfig(block_size=16))
        cleanup_router = router

        prefix = list(range(64))  # 4 blocks

        async def run_one(tokens):
            got = []
            async for item in router.generate(req(tokens), Context()):
                if item.data:
                    got.append(item.data)
            return got

        # First request lands somewhere; its KV events register the prefix.
        await run_one(prefix)
        await asyncio.sleep(0.2)  # let events flow into the indexer

        scores = router.indexer.find_matches_for_tokens(prefix)
        assert scores.scores, "router index must have learned the prefix"
        warm = max(scores.scores, key=scores.scores.get)

        # Follow-ups with the same prefix must all go to the warm worker.
        decisions = []
        for i in range(6):
            d = await router.schedule(prefix + list(range(100 + i, 104 + i)))
            decisions.append(d.worker)
        assert all(w == warm for w in decisions), (decisions, warm)

        # A cold different prefix should go to the other (idle) worker.
        cold_prefix = list(range(5000, 5064))
        d = await router.schedule(cold_prefix)
        assert d.overlap_blocks == 0

        await cleanup_router.close()
    finally:
        for engine, handle, pub, mpub in cleanup:
            await pub.stop()
            await mpub.stop()
        await drt.shutdown()


async def test_kv_routing_many_requests_spread_and_complete():
    """100 requests across 2 mockers (ref test sends 100): all complete, both
    workers get work, allocator fully drains."""
    drt = await DistributedRuntime.detached()
    cleanup = []
    try:
        ep = drt.namespace("kvtest2").component("mocker").endpoint("generate")
        for _ in range(2):
            cleanup.append(await spawn_mocker(drt, ep, speedup=200.0))
        client = await ep.client()
        await client.wait_for_instances(2, timeout=5)
        router = await KvPushRouter.create(client, KvRouterConfig(block_size=16))

        async def run_one(i):
            # 10 distinct prefixes → reuse within a group, spread across groups.
            group = i % 10
            tokens = list(range(group * 100, group * 100 + 48))
            n = 0
            async for item in router.generate(req(tokens, max_tokens=3), Context()):
                if item.data and item.data.get("token_ids"):
                    n += len(item.data["token_ids"])
            return n

        results = await asyncio.gather(*(run_one(i) for i in range(100)))
        assert all(n == 3 for n in results)

        served = [c[0].request_total for c in cleanup]
        assert sum(served) == 100
        assert all(s > 0 for s in served), f"load should spread: {served}"
        # All in-flight state drained.
        assert all(c[0].allocator.num_active == 0 for c in cleanup)
        await router.close()
    finally:
        for engine, handle, pub, mpub in cleanup:
            await pub.stop()
            await mpub.stop()
        await drt.shutdown()


async def test_worker_death_reroutes():
    drt = await DistributedRuntime.detached()
    cleanup = []
    try:
        ep = drt.namespace("kvtest3").component("mocker").endpoint("generate")
        for _ in range(2):
            cleanup.append(await spawn_mocker(drt, ep))
        client = await ep.client()
        await client.wait_for_instances(2, timeout=5)
        router = await KvPushRouter.create(client, KvRouterConfig(block_size=16))

        prefix = list(range(64))
        d1 = await router.schedule(prefix)
        # Kill the scheduled worker.
        victim = next(c for c in cleanup if c[1].instance.instance_id == d1.worker)
        await victim[1].stop()
        for _ in range(100):
            if len(client.instances) == 1:
                break
            await asyncio.sleep(0.02)

        d2 = await router.schedule(prefix)
        assert d2.worker != d1.worker
        # Dead worker fully purged from router state.
        assert d1.worker not in router.sequences._prefill_tokens
        await router.close()
    finally:
        for engine, handle, pub, mpub in cleanup:
            await pub.stop()
            await mpub.stop()
        await drt.shutdown()


async def test_snapshot_restore_and_purge():
    """Radix snapshot uploads at the threshold; a fresh router restores it
    (ref: subscriber.rs snapshot/purge design)."""
    drt = await DistributedRuntime.detached()
    cleanup = []
    try:
        ep = drt.namespace("kvsnap").component("mocker").endpoint("generate")
        cleanup.append(await spawn_mocker(drt, ep))
        client = await ep.client()
        await client.wait_for_instances(1, timeout=5)
        router = await KvPushRouter.create(
            client, KvRouterConfig(block_size=16, snapshot_threshold=2)
        )
        for g in range(4):
            tokens = list(range(g * 1000, g * 1000 + 32))
            async for _ in router.generate(req(tokens, max_tokens=2), Context()):
                pass
        await asyncio.sleep(0.3)  # events consumed + snapshot triggered

        from dynamo_tpu.llm.kv_router.subscriber import RADIX_STATE_BUCKET

        bucket = await drt.bus.object_store(RADIX_STATE_BUCKET)
        names = await bucket.list()
        assert names, "snapshot should have been uploaded"

        # New router replica restores from snapshot without replaying purged events.
        router2 = await KvPushRouter.create(client, KvRouterConfig(block_size=16))
        assert router2.indexer.tree.size() > 0
        await router.close()
        await router2.close()
    finally:
        for engine, handle, pub, mpub in cleanup:
            await pub.stop()
            await mpub.stop()
        await drt.shutdown()


@pytest.mark.soak
async def test_soak_churn_8_mockers_kill_join_under_load():
    """Soak (VERDICT r2 #8): 8-mocker fleet with the sharded indexer +
    prefill counters + snapshotting active; mid-load one worker is killed
    and a fresh one joins; assert ZERO lost requests, bounded index
    staleness (dead worker purged from router state), and full drain."""
    drt = await DistributedRuntime.detached()
    cleanup = []
    try:
        ep = drt.namespace("kvsoak").component("mocker").endpoint("generate")
        for _ in range(8):
            cleanup.append(await spawn_mocker(drt, ep, speedup=300.0))
        client = await ep.client()
        await client.wait_for_instances(8, timeout=10)
        router = await KvPushRouter.create(
            client,
            KvRouterConfig(block_size=16, num_indexer_shards=4,
                           track_prefill_counters=True, snapshot_threshold=50),
        )

        completed = []
        failed = []

        async def run_one(i):
            group = i % 16
            tokens = list(range(group * 200, group * 200 + 48))
            try:
                n = 0
                async for item in router.generate(req(tokens, max_tokens=4), Context()):
                    if item.data and item.data.get("token_ids"):
                        n += len(item.data["token_ids"])
                completed.append(n)
            except Exception as e:  # noqa: BLE001 — count, don't mask
                failed.append((i, repr(e)))

        async def churn():
            # Mid-load: kill worker 0, then join a fresh one.
            await asyncio.sleep(0.15)
            engine, handle, pub, mpub = cleanup[0]
            victim_id = handle.instance.instance_id
            await handle.stop()
            await pub.stop()
            await mpub.stop()
            await asyncio.sleep(0.15)
            cleanup.append(await spawn_mocker(drt, ep, speedup=300.0))
            return victim_id

        load = [asyncio.create_task(run_one(i)) for i in range(160)]
        churn_task = asyncio.create_task(churn())
        await asyncio.gather(*load)
        victim_id = await churn_task

        # No lost requests: every request completed with all its tokens.
        assert not failed, failed[:5]
        assert len(completed) == 160 and all(n == 4 for n in completed)

        # Bounded staleness: worker-set sync happens at scheduling decisions,
        # so one post-churn round must purge the dead worker from live state.
        post = [asyncio.create_task(run_one(1000 + i)) for i in range(8)]
        await asyncio.gather(*post)
        assert len(completed) == 168 and not failed, (len(completed), failed[:3])
        assert victim_id not in router.sequences._prefill_tokens

        # The joined worker is routable.
        assert len(client.instances) == 8

        # Sharded indexer holds learned prefixes across the churn.
        router.indexer.flush()
        assert router.indexer.size() > 0

        # All engines fully drained (no leaked blocks).
        for engine, handle, pub, mpub in cleanup:
            assert engine.allocator.num_active == 0
        await router.close()
    finally:
        for engine, handle, pub, mpub in cleanup:
            await pub.stop()
            await mpub.stop()
        await drt.shutdown()


async def test_cached_tokens_accounting_over_wire():
    """Prefix-cache hit accounting must flow engine→router over the real
    wire path: the mocker reports cached_tokens on its first frame, the
    router folds it into per-worker reuse accounting, and the totals match
    the workers' own counters."""
    drt = await DistributedRuntime.detached()
    cleanup = []
    try:
        ep = drt.namespace("kvcached").component("mocker").endpoint("generate")
        for _ in range(2):
            cleanup.append(await spawn_mocker(drt, ep))
        client = await ep.client()
        await client.wait_for_instances(2, timeout=5)
        router = await KvPushRouter.create(client, KvRouterConfig(block_size=16))

        prefix = list(range(64))  # 4 blocks

        async def run_one(tokens):
            async for item in router.generate(req(tokens), Context()):
                pass

        # Cold establishment, then same-prefix follow-ups that must hit.
        await run_one(prefix + [900, 901])
        await asyncio.sleep(0.2)  # KV events → indexer
        for i in range(4):
            await run_one(prefix + [1000 + i, 2000 + i])

        stats = router.stats()
        # 4 follow-ups × 4 shared blocks × 16 tokens.
        assert stats["cached_tokens_total"] == 4 * 4 * 16, stats
        assert stats["cached_tokens_total"] == sum(
            c[0].cached_tokens_total for c in cleanup
        )
        assert sum(stats["cached_tokens_by_worker"].values()) == stats["cached_tokens_total"]
        # Predicted overlap (index) is closed-loop with the engine's report.
        assert stats["predicted_cached_tokens_total"] >= stats["cached_tokens_total"]
        # The scrape path exposes the same accounting keys.
        wire_stats = [c[0].stats_handler() for c in cleanup]
        assert sum(s["cached_tokens_total"] for s in wire_stats) == stats["cached_tokens_total"]
        assert sum(s["prefix_hit_blocks_total"] for s in wire_stats) >= 16
        await router.close()
    finally:
        for engine, handle, pub, mpub in cleanup:
            await pub.stop()
            await mpub.stop()
        await drt.shutdown()
