"""The Grafana dashboard must reference only metric families the code
actually registers (panels silently show 'no data' otherwise — the failure
mode that makes dashboards rot)."""

import json
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dashboard_metrics_exist_in_code():
    with open(os.path.join(REPO, "deploy", "grafana", "dynamo_tpu_serving.json")) as f:
        dash = json.load(f)
    assert dash["panels"], "dashboard has no panels"
    exprs = [t["expr"] for p in dash["panels"] for t in p["targets"]]
    families = set()
    for e in exprs:
        for m in re.findall(r"dynamo_[a-z_]+", e):
            families.add(re.sub(r"_(bucket|sum|count)$", "", m))

    # Registered names: frontend metrics in llm/http/service.py (prefix
    # dynamo_frontend_), worker fields forwarded by metrics_aggregator
    # (prefix dynamo_component_).
    src = open(os.path.join(REPO, "dynamo_tpu", "llm", "http", "service.py")).read()
    agg = open(os.path.join(REPO, "dynamo_tpu", "metrics_aggregator.py")).read()
    for fam in families:
        if fam.startswith("dynamo_frontend_"):
            short = fam[len("dynamo_frontend_"):]
            assert f'"{short}"' in src, f"dashboard references unregistered {fam}"
        elif fam.startswith("dynamo_component_"):
            short = fam[len("dynamo_component_"):]
            assert short in agg, f"dashboard references unforwarded {fam}"
        else:
            raise AssertionError(f"unknown metric prefix: {fam}")
