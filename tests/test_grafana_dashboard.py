"""The Grafana dashboard must reference only metric families the code
actually registers (panels silently show 'no data' otherwise — the failure
mode that makes dashboards rot)."""

import json
import os
import re

from dynamo_tpu.metrics_aggregator import (
    COUNTER_KEYS,
    DIGEST_KEYS,
    FLEET_DIGEST_PREFIX,
    GAUGE_KEYS,
    TENANT_FAMILY_BY_DIM,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Fleet-merged per-tenant counter families the aggregator exports from the
# merged ledger sketches (labeled by tenant, plus tenant+phase for SLO).
TENANT_FLEET_FAMILIES = set(TENANT_FAMILY_BY_DIM.values()) | {
    "tenant_slo_violated_total",
    "tenant_slo_attained_total",
}


def _component_families():
    """Exact family names the aggregator exports (prometheus_client strips a
    Counter's ``_total`` from the family name and re-appends it on the
    sample, so the PromQL-visible name keeps the suffix)."""
    fams = {"dynamo_component_workers"}
    for key in GAUGE_KEYS:
        fams.add(f"dynamo_component_worker_{key}")
    for key in COUNTER_KEYS:
        fams.add(f"dynamo_component_worker_{key}")
        if not key.endswith("_total"):
            fams.add(f"dynamo_component_worker_{key}_total")
    # Fleet-merged digest re-exports (DigestCollector): native histogram +
    # quantile-gauge families per digest stream.
    for key in DIGEST_KEYS:
        fams.add(f"{FLEET_DIGEST_PREFIX}{key}_seconds")
        fams.add(f"{FLEET_DIGEST_PREFIX}{key}_seconds_quantile")
    # Fleet-merged per-tenant families (MetricsAggregator._export_tenant_families).
    for key in TENANT_FLEET_FAMILIES:
        fams.add(f"dynamo_component_{key}")
    return fams


def test_dashboard_metrics_exist_in_code():
    with open(os.path.join(REPO, "deploy", "grafana", "dynamo_tpu_serving.json")) as f:
        dash = json.load(f)
    assert dash["panels"], "dashboard has no panels"
    exprs = [t["expr"] for p in dash["panels"] for t in p["targets"]]
    families = set()
    for e in exprs:
        # Digits are legitimate in family names (incidents_ttft_p99_total);
        # same character class as dtlint's MET001 grafana scan.
        for m in re.findall(r"dynamo_[a-z0-9_]+", e):
            families.add(re.sub(r"_(bucket|sum|count)$", "", m))

    # Frontend metrics are registered in llm/http/service.py (prefix
    # dynamo_frontend_); worker stats are forwarded by metrics_aggregator
    # (prefix dynamo_component_worker_* from GAUGE_KEYS/COUNTER_KEYS).
    src = open(os.path.join(REPO, "dynamo_tpu", "llm", "http", "service.py")).read()
    component_fams = _component_families()
    for fam in families:
        if fam.startswith("dynamo_frontend_"):
            short = fam[len("dynamo_frontend_"):]
            assert f'"{short}"' in src, f"dashboard references unregistered {fam}"
        elif fam.startswith("dynamo_component_"):
            assert fam in component_fams, f"dashboard references unforwarded {fam}"
        else:
            raise AssertionError(f"unknown metric prefix: {fam}")


def test_dashboard_counters_use_rate_friendly_names():
    """Every ``*_total`` family the dashboard rates must be a COUNTER_KEYS
    export (a Gauge under a ``_total`` name breaks PromQL rate())."""
    with open(os.path.join(REPO, "deploy", "grafana", "dynamo_tpu_serving.json")) as f:
        dash = json.load(f)
    rated = set()
    for p in dash["panels"]:
        for t in p["targets"]:
            for m in re.findall(r"(?:rate|increase)\((dynamo_component_[a-z0-9_]+_total)\b", t["expr"]):
                rated.add(m)
    assert rated, "dashboard should rate() at least one worker counter"
    counter_fams = {f"dynamo_component_worker_{k}" for k in COUNTER_KEYS}
    counter_fams |= {f"dynamo_component_{k}" for k in TENANT_FLEET_FAMILIES}
    for fam in rated:
        assert fam in counter_fams, f"{fam} is rate()d but not exported as a Counter"
