"""Automatic prefix caching: engine-level KV block reuse.

Covers the acceptance bar for the prefix-cache tentpole: cached-vs-cold
parity (token streams AND KV block contents bit-identical), copy-on-write
divergence (a full-cover hit must not write into a block another live
sequence references), LRU eviction under memory pressure with in-use blocks
pinned, the DRAM (KVBM G2) onboard hit path, and the
0-post-warmup-XLA-compiles invariant with prefix caching enabled.
"""

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions
from dynamo_tpu.llm.block_manager import KvBlockManager
from dynamo_tpu.llm.block_manager.transfer import gather_blocks

CFG = get_config("tiny")
BS = CFG.block_size
PARAMS = llama.init_params(CFG, jax.random.PRNGKey(3), dtype=jnp.float32)


def make_sched(num_blocks=256, caching=True, **kw):
    sc = SchedulerConfig(
        num_blocks=num_blocks,
        prefill_buckets=[32, 64, 128],
        decode_buckets=[1, 2, 4],
        enable_prefix_caching=caching,
        num_scheduler_steps=1,
        **kw,
    )
    return Scheduler(CFG, PARAMS, sc, dtype=jnp.float32)


def run_one(sched, rid, prompt, max_tokens=6):
    """Serve one request to completion; returns (tokens, cached_tokens,
    prompt block ids snapshotted at first token)."""
    sched.add_request(rid, prompt, SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=max_tokens, ignore_eos=True))
    tokens, cached, block_ids = [], None, None
    for _ in range(400):
        for s, o in sched.step():
            if s.request_id != rid:
                continue
            if o.cached_tokens is not None:
                cached = o.cached_tokens
                block_ids = list(s.block_ids)
            if o.token_id >= 0:
                tokens.append(o.token_id)
        if not sched.has_work():
            break
    assert not sched.has_work()
    return tokens, cached, block_ids


def prompt_kv(sched, block_ids, n_tokens):
    """Host copy of the KV rows covering the first n_tokens behind the
    given block table."""
    rows_k, rows_v = [], []
    for bid in block_ids[: (n_tokens + BS - 1) // BS]:
        k, v = gather_blocks(sched.cache, bid)
        rows_k.append(k)
        rows_v.append(v)
    k = np.concatenate(rows_k, axis=1)[:, :n_tokens]
    v = np.concatenate(rows_v, axis=1)[:, :n_tokens]
    return k, v


def test_cached_vs_cold_parity_tokens_and_kv():
    """A full-prefix hit must produce bit-identical outputs AND KV to a
    cold run: reuse skips compute, never changes results."""
    prompt = list(range(1, 97))  # 96 = 6 full blocks → full-cover hit
    cold = make_sched(caching=False)
    t_cold, _, b_cold = run_one(cold, "cold", prompt)
    kv_cold = prompt_kv(cold, b_cold, len(prompt))

    sched = make_sched()
    t1, c1, b1 = run_one(sched, "r1", prompt)
    t2, c2, b2 = run_one(sched, "r2", prompt)
    assert t1 == t_cold and t2 == t_cold
    assert c1 == 0
    # Full cover: every prompt token but the recomputed last one is served
    # from cache.
    assert c2 == len(prompt) - 1
    kv_hit = prompt_kv(sched, b2, len(prompt))
    # Cached rows are the SAME buffers the cold path wrote — bit-identical.
    np.testing.assert_array_equal(kv_hit[0][:, :-1], kv_cold[0][:, :-1])
    np.testing.assert_array_equal(kv_hit[1][:, :-1], kv_cold[1][:, :-1])
    # The one recomputed row (logits producer) runs in a different-bucket
    # executable — numerically equal up to f32 reduction order.
    np.testing.assert_allclose(kv_hit[0][:, -1], kv_cold[0][:, -1], atol=1e-5, rtol=1e-4)
    np.testing.assert_allclose(kv_hit[1][:, -1], kv_cold[1][:, -1], atol=1e-5, rtol=1e-4)


def test_partial_prefix_hit_prefills_only_suffix():
    shared = list(range(1, 81))  # 5 full blocks
    sched = make_sched()
    t1, _, _ = run_one(sched, "a", shared + list(range(500, 532)))
    t2, c2, _ = run_one(sched, "b", shared + list(range(700, 732)))
    assert c2 == (len(shared) // BS) * BS  # 80 tokens skipped
    # Parity with an uncached run of the same prompt.
    cold = make_sched(caching=False)
    t2_cold, _, _ = run_one(cold, "b", shared + list(range(700, 732)))
    assert t2 == t2_cold


def test_copy_on_write_divergence():
    """A full-cover hit whose final matched block another RUNNING sequence
    still references must copy-on-write: the holder's block is untouched,
    both sequences produce reference outputs."""
    prompt = list(range(1, 97))
    # Reference streams, computed on isolated schedulers.
    ref = make_sched(caching=False)
    a_ref, _, _ = run_one(ref, "a", prompt, max_tokens=20)
    b_ref = run_one(make_sched(caching=False), "b", prompt, max_tokens=4)[0]

    sched = make_sched(enable_mixed_batching=False)
    sched.add_request("a", prompt, SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=20, ignore_eos=True))
    got = {"a": [], "b": []}
    a_blocks = None
    # Run A through prefill + a few decode steps so it HOLDS its blocks.
    for _ in range(6):
        for s, o in sched.step():
            if o.token_id >= 0:
                got[s.request_id].append(o.token_id)
    a_blocks = list(sched.by_id["a"].block_ids)
    a_last_kv = gather_blocks(sched.cache, a_blocks[5])
    # B arrives with the SAME prompt while A runs: full-cover match, last
    # block shared with a live holder → COW.
    sched.add_request("b", prompt, SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=4, ignore_eos=True))
    b_blocks = None
    for _ in range(400):
        for s, o in sched.step():
            if o.token_id >= 0:
                got[s.request_id].append(o.token_id)
            if s.request_id == "b" and b_blocks is None and o.cached_tokens is not None:
                b_blocks = list(s.block_ids)
        if not sched.has_work():
            break
    assert not sched.has_work()
    assert sched.cow_blocks_total == 1
    # Shared prefix blocks identical, final prompt block diverged (private
    # copy), and A's original block content is untouched.
    assert b_blocks[:5] == a_blocks[:5]
    assert b_blocks[5] != a_blocks[5]
    after = gather_blocks(sched.cache, a_blocks[5])
    np.testing.assert_array_equal(after[0], a_last_kv[0])
    np.testing.assert_array_equal(after[1], a_last_kv[1])
    assert got["a"] == a_ref
    assert got["b"] == b_ref


def test_eviction_under_pressure_pins_in_use_blocks():
    """Cache churn under a tight pool evicts only refcount-0 cached blocks;
    a running sequence's blocks are pinned and its output is unaffected."""
    ref = make_sched(num_blocks=256)
    long_ref, _, _ = run_one(ref, "long", list(range(1, 49)), max_tokens=60)

    sched = make_sched(num_blocks=20)  # 19 usable
    sched.add_request("long", list(range(1, 49)), SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=60, ignore_eos=True))
    got: dict = {}
    filler = 0
    for i in range(900):
        # Keep injecting distinct prompts so the pool churns: each registers
        # blocks that must be evicted to admit the next.
        if i % 3 == 0 and len(sched.waiting) < 2 and sched.by_id.get("long") is not None:
            filler += 1
            sched.add_request(f"f{filler}", list(range(100 * filler, 100 * filler + 33)),
                              SamplingParams(temperature=0.0),
                              StopConditions(max_tokens=2, ignore_eos=True))
        for s, o in sched.step():
            if o.token_id >= 0:
                got.setdefault(s.request_id, []).append(o.token_id)
        if "long" not in sched.by_id and not sched.has_work():
            break
    assert got["long"] == long_ref
    assert sched.allocator.evicted_blocks_total > 0
    # Pool bookkeeping intact after the churn: nothing double-freed.
    sched_ids = set(sched.allocator._free) | set(sched.allocator._cached_lru)
    assert len(sched.allocator._free) == len(set(sched.allocator._free))
    assert len(sched_ids) <= sched.allocator.num_blocks


def test_dram_onboard_hit_path():
    """Blocks evicted HBM→DRAM (KVBM G2) stay indexed: a later request
    onboards them back and still skips prefill, with parity."""
    sched = make_sched(num_blocks=16)  # 15 usable — tight
    kvbm = KvBlockManager(sched.cache, sched.allocator, host_blocks=32)
    sched.attach_kvbm(kvbm)

    prompt = list(range(1, 81)) + list(range(900, 916))  # 6 blocks + slack
    t1, c1, _ = run_one(sched, "p1", prompt, max_tokens=2)
    assert c1 == 0
    # Churn the pool so p1's cached blocks evict → offload to the host tier.
    for i in range(3):
        run_one(sched, f"f{i}", list(range(200 * (i + 1), 200 * (i + 1) + 81)), max_tokens=2)
    kvbm.flush_pending()
    assert kvbm.metrics.offloads_g2 > 0

    t2, c2, _ = run_one(sched, "p2", prompt, max_tokens=2)
    assert t2 == t1
    assert c2 and c2 > 0, "onboarded blocks must count as cached tokens"
    assert sched.prefix_onboard_total > 0
    assert kvbm.metrics.onboards_g2 > 0
    m = sched.metrics()
    assert m.prefix_onboard_total == sched.prefix_onboard_total


def test_zero_postwarmup_compiles_with_prefix_caching():
    """Warmup must cover the prefix-cache serving set: cold prefill,
    full-cover hit (COW block copy), partial hit continuation, and decode —
    all with 0 XLA compiles after warmup (flight-recorder-verified)."""
    sched = make_sched(enable_mixed_batching=False)
    sched.warmup(160)
    sched.flight.mark_warmup_done(warmed=True)

    prompt = list(range(1, 97))
    run_one(sched, "cold", prompt, max_tokens=4)
    # Full-cover in-place hit (sole owner).
    run_one(sched, "hit", prompt, max_tokens=4)
    # COW path: B full-covers while A holds the last block.
    sched.add_request("a", prompt, SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=16, ignore_eos=True))
    for _ in range(4):
        sched.step()
    sched.add_request("b", prompt, SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=4, ignore_eos=True))
    for _ in range(400):
        sched.step()
        if not sched.has_work():
            break
    # Partial-prefix continuation.
    run_one(sched, "part", prompt[:80] + list(range(600, 632)), max_tokens=4)
    assert sched.cow_blocks_total >= 1
    assert sched.flight.compiles_after_warmup_total == 0, (
        sched.flight.post_warmup_keys
    )


def test_cached_tokens_accounting_matches_allocator():
    """StepOutput.cached_tokens must equal the blocks the allocator served
    from cache (full-cover: n·bs − 1)."""
    sched = make_sched()
    prompt = list(range(1, 97))
    run_one(sched, "a", prompt)
    h0 = sched.allocator.hit_blocks_total
    _, cached, _ = run_one(sched, "b", prompt)
    matched = sched.allocator.hit_blocks_total - h0
    assert cached == matched * BS - 1  # full cover recomputes one token
    h0 = sched.allocator.hit_blocks_total
    _, cached, _ = run_one(sched, "c", prompt[:80] + list(range(700, 717)))
    matched = sched.allocator.hit_blocks_total - h0
    assert cached == matched * BS
    assert sched.metrics().cached_tokens_total == sched.cached_tokens_total
