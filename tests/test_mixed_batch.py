"""Mixed prefill+decode ragged batching (llama.mixed_step + scheduler
mixed steps): parity with phase-separated scheduling (identical tokens,
identical KV contents), admission-latency bound under a long-prefill +
active-decode workload, and the compile-count bound across bucket rungs."""

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, SeqState, StopConditions

CFG = get_config("tiny")


def _params():
    return llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def _prefill(params, k, v, prompt, table, cache_len=0):
    logits, k, v = llama.prefill(
        params, CFG, k, v,
        jnp.asarray(prompt, dtype=jnp.int32), jnp.int32(len(prompt)),
        jnp.int32(cache_len), table,
    )
    return logits, k, v


# --- model-level parity -----------------------------------------------------

def test_mixed_step_matches_prefill_plus_decode():
    """One mixed dispatch ≡ (prefill chunk ; decode step) run separately:
    logits match at every sequence's last row and the KV pools are
    byte-identical afterwards."""
    params = _params()
    y_prompt = list(range(40, 56))  # fresh 16-token chunk, blocks 5-6
    y_table = jnp.array([5, 6, 0, 0], dtype=jnp.int32)
    d_prompts = [list(range(1, 17)), list(range(7, 23))]  # blocks 1-2 / 3-4
    d_tables = jnp.array([[1, 2, 0, 0], [3, 4, 0, 0]], dtype=jnp.int32)

    # Shared setup: both decode sequences prefilled.
    cache = KvCacheArrays.create(CFG, 24, dtype=jnp.float32)
    k, v = cache.k, cache.v
    d_toks, d_pos = [], []
    for i, p in enumerate(d_prompts):
        lg, k, v = _prefill(params, k, v, p, d_tables[i])
        d_toks.append(int(jnp.argmax(lg)))
        d_pos.append(len(p))
    d_toks = jnp.asarray(d_toks, dtype=jnp.int32)
    d_pos = jnp.asarray(d_pos, dtype=jnp.int32)
    act = jnp.ones((2,), dtype=bool)

    # Reference: phase-separated prefill then decode.
    p_ref, k_ref, v_ref = _prefill(params, k, v, y_prompt, y_table)
    d_ref, k_ref, v_ref = llama.decode(
        params, CFG, k_ref, v_ref, d_toks, d_pos, d_tables, act
    )

    # Mixed: same work in ONE dispatch.
    logits, k_mix, v_mix = llama.mixed_step(
        params, CFG, k, v,
        jnp.asarray(y_prompt, dtype=jnp.int32), jnp.int32(len(y_prompt)),
        jnp.int32(0), y_table, d_toks, d_pos, d_tables, act,
    )

    np.testing.assert_allclose(np.asarray(logits[0]), np.asarray(p_ref), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(logits[1:]), np.asarray(d_ref), rtol=1e-5, atol=1e-5)
    # Identical KV contents — every real block, both pools (skip scratch 0).
    np.testing.assert_allclose(np.asarray(k_mix[:, 1:]), np.asarray(k_ref[:, 1:]), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(v_mix[:, 1:]), np.asarray(v_ref[:, 1:]), rtol=1e-5, atol=1e-5)


def test_mixed_step_chunked_continuation_matches():
    """A continuation chunk (cache_len > 0, the ragged row's ``start``)
    attends its own cached prefix exactly as a phase-separated chunk."""
    params = _params()
    y_all = list(range(30, 54))  # 24 tokens: 16 prefilled, 8 continue
    y_table = jnp.array([5, 6, 0, 0], dtype=jnp.int32)
    d_table = jnp.array([[1, 2, 0, 0]], dtype=jnp.int32)

    cache = KvCacheArrays.create(CFG, 24, dtype=jnp.float32)
    lg, k, v = _prefill(params, k=cache.k, v=cache.v, prompt=list(range(1, 17)), table=d_table[0])
    d_toks = jnp.asarray([int(jnp.argmax(lg))], dtype=jnp.int32)
    d_pos = jnp.asarray([16], dtype=jnp.int32)
    _, k, v = _prefill(params, k, v, y_all[:16], y_table)  # chunk 1 of Y

    act = jnp.ones((1,), dtype=bool)
    p_ref, k_ref, v_ref = _prefill(params, k, v, y_all[16:], y_table, cache_len=16)
    d_ref, k_ref, v_ref = llama.decode(params, CFG, k_ref, v_ref, d_toks, d_pos, d_table, act)

    logits, k_mix, v_mix = llama.mixed_step(
        params, CFG, k, v,
        jnp.asarray(y_all[16:], dtype=jnp.int32), jnp.int32(8), jnp.int32(16),
        y_table, d_toks, d_pos, d_table, act,
    )
    np.testing.assert_allclose(np.asarray(logits[0]), np.asarray(p_ref), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(logits[1:]), np.asarray(d_ref), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(k_mix[:, 1:]), np.asarray(k_ref[:, 1:]), rtol=1e-5, atol=1e-5)


# --- scheduler-level parity --------------------------------------------------

def _sched(mixed: bool, **kw):
    params = _params()
    sc = SchedulerConfig(
        num_blocks=96,
        prefill_buckets=[16, 32, 64],
        decode_buckets=[1, 2, 4],
        enable_prefix_caching=False,
        enable_mixed_batching=mixed,
        num_scheduler_steps=1,
        **kw,
    )
    return Scheduler(CFG, params, sc, dtype=jnp.float32)


def _drain(sched, max_iters=500):
    produced = {}
    for _ in range(max_iters):
        if not sched.has_work():
            break
        for seq, out in sched.step():
            produced.setdefault(seq.request_id, []).append(out)
    assert not sched.has_work(), "scheduler did not drain"
    return {rid: [o.token_id for o in outs if o.token_id >= 0] for rid, outs in produced.items()}


def test_mixed_scheduling_token_parity_greedy():
    """Mixed-step output is token-identical to phase-separated scheduling:
    a long prompt admitted while another sequence decodes produces the
    same greedy tokens either way."""
    results = {}
    for mixed in (True, False):
        sched = _sched(mixed, mixed_prefill_budget=32)
        sched.add_request("a", list(range(1, 17)), SamplingParams(temperature=0.0),
                          StopConditions(max_tokens=24))
        for _ in range(3):
            sched.step()  # "a" enters decode
        sched.add_request("b", list(range(5, 101)), SamplingParams(temperature=0.0),
                          StopConditions(max_tokens=8))
        results[mixed] = _drain(sched)
        if mixed:
            assert sched.mixed_steps_total >= 3, "long prompt should ride mixed steps"
            assert sched.mixed_prefill_tokens_total == 96
    assert results[True] == results[False]


def test_mixed_admission_latency_bound():
    """While a long prompt prefills, decode makes progress EVERY iteration
    (no prefill-induced stall) and the prompt's first token lands within
    chunk-count + slack iterations of arrival."""
    sched = _sched(True, mixed_prefill_budget=32)
    sched.add_request("short", list(range(1, 17)), SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=64))
    for _ in range(3):
        sched.step()
    assert any(s.request_id == "short" for s in sched.running)

    sched.add_request("long", list(range(5, 101)), SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=4))
    iters = 0
    long_first = None
    while long_first is None and iters < 20:
        outs = sched.step()
        iters += 1
        decode_tokens = sum(1 for s, o in outs if s.request_id == "short" and o.token_id >= 0)
        assert decode_tokens >= 1, f"iteration {iters} stalled the decode wave"
        if any(s.request_id == "long" and o.token_id >= 0 for s, o in outs):
            long_first = iters
    # 96-token prompt at a 32-token budget = 3 chunks; allow 2 slack.
    assert long_first is not None and long_first <= 5
    assert sched.mixed_steps_total >= 3


def test_mixed_compile_count_bounded_across_rungs():
    """Chunk lengths bucket on the prefill rungs and decode widths on the
    pow2/1.5·pow2 rungs, so a varied workload compiles a handful of mixed
    executables, not one per shape."""
    sched = _sched(True, mixed_prefill_budget=64)
    sched.add_request("d0", list(range(1, 17)), SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=80))
    for _ in range(3):
        sched.step()
    # A spread of prompt lengths: every chunk must land on a bucket rung.
    for i, n in enumerate((24, 40, 50, 61, 90, 33, 17)):
        sched.add_request(f"p{i}", list(range(2, 2 + n)), SamplingParams(temperature=0.0),
                          StopConditions(max_tokens=2))
        for _ in range(6):
            sched.step()
    _drain(sched)
    assert sched.mixed_steps_total >= 5
    keys = list(sched._mixed_jits)
    assert 0 < len(keys) <= 6, keys
    for s_bucket, p_w, d_bucket, d_w in keys:
        assert s_bucket in sched.sc.prefill_buckets
        assert d_bucket in sched.sc.decode_buckets


def test_mixed_preemption_resume_parity():
    """Preemption resumes ride mixed steps (recompute chunk + silent
    re-entry): a block-starved run still matches the unconstrained run."""
    ref = _sched(True)
    for i in range(2):
        ref.add_request(f"r{i}", list(range(1 + i, 33 + i)), SamplingParams(temperature=0.0),
                        StopConditions(max_tokens=24))
    want = _drain(ref)

    tight = Scheduler(CFG, _params(), SchedulerConfig(
        num_blocks=8, prefill_buckets=[16, 32, 64], decode_buckets=[1, 2, 4],
        enable_prefix_caching=False, enable_mixed_batching=True, num_scheduler_steps=1,
    ), dtype=jnp.float32)
    for i in range(2):
        tight.add_request(f"r{i}", list(range(1 + i, 33 + i)), SamplingParams(temperature=0.0),
                          StopConditions(max_tokens=24))
    got = _drain(tight)
    assert tight.preempt_total >= 1
    assert got == want
    assert tight.allocator.num_active == 0


def test_mixed_flash_path_parity():
    """The Pallas flash kernel (interpret mode off-TPU) produces the same
    mixed-step logits as the XLA chunk path."""
    params = _params()
    y_prompt = list(range(40, 56))
    y_table = jnp.array([5, 6, 0, 0], dtype=jnp.int32)
    d_table = jnp.array([[1, 2, 0, 0]], dtype=jnp.int32)
    cache = KvCacheArrays.create(CFG, 24, dtype=jnp.float32)
    lg, k, v = _prefill(params, cache.k, cache.v, list(range(1, 17)), d_table[0])
    d_toks = jnp.asarray([int(jnp.argmax(lg))], dtype=jnp.int32)
    d_pos = jnp.asarray([16], dtype=jnp.int32)
    act = jnp.ones((1,), dtype=bool)
    args = (
        jnp.asarray(y_prompt, dtype=jnp.int32), jnp.int32(len(y_prompt)),
        jnp.int32(0), y_table, d_toks, d_pos, d_table, act,
    )
    lg_xla, _, _ = llama.mixed_step(params, CFG, k, v, *args)
    lg_flash, _, _ = llama.mixed_step(params, CFG, k, v, *args,
                                      use_flash=True, has_prefix=False)
    np.testing.assert_allclose(np.asarray(lg_flash), np.asarray(lg_xla), rtol=2e-4, atol=2e-4)
