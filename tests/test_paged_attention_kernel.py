"""Pallas paged-attention decode kernel vs the XLA gather reference.

Runs the kernel in interpreter mode on the CPU mesh (same code path that
compiles on TPU — pallas_guide.md: ``interpret=True``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.attention.paged import paged_decode_attention
from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.models.llama import _attend


def _reference(q, k_cache, v_cache, tables, kv_lens, config):
    """Gather-based reference: the llama.py decode attention path."""
    B = q.shape[0]
    bs = config.block_size
    ctx = tables.shape[1] * bs
    k_ctx = k_cache[tables].reshape(B, ctx, config.num_kv_heads, config.head_dim)
    v_ctx = v_cache[tables].reshape(B, ctx, config.num_kv_heads, config.head_dim)
    key_pos = jnp.arange(ctx, dtype=jnp.int32)
    mask = key_pos[None, :] < kv_lens[:, None]
    return jax.vmap(lambda qb, kb, vb, mb: _attend(qb[None], kb, vb, mb[None], config)[0])(
        q, k_ctx, v_ctx, mask
    )


@pytest.mark.parametrize("seed", [0, 1])
def test_paged_decode_matches_gather(seed):
    cfg = get_config("tiny")
    key = jax.random.PRNGKey(seed)
    B, N, W = 4, 32, 8
    kq, kk, kv, kt, kl = jax.random.split(key, 5)

    q = jax.random.normal(kq, (B, cfg.num_heads, cfg.head_dim), dtype=jnp.float32)
    k_cache = jax.random.normal(kk, (N, cfg.block_size, cfg.num_kv_heads, cfg.head_dim), dtype=jnp.float32)
    v_cache = jax.random.normal(kv, (N, cfg.block_size, cfg.num_kv_heads, cfg.head_dim), dtype=jnp.float32)
    tables = jax.random.randint(kt, (B, W), 1, N, dtype=jnp.int32)
    # Mixed lengths incl. a partial page and an inactive row (len 0).
    kv_lens = jnp.array([1, cfg.block_size * 2 + 3, cfg.block_size * W, 0], dtype=jnp.int32)

    out = paged_decode_attention(
        q, k_cache, v_cache, tables, kv_lens, block_size=cfg.block_size, interpret=True
    )
    ref = _reference(q, k_cache, v_cache, tables, kv_lens, cfg)

    np.testing.assert_allclose(
        np.asarray(out[:3]), np.asarray(ref[:3]), rtol=2e-5, atol=2e-5
    )
    # Inactive row: kernel returns zeros (never consumed — padded batch slot).
    np.testing.assert_array_equal(np.asarray(out[3]), np.zeros_like(out[3]))


def test_paged_decode_bf16():
    cfg = get_config("tiny")
    key = jax.random.PRNGKey(2)
    B, N, W = 2, 16, 4
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, cfg.num_heads, cfg.head_dim), dtype=jnp.bfloat16)
    k_cache = jax.random.normal(kk, (N, cfg.block_size, cfg.num_kv_heads, cfg.head_dim), dtype=jnp.bfloat16)
    v_cache = jax.random.normal(kv, (N, cfg.block_size, cfg.num_kv_heads, cfg.head_dim), dtype=jnp.bfloat16)
    tables = jnp.arange(1, 1 + B * W, dtype=jnp.int32).reshape(B, W)
    kv_lens = jnp.array([cfg.block_size + 5, 7], dtype=jnp.int32)

    out = paged_decode_attention(
        q, k_cache, v_cache, tables, kv_lens, block_size=cfg.block_size, interpret=True
    )
    ref = _reference(q.astype(jnp.float32), k_cache.astype(jnp.float32), v_cache.astype(jnp.float32), tables, kv_lens, cfg)
    np.testing.assert_allclose(
        np.asarray(out).astype(np.float32), np.asarray(ref), rtol=5e-2, atol=5e-2
    )


async def test_engine_e2e_with_paged_kernel():
    """Full scheduler decode loop with the Pallas kernel (interpret mode on
    CPU) must produce the same greedy tokens as the gather path."""
    from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.runtime.engine import Context

    async def run(impl):
        args = EngineArgs(
            model="tiny",
            model_config=get_config("tiny").replace(attention_impl=impl),
            dtype="float32",
            scheduler=SchedulerConfig(
                num_blocks=64, max_running=4,
                prefill_buckets=[16, 32], decode_buckets=[1, 2, 4],
            ),
        )
        engine = TpuEngine.build(args)
        try:
            out = []
            async for frame in engine.generate(
                {"token_ids": list(range(10, 30)),
                 "sampling_options": {"temperature": 0.0},
                 "stop_conditions": {"max_tokens": 6}},
                Context(),
            ):
                out.extend(frame["token_ids"])
            return out
        finally:
            await engine.stop()

    gather = await run("gather")
    kernel = await run("paged_kernel")
    assert len(gather) == 6
    assert gather == kernel
