"""Decode-attention two-piece online-softmax math (the decode backend after
the Pallas paged kernel's r4 deletion — see ModelConfig.attention_impl for
the measurement record). The pieces and merge must equal dense masked
attention exactly."""

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.models.llama import _attend_piece, _merge_pieces


def _dense_reference(qg, k_all, v_all, mask):
    s = jnp.einsum("bkgd,bskd->bkgs", qg, k_all) * (qg.shape[-1] ** -0.5)
    s = jnp.where(mask[:, None, None, :], s, -1e30)
    return jnp.einsum("bkgs,bskd->bkgd", jax.nn.softmax(s, axis=-1), v_all)


def test_two_piece_merge_matches_dense():
    B, S1, S2, KVH, G, HD = 3, 24, 5, 2, 4, 16
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, KVH, G, HD), jnp.float32)
    k1 = jax.random.normal(jax.random.fold_in(key, 1), (B, S1, KVH, HD), jnp.float32)
    v1 = jax.random.normal(jax.random.fold_in(key, 2), (B, S1, KVH, HD), jnp.float32)
    k2 = jax.random.normal(jax.random.fold_in(key, 3), (B, S2, KVH, HD), jnp.float32)
    v2 = jax.random.normal(jax.random.fold_in(key, 4), (B, S2, KVH, HD), jnp.float32)
    m1_mask = jnp.arange(S1)[None, :] < jnp.asarray([24, 9, 0])[:, None]  # full/ragged/empty
    m2_mask = jnp.ones((B, S2), bool)

    scale = HD**-0.5
    m1, l1, a1 = _attend_piece(q, k1, v1, m1_mask, scale)
    m2, l2, a2 = _attend_piece(q, k2, v2, m2_mask, scale)
    out = _merge_pieces(m1, l1, a1, m2, l2, a2)

    ref = _dense_reference(
        q, jnp.concatenate([k1, k2], 1), jnp.concatenate([v1, v2], 1),
        jnp.concatenate([m1_mask, m2_mask], 1),
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_empty_piece_drops_out():
    """A fully-masked piece (m=-inf, l=0) must not perturb the merge."""
    B, S, KVH, G, HD = 2, 8, 2, 2, 16
    key = jax.random.PRNGKey(7)
    q = jax.random.normal(key, (B, KVH, G, HD), jnp.float32)
    k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, KVH, HD), jnp.float32)
    v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, KVH, HD), jnp.float32)
    live = jnp.ones((B, S), bool)
    dead = jnp.zeros((B, S), bool)

    m1, l1, a1 = _attend_piece(q, k, v, live, HD**-0.5)
    m2, l2, a2 = _attend_piece(q, k, v, dead, HD**-0.5)
    merged = _merge_pieces(m1, l1, a1, m2, l2, a2)
    solo = a1 / jnp.maximum(l1, 1e-30)[..., None]
    np.testing.assert_allclose(np.asarray(merged), np.asarray(solo), rtol=1e-6, atol=1e-6)
