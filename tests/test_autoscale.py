"""Closed-loop SLA autoscaler tests (PR 11).

Three layers, matching the subsystem's split:

- **decision table** — the controller is a pure function over replayed
  ``ObservedLoad`` sequences, so ramp-up / ramp-down / flash-crowd /
  noisy-flat each assert the EXACT add/drain decision sequence, that
  hysteresis suppresses flapping, cooldown suppresses echoes, and the
  drain debounce never stacks scale-downs;
- **fleet** — decisions become real in-process mocker launches/drains over
  the wire path, including the slow-drain chaos case and coldest-worker
  (KV-warmth) victim selection;
- **closed loop** — the shortened traffic-harness ramp drives the whole
  plane (fleet → aggregator → observer → controller → fleet) with a chaos
  fault firing during a scale event: pools converge to the capacity
  oracle, SLO attainment holds, zero token loss on surviving requests.
"""

import asyncio
import math
import random

import pytest

from dynamo_tpu.planner.controller import (
    DECODE,
    PREFILL,
    AutoscaleController,
    ControllerConfig,
    FleetView,
    MockerCapacityModel,
    StaticCapacityModel,
    WorkerView,
    rank_coldest,
)
from dynamo_tpu.planner.load_predictor import (
    ConstantPredictor,
    SeasonalTrendPredictor,
    TrendPredictor,
    make_predictor,
)
from dynamo_tpu.planner.planner_core import ObservedLoad


def make_controller(**overrides) -> AutoscaleController:
    kw = dict(
        min_prefill=1, max_prefill=8, min_decode=1, max_decode=8,
        scale_cooldown_s=10.0, scale_up_stable_intervals=1,
        scale_down_stable_intervals=2, max_step=2,
        load_predictor="constant",  # deterministic replay
    )
    kw.update(overrides)
    cfg = ControllerConfig(**kw)
    # prefill 400 tok/s, decode 80 tok/s per worker; utilization 1.0 keeps
    # the expected sizes mental-math-exact.
    return AutoscaleController(cfg, StaticCapacityModel(400.0, 80.0, utilization=1.0))


def view_of(prefill: int, decode: int, drains=None) -> FleetView:
    return FleetView(
        pools={
            PREFILL: [WorkerView(worker_id=100 + i) for i in range(prefill)],
            DECODE: [WorkerView(worker_id=200 + i) for i in range(decode)],
        },
        drains_in_flight=drains or {},
    )


def load(rate, isl=100, osl=16, **kw) -> ObservedLoad:
    return ObservedLoad(request_rate=rate, avg_isl=isl, avg_osl=osl, **kw)


def actions(decisions):
    return [(d.pool, d.action, d.count) for d in decisions]


# --- decision table -----------------------------------------------------------
def test_decision_table_ramp_up_down():
    """Replayed ramp: exact add sequence on the way up (slice-granular,
    max_step-capped), hysteresis-delayed drains on the way down, cooldown
    suppressing the echo in between."""
    c = make_controller()
    sizes = {PREFILL: 1, DECODE: 1}

    def step(rate, t, drains=None):
        ds = c.decide(load(rate), view_of(sizes[PREFILL], sizes[DECODE], drains), t)
        for d in ds:
            if d.action != "hold":
                sizes[d.pool] = d.target
        return ds

    # rate 1: want (1,1) == current -> hold.
    assert actions(step(1.0, t=0.0)) == [(PREFILL, "hold", 0), (DECODE, "hold", 0)]
    # rate 8: want (ceil(800/400)=2, ceil(128/80)=2) -> immediate add (up_stable=1).
    assert actions(step(8.0, t=20.0)) == [(PREFILL, "add", 1), (DECODE, "add", 1)]
    # rate 16: want (4,4) from (2,2) -> add capped at max_step=2.
    assert actions(step(16.0, t=40.0)) == [(PREFILL, "add", 2), (DECODE, "add", 2)]
    assert sizes == {PREFILL: 4, DECODE: 4}
    # steady: hold.
    assert actions(step(16.0, t=60.0)) == [(PREFILL, "hold", 0), (DECODE, "hold", 0)]
    # rate 2: want (1,1) — hysteresis needs 2 consecutive under-windows.
    assert actions(step(2.0, t=80.0)) == [(PREFILL, "hold", 0), (DECODE, "hold", 0)]
    ds = step(2.0, t=100.0)
    assert actions(ds) == [(PREFILL, "drain", 2), (DECODE, "drain", 2)]
    assert all(d.victims for d in ds if d.action == "drain")
    assert sizes == {PREFILL: 2, DECODE: 2}
    # still low, stable again — but inside the 10s cooldown: suppressed.
    step(2.0, t=104.0)
    ds = step(2.0, t=108.0)
    assert actions(ds) == [(PREFILL, "hold", 0), (DECODE, "hold", 0)]
    assert c.cooldown_suppressed_total >= 2
    # cooldown expired: the final drain lands.
    ds = step(2.0, t=111.0)
    assert actions(ds) == [(PREFILL, "drain", 1), (DECODE, "drain", 1)]
    assert sizes == {PREFILL: 1, DECODE: 1}
    # Counters are per-pool actions: 2 up + 2 down passes × both pools.
    assert c.scale_up_total == 4 and c.scale_down_total == 4


def test_noisy_flat_does_not_flap():
    """Quantile/rate noise oscillating the desired size between 2 and 3
    every window must produce ZERO fleet actions once hysteresis requires
    consecutive agreement in BOTH directions — alternating windows never
    build a streak."""
    c = make_controller(scale_up_stable_intervals=2, scale_down_stable_intervals=2)
    sizes = {PREFILL: 2, DECODE: 2}
    rng = random.Random(7)
    moved = []
    for i in range(20):
        # rate alternates so desired prefill flips 2 <-> 3 (800±200 / 400).
        rate = 8.0 + (2.0 if i % 2 else -2.0) * rng.uniform(0.8, 1.0)
        ds = c.decide(load(rate, isl=100, osl=20),
                      view_of(sizes[PREFILL], sizes[DECODE]), float(i * 10))
        for d in ds:
            if d.action != "hold":
                sizes[d.pool] = d.target
                moved.append(d)
    assert moved == [], [f"{d.pool}:{d.action}" for d in moved]
    assert c.hysteresis_suppressed_total > 0


def test_flash_crowd_sequence():
    """Flash crowd: immediate scale-up on the spike window, cooldown holds
    through the spike, hysteresis-delayed drain after it passes."""
    c = make_controller(scale_cooldown_s=15.0)
    sizes = {PREFILL: 1, DECODE: 1}

    def step(rate, t):
        ds = c.decide(load(rate), view_of(sizes[PREFILL], sizes[DECODE]), t)
        for d in ds:
            if d.action != "hold":
                sizes[d.pool] = d.target
        return ds

    step(1.0, t=0.0)
    assert actions(step(20.0, t=10.0))[0] == (PREFILL, "add", 2)  # spike hits
    assert actions(step(20.0, t=20.0)) == [(PREFILL, "hold", 0), (DECODE, "hold", 0)]  # cooldown
    assert actions(step(20.0, t=26.0))[0] == (PREFILL, "add", 2)  # still hot, cooldown over
    # Spike gone: two stable windows + cooldown before the first drain.
    step(1.0, t=42.0)
    ds = step(1.0, t=44.0)
    assert [a for a in actions(ds) if a[1] == "drain"], actions(ds)


def test_drain_debounce_blocks_second_scale_down():
    """Never a second scale-down while a drain is still in flight — and the
    held decision lands once the drain clears."""
    c = make_controller(scale_cooldown_s=0.0, scale_down_stable_intervals=1)
    # Demand wants 1 prefill; current 4, a drain from the previous decision
    # still in flight.
    ds = c.decide(load(1.0), view_of(4, 1, drains={PREFILL: 1}), 0.0)
    pre = next(d for d in ds if d.pool == PREFILL)
    assert pre.action == "hold" and "drain in flight" in pre.reason
    assert c.drain_debounced_total == 1
    # Drain landed: the scale-down proceeds (victims ranked).
    ds = c.decide(load(1.0), view_of(3, 1, drains={PREFILL: 0}), 1.0)
    pre = next(d for d in ds if d.pool == PREFILL)
    assert pre.action == "drain" and pre.count == 2 and len(pre.victims) == 2


def test_sla_feedback_bumps_pressured_pool():
    """Closed-loop corrections: a TTFT/queue breach bumps prefill, a TPOT
    breach bumps decode, KV pressure bumps decode — independent pools."""
    c = make_controller(ttft_sla_s=0.2, tpot_sla_s=0.05, slo_floor=0.9)
    base = c.desired_sizes(load(4.0))  # want (1, 1) at rate 4
    assert base == {PREFILL: 1, DECODE: 1}
    hot_ttft = c.desired_sizes(load(4.0, ttft_p99=0.5, slo_attainment=0.5))
    assert hot_ttft[PREFILL] == base[PREFILL] + 1
    hot_tpot = c.desired_sizes(load(4.0, tpot_p99=0.2))
    assert hot_tpot[DECODE] == base[DECODE] + 1
    hot_kv = c.desired_sizes(load(4.0, kv_util=0.95))
    assert hot_kv[DECODE] == base[DECODE] + 1


def test_rank_coldest_prefers_router_reuse_then_engine_warmth():
    workers = [
        WorkerView(1, kv_util=0.9, kv_warmth=0.1, cached_tokens_total=0),     # cold, busy
        WorkerView(2, kv_util=0.1, kv_warmth=0.8, cached_tokens_total=4096),  # warm (router-proven)
        WorkerView(3, kv_util=0.1, kv_warmth=0.5, cached_tokens_total=0),     # lukewarm engine-side
        WorkerView(4, kv_util=0.0, kv_warmth=0.0, cached_tokens_total=0, draining=True),
    ]
    # Draining worker is never a candidate; router-proven reuse dominates:
    # worker 2 must be the LAST drain candidate.
    order = rank_coldest(workers, 3)
    assert 4 not in order
    assert order[-1] == 2 and 2 not in order[:2]
    # Exact order follows the documented composite score (ties break by id).
    scores = {w.worker_id: w.warmth_score(4096) for w in workers[:3]}
    assert order == sorted(scores, key=lambda k: (scores[k], k))


def test_budget_clamp_preserves_ratio():
    c = make_controller(max_total=4)
    want = c.desired_sizes(load(40.0, isl=100, osl=40))  # raw: pre 10, dec 20 -> clamped
    assert want[PREFILL] + want[DECODE] <= 4 + 1
    assert want[PREFILL] >= 1 and want[DECODE] >= 1
    assert want[DECODE] >= want[PREFILL]  # ratio preserved under the clamp


# --- predictors ---------------------------------------------------------------
def test_trend_predictor_fixes_constant_ramp_lag():
    """On a linear ramp the constant predictor is exactly one interval
    behind; the trend predictor's one-step-ahead extrapolation is not."""
    const, trend = ConstantPredictor(), TrendPredictor()
    slope = 3.0
    const_err = trend_err = 0.0
    for i in range(20):
        v = slope * i
        const.observe(v)
        trend.observe(v)
        nxt = slope * (i + 1)
        const_err = abs(const.predict() - nxt)
        trend_err = abs(trend.predict() - nxt)
    assert const_err == pytest.approx(slope)  # the structural one-interval lag
    assert trend_err < 0.2 * const_err


def test_trend_predictor_tracks_diurnal_ramp():
    """Against the harness's diurnal shape: mean absolute one-step-ahead
    error of the trend predictor beats the constant predictor on the ramp
    segments (the bias the satellite names)."""
    from tools.traffic_harness import TrafficPattern

    pat = TrafficPattern(kind="diurnal", duration_s=100.0, base_rate=2.0, peak_rate=20.0)
    const, trend = ConstantPredictor(), TrendPredictor()
    errs = {"const": [], "trend": []}
    ts = [float(t) for t in range(0, 100, 2)]
    for t in ts:
        v = pat.rate(t)
        const.observe(v)
        trend.observe(v)
        nxt = pat.rate(t + 2)
        errs["const"].append(abs(const.predict() - nxt))
        errs["trend"].append(abs(trend.predict() - nxt))
    # Strictly better over the whole day; the big wins are on the ramp
    # segments (the crest/trough turns give some back — that is what the
    # seasonal_trend mode is for).
    assert sum(errs["trend"]) < 0.85 * sum(errs["const"])
    ramp = [i for i, t in enumerate(ts) if abs(math.sin(2 * math.pi * t / 100.0)) > 0.5]
    assert sum(errs["trend"][i] for i in ramp) < 0.6 * sum(errs["const"][i] for i in ramp)


def test_seasonal_trend_predictor():
    """Second day of a growing diurnal cycle: seasonal+trend anticipates
    the crest where trend-on-levels overshoots and seasonal-naive lags."""
    period = 24
    p = SeasonalTrendPredictor(period=period, trend_window=6)
    series = []
    for day in range(3):
        for h in range(period):
            v = (10 + 2 * day) * (1 - math.cos(2 * math.pi * h / period)) / 2
            series.append(v)
    errs = []
    for i, v in enumerate(series):
        p.observe(v)
        if i >= 2 * period and i + 1 < len(series):
            errs.append(abs(p.predict() - series[i + 1]))
    naive = make_predictor("seasonal", period=period)
    errs_naive = []
    for i, v in enumerate(series):
        naive.observe(v)
        if i >= 2 * period and i + 1 < len(series):
            errs_naive.append(abs(naive.predict() - series[i + 1]))
    assert sum(errs) < sum(errs_naive)


# --- planner_core satellites (CLI knob semantics) -----------------------------
async def test_planner_dry_run_and_cooldown():
    from dynamo_tpu.planner import (
        DecodeInterpolator,
        Planner,
        PlannerConfig,
        PrefillInterpolator,
        VirtualConnector,
    )

    prefill = PrefillInterpolator(isl=[128, 1024], ttft_ms=[20, 130],
                                  thpt_per_chip=[8000, 11000])
    decode = DecodeInterpolator(active_kv=[8, 512], context_len=[1024, 1024],
                                itl_ms=[5, 15], thpt_per_chip=[50, 600])

    loads = iter([
        ObservedLoad(request_rate=1.0, avg_isl=512, avg_osl=64),
        ObservedLoad(request_rate=30.0, avg_isl=1024, avg_osl=256),
        ObservedLoad(request_rate=30.0, avg_isl=1024, avg_osl=256),
    ])

    async def observe():
        return next(loads)

    # Dry run: decisions logged/counted, connector never driven.
    conn = VirtualConnector()
    p = Planner(PlannerConfig(dry_run=True, load_predictor="constant"),
                conn, prefill, decode, observe)
    await p.step()
    assert conn.history == [] and p.dry_run_decisions_total == 1

    # Cooldown: the second (different) plan inside the window is held.
    loads2 = iter([
        ObservedLoad(request_rate=1.0, avg_isl=512, avg_osl=64),
        ObservedLoad(request_rate=30.0, avg_isl=1024, avg_osl=256),
    ])

    async def observe2():
        return next(loads2)

    conn2 = VirtualConnector()
    p2 = Planner(PlannerConfig(scale_cooldown_s=3600.0, load_predictor="constant"),
                 conn2, prefill, decode, observe2)
    plan1 = await p2.step()
    held = await p2.step()  # burst arrives inside the cooldown -> held
    assert held == plan1 and p2.cooldown_holds_total == 1
    assert len(conn2.history) == 2  # only the first plan's two set_replicas

    # Per-pool max clamp.
    p3 = Planner(PlannerConfig(max_prefill_replicas=1, max_decode_replicas=2,
                               max_chip_budget=64),
                 VirtualConnector(), prefill, decode, None)
    plan = p3.compute_replicas(ObservedLoad(request_rate=1000.0, avg_isl=4096, avg_osl=512))
    assert plan.prefill <= 1 and plan.decode <= 2


# --- fleet: real launches/drains ----------------------------------------------
async def test_fleet_scale_and_coldest_drain_e2e():
    """Launch a 3-worker prefill pool, warm ONE worker with same-prefix
    traffic through the KV router, then scale down: the drained victim must
    be a cold worker, never the warm one — and the drain completes with the
    allocator clean."""
    from dynamo_tpu.llm.kv_router import KvPushRouter, KvRouterConfig
    from dynamo_tpu.llm.mocker import MockEngineArgs
    from dynamo_tpu.planner.fleet import MockerFleet
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.engine import Context

    drt = await DistributedRuntime.detached()
    try:
        fleet = MockerFleet(
            drt, "fleete2e",
            make_args=lambda c: MockEngineArgs(speedup_ratio=100.0, num_blocks=128,
                                               token_rule="position"),
            drain_timeout_s=5.0,
        )
        for _ in range(3):
            await fleet.add_worker("prefill")
        client = await fleet.endpoint("prefill").client()
        await client.wait_for_instances(3, timeout=5)
        router = await KvPushRouter.create(client, KvRouterConfig(block_size=16))

        prefix = list(range(64))

        async def run_one(tokens):
            async for _ in router.generate(
                {"token_ids": tokens, "stop_conditions": {"max_tokens": 2}}, Context()
            ):
                pass

        await run_one(prefix + [900])
        await asyncio.sleep(0.3)  # KV events -> indexer
        for i in range(5):
            await run_one(prefix + [1000 + i])
        stats = router.stats()
        assert stats["cached_tokens_total"] > 0
        warm = max(stats["cached_tokens_by_worker"], key=stats["cached_tokens_by_worker"].get)

        view = fleet.view(router_stats=stats)
        victims = rank_coldest(view.pools["prefill"], 2)
        assert warm not in victims, (warm, victims)

        # Drain one cold worker through the fleet; debounce signal visible.
        task = fleet.drain_worker("prefill", victims[0])
        assert task is not None
        assert fleet.size("prefill") == 2
        await task
        assert fleet.drains_in_flight("prefill") == 0
        for _ in range(100):
            if len(client.instances) == 2:
                break
            await asyncio.sleep(0.02)
        assert len(client.instances) == 2
        # Warm worker still serving, and the fleet drains clean.
        assert any(w.worker_id == warm for w in fleet.pools["prefill"])
        await router.close()
        await fleet.shutdown()
        assert fleet.size("prefill") == 0
    finally:
        await drt.shutdown()


async def test_slow_drain_debounces_second_scale_down():
    """Slow-drain chaos: a long in-flight stream keeps the drain open; the
    controller must HOLD the next scale-down until the drain lands, then
    proceed — and the slow request survives token-exact (migration on
    sever)."""
    from dynamo_tpu.llm.kv_router import KvPushRouter, KvRouterConfig
    from dynamo_tpu.llm.migration import Migration
    from dynamo_tpu.llm.mocker import MockEngineArgs
    from dynamo_tpu.planner.fleet import MockerFleet
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.engine import Context

    drt = await DistributedRuntime.detached()
    try:
        fleet = MockerFleet(
            drt, "fleetslow",
            make_args=lambda c: MockEngineArgs(itl_base_ms=30.0, num_blocks=128,
                                               token_rule="position"),
            drain_timeout_s=8.0,
        )
        for _ in range(3):
            await fleet.add_worker("decode")
        client = await fleet.endpoint("decode").client()
        await client.wait_for_instances(3, timeout=5)
        router = await KvPushRouter.create(client, KvRouterConfig(block_size=16))
        engine = Migration(2).attach(router)

        # A slow stream (~1.2s) pinned to whichever worker the router picks.
        got = []

        async def slow_request():
            async for item in engine.generate(
                {"token_ids": list(range(10)), "stop_conditions": {"max_tokens": 40}},
                Context(),
            ):
                data = item.data if hasattr(item, "data") else item
                if isinstance(data, dict):
                    got.extend(data.get("token_ids") or ())

        stream = asyncio.create_task(slow_request())
        await asyncio.sleep(0.2)
        busy = [w.worker_id for w in fleet.pools["decode"]
                if w.engine.running or w.engine.waiting]
        assert busy, "slow stream should be in flight somewhere"

        c = make_controller(scale_cooldown_s=0.0, scale_down_stable_intervals=1,
                            max_step=1)
        # Scale-down #1: drain the busy worker (force victim via warmth: give
        # the others router-proven warmth so the busy one ranks coldest).
        stats = {"cached_tokens_by_worker": {
            w.worker_id: (0 if w.worker_id in busy else 4096)
            for w in fleet.pools["decode"]}}
        ds = c.decide(load(0.1, osl=8), fleet.view(stats), 0.0)
        dec = next(d for d in ds if d.pool == DECODE)
        assert dec.action == "drain" and dec.victims[0] == busy[0]
        await fleet.apply([dec])
        assert fleet.drains_in_flight("decode") == 1

        # Scale-down #2 while the drain is in flight: DEBOUNCED.
        ds = c.decide(load(0.1, osl=8), fleet.view(stats), 1.0)
        dec2 = next(d for d in ds if d.pool == DECODE)
        assert dec2.action == "hold" and "drain in flight" in dec2.reason
        assert c.drain_debounced_total == 1

        await fleet.wait_drains(timeout=12.0)
        await stream
        # Token-exact survival across the drain (finish or migrate).
        assert got == list(range(10, 50))

        # Drain landed: the next scale-down proceeds.
        ds = c.decide(load(0.1, osl=8), fleet.view(stats), 2.0)
        dec3 = next(d for d in ds if d.pool == DECODE)
        assert dec3.action == "drain" and dec3.count == 1
        await router.close()
        await fleet.shutdown()
    finally:
        await drt.shutdown()


async def test_planner_stats_flow_through_aggregator():
    """Controller counters/gauges reach Prometheus through the real scrape:
    fleet serves the planner endpoint, the aggregator's multi-endpoint
    scrape merges it, and the planner_* families render."""
    from dynamo_tpu.metrics_aggregator import MetricsAggregator
    from dynamo_tpu.planner.fleet import MockerFleet
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.detached()
    try:
        fleet = MockerFleet(drt, "plagg")
        await fleet.add_worker("prefill")
        await fleet.add_worker("decode")
        c = make_controller()
        c.decide(load(8.0), fleet.view(), 0.0)
        await fleet.serve_planner(c)

        agg = MetricsAggregator(
            drt, "plagg", "prefill", "generate",
            extra_endpoints=["plagg/decode/generate", "plagg/planner/control"],
        )
        await agg.start()
        stats = await agg.scrape_once()
        # Both pool workers + the planner pseudo-worker.
        assert len(stats) == 3
        assert any("planner_decisions_total" in s for s in stats.values())
        assert any("kv_warmth" in s for s in stats.values())
        agg.export_stats(stats)
        text = agg.registry.render().decode()
        assert "dynamo_component_worker_planner_decisions_total" in text
        assert "dynamo_component_worker_planner_prefill_target" in text
        assert "dynamo_component_worker_kv_warmth" in text
        await agg.stop()
        await fleet.shutdown()
    finally:
        await drt.shutdown()


# --- the closed loop ----------------------------------------------------------
@pytest.mark.slow  # ~25s of real-time ramp; the CI `autoscale` job runs this
# same loop every push via `BENCH_AUTOSCALE_ONLY=1 python bench.py` and gates
# on convergence/SLO/token-loss — tier-1 keeps the fast decision/fleet layers.
async def test_autoscale_closed_loop_with_chaos():
    """Shortened harness diurnal ramp through the FULL plane. Asserts the
    acceptance criteria: independent pool growth, convergence to the
    capacity oracle at the trough, SLO attainment, a chaos fault fired
    during a scale event, and zero token loss on surviving requests."""
    from tools.traffic_harness import (
        AutoscaleBenchConfig,
        TrafficPattern,
        run_autoscale_bench,
    )

    cfg = AutoscaleBenchConfig(
        pattern=TrafficPattern(kind="diurnal", duration_s=16.0, base_rate=1.5,
                               peak_rate=8.0, isl=96, isl_end=144, osl=16, seed=0),
        adjustment_interval_s=1.5,
        scale_cooldown_s=3.0,
        settle_s=5.0,
    )
    report = await run_autoscale_bench(cfg)

    totals = report["totals"]
    assert totals["requests"] > 30
    assert totals["token_loss"] == 0, report["totals"]
    assert totals["errors"] == 0, report["totals"]

    # The planner really scaled both pools up and back down.
    planner = report["planner"]
    assert planner["planner_scale_up_total"] >= 2
    assert planner["planner_scale_down_total"] >= 1
    assert report["max_pools"]["prefill"] > 1
    assert report["max_pools"]["decode"] > 1
    # Peak capacity at least covered the oracle for the crest load.
    assert report["max_pools"]["prefill"] >= report["peak_oracle"]["prefill"]
    assert report["max_pools"]["decode"] >= report["peak_oracle"]["decode"]

    # Converged back to the oracle at the trough (±1).
    assert report["final"]["converged"], report["final"]

    # Chaos fired mid-scale-event; surviving requests stayed token-exact.
    assert report["chaos"]["armed_at_s"] is not None
    assert report["chaos"]["injections"] >= 1

    # SLO-attainment/goodput curves exist across the ramp and hold a floor.
    assert len(report["windows"]) >= 6
    assert report["slo_attainment"] is not None and report["slo_attainment"] >= 0.7
