"""Leader/worker barrier rendezvous (ref: utils/leader_worker_barrier.rs)."""

import asyncio

import pytest

from dynamo_tpu.runtime.barrier import (
    BarrierAborted,
    BarrierTimeout,
    LeaderBarrier,
    WorkerBarrier,
)
from dynamo_tpu.runtime.transports.kvstore import KeyExists, MemKvStore


async def test_leader_and_workers_rendezvous():
    store = MemKvStore()
    leader = LeaderBarrier("b1", num_workers=3)
    workers = [WorkerBarrier("b1", f"w{i}") for i in range(3)]

    async def run_worker(w, i):
        return await w.sync(store, {"rank": i})

    results = await asyncio.gather(
        leader.sync(store, {"mesh": [2, 4]}),
        *(run_worker(w, i) for i, w in enumerate(workers)),
    )
    leader_result, *worker_results = results
    assert set(leader_result) == {"w0", "w1", "w2"}
    assert leader_result["w1"] == {"rank": 1}
    assert all(r == {"mesh": [2, 4]} for r in worker_results)
    await store.close()


async def test_workers_arrive_before_leader():
    store = MemKvStore()
    worker_task = asyncio.create_task(WorkerBarrier("b2", "w0").sync(store, {"rank": 0}))
    await asyncio.sleep(0.05)  # worker is parked waiting for data
    assert not worker_task.done()
    leader_result = await LeaderBarrier("b2", num_workers=1).sync(store, "cfg")
    assert leader_result == {"w0": {"rank": 0}}
    assert await worker_task == "cfg"
    await store.close()


async def test_leader_timeout_aborts_workers():
    store = MemKvStore()
    worker_task = asyncio.create_task(WorkerBarrier("b3", "w0").sync(store, None))
    with pytest.raises(BarrierTimeout):
        await LeaderBarrier("b3", num_workers=2, timeout_s=0.2).sync(store, None)
    with pytest.raises(BarrierAborted):
        await worker_task
    await store.close()


async def test_duplicate_worker_id_rejected():
    store = MemKvStore()
    leader_task = asyncio.create_task(LeaderBarrier("b4", num_workers=2).sync(store, None))
    ok = asyncio.create_task(WorkerBarrier("b4", "w0").sync(store, None))
    await asyncio.sleep(0.05)
    with pytest.raises(KeyExists):
        await WorkerBarrier("b4", "w0").sync(store, None)
    # A distinct worker completes the rendezvous.
    other = asyncio.create_task(WorkerBarrier("b4", "w1").sync(store, None))
    assert set(await leader_task) == {"w0", "w1"}
    await asyncio.gather(ok, other)
    await store.close()


async def test_lease_bound_keys_vanish_with_lease():
    store = MemKvStore(reaper_interval_s=0.05)
    lease = await store.grant_lease(0.15)
    leader_task = asyncio.create_task(
        LeaderBarrier("b5", num_workers=1).sync(store, "d", lease_id=lease.id)
    )
    w = await WorkerBarrier("b5", "w0").sync(store, None)
    assert w == "d"
    await leader_task
    assert await store.get("barrier/b5/data") is not None
    await asyncio.sleep(0.4)  # lease expires; reaper deletes barrier keys
    assert await store.get("barrier/b5/data") is None
    assert await store.get("barrier/b5/complete") is None
    await store.close()
