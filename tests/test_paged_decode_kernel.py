"""Parity tests for the Pallas paged flash-decode kernel (interpret mode)
against the XLA gather reference — same (m, l, acc) partial contract.

The kernel is explicit opt-in (attention_impl="paged"); these tests keep it
correct while it waits for a runtime where per-pallas-call dispatch cost
does not dominate (see ModelConfig.attention_impl)."""

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.attention.decode import paged_decode_partials
from dynamo_tpu.engine.models.llama import _attend_piece, _merge_pieces


def _reference(q, kp, vp, tables, lengths, KVH):
    B, H, HD = q.shape
    G = H // KVH
    BS = kp.shape[1]
    ctx = tables.shape[1] * BS
    k_ctx = kp[tables].reshape(B, ctx, KVH, HD)
    v_ctx = vp[tables].reshape(B, ctx, KVH, HD)
    mask = jnp.arange(ctx)[None, :] < lengths[:, None]
    qg = q.reshape(B, KVH, G, HD)
    return _attend_piece(qg, k_ctx, v_ctx, mask, HD**-0.5)


def test_kernel_matches_gather_partials():
    B, BS, KVH, HD, G = 4, 32, 2, 64, 4
    H = KVH * G
    NP_, W = 40, 6
    key = jax.random.PRNGKey(0)
    kp = jax.random.normal(key, (NP_, BS, KVH, HD), jnp.float32)
    vp = kp * 0.5 + 1
    q = jax.random.normal(jax.random.PRNGKey(1), (B, H, HD), jnp.float32)
    tables = jnp.array(
        [[3, 7, 11, 0, 0, 0], [5, 6, 0, 0, 0, 0], [9, 4, 8, 2, 12, 13], [0, 0, 0, 0, 0, 0]],
        jnp.int32,
    )
    lengths = jnp.array([70, 33, 192, 0], jnp.int32)

    m, l, acc = paged_decode_partials(
        q, kp, vp, tables, lengths, num_kv_heads=KVH, block_size=BS, interpret=True
    )
    m2, l2, acc2 = _reference(q, kp, vp, tables, lengths, KVH)

    # Rows 0-2 carry real prefixes — partials must match. Row 3 is empty:
    # the kernel returns the canonical empty piece (m=-inf, l=0) while the
    # gather reference returns (m=-1e30, l=ctx); both vanish in the merge.
    np.testing.assert_allclose(np.asarray(m[:3]), np.asarray(m2[:3]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(l[:3]), np.asarray(l2[:3]), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(acc[:3]), np.asarray(acc2[:3]), rtol=1e-4, atol=1e-4)
    assert float(jnp.max(l[3])) == 0.0


def test_empty_piece_drops_out_of_merge():
    B, BS, KVH, HD, G = 2, 16, 2, 32, 2
    H = KVH * G
    key = jax.random.PRNGKey(2)
    kp = jax.random.normal(key, (8, BS, KVH, HD), jnp.float32)
    vp = kp + 1
    q = jax.random.normal(jax.random.PRNGKey(3), (B, H, HD), jnp.float32)
    tables = jnp.zeros((B, 4), jnp.int32)
    lengths = jnp.zeros((B,), jnp.int32)  # all rows empty
    m1, l1, acc1 = paged_decode_partials(
        q, kp, vp, tables, lengths, num_kv_heads=KVH, block_size=BS, interpret=True
    )
    # Merge the empty kernel piece with a one-token in-register piece: the
    # result must equal attention over that single token alone.
    qg = q.reshape(B, KVH, G, HD)
    k1t = jax.random.normal(jax.random.PRNGKey(4), (B, 1, KVH, HD), jnp.float32)
    v1t = k1t * 2
    m2, l2, acc2 = _attend_piece(qg, k1t, v1t, jnp.ones((B, 1), bool), HD**-0.5)
    out = _merge_pieces(m1, l1, acc1, m2, l2, acc2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(v1t[:, 0, :, None, :].repeat(G, 2) * 0 + v1t[:, 0][:, :, None, :]), rtol=1e-5)
