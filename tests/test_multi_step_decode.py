"""Multi-step decode: N on-device autoregressive steps per dispatch must
match single-step results exactly (greedy), and the scheduler must trim
tokens past a stop condition mid-window."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.runtime.engine import Context


def test_decode_multi_matches_single_greedy():
    cfg = get_config("tiny").replace(num_layers=2)
    B, steps = 4, 6
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    cache = KvCacheArrays.create(cfg, num_blocks=B * 6 + 2, dtype=jnp.float32)
    max_blocks = 6
    tables = jnp.array(1 + np.arange(B * max_blocks).reshape(B, max_blocks), dtype=jnp.int32)
    toks0 = jnp.arange(B, dtype=jnp.int32) + 5
    act = jnp.ones((B,), bool)
    greedy = jnp.zeros((B,), jnp.float32)
    top_k = jnp.zeros((B,), jnp.int32)
    top_p = jnp.ones((B,), jnp.float32)

    # Single-step reference rollout.
    k, v = cache.k, cache.v
    toks = toks0
    ref = []
    for s in range(steps):
        poss = jnp.full((B,), s, jnp.int32)
        logits, k, v = llama.decode(params, cfg, k, v, toks, poss, tables, act)
        toks = jnp.argmax(logits, -1).astype(jnp.int32)
        ref.append(np.asarray(toks))

    out, k2, v2 = llama.decode_multi(
        params, cfg, cache.k, cache.v, toks0, jnp.zeros((B,), jnp.int32),
        tables, act, greedy, top_k, top_p, jax.random.PRNGKey(1), steps,
    )
    np.testing.assert_array_equal(np.asarray(out), np.stack(ref))
    # KV caches identical too (skip scratch block 0).
    np.testing.assert_allclose(np.asarray(k2[:, 1:]), np.asarray(k[:, 1:]), rtol=1e-5, atol=1e-5)


def build_engine(steps: int, **kw):
    return TpuEngine.build(
        EngineArgs(
            model="tiny",
            dtype="float32",
            seed=3,
            eos_token_ids=[1],
            scheduler=SchedulerConfig(
                num_blocks=64,
                prefill_buckets=[16, 32, 64],
                decode_buckets=[1, 2, 4, 8],
                num_scheduler_steps=steps,
                **kw,
            ),
        )
    )


def req(tokens, max_tokens):
    return {
        "token_ids": tokens,
        "sampling_options": {"temperature": 0.0},
        "stop_conditions": {"max_tokens": max_tokens, "ignore_eos": True},
    }


async def collect(engine, request):
    out, fin = [], None
    async for frame in engine.generate(request, Context()):
        data = frame.data if hasattr(frame, "data") else frame
        if data:
            out.extend(data.get("token_ids") or [])
            fin = data.get("finish_reason") or fin
    return out, fin


async def test_engine_multi_step_matches_single_step():
    # max_tokens=10 is NOT a multiple of the 4-step window: trimming matters.
    single = build_engine(steps=1)
    out1, fin1 = await collect(single, req(list(range(20, 36)), max_tokens=10))
    await single.stop()

    multi = build_engine(steps=4)
    out4, fin4 = await collect(multi, req(list(range(20, 36)), max_tokens=10))
    await multi.stop()

    assert out4 == out1, f"multi-step {out4} != single-step {out1}"
    assert len(out4) == 10 and fin4 == fin1 == "length"


async def test_multi_step_near_max_seq_len_falls_back():
    """A window that would run past max_seq_len (tiny: 256) must fall back
    to single-step and finish with 'length' instead of crashing on the
    clamped block table."""
    eng = build_engine(steps=8)
    prompt = list(range(2, 250))  # 248 tokens; limit hit mid-generation
    out, fin = await collect(eng, req(prompt, max_tokens=64))
    await eng.stop()
    assert fin == "length"
    assert 0 < len(out) <= 256 - 248


async def test_engine_multi_step_concurrent_batch():
    multi = build_engine(steps=4)
    reqs = [req(list(range(10 + i, 26 + i)), max_tokens=9) for i in range(3)]
    results = await asyncio.gather(*(collect(multi, r) for r in reqs))
    await multi.stop()
    for out, fin in results:
        assert len(out) == 9 and fin == "length"
    # Allocator fully drained after all sequences finish.
    assert multi.scheduler.allocator.num_active == 0


def test_decode_multi_kernel_matches_gather():
    """Multi-step window with the Pallas kernel (in-register window fold,
    interpret mode on CPU) ≡ the gather path, greedy."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.kv_cache import KvCacheArrays
    from dynamo_tpu.engine.models import llama

    cfg = get_config("tiny")
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    cache = KvCacheArrays.create(cfg, 24, dtype=jnp.float32)
    B, w = 2, 4
    table = jnp.array([1, 2, 3, 0], dtype=jnp.int32)
    logits, k, v = llama.prefill(
        params, cfg, cache.k, cache.v,
        jnp.arange(7, 23, dtype=jnp.int32), jnp.int32(16), jnp.int32(0), table,
    )
    toks = jnp.array([int(jnp.argmax(logits)), 0], dtype=jnp.int32)
    pos = jnp.array([16, 0], dtype=jnp.int32)
    tables = jnp.zeros((B, 4), dtype=jnp.int32).at[0].set(table)
    active = jnp.array([True, False])
    out, _, _ = llama.decode_multi(
        params, cfg, k, v, toks, pos, tables, active,
        jnp.zeros((B,)), jnp.zeros((B,), jnp.int32), jnp.ones((B,)),
        jax.random.PRNGKey(1), w,
    )
    window_toks = [int(t) for t in out[:, 0]]
    # Reference: repeated single-step greedy decode over the same cache.
    single = []
    cur, p0 = toks, pos
    for _ in range(w):
        lg, k, v = llama.decode(params, cfg, k, v, cur, p0, tables, active)
        nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        single.append(int(nxt[0]))
        cur, p0 = nxt, p0 + 1
    assert window_toks == single


def test_mla_decode_multi_matches_single_greedy():
    """MLA window-local multi-step ≡ repeated single-step decode, greedy."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.kv_cache import KvCacheArrays
    from dynamo_tpu.engine.models import mla

    cfg = get_config("tiny-mla")
    params = mla.init_params(cfg, jax.random.PRNGKey(2), dtype=jnp.float32)
    B, w = 2, 4
    table = jnp.array([1, 2, 3, 0], dtype=jnp.int32)

    def prefill_once():
        cache = KvCacheArrays.create(cfg, 24, dtype=jnp.float32)
        logits, k, v = mla.prefill(
            params, cfg, cache.k, cache.v,
            jnp.arange(7, 23, dtype=jnp.int32), jnp.int32(16), jnp.int32(0), table,
        )
        return int(jnp.argmax(logits)), k, v

    t0, k, v = prefill_once()
    toks = jnp.array([t0, 0], dtype=jnp.int32)
    pos = jnp.array([16, 0], dtype=jnp.int32)
    tables = jnp.zeros((B, 4), dtype=jnp.int32).at[0].set(table)
    active = jnp.array([True, False])

    out, k_multi, _ = mla.decode_multi(
        params, cfg, k, v, toks, pos, tables, active,
        jnp.zeros((B,)), jnp.zeros((B,), jnp.int32), jnp.ones((B,)),
        jax.random.PRNGKey(1), w,
    )
    multi_toks = [int(t) for t in out[:, 0]]

    # Reference: repeated single-step decode from the same prefill state.
    _, k2, v2 = prefill_once()
    cur, cur_pos = toks, pos
    single_toks = []
    for _ in range(w):
        logits, k2, _ = mla.decode(params, cfg, k2, v2, cur, cur_pos, tables, active)
        nxt = int(jnp.argmax(logits[0]))
        single_toks.append(nxt)
        cur = jnp.array([nxt, 0], dtype=jnp.int32)
        cur_pos = cur_pos + 1
    assert multi_toks == single_toks, (multi_toks, single_toks)
    # Cache contents identical after the window — real blocks only (block 0
    # is the scratch sink for inactive lanes: duplicate scatter targets there
    # legitimately pick different winners between the two paths).
    import numpy as np
    np.testing.assert_allclose(
        np.asarray(k_multi[:, 1:4]), np.asarray(k2[:, 1:4]), rtol=1e-5, atol=1e-5
    )
