"""Unit tests for the pub/sub transport (NATS-role semantics)."""

import asyncio

from dynamo_tpu.runtime.transports.pubsub import MemPubSub, subject_matches


def test_subject_matching():
    assert subject_matches("a.b.c", "a.b.c")
    assert subject_matches("a.*.c", "a.b.c")
    assert subject_matches("a.>", "a.b.c.d")
    assert not subject_matches("a.b", "a.b.c")
    assert not subject_matches("a.b.c", "a.b")
    assert not subject_matches("a.*.x", "a.b.c")


async def test_publish_subscribe():
    bus = MemPubSub()
    sub = await bus.subscribe("rq.ns.comp.ep.*")
    await bus.publish("rq.ns.comp.ep.1a", b"hello")
    msg = await asyncio.wait_for(sub.next(), 2)
    assert msg.data == b"hello" and msg.subject == "rq.ns.comp.ep.1a"
    await sub.unsubscribe()
    await bus.close()


async def test_queue_group_load_balance():
    bus = MemPubSub()
    s1 = await bus.subscribe("work.q", queue_group="g")
    s2 = await bus.subscribe("work.q", queue_group="g")
    for i in range(4):
        await bus.publish("work.q", str(i).encode())
    got1 = [await asyncio.wait_for(s1.next(), 2) for _ in range(2)]
    got2 = [await asyncio.wait_for(s2.next(), 2) for _ in range(2)]
    all_data = sorted(m.data for m in got1 + got2)
    assert all_data == [b"0", b"1", b"2", b"3"]
    await bus.close()


async def test_request_reply():
    bus = MemPubSub()
    sub = await bus.subscribe("svc.echo")

    async def responder():
        msg = await sub.next()
        await bus.publish(msg.reply_to, b"pong:" + msg.data)

    task = asyncio.create_task(responder())
    reply = await asyncio.wait_for(bus.request("svc.echo", b"ping"), 2)
    assert reply.data == b"pong:ping"
    await task
    await bus.close()


async def test_stream_replay_and_tail():
    bus = MemPubSub()
    stream = await bus.stream("kv_events")
    for i in range(3):
        await stream.publish("kv_events", str(i).encode())

    got = []

    async def consume():
        async for msg in stream.consume(from_seq=1):
            got.append(msg)
            if len(got) == 5:
                return

    task = asyncio.create_task(consume())
    await asyncio.sleep(0.01)
    await stream.publish("kv_events", b"3")
    await stream.publish("kv_events", b"4")
    await asyncio.wait_for(task, 2)
    assert [m.data for m in got] == [b"0", b"1", b"2", b"3", b"4"]
    assert [m.seq for m in got] == [1, 2, 3, 4, 5]


async def test_stream_purge_after_snapshot():
    bus = MemPubSub()
    stream = await bus.stream("s")
    for i in range(10):
        await stream.publish("s", str(i).encode())
    await stream.purge(up_to_seq=7)
    batch = await stream.fetch(from_seq=1)
    assert [m.seq for m in batch] == [8, 9, 10]


async def test_object_store():
    bus = MemPubSub()
    store = await bus.object_store("radix-bucket")
    await store.put("snapshot", b"\x00\x01")
    assert await store.get("snapshot") == b"\x00\x01"
    assert await store.list() == ["snapshot"]
    assert await store.delete("snapshot")
    assert await store.get("snapshot") is None
