"""Mocker timing-model fidelity (VERDICT r2 #5): the batched simulation core
must exhibit the queueing effects routers/planner decisions depend on —
ITL rising with batch width and active KV, watermark preemption, and load
curves realistic enough to drive the planner end-to-end.
Ref: lib/llm/src/mocker/{engine.rs:48, scheduler.rs:240}."""

import asyncio
import time

from dynamo_tpu.llm.mocker import MockEngineArgs, MockTpuEngine
from dynamo_tpu.runtime.engine import Context


def req(tokens, n):
    return {"token_ids": tokens, "stop_conditions": {"max_tokens": n}}


async def run_fleet(engine, n_requests, prompt_len=64, out_len=20):
    async def one(i):
        gaps = []
        last = None
        async for frame in engine.generate(req(list(range(i, i + prompt_len)), out_len), Context()):
            now = time.monotonic()
            if last is not None:
                gaps.append(now - last)
            last = now
        return gaps

    results = await asyncio.gather(*(one(i) for i in range(n_requests)))
    gaps = [g for r in results for g in r]
    return sum(gaps) / len(gaps)


async def test_itl_rises_with_batch():
    """Mean inter-token latency at batch 16 must exceed batch 1 — the
    per-sequence and per-active-KV terms of decode_ms at work."""
    args = MockEngineArgs(num_blocks=4096, itl_base_ms=3.0, itl_per_seq_ms=0.5,
                          itl_per_kv_token_us=1.0, speedup_ratio=1.0)
    itl_1 = await run_fleet(MockTpuEngine(args), 1)
    itl_16 = await run_fleet(MockTpuEngine(args), 16)
    assert itl_16 > itl_1 * 1.5, (itl_1, itl_16)


async def test_itl_rises_with_context():
    """Same batch, longer active context ⇒ slower steps (KV term)."""
    args = MockEngineArgs(num_blocks=4096, itl_base_ms=2.0, itl_per_seq_ms=0.0,
                          itl_per_kv_token_us=5.0, speedup_ratio=1.0)
    short = await run_fleet(MockTpuEngine(args), 4, prompt_len=16)
    long = await run_fleet(MockTpuEngine(args), 4, prompt_len=512)
    assert long > short * 1.5, (short, long)


async def test_watermark_preemption_under_pressure():
    """A pool too small for the fleet forces preemptions, and every request
    still completes (recompute on readmission)."""
    args = MockEngineArgs(num_blocks=24, itl_base_ms=0.5, speedup_ratio=20.0,
                          watermark=0.1)
    engine = MockTpuEngine(args)

    async def one(i):
        toks = []
        async for frame in engine.generate(req(list(range(i * 7, i * 7 + 48)), 24), Context()):
            toks.extend(frame["token_ids"])
        return toks

    results = await asyncio.gather(*(one(i) for i in range(6)))
    assert all(len(r) == 24 for r in results)
    assert engine.preempt_total > 0
    assert engine.allocator.num_active == 0


async def test_planner_e2e_driven_by_mocker_load_curves():
    """Planner scaling decisions driven by load observed FROM a mocker fleet
    under two traffic levels: the high-load window must plan at least as
    many decode replicas, using the mocker's own metrics as the source."""
    from dynamo_tpu.planner import (
        DecodeInterpolator, Planner, PlannerConfig, PrefillInterpolator,
        SlaTargets, VirtualConnector,
    )
    from dynamo_tpu.planner.planner_core import ObservedLoad

    args = MockEngineArgs(num_blocks=2048, itl_base_ms=1.0, itl_per_seq_ms=0.2,
                          speedup_ratio=10.0)
    engine = MockTpuEngine(args)

    async def observe(rate_reqs, prompt_len=64, out_len=16):
        """Drive `rate_reqs` concurrent requests, sample the mocker's metrics
        mid-flight, and convert them into an ObservedLoad window."""
        t0 = time.monotonic()

        async def one(i):
            async for _ in engine.generate(req(list(range(i, i + prompt_len)), out_len), Context()):
                pass

        tasks = [asyncio.create_task(one(i)) for i in range(rate_reqs)]
        await asyncio.sleep(0.01)
        m = engine.metrics()  # mocker-sourced snapshot under load
        await asyncio.gather(*tasks)
        wall = max(time.monotonic() - t0, 1e-3)
        if rate_reqs >= 8:  # small bursts can drain before the sample lands
            assert m.num_running + m.num_waiting > 0  # snapshot really saw load
        return ObservedLoad(request_rate=rate_reqs / wall, avg_isl=prompt_len, avg_osl=out_len)

    prefill_interp = PrefillInterpolator(
        isl=[16, 64, 256, 1024], ttft_ms=[2, 5, 15, 60], thpt_per_chip=[4000, 6000, 7000, 6500],
    )
    decode_interp = DecodeInterpolator(
        active_kv=[8, 32, 128, 512], context_len=[256, 256, 256, 256],
        itl_ms=[3, 5, 9, 20], thpt_per_chip=[80, 250, 700, 1400],
    )
    planner = Planner(
        PlannerConfig(max_chip_budget=16, sla=SlaTargets(itl_ms=8.0)),
        VirtualConnector(),
        prefill_interp,
        decode_interp,
        observe_fn=None,
    )
    low = planner.compute_replicas(await observe(2))
    high = planner.compute_replicas(await observe(24))
    assert high.decode >= low.decode
    assert high.prefill >= low.prefill
    assert high.decode > 1 or high.prefill > 1  # high load actually scales
