"""Device-truth profiling plane tests (PR 15).

Four layers, matching the subsystem's split:

- **trace parser** — pure-stdlib Chrome trace-event attribution against
  hand-built fixtures: exact per-kernel durations, interval-union busy time
  (nested/overlapping events never double-count), host-lane exclusion, the
  ops-thread filter, the no-device-lane fallback, and tolerance for
  truncated gzip / truncated JSON / outright garbage (a profiler artifact
  cut mid-write must yield its prefix, not a crash);
- **continuous sampler** — duty-cycle and rate-limit gating under an
  injected clock (first-window grace, max_duty interval clamp, force
  bypass), busy-yield accounting, error accounting, and one full window
  against a stub profiler writing a fixture artifact;
- **capture serialization** — DeviceProfiler's one-capture-at-a-time
  invariant under real thread races: wait=False gets a structured busy,
  wait=True queues, collisions are counted, start/stop never interleave;
- **measured truth → capacity** — record_measured_window's derived gauges,
  the cost-model calibration sanity band, and the ProfiledCapacityModel
  replay: an autoscale decision table that starts on wrong declared rates
  and converges to the measured-rate oracle.

Plus ``tools/bench_diff.py``: every load_round input shape the BENCH_r*
history actually contains, and the per-direction regression verdicts.
"""

import gzip
import importlib.util
import json
import os
import sys
import threading
import time

import pytest

from dynamo_tpu.engine.flight_recorder import FlightRecorder, StepCostModel
from dynamo_tpu.planner.controller import (
    DECODE,
    PREFILL,
    AutoscaleController,
    ControllerConfig,
    FleetView,
    ProfiledCapacityModel,
    StaticCapacityModel,
    WorkerView,
)
from dynamo_tpu.planner.planner_core import ObservedLoad
from dynamo_tpu.runtime.profiling import (
    ContinuousProfileConfig,
    ContinuousProfiler,
    DeviceProfiler,
    load_trace_dir,
    parse_trace_bytes,
    parse_trace_events,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- fixture builders ---------------------------------------------------------
def _pmeta(pid, name):
    return {"ph": "M", "pid": pid, "name": "process_name", "args": {"name": name}}


def _tmeta(pid, tid, name):
    return {"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
            "args": {"name": name}}


def _x(pid, tid, name, ts, dur):
    return {"ph": "X", "pid": pid, "tid": tid, "name": name, "ts": ts, "dur": dur}


def device_fixture_events():
    """One TPU lane with an ops thread + a modules thread, one host lane.

    Kernel lane (7, 1): fused windows at [0,100) [200,250) [300,350) and a
    sampler fusion at [400,425) — busy union 225us, wall span 425us.
    """
    return [
        _pmeta(7, "/device:TPU:0 (fixture)"),
        _tmeta(7, 1, "XLA Ops"),
        _tmeta(7, 2, "XLA Modules"),
        _pmeta(99, "python"),
        _tmeta(99, 1, "main"),
        _x(7, 1, "fused_decode_window(steps=8)", 0, 100),
        _x(7, 1, "fused_decode_window(steps=8)", 200, 50),
        _x(7, 1, "fused_decode_window(steps=8)", 300, 50),
        _x(7, 1, "fusion.sample_rows", 400, 25),
        _x(7, 2, "jit_decode_window", 0, 425),  # module span, not a kernel
        _x(99, 1, "host_busy_loop", 0, 1000),   # host lane, excluded
    ]


FIXTURE_BUSY_US = 225.0
FIXTURE_WALL_US = 425.0


# --- trace parser -------------------------------------------------------------
def test_fixture_exact_attribution():
    s = parse_trace_events(device_fixture_events())
    assert s.device_lane_found
    assert not s.truncated
    assert s.events_total == 6          # every ph=="X", host included
    assert s.kernel_events == 4         # ops-thread events only
    assert s.device_lanes == 1
    assert s.device_time_us == FIXTURE_BUSY_US
    assert s.wall_us == FIXTURE_WALL_US
    fused = s.kernels["fused_decode_window(steps=8)"]
    assert (fused.count, fused.total_us, fused.max_us) == (3, 200.0, 100.0)
    sample = s.kernels["fusion.sample_rows"]
    assert (sample.count, sample.total_us) == (1, 25.0)
    assert s.launch_count("fused_decode_window") == 3
    top = s.top(2)
    assert top[0]["name"] == "fused_decode_window(steps=8)"
    assert top[0]["share"] == pytest.approx(200.0 / 225.0, abs=1e-3)
    assert s.top_share() == pytest.approx(200.0 / 225.0)


def test_nested_and_overlapping_events_union_once():
    """Nested sub-events and overlapping launches in one lane must not
    double-count busy time — attribution per kernel still sums raw."""
    events = [
        _pmeta(7, "/device:TPU:0"),
        _x(7, 1, "outer_fusion", 0, 100),
        _x(7, 1, "nested.child", 10, 30),    # inside outer
        _x(7, 1, "tail_overlap", 90, 30),    # overlaps outer's tail
    ]
    s = parse_trace_events(events)
    assert s.device_time_us == 120.0          # union of [0,100)∪[10,40)∪[90,120)
    assert s.kernels["outer_fusion"].total_us == 100.0
    assert s.kernels["nested.child"].total_us == 30.0
    # Two parallel lanes ADD: same events split across tids double the union.
    par = [
        _pmeta(7, "/device:TPU:0"),
        _x(7, 1, "k", 0, 100),
        _x(7, 2, "k", 0, 100),
    ]
    assert parse_trace_events(par).device_time_us == 200.0


def test_thread_filter_requires_named_ops_threads():
    """The ops-thread filter only applies when the device pid HAS a named
    ops thread; device fixtures without thread metadata keep everything."""
    bare = [
        _pmeta(7, "/device:TPU:0"),
        _x(7, 1, "kernel_a", 0, 10),
        _x(7, 5, "kernel_b", 20, 10),
    ]
    s = parse_trace_events(bare)
    assert s.kernel_events == 2 and s.device_time_us == 20.0
    # With an ops thread present, other device threads are module/host noise.
    s2 = parse_trace_events(device_fixture_events())
    assert "jit_decode_window" not in s2.kernels
    assert "host_busy_loop" not in s2.kernels


def test_no_device_lane_falls_back_to_all_events():
    """CPU CI traces have no /device: lane — the parser degrades to
    'everything is a kernel' rather than an empty summary."""
    events = [
        _pmeta(1, "python"),
        _x(1, 1, "cpu_fusion", 0, 40),
        _x(1, 2, "cpu_copy", 100, 10),
    ]
    s = parse_trace_events(events)
    assert not s.device_lane_found
    assert s.kernel_events == 2
    assert s.device_time_us == 50.0


def test_malformed_events_skipped():
    events = [
        _pmeta(7, "/device:TPU:0"),
        _x(7, 1, "good", 0, 10),
        _x(7, 1, "negative_dur", 20, -5),
        {"ph": "X", "pid": 7, "tid": 1, "name": "bad_ts", "ts": "nan?", "dur": "x"},
        "not even a dict",
        {"ph": "B", "pid": 7, "tid": 1, "name": "begin_only", "ts": 5},
    ]
    s = parse_trace_events(events)
    assert list(s.kernels) == ["good"]
    assert s.device_time_us == 10.0


def _doc_bytes(events):
    return json.dumps({"traceEvents": events, "displayTimeUnit": "ns"}).encode()


def test_gzip_roundtrip_matches_plain():
    raw = _doc_bytes(device_fixture_events())
    plain = parse_trace_bytes(raw)
    gz = parse_trace_bytes(gzip.compress(raw))
    assert not gz.truncated
    assert gz.device_time_us == plain.device_time_us == FIXTURE_BUSY_US
    assert gz.launch_count("fused_decode_window") == 3


def test_truncated_json_recovers_prefix_exactly():
    """Cut the document right after the second fused launch: the scanner
    must recover exactly the events serialized before the cut."""
    events = device_fixture_events()
    parts = [json.dumps(e) for e in events]
    keep = 7  # metadata (5) + first two fused launches
    text = '{"traceEvents": [' + ", ".join(parts[:keep]) + ", " + parts[keep][:10]
    s = parse_trace_bytes(text.encode())
    assert s.truncated
    assert s.kernel_events == 2
    assert s.device_time_us == 150.0  # [0,100) + [200,250)
    assert s.launch_count("fused_decode_window") == 2


def test_truncated_gzip_yields_prefix_not_crash():
    data = gzip.compress(_doc_bytes(device_fixture_events()))
    s = parse_trace_bytes(data[: len(data) // 2])
    assert s.truncated
    assert s.kernel_events <= 4
    assert s.device_time_us <= FIXTURE_BUSY_US


def test_garbage_bytes_yield_empty_summary():
    s = parse_trace_bytes(b"\x00\xffnot a trace at all")
    assert s.truncated
    assert s.kernel_events == 0 and s.device_time_us == 0.0
    assert s.top() == [] and s.top_share() == 0.0


def test_load_trace_dir_newest_artifact_wins(tmp_path):
    assert load_trace_dir(str(tmp_path)) is None           # empty dir
    assert load_trace_dir(str(tmp_path / "missing")) is None
    old = tmp_path / "plugins" / "profile" / "run1"
    old.mkdir(parents=True)
    (old / "host.trace.json").write_bytes(_doc_bytes([
        _pmeta(7, "/device:TPU:0"), _x(7, 1, "old_kernel", 0, 10),
    ]))
    new = tmp_path / "plugins" / "profile" / "run2"
    new.mkdir(parents=True)
    p = new / "host.trace.json.gz"
    p.write_bytes(gzip.compress(_doc_bytes(device_fixture_events())))
    now = time.time()
    os.utime(old / "host.trace.json", (now - 100, now - 100))
    os.utime(p, (now, now))
    s = load_trace_dir(str(tmp_path))
    assert s is not None and "old_kernel" not in s.kernels
    assert s.launch_count("fused_decode_window") == 3


# --- continuous sampler gating under an injected clock ------------------------
class _StubProfiler:
    """DeviceProfiler stand-in: no jax, no sleeping — returns a canned
    status, writing a fixture artifact on the "ok" path."""

    def __init__(self, tmp_path, mode="ok", events=None):
        self.tmp_path = tmp_path
        self.mode = mode
        self.events = events if events is not None else device_fixture_events()
        self.calls = []
        self._seq = 0

    def capture(self, seconds, label="manual", wait=False):
        self.calls.append((seconds, label, wait))
        if self.mode == "busy":
            return {"status": "busy"}
        if self.mode == "error":
            return {"status": "error: RuntimeError: no backend"}
        self._seq += 1
        d = os.path.join(str(self.tmp_path), f"cap_{self._seq}")
        os.makedirs(d)
        with open(os.path.join(d, "host.trace.json"), "w") as f:
            json.dump({"traceEvents": self.events}, f)
        return {"status": "ok", "path": d, "seconds": seconds, "label": label}


def _clocked(profiler, cfg=None, **kw):
    t = [0.0]
    cont = ContinuousProfiler(profiler, cfg or ContinuousProfileConfig(),
                              clock=lambda: t[0], **kw)
    return cont, t


def test_first_window_waits_full_interval(tmp_path):
    cont, t = _clocked(_StubProfiler(tmp_path))
    assert cont.effective_interval_s == 30.0
    assert not cont.due(0.0) and not cont.due(29.9)
    assert cont.due(30.0)
    assert cont.sample_once(now=10.0) == {"status": "not_due"}
    assert cont.windows_total == 0 and not cont.profiler.calls


def test_max_duty_clamps_interval():
    cfg = ContinuousProfileConfig(window_s=0.5, interval_s=1.0, max_duty=0.02)
    cont, _ = _clocked(_StubProfiler("/tmp"), cfg)
    assert cont.effective_interval_s == 25.0  # 0.5 / 0.02 floors the 1s ask
    assert cont.duty_cycle == pytest.approx(0.02)
    # Defaults sit well inside the cap.
    d, _ = _clocked(_StubProfiler("/tmp"))
    assert d.duty_cycle == pytest.approx(0.25 / 30.0)
    assert d.duty_cycle <= d.config.max_duty


def test_force_bypasses_gate_and_rearms_it(tmp_path):
    cont, t = _clocked(_StubProfiler(tmp_path))
    rec = cont.sample_once(now=10.0, force=True)
    assert rec["status"] == "ok"
    assert cont.windows_total == 1
    # The forced window reset the limiter: next one is due at 10 + interval.
    assert not cont.due(35.0) and cont.due(40.0)
    assert cont.sample_once(now=20.0) == {"status": "not_due"}


def test_busy_profiler_yields_and_counts(tmp_path):
    cont, _ = _clocked(_StubProfiler(tmp_path, mode="busy"))
    assert cont.sample_once(force=True) == {"status": "skipped_busy"}
    assert cont.skipped_busy_total == 1 and cont.errors_total == 0
    assert cont.windows_total == 0
    # The sampler never queues: the stub saw wait=False.
    assert cont.profiler.calls[-1][2] is False


def test_capture_error_counts_not_raises(tmp_path):
    cont, _ = _clocked(_StubProfiler(tmp_path, mode="error"))
    res = cont.sample_once(force=True)
    assert res["status"].startswith("error")
    assert cont.errors_total == 1 and cont.windows_total == 0


def test_full_window_record_and_sink(tmp_path):
    probes = [(1e12, 2e12, 0.20, 10), (2e12, 3e12, 0.43, 13)]
    sunk = []
    stub = _StubProfiler(tmp_path)
    cont, _ = _clocked(stub, cost_probe=lambda: probes.pop(0),
                       sink=sunk.append)
    rec = cont.sample_once(force=True)
    assert rec["status"] == "ok"
    assert rec["wall_s"] == 0.25
    assert rec["device_time_s"] == pytest.approx(FIXTURE_BUSY_US / 1e6)
    assert rec["flops"] == pytest.approx(1e12)
    assert rec["bytes"] == pytest.approx(1e12)
    assert rec["step_seconds"] == pytest.approx(0.23)
    assert rec["fused_windows"] == 3            # cost-probe delta
    assert rec["fused_kernel_launches"] == 3    # trace-side count
    assert rec["launches_per_fused_window"] == 1.0
    assert rec["device_lane_found"] and not rec["truncated"]
    assert sunk == [rec]
    # keep_artifacts defaults off: the capture dir is gone after parsing.
    assert not os.path.exists(os.path.join(str(tmp_path), "cap_1"))
    stats = cont.to_stats()
    assert stats["device_profile_windows_total"] == 1
    assert stats["device_profile_window_seconds_total"] == 0.25
    assert stats["device_profile_errors_total"] == 0
    assert stats["device_profile_duty_cycle"] <= 0.02


def test_sink_failure_does_not_kill_the_window(tmp_path):
    def bad_sink(_rec):
        raise RuntimeError("sink bug")

    cont, _ = _clocked(_StubProfiler(tmp_path), sink=bad_sink)
    assert cont.sample_once(force=True)["status"] == "ok"
    assert cont.windows_total == 1 and cont.errors_total == 0


# --- DeviceProfiler serialization under real thread races ---------------------
def test_capture_conflicts_serialize_not_overlap(tmp_path, monkeypatch):
    jax = pytest.importorskip("jax")
    seq, started = [], threading.Event()
    lock = threading.Lock()

    def fake_start(path):
        with lock:
            seq.append("start")
        started.set()

    def fake_stop():
        with lock:
            seq.append("stop")

    monkeypatch.setattr(jax.profiler, "start_trace", fake_start)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake_stop)
    prof = DeviceProfiler(out_dir=str(tmp_path))

    results = {}
    t1 = threading.Thread(
        target=lambda: results.__setitem__("a", prof.capture(0.3, label="a")))
    t1.start()
    assert started.wait(5.0)
    # Non-waiting caller (the HTTP 409 path) gets a structured busy.
    busy = prof.capture(0.05, label="b", wait=False)
    assert busy["status"] == "busy" and busy["label"] == "b"
    # Waiting caller (incident path) queues behind the running window.
    t2 = threading.Thread(
        target=lambda: results.__setitem__("c", prof.capture(0.05, label="c",
                                                             wait=True)))
    assert prof.status()["busy"]
    t2.start()
    t1.join(10.0)
    t2.join(10.0)
    assert results["a"]["status"] == "ok" and results["c"]["status"] == "ok"
    st = prof.status()
    assert st["captures_total"] == 2
    assert st["capture_conflicts_total"] >= 1   # b for sure; c if it raced in
    assert not st["busy"]
    # THE invariant: trace windows never interleave.
    assert seq == ["start", "stop", "start", "stop"]


# --- measured truth in the flight recorder ------------------------------------
def _cost_model(**kw):
    kw.setdefault("param_count", 10**9)
    kw.setdefault("param_bytes", 2 * 10**9)
    kw.setdefault("kv_bytes_per_token", 1000.0)
    kw.setdefault("peak_flops", 1e14)
    kw.setdefault("peak_bw", 1e12)
    return StepCostModel(**kw)


def test_record_measured_window_derived_gauges():
    fr = FlightRecorder()
    fr.set_cost_model(_cost_model())
    assert "measured_windows_total" not in fr.to_stats()  # gated until data
    fr.record_measured_window({
        "wall_s": 0.25, "device_time_s": 0.2, "flops": 1e12, "bytes": 1e11,
        "step_seconds": 0.19, "top_kernel_share": 0.6,
        "launches_per_fused_window": 1.0,
        "top_kernels": [{"name": "fused_decode_window", "share": 0.6}],
    })
    stats = fr.to_stats()
    assert stats["measured_windows_total"] == 1
    assert stats["measured_mfu"] == pytest.approx(1e12 / 0.2 / 1e14)
    assert stats["measured_hbm_frac"] == pytest.approx(1e11 / 0.2 / 1e12)
    assert stats["measured_device_frac"] == pytest.approx(0.8)
    assert stats["measured_modeled_mfu_ratio"] == pytest.approx(0.19 / 0.2)
    assert stats["measured_top_kernel_share"] == pytest.approx(0.6)
    assert stats["measured_launches_per_fused_window"] == 1.0
    snap = fr.measured_snapshot()
    assert snap is not None and snap["top_kernels"][0]["name"] == "fused_decode_window"


def test_cost_model_calibration_band():
    cm = _cost_model()
    hand = 2.0 * cm.param_count
    assert cm.flops_per_token == hand and not cm.calibrated
    assert not cm.calibrate(hand * 0.1)       # below band: rejected
    assert not cm.calibrate(hand * 6.0)       # above band: rejected
    assert not cm.calibrate(0.0)
    assert cm.flops_per_token == hand and not cm.calibrated
    assert cm.calibrate(hand * 0.2)           # band edges inclusive
    assert cm.calibrated and cm.flops_per_token == hand * 0.2
    assert cm.calibration_source == "xla_cost_analysis"
    fr = FlightRecorder()
    fr.set_cost_model(cm)
    assert fr.to_stats()["cost_model_calibrated"] == 1.0


# --- profile-derived capacity -------------------------------------------------
def _measured_load(pre, dec, rate=4.0, isl=200.0, osl=50.0):
    return ObservedLoad(request_rate=rate, avg_isl=isl, avg_osl=osl,
                        measured_prefill_tok_s=pre, measured_decode_tok_s=dec)


def test_profiled_capacity_ema_and_gating():
    prior = StaticCapacityModel(400.0, 80.0, utilization=1.0)
    m = ProfiledCapacityModel(prior, alpha=0.5, min_windows=2)
    assert m.utilization == 1.0               # inherited from the prior
    m.observe(_measured_load(0.0, 0.0))       # idle window: never averaged in
    assert m.observations_total == 0
    m.observe(_measured_load(200.0, 40.0))    # first real window seeds the EMA
    assert m.measured_rates() == (0.0, 0.0)   # still riding the prior
    assert m.prefill_tokens_per_s(200.0) == 400.0
    m.observe(_measured_load(100.0, 20.0))
    assert m.measured_rates() == (150.0, 30.0)  # 200+0.5·(100−200), 40+0.5·(20−40)
    assert m.prefill_tokens_per_s(200.0) == 150.0
    assert m.decode_tokens_per_s(200.0, 50.0) == 30.0
    m.observe(_measured_load(0.0, 30.0))      # phases gate independently
    assert m.measured_rates() == (150.0, 30.0)
    assert m.observations_total == 3


def _view(pools):
    return FleetView(pools={
        PREFILL: [WorkerView(worker_id=100 + i) for i in range(pools[PREFILL])],
        DECODE: [WorkerView(worker_id=200 + i) for i in range(pools[DECODE])],
    }, drains_in_flight={})


def test_replay_decision_table_converges_to_measured_oracle():
    """The PR's closing loop: declared rates say 400/80 tok/s per worker,
    the device says 200/40. Replaying measured windows through decide(),
    the decision table starts at the declared-rate sizes and converges to
    the measured-rate oracle — then holds there."""
    prior = StaticCapacityModel(400.0, 80.0, utilization=1.0)
    model = ProfiledCapacityModel(prior, alpha=0.5, min_windows=2,
                                  utilization=1.0)
    ctrl = AutoscaleController(ControllerConfig(
        min_prefill=1, max_prefill=16, min_decode=1, max_decode=16,
        scale_cooldown_s=0.0, scale_up_stable_intervals=1,
        scale_down_stable_intervals=1, max_step=8, load_predictor="constant",
    ), model)
    pools = {PREFILL: 1, DECODE: 1}
    table = []
    now = 0.0
    for _ in range(6):
        decisions = ctrl.decide(_measured_load(200.0, 40.0), _view(pools), now)
        for d in decisions:
            if d.action != "hold":
                pools[d.pool] = d.target
        table.append((pools[PREFILL], pools[DECODE]))
        now += 30.0
    declared = prior.required(4.0, 200.0, 50.0)
    oracle = StaticCapacityModel(200.0, 40.0, utilization=1.0).required(
        4.0, 200.0, 50.0)
    assert table[0] == (declared[PREFILL], declared[DECODE]) == (2, 3)
    assert table[1] == (oracle[PREFILL], oracle[DECODE]) == (4, 5)
    assert table[-1] == table[-2] == table[-3] == (4, 5)  # converged, stable
    stats = ctrl.to_stats()
    assert stats["planner_measured_prefill_tok_s"] == 200.0
    assert stats["planner_measured_decode_tok_s"] == 40.0


def test_planner_stats_ride_prior_until_warm():
    ctrl = AutoscaleController(
        ControllerConfig(load_predictor="constant"),
        ProfiledCapacityModel(StaticCapacityModel(400.0, 80.0), min_windows=2))
    ctrl.decide(_measured_load(200.0, 40.0), _view({PREFILL: 1, DECODE: 1}), 0.0)
    stats = ctrl.to_stats()
    assert stats["planner_measured_prefill_tok_s"] == 0.0
    assert stats["planner_measured_decode_tok_s"] == 0.0


# --- tools/bench_diff.py ------------------------------------------------------
@pytest.fixture(scope="module")
def bench_diff():
    spec = importlib.util.spec_from_file_location(
        "bench_diff", os.path.join(REPO, "tools", "bench_diff.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_diff"] = mod  # dataclass field resolution needs this
    spec.loader.exec_module(mod)
    yield mod
    sys.modules.pop("bench_diff", None)


def _round(detail, metric="tok_s", value=100.0):
    return {"metric": metric, "value": value, "detail": detail}


def test_bench_diff_load_round_all_history_shapes(bench_diff, tmp_path):
    raw = tmp_path / "raw.json"
    raw.write_text(json.dumps(_round({"observability": {"overhead_pct": 1.0}})))
    obj, src = bench_diff.load_round(str(raw))
    assert src == "raw" and obj["detail"]["observability"]["overhead_pct"] == 1.0

    wrapped = tmp_path / "wrapped.json"
    wrapped.write_text(json.dumps({"n": 6, "cmd": "bench", "rc": 0, "tail": "",
                                   "parsed": _round({})}))
    _, src = bench_diff.load_round(str(wrapped))
    assert src == "wrapper"

    # parsed=null but a complete final JSON line survived in the tail.
    tail_line = tmp_path / "tail_line.json"
    tail_line.write_text(json.dumps({
        "n": 7, "cmd": "bench", "rc": 1, "parsed": None,
        "tail": "noise line\n" + json.dumps(_round({"prefill": {"tok_s": 9}})),
    }))
    obj, src = bench_diff.load_round(str(tail_line))
    assert src == "tail-line" and obj["detail"]["prefill"]["tok_s"] == 9

    # parsed=null and the tail is a front-truncated fragment (the BENCH_r05
    # shape): intact per-section sub-objects are still recovered.
    frag = ('"ttft_p50_ms": 38.7}, "observability": {"overhead_pct": 1.2, '
            '"within_budget": true}, "autoscale": {"summary": '
            '{"slo_attainment": 0.97, "converged": true}}, '
            '"decode_sweep": [{"batch": 8, "ctx": 1024, "tok_s_per_user": 11.0}]')
    tail_frag = tmp_path / "tail_frag.json"
    tail_frag.write_text(json.dumps({"n": 5, "cmd": "bench", "rc": 1,
                                     "parsed": None, "tail": frag}))
    obj, src = bench_diff.load_round(str(tail_frag))
    assert src.startswith("tail-fragment")
    assert obj["detail"]["observability"]["within_budget"] is True
    assert obj["detail"]["decode_sweep"][0]["batch"] == 8

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"something": "else"}))
    with pytest.raises(ValueError):
        bench_diff.load_round(str(bad))


def test_bench_diff_verdicts_per_direction(bench_diff):
    old = _round({
        "observability": {"overhead_pct": 1.0, "within_budget": True,
                          "compiles_after_warmup": 0},
        "prefix_reuse": {"speedup": 2.0},
        "autoscale": {"summary": {"slo_attainment": 0.97, "converged": True}},
        "http_e2e": {"tok_s": 100.0},
        "decode_sweep": [{"batch": 8, "ctx": 1024, "tok_s_per_user": 10.0}],
    })
    new = _round({
        "observability": {"overhead_pct": 2.5, "within_budget": False,
                          "compiles_after_warmup": 0},
        "prefix_reuse": {"speedup": 1.9},     # −5%: inside the 15% band
        "autoscale": {"summary": {"slo_attainment": 0.99, "converged": True}},
        "http_e2e": {"tok_s": 120.0},
        "decode_sweep": [{"batch": 8, "ctx": 1024, "tok_s_per_user": 8.0}],
    }, value=50.0)
    rows = bench_diff.compare(old, new)
    by_label = {r["label"]: r["verdict"] for r in rows}
    assert by_label["tok_s"] == "regression"              # headline −50%
    assert by_label["b8 ctx1024 tok/s/user"] == "regression"  # −20% point
    assert by_label["tracing overhead %"] == "regression"  # +1.5 > 1.0 abs tol
    assert by_label["within ≤2% budget"] == "regression"   # flag flip
    assert by_label["post-warmup compiles = 0"] == "ok"
    assert by_label["prefix-reuse speedup"] == "ok"        # inside rel band
    assert by_label["SLO attainment"] == "improved"        # summary fallback dug
    assert by_label["http e2e tok/s"] == "improved"
    assert by_label["measured/modeled agreement"] == "not-comparable"
    # A side with no sections at all can never regress anything.
    only_old = bench_diff.compare(old, _round({}))
    assert all(r["verdict"] != "regression" for r in only_old)


def test_bench_diff_strict_exit_codes(bench_diff, tmp_path, capsys):
    good = _round({"observability": {"overhead_pct": 1.0, "within_budget": True}})
    bad = _round({"observability": {"overhead_pct": 3.0, "within_budget": False}})
    p_good, p_bad = tmp_path / "g.json", tmp_path / "b.json"
    p_good.write_text(json.dumps(good))
    p_bad.write_text(json.dumps(bad))
    assert bench_diff.main([str(p_good), str(p_bad)]) == 0          # report only
    assert bench_diff.main([str(p_good), str(p_bad), "--strict"]) == 1
    assert bench_diff.main([str(p_good), str(p_good), "--strict"]) == 0
    capsys.readouterr()  # drop the human-format reports
    assert bench_diff.main([str(p_good), str(p_bad), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["regressions"] >= 2
    assert payload["new"]["source"] == "raw"
