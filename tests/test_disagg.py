"""Disaggregated prefill/decode tests: KV transfer correctness (disagg ≡
aggregated, token-exact), conditional disagg, prefill-pool fallback.
Ref: SURVEY.md §3C + tests/serve disagg coverage."""

import asyncio
import time

import pytest

from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.disagg import (
    DisaggDecodeHandler,
    DisaggRouter,
    DisaggRouterConf,
    KvExportService,
    PrefillQueueWorker,
)
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context


def build_engine():
    # Same seed ⇒ identical weights across instances (random-init parity).
    return TpuEngine.build(
        EngineArgs(
            model="tiny",
            dtype="float32",
            seed=7,
            scheduler=SchedulerConfig(
                num_blocks=64,
                prefill_buckets=[16, 32, 64],
                decode_buckets=[1, 2, 4, 8],
                enable_prefix_caching=False,  # isolate the transfer path
            ),
        )
    )


def req(tokens, max_tokens=6):
    return {
        "token_ids": tokens,
        "sampling_options": {"temperature": 0.0},
        "stop_conditions": {"max_tokens": max_tokens},
    }


async def collect(engine_like, request, ctx=None):
    out = []
    fin = None
    async for frame in engine_like.generate(request, ctx or Context()):
        data = frame.data if hasattr(frame, "data") else frame
        if data:
            out.extend(data.get("token_ids") or [])
            fin = data.get("finish_reason") or fin
    return out, fin


async def setup_disagg(drt, *, conf=None):
    """Prefill worker + decode handler wired over the real wire path."""
    prefill_engine = build_engine()
    decode_engine = build_engine()

    prefill_ep = drt.namespace("disagg").component("prefill").endpoint("generate")
    handle = await prefill_ep.serve_endpoint(prefill_engine.generate, stats_handler=prefill_engine.stats_handler)
    kvx = KvExportService(drt, prefill_engine, handle.instance)
    await kvx.start()
    drt.local_engines.pop(handle.instance.instance_id)  # force wire path

    prefill_client = await prefill_ep.client()
    await prefill_client.wait_for_instances(1, timeout=5)

    disagg_router = None
    if conf is not None:
        disagg_router = DisaggRouter(drt, "tiny", conf=conf)
    handler = DisaggDecodeHandler(drt, decode_engine, prefill_client, disagg_router)
    return handler, prefill_engine, decode_engine, kvx, handle


async def test_disagg_matches_aggregated():
    drt = await DistributedRuntime.detached()
    try:
        handler, prefill_engine, decode_engine, kvx, handle = await setup_disagg(drt)
        prompt = list(range(20, 60))  # 40 tokens

        # Aggregated reference on a third identical engine.
        ref_engine = build_engine()
        ref, _ = await collect(ref_engine, req(prompt))
        await ref_engine.stop()

        out, fin = await collect(handler, req(prompt))
        assert out == ref, f"disagg {out} != aggregated {ref}"
        assert fin == "length"
        assert handler.remote_prefills == 1 and handler.local_prefills == 0
        # Prefill worker's export was consumed: no leaked blocks.
        assert prefill_engine.scheduler.allocator.num_active == 0
        assert not prefill_engine.scheduler._pending_exports

        await kvx.stop()
        await prefill_engine.stop()
        await decode_engine.stop()
    finally:
        await drt.shutdown()


async def test_conditional_disagg_short_prompt_local():
    drt = await DistributedRuntime.detached()
    try:
        handler, prefill_engine, decode_engine, kvx, handle = await setup_disagg(
            drt, conf=DisaggRouterConf(max_local_prefill_length=100)
        )
        out, _ = await collect(handler, req(list(range(30))))  # 30 < 100 ⇒ local
        assert handler.local_prefills == 1 and handler.remote_prefills == 0

        out2, _ = await collect(handler, req(list(range(120))))  # 120 > 100 ⇒ remote
        assert handler.remote_prefills == 1

        await kvx.stop()
        await prefill_engine.stop()
        await decode_engine.stop()
    finally:
        await drt.shutdown()


async def test_prefill_pool_death_falls_back_to_local():
    drt = await DistributedRuntime.detached()
    try:
        handler, prefill_engine, decode_engine, kvx, handle = await setup_disagg(drt)
        # Kill the prefill worker: its instance vanishes.
        await handle.stop()
        for _ in range(100):
            if not handler.prefill_client.instances:
                break
            await asyncio.sleep(0.02)

        out, fin = await collect(handler, req(list(range(40))))
        assert len(out) == 6 and fin == "length"
        assert handler.local_prefills == 1  # degraded gracefully

        await kvx.stop()
        await prefill_engine.stop()
        await decode_engine.stop()
    finally:
        await drt.shutdown()


async def test_prefill_first_queue_matches_aggregated():
    """prefill_first strategy: decode enqueues, a queue worker pulls the job,
    KV still transfers over the wire — output must match aggregated."""
    drt = await DistributedRuntime.detached()
    try:
        prefill_engine = build_engine()
        decode_engine = build_engine()

        # Prefill worker registers an endpoint only to own an Instance for the
        # KV export subject; jobs arrive via the queue, not the push path.
        prefill_ep = drt.namespace("disagg").component("prefill").endpoint("generate")
        handle = await prefill_ep.serve_endpoint(prefill_engine.generate, stats_handler=prefill_engine.stats_handler)
        kvx = KvExportService(drt, prefill_engine, handle.instance)
        await kvx.start()
        drt.local_engines.pop(handle.instance.instance_id)

        worker = PrefillQueueWorker(drt, prefill_engine, handle.instance)
        await worker.start()

        handler = DisaggDecodeHandler(
            drt, decode_engine, strategy="prefill_first", queue_reply_timeout_s=10.0
        )
        prompt = list(range(20, 60))

        ref_engine = build_engine()
        ref, _ = await collect(ref_engine, req(prompt))
        await ref_engine.stop()

        out, fin = await collect(handler, req(prompt))
        assert out == ref, f"prefill_first {out} != aggregated {ref}"
        assert fin == "length"
        assert handler.remote_prefills == 1 and worker.jobs_served == 1
        assert prefill_engine.scheduler.allocator.num_active == 0

        await worker.stop()
        await kvx.stop()
        await prefill_engine.stop()
        await decode_engine.stop()
    finally:
        await drt.shutdown()


async def test_prefill_first_no_workers_falls_back_local():
    """Zero live queue workers ⇒ immediate local prefill — the request must
    NOT pay queue_reply_timeout_s of TTFT discovering nobody will pull."""
    drt = await DistributedRuntime.detached()
    try:
        decode_engine = build_engine()
        handler = DisaggDecodeHandler(
            drt, decode_engine, strategy="prefill_first", queue_reply_timeout_s=30.0
        )
        t0 = time.monotonic()
        out, fin = await collect(handler, req(list(range(40))))
        assert len(out) == 6 and fin == "length"
        # Guard against paying the 30s queue timeout; generous margin for
        # first-jit compiles on a loaded single-core box (flaked at 5s).
        assert time.monotonic() - t0 < 15.0
        assert handler.remote_prefills == 0 and handler.local_prefills == 1
        await decode_engine.stop()
    finally:
        await drt.shutdown()


async def test_prefill_first_backoff_after_timeout():
    """A live-looking registration whose worker never replies triggers the
    timeout once, then the handler backs off to local for subsequent calls."""
    drt = await DistributedRuntime.detached()
    try:
        decode_engine = build_engine()
        handler = DisaggDecodeHandler(
            drt, decode_engine, strategy="prefill_first", queue_reply_timeout_s=0.3
        )
        # Stale-but-live registration (no actual worker pulling).
        await drt.store.put("wq/prefill/workers/dead", b"")
        out, fin = await collect(handler, req(list(range(40))))
        assert len(out) == 6 and fin == "length"
        assert handler.remote_prefills == 1  # attempted, timed out, degraded
        assert handler._backoff_until > time.monotonic()
        # Second request: inside the backoff window ⇒ straight to local.
        out, fin = await collect(handler, req(list(range(40, 80))))
        assert len(out) == 6
        assert handler.remote_prefills == 1 and handler.local_prefills == 2
        await decode_engine.stop()
    finally:
        await drt.shutdown()


async def test_unpulled_export_reclaimed_after_ttl():
    """Orphan guard: prefill exports nobody pulls are reclaimed after
    export_ttl_s instead of pinning KV blocks forever."""
    engine = TpuEngine.build(
        EngineArgs(
            model="tiny", dtype="float32", seed=7,
            scheduler=SchedulerConfig(num_blocks=64, export_ttl_s=0.3,
                                      prefill_buckets=[16, 32], decode_buckets=[1, 2]),
        )
    )
    engine.start()
    try:
        r = req(list(range(16)), max_tokens=1)
        r["disagg_params"] = {"do_remote_decode": True}
        await collect(engine, r)
        assert engine.scheduler._pending_exports  # export parked, blocks held
        held = engine.scheduler.allocator.num_active
        assert held > 0
        for _ in range(100):  # TTL sweep runs in the idle engine loop
            if not engine.scheduler._pending_exports:
                break
            await asyncio.sleep(0.05)
        assert not engine.scheduler._pending_exports
        assert engine.scheduler.allocator.num_active == 0
    finally:
        await engine.stop()


async def test_disagg_conf_hot_reload():
    drt = await DistributedRuntime.detached()
    try:
        router = DisaggRouter(drt, "m1", conf=DisaggRouterConf(max_local_prefill_length=10))
        await router.start()
        assert router.prefill_remote(50, True)
        assert not router.prefill_remote(5, True)
        # Dynamic config update through the store (the etcd-watch role).
        await drt.store.put(DisaggRouterConf.store_key("chat", "m1"), b'{"max_local_prefill_length": 1000}')
        for _ in range(50):
            if router.conf.max_local_prefill_length == 1000:
                break
            await asyncio.sleep(0.02)
        assert not router.prefill_remote(50, True)
        await router.stop()
    finally:
        await drt.shutdown()
