"""Sampled (rejection-sampling) speculative verification + sharded spec
serving (VERDICT r3 #7; ref surface: SpecDecodeStats _core.pyi:354-427,
algorithm: speculative sampling)."""

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions
from dynamo_tpu.engine.spec_decode import spec_verify


def test_spec_verify_greedy_matches_argmax_agreement():
    """temp=0 rows: accept == (proposal == target argmax); the correction /
    bonus token is the target argmax at the decision position."""
    B, G, V = 3, 3, 16
    rng = np.random.RandomState(0)
    d = jnp.asarray(rng.randn(B, G, V), jnp.float32)
    t = jnp.asarray(rng.randn(B, G + 1, V), jnp.float32)
    t_arg = np.asarray(jnp.argmax(t, axis=-1))
    proposals = np.zeros((B, G), np.int32)
    proposals[0] = t_arg[0, :G]        # full agreement
    proposals[1] = t_arg[1, :G]
    proposals[1, 1] = (t_arg[1, 1] + 1) % V  # disagree at position 1
    proposals[2, 0] = (t_arg[2, 0] + 3) % V  # disagree immediately
    zeros = jnp.zeros((B,), jnp.float32)
    accepted, nxt = spec_verify(
        d, t, jnp.asarray(proposals), zeros, jnp.zeros((B,), jnp.int32),
        jnp.ones((B,), jnp.float32), jax.random.PRNGKey(0),
    )
    accepted, nxt = np.asarray(accepted), np.asarray(nxt)
    assert accepted.tolist() == [G, 1, 0]
    assert nxt[0] == t_arg[0, G]   # bonus from position G
    assert nxt[1] == t_arg[1, 1]   # correction at the rejected position
    assert nxt[2] == t_arg[2, 0]


def test_spec_verify_identical_dists_accept_all():
    """Sampled rows where draft == target distributions: rejection sampling
    accepts every proposal (ratio = 1)."""
    B, G, V = 2, 4, 32
    logits = jnp.asarray(np.random.RandomState(1).randn(B, G + 1, V), jnp.float32)
    d = logits[:, :G]
    proposals = jnp.asarray(np.random.RandomState(2).randint(0, V, (B, G)), jnp.int32)
    temps = jnp.full((B,), 0.8, jnp.float32)
    accepted, _ = spec_verify(
        d, logits, proposals, temps, jnp.zeros((B,), jnp.int32),
        jnp.ones((B,), jnp.float32), jax.random.PRNGKey(3),
    )
    assert np.asarray(accepted).tolist() == [G, G]


def _spec_sched(mesh=None, parallel=None, gamma=3):
    c = get_config("tiny")
    params = llama.init_params(c, jax.random.PRNGKey(0), dtype=jnp.float32)
    draft = llama.init_params(c, jax.random.PRNGKey(1), dtype=jnp.float32)
    sched = Scheduler(
        c, params, SchedulerConfig(num_blocks=96, decode_buckets=[1, 2, 4]),
        dtype=jnp.float32, mesh=mesh, parallel=parallel,
    )
    sched.attach_draft(c, draft, gamma=gamma)
    return sched


def _drain(sched, n_steps=200):
    produced = {}
    for _ in range(n_steps):
        if not sched.has_work():
            break
        for seq, out in sched.step():
            produced.setdefault(seq.request_id, []).append(out)
    assert not sched.has_work()
    return produced


def test_mixed_greedy_and_sampled_spec_rounds():
    """A batch mixing temperature 0 and 0.8 rows runs SPECULATIVE rounds
    (previously sampled rows disabled speculation for the whole batch)."""
    sched = _spec_sched()
    sched.add_request("greedy", [1, 2, 3, 4], SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=10, ignore_eos=True))
    sched.add_request("sampled", [5, 6, 7, 8], SamplingParams(temperature=0.8, top_p=0.9),
                      StopConditions(max_tokens=10, ignore_eos=True))
    produced = _drain(sched)
    for rid in ("greedy", "sampled"):
        toks = [o.token_id for o in produced[rid] if o.token_id >= 0]
        assert len(toks) == 10, (rid, toks)
    assert sched.spec_stats.num_rounds > 0
    assert sched.spec_stats.num_draft_tokens > 0


def test_greedy_spec_output_matches_non_spec():
    """Greedy rows through rejection-sampling verification produce exactly
    the no-draft greedy continuation (one-hot dists make it deterministic)."""
    prompt = [9, 8, 7, 6, 5]
    sched = _spec_sched()
    sched.add_request("r", prompt, SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=8, ignore_eos=True))
    spec_toks = [o.token_id for o in _drain(sched)["r"] if o.token_id >= 0]

    c = get_config("tiny")
    params = llama.init_params(c, jax.random.PRNGKey(0), dtype=jnp.float32)
    plain = Scheduler(c, params, SchedulerConfig(num_blocks=96, decode_buckets=[1, 2, 4]),
                      dtype=jnp.float32)
    plain.add_request("r", prompt, SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=8, ignore_eos=True))
    plain_toks = [o.token_id for o in _drain(plain)["r"] if o.token_id >= 0]
    assert spec_toks == plain_toks


def test_spec_under_sharded_serving():
    """Draft params/cache ride the target's dp×tp mesh (VERDICT r3 #7)."""
    from dynamo_tpu.engine.sharding import ParallelConfig, build_mesh

    parallel = ParallelConfig(dp=4, tp=2)
    mesh = build_mesh(parallel)
    sched = _spec_sched(mesh=mesh, parallel=parallel)
    sched.add_request("r0", [1, 2, 3, 4, 5], SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=6, ignore_eos=True))
    sched.add_request("r1", [6, 7, 8], SamplingParams(temperature=0.6),
                      StopConditions(max_tokens=6, ignore_eos=True))
    produced = _drain(sched, 300)
    for rid in ("r0", "r1"):
        assert len([o for o in produced[rid] if o.token_id >= 0]) == 6
    assert sched.spec_stats.num_rounds > 0
