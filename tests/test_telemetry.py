"""SLA telemetry plane: digest correctness (relative-error + merge
properties), windowed views, SLO/goodput accounting, the stall watchdog,
fleet digest aggregation, the Prometheus parser against real render()
output, and the end-to-end signal path frontend → worker → scheduler →
aggregator → PrometheusObserver."""

import asyncio
import json
import math
import os
import random
import subprocess
import sys
import time

import aiohttp
import pytest

from dynamo_tpu.metrics_aggregator import DIGEST_KEYS, MetricsAggregator
from dynamo_tpu.planner.observer import (
    PrometheusObserver,
    parse_prometheus,
    parse_prometheus_samples,
)
from dynamo_tpu.runtime.telemetry import (
    DigestCollector,
    LatencyDigest,
    SloConfig,
    SloJudge,
    StallWatchdog,
    Telemetry,
    WindowedDigest,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RE = 0.01


def exact_quantile(values, q):
    vs = sorted(values)
    return vs[int(q * (len(vs) - 1))]


# --- digest correctness ------------------------------------------------------

STREAMS = {
    "lognormal": lambda rng: [rng.lognormvariate(0, 2) for _ in range(20000)],
    "uniform": lambda rng: [rng.uniform(1e-4, 10.0) for _ in range(20000)],
    # Adversarial: sorted ramp (every bucket in order), constant stream
    # (single bucket), nine decades of dynamic range, zeros mixed in.
    "sorted_ramp": lambda rng: [i / 1000.0 + 1e-6 for i in range(20000)],
    "constant": lambda rng: [0.25] * 5000,
    "nine_decades": lambda rng: [10 ** rng.uniform(-6, 3) for _ in range(20000)],
    "with_zeros": lambda rng: [0.0] * 500 + [rng.uniform(0.001, 1.0) for _ in range(5000)],
}


@pytest.mark.parametrize("name", sorted(STREAMS))
def test_digest_quantiles_within_relative_error(name):
    rng = random.Random(1234)
    values = STREAMS[name](rng)
    d = LatencyDigest(relative_error=RE)
    for v in values:
        d.observe(v)
    assert d.count == len(values)
    for q in (0.01, 0.1, 0.5, 0.9, 0.99, 0.999):
        est = d.quantile(q)
        exact = exact_quantile(values, q)
        if exact <= 1e-9:
            assert est == 0.0
            continue
        # DDSketch guarantee: the estimate is within the relative error of
        # a true sample value at (or adjacent to) the rank — allow 2α for
        # the rank-interpolation edge.
        assert abs(est - exact) <= 2 * RE * exact + 1e-12, (q, est, exact)


def test_digest_merge_equals_single_stream():
    rng = random.Random(7)
    values = [rng.lognormvariate(-2, 3) for _ in range(30000)]
    single = LatencyDigest(RE)
    parts = [LatencyDigest(RE) for _ in range(4)]
    for i, v in enumerate(values):
        single.observe(v)
        parts[i % 4].observe(v)
    merged = parts[0]
    for p in parts[1:]:
        merged.merge(p)
    assert merged.buckets == single.buckets
    assert merged.count == single.count and merged.zero_count == single.zero_count
    assert math.isclose(merged.sum, single.sum, rel_tol=1e-9)
    for q in (0.5, 0.9, 0.99):
        assert merged.quantile(q) == single.quantile(q)


def test_digest_merge_rejects_mismatched_error():
    with pytest.raises(ValueError, match="relative error"):
        LatencyDigest(0.01).merge(LatencyDigest(0.02))


def test_digest_wire_roundtrip_through_json():
    d = LatencyDigest(RE)
    for v in (0.0, 1e-5, 0.01, 0.5, 3.0, 3.0, 120.0):
        d.observe(v)
    # JSON stringifies int bucket keys — from_wire must accept both forms.
    wire = json.loads(json.dumps(d.to_wire()))
    back = LatencyDigest.from_wire(wire)
    assert back.buckets == d.buckets
    assert back.count == d.count and back.zero_count == d.zero_count
    for q in (0.5, 0.99):
        assert back.quantile(q) == d.quantile(q)


def test_windowed_digest_rotation_with_fake_clock():
    clock = [0.0]
    wd = WindowedDigest(RE, window_s=6.0, slices=3, clock=lambda: clock[0])
    wd.observe(1.0)
    assert wd.snapshot().count == 1 and wd.total.count == 1
    clock[0] = 4.0  # two slices later: sample still in window
    assert wd.snapshot().count == 1
    clock[0] = 100.0  # far past the window
    assert wd.snapshot().count == 0
    assert wd.total.count == 1  # cumulative never forgets
    wd.observe(2.0)
    assert wd.snapshot().count == 1 and wd.total.count == 2


# --- SLO / goodput / watchdog ------------------------------------------------

def test_slo_judge_counters_and_goodput():
    clock = [0.0]
    judge = SloJudge(SloConfig(ttft_ms=100.0, tpot_ms=10.0),
                     clock=lambda: clock[0], rate_window_s=30.0)
    assert judge.judge(0.05, 0.005, 100)  # both attained
    clock[0] = 1.0
    assert not judge.judge(0.5, 0.005, 50)  # ttft violated
    clock[0] = 2.0
    assert not judge.judge(0.05, 0.5, 50)  # tpot violated
    clock[0] = 3.0
    assert judge.judge(0.01, None, 1)  # single-token: tpot unjudged
    assert judge.attained == {"ttft": 3, "tpot": 2}
    assert judge.violated == {"ttft": 1, "tpot": 1}
    assert judge.goodput_requests_total == 2
    assert judge.goodput_tokens_total == 101
    assert math.isclose(judge.attainment(), 5 / 7)
    req_s, tok_s = judge.goodput_rates()
    assert req_s > 0 and tok_s > 0
    stats = judge.to_stats()
    assert stats["slo_ttft_attained_total"] == 3
    assert stats["goodput_tokens_total"] == 101
    # Window expiry: far future → rates drain to zero.
    clock[0] = 1000.0
    assert judge.goodput_rates() == (0.0, 0.0)


def test_slo_judge_disabled_counts_nothing():
    judge = SloJudge(SloConfig())
    assert judge.judge(99.0, 99.0, 5)
    assert judge.requests_total == 0 and judge.attainment() == 1.0


def test_stall_watchdog_monkeypatched_clock():
    clock = [0.0]
    state = {"has_work": False, "last_step": None}
    wd = StallWatchdog(
        probe=lambda: (state["has_work"], state["last_step"]),
        stall_after_s=30.0, clock=lambda: clock[0],
    )
    assert not wd.check()
    # Idle engine far past the threshold: not stalled (no work queued).
    clock[0] = 1000.0
    assert not wd.check() and wd.stalls_total == 0
    # Work queued, steps advancing: healthy.
    state["has_work"] = True
    state["last_step"] = 995.0
    assert not wd.check()
    # Steps stop while work is queued: stalled exactly once past threshold.
    clock[0] = 1026.0  # 31s after last step
    assert wd.check() and wd.stalled
    assert wd.stalls_total == 1
    assert wd.check() and wd.stalls_total == 1  # no re-fire while stalled
    stats = wd.to_stats()
    assert stats["engine_stalled"] == 1.0 and stats["last_step_age_s"] == 31.0
    # Step loop recovers: stall clears; a second wedge fires again.
    state["last_step"] = 1025.0
    assert not wd.check()
    clock[0] = 1100.0
    assert wd.check() and wd.stalls_total == 2


# --- fleet aggregation --------------------------------------------------------

def test_aggregator_merges_worker_digests_into_fleet_quantiles():
    t_a, t_b = Telemetry(), Telemetry()
    for _ in range(1000):
        t_a.observe("ttft", 0.1)
        t_b.observe("ttft", 0.4)
    agg = MetricsAggregator(drt=None, namespace="ns", component="backend",
                            endpoint="generate")
    stats = {1: {"digests": t_a.to_wire()}, 2: {"digests": t_b.to_wire()}}
    agg.export_stats(stats)
    text = agg.registry.render().decode()

    samples = parse_prometheus_samples(text)
    by = {(s.name, s.labels.get("quantile")): s.value for s in samples}
    p50 = by[("dynamo_component_fleet_ttft_seconds_quantile", "0.5")]
    p99 = by[("dynamo_component_fleet_ttft_seconds_quantile", "0.99")]
    # Fleet p50 must reflect worker A's half (0.1) and p99 worker B's (0.4)
    # — the property averaging per-worker quantiles destroys.
    assert abs(p50 - 0.1) <= 2 * RE * 0.1
    assert abs(p99 - 0.4) <= 2 * RE * 0.4
    # Native histogram: cumulative counts + conservation of mass.
    count = next(s.value for s in samples
                 if s.name == "dynamo_component_fleet_ttft_seconds_count")
    assert count == 2000
    inf = next(s.value for s in samples
               if s.name == "dynamo_component_fleet_ttft_seconds_bucket"
               and s.labels.get("le") == "+Inf")
    assert inf == 2000
    # Re-export is idempotent across scrapes (cumulative, not re-added).
    agg.export_stats(stats)
    text2 = agg.registry.render().decode()
    count2 = next(s.value for s in parse_prometheus_samples(text2)
                  if s.name == "dynamo_component_fleet_ttft_seconds_count")
    assert count2 == 2000


def test_digest_collector_histogram_buckets_monotone():
    t = Telemetry()
    rng = random.Random(3)
    for _ in range(5000):
        t.observe("itl", rng.lognormvariate(-5, 2))
    from prometheus_client import CollectorRegistry, generate_latest

    reg = CollectorRegistry()
    dc = DigestCollector("dynamo_component_fleet_", registry=reg)
    dc.update_from_wire([t.to_wire()])
    text = generate_latest(reg).decode()
    buckets = [
        (s.labels["le"], s.value) for s in parse_prometheus_samples(text)
        if s.name == "dynamo_component_fleet_itl_seconds_bucket"
    ]
    vals = [v for _, v in buckets]
    assert vals == sorted(vals), "histogram buckets must be cumulative"
    assert vals[-1] == 5000


# --- prometheus parsing (satellite: real render() output) --------------------

def real_render_text() -> str:
    from dynamo_tpu.runtime.metrics import MetricsRegistry, TTFT_BUCKETS

    reg = MetricsRegistry(labels={"namespace": "ns"})
    reg.counter("requests_total", "req", model="m", status="200").inc(5)
    reg.counter("requests_total", "req", model="m", status="400").inc(2)
    reg.gauge("kv_usage", "usage", worker="a").set(0.25)
    reg.gauge("kv_usage", "usage", worker="b").set(0.75)
    h = reg.histogram("ttft_seconds_hist", "ttft", buckets=TTFT_BUCKETS, model="m")
    h.observe(0.1)
    h.observe(0.3)
    return reg.render().decode()


def test_parse_prometheus_labeled_and_histogram_families():
    text = real_render_text()
    out = parse_prometheus(text)
    # Labeled counter series sum across label sets.
    assert out["dynamo_component_requests_total"] == 7
    assert out["dynamo_component_kv_usage"] == 1.0
    # Histogram children are parsed, not dropped.
    assert out["dynamo_component_ttft_seconds_hist_count"] == 2
    assert math.isclose(out["dynamo_component_ttft_seconds_hist_sum"], 0.4)
    samples = parse_prometheus_samples(text)
    le_inf = [s for s in samples
              if s.name == "dynamo_component_ttft_seconds_hist_bucket"
              and s.labels.get("le") == "+Inf"]
    assert le_inf and le_inf[0].value == 2
    # Label values survive with their metadata.
    workers = {s.labels["worker"]: s.value for s in samples
               if s.name == "dynamo_component_kv_usage"}
    assert workers == {"a": 0.25, "b": 0.75}


def test_parse_prometheus_edge_values():
    text = (
        'thing_total{label="va\\"lue"} 1e+05\n'
        "bad_gauge NaN\n"
        "inf_bucket{le=\"+Inf\"} +Inf\n"
        "plain 3\n"
    )
    out = parse_prometheus(text)
    assert out["thing_total"] == 1e5
    assert "bad_gauge" not in out  # NaN must not poison sums
    assert out["plain"] == 3
    samples = parse_prometheus_samples(text)
    assert any(s.labels.get("label") == 'va"lue' for s in samples)


def test_observer_derives_load_from_two_scrapes():
    obs = PrometheusObserver("http://unused/metrics")
    scrape1 = (
        "dynamo_frontend_requests_total 10\n"
        "dynamo_frontend_input_tokens_total 1000\n"
        "dynamo_frontend_output_tokens_total 500\n"
        "dynamo_component_worker_slo_ttft_attained_total 8\n"
        "dynamo_component_worker_slo_ttft_violated_total 2\n"
        "dynamo_component_worker_goodput_requests_total 8\n"
        "dynamo_component_worker_goodput_tokens_total 400\n"
        'dynamo_component_fleet_ttft_seconds_quantile{quantile="0.5"} 0.05\n'
        'dynamo_component_fleet_ttft_seconds_quantile{quantile="0.9"} 0.2\n'
        'dynamo_component_fleet_ttft_seconds_quantile{quantile="0.99"} 0.4\n'
        'dynamo_component_fleet_tpot_seconds_quantile{quantile="0.99"} 0.02\n'
        'dynamo_component_fleet_queue_wait_seconds_quantile{quantile="0.99"} 0.1\n'
        'dynamo_component_worker_kv_usage{worker="a"} 0.3\n'
        'dynamo_component_worker_kv_usage{worker="b"} 0.5\n'
    )
    scrape2 = scrape1.replace(
        "dynamo_frontend_requests_total 10", "dynamo_frontend_requests_total 20"
    ).replace(
        "dynamo_frontend_input_tokens_total 1000", "dynamo_frontend_input_tokens_total 3000"
    ).replace(
        "dynamo_frontend_output_tokens_total 500", "dynamo_frontend_output_tokens_total 1500"
    ).replace(
        "dynamo_component_worker_slo_ttft_attained_total 8",
        "dynamo_component_worker_slo_ttft_attained_total 11",
    ).replace(
        "dynamo_component_worker_slo_ttft_violated_total 2",
        "dynamo_component_worker_slo_ttft_violated_total 3",
    ).replace(
        "dynamo_component_worker_goodput_requests_total 8",
        "dynamo_component_worker_goodput_requests_total 13",
    )
    obs.load_from_text(scrape1, now=0.0)
    load = obs.load_from_text(scrape2, now=10.0)
    assert math.isclose(load.request_rate, 1.0)
    assert math.isclose(load.avg_isl, 200.0)
    assert math.isclose(load.avg_osl, 100.0)
    assert load.ttft_p50 == 0.05 and load.ttft_p90 == 0.2 and load.ttft_p99 == 0.4
    assert load.tpot_p99 == 0.02 and load.queue_wait_p99 == 0.1
    assert math.isclose(load.slo_attainment, 3 / 4)  # window deltas, not totals
    assert math.isclose(load.goodput_req_s, 0.5)
    assert math.isclose(load.kv_util, 0.4)


# --- engine + mocker stats surface -------------------------------------------

def tiny_engine(**sched_kw):
    from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
    from dynamo_tpu.engine.scheduler import SchedulerConfig

    return TpuEngine.build(
        EngineArgs(
            model="tiny", dtype="float32", eos_token_ids=[0],
            scheduler=SchedulerConfig(
                num_blocks=64, prefill_buckets=[16, 32, 64], decode_buckets=[1, 2, 4],
                **sched_kw,
            ),
        )
    )


async def test_engine_stats_expose_telemetry_plane():
    from dynamo_tpu.runtime.engine import Context

    engine = tiny_engine(slo_ttft_ms=60000.0, slo_tpot_ms=60000.0)
    try:
        for start in (0, 40):
            req = {"token_ids": list(range(start, start + 20)),
                   "sampling_options": {"temperature": 0},
                   "stop_conditions": {"max_tokens": 4}}
            async for _ in engine.generate(req, Context()):
                pass
        stats = engine.stats_handler()
        for key in ("digests", "slo_ttft_attained_total", "goodput_requests_total",
                    "kv_free_blocks", "kv_cached_blocks", "kv_fragmentation",
                    "prefix_hit_rate", "engine_stalled", "engine_stalls_total",
                    "last_step_age_s", "slo_attainment",
                    "step_decode_flops_total", "step_decode_bytes_total",
                    "mfu_decode", "hbm_frac_decode"):
            assert key in stats, key
        assert stats["digests"]["ttft"]["total"]["count"] == 2
        assert stats["digests"]["itl"]["total"]["count"] > 0
        assert stats["slo_ttft_attained_total"] == 2
        assert stats["goodput_requests_total"] == 2
        assert stats["engine_stalled"] == 0.0
        json.dumps(stats)  # the scrape payload must stay wire-serializable

        state = engine.debug_state()
        assert state["block_pool"]["total"] == 64
        assert state["flight"]["recent_steps"], "step timeline empty"
        assert "ttft" in state["digests"]
        assert state["watchdog"]["stall_after_s"] > 0
    finally:
        await engine.stop()


async def test_health_server_reports_stalled_engine_notready():
    """Satellite: /health readiness gains engine liveness — a stalled
    engine reports notready (monkeypatched clock), /debug/state dumps the
    live scheduler view, /debug/stacks answers."""
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import StopConditions
    from dynamo_tpu.runtime.config import SystemConfig
    from dynamo_tpu.runtime.health import SystemHealth, SystemStatusServer

    engine = tiny_engine(stall_after_s=30.0)
    health = SystemHealth()
    health.set_system_ready()
    health.attach_engine(
        lambda: {
            **engine.watchdog.to_stats(),
            "compiles_after_warmup_total":
                engine.scheduler.flight.compiles_after_warmup_total,
        }
    )
    server = SystemStatusServer(
        health, config=SystemConfig(enabled=True, port=0, host="127.0.0.1"),
        state_probe=engine.debug_state,
    )
    await server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        async with aiohttp.ClientSession() as s:
            async with s.get(base + "/health") as r:
                body = await r.json()
                assert r.status == 200 and body["status"] == "ready"
                assert "engine" in body and "last_step_age_s" in body["engine"]
                assert "compiles_after_warmup_total" in body["engine"]

            # Queue work WITHOUT stepping (no engine loop is running), then
            # advance the watchdog's clock past the threshold: stalled.
            engine.scheduler.add_request(
                "stuck", list(range(8)), SamplingParams(temperature=0.0),
                StopConditions(max_tokens=2),
            )
            t0 = engine.watchdog._start_ts
            engine.watchdog._clock = lambda: t0 + 1000.0
            async with s.get(base + "/health") as r:
                body = await r.json()
                assert r.status == 503 and body["status"] == "notready"
                assert body["engine"]["engine_stalled"] == 1.0
            assert engine.watchdog.stalls_total == 1

            async with s.get(base + "/debug/state") as r:
                assert r.status == 200
                state = await r.json()
                assert state["waiting"][0]["request_id"] == "stuck"
                assert "block_pool" in state and "digests" in state

            async with s.get(base + "/debug/stacks") as r:
                assert r.status == 200
                stacks = await r.json()
                assert any("MainThread" in k for k in stacks)
    finally:
        await server.stop()
        engine.scheduler.abort("stuck")


async def test_mocker_emits_same_telemetry_stats():
    """Satellite: the mocker's stats path carries the same digest/SLO keys
    as the real engine, so planner stacks run engine-free."""
    from dynamo_tpu.llm.mocker import MockEngineArgs, MockTpuEngine
    from dynamo_tpu.runtime.engine import Context

    mock = MockTpuEngine(MockEngineArgs(
        speedup_ratio=100.0, slo_ttft_ms=60000.0, slo_tpot_ms=0.000001,
    ))

    async def run(tokens):
        async for _ in mock.generate(
            {"token_ids": tokens, "stop_conditions": {"max_tokens": 8}}, Context()
        ):
            pass

    await asyncio.gather(*(run(list(range(1, 20 + i))) for i in range(4)))
    stats = mock.stats_handler()
    for key in ("digests", "slo_ttft_attained_total", "slo_tpot_violated_total",
                "goodput_requests_total", "slo_attainment",
                "kv_free_blocks", "prefix_hit_rate"):
        assert key in stats, key
    assert stats["digests"]["ttft"]["total"]["count"] == 4
    assert stats["digests"]["tpot"]["total"]["count"] == 4
    assert stats["slo_ttft_attained_total"] == 4
    assert stats["slo_tpot_violated_total"] == 4  # impossible 1ns TPOT target
    assert stats["goodput_requests_total"] == 0  # tpot violations kill goodput
    assert 0.0 < stats["slo_attainment"] < 1.0

    # The aggregator consumes the mocker scrape exactly like an engine's.
    agg = MetricsAggregator(drt=None, namespace="ns", component="mock",
                            endpoint="generate")
    agg.export_stats({7: stats})
    text = agg.registry.render().decode()
    assert "dynamo_component_fleet_ttft_seconds_quantile" in text
    assert "dynamo_component_worker_slo_ttft_attained_total" in text


# --- trace_view --summary (satellite) ----------------------------------------

def test_trace_view_summary_tolerates_truncated_file(tmp_path):
    path = tmp_path / "crash.jsonl"
    records = [
        {"kind": "span", "name": "http_request", "trace_id": "t1", "span_id": "s1",
         "ts": 1.0, "dur_s": 0.5, "service": "frontend"},
        {"kind": "event", "name": "admitted", "trace_id": "t1", "ts": 1.1,
         "service": "scheduler", "attrs": {"queue_s": 0.02}},
        {"kind": "event", "name": "first_token", "trace_id": "t1", "ts": 1.2,
         "service": "scheduler", "attrs": {"ttft_s": 0.12}},
        {"kind": "event", "name": "prefill_chunk", "trace_id": "t1", "ts": 1.15,
         "service": "scheduler", "attrs": {"dur_s": 0.03, "tokens": 64}},
        # ts-less fragment (partial serialization before a crash).
        {"kind": "event", "name": "finish", "trace_id": "t1"},
    ]
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
        # Crash-time truncation: the final line is cut mid-record.
        f.write('{"kind": "span", "name": "worker_handle", "trace_id": "t1", "ts"')

    tool = os.path.join(REPO, "tools", "trace_view.py")
    # --summary prints per-phase digest percentiles.
    proc = subprocess.run([sys.executable, tool, str(path), "--summary"],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    for needle in ("ttft", "queue_wait", "prefill_chunk", "span:http_request", "p99"):
        assert needle in proc.stdout, proc.stdout
    # 120 ms ttft renders in the table.
    ttft_line = next(l for l in proc.stdout.splitlines() if l.startswith("ttft"))
    assert "120.0" in ttft_line or "119." in ttft_line, ttft_line
    # The timeline modes tolerate the same file.
    for argv in ([str(path)], [str(path), "--all"]):
        proc = subprocess.run([sys.executable, tool, *argv],
                              capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr


# --- end-to-end signal path ---------------------------------------------------

async def test_e2e_signal_path_frontend_to_observer():
    """Acceptance: traffic through frontend → worker → scheduler produces
    non-trivial ttft_p99 / slo_attainment / kv_util in
    PrometheusObserver.observe(), consistent with the per-request values
    the test measured itself."""
    from dynamo_tpu.llm.discovery import ModelManager
    from dynamo_tpu.llm.entrypoint import build_routed_pipeline, register_llm
    from dynamo_tpu.llm.http.service import HttpService
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.tokenizer import ByteTokenizer
    from dynamo_tpu.runtime.config import SystemConfig
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.health import SystemHealth, SystemStatusServer
    from dynamo_tpu.runtime.push_router import PushRouter

    MODEL = "tiny-sla"
    drt = await DistributedRuntime.detached()
    # Engine SLO: generous TTFT (always attained on CPU) + impossible TPOT
    # (always violated) → attainment is a KNOWN 0.5 from the engine side.
    engine = tiny_engine(slo_ttft_ms=60000.0, slo_tpot_ms=0.000001)
    service = agg_server = None
    try:
        ep = drt.namespace("slatest").component("backend").endpoint("generate")
        card = ModelDeploymentCard(name=MODEL, model_type="chat")
        handle, _ = await register_llm(drt, ep, engine, card,
                                       stats_handler=engine.stats_handler)
        worker_id = handle.instance.instance_id
        client = await ep.client()
        await client.wait_for_instances(1, timeout=5)

        manager = ModelManager()
        pipeline = build_routed_pipeline(ByteTokenizer(), PushRouter(client), card)
        manager.add_model("chat", MODEL, pipeline)
        service = HttpService(manager, host="127.0.0.1", port=0)
        await service.start()

        # Aggregator fed from the REAL stats scrape wire (msgpack round
        # trip), served on its own /metrics like production.
        agg = MetricsAggregator(drt, "slatest", "backend", "generate")
        agg_health = SystemHealth()
        agg_health.set_system_ready()
        agg_server = SystemStatusServer(
            agg_health, metrics=agg.registry,
            config=SystemConfig(enabled=True, port=0, host="127.0.0.1"),
        )
        await agg_server.start()

        fe_url = f"http://127.0.0.1:{service.port}/metrics"
        agg_url = f"http://127.0.0.1:{agg_server.port}/metrics"
        observer = PrometheusObserver(fe_url, extra_urls=[agg_url])

        async def scrape_to_agg():
            agg.export_stats(await client.scrape_stats())

        await scrape_to_agg()
        await observer.observe()  # baseline window

        # Drive traffic, measuring client-side per-request TTFT ourselves.
        # Completions streaming: unlike chat (which emits an instant role
        # preamble), a completion chunk only appears once a real token
        # decoded — so first-data-line time IS the client-observed TTFT.
        client_ttfts = []
        async with aiohttp.ClientSession() as s:
            for i in range(6):
                body = {"model": MODEL, "prompt": f"req {i} " + "x" * i,
                        "max_tokens": 6, "temperature": 0, "stream": True}
                t0 = time.monotonic()
                first_at = None
                async with s.post(f"http://127.0.0.1:{service.port}/v1/completions",
                                  json=body) as r:
                    assert r.status == 200
                    async for raw in r.content:
                        if raw.startswith(b"data: ") and b"[DONE]" not in raw and first_at is None:
                            first_at = time.monotonic()
                assert first_at is not None
                client_ttfts.append(first_at - t0)

        await scrape_to_agg()
        load = await observer.observe()

        # Request-shape deltas came through the frontend counters.
        assert load.request_rate > 0
        assert load.avg_osl > 0

        # Quantiles: non-trivial and consistent with what the client saw —
        # engine-internal TTFT can't exceed the worst client-observed TTFT
        # (which includes tokenize/route/detok), and a p99 of positives is
        # positive.
        assert load.ttft_p50 > 0 and load.ttft_p99 > 0
        assert load.ttft_p50 <= load.ttft_p99
        assert load.ttft_p99 <= max(client_ttfts) * (1 + 2 * RE) + 0.005, (
            load.ttft_p99, max(client_ttfts))
        assert load.tpot_p99 > 0  # engine decoded multiple tokens per request

        # SLO attainment: engine judged ttft attained + tpot violated for
        # every request → exactly half the engine's phase checks attained.
        stats = engine.stats_handler()
        assert stats["slo_ttft_attained_total"] == 6
        assert stats["slo_tpot_violated_total"] == 6
        assert 0.0 < load.slo_attainment < 1.0
        assert math.isclose(load.slo_attainment, 0.5, abs_tol=1e-6)
        assert load.goodput_req_s == 0.0  # nothing attained BOTH targets

        # KV utilization: prefix caching keeps blocks resident, so the
        # worker's kv_usage gauge is live and non-zero after traffic.
        assert load.kv_util > 0

        # The same worker id labels the per-worker series on the aggregator.
        async with aiohttp.ClientSession() as s:
            async with s.get(agg_url) as r:
                text = await r.text()
        assert f'worker="{worker_id:x}"' in text
    finally:
        if service is not None:
            await service.stop()
        if agg_server is not None:
            await agg_server.stop()
        await engine.stop()
        await drt.shutdown()
