"""Unit tests for the KV router stack: radix indexer, scheduler cost
function, approx indexer, active sequences, snapshot round-trip."""

import pytest

from dynamo_tpu.llm.kv_router import (
    ActiveSequencesMultiWorker,
    ApproxKvIndexer,
    KvIndexer,
    KvScheduler,
    RadixTree,
)
from dynamo_tpu.llm.tokens import compute_block_hashes

BS = 16


def hashes_for(tokens):
    return compute_block_hashes(tokens, BS)


def test_radix_tree_store_and_match():
    tree = RadixTree()
    seq = list(range(64))  # 4 blocks
    h = hashes_for(seq)
    tree.apply_stored(1, h, None)
    tree.apply_stored(2, h[:2], None)

    scores = tree.find_matches(h)
    assert scores.scores == {1: 4, 2: 2}

    # Diverging suffix matches only the shared prefix.
    other = seq[:32] + list(range(1000, 1032))
    scores2 = tree.find_matches(hashes_for(other))
    assert scores2.scores == {1: 2, 2: 2}


def test_radix_tree_incremental_store_with_parent():
    tree = RadixTree()
    seq = list(range(64))
    h = hashes_for(seq)
    tree.apply_stored(1, h[:2], None)
    tree.apply_stored(1, h[2:], h[1])  # chained continuation
    assert tree.find_matches(h).scores == {1: 4}


def test_radix_tree_removed_and_prune():
    tree = RadixTree()
    h = hashes_for(list(range(64)))
    tree.apply_stored(1, h, None)
    tree.apply_removed(1, h[2:])
    assert tree.find_matches(h).scores == {1: 2}
    assert tree.size() == 2  # pruned leaves


def test_radix_tree_remove_worker():
    tree = RadixTree()
    h = hashes_for(list(range(32)))
    tree.apply_stored(1, h, None)
    tree.apply_stored(2, h, None)
    tree.remove_worker(1)
    assert tree.find_matches(h).scores == {2: 2}


def test_radix_snapshot_roundtrip():
    tree = RadixTree()
    a = hashes_for(list(range(64)))
    b = hashes_for(list(range(500, 532)))
    tree.apply_stored(1, a, None)
    tree.apply_stored(2, b, None)
    restored = RadixTree.load(tree.dump())
    assert restored.find_matches(a).scores == {1: 4}
    assert restored.find_matches(b).scores == {2: 2}
    assert restored.size() == tree.size()


def test_scheduler_prefers_overlap():
    seqs = ActiveSequencesMultiWorker(block_size=BS)
    sched = KvScheduler(seqs)
    from dynamo_tpu.llm.kv_router.indexer import OverlapScores

    # Worker 1 holds 4 of 6 blocks; worker 2 none. Equal load.
    d = sched.select_worker([1, 2], prompt_blocks=6, overlaps=OverlapScores(scores={1: 4}))
    assert d.worker == 1 and d.overlap_blocks == 4


def test_scheduler_load_beats_small_overlap():
    seqs = ActiveSequencesMultiWorker(block_size=BS)
    from dynamo_tpu.llm.kv_router.indexer import OverlapScores

    sched = KvScheduler(seqs)
    # Worker 1 has 1 block overlap but is heavily loaded with decode work.
    for i in range(20):
        seqs.add_request(f"r{i}", 1, prompt_tokens=64, overlap_blocks=0)
    d = sched.select_worker([1, 2], prompt_blocks=4, overlaps=OverlapScores(scores={1: 1}))
    assert d.worker == 2


def test_scheduler_softmax_temperature_spreads():
    seqs = ActiveSequencesMultiWorker(block_size=BS)
    from dynamo_tpu.llm.kv_router.indexer import OverlapScores

    sched = KvScheduler(seqs, temperature=5.0)
    chosen = {sched.select_worker([1, 2, 3], 4, OverlapScores()).worker for _ in range(50)}
    assert len(chosen) > 1  # high temperature spreads across equal workers


def test_active_sequences_lifecycle():
    seqs = ActiveSequencesMultiWorker(block_size=BS)
    seqs.add_request("r1", 7, prompt_tokens=64, overlap_blocks=2)
    assert seqs.prefill_tokens(7) == 32  # 64 - 2*16 cached
    # Decode load counts only the NEW blocks (4 total - 2 shared with the
    # resident prefix): overlapped blocks cost the worker nothing extra.
    assert seqs.decode_blocks(7) == 2
    seqs.mark_prefill_done("r1")
    assert seqs.prefill_tokens(7) == 0
    assert seqs.decode_blocks(7) == 2
    assert seqs.free("r1") == 7
    assert seqs.decode_blocks(7) == 0


def test_approx_indexer_ttl():
    idx = ApproxKvIndexer(block_size=BS, ttl_s=0.0)  # immediate expiry
    tokens = list(range(32))
    idx.process_routing_decision(5, tokens)
    # expire() runs inside find_matches; ttl=0 ⇒ gone.
    assert idx.find_matches(hashes_for(tokens)).scores == {}

    idx2 = ApproxKvIndexer(block_size=BS, ttl_s=60.0)
    idx2.process_routing_decision(5, tokens)
    assert idx2.find_matches(hashes_for(tokens)).scores == {5: 2}


def test_indexer_event_application():
    idx = KvIndexer(block_size=BS)
    h = hashes_for(list(range(48)))
    idx.apply_event(9, {"kind": "stored", "block_hashes": h, "parent_hash": None})
    assert idx.find_matches_for_tokens(list(range(48))).scores == {9: 3}
    idx.apply_event(9, {"kind": "removed", "block_hashes": h[1:]})
    assert idx.find_matches(h).scores == {9: 1}
    idx.apply_event(9, {"kind": "cleared"})
    assert idx.find_matches(h).scores == {}
