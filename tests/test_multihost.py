"""Multi-host coordination: jax.distributed 2-process CPU mesh + store
rendezvous (ref: engines.rs:28 MultiNodeConfig, trtllm multinode srun)."""

import os
import socket
import subprocess
import sys
import textwrap

from dynamo_tpu.engine.multihost import MultiHostConfig, build_multihost_mesh, rendezvous
from dynamo_tpu.runtime.distributed import DistributedRuntime

WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    rank = int(sys.argv[1]); coord = sys.argv[2]

    from dynamo_tpu.engine.multihost import MultiHostConfig, init_multihost
    cfg = MultiHostConfig(num_processes=2, process_id=rank, coordinator=coord)
    init_multihost(cfg)
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4

    # Sharded compute across both processes: global psum over a dp×tp mesh.
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental import mesh_utils

    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), axis_names=("dp", "tp"))
    x = jnp.arange(16.0, dtype=jnp.float32).reshape(8, 2)
    sharding = NamedSharding(mesh, P("dp", None))

    @jax.jit
    def total(x):
        return jnp.sum(x)

    xs = jax.device_put(x, sharding)
    out = total(xs)
    expect = float(np.arange(16.0).sum())
    assert float(out) == expect, (float(out), expect)
    print(f"RANK{rank}_OK", flush=True)
""")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_cpu_mesh():
    """Two OS processes join one jax.distributed runtime (the multi-host
    serving topology) and run a jitted global reduction over a 2×4 mesh."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    coord = f"127.0.0.1:{_free_port()}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, str(rank), coord],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True, cwd=repo,
        )
        for rank in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"RANK{rank}_OK" in out


async def test_rendezvous_assigns_dense_ranks():
    drt = await DistributedRuntime.detached()
    try:
        a = await rendezvous(drt, "grp", 3)
        b = await rendezvous(drt, "grp", 3)
        c = await rendezvous(drt, "grp", 3)
        assert sorted([a.process_id, b.process_id, c.process_id]) == [0, 1, 2]
        assert a.coordinator == b.coordinator == c.coordinator
        assert a.num_processes == 3
        # Leader flag follows rank 0.
        leaders = [x for x in (a, b, c) if x.is_leader]
        assert len(leaders) == 1
    finally:
        await drt.shutdown()


async def test_rendezvous_full_group_times_out():
    drt = await DistributedRuntime.detached()
    try:
        await rendezvous(drt, "g2", 1)
        import pytest

        with pytest.raises(TimeoutError):
            await rendezvous(drt, "g2", 1, timeout_s=0.3)
    finally:
        await drt.shutdown()


def test_build_multihost_mesh_single_slice():
    cfg = MultiHostConfig()
    assert not cfg.enabled and cfg.is_leader
    from dynamo_tpu.engine.sharding import ParallelConfig

    mesh = build_multihost_mesh(ParallelConfig(tp=2, dp=2), dcn_dp=2)
    assert dict(mesh.shape) == {"dp": 4, "pp": 1, "sp": 1, "ep": 1, "tp": 2}
