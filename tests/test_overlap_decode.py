"""Zero-bubble overlapped decode: the fused decode+sample pipeline with
on-device token feedback must be token-exact vs the sync path (greedy
sequences are a pure function of the prompt, whatever the scheduling), flush
correctly on composition changes, retire one step behind with KV-slot
rollback for sequences that finish mid-pipeline, and hold the steady-state
host-sync bound the whole feature exists for (≤1 blocking sync per step)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions
from dynamo_tpu.runtime.engine import Context

CFG = get_config("tiny").replace(max_seq_len=4096)
PARAMS = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def mk_sched(overlap: bool, **kw) -> Scheduler:
    return Scheduler(
        CFG, PARAMS,
        SchedulerConfig(
            num_blocks=256, max_running=8,
            prefill_buckets=[32, 64], decode_buckets=[1, 2, 4, 8],
            num_scheduler_steps=1, enable_prefix_caching=False,
            enable_overlap_decode=overlap, **kw,
        ),
        dtype=jnp.float32,
    )


def add(sched, rid, prompt, max_tokens):
    sched.add_request(
        rid, prompt, SamplingParams(temperature=0.0),
        StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )


def drain(sched, hook=None) -> dict:
    """Run to completion, returning {request_id: [token, ...]}."""
    out: dict = {}
    for _ in range(4000):
        if not sched.has_work():
            break
        for seq, o in sched.step():
            if o.token_id >= 0:
                out.setdefault(seq.request_id, []).append(o.token_id)
        if hook is not None:
            hook(sched)
    assert not sched.has_work(), "scheduler did not drain"
    return out


def test_overlap_matches_sync_greedy_multi_request():
    reqs = [(f"r{i}", list(range(3 + i, 23 + i)), 20 + 7 * i) for i in range(4)]

    def run(overlap):
        sched = mk_sched(overlap)
        for rid, prompt, mt in reqs:
            add(sched, rid, prompt, mt)
        toks = drain(sched)
        return sched, toks

    s_on, on = run(True)
    s_off, off = run(False)
    assert on == off
    assert all(len(on[rid]) == mt for rid, _, mt in reqs)
    assert s_on.overlap_steps_total > 0
    assert s_off.overlap_steps_total == 0


def test_finish_mid_pipeline_rolls_back_kv_slot():
    """One request stops while its batchmate keeps decoding: the speculative
    in-flight step's token for the stopped row is discarded, the KV slot it
    wrote is zeroed, and the survivor's cache contents stay byte-identical
    to the sync path."""
    bs = CFG.block_size

    def run(overlap):
        sched = mk_sched(overlap)
    p = 20 + 6 - 1  # short's final token slot: prompt + max_tokens - 1

    def run(overlap):
        sched = mk_sched(overlap)
        add(sched, "short", list(range(5, 25)), 6)
        add(sched, "long", list(range(7, 27)), 40)
        blocks: dict = {}
        slot = [None]

        def snapshot(s):
            for rid in ("short", "long"):
                seq = s.by_id.get(rid)
                if seq is not None and seq.block_ids:
                    blocks[rid] = list(seq.block_ids)
            # The step "short" finished on: read its speculative slot NOW,
            # before the allocator hands the released blocks to "long".
            if slot[0] is None and "short" not in s.by_id and "short" in blocks:
                blk = blocks["short"][p // bs]
                slot[0] = np.asarray(s.cache.k[:, blk, p % bs])
        toks = drain(sched, hook=snapshot)
        return sched, toks, blocks, slot[0]

    s_on, on, blk_on, slot_on = run(True)
    s_off, off, blk_off, slot_off = run(False)
    assert on == off and len(on["short"]) == 6 and len(on["long"]) == 40
    assert s_on.overlap_flushes_total >= 1  # the finish forced a flush

    # The allocator is deterministic and both runs made identical
    # allocations, so per-request block ids line up run-to-run.
    assert blk_on == blk_off

    # Rollback: "short" finished at some step N with step N+1 in flight;
    # that in-flight dispatch wrote short's last token's KV at position
    # total_len-1 — a slot the sync path never writes (a finished row's
    # last token is never fed back). Zeroing restores sync parity.
    np.testing.assert_array_equal(slot_on, 0.0)
    np.testing.assert_array_equal(slot_off, 0.0)
    k_on = np.asarray(s_on.cache.k)
    k_off = np.asarray(s_off.cache.k)

    # Survivor parity: every KV row "long" actually wrote matches sync.
    # (Slots past the written extent hold stale pre-release data in the
    # sync run vs rollback zeros in the overlap run — released-block
    # garbage neither path ever reads.)
    total = 20 + 40
    for pos in range(total - 1):
        blk = blk_on["long"][pos // bs]
        np.testing.assert_allclose(
            k_on[:, blk, pos % bs], k_off[:, blk, pos % bs], rtol=1e-6, atol=1e-6,
            err_msg=f"long KV row at position {pos} diverged",
        )
    # Long's own final slot: the overlap run zeroed it at finish-flush.
    final_blk = blk_on["long"][(total - 1) // bs]
    np.testing.assert_array_equal(k_on[:, final_blk, (total - 1) % bs], 0.0)


def test_flush_on_admission_mid_pipeline():
    """A request arriving while the pipeline runs must flush it (the batch
    composition changes), admit the newcomer, and keep every token stream
    exact."""
    sched = mk_sched(True)
    for i in range(3):
        add(sched, f"r{i}", list(range(2 + i, 22 + i)), 30)
    late_added = [False]
    flushes_at_add = [0]

    def hook(s):
        if not late_added[0] and s._pipe is not None:
            flushes_at_add[0] = s.overlap_flushes_total
            add(s, "late", list(range(40, 60)), 12)
            late_added[0] = True

    on = drain(sched, hook=hook)
    assert late_added[0]
    assert sched.overlap_flushes_total > flushes_at_add[0]
    assert len(on["late"]) == 12

    sync = mk_sched(False)
    for i in range(3):
        add(sync, f"r{i}", list(range(2 + i, 22 + i)), 30)
    add(sync, "late", list(range(40, 60)), 12)
    assert drain(sync) == on  # greedy streams are scheduling-invariant


def test_steady_state_single_blocking_sync(monkeypatch):
    """The pipeline's whole point: once overlapped, each step() performs at
    most ONE blocking device sync (the previous step's token readback) and
    zero jax.device_get calls — counted by instrumenting the only two
    blocking-readback entry points the scheduler uses."""
    import dynamo_tpu.engine.scheduler as sched_mod

    sched = mk_sched(True)
    for i in range(4):
        add(sched, f"r{i}", list(range(3 + i, 23 + i)), 200)
    for _ in range(60):
        if sched._pipe is not None:
            break
        sched.step()
    assert sched._pipe is not None, "pipeline never engaged"
    sched.step()  # one steady-state step before instrumenting

    counter = {"n": 0}
    real_asarray = np.asarray
    real_device_get = jax.device_get

    def counting_asarray(a, *args, **kw):
        if isinstance(a, jax.Array):
            counter["n"] += 1
        return real_asarray(a, *args, **kw)

    def counting_device_get(x, *args, **kw):
        counter["n"] += 1
        return real_device_get(x, *args, **kw)

    monkeypatch.setattr(sched_mod.np, "asarray", counting_asarray)
    monkeypatch.setattr(sched_mod.jax, "device_get", counting_device_get)
    steps, tokens = 10, 0
    try:
        for _ in range(steps):
            outs = sched.step()
            assert sched._pipe is not None, "pipeline flushed mid steady-state"
            tokens += sum(1 for _, o in outs if o.token_id >= 0)
    finally:
        monkeypatch.undo()
    assert tokens == steps * 4  # one token per row per step, one step behind
    assert counter["n"] <= steps, (
        f"{counter['n']} blocking syncs over {steps} steady-state steps"
    )
    drain(sched)


async def test_overlap_zero_post_warmup_compiles():
    """Warmed engine serving overlap traffic (incl. a finish-mid-pipeline
    rollback) compiles nothing new — the flight-recorder gate every decode
    path must hold."""
    engine = TpuEngine.build(
        EngineArgs(
            model="tiny", dtype="float32", eos_token_ids=[0],
            scheduler=SchedulerConfig(
                num_blocks=64, prefill_buckets=[16, 32, 64],
                decode_buckets=[1, 2, 4], num_scheduler_steps=1,
            ),
            warmup_ctx=64,
        )
    )

    async def one(start, max_tokens):
        req = {"token_ids": list(range(start, start + 20)),
               "sampling_options": {"temperature": 0},
               "stop_conditions": {"max_tokens": max_tokens, "ignore_eos": True}}
        out = []
        async for frame in engine.generate(req, Context()):
            out.extend(frame.get("token_ids") or [])
        return out

    try:
        # Sequential requests (same discipline as the tracing compile test:
        # wave/mixed admission keys compile lazily BY DESIGN for uncommon
        # shapes — the subject here is the overlap executables). Each
        # request decodes alone through the pipeline and finishes mid-
        # pipeline, so the rollback executable runs too.
        outs = [await one(0, 6), await one(40, 12), await one(80, 12)]
        stats = engine.stats_handler()
        assert stats["compiles_after_warmup_total"] == 0, (
            f"compiled mid-traffic: {engine.scheduler.flight.post_warmup_keys}"
        )
        assert stats["overlap_steps_total"] > 0
        assert stats["decode_host_gap_events_total"] > 0
        assert [len(o) for o in outs] == [6, 12, 12]
    finally:
        await engine.stop()


def test_overlap_streams_one_step_behind():
    """Documented semantics: the pipeline's first dispatch emits nothing
    (its tokens retire with the next step); steady steps emit one token per
    row."""
    sched = mk_sched(True)
    add(sched, "r0", list(range(4, 24)), 50)
    while sched.waiting:
        sched.step()
    assert sched._pipe is None or True  # admission may already have stepped
    # Find the starting step: pipeline engages and emits nothing.
    for _ in range(20):
        before = sched._pipe
        outs = sched.step()
        if before is None and sched._pipe is not None:
            assert outs == []  # one-step lag on pipeline start
            break
    outs = sched.step()  # steady state retires exactly one step
    assert sum(1 for _, o in outs if o.token_id >= 0) == 1
    drain(sched)
