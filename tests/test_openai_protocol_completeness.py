"""OpenAI protocol completeness (VERDICT r2 #7): logprobs (unary + stream
deltas), per-request seed determinism, n>1 choices, and OpenAI-shaped
validation errors. Ref: lib/llm/src/protocols/openai/*,
http/service/openai.rs:481."""

import json

import aiohttp
import pytest

from dynamo_tpu.llm.protocols import openai as oai
from tests.test_http_serve import MODEL, make_local_service


def chat_body(**kw):
    body = {
        "model": MODEL,
        "messages": [{"role": "user", "content": "hello protocol tests"}],
        "max_tokens": 6,
    }
    body.update(kw)
    return body


# --- validation (OpenAI-shaped errors) --------------------------------------

@pytest.mark.parametrize("body,frag", [
    (chat_body(n=0), "n must be"),
    (chat_body(n=99), "n must be"),
    (chat_body(seed="abc"), "seed must be"),
    (chat_body(logprobs=3), "logprobs must be a boolean"),
    (chat_body(top_logprobs=5), "top_logprobs requires"),
    (chat_body(logprobs=True, top_logprobs=21), "top_logprobs must be an integer in"),
    (chat_body(temperature=9.0), "temperature must be in"),
    (chat_body(logit_bias=[1, 2]), "logit_bias must be an object"),
    (chat_body(logit_bias={"abc": 1}), "logit_bias keys must be token ids"),
    (chat_body(logit_bias={"5": 200}), "logit_bias values must be numbers in"),
])
def test_chat_validation_errors(body, frag):
    with pytest.raises(oai.RequestError, match=frag):
        oai.validate_chat_request(body)


def test_top_logprobs_accepted_and_mapped():
    body = chat_body(logprobs=True, top_logprobs=5)
    assert oai.validate_chat_request(body) is body
    s = oai.sampling_from_request(body)
    assert s["logprobs"] is True and s["top_logprobs"] == 5
    # Completions: the legacy int doubles as the alternatives count.
    comp = {"model": "m", "prompt": "hi", "logprobs": 3}
    assert oai.validate_completion_request(comp) is comp
    s = oai.sampling_from_request(comp)
    assert s["logprobs"] is True and s["top_logprobs"] == 3


def test_logprobs_block_builders_with_tops():
    tops = [[[7, -0.1], [9, -2.0]], [[4, -0.5]]]
    blk = oai.chat_logprobs_content(None, [-0.1, -0.5], tops)
    assert [e["logprob"] for e in blk["content"]] == [-0.1, -0.5]
    assert blk["content"][0]["top_logprobs"] == [
        {"token": "token_id:7", "logprob": -0.1, "bytes": None},
        {"token": "token_id:9", "logprob": -2.0, "bytes": None},
    ]
    cblk = oai.completion_logprobs_block(["a", "b"], [-0.1, -0.5], tops)
    assert cblk["top_logprobs"] == [
        {"token_id:7": -0.1, "token_id:9": -2.0},
        {"token_id:4": -0.5},
    ]
    # Without alternatives the block keeps its pre-elastic shape.
    assert oai.completion_logprobs_block(["a"], [-0.1])["top_logprobs"] is None


def test_logit_bias_accepted_and_normalized():
    body = chat_body(logit_bias={"122": 50, 7: -1.5})
    assert oai.validate_chat_request(body) is body
    assert oai.sampling_from_request(body)["logit_bias"] == {122: 50.0, 7: -1.5}
    # Completions share the validation path.
    ok = {"model": "m", "prompt": "hi", "logit_bias": {"3": -100}}
    assert oai.validate_completion_request(ok) is ok


async def test_logit_bias_steers_greedy_decode_http():
    """VERDICT missing #2: logit_bias flows protocol → preprocessor →
    engine and is applied pre-sampling — +100 on one byte token forces a
    greedy completion of exactly that byte."""
    service, engine = await make_local_service()
    try:
        async with aiohttp.ClientSession() as s:
            body = chat_body(
                temperature=0, max_tokens=4,
                logit_bias={str(ord("z")): 100},
            )
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions", json=body
            ) as r:
                assert r.status == 200, await r.text()
                content = (await r.json())["choices"][0]["message"]["content"]
                assert content == "zzzz", content
    finally:
        await service.stop()
        await engine.stop()


def test_completion_validation():
    ok = {"model": "m", "prompt": "hi", "n": 2, "seed": 7, "logprobs": 2}
    assert oai.validate_completion_request(ok) is ok
    with pytest.raises(oai.RequestError, match="logprobs must be an integer"):
        oai.validate_completion_request({"model": "m", "prompt": "hi", "logprobs": 9})


async def test_validation_error_http_shape():
    service, engine = await make_local_service()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json=chat_body(n=99),
            ) as r:
                assert r.status == 400
                err = (await r.json())["error"]
                assert err["type"] == "invalid_request_error" and "n must be" in err["message"]
    finally:
        await service.stop()
        await engine.stop()


# --- seed -------------------------------------------------------------------

async def test_seed_reproducible_and_batch_independent():
    """Same seed ⇒ same completion; different seed ⇒ (almost surely)
    different. Sampling temperature high enough to make collisions unlikely."""
    service, engine = await make_local_service()
    url_tmpl = f"http://127.0.0.1:{service.port}/v1/chat/completions"
    try:
        async with aiohttp.ClientSession() as s:
            async def run(seed):
                async with s.post(url_tmpl, json=chat_body(
                        temperature=1.5, seed=seed, max_tokens=12)) as r:
                    assert r.status == 200
                    return (await r.json())["choices"][0]["message"]["content"]

            a1 = await run(1234)
            a2 = await run(1234)
            b = await run(99)
            assert a1 == a2, "same seed must reproduce"
            assert a1 != b, "different seeds should diverge"
    finally:
        await service.stop()
        await engine.stop()


# --- logprobs ---------------------------------------------------------------

async def test_chat_logprobs_unary_and_stream():
    service, engine = await make_local_service()
    base = f"http://127.0.0.1:{service.port}/v1/chat/completions"
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(base, json=chat_body(logprobs=True)) as r:
                assert r.status == 200
                choice = (await r.json())["choices"][0]
                content = choice["logprobs"]["content"]
                assert len(content) >= 1
                assert all(e["logprob"] <= 0.0 for e in content)

            async with s.post(base, json=chat_body(logprobs=True, stream=True)) as r:
                assert r.status == 200
                lp_entries = 0
                async for line in r.content:
                    if not line.startswith(b"data:") or b"[DONE]" in line:
                        continue
                    chunk = json.loads(line[5:])
                    lp = chunk["choices"][0].get("logprobs")
                    if lp:
                        lp_entries += len(lp["content"])
                        assert all(e["logprob"] <= 0.0 for e in lp["content"])
                assert lp_entries >= 1
    finally:
        await service.stop()
        await engine.stop()


# --- n > 1 ------------------------------------------------------------------

async def test_n_choices_unary_and_stream():
    service, engine = await make_local_service()
    base = f"http://127.0.0.1:{service.port}/v1/chat/completions"
    try:
        async with aiohttp.ClientSession() as s:
            async with s.post(base, json=chat_body(n=3, temperature=1.5, seed=5)) as r:
                assert r.status == 200
                data = await r.json()
                assert [c["index"] for c in data["choices"]] == [0, 1, 2]
                texts = [c["message"]["content"] for c in data["choices"]]
                assert all(isinstance(t, str) and t for t in texts)
                # Seeded choices use seed+i: not all identical (overwhelmingly).
                assert len(set(texts)) > 1

            async with s.post(base, json=chat_body(n=2, stream=True)) as r:
                assert r.status == 200
                seen = {0: 0, 1: 0}
                finishes = set()
                async for line in r.content:
                    if not line.startswith(b"data:") or b"[DONE]" in line:
                        continue
                    chunk = json.loads(line[5:])
                    ch = chunk["choices"][0]
                    if ch["delta"].get("content"):
                        seen[ch["index"]] += 1
                    if ch.get("finish_reason"):
                        finishes.add(ch["index"])
                assert seen[0] > 0 and seen[1] > 0
                assert finishes == {0, 1}
    finally:
        await service.stop()
        await engine.stop()
