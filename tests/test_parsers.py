"""Tool-call + reasoning parser tests (ref test shapes: lib/parsers/src/
tool_calling/parsers.rs #[cfg(test)], reasoning/base_parser.rs)."""

import json

import pytest

from dynamo_tpu.llm.backend import Backend
from dynamo_tpu.llm.parsers import (
    StreamingToolCallJail,
    detect_tool_call_start,
    get_available_reasoning_parsers,
    get_available_tool_parsers,
    get_reasoning_parser,
    get_tool_parser,
    try_tool_call_parse,
)
from dynamo_tpu.llm.protocols.common import LLMEngineOutput
from dynamo_tpu.llm.tokenizer import ByteTokenizer
from dynamo_tpu.runtime.engine import Annotated, Context


# --- tool calling -----------------------------------------------------------


def test_registry_names():
    names = get_available_tool_parsers()
    for expected in ("hermes", "llama3_json", "mistral", "nemotron_deci", "phi4",
                     "pythonic", "harmony", "deepseek_v3_1", "default"):
        assert expected in names
    with pytest.raises(ValueError):
        get_tool_parser("nope")


def test_hermes_single_call():
    calls, content = try_tool_call_parse(
        'sure!\n<tool_call>\n{"name": "get_weather", "arguments": {"city": "SF"}}\n</tool_call>',
        get_tool_parser("hermes"),
    )
    assert len(calls) == 1
    assert calls[0].name == "get_weather"
    assert json.loads(calls[0].arguments) == {"city": "SF"}
    assert content == "sure!"


def test_hermes_parallel_calls():
    text = (
        '<tool_call>{"name": "a", "arguments": {}}</tool_call>'
        '<tool_call>{"name": "b", "arguments": {"x": 1}}</tool_call>'
    )
    calls, content = try_tool_call_parse(text, get_tool_parser("hermes"))
    assert [c.name for c in calls] == ["a", "b"]
    assert content is None


def test_hermes_no_bare_json():
    calls, content = try_tool_call_parse('{"name": "a", "arguments": {}}', get_tool_parser("hermes"))
    assert calls == [] and content is not None


def test_mistral_array():
    calls, _ = try_tool_call_parse(
        '[TOOL_CALLS] [{"name": "f", "arguments": {"a": 2}}, {"name": "g", "arguments": {}}]',
        get_tool_parser("mistral"),
    )
    assert [c.name for c in calls] == ["f", "g"]


def test_llama3_json_python_tag():
    calls, _ = try_tool_call_parse(
        '<|python_tag|>{"name": "lookup", "parameters": {"q": "tpu"}}',
        get_tool_parser("llama3_json"),
    )
    assert calls[0].name == "lookup"
    assert json.loads(calls[0].arguments) == {"q": "tpu"}


def test_nemotron_toolcall_wrapper():
    calls, content = try_tool_call_parse(
        'thinking done <TOOLCALL>[{"name": "calc", "arguments": {"expr": "1+1"}}]</TOOLCALL>',
        get_tool_parser("nemotron_deci"),
    )
    assert calls[0].name == "calc"
    assert content == "thinking done"


def test_pythonic():
    calls, content = try_tool_call_parse(
        '[get_weather(city="SF", units="metric"), get_time(tz="PST")]',
        get_tool_parser("pythonic"),
    )
    assert [c.name for c in calls] == ["get_weather", "get_time"]
    assert json.loads(calls[0].arguments) == {"city": "SF", "units": "metric"}
    assert content is None


def test_pythonic_rejects_plain_list():
    calls, content = try_tool_call_parse("[1, 2, 3]", get_tool_parser("pythonic"))
    assert calls == [] and content == "[1, 2, 3]"


def test_harmony_channels():
    text = (
        "<|channel|>analysis<|message|>user wants weather<|end|>"
        '<|channel|>commentary to=functions.get_weather <|constrain|>json<|message|>{"city": "SF"}<|call|>'
    )
    calls, _ = try_tool_call_parse(text, get_tool_parser("harmony"))
    assert calls[0].name == "get_weather"
    assert json.loads(calls[0].arguments) == {"city": "SF"}


def test_xml_invoke():
    text = (
        "<function_calls><invoke name=\"search\">"
        "<parameter name=\"query\">tpu kernels</parameter>"
        "<parameter name=\"limit\">5</parameter>"
        "</invoke></function_calls>"
    )
    calls, _ = try_tool_call_parse(text, get_tool_parser("xml"))
    assert calls[0].name == "search"
    assert json.loads(calls[0].arguments) == {"query": "tpu kernels", "limit": 5}


def test_typescript():
    text = '<function_call>```typescript\nfunctions.get_current_weather({"location": "Shanghai"})\n```'
    calls, _ = try_tool_call_parse(text, get_tool_parser("typescript"))
    assert calls[0].name == "get_current_weather"


def test_detect_start():
    cfg = get_tool_parser("hermes")
    assert detect_tool_call_start("<tool", cfg)  # marker prefix
    assert detect_tool_call_start("<tool_call>{", cfg)
    assert not detect_tool_call_start("hello", cfg)


# --- reasoning --------------------------------------------------------------


def test_reasoning_registry():
    names = get_available_reasoning_parsers()
    for expected in ("basic", "deepseek_r1", "qwen", "mistral", "kimi", "gpt_oss"):
        assert expected in names


def test_basic_reasoning_split():
    p = get_reasoning_parser("basic")
    r = p.parse("<think>step 1. step 2.</think>The answer is 4.")
    assert r.reasoning == "step 1. step 2."
    assert r.content == "The answer is 4."


def test_deepseek_r1_starts_in_reasoning():
    p = get_reasoning_parser("deepseek_r1")
    r = p.parse("chain of thought here</think>final answer")
    assert r.reasoning == "chain of thought here"
    assert r.content == "final answer"


def test_reasoning_truncated_stream():
    p = get_reasoning_parser("basic")
    r = p.parse("<think>never closed reasoning")
    assert r.reasoning == "never closed reasoning"
    assert r.content == ""


def test_kimi_markers():
    p = get_reasoning_parser("kimi")
    r = p.parse("◁think▷hmm◁/think▷ok")
    assert r.reasoning == "hmm" and r.content == "ok"


def test_reasoning_streaming_marker_across_deltas():
    p = get_reasoning_parser("basic")
    chunks = ["<th", "ink>rea", "soning</th", "ink>con", "tent"]
    reasoning = content = ""
    for c in chunks:
        r, t = p.feed(c)
        reasoning += r
        content += t
    r, t = p.flush()
    reasoning += r
    content += t
    assert reasoning == "reasoning"
    assert content == "content"


def test_gpt_oss_harmony_reasoning():
    p = get_reasoning_parser("gpt_oss")
    r = p.parse(
        "<|channel|>analysis<|message|>let me think<|end|>"
        "<|channel|>final<|message|>answer<|return|>"
    )
    assert r.reasoning == "let me think"
    assert r.content == "answer"


# --- streaming jail ---------------------------------------------------------


def test_jail_passthrough_plain_text():
    jail = StreamingToolCallJail(config=get_tool_parser("hermes"))
    out = ""
    for d in ["hello ", "world"]:
        _, c = jail.feed(d)
        out += c
    _, tail, calls = jail.finish()
    assert out + tail == "hello world" and calls == []


def test_jail_captures_tool_call():
    jail = StreamingToolCallJail(config=get_tool_parser("hermes"))
    streamed = ""
    for d in ["<tool_call>", '{"name": "f",', ' "arguments": {"x": 1}}', "</tool_call>"]:
        _, c = jail.feed(d)
        streamed += c
    assert streamed == ""  # everything jailed
    _, content, calls = jail.finish()
    assert calls[0].name == "f" and content == ""


def test_jail_releases_non_call():
    # "<tool" prefix looks like a call start but never completes one.
    jail = StreamingToolCallJail(config=get_tool_parser("hermes"))
    _, c1 = jail.feed("<tool")
    _, c2 = jail.feed("ing along>")
    _, tail, calls = jail.finish()
    assert calls == []
    assert c1 + c2 + tail == "<tooling along>"


# --- backend integration ----------------------------------------------------


async def _drive_backend(frames, request):
    backend = Backend(ByteTokenizer())

    async def engine_stream():
        for f in frames:
            yield Annotated(data=f.to_wire())

    out = []
    async for item in backend.transform_response(engine_stream(), request, Context()):
        if isinstance(item, Annotated) and not item.is_annotation():
            out.append(LLMEngineOutput.from_wire(item.data))
    return out


async def test_backend_emits_tool_calls():
    tok = ByteTokenizer()
    payload = '<tool_call>{"name": "f", "arguments": {"x": 1}}</tool_call>'
    ids = tok.encode(payload)
    frames = [LLMEngineOutput(token_ids=ids[:4]), LLMEngineOutput(token_ids=ids[4:]),
              LLMEngineOutput(finish_reason="stop")]
    request = {
        "stop_conditions": {},
        "parser_options": {"tool_call_parser": "hermes", "reasoning_parser": None},
    }
    outs = await _drive_backend(frames, request)
    final = outs[-1]
    assert final.finish_reason == "tool_calls"
    assert final.tool_calls and final.tool_calls[0]["function"]["name"] == "f"
    # No text streamed for a pure tool-call response.
    assert all(not o.text for o in outs)


async def test_backend_reasoning_deltas():
    tok = ByteTokenizer()
    text = "<think>why</think>answer"
    ids = tok.encode(text)
    frames = [LLMEngineOutput(token_ids=ids), LLMEngineOutput(finish_reason="length")]
    request = {
        "stop_conditions": {},
        "parser_options": {"tool_call_parser": None, "reasoning_parser": "basic"},
    }
    outs = await _drive_backend(frames, request)
    reasoning = "".join(o.reasoning or "" for o in outs)
    content = "".join(o.text or "" for o in outs)
    assert reasoning == "why"
    assert content == "answer"
