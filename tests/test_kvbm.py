"""KVBM tests: offload cascade G1→G2→G3, tiered matching, onboarding, and —
the determinism property the reference guards hardest
(tests/kvbm/test_determinism.py) — identical tokens across offload/onboard
cycles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
from dynamo_tpu.engine.kv_cache import BlockAllocator, KvCacheArrays
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.block_manager import CacheLevel, KvBlockManager
from dynamo_tpu.llm.block_manager.storage import DiskPool, HostPool
from dynamo_tpu.llm.tokens import compute_block_hashes
from dynamo_tpu.runtime.engine import Context

CFG = get_config("tiny").replace(dtype="float32")


def make_kvbm(num_device=8, host=4, disk=0, tmp=None):
    cache = KvCacheArrays.create(CFG, num_device, dtype=jnp.float32)
    alloc = BlockAllocator(num_device)
    alloc._free.remove(0)
    kvbm = KvBlockManager(
        cache,
        alloc,
        host_blocks=host,
        disk_dir=str(tmp) if disk else None,
        disk_blocks=disk,
    )
    return kvbm, cache, alloc


def fill_block(cache, bid, value):
    k = np.full((CFG.num_layers, CFG.block_size, CFG.num_kv_heads, CFG.head_dim), value, dtype=np.float32)
    from dynamo_tpu.llm.block_manager.transfer import scatter_blocks

    scatter_blocks(cache, bid, k, -k)
    return k


def test_offload_on_eviction_then_onboard():
    kvbm, cache, alloc = make_kvbm(num_device=5, host=4)  # 4 usable (block 0 reserved)
    tokens = list(range(64))
    hashes = compute_block_hashes(tokens, 16)

    blocks = alloc.allocate(4)
    contents = {h: fill_block(cache, b, float(i + 1)) for i, (b, h) in enumerate(zip(blocks, hashes))}
    alloc.register_hashes(blocks, hashes)
    alloc.release(blocks)
    assert alloc.num_cached == 4

    # Exhaust the pool: cached blocks evict → offload snapshots queue
    # (async — the device copy is dispatch-ordered, the host transfer
    # batches at drain).
    got = alloc.allocate(4)
    kvbm.flush_pending()
    assert kvbm.metrics.offloads_g2 == 4
    assert len(kvbm.host) == 4
    alloc.release(got)

    # Tiered match finds all 4 in G2; onboard copies them back.
    match = kvbm.match_prefix(hashes)
    assert match.g1_blocks == [] and [t for _, t in match.onboardable] == [CacheLevel.G2] * 4
    device_blocks = kvbm.onboard(match, hashes)
    assert len(device_blocks) == 4
    assert kvbm.metrics.onboards_g2 == 4

    # Contents survived the round-trip bit-exactly.
    from dynamo_tpu.llm.block_manager.transfer import gather_blocks

    for bid, h in zip(device_blocks, hashes):
        k_np, v_np = gather_blocks(cache, bid)
        np.testing.assert_array_equal(k_np, contents[h])
        np.testing.assert_array_equal(v_np, -contents[h])

    # Onboarded blocks are registered: a second match hits G1 directly.
    alloc.release(device_blocks)
    match2 = kvbm.match_prefix(hashes)
    assert len(match2.g1_blocks) == 4 and not match2.onboardable


def test_cascade_to_disk(tmp_path):
    kvbm, cache, alloc = make_kvbm(num_device=5, host=2, disk=8, tmp=tmp_path)
    tokens = list(range(64))
    hashes = compute_block_hashes(tokens, 16)
    blocks = alloc.allocate(4)
    for i, b in enumerate(blocks):
        fill_block(cache, b, float(i + 1))
    alloc.register_hashes(blocks, hashes)
    alloc.release(blocks)

    # Evict all 4: host holds 2 (capacity), 2 spill to disk.
    alloc.allocate(4)
    kvbm.flush_pending()
    assert kvbm.metrics.offloads_g2 == 4
    assert kvbm.metrics.offloads_g3 == 2
    assert len(kvbm.host) == 2 and len(kvbm.disk) == 2

    tiers = [t for _, t in kvbm.match_prefix(hashes).onboardable]
    assert set(tiers) == {CacheLevel.G2, CacheLevel.G3}


def test_disk_pool_restart_recovery(tmp_path):
    pool = DiskPool(str(tmp_path), capacity=4)
    k = np.ones((2, 16, 2, 16), dtype=np.float32)
    pool.put(0xABC, k, k * 2)
    # New pool over the same dir recovers the index (resume semantics).
    pool2 = DiskPool(str(tmp_path), capacity=4)
    assert pool2.has(0xABC)
    got = pool2.get(0xABC)
    np.testing.assert_array_equal(got[0], k)
    np.testing.assert_array_equal(got[1], k * 2)


def test_host_pool_lru_spill():
    pool = HostPool(capacity=2)
    a = np.zeros((1,))
    assert pool.put(1, a, a) is None
    assert pool.put(2, a, a) is None
    spilled = pool.put(3, a, a)
    assert spilled is not None and spilled[0] == 1  # LRU out
    pool.get(2)  # touch 2
    spilled = pool.put(4, a, a)
    assert spilled[0] == 3  # 3 is now LRU


async def test_engine_determinism_across_offload_cycles():
    """Generate, evict through a tiny device pool with KVBM host tier, then
    re-generate the same prompt: tokens must be identical (the KVBM
    determinism property, ref tests/kvbm/test_determinism.py)."""

    def build(host_blocks):
        return TpuEngine.build(
            EngineArgs(
                model="tiny",
                dtype="float32",
                kvbm_host_blocks=host_blocks,
                scheduler=SchedulerConfig(
                    num_blocks=8,  # tiny device pool → heavy eviction
                    prefill_buckets=[16, 32, 64],
                    decode_buckets=[1, 2, 4],
                ),
            )
        )

    async def run(engine, prompt):
        out = []
        req = {
            "token_ids": prompt,
            "sampling_options": {"temperature": 0.0},
            "stop_conditions": {"max_tokens": 6},
        }
        async for frame in engine.generate(req, Context()):
            out.extend(frame["token_ids"])
        return out

    engine = build(host_blocks=32)
    try:
        prompt_a = list(range(10, 58))  # 3 blocks
        # B is wider than the free pool, so serving it must evict A's
        # cached blocks. (A 48-token B no longer forces eviction: the
        # full-cover copy-on-write hit made re-serves cheaper — they reuse
        # every resident block instead of re-prefilling the last one.)
        prompt_b = list(range(100, 180))  # 5 full blocks + growth
        first = await run(engine, prompt_a)
        # Push A out of device cache by running B (device pool is tiny).
        for _ in range(3):
            await run(engine, prompt_b)
        assert engine.kvbm.metrics.offloads_g2 > 0, "eviction must have offloaded"
        # A's prefix onboards from host; tokens must match exactly.
        second = await run(engine, prompt_a)
        assert second == first
        assert engine.kvbm.metrics.onboards_g2 > 0, "re-run must have onboarded"
    finally:
        await engine.stop()


async def test_g4_remote_tier_cross_worker():
    """VERDICT r2 #6: evict through G2/G3/G4 on worker A, onboard the same
    blocks on worker B (separate KVBM, shared object store), contents
    bit-identical. Ref: CacheLevel::G4 block_manager.rs:62-75,144."""
    import asyncio

    from dynamo_tpu.llm.block_manager.storage import RemotePool
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.detached()
    loop = asyncio.get_running_loop()
    try:
        def make_worker(tmp=None, disk=0):
            kvbm, cache, alloc = make_kvbm(num_device=5, host=1, disk=disk, tmp=tmp)
            kvbm.attach_remote(RemotePool(drt, loop, refresh_s=0.0))
            return kvbm, cache, alloc

        import tempfile
        with tempfile.TemporaryDirectory() as tmp_a:
            kvbm_a, cache_a, alloc_a = make_worker(tmp=tmp_a + "/a", disk=1)
            tokens = list(range(64))
            hashes = compute_block_hashes(tokens, 16)

            def worker_a_evicts():
                blocks = alloc_a.allocate(4)
                contents = {h: fill_block(cache_a, b, float(i + 1))
                            for i, (b, h) in enumerate(zip(blocks, hashes))}
                alloc_a.register_hashes(blocks, hashes)
                alloc_a.release(blocks)
                # Evicting all 4 cascades: host holds 1, disk holds 1, the
                # rest spill to G4 (remote). Eviction consumes the chain
                # TAIL-first (the graceful-degradation LRU order), so the
                # head blocks land in A's local tiers.
                got = alloc_a.allocate(4)
                kvbm_a.flush_pending()
                alloc_a.release(got)
                # Two more eviction rounds push the chain HEAD through
                # host→disk→remote as well — worker B can only see the
                # shared G4 pool, and a cross-worker match must walk the
                # chain from its head.
                churn = compute_block_hashes(list(range(5000, 5032)), 16)
                cblocks = alloc_a.allocate(2)
                alloc_a.register_hashes(cblocks, churn)
                alloc_a.release(cblocks)
                alloc_a.allocate(4)  # drains the free list AND evicts both
                kvbm_a.flush_pending()
                return contents

            contents = await asyncio.to_thread(worker_a_evicts)
            assert kvbm_a.metrics.offloads_g2 == 6  # 4 chain + 2 churn
            assert kvbm_a.metrics.offloads_g3 >= 1
            assert kvbm_a.metrics.offloads_g4 >= 1
            await asyncio.sleep(0.05)  # fire-and-forget puts land

            # Worker B: fresh device cache + pools, same object store.
            kvbm_b, cache_b, alloc_b = make_worker()

            def worker_b_onboards():
                match = kvbm_b.match_prefix(hashes)
                tiers = [t for _, t in match.onboardable]
                assert CacheLevel.G4 in tiers, tiers
                device_blocks = kvbm_b.onboard(match, hashes)
                return match, device_blocks

            match, device_blocks = await asyncio.to_thread(worker_b_onboards)
            assert kvbm_b.metrics.onboards_g4 >= 1

            # The G4-onboarded prefix must be contiguous from the front (a
            # tier miss ends the walk) and contents bit-identical.
            from dynamo_tpu.llm.block_manager.transfer import gather_blocks

            for bid, h in zip(device_blocks, hashes):
                k_np, v_np = gather_blocks(cache_b, bid)
                np.testing.assert_array_equal(k_np, contents[h])
                np.testing.assert_array_equal(v_np, -contents[h])
    finally:
        await drt.shutdown()


async def test_g4_loop_thread_guard():
    """Calling the remote pool's blocking ops from the event-loop thread
    must raise, not deadlock."""
    import asyncio

    from dynamo_tpu.llm.block_manager.storage import RemotePool
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.detached()
    try:
        pool = RemotePool(drt, asyncio.get_running_loop(), refresh_s=0.0)
        with pytest.raises(RuntimeError, match="worker thread"):
            pool.get(123)
    finally:
        await drt.shutdown()
