"""Tests for the continuous-batching scheduler + TpuEngine facade: greedy
determinism, concurrency, prefix-cache hits, cancellation, stop conditions."""

import asyncio

import jax
import jax.numpy as jnp
import pytest

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.runtime.engine import Context

CFG = get_config("tiny")


def build_engine(**sched_kwargs) -> TpuEngine:
    args = EngineArgs(
        model="tiny",
        dtype="float32",
        scheduler=SchedulerConfig(
            num_blocks=64,
            max_running=8,
            prefill_buckets=[16, 32, 64],
            decode_buckets=[1, 2, 4, 8],
            **sched_kwargs,
        ),
    )
    return TpuEngine.build(args)


def req(tokens, max_tokens=8, temperature=0.0):
    return {
        "token_ids": tokens,
        "sampling_options": {"temperature": temperature},
        "stop_conditions": {"max_tokens": max_tokens},
    }


async def collect(engine, request, ctx=None):
    out = []
    finish = None
    async for frame in engine.generate(request, ctx or Context()):
        out.extend(frame["token_ids"])
        if frame["finish_reason"]:
            finish = frame["finish_reason"]
    return out, finish


async def test_greedy_generation_deterministic():
    engine = build_engine()
    try:
        prompt = list(range(20, 40))
        out1, fin1 = await collect(engine, req(prompt))
        out2, fin2 = await collect(engine, req(prompt))
        assert len(out1) == 8 and fin1 == "length"
        assert out1 == out2  # greedy + same cache → identical
    finally:
        await engine.stop()


async def test_concurrent_requests_interleave():
    engine = build_engine()
    try:
        prompts = [list(range(i * 3, i * 3 + 10)) for i in range(6)]
        results = await asyncio.gather(*(collect(engine, req(p, max_tokens=6)) for p in prompts))
        for out, fin in results:
            assert len(out) == 6 and fin == "length"
        # All KV blocks released or cached after completion.
        assert engine.scheduler.allocator.num_active == 0
    finally:
        await engine.stop()


async def test_concurrent_matches_sequential():
    """Batched decode must produce the same greedy tokens as solo runs."""
    engine = build_engine(enable_prefix_caching=False)
    try:
        prompts = [list(range(10, 26)), list(range(30, 46)), list(range(50, 66))]
        solo = []
        for p in prompts:
            out, _ = await collect(engine, req(p, max_tokens=5))
            solo.append(out)
        conc = await asyncio.gather(*(collect(engine, req(p, max_tokens=5)) for p in prompts))
        assert [c[0] for c in conc] == solo
    finally:
        await engine.stop()


async def test_prefix_cache_hit_skips_prefill():
    engine = build_engine()
    try:
        prompt = list(range(64, 96))  # two full blocks
        await collect(engine, req(prompt, max_tokens=4))
        # Second request with same prompt: prefix blocks should match.
        queue_before = engine.scheduler.request_total
        out, _ = await collect(engine, req(prompt, max_tokens=4))
        assert engine.scheduler.request_total == queue_before + 1
        # The cached-prefix path must still generate correct greedy tokens.
        engine2 = build_engine(enable_prefix_caching=False)
        try:
            ref, _ = await collect(engine2, req(prompt, max_tokens=4))
            assert out == ref
        finally:
            await engine2.stop()
    finally:
        await engine.stop()


async def test_stop_token():
    engine = build_engine()
    try:
        prompt = list(range(20, 40))
        # Find what greedy generates, then use its 3rd token as a stop token.
        out, _ = await collect(engine, req(prompt, max_tokens=8))
        stop_tok = out[2]
        request = req(prompt, max_tokens=8)
        request["stop_conditions"]["stop_token_ids"] = [stop_tok]
        out2, fin = await collect(engine, request)
        assert fin == "stop"
        # Generation halts at the stop token's *first* occurrence (inclusive;
        # the backend operator strips it from text output).
        first = out.index(stop_tok)
        assert out2 == out[: first + 1]
    finally:
        await engine.stop()


async def test_cancellation_frees_blocks():
    engine = build_engine()
    try:
        ctx = Context()
        got = []
        gen = engine.generate(req(list(range(16)), max_tokens=200), ctx)
        async for frame in gen:
            got.extend(frame["token_ids"])
            if len(got) >= 3:
                ctx.stop_generating()
        assert 3 <= len(got) < 200
        await asyncio.sleep(0.05)
        assert engine.scheduler.allocator.num_active == 0
    finally:
        await engine.stop()


async def test_long_prompt_chunked_prefill():
    engine = build_engine()
    try:
        engine.scheduler.sc.max_prefill_chunk = 32
        prompt = list(range(100)) * 2  # 200 tokens → 7 chunks of ≤32
        out, fin = await collect(engine, req(prompt, max_tokens=4))
        assert len(out) == 4 and fin == "length"

        # Must equal unchunked generation.
        engine2 = build_engine()
        try:
            engine2.scheduler.sc.max_prefill_chunk = 64
            ref, _ = await collect(engine2, req(prompt, max_tokens=4))
            assert out == ref
        finally:
            await engine2.stop()
    finally:
        await engine.stop()


async def test_metrics_snapshot():
    engine = build_engine()
    try:
        await collect(engine, req(list(range(10)), max_tokens=3))
        m = engine.metrics()
        assert m.request_total == 1
        assert m.num_running == 0
        assert 0.0 <= m.kv_usage <= 1.0
    finally:
        await engine.stop()


async def test_kv_events_emitted():
    events = []
    args = EngineArgs(
        model="tiny",
        dtype="float32",
        scheduler=SchedulerConfig(num_blocks=64, prefill_buckets=[16, 32, 64], decode_buckets=[1, 2, 4, 8]),
    )
    engine = TpuEngine.build(args, kv_event_sink=events.append)
    try:
        await collect(engine, req(list(range(32)), max_tokens=4))
        stored = [e for e in events if e.kind == "stored"]
        assert stored, "prefix blocks should emit stored events"
    finally:
        await engine.stop()
