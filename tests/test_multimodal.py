"""Multimodal encode-worker path (ref: trtllm encode_helper.py + vllm/sglang
image handling): vision encoder units, image-part extraction, and the
encode+LM two-worker topology on the CPU mesh."""

import base64
import io

import aiohttp
import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
from dynamo_tpu.engine.models import vision
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.discovery import ModelManager
from dynamo_tpu.llm.entrypoint import build_local_pipeline
from dynamo_tpu.llm.http.service import HttpService
from dynamo_tpu.llm.multimodal import (
    EncodeOperator,
    EncodeWorkerHandler,
    LocalVisionEncoder,
    decode_image_data_url,
    extract_images,
    features_from_wire,
    features_to_wire,
)
from dynamo_tpu.llm.tokenizer import ByteTokenizer
from dynamo_tpu.runtime import DistributedRuntime, PushRouter

MODEL = "tiny-mm"


def _data_url(color, size=32):
    from PIL import Image

    img = Image.new("RGB", (size, size), color)
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    return "data:image/png;base64," + base64.b64encode(buf.getvalue()).decode()


def test_vision_encoder_shapes_and_determinism():
    cfg = vision.PRESETS["tiny-vit"]
    params = vision.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    imgs = jnp.asarray(np.random.RandomState(0).rand(2, cfg.image_size, cfg.image_size, 3), jnp.float32)
    out = vision.encode(params, cfg, imgs)
    assert out.shape == (2, cfg.num_patches, cfg.lm_hidden_size)
    out2 = vision.encode(params, cfg, imgs)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
    # Different images → different features.
    assert not np.allclose(np.asarray(out[0]), np.asarray(out[1]))


def test_extract_images_and_data_url():
    url = _data_url("red")
    messages = [
        {"role": "user", "content": [
            {"type": "text", "text": "what is "},
            {"type": "image_url", "image_url": {"url": url}},
            {"type": "text", "text": "this?"},
        ]},
        {"role": "assistant", "content": "plain string survives"},
    ]
    flat, urls = extract_images(messages)
    assert flat[0]["content"] == "what is this?"
    assert flat[1]["content"] == "plain string survives"
    assert urls == [url]
    img = decode_image_data_url(url, 32)
    assert img.shape == (32, 32, 3)
    np.testing.assert_allclose(img[0, 0], [1.0, 0.0, 0.0], atol=0.02)
    wire = features_to_wire(np.ones((3, 4), np.float32))
    np.testing.assert_array_equal(features_from_wire(wire), np.ones((3, 4), np.float32))


def _lm_engine():
    return TpuEngine.build(
        EngineArgs(
            model="tiny", dtype="float32",
            scheduler=SchedulerConfig(num_blocks=128, prefill_buckets=[16, 32, 64, 128],
                                      decode_buckets=[1, 2, 4]),
        )
    )


async def _chat_with_image(service, url):
    async with aiohttp.ClientSession() as s:
        body = {
            "model": MODEL,
            "messages": [{"role": "user", "content": [
                {"type": "image_url", "image_url": {"url": url}},
                {"type": "text", "text": "describe"},
            ]}],
            "max_tokens": 6,
            "temperature": 0,
        }
        async with s.post(f"http://127.0.0.1:{service.port}/v1/chat/completions", json=body) as r:
            assert r.status == 200, await r.text()
            data = await r.json()
    return data["choices"][0]["message"]["content"], data["usage"]


async def test_local_encoder_http_e2e():
    """Chat request with an image content part served end-to-end; the image
    content influences generation (different images ⇒ different outputs)."""
    engine = _lm_engine()
    encoder = LocalVisionEncoder(preset="tiny-vit")
    manager = ModelManager()
    manager.add_model("chat", MODEL, build_local_pipeline(ByteTokenizer(), engine, encoder=encoder))
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    try:
        text_red, usage = await _chat_with_image(service, _data_url("red"))
        text_blue, _ = await _chat_with_image(service, _data_url("blue"))
        # 16 feature rows (32/8 → 4x4 patches) prepended to the prompt.
        assert usage["prompt_tokens"] > 0
        assert text_red != text_blue, "image features did not reach prefill"
    finally:
        await service.stop()
        await engine.stop()


async def test_encode_worker_two_worker_topology():
    """Ref done-criterion: image chat request through an encode+LM 2-worker
    topology — the frontend pipeline calls the encode worker over the
    runtime, features flow to the LM worker's prefill."""
    drt = await DistributedRuntime.detached()
    engine = _lm_engine()
    try:
        # Encode worker (its own component, as `--role encode` serves it).
        enc_handler = EncodeWorkerHandler(LocalVisionEncoder(preset="tiny-vit"))
        enc_ep = drt.namespace("mmtest").component("encode").endpoint("generate")
        await enc_ep.serve_endpoint(enc_handler.generate, stats_handler=enc_handler.stats_handler)
        enc_client = PushRouter(await enc_ep.client())

        manager = ModelManager()
        manager.add_model(
            "chat", MODEL,
            build_local_pipeline(ByteTokenizer(), engine, encode_client=enc_client),
        )
        service = HttpService(manager, host="127.0.0.1", port=0)
        await service.start()
        try:
            text_red, _ = await _chat_with_image(service, _data_url("red"))
            text_blue, _ = await _chat_with_image(service, _data_url("blue"))
            assert enc_handler.requests_total == 2
            assert text_red != text_blue
        finally:
            await service.stop()
    finally:
        await engine.stop()
        await drt.shutdown()


def test_scheduler_rejects_oversized_features():
    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.models import llama
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import Scheduler, StopConditions

    c = get_config("tiny")
    params = llama.init_params(c, jax.random.PRNGKey(0), dtype=jnp.float32)
    sched = Scheduler(c, params, SchedulerConfig(num_blocks=32), dtype=jnp.float32)
    try:
        sched.add_request(
            "r", [1, 2], SamplingParams(), StopConditions(max_tokens=2),
            mm_features=np.zeros((5, c.hidden_size), np.float32),
        )
        raise AssertionError("expected ValueError")
    except ValueError:
        pass
