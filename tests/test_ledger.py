"""Tenant capacity ledger (runtime/ledger.py): SpaceSaving sketch error
bounds vs exact counts on adversarial streams, merge semantics, bill
conservation through the bounded ledger's ``other`` bucket, the fleet
merge + autopsy ``--tenant`` attribution path, the real scheduler's
billing choke point (with the 0-post-warmup-compile invariant while the
ledger is armed), and the demo-stack e2e: two tenants at 9:1 skew through
wire-path mockers ranked by the fleet-merged top-K, with per-tenant SLO
telemetry that disagrees between them — plus chaos: a crash+migration leg
bills exactly once per surviving leg, so per-tenant totals conserve."""

import asyncio
import random
import time
from collections import Counter

from dynamo_tpu.runtime.ledger import (
    RequestBill,
    SpaceSaving,
    TenantFleet,
    TenantLedger,
    attribute,
)
from dynamo_tpu.runtime.telemetry import SloConfig


# --- SpaceSaving: error bounds vs exact, on adversarial streams ---------------

def _adversarial_streams():
    """(name, [(key, weight)]) streams built to stress eviction: long
    distinct-key tails (every offer evicts), heavy hitters arriving late
    (after their slot was recycled many times), and weighted skew."""
    rng = random.Random(7)
    # 1. Distinct-key churn with two late heavy hitters: the worst case for
    #    over-estimation — every singleton inherits the eviction floor.
    churn = [(f"t{i:04d}", 1.0) for i in range(400)]
    churn += [("hog", 3.0)] * 120 + [("warm", 2.0)] * 60
    rng.shuffle(churn)
    # 2. Zipf-ish skew over 100 tenants, weighted offers.
    zipf = []
    for i in range(100):
        for _ in range(max(1, 200 // (i + 1))):
            zipf.append((f"z{i:03d}", rng.uniform(0.5, 2.0)))
    rng.shuffle(zipf)
    # 3. Alternating attack: k equal "decoys" keeping every slot at the same
    #    count, then a burst of fresh keys forcing lexicographic evictions.
    attack = [(f"d{i}", 1.0) for i in range(8)] * 20
    attack += [(f"fresh{i:03d}", 1.0) for i in range(50)]
    return [("churn", churn), ("zipf", zipf), ("attack", attack)]


def test_spacesaving_error_bounds_vs_exact_adversarial():
    for name, stream in _adversarial_streams():
        k = 8
        sk = SpaceSaving(k)
        exact = Counter()
        for key, w in stream:
            sk.offer(key, w)
            exact[key] += w
        total = sum(exact.values())
        assert abs(sk.total - total) < 1e-6, name
        bound = total / k
        for key, true in exact.items():
            est = sk.estimate(key)
            if key in sk:
                # Over-estimate only, by at most the tracked error, which is
                # itself within the classic total/k bound.
                assert est >= true - 1e-9, (name, key)
                assert est - true <= sk.error(key) + 1e-9, (name, key)
                assert sk.error(key) <= bound + 1e-9, (name, key)
            else:
                # An untracked key's true count can't exceed the floor.
                assert true <= sk.min_count() + 1e-9, (name, key)
        # Any key heavier than total/k is guaranteed tracked.
        for key, true in exact.items():
            if true > bound:
                assert key in sk, (name, key, true, bound)


def test_spacesaving_merge_equals_single_stream_when_k_covers():
    """With k ≥ distinct keys the sketch is exact, so merging two halves
    must reproduce the single-stream sketch bit-for-bit."""
    rng = random.Random(3)
    stream = [(f"t{rng.randrange(12)}", rng.uniform(0.1, 3.0)) for _ in range(500)]
    whole = SpaceSaving(16)
    a, b = SpaceSaving(16), SpaceSaving(16)
    for i, (key, w) in enumerate(stream):
        whole.offer(key, w)
        (a if i % 2 else b).offer(key, w)
    merged = a.merge(b)
    assert abs(merged.total - whole.total) < 1e-9
    got = {key: (c, e) for key, c, e in merged.items()}
    want = {key: (c, e) for key, c, e in whole.items()}
    assert set(got) == set(want)
    for key in want:
        assert abs(got[key][0] - want[key][0]) < 1e-9
        assert got[key][1] == want[key][1] == 0.0  # exact ⇒ zero error


def test_spacesaving_merge_preserves_bounds_under_eviction():
    """Merging two lossy sketches keeps the over-estimate property and the
    summed error bound (≤ total_a/k + total_b/k)."""
    rng = random.Random(11)
    k = 8
    stream = [(f"t{rng.randrange(60)}", 1.0) for _ in range(2000)]
    half = len(stream) // 2
    exact = Counter()
    for key, _ in stream:
        exact[key] += 1
    a, b = SpaceSaving(k), SpaceSaving(k)
    for key, w in stream[:half]:
        a.offer(key, w)
    for key, w in stream[half:]:
        b.offer(key, w)
    merged = a.merge(b)
    assert merged.total == len(stream)
    bound = len(stream) / k  # total_a/k + total_b/k = total/k
    for key, _c, e in merged.items():
        assert merged.estimate(key) >= exact[key] - 1e-9
        assert e <= bound + 1e-9


def test_spacesaving_deterministic_tie_breaks():
    # Rank ties: equal counts order by the lexicographically smaller key.
    sk = SpaceSaving(4)
    for key in ("bravo", "alpha", "delta"):
        sk.offer(key, 2.0)
    assert [t for t, _, _ in sk.items()] == ["alpha", "bravo", "delta"]
    # Eviction ties: the (count, key) lexicographic minimum is the victim.
    sk.offer("zulu", 2.0)  # fills slot 4
    sk.offer("newcomer", 1.0)  # all at count 2 → "alpha" is the victim
    assert "alpha" not in sk
    assert sk.estimate("newcomer") == 3.0 and sk.error("newcomer") == 2.0
    # Replicas of the same stream agree exactly (items() identical).
    rng = random.Random(5)
    stream = [(f"t{rng.randrange(30)}", rng.uniform(0.1, 2.0)) for _ in range(800)]
    r1, r2 = SpaceSaving(8), SpaceSaving(8)
    for key, w in stream:
        r1.offer(key, w)
        r2.offer(key, w)
    assert r1.items() == r2.items()


def test_spacesaving_wire_roundtrip():
    sk = SpaceSaving(4)
    for i in range(10):
        sk.offer(f"t{i}", float(i + 1))
    back = SpaceSaving.from_wire(sk.to_wire())
    assert back.items() == sk.items()
    assert back.total == sk.total and back.k == sk.k


# --- TenantLedger: conservation, bounded memory, SLO ---------------------------

def _bill(tenant, device=0.0, kv=0.0, queue=0.0, tokens=0, reason="stop",
          ttft_s=None, tpot_s=None):
    return RequestBill(
        tenant=tenant, request_id=f"r-{tenant}", queue_s=queue,
        prefill_device_s=device * 0.4, decode_device_s=device * 0.6,
        flops=device * 1e12, output_tokens=tokens, kv_block_s=kv,
        finish_reason=reason, ttft_s=ttft_s, tpot_s=tpot_s,
    )


def test_ledger_bill_conservation_with_other_bucket():
    """Σ tracked estimates + other stays within 1% of the exact fleet
    total on a skewed 40-tenant stream through a top-8 ledger, and the
    heavy hitter ranks first in every dimension."""
    rng = random.Random(2)
    ledger = TenantLedger(top_k=8)
    exact = {"device_seconds": 0.0, "kv_block_seconds": 0.0, "queue_seconds": 0.0}
    for i in range(600):
        tenant = "hog" if rng.random() < 0.5 else f"t{rng.randrange(40):02d}"
        d, k, q = rng.uniform(0.01, 0.2), rng.uniform(0.1, 2.0), rng.uniform(0.0, 0.05)
        if tenant == "hog":
            d, k, q = d * 8, k * 8, q * 8
        ledger.record(_bill(tenant, device=d, kv=k, queue=q, tokens=10))
        exact["device_seconds"] += d
        exact["kv_block_seconds"] += k
        exact["queue_seconds"] += q
    report = attribute(ledger.to_wire())
    assert report["bills"] == 600
    for dim, true_total in exact.items():
        r = report[dim]
        assert abs(r["total"] - true_total) < 1e-6
        recovered = sum(t["value"] for t in r["tenants"]) + r["other"]
        assert abs(recovered - true_total) <= 0.01 * true_total + 1e-9, (
            f"{dim}: Σ tracked + other = {recovered} vs exact {true_total}"
        )
        assert r["tenants"][0]["tenant"] == "hog"
        assert 0.0 <= r["other_share"] <= 1.0
        assert all(0.0 <= t["share"] <= 1.0 for t in r["tenants"])


def test_ledger_bounded_memory_and_digest_eviction():
    """200 one-shot tenants through a top-4 ledger: sketches, digests and
    SLO state all stay O(top_k) — eviction from the device sketch drops the
    tenant's telemetry too."""
    ledger = TenantLedger(top_k=4, slo=SloConfig(ttft_ms=100.0, tpot_ms=10.0))
    for i in range(200):
        ledger.record(_bill(f"one{i:03d}", device=0.01, kv=0.1, queue=0.001,
                            tokens=4, ttft_s=0.05, tpot_s=0.005))
    wire = ledger.to_wire()
    assert len(wire["sketches"]["device_seconds"]["items"]) <= 4
    assert len(wire["digests"]) <= 4
    assert len(wire["slo"]) <= 4
    assert wire["bills"] == 200
    # The exact totals still conserve everything the sketch forgot.
    assert abs(wire["totals"]["device_seconds"] - 2.0) < 1e-6
    stats = ledger.to_stats()
    assert stats["tenant_bills_total"] == 200
    assert stats["tenant_tracked"] <= 4


def test_ledger_slo_judging_per_tenant():
    """Tracked tenants get per-phase attained/violated counters; cancelled
    and timed-out requests are never judged."""
    ledger = TenantLedger(top_k=8, slo=SloConfig(ttft_ms=100.0, tpot_ms=10.0))
    ledger.record(_bill("good", device=1.0, ttft_s=0.05, tpot_s=0.005))
    ledger.record(_bill("bad", device=1.0, ttft_s=0.5, tpot_s=0.05))
    ledger.record(_bill("bad", device=1.0, ttft_s=0.5, reason="cancelled"))
    ledger.record(_bill("bad", device=1.0, ttft_s=0.5, reason="timeout"))
    wire = ledger.to_wire()
    assert wire["slo"]["good"] == {"attained": {"ttft": 1, "tpot": 1},
                                   "violated": {"ttft": 0, "tpot": 0}}
    assert wire["slo"]["bad"] == {"attained": {"ttft": 0, "tpot": 0},
                                  "violated": {"ttft": 1, "tpot": 1}}
    # Digests observed the latency even on unjudged finishes (the stream is
    # still real traffic), but the verdict counters did not move.
    assert wire["digests"]["bad"]["ttft"]["window"]["count"] == 3
    stats = ledger.to_stats()
    assert stats["tenant_slo_attained_total"] == 2
    assert stats["tenant_slo_violated_total"] == 2


def test_tenant_fleet_merge_across_workers():
    """The aggregator-side merge: totals/bills/SLO sum exactly, and the
    merged sketch keeps the over-estimate property over the union stream."""
    ledgers = [TenantLedger(top_k=8) for _ in range(3)]
    exact = Counter()
    rng = random.Random(9)
    for w, ledger in enumerate(ledgers):
        for i in range(200):
            tenant = f"t{rng.randrange(20):02d}"
            d = rng.uniform(0.01, 0.1) * (5 if tenant == "t00" else 1)
            ledger.record(_bill(tenant, device=d, kv=d * 4, queue=d / 10,
                                tokens=8, ttft_s=0.01, tpot_s=0.001))
            exact[tenant] += d
    merged = TenantFleet().merge([led.to_wire() for led in ledgers])
    assert merged["bills"] == 600
    want_total = sum(led.totals["device_seconds"] for led in ledgers)
    assert abs(merged["totals"]["device_seconds"] - want_total) < 1e-6
    fleet_sketch = SpaceSaving.from_wire(merged["sketches"]["device_seconds"])
    for tenant, true in exact.items():
        if tenant in fleet_sketch:
            assert fleet_sketch.estimate(tenant) >= true - 1e-9
    assert fleet_sketch.items()[0][0] == "t00"  # the heavy tenant survives the merge
    # SLO counters sum across workers.
    want_attained = sum(led.totals["slo_attained"] for led in ledgers)
    got_attained = sum(s["attained"]["ttft"] + s["attained"]["tpot"]
                       for s in merged["slo"].values())
    assert got_attained == want_attained == 0  # no SloConfig ⇒ nothing judged
    # Empty input is a clean no-op.
    assert TenantFleet().merge([]) == {}


# --- autopsy --tenant ----------------------------------------------------------

def _spiky_bundle(ledger_snapshot=None, raw_wire=None):
    bundle = {
        "reason": "queue_wait_p99",
        "ts": 1234.5,
        "detector": {
            "last_values": {"queue_wait_p99": 1.5, "ttft_p99": 0.2},
            "baselines": {"queue_wait_p99": 0.01, "ttft_p99": 0.1},
        },
        "stats": {},
        "evidence": {},
    }
    if ledger_snapshot is not None:
        bundle["evidence"]["tenant_ledger"] = ledger_snapshot
    if raw_wire is not None:
        bundle["stats"]["tenant_ledger"] = raw_wire
    return bundle


def test_autopsy_tenant_attributes_spike_to_heavy_tenant(capsys):
    from tools.autopsy import render, tenant_report

    ledger = TenantLedger(top_k=8, slo=SloConfig(ttft_ms=100.0))
    for _ in range(9):
        ledger.record(_bill("acme", device=0.9, kv=3.0, queue=0.9, ttft_s=0.2))
    ledger.record(_bill("beta", device=0.1, kv=0.3, queue=0.1, ttft_s=0.01))

    report = tenant_report(_spiky_bundle(ledger_snapshot=ledger.snapshot()))
    assert report["mode"] == "tenant"
    # The 150x queue-wait excursion wins the window attribution, so the
    # tenant join ranks by queue-seconds.
    assert report["attribution"] == "queue_wait"
    assert report["dimension"] == "queue_seconds"
    ranked = report["ledger"]["queue_seconds"]["tenants"]
    assert ranked[0]["tenant"] == "acme" and ranked[0]["share"] > 0.8
    assert "acme" in report["headline"] and "queue" in report["headline"]
    assert report["slo"]["acme"]["violated"]["ttft"] == 9
    render(report)  # must not raise; human-readable output
    out = capsys.readouterr().out
    assert "acme" in out and "<other>" in out

    # Fallback: an older bundle without the evidence probe but with the raw
    # sketch wire on the captured stats scrape attributes identically.
    fallback = tenant_report(_spiky_bundle(raw_wire=ledger.to_wire()))
    assert fallback["dimension"] == "queue_seconds"
    assert fallback["ledger"]["queue_seconds"]["tenants"][0]["tenant"] == "acme"

    # No ledger anywhere → structured error, not a crash.
    empty = tenant_report(_spiky_bundle())
    assert "no tenant ledger" in empty["error"]


# --- real scheduler: billing choke point + 0 post-warmup compiles --------------

def test_scheduler_bills_tenants_with_zero_post_warmup_compiles():
    """The billing plane armed on the real scheduler: per-tenant bills are
    emitted at finish with positive device/KV/queue charges, per-step
    conservation holds (device-seconds bounded by wall time × the clamped
    measured multiplier), blocks drain, and the ledger adds no post-warmup
    XLA compiles — the accounting is pure host arithmetic."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.models import llama
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions

    cfg = get_config("tiny")
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    sched = Scheduler(cfg, params, SchedulerConfig(
        num_blocks=128, prefill_buckets=[16, 32, 64], decode_buckets=[1, 2, 4],
        num_scheduler_steps=1, enable_prefix_caching=False,
        slo_ttft_ms=10_000.0, slo_tpot_ms=1_000.0, ledger_top_k=8,
    ), dtype=jnp.float32)
    sched.warmup(64)
    sched.flight.mark_warmup_done(warmed=True)

    t0 = time.perf_counter()
    for i in range(3):
        sched.add_request(f"a{i}", list(range(1 + i, 17 + i)),
                          SamplingParams(temperature=0.0),
                          StopConditions(max_tokens=8), tenant="acme")
    sched.add_request("b0", list(range(5, 21)), SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=8), tenant="beta")
    finished = {}
    for _ in range(400):
        if not sched.has_work():
            break
        for seq, out in sched.step():
            if out.finish_reason:
                finished[seq.request_id] = out.finish_reason
    wall_s = time.perf_counter() - t0

    assert len(finished) == 4 and not sched.has_work()
    assert sched.flight.compiles_after_warmup_total == 0, sched.flight.post_warmup_keys
    assert sched.allocator.num_active == 0

    wire = sched.ledger.to_wire()
    assert wire["bills"] == 4
    totals = wire["totals"]
    assert totals["device_seconds"] > 0.0
    assert totals["kv_block_seconds"] > 0.0
    assert totals["queue_seconds"] >= 0.0
    assert totals["output_tokens"] == 32
    assert totals["flops"] > 0.0
    # Per-step conservation: Σ billed device-seconds can't exceed the wall
    # time of the whole drive loop times the clamped measured multiplier.
    assert totals["device_seconds"] <= wall_s * 4.0
    report = attribute(wire)
    ranked = report["device_seconds"]["tenants"]
    assert [t["tenant"] for t in ranked] == ["acme", "beta"]
    assert ranked[0]["value"] > ranked[1]["value"]
    # 4 bills through a k=8 sketch: exact, so the other bucket is empty.
    assert report["device_seconds"]["other"] == 0.0
    # Both tenants were judged against the (generous) SLO.
    assert wire["slo"]["acme"]["attained"]["ttft"] == 3
    assert wire["slo"]["beta"]["attained"]["ttft"] == 1


# --- demo stack e2e: 9:1 tenant skew through wire-path mockers -----------------

async def _tenant_stack(drt, ns, n_workers=2):
    from dynamo_tpu.llm.entrypoint import RouterEngine
    from dynamo_tpu.llm.migration import Migration
    from dynamo_tpu.llm.mocker import MockEngineArgs, MockTpuEngine
    from dynamo_tpu.runtime.push_router import PushRouter, RetryPolicy

    ep = drt.namespace(ns).component("w").endpoint("gen")
    workers = []
    for _ in range(n_workers):
        engine = MockTpuEngine(MockEngineArgs(
            speedup_ratio=50.0, num_blocks=128, token_rule="position",
            slo_ttft_ms=10_000.0, slo_tpot_ms=1_000.0))
        handle = await ep.serve_endpoint(
            engine.generate, stats_handler=engine.stats_handler)
        drt.local_engines.pop(handle.instance.instance_id)
        workers.append((engine, handle))
    client = await ep.client()
    await client.wait_for_instances(n_workers, timeout=5)
    router = PushRouter(client, retry=RetryPolicy(max_retries=2, backoff_base_s=0.01, seed=0))
    engine = Migration(2).attach(RouterEngine(router))
    return client, engine, workers


def _req(tokens, tenant, max_tokens=8):
    return {"token_ids": list(tokens), "sampling_options": {},
            "stop_conditions": {"max_tokens": max_tokens}, "tenant": tenant}


async def _collect(engine, request):
    from dynamo_tpu.runtime.engine import Context

    got, finish = [], None
    async for item in engine.generate(dict(request), Context()):
        data = item.data if hasattr(item, "data") else item
        if isinstance(data, dict):
            got.extend(data.get("token_ids") or [])
            if data.get("finish_reason"):
                finish = data["finish_reason"]
    return got, finish


async def test_demo_stack_two_tenants_nine_to_one():
    """18 'heavy' requests vs 2 'light' (9:1) through two wire-path mocker
    workers: the fleet-merged top-K ranks heavy first in every dimension
    with ~90% share, per-tenant SLO telemetry exists for BOTH tenants and
    disagrees (digest mass 9:1), and the aggregator renders fleet-true
    labeled families from the merged sketches."""
    from dynamo_tpu.metrics_aggregator import MetricsAggregator
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.detached()
    try:
        client, engine, workers = await _tenant_stack(drt, "ledg1")
        jobs = [_req(range(10), "heavy") for _ in range(18)]
        jobs += [_req(range(10), "light") for _ in range(2)]
        results = await asyncio.gather(*(_collect(engine, j) for j in jobs))
        for got, finish in results:
            assert got == list(range(10, 18)) and finish == "length"

        stats = await client.scrape_stats(timeout=1.0)
        assert len(stats) == 2
        wires = [s["tenant_ledger"] for s in stats.values()]
        assert sum(w["bills"] for w in wires) == 20

        merged = TenantFleet().merge(wires)
        report = attribute(merged)
        for dim in ("device_seconds", "kv_block_seconds", "queue_seconds"):
            ranked = report[dim]["tenants"]
            assert [t["tenant"] for t in ranked] == ["heavy", "light"], dim
        share = report["device_seconds"]["tenants"][0]["share"]
        assert 0.75 <= share <= 0.98, f"heavy's device share {share} not ~0.9"

        # Per-tenant SLO telemetry exists for both and disagrees 9:1.
        assert merged["slo"]["heavy"]["attained"]["ttft"] == 18
        assert merged["slo"]["light"]["attained"]["ttft"] == 2
        heavy_obs = sum(w["digests"].get("heavy", {}).get("ttft", {})
                        .get("window", {}).get("count", 0) for w in wires)
        light_obs = sum(w["digests"].get("light", {}).get("ttft", {})
                        .get("window", {}).get("count", 0) for w in wires)
        assert heavy_obs == 18 and light_obs == 2

        # The aggregator exports fleet-true labeled families.
        agg = MetricsAggregator(drt, "ledg1", "w", "gen")
        agg.export_stats(stats)
        text = agg.registry.render().decode()

        def family_value(family, tenant):
            for line in text.splitlines():
                if line.startswith(f"{family}{{") and f'tenant="{tenant}"' in line:
                    return float(line.rsplit(" ", 1)[1])
            return 0.0

        assert family_value("dynamo_component_tenant_kv_block_seconds_total",
                            "light") > 0.0
        # The conservation bucket is always present (even when empty).
        assert any(line.startswith("dynamo_component_tenant_device_seconds_total{")
                   and 'tenant="other"' in line for line in text.splitlines())

        dev = "dynamo_component_tenant_device_seconds_total"
        assert family_value(dev, "heavy") > family_value(dev, "light") > 0.0
        # Labeled families conserve: tracked + other ≈ the exact fleet total.
        recovered = (family_value(dev, "heavy") + family_value(dev, "light")
                     + family_value(dev, "other"))
        assert abs(recovered - merged["totals"]["device_seconds"]) <= (
            0.01 * merged["totals"]["device_seconds"] + 1e-9)
    finally:
        await drt.shutdown()


async def test_chaos_crash_migration_conserves_tenant_totals():
    """A worker crash mid-stream: the dead leg's in-flight consumption
    bills nowhere (process death — same as a real engine), the replayed
    leg bills exactly once on the survivor, and the fleet-merged per-tenant
    totals equal the per-worker sums exactly — no double billing across
    migration legs."""
    from dynamo_tpu.runtime import faults
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.detached()
    try:
        client, engine, workers = await _tenant_stack(drt, "ledg2")
        faults.arm(faults.FaultInjector(
            [{"site": "worker.step", "kind": "crash", "after": 4}], seed=7))

        got, finish = await _collect(engine, _req(range(10), "acme", max_tokens=8))
        assert got == list(range(10, 18)) and finish == "length"
        assert faults.get_injector().to_stats()["faults_crash_total"] == 1

        for mocker, _ in workers:
            assert mocker.allocator.num_active == 0
        wires = [mocker.ledger.to_wire() for mocker, _ in workers]
        # Exactly ONE bill in the whole fleet: the crashed leg never reached
        # its finish choke point (its partial consumption died with the
        # 'process'), the survivor's replay billed once.
        assert sum(w["bills"] for w in wires) == 1
        merged = TenantFleet().merge(wires)
        per_worker_sum = sum(w["totals"]["device_seconds"] for w in wires)
        assert abs(merged["totals"]["device_seconds"] - per_worker_sum) < 1e-9
        report = attribute(merged)
        ranked = report["device_seconds"]["tenants"]
        assert [t["tenant"] for t in ranked] == ["acme"]
        # Conservation: the single tracked tenant owns the entire total.
        assert abs(ranked[0]["value"] - merged["totals"]["device_seconds"]) < 1e-9
        assert report["device_seconds"]["other"] == 0.0
        # The surviving leg billed the full 8 output tokens (migration folds
        # emitted tokens into the replay prompt; the mocker regenerates and
        # bills what IT computed).
        assert merged["totals"]["output_tokens"] >= 1
    finally:
        faults.disarm()
        await drt.shutdown()
