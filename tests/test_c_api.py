"""C ABI KV-event bridge (ref: lib/bindings/c/src/lib.rs)."""

import asyncio
import ctypes
import json

import pytest

from dynamo_tpu.native import available, get_native

pytestmark = pytest.mark.skipif(not available(), reason="native extension not built")


def _publish_via_ctypes(lib, worker_id, hashes, parent=None):
    arr = (ctypes.c_uint64 * len(hashes))(*hashes)
    return lib.dynamo_tpu_kv_event_publish_stored(
        worker_id, arr, len(hashes), parent or 0, 1 if parent is not None else 0
    )


def test_c_abi_publish_and_drain():
    from dynamo_tpu.llm.c_api import load_c_abi

    lib = load_c_abi()
    assert lib.dynamo_tpu_llm_init() == 0
    try:
        assert _publish_via_ctypes(lib, 7, [11, 22, 33], parent=5) == 0
        arr = (ctypes.c_uint64 * 2)(22, 33)
        assert lib.dynamo_tpu_kv_event_publish_removed(7, arr, 2) == 0

        native = get_native()
        evs = native.drain_kv_events()
        assert len(evs) == 2
        assert evs[0] == {"worker_id": 7, "kind": "stored", "block_hashes": [11, 22, 33], "parent_hash": 5}
        assert evs[1]["kind"] == "removed" and evs[1]["parent_hash"] is None
        assert native.drain_kv_events() == []  # drained
    finally:
        assert lib.dynamo_tpu_llm_shutdown() == 0


def test_c_abi_requires_init():
    from dynamo_tpu.llm.c_api import load_c_abi

    lib = load_c_abi()
    lib.dynamo_tpu_llm_shutdown()
    assert _publish_via_ctypes(lib, 1, [1]) == -1  # not initialized


async def test_native_events_pump_to_router_stream():
    """C ABI → NativeKvEventSource → KvEventPublisher → durable stream."""
    from dynamo_tpu.llm.c_api import NativeKvEventSource, load_c_abi
    from dynamo_tpu.llm.kv_router.publisher import KvEventPublisher, kv_events_stream_name
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.detached()
    lib = load_c_abi()
    lib.dynamo_tpu_llm_init()
    try:
        pub = KvEventPublisher(drt, "ns", "backend", worker_id=9)
        pub.start()
        source = NativeKvEventSource(pub, poll_interval_s=0.02)
        source.start()

        _publish_via_ctypes(lib, 9, [101, 102])
        for _ in range(100):
            if source.events_pumped >= 1:
                break
            await asyncio.sleep(0.02)
        await asyncio.sleep(0.1)  # let the publisher drain to the stream
        await source.stop()
        await pub.stop()

        stream = await drt.bus.stream(kv_events_stream_name("ns", "backend"))
        msgs = await stream.fetch(1)
        assert len(msgs) >= 1
        payload = json.loads(msgs[0].data)
        assert payload["block_hashes"] == [101, 102] and payload["worker_id"] == 9
    finally:
        lib.dynamo_tpu_llm_shutdown()
        await drt.shutdown()
