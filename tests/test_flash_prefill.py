"""Numerics parity: Pallas flash prefill (interpret mode on CPU) vs the XLA
reference path. Covers fresh prefills (no prefix piece), chunked prefills
with a cached prefix (online-softmax merge), padded buckets, and GQA.
Ref role: the engines' FlashAttention prefill kernels (SURVEY.md §1 L5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama


def _mk(config, seed=0):
    params = llama.init_params(config, jax.random.PRNGKey(seed), dtype=jnp.float32)
    cache = KvCacheArrays.create(config, num_blocks=32, dtype=jnp.float32)
    return params, cache


def _tokens(n, vocab, seed=1):
    return np.asarray(jax.random.randint(jax.random.PRNGKey(seed), (n,), 0, vocab))


@pytest.mark.parametrize("valid", [64, 50])
def test_fresh_prefill_parity(valid):
    """cache_len=0 path: kernel-only attention must match the XLA path."""
    c = get_config("tiny")
    params, cache = _mk(c)
    T = 64
    toks = np.zeros((T,), np.int32)
    toks[:valid] = _tokens(valid, c.vocab_size)
    table = jnp.asarray(np.arange(1, 5, dtype=np.int32).repeat(1))
    args = (
        jnp.asarray(toks),
        jnp.int32(valid),
        jnp.int32(0),
        jnp.pad(table, (0, 12)),
    )
    ref, kr, vr = llama.prefill(params, c, cache.k, cache.v, *args, use_flash=False)
    out, kf, vf = llama.prefill(params, c, cache.k, cache.v, *args, use_flash=True, has_prefix=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)
    # Cache contents written identically.
    np.testing.assert_allclose(np.asarray(kf), np.asarray(kr), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(vf), np.asarray(vr), rtol=2e-4, atol=2e-4)


def test_chunked_prefill_prefix_merge_parity():
    """Second chunk attends [cached prefix ; chunk] — the merge path."""
    c = get_config("tiny")
    params, _ = _mk(c)
    total, first = 96, 64
    toks = _tokens(total, c.vocab_size)
    table = jnp.asarray(np.pad(np.arange(1, 8, dtype=np.int32), (0, 9)))

    def run(use_flash):
        cache = KvCacheArrays.create(c, num_blocks=32, dtype=jnp.float32)
        k, v = cache.k, cache.v
        t0 = np.zeros((64,), np.int32)
        t0[:first] = toks[:first]
        _, k, v = llama.prefill(
            params, c, k, v, jnp.asarray(t0), jnp.int32(first), jnp.int32(0), table,
            use_flash=use_flash, has_prefix=False,
        )
        t1 = np.zeros((32,), np.int32)
        t1[: total - first] = toks[first:]
        logits, k, v = llama.prefill(
            params, c, k, v, jnp.asarray(t1), jnp.int32(total - first), jnp.int32(first), table,
            use_flash=use_flash, has_prefix=True,
        )
        return logits, k, v

    ref, kr, vr = run(False)
    out, kf, vf = run(True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(kf), np.asarray(kr), rtol=2e-4, atol=2e-4)


def test_all_logits_parity():
    """Spec-decode verification surface (all_logits=True) under flash."""
    c = get_config("tiny")
    params, cache = _mk(c)
    T, valid = 32, 20
    toks = np.zeros((T,), np.int32)
    toks[:valid] = _tokens(valid, c.vocab_size)
    table = jnp.asarray(np.pad(np.arange(1, 4, dtype=np.int32), (0, 13)))
    args = (jnp.asarray(toks), jnp.int32(valid), jnp.int32(0), table)
    ref, _, _ = llama.prefill(params, c, cache.k, cache.v, *args, all_logits=True, use_flash=False)
    out, _, _ = llama.prefill(
        params, c, cache.k, cache.v, *args, all_logits=True, use_flash=True, has_prefix=False
    )
    np.testing.assert_allclose(
        np.asarray(out)[:valid], np.asarray(ref)[:valid], rtol=5e-4, atol=5e-4
    )


def test_scheduler_flash_prefill_e2e():
    """Scheduler with prefill_impl="flash" (interpreted kernel) produces the
    same greedy tokens as the XLA path."""
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions

    prompt = list(_tokens(40, 256, seed=7))

    def run(impl):
        c = get_config("tiny").replace(prefill_impl=impl)
        params = llama.init_params(c, jax.random.PRNGKey(0), dtype=jnp.float32)
        sched = Scheduler(c, params, SchedulerConfig(num_blocks=64), dtype=jnp.float32)
        seq = sched.add_request(
            "r1", [int(t) for t in prompt], SamplingParams(temperature=0.0),
            StopConditions(max_tokens=8),
        )
        for _ in range(40):
            sched.step()
            if seq.state.value == "finished":
                break
        return seq.output_ids

    assert run("flash") == run("xla")
