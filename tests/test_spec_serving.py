"""Speculative decoding wired into the serving Scheduler (VERDICT r2 #4):
identical greedy output with and without a draft model, acceptance stats
published through ForwardPassMetrics, prefix-cache + preemption interplay.
Ref surface: SpecDecodeStats in ForwardPassMetrics (_core.pyi:354-427)."""

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions

CFG = get_config("tiny")


def make_sched(params, draft=None, gamma=4, **kw):
    defaults = dict(num_blocks=64, prefill_buckets=[16, 32, 64], decode_buckets=[1, 2, 4])
    defaults.update(kw)
    s = Scheduler(CFG, params, SchedulerConfig(**defaults), dtype=jnp.float32)
    if draft is not None:
        s.attach_draft(CFG, draft, gamma=gamma)
    return s


def drain(s, cap=500):
    produced = {}
    for _ in range(cap):
        if not s.has_work():
            break
        for seq, out in s.step():
            produced.setdefault(seq.request_id, []).append(out)
    assert not s.has_work()
    return {r: [o.token_id for o in outs if o.token_id >= 0] for r, outs in produced.items()}


def add(s, rid, prompt, n=20):
    s.add_request(rid, prompt, SamplingParams(temperature=0.0), StopConditions(max_tokens=n))


def test_self_speculation_identical_and_full_acceptance():
    """Draft == target ⇒ every proposal accepted; output identical to the
    plain scheduler and each round advances γ+1 tokens."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    base = make_sched(params)
    for i in range(2):
        add(base, f"r{i}", list(range(3 + i, 19 + i)))
    ref = drain(base)

    spec = make_sched(params, draft=params, gamma=4)
    for i in range(2):
        add(spec, f"r{i}", list(range(3 + i, 19 + i)))
    out = drain(spec)
    assert out == ref, (out, ref)

    st = spec.spec_stats
    assert st.num_rounds > 0
    assert st.acceptance_rate == 1.0, st.to_dict()
    # >1 token materialized per target forward (the whole point).
    produced = sum(len(v) for v in out.values())
    assert produced / st.num_rounds > 2.0
    # Stats flow into the published metrics.
    m = spec.metrics()
    assert m.spec_decode["num_accepted_tokens"] == st.num_accepted_tokens


def test_disagreeing_draft_still_exact():
    """A differently-initialized draft mostly disagrees — output must STILL
    equal the plain scheduler's (speculation is lossless)."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    draft = llama.init_params(CFG, jax.random.PRNGKey(42), dtype=jnp.float32)
    base = make_sched(params)
    for i in range(2):
        add(base, f"r{i}", list(range(5 + i, 21 + i)))
    ref = drain(base)

    spec = make_sched(params, draft=draft, gamma=3)
    for i in range(2):
        add(spec, f"r{i}", list(range(5 + i, 21 + i)))
    out = drain(spec)
    assert out == ref, (out, ref)
    assert spec.spec_stats.num_rounds > 0


def test_spec_with_prefix_cache_hit():
    """Second request shares the first's prompt: the target prefix-hits but
    the draft must recompute its own KV — outputs stay identical."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = list(range(7, 39))  # 2 full blocks

    base = make_sched(params)
    add(base, "a", prompt, n=12)
    ref_a = drain(base)["a"]
    add(base, "b", prompt, n=12)
    ref_b = drain(base)["b"]
    assert ref_a == ref_b

    spec = make_sched(params, draft=params, gamma=4)
    add(spec, "a", prompt, n=12)
    out_a = drain(spec)["a"]
    add(spec, "b", prompt, n=12)
    out_b = drain(spec)["b"]
    assert out_a == ref_a
    assert out_b == ref_b


def test_spec_mixed_sampling_falls_back_then_recovers():
    """A batch containing a sampling row skips spec rounds; once the sampled
    row finishes, the greedy row's accumulated draft lag is absorbed and
    speculation RESUMES (it must not latch off permanently)."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    spec = make_sched(params, draft=params)
    spec.add_request("g", list(range(3, 19)), SamplingParams(temperature=0.0),
                     StopConditions(max_tokens=40))
    spec.add_request("s", list(range(4, 20)), SamplingParams(temperature=0.8),
                     StopConditions(max_tokens=8))
    out = drain(spec)
    assert len(out["g"]) == 40 and len(out["s"]) == 8
    # The sampled row forced >gamma+1 plain rounds; speculation must still
    # have run (lag absorbed, rounds recorded) once the batch went greedy.
    assert spec.spec_stats.num_rounds > 0, spec.spec_stats.to_dict()
    # And the greedy row matches a plain scheduler end-to-end.
    base = make_sched(params)
    base.add_request("g", list(range(3, 19)), SamplingParams(temperature=0.0),
                     StopConditions(max_tokens=40))
    assert out["g"] == drain(base)["g"]


async def test_engine_e2e_with_draft_model():
    """The aggregated-worker path: TpuEngine built with draft_model (same
    seed ⇒ identical models ⇒ full acceptance) serves the same greedy tokens
    as a plain engine, with >1 accepted token per round in the stats."""
    from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
    from dynamo_tpu.runtime.engine import Context

    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)

    def build(draft):
        return TpuEngine.build(EngineArgs(
            model="tiny", dtype="float32",
            scheduler=SchedulerConfig(num_blocks=64, max_running=8,
                                      prefill_buckets=[16, 32, 64],
                                      decode_buckets=[1, 2, 4, 8]),
            draft_model="tiny" if draft else None, spec_gamma=4,
        ), params=params, draft_params=params if draft else None)

    async def collect(engine, prompt, n=12):
        out = []
        async for frame in engine.generate(
            {"token_ids": prompt, "sampling_options": {"temperature": 0.0},
             "stop_conditions": {"max_tokens": n}}, Context()):
            out.extend(frame["token_ids"])
        return out

    prompt = list(range(20, 40))
    plain = build(draft=False)
    try:
        ref = await collect(plain, prompt)
    finally:
        await plain.stop()

    spec = build(draft=True)
    try:
        out = await collect(spec, prompt)
        st = spec.scheduler.spec_stats
        assert out == ref, (out, ref)
        assert st.num_rounds > 0
        assert st.num_accepted_tokens / st.num_rounds > 1.0, st.to_dict()
    finally:
        await spec.stop()
