"""Helm chart consistency (deploy/helm/dynamo-tpu): every .Values reference
resolves against values.yaml, the bundled CRD matches crd.py's schema, and
the operator RBAC covers the reconciler's API groups. (helm itself is not
in this image; these checks catch the rot classes a template render
would.)"""

import os
import re

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHART = os.path.join(REPO, "deploy", "helm", "dynamo-tpu")


def _values():
    with open(os.path.join(CHART, "values.yaml")) as f:
        return yaml.safe_load(f)


def test_values_references_resolve():
    vals = _values()
    tmpl_dir = os.path.join(CHART, "templates")
    refs = set()
    for fn in os.listdir(tmpl_dir):
        body = open(os.path.join(tmpl_dir, fn)).read()
        refs.update(re.findall(r"\.Values\.([A-Za-z0-9_.]+)", body))
    for ref in refs:
        node = vals
        for part in ref.split("."):
            if isinstance(node, dict) and part in node:
                node = node[part]
                continue
            # range-scoped fields ($w.*) resolve under each workers entry
            if part in ("replicas", "command", "tpuChips"):
                break
            raise AssertionError(f"template references .Values.{ref} missing from values.yaml")


def test_bundled_crd_matches_code_schema():
    from dynamo_tpu.deploy.crd import crd_manifest

    with open(os.path.join(CHART, "crds", "dynamographdeployment.yaml")) as f:
        bundled = yaml.safe_load(f)
    assert bundled == crd_manifest(), "chart CRD drifted from deploy/crd.py"


def test_operator_rbac_matches_reconciler():
    from dynamo_tpu.deploy.crd import GROUP

    body = open(os.path.join(CHART, "templates", "operator.yaml")).read()
    assert GROUP in body, "operator Role must grant the CRD group"
    assert "dynamographdeployments/status" in body, "status subresource patch needed"
    assert '"deployments"' in body
