"""Preemption + ITL-budgeted chunked prefill (ref: vLLM recompute
preemption; chunked-prefill interleaving in mocker/scheduler.rs:240)."""

import jax.numpy as jnp

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, SeqState, StopConditions


def make_sched(num_blocks, **kw):
    cfg = get_config("tiny")
    params = llama.init_params(cfg, __import__("jax").random.PRNGKey(3), dtype=jnp.float32)
    sc = SchedulerConfig(
        num_blocks=num_blocks,
        prefill_buckets=[16, 32, 64],
        decode_buckets=[1, 2, 4],
        enable_prefix_caching=False,
        **kw,
    )
    return Scheduler(cfg, params, sc, dtype=jnp.float32)


def drain(sched, max_iters=500):
    produced = {}
    for _ in range(max_iters):
        if not sched.has_work():
            break
        for seq, out in sched.step():
            produced.setdefault(seq.request_id, []).append(out)
    assert not sched.has_work(), "scheduler did not drain"
    return produced


def tokens_of(outs):
    return [o.token_id for o in outs if o.token_id >= 0]


def test_preemption_frees_blocks_and_resumes_exactly():
    """Two greedy sequences in a pool too small for both to finish: one gets
    preempted mid-decode, resumes via recompute, and produces exactly the
    same tokens as an unconstrained run."""
    # Reference run: plenty of blocks, no preemption possible.
    ref = make_sched(num_blocks=64)
    for i in range(2):
        ref.add_request(f"r{i}", list(range(1 + i, 33 + i)), SamplingParams(temperature=0.0),
                        StopConditions(max_tokens=24))
    ref_out = {rid: tokens_of(outs) for rid, outs in drain(ref).items()}

    # Tight pool: 2 prompts of 32 tokens (2 blocks each) + 24 new tokens
    # each (needs 2 more blocks each) against 7 usable blocks forces a
    # mid-decode OutOfBlocks.
    tight = make_sched(num_blocks=8)  # block 0 reserved → 7 usable
    for i in range(2):
        tight.add_request(f"r{i}", list(range(1 + i, 33 + i)), SamplingParams(temperature=0.0),
                          StopConditions(max_tokens=24))
    out = {rid: tokens_of(outs) for rid, outs in drain(tight).items()}

    assert tight.preempt_total >= 1, "expected at least one preemption"
    for rid in ref_out:
        assert out[rid] == ref_out[rid], f"{rid}: preempted run diverged"
    # All blocks back in the pool at the end.
    assert tight.allocator.num_active == 0


def test_preemption_disabled_finishes_with_length():
    sched = make_sched(num_blocks=8, enable_preemption=False)
    for i in range(2):
        sched.add_request(f"r{i}", list(range(1 + i, 33 + i)), SamplingParams(temperature=0.0),
                          StopConditions(max_tokens=24))
    produced = drain(sched)
    reasons = {rid: outs[-1].finish_reason for rid, outs in produced.items()}
    assert "length" in reasons.values()
    assert sched.preempt_total == 0


def test_chunk_budget_caps_prefill_chunks():
    sched = make_sched(num_blocks=64, itl_budget_ms=10.0, max_prefill_chunk=64)
    # No decodes running → full chunk regardless of budget.
    assert sched._chunk_budget() == 64
    # Fake a running decode + a learned rate of 1600 tok/s ⇒ 10ms ≈ 16 tokens.
    sched.running.append(object())
    sched._prefill_tok_s = 1600.0
    assert sched._chunk_budget() == 16
    # Budget never drops below the smallest bucket.
    sched._prefill_tok_s = 10.0
    assert sched._chunk_budget() == sched.sc.prefill_buckets[0]
    sched.running.clear()


def test_itl_budget_bounds_stall_with_running_decode():
    """With an ITL budget, a long prompt admitted next to a running sequence
    prefills in small chunks (multiple scheduler iterations), and the
    running sequence keeps producing tokens between chunks."""
    # Single-step decode: the test's subject is chunked-prefill interleaving
    # BETWEEN steps; a 32-step window would finish the short request in one
    # dispatch before the long prompt arrives.
    sched = make_sched(num_blocks=64, itl_budget_ms=0.001, max_prefill_chunk=64,
                       num_scheduler_steps=1)
    sched.add_request("short", list(range(1, 17)), SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=30))
    # Let the short one enter decode and learn a prefill rate.
    for _ in range(4):
        sched.step()
    assert any(s.request_id == "short" for s in sched.running)
    sched.add_request("long", list(range(1, 65)), SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=4))
    interleaved_tokens = 0
    iters = 0
    while any(s.request_id == "long" and s.state != SeqState.RUNNING for s in sched.waiting + sched.running):
        outs = sched.step()
        interleaved_tokens += sum(1 for s, o in outs if s.request_id == "short" and o.token_id >= 0)
        iters += 1
        if iters > 50:
            break
    # The 64-token prompt must NOT have landed in one chunk (budget caps at
    # the 16-token bucket), and the short sequence decoded meanwhile.
    assert iters >= 2, "long prompt prefilled in one iteration despite tiny ITL budget"
    assert interleaved_tokens >= 1
    drain(sched)
