"""Stream perf capture + JSONL recorder (ref: perf.rs, recorder.rs)."""

import asyncio
import json

from dynamo_tpu.llm.kv_router.publisher import KvEventPublisher
from dynamo_tpu.engine.kv_cache import KvEvent
from dynamo_tpu.llm.perf import (
    KvRecorder,
    RecordedStream,
    Recorder,
    analyze_logprobs,
    record_stream,
)
from dynamo_tpu.runtime.distributed import DistributedRuntime


async def test_record_stream_passthrough_and_stats():
    async def gen():
        for i in range(5):
            await asyncio.sleep(0.01)
            yield {"token_ids": [i]}

    rec = RecordedStream()
    items = [item async for item in record_stream(gen(), rec)]
    assert [i["token_ids"][0] for i in items] == list(range(5))  # unchanged
    assert len(rec.responses) == 5
    assert rec.ttft_s > 0
    assert len(rec.itls_s) == 4 and all(d > 0 for d in rec.itls_s)
    s = rec.summarize()
    assert s["responses"] == 5 and s["itl_p50_s"] > 0 and s["duration_s"] >= s["ttft_s"]


async def test_recorder_writes_jsonl(tmp_path):
    path = str(tmp_path / "events.jsonl")
    rec = Recorder(path)
    rec.start()
    for i in range(10):
        rec.emit("step", i=i)
    await rec.close()
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 10 and rec.events_written == 10
    assert lines[3]["event"] == "step" and lines[3]["i"] == 3
    assert all("ts" in l for l in lines)


async def test_kv_recorder_taps_event_stream(tmp_path):
    drt = await DistributedRuntime.detached()
    try:
        path = str(tmp_path / "kv.jsonl")
        rec = Recorder(path)
        rec.start()
        tap = KvRecorder(drt, "ns", "backend", rec)
        await tap.start()

        pub = KvEventPublisher(drt, "ns", "backend", worker_id=7)
        pub.start()
        pub.publish(KvEvent(kind="stored", block_hashes=[1, 2, 3], parent_hash=None))
        pub.publish(KvEvent(kind="removed", block_hashes=[2]))

        for _ in range(100):
            if rec.events_written >= 2:
                break
            await asyncio.sleep(0.02)
        await pub.stop()
        await tap.stop()
        await rec.close()

        lines = [json.loads(l) for l in open(path)]
        assert len(lines) >= 2
        assert lines[0]["event"] == "kv_event" and lines[0]["worker_id"] == 7
        kinds = [l.get("kind") or l.get("type") for l in lines]
        assert "stored" in str(kinds)
    finally:
        await drt.shutdown()


def test_analyze_logprobs():
    out = analyze_logprobs([-0.1, -0.2, -0.3])
    assert out["tokens"] == 3
    assert abs(out["mean_logprob"] + 0.2) < 1e-9
    assert out["perplexity"] > 1.0
    assert analyze_logprobs([])["perplexity"] is None
