"""KServe v2 gRPC frontend e2e (ref: grpc/service/kserve.rs + tests/serve).

Drives the real grpc.aio server over localhost with generated protobuf
messages: health/metadata, unary ModelInfer, and ModelStreamInfer chunks.
"""

import grpc
import pytest

from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.discovery import ModelManager
from dynamo_tpu.llm.entrypoint import build_local_pipeline
from dynamo_tpu.llm.grpc import KserveGrpcService
from dynamo_tpu.llm.grpc import kserve_pb2 as pb
from dynamo_tpu.llm.tokenizer import ByteTokenizer

MODEL = "tiny-grpc"
SVC = "/inference.GRPCInferenceService/"


def tiny_engine() -> TpuEngine:
    return TpuEngine.build(
        EngineArgs(
            model="tiny",
            dtype="float32",
            eos_token_ids=[0],
            scheduler=SchedulerConfig(num_blocks=64, prefill_buckets=[16, 32, 64, 128], decode_buckets=[1, 2, 4, 8]),
        )
    )


async def start_service():
    engine = tiny_engine()
    manager = ModelManager()
    manager.add_model("completions", MODEL, build_local_pipeline(ByteTokenizer(), engine))
    svc = KserveGrpcService(manager, host="127.0.0.1", port=0)
    await svc.start()
    return svc, engine


def infer_request(prompt: str, max_tokens: int = 8, streaming: bool = False) -> pb.ModelInferRequest:
    req = pb.ModelInferRequest(model_name=MODEL, id="req-1")
    t = req.inputs.add()
    t.name, t.datatype = "text_input", "BYTES"
    t.shape.extend([1])
    t.contents.bytes_contents.append(prompt.encode())
    if streaming:
        s = req.inputs.add()
        s.name, s.datatype = "streaming", "BOOL"
        s.shape.extend([1])
        s.contents.bool_contents.append(True)
    req.parameters["max_tokens"].int64_param = max_tokens
    req.parameters["temperature"].double_param = 0.0
    return req


async def test_health_and_metadata():
    svc, engine = await start_service()
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{svc.port}") as ch:
            live = await ch.unary_unary(
                SVC + "ServerLive",
                request_serializer=pb.ServerLiveRequest.SerializeToString,
                response_deserializer=pb.ServerLiveResponse.FromString,
            )(pb.ServerLiveRequest())
            assert live.live
            ready = await ch.unary_unary(
                SVC + "ModelReady",
                request_serializer=pb.ModelReadyRequest.SerializeToString,
                response_deserializer=pb.ModelReadyResponse.FromString,
            )(pb.ModelReadyRequest(name=MODEL))
            assert ready.ready
            meta = await ch.unary_unary(
                SVC + "ModelMetadata",
                request_serializer=pb.ModelMetadataRequest.SerializeToString,
                response_deserializer=pb.ModelMetadataResponse.FromString,
            )(pb.ModelMetadataRequest(name=MODEL))
            assert [t.name for t in meta.inputs] == ["text_input", "streaming"]
            assert meta.outputs[0].name == "text_output"
            missing = await ch.unary_unary(
                SVC + "ModelReady",
                request_serializer=pb.ModelReadyRequest.SerializeToString,
                response_deserializer=pb.ModelReadyResponse.FromString,
            )(pb.ModelReadyRequest(name="nope"))
            assert not missing.ready
    finally:
        await svc.stop()
        await engine.stop()


async def test_model_infer_unary():
    svc, engine = await start_service()
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{svc.port}") as ch:
            infer = ch.unary_unary(
                SVC + "ModelInfer",
                request_serializer=pb.ModelInferRequest.SerializeToString,
                response_deserializer=pb.ModelInferResponse.FromString,
            )
            resp = await infer(infer_request("hello tpu"))
            assert resp.model_name == MODEL and resp.id == "req-1"
            assert resp.outputs[0].name == "text_output"
            text = resp.outputs[0].contents.bytes_contents[0].decode()
            assert isinstance(text, str)  # byte tokenizer output, any content
            assert resp.parameters["finish_reason"].string_param in ("length", "stop")

            with pytest.raises(grpc.aio.AioRpcError) as e:
                await infer(infer_request("x", streaming=True))
            assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT

            bad = infer_request("x")
            bad.model_name = "nope"
            with pytest.raises(grpc.aio.AioRpcError) as e:
                await infer(bad)
            assert e.value.code() == grpc.StatusCode.NOT_FOUND
    finally:
        await svc.stop()
        await engine.stop()


async def test_model_stream_infer():
    svc, engine = await start_service()
    try:
        async with grpc.aio.insecure_channel(f"127.0.0.1:{svc.port}") as ch:
            stream = ch.stream_stream(
                SVC + "ModelStreamInfer",
                request_serializer=pb.ModelInferRequest.SerializeToString,
                response_deserializer=pb.ModelStreamInferResponse.FromString,
            )
            call = stream()
            await call.write(infer_request("stream me", max_tokens=6, streaming=True))
            await call.done_writing()
            chunks = []
            finish = None
            async for resp in call:
                assert not resp.error_message
                out = resp.infer_response.outputs[0]
                chunks.append(out.contents.bytes_contents[0].decode())
                fr = resp.infer_response.parameters["finish_reason"].string_param
                finish = fr or finish
            assert len(chunks) >= 1
            assert finish in ("length", "stop")
    finally:
        await svc.stop()
        await engine.stop()
