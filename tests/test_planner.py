"""Planner tests: replica math against profiles (ref:
tests/planner/test_replica_calculation.py), predictors, and a real scaling
e2e with the LocalConnector spawning mocker workers (ref:
test_scaling_e2e.py with VirtualConnector simulation)."""

import asyncio
import math

import numpy as np
import pytest

from dynamo_tpu.planner import (
    ARIMAPredictor,
    ConstantPredictor,
    DecodeInterpolator,
    LocalConnector,
    Planner,
    PlannerConfig,
    PrefillInterpolator,
    SeasonalNaivePredictor,
    SlaTargets,
    VirtualConnector,
)
from dynamo_tpu.planner.observer import parse_prometheus
from dynamo_tpu.planner.planner_core import ObservedLoad


def make_interps():
    # Synthetic but realistic profile: TTFT grows ~quadratically with ISL;
    # ITL grows with active KV; throughput degrades as ITL grows.
    prefill = PrefillInterpolator(
        isl=[128, 512, 1024, 4096],
        ttft_ms=[20, 60, 130, 700],
        thpt_per_chip=[8000, 10000, 11000, 9000],
    )
    decode = DecodeInterpolator(
        active_kv=[8, 32, 128, 512],
        context_len=[1024, 1024, 1024, 1024],
        itl_ms=[5, 8, 15, 40],
        thpt_per_chip=[50, 180, 600, 1200],
    )
    return prefill, decode


def test_replica_math_scales_with_rate():
    prefill, decode = make_interps()
    cfg = PlannerConfig(max_chip_budget=64, sla=SlaTargets(itl_ms=16.0))
    planner = Planner(cfg, VirtualConnector(), prefill, decode, observe_fn=None)

    low = planner.compute_replicas(ObservedLoad(request_rate=1.0, avg_isl=1024, avg_osl=128))
    high = planner.compute_replicas(ObservedLoad(request_rate=20.0, avg_isl=1024, avg_osl=128))
    assert high.prefill >= low.prefill
    assert high.decode >= low.decode
    assert high.prefill > 1  # 20 req/s * 1024 isl needs real prefill capacity

    # ITL SLA inversion: decode throughput cap excludes points violating SLA.
    thpt = decode.find_best_throughput_per_chip(16.0, 1024)
    assert thpt == 600  # the 40ms point (1200 thpt) violates the 16ms SLA


def test_budget_clamp():
    prefill, decode = make_interps()
    cfg = PlannerConfig(max_chip_budget=4)
    planner = Planner(cfg, VirtualConnector(), prefill, decode, observe_fn=None)
    plan = planner.compute_replicas(ObservedLoad(request_rate=1000.0, avg_isl=4096, avg_osl=512))
    assert plan.prefill + plan.decode <= 4 + 1  # floor() rounding tolerance


def test_predictors():
    c = ConstantPredictor()
    for v in [1, 2, 3]:
        c.observe(v)
    assert c.predict() == 3

    a = ARIMAPredictor(order=2)
    for v in range(20):  # linear ramp
        a.observe(float(v))
    assert 19.5 <= a.predict() <= 21.5  # extrapolates the trend

    s = SeasonalNaivePredictor(period=4)
    for v in [1, 2, 3, 4] * 3:
        s.observe(float(v))
    assert s.predict() == 1.0  # one period back


def test_parse_prometheus():
    text = """# HELP x
dynamo_frontend_requests_total{model="m",status="200"} 5
dynamo_frontend_requests_total{model="m",status="400"} 2
dynamo_frontend_output_tokens_total{model="m"} 130
"""
    out = parse_prometheus(text)
    assert out["dynamo_frontend_requests_total"] == 7
    assert out["dynamo_frontend_output_tokens_total"] == 130


async def test_planner_scaling_e2e_with_local_connector():
    """The planner drives a LocalConnector that spawns/retires real mocker
    workers registered in a live DistributedRuntime."""
    from dynamo_tpu.llm.mocker import MockEngineArgs, MockTpuEngine
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.detached()
    try:
        ep = drt.namespace("plan").component("decode").endpoint("generate")
        prefill_ep = drt.namespace("plan").component("prefill").endpoint("generate")

        async def factory(component):
            engine = MockTpuEngine(MockEngineArgs(speedup_ratio=100.0))
            target = ep if component == "decode" else prefill_ep
            handle = await target.serve_endpoint(engine.generate, stats_handler=engine.stats_handler)
            return handle

        connector = LocalConnector(factory)
        prefill, decode = make_interps()
        cfg = PlannerConfig(max_chip_budget=8, min_prefill_replicas=1, min_decode_replicas=1)

        loads = iter(
            [
                ObservedLoad(request_rate=0.5, avg_isl=512, avg_osl=64),
                ObservedLoad(request_rate=30.0, avg_isl=1024, avg_osl=256),  # burst
                ObservedLoad(request_rate=0.2, avg_isl=256, avg_osl=32),  # cooldown
            ]
        )

        async def observe():
            return next(loads)

        planner = Planner(cfg, connector, prefill, decode, observe)
        planner.rate_predictor = ConstantPredictor()  # deterministic for test

        p1 = await planner.step()
        client = await ep.client()
        await client.wait_for_instances(p1.decode, timeout=5)
        n1 = len(client.instances)

        p2 = await planner.step()  # burst → scale up
        assert p2.decode > p1.decode
        await client.wait_for_instances(p2.decode, timeout=5)

        p3 = await planner.step()  # cooldown → scale down
        assert p3.decode < p2.decode
        for _ in range(100):
            if len(client.instances) == p3.decode:
                break
            await asyncio.sleep(0.05)
        assert len(client.instances) == p3.decode

        await connector.shutdown()
    finally:
        await drt.shutdown()


def test_planner_e2e_against_profiled_surfaces(tmp_path):
    """The full SLA loop against MEASURED (not hardcoded) surfaces: run the
    profiler on the tiny model, persist npz, load interpolators from disk,
    and drive replica math with a bursty load generator. Ref:
    benchmarks/profiler/profile_sla.py + pre_deployment_profiling.md:60-84."""
    import numpy as np

    from dynamo_tpu.planner.profiler import profile_decode, profile_prefill

    pre = profile_prefill("tiny", isls=[32, 64, 128])
    dec = profile_decode("tiny", batches=[1, 2, 4], ctxs=[64, 128])
    np.savez(tmp_path / "prefill.npz", **{k: np.asarray(v) for k, v in pre.items()})
    np.savez(tmp_path / "decode.npz", **{k: np.asarray(v) for k, v in dec.items()})

    prefill = PrefillInterpolator.from_npz(str(tmp_path / "prefill.npz"))
    decode = DecodeInterpolator.from_npz(str(tmp_path / "decode.npz"))

    # The decode surface is a real 2D grid.
    assert len(set(dec["context_len"])) == 2
    assert len(dec["itl_ms"]) == 6

    # Monotonicity sanity on the measured fits inside the profiled range.
    assert prefill.ttft_ms(128) >= prefill.ttft_ms(32) * 0.5
    itl_small = decode.itl_ms(dec["active_kv"][0], 64)
    itl_big = decode.itl_ms(dec["active_kv"][-1], 128)
    assert itl_big > 0 and itl_small > 0

    cfg = PlannerConfig(
        max_chip_budget=64,
        sla=SlaTargets(itl_ms=max(itl_big * 1.5, 1.0), ttft_ms=prefill.ttft_ms(128) * 4),
    )
    planner = Planner(cfg, VirtualConnector(), prefill, decode, observe_fn=None)

    # Load generator: a bursty day — ramp, spike, decay. Replica plans must
    # track the rate monotonically and stay within budget.
    rates = [0.5, 2.0, 8.0, 20.0, 6.0, 1.0]
    plans = [
        planner.compute_replicas(ObservedLoad(request_rate=r, avg_isl=96, avg_osl=32))
        for r in rates
    ]
    totals = [p.prefill + p.decode for p in plans]
    assert totals[3] == max(totals), "spike must size the largest fleet"
    assert all(1 <= t <= 64 for t in totals)
    assert totals[0] <= totals[2] <= totals[3]
