"""End-to-end serving tests: OpenAI HTTP frontend → preprocessor → router →
TpuEngine (tiny model, byte tokenizer) → backend → SSE. Mirrors the
reference's serve e2e tests (tests/serve/test_vllm.py) without GPUs."""

import asyncio
import json

import aiohttp
import pytest

from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.discovery import ModelManager
from dynamo_tpu.llm.entrypoint import (
    FrontendConfig,
    build_local_pipeline,
    register_llm,
    start_frontend,
)
from dynamo_tpu.llm.http.service import HttpService
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.tokenizer import ByteTokenizer
from dynamo_tpu.runtime.distributed import DistributedRuntime

MODEL = "tiny-chat"


def tiny_engine() -> TpuEngine:
    return TpuEngine.build(
        EngineArgs(
            model="tiny",
            dtype="float32",
            eos_token_ids=[0],
            scheduler=SchedulerConfig(num_blocks=64, prefill_buckets=[16, 32, 64, 128], decode_buckets=[1, 2, 4, 8]),
        )
    )


async def make_local_service():
    engine = tiny_engine()
    manager = ModelManager()
    pipeline = build_local_pipeline(ByteTokenizer(), engine)
    manager.add_model("chat", MODEL, pipeline)
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    return service, engine


async def test_models_and_health():
    service, engine = await make_local_service()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{service.port}/v1/models") as r:
                assert r.status == 200
                data = await r.json()
                assert data["data"][0]["id"] == MODEL
            async with s.get(f"http://127.0.0.1:{service.port}/health") as r:
                assert (await r.json())["models"] == [MODEL]
    finally:
        await service.stop()
        await engine.stop()


async def test_chat_completion_unary():
    service, engine = await make_local_service()
    try:
        async with aiohttp.ClientSession() as s:
            body = {
                "model": MODEL,
                "messages": [{"role": "user", "content": "hello"}],
                "max_tokens": 8,
                "temperature": 0,
            }
            async with s.post(f"http://127.0.0.1:{service.port}/v1/chat/completions", json=body) as r:
                assert r.status == 200, await r.text()
                data = await r.json()
                assert data["object"] == "chat.completion"
                assert data["choices"][0]["finish_reason"] in ("length", "stop")
                assert isinstance(data["choices"][0]["message"]["content"], str)
                assert data["usage"]["completion_tokens"] > 0
    finally:
        await service.stop()
        await engine.stop()


async def test_chat_completion_streaming_sse():
    service, engine = await make_local_service()
    try:
        async with aiohttp.ClientSession() as s:
            body = {
                "model": MODEL,
                "messages": [{"role": "user", "content": "count"}],
                "max_tokens": 6,
                "temperature": 0,
                "stream": True,
            }
            chunks = []
            async with s.post(f"http://127.0.0.1:{service.port}/v1/chat/completions", json=body) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/event-stream")
                async for line in r.content:
                    line = line.decode().strip()
                    if line.startswith("data: "):
                        payload = line[6:]
                        if payload == "[DONE]":
                            chunks.append("DONE")
                        else:
                            chunks.append(json.loads(payload))
            assert chunks[-1] == "DONE"
            finish = [c for c in chunks[:-1] if c["choices"][0].get("finish_reason")]
            assert finish and finish[-1]["choices"][0]["finish_reason"] == "length"
    finally:
        await service.stop()
        await engine.stop()


async def test_completions_endpoint():
    service, engine = await make_local_service()
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": MODEL, "prompt": "abc", "max_tokens": 4, "temperature": 0}
            async with s.post(f"http://127.0.0.1:{service.port}/v1/completions", json=body) as r:
                assert r.status == 200
                data = await r.json()
                assert data["object"] == "text_completion"
    finally:
        await service.stop()
        await engine.stop()


async def test_errors():
    service, engine = await make_local_service()
    try:
        async with aiohttp.ClientSession() as s:
            url = f"http://127.0.0.1:{service.port}/v1/chat/completions"
            async with s.post(url, json={"model": "nope", "messages": [{"role": "user", "content": "x"}]}) as r:
                assert r.status == 404
            async with s.post(url, json={"model": MODEL, "messages": []}) as r:
                assert r.status == 400
            async with s.post(url, json={"model": MODEL, "messages": [{"role": "user", "content": "x"}], "temperature": 9}) as r:
                assert r.status == 400
            async with s.post(url, data=b"not json") as r:
                assert r.status == 400
    finally:
        await service.stop()
        await engine.stop()


@pytest.mark.e2e
async def test_distributed_discovery_and_serving():
    """Worker registers model in the store; frontend ModelWatcher builds a
    routed pipeline; request flows over the wire path end-to-end."""
    drt = await DistributedRuntime.detached()
    engine = tiny_engine()
    try:
        ep = drt.namespace("dyn").component("backend").endpoint("generate")
        card = ModelDeploymentCard(name=MODEL, model_type="chat", context_length=256, kv_cache_block_size=16)
        handle, _ = await register_llm(drt, ep, engine, card, stats_handler=engine.stats_handler)
        # Force the wire path (no in-proc shortcut).
        drt.local_engines.pop(handle.instance.instance_id)

        service = await start_frontend(drt, FrontendConfig(host="127.0.0.1", port=0))
        try:
            async with aiohttp.ClientSession() as s:
                # Model discovered?
                async with s.get(f"http://127.0.0.1:{service.port}/v1/models") as r:
                    assert [m["id"] for m in (await r.json())["data"]] == [MODEL]
                body = {
                    "model": MODEL,
                    "messages": [{"role": "user", "content": "distributed"}],
                    "max_tokens": 5,
                    "temperature": 0,
                }
                async with s.post(f"http://127.0.0.1:{service.port}/v1/chat/completions", json=body) as r:
                    assert r.status == 200, await r.text()
                    data = await r.json()
                    assert data["usage"]["completion_tokens"] == 5
        finally:
            await service.watcher.stop()
            await service.stop()
    finally:
        await engine.stop()
        await drt.shutdown()


async def test_tls_frontend(tmp_path):
    """HTTPS serving with --tls-cert/--tls-key (ref: frontend --tls-*-path
    flags, components/frontend main.py:81-286). Self-signed cert; the client
    pins it."""
    import ssl
    import subprocess
    import sys

    cert = tmp_path / "cert.pem"
    key = tmp_path / "key.pem"
    gen = subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=localhost"],
        capture_output=True,
    )
    if gen.returncode != 0:
        pytest.skip(f"openssl unavailable: {gen.stderr[-120:]}")

    engine = tiny_engine()
    manager = ModelManager()
    manager.add_model("chat", MODEL, build_local_pipeline(ByteTokenizer(), engine))
    service = HttpService(manager, host="127.0.0.1", port=0,
                          tls_cert=str(cert), tls_key=str(key))
    await service.start()
    try:
        client_ssl = ssl.create_default_context(cafile=str(cert))
        client_ssl.check_hostname = False
        async with aiohttp.ClientSession() as s:
            async with s.get(f"https://127.0.0.1:{service.port}/health", ssl=client_ssl) as r:
                assert r.status == 200
            async with s.post(
                f"https://127.0.0.1:{service.port}/v1/chat/completions",
                json={"model": MODEL, "messages": [{"role": "user", "content": "hi"}],
                      "max_tokens": 3},
                ssl=client_ssl,
            ) as r:
                assert r.status == 200
                assert (await r.json())["choices"][0]["message"]["content"]
    finally:
        await service.stop()
        await engine.stop()


def test_tls_requires_both_paths():
    manager = ModelManager()
    with pytest.raises(ValueError, match="both"):
        HttpService(manager, tls_cert="/tmp/x.pem")


async def test_queue_time_metric_exported():
    """The frontend histograms engine-admission queue time per request
    (ref: http_queue_guard, http/service/metrics.rs) — the SLA planner's
    saturation signal."""
    service, engine = await make_local_service()
    try:
        async with aiohttp.ClientSession() as s:
            body = {
                "model": MODEL,
                "messages": [{"role": "user", "content": "queue metric probe"}],
                "max_tokens": 4,
            }
            async with s.post(f"http://127.0.0.1:{service.port}/v1/chat/completions", json=body) as r:
                assert r.status == 200
                await r.json()
            async with s.get(f"http://127.0.0.1:{service.port}/metrics") as r:
                text = await r.text()
        assert "queue_time_seconds" in text
        for line in text.splitlines():
            if line.startswith("dynamo_frontend_queue_time_seconds_count"):
                assert float(line.split()[-1]) >= 1
                break
        else:
            raise AssertionError("queue_time_seconds histogram count not found")
    finally:
        await service.stop()
        await engine.stop()


async def test_cached_tokens_in_usage_details():
    """Engine-reported prefix-cache reuse surfaces as OpenAI
    usage.prompt_tokens_details.cached_tokens (and the frontend's
    input_cached_tokens counter): second identical prompt hits."""
    service, engine = await make_local_service()
    try:
        async with aiohttp.ClientSession() as s:
            body = {
                "model": MODEL,
                # Long enough to span several 16-token KV blocks.
                "messages": [{"role": "user", "content": "cached tokens probe " * 8}],
                "max_tokens": 4,
                "temperature": 0,
            }
            url = f"http://127.0.0.1:{service.port}/v1/chat/completions"
            async with s.post(url, json=body) as r:
                cold = await r.json()
            async with s.post(url, json=body) as r:
                warm = await r.json()
            assert cold["usage"]["prompt_tokens_details"]["cached_tokens"] == 0
            warm_cached = warm["usage"]["prompt_tokens_details"]["cached_tokens"]
            assert warm_cached > 0
            # Identical prompts → full cover: everything but the one
            # recomputed logits token is served from cache.
            assert warm_cached >= warm["usage"]["prompt_tokens"] - 16
            async with s.get(f"http://127.0.0.1:{service.port}/metrics") as r:
                text = await r.text()
        for line in text.splitlines():
            if line.startswith("dynamo_frontend_input_cached_tokens_total"):
                assert float(line.split()[-1]) == warm_cached
                break
        else:
            raise AssertionError("input_cached_tokens_total not exported")
    finally:
        await service.stop()
        await engine.stop()
