"""Frequency/presence penalties: batched logit op semantics + end-to-end
through the scheduler and the engine wire (VERDICT r3 #4; ref:
protocols/common SamplingOptions, protocols/openai/validate.rs)."""

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.sampling import SamplingParams, apply_penalties
from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions


def test_apply_penalties_semantics():
    logits = jnp.zeros((2, 8), jnp.float32)
    # Row 0: token 3 twice, token 5 once. Row 1: no history.
    hist = jnp.asarray([[3, 3, 5, 0], [0, 0, 0, 0]], jnp.int32)
    hist_len = jnp.asarray([3, 0], jnp.int32)
    freq = jnp.asarray([0.5, 0.5], jnp.float32)
    pres = jnp.asarray([1.0, 1.0], jnp.float32)
    out = np.asarray(apply_penalties(logits, hist, hist_len, freq, pres))
    np.testing.assert_allclose(out[0, 3], -0.5 * 2 - 1.0)
    np.testing.assert_allclose(out[0, 5], -0.5 * 1 - 1.0)
    np.testing.assert_allclose(out[0, 0], 0.0)  # padding adds nothing to token 0
    np.testing.assert_allclose(out[1], np.zeros(8))  # empty history: untouched


def test_greedy_presence_penalty_no_repeats():
    """A huge presence penalty makes greedy decoding emit all-distinct
    tokens; the unpenalized run (tiny random model) repeats."""
    c = get_config("tiny")
    params = llama.init_params(c, jax.random.PRNGKey(0), dtype=jnp.float32)

    def run(pres):
        sched = Scheduler(c, params, SchedulerConfig(num_blocks=64), dtype=jnp.float32)
        seq = sched.add_request(
            "r", [1, 2, 3, 4], SamplingParams(temperature=0.0, presence_penalty=pres),
            StopConditions(max_tokens=12, ignore_eos=True),
        )
        for _ in range(40):
            sched.step()
            if seq.state.value == "finished":
                break
        return seq.output_ids

    penalized = run(1e6)
    assert len(penalized) == len(set(penalized)), penalized
    # Sanity: the penalty actually changed the distribution vs baseline.
    assert penalized != run(0.0)


async def test_engine_wire_accepts_penalties():
    """sampling_options.{frequency,presence}_penalty reach SamplingParams."""
    from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
    from dynamo_tpu.runtime.engine import Context

    eng = TpuEngine.build(EngineArgs(model="tiny", dtype="float32"))
    req = {
        "token_ids": [1, 2, 3],
        "sampling_options": {"temperature": 0.0, "frequency_penalty": 0.7, "presence_penalty": 0.2},
        "stop_conditions": {"max_tokens": 4, "ignore_eos": True},
    }
    captured = {}
    orig_add = eng.scheduler.add_request

    def spy(rid, tokens, sampling, stop, **kw):
        captured["sampling"] = sampling
        return orig_add(rid, tokens, sampling, stop, **kw)

    eng.scheduler.add_request = spy
    toks = []
    async for frame in eng.generate(req, Context(id="p1")):
        toks.extend(frame["token_ids"])
    assert len(toks) == 4
    assert captured["sampling"].frequency_penalty == 0.7
    assert captured["sampling"].presence_penalty == 0.2
    await eng.stop()
