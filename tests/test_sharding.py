"""Tensor-parallel sharding tests on the virtual 8-device CPU mesh: sharded
prefill/decode must match single-device results (GSPMD inserts the
collectives; correctness is what we assert here)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.sharding import (
    ParallelConfig,
    build_mesh,
    kv_cache_spec,
    param_specs,
    shard_params,
)

CFG = get_config("tiny").replace(dtype="float32")


def test_mesh_axes():
    mesh = build_mesh(ParallelConfig(tp=4, dp=2))
    assert mesh.shape == {"dp": 2, "pp": 1, "sp": 1, "ep": 1, "tp": 4}


def test_param_specs_cover_params():
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    specs = param_specs(CFG.tie_word_embeddings)
    # Same tree structure — zip must not error.
    jax.tree.map(lambda a, b: None, params, specs, is_leaf=lambda x: isinstance(x, (jax.Array, P)))


def test_tp_prefill_decode_matches_single_device():
    mesh = build_mesh(ParallelConfig(tp=2))
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)

    tokens = list(range(40, 60))
    T = len(tokens)
    block_table = jnp.array([1, 2, 3, 0], dtype=jnp.int32)
    padded = jnp.array(tokens + [0] * (32 - T), dtype=jnp.int32)

    # Single-device reference.
    cache = KvCacheArrays.create(CFG, 16, dtype=jnp.float32)
    ref_logits, ref_k, ref_v = llama.prefill(
        params, CFG, cache.k, cache.v, padded, jnp.int32(T), jnp.int32(0), block_table
    )

    # Sharded run: params TP-sharded, cache sharded over kv heads.
    sp = shard_params(params, mesh, CFG.tie_word_embeddings)
    cache_sharding = NamedSharding(mesh, kv_cache_spec(CFG.num_kv_heads, 2))
    k_sh = jax.device_put(jnp.zeros_like(cache.k), cache_sharding)
    v_sh = jax.device_put(jnp.zeros_like(cache.v), cache_sharding)

    logits, k_sh, v_sh = jax.jit(
        lambda p, k, v, t: llama.prefill(p, CFG, k, v, t, jnp.int32(T), jnp.int32(0), block_table)
    )(sp, k_sh, v_sh, padded)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits), rtol=1e-4, atol=1e-4)

    # Decode one step sharded vs reference.
    next_tok = int(jnp.argmax(ref_logits))
    B = 2
    toks = jnp.zeros((B,), dtype=jnp.int32).at[0].set(next_tok)
    positions = jnp.zeros((B,), dtype=jnp.int32).at[0].set(T)
    tables = jnp.zeros((B, 4), dtype=jnp.int32).at[0].set(block_table)
    active = jnp.zeros((B,), dtype=bool).at[0].set(True)

    ref_dec, _, _ = llama.decode(params, CFG, ref_k, ref_v, toks, positions, tables, active)
    dec, _, _ = jax.jit(lambda p, k, v: llama.decode(p, CFG, k, v, toks, positions, tables, active))(
        sp, k_sh, v_sh
    )
    np.testing.assert_allclose(np.asarray(dec[0]), np.asarray(ref_dec[0]), rtol=1e-4, atol=1e-4)


def test_tp4_with_dp2_mesh_compiles():
    """Full 8-device mesh (dp=2, tp=4): sharded decode step compiles and runs."""
    cfg = CFG.replace(num_heads=8, num_kv_heads=4, head_dim=8)
    mesh = build_mesh(ParallelConfig(tp=4, dp=2))
    params = llama.init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    sp = shard_params(params, mesh, cfg.tie_word_embeddings)
    cache_sharding = NamedSharding(mesh, kv_cache_spec(cfg.num_kv_heads, 4))
    cache = KvCacheArrays.create(cfg, 16, dtype=jnp.float32, sharding=cache_sharding)

    B = 4
    toks = jnp.arange(B, dtype=jnp.int32)
    positions = jnp.zeros((B,), dtype=jnp.int32)
    tables = jnp.ones((B, 4), dtype=jnp.int32)
    active = jnp.ones((B,), dtype=bool)
    logits, _, _ = jax.jit(lambda p, k, v: llama.decode(p, cfg, k, v, toks, positions, tables, active))(
        sp, cache.k, cache.v
    )
    assert logits.shape == (B, cfg.vocab_size)
