"""Sharded indexer + inter-router prefill counters (ref: indexer.rs:970
KvIndexerSharded, prefill_counter.rs PrefillCountersMultiWorker)."""

import asyncio
import json

import pytest

from dynamo_tpu.llm.kv_router import KvIndexer, KvIndexerSharded, KvScheduler
from dynamo_tpu.llm.kv_router.prefill_counter import (
    PrefillCountersMultiWorker,
    prefill_events_subject,
)
from dynamo_tpu.llm.kv_router.sequence import ActiveSequencesMultiWorker
from dynamo_tpu.llm.tokens import compute_block_hashes
from dynamo_tpu.runtime.distributed import DistributedRuntime

BS = 16


def ev_stored(tokens, parent=None):
    return {"kind": "stored", "block_hashes": compute_block_hashes(tokens, BS), "parent_hash": parent}


def test_sharded_matches_unsharded():
    plain = KvIndexer(block_size=BS)
    sharded = KvIndexerSharded(block_size=BS, num_shards=3)
    try:
        seqs = {w: list(range(w, w + 64)) for w in range(1, 8)}
        for w, toks in seqs.items():
            plain.apply_event(w, ev_stored(toks))
            sharded.apply_event(w, ev_stored(toks))
        sharded.flush()
        for toks in seqs.values():
            h = compute_block_hashes(toks, BS)
            assert sharded.find_matches(h).scores == plain.find_matches(h).scores
        assert sharded.size() == plain.size()
    finally:
        sharded.close()


def test_sharded_worker_pinning_and_removal():
    idx = KvIndexerSharded(block_size=BS, num_shards=2)
    try:
        toks = list(range(48))
        for w in (1, 2, 3, 4):
            idx.apply_event(w, ev_stored(toks))
        idx.flush()
        # Workers balance across shards.
        assert sorted(idx._counts) == [2, 2]
        h = compute_block_hashes(toks, BS)
        assert set(idx.find_matches(h).scores) == {1, 2, 3, 4}

        idx.remove_worker(2)
        idx.flush()
        assert set(idx.find_matches(h).scores) == {1, 3, 4}
        assert sorted(idx._counts) == [1, 2]
    finally:
        idx.close()


def test_sharded_removed_events_and_snapshot_roundtrip():
    idx = KvIndexerSharded(block_size=BS, num_shards=2)
    idx2 = KvIndexerSharded(block_size=BS, num_shards=3)
    try:
        toks = list(range(64))
        h = compute_block_hashes(toks, BS)
        idx.apply_event(1, ev_stored(toks))
        idx.apply_event(2, ev_stored(toks[:32]))
        idx.apply_event(1, {"kind": "removed", "block_hashes": h[3:]})
        idx.flush()
        assert idx.find_matches(h).scores == {1: 3, 2: 2}

        # Snapshot restores into a differently-sharded indexer.
        idx2.load_snapshot(idx.dump())
        assert idx2.find_matches(h).scores == {1: 3, 2: 2}
    finally:
        idx.close()
        idx2.close()


def test_sharded_parallel_event_throughput():
    """Many interleaved stored/removed events across workers stay consistent."""
    idx = KvIndexerSharded(block_size=BS, num_shards=4)
    try:
        for rep in range(20):
            for w in range(8):
                toks = list(range(w * 1000, w * 1000 + 64))
                idx.apply_event(w, ev_stored(toks))
        idx.flush()
        for w in range(8):
            h = compute_block_hashes(list(range(w * 1000, w * 1000 + 64)), BS)
            assert idx.find_matches(h).scores == {w: 4}
    finally:
        idx.close()


async def test_prefill_counters_gossip():
    drt = await DistributedRuntime.detached()
    try:
        a = PrefillCountersMultiWorker(drt, "ns", "comp")
        b = PrefillCountersMultiWorker(drt, "ns", "comp")
        await a.start()
        await b.start()

        # Router A routes a 320-token prefill to worker 7.
        await a.new_prefill("req-1", 7, 320)
        await asyncio.sleep(0.05)
        # A does NOT count its own (ActiveSequences already has it); B does.
        assert a.pending_tokens(7) == 0
        assert b.pending_tokens(7) == 320

        await a.complete_prefill("req-1", 7)
        await asyncio.sleep(0.05)
        assert b.pending_tokens(7) == 0

        await a.stop()
        await b.stop()
    finally:
        await drt.shutdown()


async def test_prefill_counters_in_scheduler_cost():
    """External pending prefills steer the cost function away from a worker
    another router just loaded."""
    seqs = ActiveSequencesMultiWorker(block_size=BS)
    for w in (1, 2):
        seqs.ensure_worker(w)
    sched = KvScheduler(seqs)
    from dynamo_tpu.llm.kv_router.indexer import OverlapScores

    # No overlap anywhere; worker 1 carries 10 blocks of gossiped prefill.
    d = sched.select_worker([1, 2], 4, OverlapScores(), external_prefill_tokens={1: 160})
    assert d.worker == 2


async def test_prefill_counters_complete_without_new():
    """A 'complete' seen without its 'new' (late join) is harmless."""
    drt = await DistributedRuntime.detached()
    try:
        a = PrefillCountersMultiWorker(drt, "ns", "c2")
        await a.start()
        await drt.bus.publish(
            prefill_events_subject("ns", "c2"),
            json.dumps({"router_id": "other", "kind": "complete", "request_id": "zz", "worker_id": 3}).encode(),
        )
        await asyncio.sleep(0.05)
        assert a.pending_tokens(3) == 0
        await a.stop()
    finally:
        await drt.shutdown()
