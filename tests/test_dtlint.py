"""dtlint's own test suite: every rule catches its seeded fixture
violations at exact (rule, file, line); suppression comments and the
baseline behave; and the real ``dynamo_tpu`` tree is clean modulo the
reviewed baseline (the static half of the repo's perf invariants).

Fixture modules under ``tests/dtlint_fixtures/`` mark each seeded
violation with a trailing ``# expect: RULE`` comment, so the expected
(file, line, rule) set is read from the fixtures themselves — adding a
fixture case is one line, and line-number drift cannot silently pass.
"""

import json
import os
import re
import subprocess
import sys

import pytest

from tools.dtlint import LintConfig, RULES, apply_baseline, load_baseline, run_lint
from tools.dtlint.core import BaselineError, Finding

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = "tests/dtlint_fixtures"

FIXTURE_CONFIG = LintConfig(
    root=REPO,
    paths=(FIXTURES,),
    aggregator_path=f"{FIXTURES}/fx_met001/mini_aggregator.py",
    grafana_path=f"{FIXTURES}/fx_met001/grafana.json",
    sync_allowlist_path=f"{FIXTURES}/sync_allowlist.json",
    thread_entries=((f"{FIXTURES}/fx_thr001.py", "Poller.poll"),),
    # v2 rule anchors, re-pointed at the fixture tree.
    warmup_scopes=(f"{FIXTURES}/fx_warm001.py",),
    warmup_func="Mini.warmup",
    async_scopes=(f"{FIXTURES}/fx_async001.py",),
    wire_writers=(
        f"{FIXTURES}/fx_wire001/writer.py::Pre.to_wire",
        f"{FIXTURES}/fx_wire001/writer.py::Pre.transform",
    ),
    wire_readers=(f"{FIXTURES}/fx_wire001/reader.py::Eng.generate",),
    wire_stop_writers=(f"{FIXTURES}/fx_wire001/writer.py::stops",),
    wire_stop_readers=(f"{FIXTURES}/fx_wire001/reader.py::StopC.from_dict",),
    mocker_path=f"{FIXTURES}/fx_wire001/mock.py",
    # Keep MET001 off the wire fixtures: the mocker mini's stats families
    # are channel-C subjects, not scrape-registry subjects.
    met001_exclude=("fx_wire001/",),
)


def expected_markers(relpath: str):
    """{(line, rule)} parsed from ``# expect: RULE`` fixture comments."""
    out = set()
    with open(os.path.join(REPO, relpath)) as f:
        for i, line in enumerate(f, start=1):
            m = re.search(r"#\s*expect:\s*([A-Z]+\d+)", line)
            if m:
                out.add((i, m.group(1)))
    return out


def fixture_findings(rules=None):
    return run_lint(FIXTURE_CONFIG, rules=rules).findings


# --- exact per-rule detection -------------------------------------------------

@pytest.mark.parametrize("rule,fixture", [
    ("JIT001", f"{FIXTURES}/fx_jit001.py"),
    ("JIT002", f"{FIXTURES}/fx_jit002.py"),
    ("DON001", f"{FIXTURES}/fx_don001.py"),
    ("SYNC001", f"{FIXTURES}/fx_sync001.py"),
    ("THR001", f"{FIXTURES}/fx_thr001.py"),
    ("WARM001", f"{FIXTURES}/fx_warm001.py"),
    ("ASYNC001", f"{FIXTURES}/fx_async001.py"),
    ("LEAK001", f"{FIXTURES}/fx_leak001.py"),
    ("WIRE001", f"{FIXTURES}/fx_wire001/writer.py"),
    ("WIRE001", f"{FIXTURES}/fx_wire001/reader.py"),
    ("WIRE001", f"{FIXTURES}/fx_wire001/mock.py"),
])
def test_rule_catches_fixture_violations_at_exact_lines(rule, fixture):
    found = {
        (f.line, f.rule)
        for f in fixture_findings(rules=[rule])
        if f.file == fixture
    }
    assert found == expected_markers(fixture), (
        f"{rule} findings diverge from the fixture's # expect markers"
    )


def test_met001_covers_all_drift_directions():
    findings = fixture_findings(rules=["MET001"])
    keys = {f.key for f in findings}
    # (a) emitted but unregistered, (b) registered but unemitted,
    # (c) registered but unpinned, (d) pinned but unknown.
    assert "unregistered:rogue_total" in keys
    assert "unemitted:ghost_total" in keys
    assert "unpinned:ghost_total" in keys
    assert "unpinned:lonely_gauge" in keys
    assert "unknown:phantom_total" in keys
    # f-string wildcard emission satisfies registration (no unemitted
    # finding for the step_{phase} key), and clean keys stay clean.
    assert not any("step_decode_ok_total" in k for k in keys)
    assert not any("good" in k for k in keys)
    # Marker lines in the two fixture sources line up exactly.
    agg = f"{FIXTURES}/fx_met001/mini_aggregator.py"
    emit = f"{FIXTURES}/fx_met001/emitter.py"
    for path in (agg, emit):
        found_lines = {(f.line, f.rule) for f in findings if f.file == path}
        assert found_lines == expected_markers(path), path
    # The grafana-side unknown-key finding anchors on the dashboard file.
    grafana = [f for f in findings if f.key == "unknown:phantom_total"]
    assert grafana[0].file == f"{FIXTURES}/fx_met001/grafana.json"


def test_clean_fixture_has_zero_findings():
    clean = [f for f in fixture_findings() if f.file == f"{FIXTURES}/fx_clean.py"]
    assert clean == []


def test_suppression_comments_silence_only_their_line():
    # Every fixture carries one would-be violation with an inline
    # ``# dtlint: disable=RULE`` — none of those lines may be reported.
    for fixture in (f"{FIXTURES}/fx_jit001.py", f"{FIXTURES}/fx_jit002.py",
                    f"{FIXTURES}/fx_don001.py", f"{FIXTURES}/fx_sync001.py",
                    f"{FIXTURES}/fx_async001.py", f"{FIXTURES}/fx_leak001.py"):
        src = open(os.path.join(REPO, fixture)).read().splitlines()
        suppressed_lines = {
            i for i, l in enumerate(src, start=1) if "dtlint: disable=" in l
        }
        assert suppressed_lines, f"{fixture} lost its suppression case"
        hits = {f.line for f in fixture_findings() if f.file == fixture}
        assert not (hits & suppressed_lines), (
            f"{fixture}: suppressed lines {hits & suppressed_lines} reported"
        )


def test_sync001_allowlist_sanctions_exactly_the_named_sync():
    findings = fixture_findings(rules=["SYNC001"])
    # retire()'s np.asarray is allowlisted; decode_step's identical call is
    # not — same file, same call, different function.
    assert not any(f.qualname == "HotLoop.retire" for f in findings)
    assert any(
        f.qualname == "HotLoop.decode_step" and f.key == "sync:np.asarray"
        for f in findings
    )
    # off_path() is outside the hot-path scope entirely.
    assert not any(f.qualname == "HotLoop.off_path" for f in findings)


def test_warm001_distinguishes_unwarmed_from_arity_drift():
    keys = {f.key for f in fixture_findings(rules=["WARM001"])}
    assert keys == {"unwarmed:spec", "arity:admit"}


def test_wire001_covers_both_channels_and_directions():
    keys = {f.key for f in fixture_findings(rules=["WIRE001"])}
    assert keys == {
        "ghost-read:request:ghost_field",
        "dead-write:request:dead_field",
        "ghost-read:stop_conditions:ghost_stop",
        "dead-write:stop_conditions:phantom_stop",
        "mocker-stats:mock_only_total",
    }


def test_sync001_flags_stale_allowlist_entries(tmp_path):
    """The allowlist can only shrink: entries naming vanished functions or
    vanished syncs fail the run like a stale baseline would."""
    stale = {
        "hot_paths": {f"{FIXTURES}/fx_sync001.py": [
            "HotLoop.decode_step", "HotLoop.gone",
        ]},
        "allowed_syncs": [{
            "file": f"{FIXTURES}/fx_sync001.py", "func": "HotLoop.decode_step",
            "call": "np.array", "role": "per_step", "path": "fixture",
            "reason": "stale: decode_step has no np.array sync",
        }],
    }
    p = tmp_path / "allow.json"
    p.write_text(json.dumps(stale))
    cfg = LintConfig(
        root=REPO, paths=(FIXTURES,), sync_allowlist_path=str(p),
        warmup_scopes=FIXTURE_CONFIG.warmup_scopes,
        warmup_func=FIXTURE_CONFIG.warmup_func,
        async_scopes=FIXTURE_CONFIG.async_scopes,
    )
    keys = {f.key for f in run_lint(cfg, rules=["SYNC001"]).findings
            if f.key.startswith("stale-allowlist:")}
    assert f"stale-allowlist:hot:{FIXTURES}/fx_sync001.py:HotLoop.gone" in keys
    assert any(k.startswith("stale-allowlist:call:") for k in keys)


# --- the whole-program call graph (v2) ----------------------------------------

def test_project_graph_resolves_cross_module_calls():
    from tools.dtlint.callgraph import gid, project_graph
    from tools.dtlint.core import ProjectIndex

    index = ProjectIndex(FIXTURE_CONFIG)
    pg = project_graph(index)
    sched = f"{FIXTURES}/fx_callgraph/sched.py"
    models = f"{FIXTURES}/fx_callgraph/models.py"
    # from-import and module-attribute call sites both resolve across
    # module boundaries into real edges.
    assert gid(models, "helper") in pg.edges[gid(sched, "Sched.step")]
    assert gid(models, "chain") in pg.edges[gid(sched, "Sched.step")]
    # jit(lambda x: self.model.device_fn(x)) resolves through the
    # module-typed attribute to a cross-module jit root.
    assert gid(models, "device_fn") in pg.jit_roots()
    # Module-returner registry pattern: m = pick(cfg); m.device_fn(x).
    assert pg.resolve_call_multi(sched, "Sched.route", "m.device_fn") == {
        gid(models, "device_fn")
    }


def test_return_class_fixpoint_crosses_modules():
    from tools.dtlint.callgraph import DEVICE, HOST, gid, project_graph
    from tools.dtlint.core import ProjectIndex

    index = ProjectIndex(FIXTURE_CONFIG)
    pg = project_graph(index)
    rc = pg.infer_return_classes()
    models = f"{FIXTURES}/fx_callgraph/models.py"
    sched = f"{FIXTURES}/fx_callgraph/sched.py"
    assert rc[gid(models, "host_fn")] == HOST
    assert rc[gid(models, "device_fn")] == DEVICE
    assert rc[gid(models, "chain")] == DEVICE   # device through a helper...
    assert rc[gid(sched, "relay")] == DEVICE    # ...and across modules


# --- baseline behavior --------------------------------------------------------

def test_baseline_absorbs_matching_findings_and_reports_stale(tmp_path):
    findings = fixture_findings(rules=["JIT001"])
    assert findings
    victim = findings[0]
    entries = [{
        "rule": victim.rule, "file": victim.file,
        "qualname": victim.qualname, "key": victim.key,
        "reason": "fixture: reviewed and kept",
    }]
    remaining, stale = apply_baseline(findings, entries)
    assert victim not in remaining and not stale
    # Identity matching survives line drift: same (rule,file,qualname,key)
    # at another line is still absorbed.
    moved = Finding(victim.rule, victim.file, victim.line + 100,
                    victim.qualname, victim.message, victim.key)
    remaining, stale = apply_baseline([moved], entries)
    assert remaining == [] and stale == []
    # A stale entry (no matching finding) is an error, not a freebie.
    bogus = [{**entries[0], "key": "call:nonexistent.thing"}]
    remaining, stale = apply_baseline(findings, bogus)
    assert stale == bogus and victim in remaining


def test_baseline_entries_require_reasons(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"entries": [{
        "rule": "JIT001", "file": "x.py", "qualname": "f", "key": "call:t",
    }]}))
    with pytest.raises(BaselineError, match="reason"):
        load_baseline(str(p))


# --- the real tree ------------------------------------------------------------

def test_real_tree_is_clean_modulo_baseline():
    """THE acceptance gate: every rule over all of dynamo_tpu/, with the
    reviewed baseline applied, finds nothing — and no baseline entry is
    stale. This is the same invocation CI runs."""
    result = run_lint(
        LintConfig(root=REPO),
        baseline_path=os.path.join(REPO, "dtlint_baseline.json"),
    )
    assert result.findings == [], "\n".join(f.render() for f in result.findings)
    assert result.stale_baseline == [], result.stale_baseline
    assert result.baseline_size <= 15, (
        f"baseline has {result.baseline_size} entries; the budget is 15 — "
        "fix findings instead of accumulating exceptions"
    )


def test_real_baseline_entries_all_carry_reasons():
    entries = load_baseline(os.path.join(REPO, "dtlint_baseline.json"))
    for e in entries:
        assert len(e["reason"]) >= 20, f"baseline reason too thin: {e}"


def test_sync_allowlist_declares_one_per_step_sync_per_path():
    """The statically declared blocking-sync budget: each decode path gets
    AT MOST one per_step allowlist entry, and the overlap path's budget is
    exactly 1 (PR 4's invariant; bench.py cross-checks the measured
    count)."""
    with open(os.path.join(REPO, "tools/dtlint/sync_allowlist.json")) as f:
        cfg = json.load(f)
    per_step = [e for e in cfg["allowed_syncs"] if e["role"] == "per_step"]
    by_path = {}
    for e in per_step:
        by_path.setdefault(e["path"], []).append(e)
    assert len(by_path.get("overlap", [])) == 1
    for path, entries in by_path.items():
        assert len(entries) == 1, f"path {path} declares {len(entries)} per-step syncs"


# --- CLI ----------------------------------------------------------------------

def test_cli_json_exit_codes():
    env = {**os.environ, "PYTHONPATH": REPO}
    # Clean run (real tree + baseline) exits 0 with ok=true JSON.
    out = subprocess.run(
        [sys.executable, "-m", "tools.dtlint", "--json"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["ok"] and payload["findings"] == []

    # An injected violation (the JIT001 fixture) fails the same invocation
    # shape CI uses — rule-scoped, no baseline.
    out = subprocess.run(
        [sys.executable, "-m", "tools.dtlint",
         f"{FIXTURES}/fx_jit001.py", "--rule", "JIT001",
         "--baseline", "", "--json"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 1
    payload = json.loads(out.stdout)
    assert not payload["ok"]
    assert {f["rule"] for f in payload["findings"]} == {"JIT001"}
    assert all(f["line"] > 0 and f["file"].endswith("fx_jit001.py")
               for f in payload["findings"])


def test_cli_github_annotations_from_json(tmp_path):
    env = {**os.environ, "PYTHONPATH": REPO}
    out = subprocess.run(
        [sys.executable, "-m", "tools.dtlint",
         f"{FIXTURES}/fx_jit001.py", "--rule", "JIT001",
         "--baseline", "", "--json"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 1
    dump = tmp_path / "findings.json"
    dump.write_text(out.stdout)
    # The CI annotation step replays the dump; it decorates but never gates
    # (the lint step already failed the job), so it exits 0.
    out2 = subprocess.run(
        [sys.executable, "-m", "tools.dtlint", "--github",
         "--from-json", str(dump)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert out2.returncode == 0, out2.stdout + out2.stderr
    assert "::error file=" in out2.stdout
    assert "title=dtlint JIT001" in out2.stdout


def test_cli_diff_mode_runs_clean():
    # Whatever the working tree's changed-file set is, a tree that is clean
    # modulo baseline filters down to zero reported findings.
    env = {**os.environ, "PYTHONPATH": REPO}
    out = subprocess.run(
        [sys.executable, "-m", "tools.dtlint", "--diff"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=180,
    )
    assert out.returncode == 0, out.stdout + out.stderr


def test_rule_registry_is_complete():
    import tools.dtlint.rules_async  # noqa: F401
    import tools.dtlint.rules_jit  # noqa: F401
    import tools.dtlint.rules_leak  # noqa: F401
    import tools.dtlint.rules_metrics  # noqa: F401
    import tools.dtlint.rules_sync  # noqa: F401
    import tools.dtlint.rules_threads  # noqa: F401
    import tools.dtlint.rules_warmup  # noqa: F401
    import tools.dtlint.rules_wire  # noqa: F401

    assert set(RULES) == {
        "JIT001", "JIT002", "SYNC001", "DON001", "MET001", "THR001",
        "WARM001", "ASYNC001", "LEAK001", "WIRE001",
    }


def test_static_warmup_report_agrees_with_the_real_scheduler():
    """The bench-facing export over the REAL tree: the kinds the scheduler
    serves are (modulo the baselined open-ended mm bucket) all statically
    warmed, including the spec-decode round added for exactly this gap."""
    from tools.dtlint.rules_warmup import static_warmup_report

    report = static_warmup_report(REPO)
    warmed = report["warmed"]
    assert "decode" in warmed
    assert "spec" in warmed, (
        "spec-round executables fell out of Scheduler.warmup()"
    )
    # Every serving-path dispatch kind (modulo the baselined mm bucket) is
    # statically warmed at an intersecting arity — the same coverage
    # relation WARM001 enforces, exported here for bench.py's dynamic
    # cross-check against the flight recorder.
    for kind, arities in report["serving"].items():
        if kind == "prefill_mm":
            continue
        assert kind in warmed, f"serving kind '{kind}' never warmed"
        if arities and warmed[kind]:
            assert set(arities) & set(warmed[kind]), (
                f"serving kind '{kind}' keys {arities} but warmup "
                f"registers {warmed[kind]}"
            )
