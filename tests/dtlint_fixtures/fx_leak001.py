"""LEAK001 fixture: allocator lifecycle exits from the live/queued sets.

- ``admit``: waiting→running promotion — clean (queued removal + promote).
- ``finish``: running removal with an inline release — clean.
- ``reap``: waiting removal whose release lives one call away — clean.
- ``drop``: running removal with no release anywhere in its closure — finding.
- ``leak_alloc``: allocate() return value discarded — finding.
- ``shed``: same shape as drop but suppressed on the line.
"""


class BlockAllocator:
    def allocate(self, n):
        return list(range(n))

    def release(self, ids):
        del ids


class Pool:
    def __init__(self):
        self.allocator = BlockAllocator()
        self.running = []
        self.waiting = []

    def admit(self, seq):
        seq.blocks = self.allocator.allocate(2)
        self.waiting.remove(seq)
        self.running.append(seq)

    def finish(self, seq):
        self.running.remove(seq)
        self.allocator.release(seq.blocks)

    def reap(self, seq):
        self.waiting.remove(seq)
        self._free(seq)

    def _free(self, seq):
        self.allocator.release(seq.blocks)

    def drop(self, seq):
        self.running.remove(seq)  # expect: LEAK001
        self._count()

    def leak_alloc(self):
        self.allocator.allocate(2)  # expect: LEAK001

    def shed(self, seq):
        self.running.remove(seq)  # dtlint: disable=LEAK001

    def _count(self):
        return len(self.running)
