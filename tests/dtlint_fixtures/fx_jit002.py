"""JIT002 fixtures: recompile risk at jitted call sites / static args."""

import jax
import jax.numpy as jnp


def next_bucket(n, buckets):
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def model_step(params, tokens, table=[]):   # mutable default on a static arg
    return tokens


step_jit = jax.jit(model_step, static_argnums=(2,))  # expect: JIT002


def serve(params, prompt, prompts):
    step_jit(params, jnp.asarray(prompt), len(prompt))        # expect: JIT002
    n = len(prompts)
    step_jit(params, jnp.asarray(prompt), n)                  # expect: JIT002
    bucket = next_bucket(len(prompt), [8, 16, 32])
    step_jit(params, jnp.asarray(prompt), bucket)             # bucketed: clean
    step_jit(params, jnp.asarray(prompt), jnp.int32(len(prompt)))  # traced: clean
    m = len(prompts)
    step_jit(params, jnp.asarray(prompt), m)  # dtlint: disable=JIT002
