"""WIRE001 fixture — worker side: wire readers.

``Eng.generate`` reads one key no writer produces (``ghost_field``);
``StopC.from_dict`` reads one stop sub-key no stop writer sets
(``ghost_stop``).
"""


class StopC:
    @classmethod
    def from_dict(cls, d):
        limit = d.get("max_tokens")
        missing = d.get("ghost_stop")  # expect: WIRE001
        return (limit, missing)


class Eng:
    def generate(self, request, ctx):
        toks = request.get("token_ids")
        ann = request["annotations"]
        stop = request.get("stop_conditions") or {}
        limit = stop.get("max_tokens")
        ghost = request.get("ghost_field")  # expect: WIRE001
        return (toks, ann, limit, ghost)
