"""WIRE001 fixtures: a mini frontend/worker wire with seeded drift in both
directions on both channels, plus a mocker with one orphan stats family."""
