"""WIRE001 fixture — mocker stats parity (channel C).

``good_total`` and ``step_decode_ok_total`` exist on the fixture engine
plane (aggregator key lists / an emitter f-string wildcard);
``mock_only_total`` does not — 1 finding.
"""


class Mock:
    def stats_handler(self):
        return {
            "good_total": 1,
            "step_decode_ok_total": 2,
            "mock_only_total": 3,  # expect: WIRE001
        }
