"""WIRE001 fixture — frontend side: wire writers.

``Pre.to_wire`` seeds one dead write (``dead_field``); ``stops`` seeds one
stop-channel dead write (``phantom_stop``). Everything else has a matching
reader in ``reader.py``.
"""


def stops(body):
    limit = body.get("max_tokens")
    return {
        "max_tokens": limit,
        "phantom_stop": True,  # expect: WIRE001
    }


class Pre:
    def to_wire(self):
        d = {
            "token_ids": [1, 2],
            "dead_field": 0,  # expect: WIRE001
        }
        d["stop_conditions"] = stops({})
        return d

    def transform(self, request, ctx):
        wire = dict(request)
        wire["annotations"] = []
        return wire
