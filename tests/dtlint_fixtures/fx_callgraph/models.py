"""Callgraph fixture — the 'model module' side of the registry pattern."""


def host_fn():
    return 1.0


def device_fn(x):
    return jnp.dot(x, x)  # noqa: F821 - parsed, never imported


def chain(x):
    return device_fn(x)


def helper(x):
    host_fn()
    return x
