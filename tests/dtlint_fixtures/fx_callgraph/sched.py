"""Callgraph fixture — the scheduler side: cross-module call resolution.

``Sched.__init__`` binds a module to ``self.model`` and jits a lambda that
dispatches through it (the scheduler's real style); ``pick`` is a
module-returner; ``relay`` returns a device value produced two functions
away in another module.
"""

from . import models
from .models import helper


def pick(cfg):
    if cfg:
        return models
    return models


def relay(x):
    return models.chain(x)


class Sched:
    def __init__(self, cfg):
        self.model = models
        self._step_jit = jax.jit(lambda x: self.model.device_fn(x))  # noqa: F821

    def step(self, x):
        y = helper(x)
        return models.chain(y)

    def route(self, cfg, x):
        m = pick(cfg)
        return m.device_fn(x)
