"""ProjectGraph (callgraph v2) fixtures: cross-module import resolution,
module-typed attribute dispatch, the module-returner registry pattern, and
the host/device return-class fixpoint."""
