"""ASYNC001 fixture: blocking calls on and off the event loop.

- ``handler``: direct ``time.sleep`` — finding; a suppressed second sleep;
  the sync helper ``_log_request`` is reached ON the loop, so its
  ``requests.post`` is a finding at the helper's own line.
- ``_subtask``: ``asyncio.to_thread(...)`` hands ``_blocking_is_fine`` to a
  thread — its ``time.sleep`` is sanctioned; the bare ``open()`` in the
  async body is a finding.
- ``guarded``: un-timeouted ``_lk.acquire()`` — finding; the timeouted
  twin right below is clean.
"""

import asyncio
import threading
import time

_lk = threading.Lock()


async def handler(req):
    time.sleep(0.01)  # expect: ASYNC001
    time.sleep(0.02)  # dtlint: disable=ASYNC001
    _log_request(req)
    await _subtask()
    await asyncio.sleep(0)


async def _subtask():
    await asyncio.to_thread(_blocking_is_fine)
    with open("/tmp/fx_async001.txt") as fh:  # expect: ASYNC001
        fh.read()


async def guarded():
    _lk.acquire()  # expect: ASYNC001
    try:
        pass
    finally:
        _lk.release()
    if _lk.acquire(timeout=0.1):
        _lk.release()


def _log_request(req):
    import requests

    requests.post("http://localhost:9", json=req)  # expect: ASYNC001


def _blocking_is_fine():
    time.sleep(0.05)
