"""MET001 fixture aggregator: key lists with seeded drift.

- ``good_total`` / ``good_gauge``: emitted and pinned — clean.
- ``step_decode_ok_total``: emitted via an f-string wildcard — clean.
- ``ghost_total``: registered, never emitted, never pinned — 2 findings.
- ``lonely_gauge``: registered + emitted but not pinned — 1 finding.
"""

GAUGE_KEYS = (
    "good_gauge",
    "lonely_gauge",    # expect: MET001
)

COUNTER_KEYS = (
    "good_total",
    "step_decode_ok_total",
    "ghost_total",     # expect: MET001
)
