"""MET001 fixture emitter: the worker-scrape wire keys."""

PHASES = ("decode", "prefill")


class Worker:
    def __init__(self):
        self.good_total = 0

    def stats_handler(self) -> dict:
        out = {
            "good_total": self.good_total,
            "good_gauge": 1.0,
            "lonely_gauge": 0.5,
            "rogue_total": 7,   # expect: MET001
        }
        for phase in PHASES:
            out[f"step_{phase}_ok_total"] = 1
        return out

    def debug_dump(self) -> dict:
        # NOT an emitter function: keys here are out of scope.
        return {"internal_scratch_total": 1}
