# Fixture modules with KNOWN dtlint violations, marked with trailing
# ``# expect: RULE`` comments. They are parsed by tools/dtlint (never
# imported/executed) and are OUTSIDE the default dynamo_tpu scan scope.
