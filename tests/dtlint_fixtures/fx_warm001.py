"""WARM001 fixture: a mini scheduler whose warmup() must cover the serving
dispatch key space.

- ``decode``: registered by warmup() at matching arity — clean.
- ``mixed``: registered through a helper warmup() calls; the serving site
  keys through a local tuple plus a conditional suffix whose arity set
  intersects the warmed one — clean (exercises the arity-set algebra).
- ``spec``: never registered by warmup — 1 finding (unwarmed kind).
- ``admit``: registered, but warmup keys 2-tuples while serving keys
  3-tuples — 1 finding (arity mismatch).
"""


class FlightRec:
    def record_exec(self, kind, key):
        self.last = (kind,) + tuple(key)


class Mini:
    def __init__(self):
        self.flight = FlightRec()
        self.decode_buckets = (8, 16)

    def warmup(self):
        for bucket in self.decode_buckets:
            self.flight.record_exec("decode", (bucket, 4))
        self.flight.record_exec("admit", (8, 4))
        self._warm_mixed()

    def _warm_mixed(self):
        self.flight.record_exec("mixed", (8, 4, 2))

    def step(self, flag):
        self.flight.record_exec("decode", (8, 4))
        mixed_key = (8, 4)
        self.flight.record_exec("mixed", mixed_key + ((2,) if flag else (1, 2)))
        self.flight.record_exec("spec", (4, 8, 16))  # expect: WARM001
        self.flight.record_exec("admit", (8, 4, 2))  # expect: WARM001
