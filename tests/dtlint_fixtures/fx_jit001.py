"""JIT001 fixtures: host impurity inside jit/pallas-reachable bodies."""

import logging
import random
import time

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

SCALE_TABLE = [1.0, 2.0, 4.0]          # mutable module global (list)
BUCKETS = (8, 16, 32)                  # immutable tuple: reads are fine


@jax.jit
def impure_kernel(x):
    t0 = time.perf_counter()           # expect: JIT001
    noise = random.random()            # expect: JIT001
    print("tracing", t0)               # expect: JIT001
    logger.info("step %s", noise)      # expect: JIT001
    return x * SCALE_TABLE[0]          # expect: JIT001


def helper(x):
    # Reachable from jitted_root below via the module call graph — the
    # impurity is flagged here even though the jit sits one level up.
    logger.debug("helper")             # expect: JIT001
    return x + len(BUCKETS)


@jax.jit
def jitted_root(x):
    return helper(x) * 2


@jax.jit
def suppressed_kernel(x):
    t = time.time()  # dtlint: disable=JIT001
    return x + t


def pure_host_fn(x):
    # NOT reachable from any jit root: host calls here are fine.
    logger.info("serving %s", time.time())
    return x
