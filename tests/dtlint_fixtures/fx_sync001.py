"""SYNC001 fixtures: blocking syncs in (fixture-)hot-path functions.

The fixture sync allowlist (``sync_allowlist.json`` beside this file)
declares ``HotLoop.decode_step`` / ``HotLoop.retire`` / ``HotLoop.host_stats``
as hot paths and sanctions exactly one sync: ``np.asarray`` in ``retire``.
"""

import jax
import jax.numpy as jnp
import numpy as np


class HotLoop:
    def __init__(self, params):
        self.params = params
        self._decode_jit = jax.jit(lambda p, t: t)

    def decode_step(self, tokens_h):
        tpa = np.zeros((3, 8), dtype=np.int32)        # host: clean
        dev = self._decode_jit(self.params, jnp.asarray(tpa))
        toks = np.asarray(dev)                         # expect: SYNC001
        dev.block_until_ready()                        # expect: SYNC001
        got = jax.device_get(dev)                      # expect: SYNC001
        x = float(dev)                                 # expect: SYNC001
        y = dev.item()                                 # expect: SYNC001
        z = np.asarray(self.params)                    # expect: SYNC001
        ok = float(len(tokens_h))                      # host float: clean
        w = np.asarray([1, 2, 3])                      # literal: clean
        s = np.asarray(dev)  # dtlint: disable=SYNC001
        return toks, got, x, y, z, ok, w, s

    def retire(self, pending):
        return np.asarray(pending)                     # allowlisted: clean

    def host_stats(self):
        # Host-only bookkeeping in a hot path: nothing to flag.
        return {"steps_total": 1}

    def off_path(self, dev):
        # NOT in the fixture hot-path list: syncs here are out of scope.
        return np.asarray(dev)
