"""DON001 fixtures: KV-buffer donation hygiene."""

from functools import partial

import jax
import jax.numpy as jnp


@jax.jit  # expect: DON001
def scatter_nodonate(k_cache, v_cache, idx, rows):
    # Writes both cache buffers without donating either: XLA must
    # double-buffer the whole pool for the update. (Two findings — one
    # per written cache param — anchor on the decorator line.)
    return k_cache.at[idx].set(rows), v_cache.at[idx].set(rows)


@partial(jax.jit, donate_argnums=(0, 1))
def scatter_donated(k_cache, v_cache, idx, rows):
    return k_cache.at[idx].set(rows), v_cache.at[idx].set(rows)


@jax.jit
def gather_readonly(k_cache, idx):
    # Read-only: no donation required.
    return k_cache[idx]


def zero_block(cache, idx):
    return cache.at[idx].set(0.0)


_zero_jit = jax.jit(zero_block, donate_argnums=(0,))


def caller_reuses_donated(cache, idx):
    out = _zero_jit(cache, idx)
    stale = cache + 1                    # expect: DON001
    return out, stale


def caller_reassigns(cache, idx):
    cache = _zero_jit(cache, idx)
    return cache + 1                     # reassigned first: clean


@jax.jit  # dtlint: disable=DON001
def suppressed_scatter(k_cache, idx, rows):
    return k_cache.at[idx].set(rows)
