"""A module every rule must pass untouched."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

BUCKETS = (8, 16, 32)


def next_bucket(n, buckets):
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


@jax.jit
def pure_step(params, tokens):
    return jnp.dot(params, tokens)


@partial(jax.jit, donate_argnums=(0,))
def write_cache(cache, idx, rows):
    return cache.at[idx].set(rows)


def serve(params, prompt):
    bucket = next_bucket(len(prompt), list(BUCKETS))
    padded = np.zeros((bucket,), dtype=np.int32)
    padded[: len(prompt)] = prompt
    return pure_step(params, jnp.asarray(padded))
